"""Cold restore: timed restore in a FRESH process whose transfer path has
never run a device→host copy — the restore-after-restart scenario
(BASELINE.md "restore-to-step0"; the reference's load benchmark is
likewise a standalone read-only process,
``/root/reference/benchmarks/load_tensor/main.py:24-61``).

On the tunneled dev chip this isolation also sidesteps a measured
environment artifact: the FIRST D2H a process performs collapses its
H2D bandwidth ~40x for the rest of the process lifetime (measured
1.3 GB/s → 0.03 GB/s; irreversible — gc/clear_caches don't restore it).
An in-process restore timed after a take therefore measures the
artifact, not the restore path. Real rollback restores in long-lived
training processes hit this only on the tunnel — real hosts don't
degrade — so the cold number is the honest hardware-limit figure and
the in-process number (bench.py's ``restore_gbps``) is kept alongside
as the worst-case.

Usage (spawned by bench.py; runs on the default platform — the real
chip when present):

    python benchmarks/cold_restore.py --snap DIR --trials 2 --json

The destination tree is rebuilt from the snapshot manifest (device-side
``jnp.zeros`` — no H2D before the timed restore). Each timed restore is
bracketed by pattern-matched H2D probes of RANDOM content (zeros can be
transparently compressed by transport layers).
"""

import argparse
import json
import os
import statistics
import sys
import time

# Repo root (parent of benchmarks/) — NOT benchmarks/common.py, which
# pins the CPU platform; this leg must run on the default platform (the
# real chip when present).
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--snap", required=True)
    p.add_argument("--trials", type=int, default=2)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.manifest import (
        ArrayEntry,
        ChunkedArrayEntry,
        ShardedArrayEntry,
    )

    snap = ts.Snapshot(args.snap)
    manifest = snap.get_manifest()
    leaves = {}
    for path, entry in manifest.items():
        if not isinstance(
            entry, (ArrayEntry, ChunkedArrayEntry, ShardedArrayEntry)
        ):
            continue
        # bench's tree: "0/state/<leaf>"
        parts = path.split("/")
        leaves["/".join(parts[2:])] = (tuple(entry.shape), entry.dtype)
    if not leaves:
        raise SystemExit("no array entries found in manifest")
    dev = jax.devices()[0]
    nbytes = sum(
        int(np.prod(s)) * np.dtype(jnp.bfloat16 if d == "bfloat16" else d).itemsize
        for s, d in leaves.values()
    )
    gib = nbytes / (1 << 30)
    n_streams = min(4, max(1, len(leaves) - 1))

    rng = np.random.default_rng(0)
    max_leaf_mib = max(
        int(np.prod(s))
        * np.dtype(jnp.bfloat16 if d_ == "bfloat16" else d_).itemsize
        for s, d_ in leaves.values()
    ) >> 20

    # Pattern matching: probe chunks scale to a quick link estimate
    # (~4 s of probe wall) but never exceed the snapshot's largest leaf
    # — the restore's actual per-placement transfer size.
    quick = np.ascontiguousarray(
        rng.integers(0, 255, (4096, 4096), dtype=np.uint8)
    )
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(quick, dev))
    est = quick.nbytes / (1 << 30) / (time.perf_counter() - t0)
    chunk_mib = int(
        min(max(32, max_leaf_mib), max(32, est * 4.0 * 1024 / n_streams))
    )
    side = int((chunk_mib * (1 << 20)) ** 0.5)

    def probe(tag: str) -> float:
        # Random content: transport-layer compression of zeros would
        # fake the ceiling.
        hosts = [
            rng.integers(0, 255, (side, side), dtype=np.uint8)
            for _ in range(n_streams)
        ]
        t0 = time.perf_counter()
        d = jax.device_put(hosts, [dev] * n_streams)
        jax.block_until_ready(d)
        r = sum(h.nbytes for h in hosts) / (1 << 30) / (time.perf_counter() - t0)
        del d, hosts
        log(
            f"cold-restore: H2D probe {tag} ({n_streams}x{chunk_mib} MiB): "
            f"{r:.3f} GB/s"
        )
        return r

    def make_dest():
        tree = {}
        for key, (shape, dtype) in leaves.items():
            jdt = jnp.bfloat16 if dtype == "bfloat16" else dtype
            tree[key] = jnp.zeros(shape, jdt)
        d = ts.PyTreeState(tree)
        jax.block_until_ready(d.tree)
        return d

    probes = [probe("before restore 0")]
    times = []
    for i in range(args.trials):
        dest = make_dest()
        # Writeback guard (repo methodology): the parent's take loop may
        # still be flushing ~GiBs of dirty pages; on the one-core box
        # that inflated timed restores up to 10x.
        os.sync()
        t0 = time.perf_counter()
        snap.restore({"state": dest})
        jax.block_until_ready(dest.tree)
        times.append(time.perf_counter() - t0)
        log(f"cold-restore: restore {i}: {times[-1]:.2f} s "
            f"({gib / times[-1]:.3f} GB/s)")
        del dest
        probes.append(probe(f"after restore {i}"))

    brackets = [max(probes[i], probes[i + 1]) for i in range(len(times))]
    ratios = [(gib / t) / b for t, b in zip(times, brackets) if b > 0]
    out = {
        "size_gib": round(gib, 2),
        # A silent CPU fallback (e.g. an exclusively-held device) must be
        # visible in the record: multi-GB/s page-cache "restores" are not
        # hardware-limit figures.
        "cold_restore_backend": (
            f"{jax.default_backend()}:{dev.device_kind}"
        ),
        "cold_restore_gbps": round(
            statistics.median(gib / t for t in times), 3
        ),
        "cold_restore_times_s": [round(t, 2) for t in times],
        "cold_restore_h2d_probes": [round(r, 3) for r in probes],
        "cold_restore_efficiency": (
            round(statistics.median(ratios), 3) if ratios else 0.0
        ),
        "cold_restore_link_unstable": any(
            max(a, b) / min(a, b) > 1.5
            for a, b in zip(probes, probes[1:])
            if min(a, b) > 0
        ),
    }
    if args.json:
        print(json.dumps(out))


if __name__ == "__main__":
    main()
