"""Preemption recovery via the peer-RAM tier on a 2-process fleet.

The recovery half of the BENCH record's robustness story: a 2-process
group saves a step with the peer tier pushing each rank's shards into
its ring neighbor's host RAM, rank 1 is then "preempted" (its peer
cache and process-local tier state are wiped and rebuilt — the
replacement-rank scenario), and the world restores — once with the
peer tier ON (the replacement's bytes ride the surviving peer's RAM)
and once kill-switched OFF (every byte comes from storage). Records
``recovery_wall_s`` and the ledger-shaped ``recovery_tier_split``
(bytes served per tier of the peer -> fast -> durable ladder) for
both runs. Spawned by bench.py's subprocess-leg runner; emits one JSON
line on stdout.

    python benchmarks/peer_restore.py --mib 64 --json
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _state(rank: int, mib: float):
    import numpy as np

    import torchsnapshot_tpu as ts

    n = max(1024, int(mib * 1024 * 1024 / 4))
    return {
        "model": ts.PyTreeState(
            {"w": (np.arange(n, dtype=np.float32) + rank)}
        )
    }


def _recover_worker(pg, root, mib, enabled):
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu import telemetry
    from torchsnapshot_tpu.pg_wrapper import PGWrapper
    from torchsnapshot_tpu.tiered import peer

    os.environ["TORCHSNAPSHOT_TPU_PEER_TIER"] = "1" if enabled else "0"
    wrapper = PGWrapper(pg)
    mgr = ts.CheckpointManager(root, pg=pg)
    mgr.save(0, _state(pg.rank, mib))
    peer.maybe_drain(timeout=60)
    wrapper.barrier()

    if pg.rank == 1:
        # Simulated single-rank preemption: the host died, its peer
        # cache with it; the replacement re-announces under rank 1.
        peer.reset_peer_tier()
        peer.maybe_configure(wrapper)
    wrapper.barrier()

    dest = _state(pg.rank, mib)
    np.asarray(dest["model"].tree["w"]).fill(0)
    t0 = time.perf_counter()
    step = mgr.restore_latest(dest)
    wall = time.perf_counter() - t0
    assert step == 0
    expect = _state(pg.rank, mib)["model"].tree["w"]
    np.testing.assert_array_equal(dest["model"].tree["w"], expect)
    report = telemetry.last_report("restore", path=mgr.step_path(0))
    return {
        "rank": pg.rank,
        "restore_s": round(wall, 3),
        "tier_split": report.tier_split if report else None,
        "peer": report.peer if report else None,
        "bytes_moved": report.bytes_moved if report else None,
        # The restore's wire split (frames/bytes/dial+RPC time, per-op
        # table): None when the run put nothing on a socket (peer tier
        # kill-switched = storage-only restore).
        "wire": report.wire if report else None,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--mib", type=float, default=64.0)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()

    from torchsnapshot_tpu.test_utils import run_multiprocess

    out = {"state_mib_per_rank": args.mib}
    for enabled, key in ((True, "peer"), (False, "fallback")):
        root = os.path.join(
            tempfile.mkdtemp(prefix="ts-peer-bench-"), "ckpt"
        )
        rows = run_multiprocess(
            _recover_worker,
            nproc=2,
            args=(root, args.mib, enabled),
            timeout=300,
        )
        # The replacement rank (1) is the recovery that matters: its
        # host died, so every byte it gets at RAM speed is storage
        # latency not paid.
        replacement = next(r for r in rows if r["rank"] == 1)
        split = {}
        for r in rows:
            for tier, b in (r.get("tier_split") or {}).items():
                split[tier] = split.get(tier, 0) + int(b)
        out[f"{key}_recovery_wall_s"] = replacement["restore_s"]
        out[f"{key}_recovery_tier_split"] = split or None
        out[f"{key}_replacement_tier_split"] = replacement.get(
            "tier_split"
        )
        # Wire split of the replacement's restore: bytes that rode
        # sockets, dial + RPC wall, and the per-op table — the "how
        # much of recovery was wire time" half of the tier split.
        out[f"{key}_replacement_wire"] = replacement.get("wire")
        wire = replacement.get("wire") or {}
        log(
            f"peer-restore[{key}]: replacement restored in "
            f"{replacement['restore_s']}s, world tier split {split}, "
            f"wire {wire.get('bytes', 0)} B in {wire.get('rpcs', 0)} "
            f"rpcs ({wire.get('rpc_s', 0)}s rpc + "
            f"{wire.get('dial_s', 0)}s dial)"
        )
    if args.json:
        print(json.dumps(out, separators=(",", ":")), flush=True)


if __name__ == "__main__":
    main()
