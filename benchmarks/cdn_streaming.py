"""Checkpoint-CDN subscriber storm: weight streaming to a serving fleet.

Bench leg 11 (docs/cdn.md): ``--subscribers`` (default 100+) real
:class:`~torchsnapshot_tpu.cdn.CdnSubscriber` instances — each with its
own peer-cache TCP server — track a publishing trainer through a
rolling update (``--churn`` of the chunk set replaced per step). The
three pins the leg grades:

- **staleness** — publish-to-swap seconds per (subscriber, step);
  median should stay sub-second at fleet scale.
- **read amplification** — durable reads / unique chunks published;
  owner election holds this at ~1.0 regardless of fleet size.
- **dedup ratio** — fleet bytes-on-wire / fleet logical step bytes; a
  rolling update ships only the churned chunks.

Emits one JSON line on stdout; ``--json`` accepted for symmetry.

    python benchmarks/cdn_streaming.py --subscribers 100 --json
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--subscribers", type=int, default=100)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--chunks", type=int, default=16)
    p.add_argument("--chunk-kib", type=int, default=64)
    p.add_argument("--churn", type=float, default=0.25)
    # Seconds between published steps. Real trainers checkpoint every
    # minutes; 0.5s is already adversarial — pushing it toward 0 stops
    # measuring staleness and starts measuring queueing backlog (the
    # fleet can't drain updates faster than they are announced).
    p.add_argument("--interval", type=float, default=0.5)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()

    from torchsnapshot_tpu.scalemodel import CdnStormConfig, run_cdn_storm

    cfg = CdnStormConfig(
        fleet_size=args.subscribers,
        steps=args.steps,
        chunks_per_step=args.chunks,
        chunk_bytes=args.chunk_kib * 1024,
        churn_fraction=args.churn,
        publish_interval_s=args.interval,
        timeout_s=max(120.0, args.subscribers * 1.0),
    )
    r = run_cdn_storm(cfg)

    out = {
        "subscribers": cfg.fleet_size,
        "steps": cfg.steps,
        "warmup_steps": cfg.warmup_steps,
        "chunks_per_step": cfg.chunks_per_step,
        "chunk_bytes": cfg.chunk_bytes,
        "churn_fraction": cfg.churn_fraction,
        "wall_s": r.wall_s,
        "converged_subscribers": r.converged_subscribers,
        "converged": r.converged(),
        "staleness_median_s": r.staleness_median_s,
        "staleness_p90_s": r.staleness_p90_s,
        "staleness_max_s": r.staleness_max_s,
        "staleness_samples": r.staleness_samples,
        "durable_reads": r.durable_reads,
        "unique_chunks_published": r.unique_chunks_published,
        "read_amplification": round(r.read_amplification, 3),
        "bytes_on_wire": r.bytes_on_wire,
        "bytes_in_steps": r.bytes_in_steps,
        "bytes_from_peer": r.bytes_from_peer,
        "bytes_from_durable": r.bytes_from_durable,
        "dedup_ratio": round(r.dedup_ratio, 4),
        "peer_fallbacks": r.peer_fallbacks,
        "errors": len(r.errors),
        # Wire split: per-tier pull-latency quantiles pooled across the
        # fleet, and the per-op frame/byte/RPC report split.
        "pull_latency": r.pull_latency,
        "wire": r.wire,
    }
    pulls = " ".join(
        f"{tier} p50/p95 {t['p50_s']}/{t['p95_s']}s"
        for tier, t in sorted(r.pull_latency.items())
    )
    log(
        f"cdn-streaming: {r.converged_subscribers}/{cfg.fleet_size} "
        f"subscribers converged over {cfg.steps} steps; staleness "
        f"med/p90/max {r.staleness_median_s}/{r.staleness_p90_s}/"
        f"{r.staleness_max_s}s; read amplification "
        f"{out['read_amplification']}x; dedup {out['dedup_ratio']} "
        f"(wire {r.bytes_on_wire} of {r.bytes_in_steps} logical); "
        f"pulls {pulls or 'none'}"
    )
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
