"""Leg 9: dense-retention storage/traffic curves, CAS on vs off.

The content-addressed chunk store's acceptance instrument (docs/cas.md):
a 2-process group runs a ``keep_last_n=20`` manager loop over a
sparsely-updated state (~5% of the weights change per step) on a tiered
root with the peer tier pushing and the run ledger on, once with
``TORCHSNAPSHOT_TPU_CAS=1`` and once with the legacy layout. Records,
per step, the cumulative storage footprint (both tiers), the mirror
bytes actually shipped to the durable tier, and the peer-tier bytes
pushed across the wire — the three curves the ISSUE's ≤1.5×-one-step
claim is judged on — plus the goodput ledger's storage attribution
(bytes per retained step, reuse ratio) as the proof instrument of
record. Spawned by bench.py's subprocess-leg runner; emits one JSON
line on stdout.

    python benchmarks/retention_curve.py --mib 32 --steps 6 --json
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _du(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for name in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return total


def _retention_worker(pg, base: str, mib: float, steps: int, cas: bool):
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu import telemetry
    from torchsnapshot_tpu.telemetry import names as tn
    from torchsnapshot_tpu.tiered import peer
    from torchsnapshot_tpu.tiered.mirror import get_mirror

    os.environ["TORCHSNAPSHOT_TPU_CAS"] = "1" if cas else "0"
    os.environ["TORCHSNAPSHOT_TPU_PEER_TIER"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_LEDGER"] = "1"

    # Many-leaf state (a layered model), ONE leaf touched per step:
    # the realistic sparse-update shape (embedding slices, unfrozen
    # towers) whose unchanged leaves are what dense retention should
    # not re-pay for. Dedup granularity is the write granularity, so a
    # monolithic array would (correctly) re-store wholesale on any
    # touch — that is the legacy curve's behavior for everything.
    layers = 16
    per = max(1024, int(mib * 1024 * 1024 / 4 / layers))
    rng = np.random.default_rng(7 + pg.rank)
    leaves = {
        f"layer{i:02d}": rng.standard_normal(per).astype(np.float32)
        for i in range(layers)
    }

    root = f"tiered://{base}/fast|{base}/dur"
    mgr = ts.CheckpointManager(root, keep_last_n=20, pg=pg)
    counters0 = telemetry.metrics().counters_snapshot()
    storage_curve, mirror_curve, peer_curve, save_s = [], [], [], []
    for step in range(steps):
        # Sparse update: one layer (~1/16 of the state) moves per step.
        leaves[f"layer{step % layers:02d}"] += 1.0
        t0 = time.perf_counter()
        mgr.save(
            step,
            {"m": ts.PyTreeState(dict(leaves))},
            record_digests=True,
        )
        save_s.append(round(time.perf_counter() - t0, 3))
        mgr.wait_durable(step, timeout=120)
        peer.maybe_drain(timeout=60)
        if pg.rank == 0:
            storage_curve.append(_du(base))
            mirror_curve.append(
                int(get_mirror().metrics()["bytes_mirrored"])
            )
        counters = telemetry.metrics().counters_snapshot()
        peer_curve.append(
            int(
                counters.get(tn.PEER_PUSH_BYTES_TOTAL, 0)
                - counters0.get(tn.PEER_PUSH_BYTES_TOTAL, 0)
            )
        )
    row = {
        "rank": pg.rank,
        "save_s": save_s,
        "peer_bytes_pushed_curve": peer_curve,
        "peer_bytes_deduped": int(
            telemetry.metrics()
            .counters_snapshot()
            .get(tn.PEER_PUSH_BYTES_DEDUPED_TOTAL, 0)
        ),
    }
    if pg.rank == 0:
        row["storage_bytes_curve"] = storage_curve
        row["mirror_bytes_shipped_curve"] = mirror_curve
        # The goodput ledger's storage attribution — the curves of
        # record the acceptance criterion cites.
        try:
            from torchsnapshot_tpu.telemetry.goodput import analyze
            from torchsnapshot_tpu.telemetry.ledger import (
                find_ledger_for,
                load_ledger,
            )

            lf = find_ledger_for(f"{base}/fast")
            if lf:
                storage = analyze(load_ledger(lf))["storage"]
                row["goodput_storage"] = {
                    k: storage.get(k)
                    for k in (
                        "retained_steps",
                        "bytes_per_retained_step",
                        "incremental_reuse_ratio",
                        "bytes_reused_total",
                    )
                }
        except Exception as e:  # noqa: BLE001 - context metric only
            log(f"retention-curve: goodput read failed: {e!r}")
    return row


def _run_mode(mib: float, steps: int, cas: bool):
    from torchsnapshot_tpu.test_utils import run_multiprocess

    base = tempfile.mkdtemp(prefix="ts-retention-")
    rows = run_multiprocess(
        _retention_worker,
        nproc=2,
        args=(base, mib, steps, cas),
        timeout=600,
    )
    r0 = next(r for r in rows if r["rank"] == 0)
    peer_total = sum(
        r["peer_bytes_pushed_curve"][-1]
        for r in rows
        if r["peer_bytes_pushed_curve"]
    )
    out = {
        "storage_bytes_curve": r0["storage_bytes_curve"],
        "mirror_bytes_shipped_curve": r0["mirror_bytes_shipped_curve"],
        "peer_bytes_pushed_total": peer_total,
        "peer_bytes_deduped_total": sum(
            r["peer_bytes_deduped"] for r in rows
        ),
        "save_s": r0["save_s"],
        "goodput_storage": r0.get("goodput_storage"),
    }
    curve = out["storage_bytes_curve"]
    if curve:
        out["storage_bytes_final"] = curve[-1]
        out["storage_bytes_first_step"] = curve[0]
        out["storage_ratio_vs_one_step"] = round(
            curve[-1] / max(1, curve[0]), 3
        )
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--mib", type=float, default=32.0)
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()

    out = {"state_mib_per_rank": args.mib, "steps": args.steps}
    for cas, key in ((True, "cas"), (False, "legacy")):
        out[key] = _run_mode(args.mib, args.steps, cas)
        log(
            f"retention-curve[{key}]: storage "
            f"{out[key].get('storage_ratio_vs_one_step')}x of one step, "
            f"mirror shipped "
            f"{(out[key]['mirror_bytes_shipped_curve'] or [0])[-1]} B, "
            f"peer pushed {out[key]['peer_bytes_pushed_total']} B"
        )
    cas_final = out["cas"].get("storage_bytes_final")
    legacy_final = out["legacy"].get("storage_bytes_final")
    if cas_final and legacy_final:
        out["cas_storage_savings"] = round(legacy_final / cas_final, 3)
    if args.json:
        print(json.dumps(out, separators=(",", ":")), flush=True)


if __name__ == "__main__":
    main()
