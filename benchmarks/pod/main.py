"""Multi-host pod benchmark: the sharded-transformer checkpoint on a real
TPU pod (or any multi-host jax.distributed world).

The reference ships SLURM launchers for its benchmarks
(reference benchmarks/ddp/run.slurm); the TPU-native equivalent is a
launcher over ``jax.distributed``:

- **TPU pod** (e.g. v4-32): run this script on every worker VM with no
  env — ``jax.distributed.initialize()`` auto-discovers the coordinator
  and process indices from the TPU metadata. See ``launch_gce.sh``.
- **Generic multi-host / local dry run**: drive it with env vars::

      TS_COORDINATOR=host0:8476 TS_NUM_PROCESSES=2 TS_PROCESS_ID=$i \
          python benchmarks/pod/main.py

  ``dryrun_local.sh`` launches exactly that with 2 local CPU processes
  (4 virtual devices each) to validate the recipe without hardware.

Snapshot coordination rides the same coordination service
(``jax_process_group`` -> JaxCoordinationStore over DCN), so no extra
rendezvous infrastructure is needed beyond what JAX itself uses.
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402  (pins JAX_PLATFORMS=cpu)


def _initialize_distributed() -> None:
    coordinator = os.environ.get("TS_COORDINATOR")
    if coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(os.environ["TS_NUM_PROCESSES"]),
            process_id=int(os.environ["TS_PROCESS_ID"]),
        )
    else:
        # TPU pod: coordinator + topology come from the TPU metadata.
        jax.distributed.initialize()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--d-model", type=int, default=1024)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--experts", type=int, default=0)
    p.add_argument("--steps", type=int, default=1)
    p.add_argument(
        "--dir",
        default=None,
        help="snapshot directory visible to ALL hosts (gs://... on pods); "
        "default: a host-local tempdir (fine for per-host FS benchmarks "
        "and the local dry run)",
    )
    p.add_argument("--async-take", action="store_true")
    args = p.parse_args()

    _initialize_distributed()

    import numpy as np  # noqa: E402
    from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

    import torchsnapshot_tpu as ts  # noqa: E402
    from torchsnapshot_tpu.dist_store import jax_process_group  # noqa: E402
    from torchsnapshot_tpu.models import (  # noqa: E402
        TransformerConfig,
        init_train_state,
        make_mesh,
        make_train_step,
    )

    rank = jax.process_index()
    world = jax.process_count()
    pg = jax_process_group()
    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=max(4, args.d_model // 64),
        n_layers=args.layers,
        d_ff=args.d_model * 4,
        n_experts=args.experts,
    )
    mesh = make_mesh()  # global mesh over every chip in the pod
    if rank == 0:
        print(
            f"pod: {world} processes, {len(jax.devices())} devices, "
            f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}"
        )

    state = init_train_state(cfg, seed=0, mesh=mesh)
    step_fn = make_train_step(cfg, mesh=mesh)
    # One GLOBAL batch, identical on every process: multi-process
    # device_put requires consistent global values (each process then
    # holds only its addressable slice).
    tokens = jax.device_put(
        np.random.default_rng(0)
        .integers(0, cfg.vocab_size, (max(4, 2 * world), 128))
        .astype(np.int32),
        NamedSharding(mesh, P("dp", None)),
    )
    for _ in range(args.steps):
        state, loss = step_fn(state, tokens)
    jax.block_until_ready(state.as_pytree())  # valid even with --steps 0
    nbytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(state.as_pytree())
    )
    if rank == 0:
        print(f"train state: {nbytes / (1 << 30):.2f} GiB global")

    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    # Rank 0 picks the directory and its path wins everywhere (take
    # broadcasts internally, but the restore below must also open the
    # same snapshot — on one box per-process tempdirs would diverge, and
    # non-zero ranks must not create orphan dirs).
    work_dir = args.dir
    if work_dir is None and rank == 0:
        work_dir = tempfile.mkdtemp(prefix="ts_pod_")
    if work_dir is None:
        snap_path = None
    elif work_dir.startswith(("gs://", "s3://")):
        snap_path = work_dir
    else:
        snap_path = os.path.join(work_dir, "step_0")
    snap_path = PGWrapper(pg).broadcast_object(snap_path)
    app_state = {"train": ts.PyTreeState(state.as_pytree())}
    t0 = time.perf_counter()
    if args.async_take:
        pending = ts.Snapshot.async_take(snap_path, app_state, pg=pg)
        stall_s = time.perf_counter() - t0
        pending.wait()
        save_s = time.perf_counter() - t0
        if rank == 0:
            print(
                f"async save: stall {stall_s:.2f}s, total {save_s:.2f}s "
                f"({nbytes / (1 << 30) / save_s:.2f} GB/s aggregate)"
            )
    else:
        ts.Snapshot.take(snap_path, app_state, pg=pg)
        save_s = time.perf_counter() - t0
        if rank == 0:
            print(
                f"save: {save_s:.2f}s "
                f"({nbytes / (1 << 30) / save_s:.2f} GB/s aggregate)"
            )

    # Destinations carry the SOURCE's exact shardings (post-step jit
    # output shardings can differ from init-time constraints): zero-fill
    # via global device_put — identical global zeros on every process.
    dest = ts.PyTreeState(
        jax.tree_util.tree_map(
            lambda x: jax.device_put(
                np.zeros(x.shape, x.dtype), x.sharding
            ),
            state.as_pytree(),
        )
    )
    t0 = time.perf_counter()
    ts.Snapshot(snap_path, pg=pg).restore({"train": dest})
    load_s = time.perf_counter() - t0
    src_leaves = jax.tree_util.tree_leaves_with_path(state.as_pytree())
    dst_leaves = jax.tree_util.tree_leaves_with_path(dest.tree)
    assert len(src_leaves) == len(dst_leaves)
    for (pa, a), (pb, b) in zip(src_leaves, dst_leaves):
        assert pa == pb, (pa, pb)
        sb_by_index = {str(s.index): s for s in b.addressable_shards}
        for sa in a.addressable_shards:
            np.testing.assert_array_equal(
                np.asarray(sa.data),
                np.asarray(sb_by_index[str(sa.index)].data),
                err_msg=str(pa),
            )
    if rank == 0:
        print(
            f"restore: {load_s:.2f}s "
            f"({nbytes / (1 << 30) / load_s:.2f} GB/s aggregate); "
            f"byte-identical on every shard"
        )
    if args.dir is None and rank == 0:
        shutil.rmtree(work_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
