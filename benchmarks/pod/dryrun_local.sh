#!/usr/bin/env bash
# Local 2-process dry run of the pod launch recipe: the exact env-driven
# rendezvous a generic multi-host deployment uses, on CPU devices.
#
#   benchmarks/pod/dryrun_local.sh [extra main.py args]
#
# Each process gets 4 virtual CPU devices; the global mesh spans 8
# devices across the 2 processes, so shardings, collectives, the
# coordination-service store, and the commit protocol all cross process
# boundaries exactly as on a pod.
set -euo pipefail
cd "$(dirname "$0")/../.."

PORT=${TS_DRYRUN_PORT:-$(python - <<'EOF'
import socket
with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    print(s.getsockname()[1])
EOF
)}

export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=4 ${XLA_FLAGS:-}"
ARGS=${*:---d-model 64 --layers 2 --vocab 128}

pids=()
for i in 0 1; do
    TS_COORDINATOR=127.0.0.1:$PORT TS_NUM_PROCESSES=2 TS_PROCESS_ID=$i \
        python benchmarks/pod/main.py $ARGS &
    pids+=($!)
done
rc=0
for pid in "${pids[@]}"; do
    wait "$pid" || rc=$?
done
exit $rc
