#!/usr/bin/env bash
# Launch the pod benchmark on a GCE TPU pod slice (e.g. v4-32).
#
# The reference's SLURM launchers (benchmarks/ddp/run.slurm) allocate N
# nodes and srun the benchmark; the TPU equivalent runs one process per
# worker VM via `gcloud ... ssh --worker=all`. jax.distributed.initialize()
# inside main.py discovers the coordinator/topology from TPU metadata —
# no rendezvous flags needed.
#
# Usage:
#   TPU_NAME=my-v4-32 ZONE=us-central2-b PROJECT=my-project \
#       benchmarks/pod/launch_gce.sh [--d-model 4096 --layers 32 \
#       --dir gs://my-bucket/ckpt --async-take]
#
# A v4-32 slice is 4 worker VMs x 4 chips; --dir must be a path every
# host can reach (a gs:// bucket) unless you only want per-host FS I/O.
set -euo pipefail

: "${TPU_NAME:?set TPU_NAME to the TPU pod slice name}"
: "${ZONE:?set ZONE (e.g. us-central2-b)}"
PROJECT_FLAG=${PROJECT:+--project="$PROJECT"}
REPO_DIR=${REPO_DIR:-"\$HOME/torchsnapshot_tpu"}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
    --zone="$ZONE" $PROJECT_FLAG \
    --worker=all \
    --command="cd $REPO_DIR && python benchmarks/pod/main.py $*"
