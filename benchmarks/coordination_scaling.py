"""Coordination-plane scaling: tuned vs baseline storms vs world size.

Bench leg 10 (the scale-model acceptance instrument, docs/scaling.md):
at each simulated world size, one full save/restore/endpoint storm
through the REAL ``dist_store``/``pg_wrapper``/``fanout`` code paths —
TCP store, so every request is a real socket round trip — in two
configurations:

- **tuned** (the shipped defaults): TreeBarrier, batched
  ``multi_set``/``multi_get``/``multi_delete`` wire ops, exponential
  poll backoff, 2 store shards;
- **baseline** (the pre-PR structures): LinearBarrier, per-key wire
  ops (the ``PerKeyStore`` adapter hides the batched commands), fixed
  5 ms polling, a single hub store.

Records the per-structure coordination split (collectives, barrier,
fan-out exchange, endpoint resolve — straggler wall per rank) per
world, the tuned/baseline speedup, and the tree barrier's growth curve
(per-step barrier wall, warmed up so thread-spawn skew is excluded).
Emits one JSON line on stdout; ``--json`` is accepted for symmetry
with the other legs.

    python benchmarks/coordination_scaling.py --worlds 8,64,256 --json
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _split(result) -> dict:
    return {
        "wall_s": result.wall_s,
        "coordination_s": round(result.coordination_s, 4),
        "barrier_s": result.max_s["barrier_s"],
        "exchange_s": result.max_s["exchange_s"],
        "collective_s": result.max_s["collective_s"],
        "endpoint_s": result.max_s["endpoint_s"],
        "store_requests": result.store_requests,
        "errors": len(result.errors),
        "hung": result.hung_ranks,
        "verified_ranks": result.verified_ranks,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--worlds", default="8,64,256")
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--shard-bytes", type=int, default=2048)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()
    worlds = [int(w) for w in args.worlds.split(",") if w]

    from torchsnapshot_tpu.scalemodel import StormConfig, run_storm

    out = {
        "worlds": worlds,
        "steps": args.steps,
        "per_world": {},
    }
    barrier_exchange = {}
    for world in worlds:
        timeout = max(120.0, world * 1.5)
        tuned = run_storm(
            StormConfig(
                world_size=world,
                steps=args.steps,
                warmup_steps=1,
                store="tcp",
                store_shards=2,
                shard_bytes=args.shard_bytes,
                timeout_s=timeout,
            )
        )
        baseline = run_storm(
            StormConfig(
                world_size=world,
                steps=args.steps,
                warmup_steps=1,
                barrier="linear",
                batched=False,
                legacy_poll=True,
                store="tcp",
                shard_bytes=args.shard_bytes,
                timeout_s=timeout,
            )
        )
        t, b = _split(tuned), _split(baseline)
        speedup = (
            round(b["coordination_s"] / t["coordination_s"], 2)
            if t["coordination_s"] > 0
            else None
        )
        be_tuned = t["barrier_s"] + t["exchange_s"]
        be_base = b["barrier_s"] + b["exchange_s"]
        be_speedup = round(be_base / be_tuned, 2) if be_tuned > 0 else None
        barrier_exchange[world] = be_speedup
        out["per_world"][str(world)] = {
            "tuned": t,
            "baseline": b,
            "coordination_speedup": speedup,
            "barrier_exchange_speedup": be_speedup,
        }
        log(
            f"coordination-scaling: world {world}: tuned "
            f"{t['coordination_s']:.2f}s vs baseline "
            f"{b['coordination_s']:.2f}s ({speedup}x; barrier+exchange "
            f"{be_speedup}x)"
        )

    # Barrier growth curves on the in-process store: pure protocol cost
    # (no socket layer), barrier-only storms, warmed up — the curve the
    # sub-linearity claim is graded on. Alongside the wall growth, the
    # hot DATA key fan-in (the error key is one shared poll target by
    # design): the tree bounds it at O(fanout) where the linear barrier
    # concentrates O(world · polls) on its leader keys.
    growth_steps = 6
    curves = {}
    for barrier in ("tree", "linear"):
        curve = {}
        for world in worlds:
            r = run_storm(
                StormConfig(
                    world_size=world,
                    steps=growth_steps,
                    warmup_steps=2,
                    barrier=barrier,
                    store="inprocess",
                    save_collectives=False,
                    restore_storm=False,
                    endpoint_round=False,
                    timeout_s=max(120.0, world * 1.0),
                )
            )
            curve[str(world)] = {
                "barrier_step_s": round(
                    r.max_s["barrier_s"] / growth_steps, 4
                ),
                "hot_data_key_touches": r.hot_data_key_touches,
                "hot_data_key": r.hot_data_key,
                "errors": len(r.errors),
            }
        curves[barrier] = curve
    out["barrier_growth"] = curves

    if len(worlds) >= 2:
        import math

        lo, hi = worlds[0], worlds[-1]
        world_ratio = round(hi / lo, 2)
        lo_t = curves["tree"][str(lo)]["barrier_step_s"]
        hi_t = curves["tree"][str(hi)]["barrier_step_s"]
        growth = round(hi_t / lo_t, 2) if lo_t > 0 else None
        slope = (
            round(math.log(hi_t / lo_t) / math.log(hi / lo), 3)
            if lo_t and hi_t
            else None
        )
        lo_k = curves["tree"][str(lo)]["hot_data_key_touches"]
        hi_k = curves["tree"][str(hi)]["hot_data_key_touches"]
        fanin_growth = round(hi_k / lo_k, 2) if lo_k else None
        out["tree_growth"] = growth
        out["tree_growth_slope"] = slope
        out["tree_hot_key_fanin_growth"] = fanin_growth
        out["world_ratio"] = world_ratio
        # Sub-linear when BOTH the wall curve's log-log slope is < 1 and
        # the per-key fan-in stayed bounded (grew slower than world).
        out["sublinear"] = (
            slope is not None
            and slope < 1.0
            and fanin_growth is not None
            and fanin_growth < world_ratio
        )
        out["coordination_speedup_max_world"] = out["per_world"][str(hi)][
            "coordination_speedup"
        ]
        out["barrier_exchange_speedup_max_world"] = barrier_exchange[hi]
        log(
            f"coordination-scaling: tree barrier growth {lo}->{hi}: "
            f"{growth}x wall (log-log slope {slope}), hot-key fan-in "
            f"{fanin_growth}x over {world_ratio}x world "
            f"({'sub' if out['sublinear'] else 'NOT sub'}-linear)"
        )

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
