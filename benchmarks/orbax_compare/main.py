"""Head-to-head vs the incumbent TPU checkpointer (orbax).

Reference parity: benchmarks/deepspeed_opt/main.py compares the patched
torchsnapshot save path against the framework-native checkpoint
(DeepSpeed's). The TPU-native incumbent is orbax: save and restore the
same sharded pytree with both systems and report wall time each way.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/orbax_compare/main.py --gb 1
"""

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402


def make_state(mesh: Mesh, total_bytes: int, seed: int):
    """Sharded fp32 blocks approximating a model's parameter pytree."""
    block_rows = 4096
    block_cols = 1024
    block_bytes = block_rows * block_cols * 4
    n = max(1, total_bytes // block_bytes)
    sharding = NamedSharding(mesh, P("x", None))
    key = jax.random.PRNGKey(seed)
    out = {}
    for i in range(n):
        key, sub = jax.random.split(key)
        out[f"w{i}"] = jax.device_put(
            jax.random.normal(sub, (block_rows, block_cols), jax.numpy.float32),
            sharding,
        )
    jax.block_until_ready(out)
    return out


def bench_snapshot(path: str, state, dest) -> None:
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    t0 = time.perf_counter()
    ts.Snapshot.take(path, {"m": ts.PyTreeState(state)})
    save_s = time.perf_counter() - t0
    dest_state = ts.PyTreeState(dest)
    t0 = time.perf_counter()
    ts.Snapshot(path).restore({"m": dest_state})
    load_s = time.perf_counter() - t0
    np.testing.assert_array_equal(
        np.asarray(dest_state.tree["w0"]), np.asarray(state["w0"])
    )
    gib = nbytes / (1 << 30)
    print(
        f"torchsnapshot_tpu: save {save_s:.2f}s ({gib / save_s:.2f} GB/s), "
        f"restore {load_s:.2f}s ({gib / load_s:.2f} GB/s)"
    )

    # Incremental dimension — no orbax counterpart (every orbax save
    # rewrites all bytes): unchanged-state save after a digest-recorded
    # base, the steady-state cost of checkpointing a converged/frozen
    # component. Warm once for the digest-program compile. Fail-soft:
    # this context line must never kill the primary comparison.
    try:
        base = path + "_base"
        ts.Snapshot.take(
            base, {"m": ts.PyTreeState(state)}, record_digests=True
        )
        ts.Snapshot.take(
            path + "_iwarm", {"m": ts.PyTreeState(state)}, incremental_base=base
        )
        t0 = time.perf_counter()
        ts.Snapshot.take(
            path + "_incr", {"m": ts.PyTreeState(state)}, incremental_base=base
        )
        incr_s = time.perf_counter() - t0
        print(
            f"torchsnapshot_tpu: incremental save (unchanged) {incr_s:.2f}s "
            f"({save_s / incr_s:.0f}x vs full; orbax has no counterpart)"
        )
    except Exception as e:  # noqa: BLE001
        print(f"incremental measurement skipped: {e!r}")


def bench_orbax(path: str, state, dest) -> None:
    import orbax.checkpoint as ocp

    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    with ocp.PyTreeCheckpointer() as ckptr:
        t0 = time.perf_counter()
        ckptr.save(path, state)
        save_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        restored = ckptr.restore(
            path,
            args=ocp.args.PyTreeRestore(
                restore_args=jax.tree_util.tree_map(
                    lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding), dest
                )
            ),
        )
        load_s = time.perf_counter() - t0
    np.testing.assert_array_equal(
        np.asarray(restored["w0"]), np.asarray(state["w0"])
    )
    gib = nbytes / (1 << 30)
    print(
        f"orbax:             save {save_s:.2f}s ({gib / save_s:.2f} GB/s), "
        f"restore {load_s:.2f}s ({gib / load_s:.2f} GB/s)"
    )


def run_json(gb: float, trials: int) -> dict:
    """Interleaved A/B trials, medians, and orbax/ts ratios (>1 = this
    framework is faster). One JSON-able dict; checksums stay ON for our
    restore (the default), which orbax's restore has no counterpart for.

    Fairness guards: each system saves a FRESH state every trial (jax
    caches an array's host copy after its first D2H — sharing one state
    would hand whichever system saves second a memcpy instead of the
    device link), the save order alternates per trial (neither system
    systematically pays first-touch costs), and ``os.sync()`` runs before
    every timed restore (background writeback from the preceding save
    otherwise inflates restore timings up to 10x on a one-core box).
    """
    import orbax.checkpoint as ocp

    mesh = Mesh(np.array(jax.devices()), ("x",))
    total = int(gb * (1 << 30))
    dest = make_state(mesh, total, seed=999)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(dest))
    restore_args = ocp.args.PyTreeRestore(
        restore_args=jax.tree_util.tree_map(
            lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding), dest
        )
    )

    ts_saves, ts_restores, ob_saves, ob_restores = [], [], [], []
    work_dir = tempfile.mkdtemp(prefix="ts_bench_orbax_")
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            for t in range(trials):
                ts_state = make_state(mesh, total, seed=2 * t)
                ob_state = make_state(mesh, total, seed=2 * t + 1)
                ts_path = os.path.join(work_dir, f"ts{t}")
                ob_path = os.path.join(work_dir, f"ob{t}")

                def save_ts():
                    t0 = time.perf_counter()
                    ts.Snapshot.take(ts_path, {"m": ts.PyTreeState(ts_state)})
                    ts_saves.append(time.perf_counter() - t0)

                def save_ob():
                    t0 = time.perf_counter()
                    ckptr.save(ob_path, ob_state)
                    ob_saves.append(time.perf_counter() - t0)

                for save in [save_ts, save_ob] if t % 2 == 0 else [save_ob, save_ts]:
                    save()

                dest_state = ts.PyTreeState(dest)
                os.sync()
                t0 = time.perf_counter()
                ts.Snapshot(ts_path).restore({"m": dest_state})
                jax.block_until_ready(dest_state.tree)
                ts_restores.append(time.perf_counter() - t0)
                np.testing.assert_array_equal(
                    np.asarray(dest_state.tree["w0"]),
                    np.asarray(ts_state["w0"]),
                )

                os.sync()
                t0 = time.perf_counter()
                restored = ckptr.restore(ob_path, args=restore_args)
                jax.block_until_ready(restored)
                ob_restores.append(time.perf_counter() - t0)
                np.testing.assert_array_equal(
                    np.asarray(restored["w0"]), np.asarray(ob_state["w0"])
                )
                print(
                    f"trial {t}: ts save {ts_saves[-1]:.2f}s / "
                    f"orbax save {ob_saves[-1]:.2f}s; ts restore "
                    f"{ts_restores[-1]:.2f}s / orbax restore "
                    f"{ob_restores[-1]:.2f}s",
                    file=sys.stderr,
                )
                del ts_state, ob_state, restored, dest_state
                shutil.rmtree(ts_path, ignore_errors=True)
                shutil.rmtree(ob_path, ignore_errors=True)
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)

    ts_save = statistics.median(ts_saves)
    ob_save = statistics.median(ob_saves)
    ts_restore = statistics.median(ts_restores)
    ob_restore = statistics.median(ob_restores)
    return {
        "size_gib": round(nbytes / (1 << 30), 2),
        "trials": trials,
        "ts_save_s": [round(x, 2) for x in ts_saves],
        "orbax_save_s": [round(x, 2) for x in ob_saves],
        "ts_restore_s": [round(x, 2) for x in ts_restores],
        "orbax_restore_s": [round(x, 2) for x in ob_restores],
        "orbax_save_ratio": round(ob_save / ts_save, 2),
        "orbax_restore_ratio": round(ob_restore / ts_restore, 2),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--gb", type=float, default=1.0)
    p.add_argument("--trials", type=int, default=3)
    p.add_argument(
        "--json",
        action="store_true",
        help="interleaved A/B trials; print one JSON line with medians "
        "and orbax/ts ratios (bench.py consumes this)",
    )
    args = p.parse_args()

    if args.json:
        print(json.dumps(run_json(args.gb, args.trials)))
        return

    mesh = Mesh(np.array(jax.devices()), ("x",))
    state = make_state(mesh, int(args.gb * (1 << 30)), seed=0)
    dest = make_state(mesh, int(args.gb * (1 << 30)), seed=1)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    print(f"state: {nbytes / (1 << 30):.2f} GiB across "
          f"{len(jax.devices())} devices")

    work_dir = tempfile.mkdtemp(prefix="ts_bench_orbax_")
    try:
        bench_snapshot(os.path.join(work_dir, "snap"), state, dest)
        try:
            bench_orbax(os.path.join(work_dir, "orbax"), state, dest)
        except Exception as e:  # orbax optional / API drift tolerated
            print(f"orbax comparison skipped: {e!r}")
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
