"""Microbench: Pallas flash attention vs the dense einsum op on one chip.

Reference anchor: the reference has no attention at all (it is a
checkpointing library); this benchmarks the flagship workload's hot op on
the hardware it was written for, reporting achieved attention FLOP/s and
the flash/dense speedup across sequence lengths.

Run: python benchmarks/flash_attention/main.py          (real TPU)
     JAX_PLATFORMS=cpu python ... --interpret           (smoke test)
"""

import argparse
import sys
import time

sys.path.append(__import__("os").path.join(__import__("os").path.dirname(__file__), ".."))
import common  # noqa: F401  (path + platform pinning)

import jax
import jax.numpy as jnp
import numpy as np

from torchsnapshot_tpu.ops.attention import causal_attention
from torchsnapshot_tpu.ops.flash_attention import flash_causal_attention


def timeit(fn, q, k, v, iters=10):
    """One-dispatch chained timing: a single jitted ``fori_loop`` runs
    ``iters`` data-dependent kernels, and a scalar fetch forces
    completion. Needed on tunneled devices, where per-call dispatch RTT
    (~15 ms) floors unfused timings and ``block_until_ready`` can return
    at enqueue — only a fused loop + D2H readback measures the kernel."""

    def chained(n):
        @jax.jit
        def run(q, k, v):
            body = lambda _, x: fn(x, k, v).astype(q.dtype)
            return jnp.sum(jax.lax.fori_loop(0, n, body, q))

        return run

    # Pilot: estimate per-iteration time, then size the real run so fused
    # compute (>= 0.5 s) dwarfs the tunnel's RTT jitter.
    pilot = chained(iters)
    float(pilot(q, k, v))  # compile + warm
    t0 = time.perf_counter()
    float(pilot(q, k, v))
    t_est = max((time.perf_counter() - t0) / iters, 1e-6)
    n = min(max(iters, int(0.5 / t_est)), 4096)
    run = chained(n)
    float(run(q, k, v))  # compile + warm
    best = float("inf")
    for _ in range(3):  # min-of-3: the dev chip is shared and noisy
        t0 = time.perf_counter()
        float(run(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return best / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    b, h, d = 4, 8, 128
    print(f"device: {jax.devices()[0]}  b={b} h={h} d={d}")
    print(f"{'seq':>6} {'dense ms':>9} {'flash ms':>9} {'speedup':>8} "
          f"{'flash TFLOP/s':>13}")
    for s in (1024, 2048, 4096, 8192):
        rng = np.random.default_rng(s)
        q, k, v = (
            jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
            for _ in range(3)
        )
        dense = jax.jit(causal_attention)
        flash = jax.jit(
            lambda q, k, v: flash_causal_attention(
                q, k, v, interpret=args.interpret
            )
        )
        t_flash = timeit(flash, q, k, v, iters=args.iters)
        try:
            dense_out = np.asarray(dense(q, k, v), np.float32)
        except Exception:
            # The s^2 logits tensor no longer fits in HBM — the reason the
            # flash kernel exists. Flash keeps going. (Only the dense
            # computation is guarded: a flash-vs-dense MISMATCH must
            # propagate, never masquerade as a capacity limit.)
            dense_ms, speedup = f"{'OOM':>9}", f"{'—':>8}"
        else:
            np.testing.assert_allclose(
                np.asarray(flash(q, k, v), np.float32),
                dense_out,
                atol=0.06, rtol=0.06,
            )
            t_dense = timeit(dense, q, k, v, iters=args.iters)
            dense_ms, speedup = f"{t_dense*1e3:9.2f}", f"{t_dense/t_flash:8.2f}"
        # causal attention FLOPs: 2 matmuls * 2*b*h*s^2*d, halved by causality
        flops = 2 * 2 * b * h * s * s * d / 2
        print(
            f"{s:>6} {dense_ms} {t_flash*1e3:>9.2f} "
            f"{speedup} {flops/t_flash/1e12:>13.2f}"
        )


if __name__ == "__main__":
    main()
