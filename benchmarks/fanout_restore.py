"""Fan-out vs fallback restore timing on a 2-process CPU-mesh fleet.

The read-path half of the BENCH record's distributed story: a sharded
snapshot is taken once, then restored by a 2-process group twice —
fan-out ON (each unique saved shard fetched from storage exactly once,
peers fed over the coordination store) and OFF (every rank reads every
byte itself) — recording wall time and the fleet read-amplification
ratio ``total_bytes_fetched / unique_checkpoint_bytes`` (fallback ~=
world size, fan-out ~= 1.0). Spawned by bench.py's subprocess-leg
runner; emits one JSON line on stdout.

    python benchmarks/fanout_restore.py --mib 256 --json
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _restore_worker(pg, path, shape, fanout):
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu import telemetry

    os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = "1" if fanout else "0"
    import jax

    dest = {
        "state": ts.PyTreeState(
            {"w": jnp.zeros(shape, jnp.float32)}
        )
    }
    jax.block_until_ready(dest["state"].tree)
    t0 = time.perf_counter()
    ts.Snapshot(path, pg=pg).restore(dest)
    jax.block_until_ready(dest["state"].tree)
    dt = time.perf_counter() - t0
    report = telemetry.last_report("restore", path=path)
    row = {
        "rank": pg.rank,
        "restore_s": round(dt, 3),
        "bytes_fetched": report.bytes_fetched if report else None,
        "bytes_received": report.bytes_received if report else None,
        "bytes_needed": report.bytes_needed if report else None,
    }
    # Integrity spot check, not a benchmark assert: the zero-initialized
    # destination must have been overwritten end to end.
    np_dest = np.asarray(dest["state"].tree["w"])
    assert np_dest[0].any() and np_dest[-1].any()
    return row


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--mib", type=float, default=64.0)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.knobs import override_max_shard_size_bytes
    from torchsnapshot_tpu.manifest import sharded_blob_windows
    from torchsnapshot_tpu.test_utils import run_multiprocess

    devs = jax.devices()
    ways = min(8, len(devs))
    cols = 1024
    rows = max(ways, int(args.mib * 1024 * 1024 / 4 / cols) // ways * ways)
    shape = (rows, cols)
    gib = rows * cols * 4 / 1024**3
    path = os.path.join(
        tempfile.mkdtemp(prefix="ts-fanout-bench-"), "snap"
    )

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, shape, jnp.float32) + 1.0
    xs = jax.device_put(
        x, NamedSharding(Mesh(np.array(devs[:ways]), ("x",)), P("x"))
    )
    # Several shard blobs per device shard so the owner table spreads.
    with override_max_shard_size_bytes(
        max(1 << 20, int(rows * cols * 4 / (ways * 4)))
    ):
        ts.Snapshot.take(path, {"state": ts.PyTreeState({"w": xs})})
    del x, xs
    unique_bytes = sum(
        hi - lo
        for lo, hi in sharded_blob_windows(
            ts.Snapshot(path).metadata.manifest
        ).values()
    )
    log(
        f"fanout-restore: {gib:.2f} GiB snapshot, "
        f"{unique_bytes / 1024**2:.0f} MiB unique shard bytes"
    )

    out = {"state_gib": round(gib, 3), "unique_shard_mib": round(
        unique_bytes / 1024**2, 1
    )}
    for fanout, key_prefix in ((True, "fanout"), (False, "fallback")):
        t0 = time.perf_counter()
        rows_out = run_multiprocess(
            _restore_worker,
            nproc=2,
            args=(path, shape, fanout),
            timeout=600.0,
        )
        wall = time.perf_counter() - t0
        restore_s = max(r["restore_s"] for r in rows_out)
        fetched = sum(r["bytes_fetched"] or 0 for r in rows_out)
        out[f"{key_prefix}_restore_s"] = restore_s
        out[f"{key_prefix}_wall_s"] = round(wall, 3)
        out[f"{key_prefix}_read_amplification"] = (
            round(fetched / unique_bytes, 3) if unique_bytes else None
        )
        out[f"{key_prefix}_per_rank"] = rows_out
        log(
            f"fanout-restore: {key_prefix} restore {restore_s:.2f} s, "
            f"fleet amplification "
            f"{out[f'{key_prefix}_read_amplification']}x"
        )

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
