"""Incremental-checkpoint benchmark: fine-tune-shaped state, full takes
vs digest-gated incremental takes.

No reference counterpart (the reference rewrites all bytes every take).
The workload models the states where incremental checkpointing pays:

- ``base``: a large frozen sharded tower (LoRA/adapter fine-tunes, EMA
  copies, frozen embedding stacks) — never changes after step 0.
- ``adapter``: small trainable weights + their optimizer moments —
  change every step, always rewritten.
- ``table``: a row-sharded embedding table whose updates hit a *hot
  region* (clustered rows) — chunk-level skipping keeps the cold chunks.

An adversarial case is also reported: ``--uniform-table`` scatters the
table updates uniformly, which dirties every skip-unit chunk and shows
incremental degrading gracefully to ~full cost plus digest overhead
(wall-time numbers below include that overhead; nothing is hidden).

Measured per save: wall time, bytes written to storage, and — the number
that matters on TPU — bytes *staged* across the device→host link.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/incremental/main.py

On the real chip drop JAX_PLATFORMS (the tunnel's D2H makes the staged-
bytes reduction directly visible as wall time).
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402


def tree_bytes(tree) -> int:
    return sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(tree)
    )


def dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _, files in os.walk(path):
        for f in files:
            total += os.path.getsize(os.path.join(dirpath, f))
    return total


def make_state(mesh, base_mib: int, table_rows: int, dim: int, seed: int):
    sharding = NamedSharding(mesh, P("x", None))
    key = jax.random.PRNGKey(seed)
    n_base = max(1, base_mib // 16)
    base = {}
    for i in range(n_base):
        key, k = jax.random.split(key)
        base[f"layer_{i}"] = jax.device_put(
            jax.random.normal(k, (4096 * 1024 // dim, dim), jax.numpy.float32),
            sharding,
        )
    key, k1, k2, k3 = jax.random.split(key, 4)
    state = {
        "base": base,
        "adapter": {
            "w": jax.random.normal(k1, (512, 512), jax.numpy.float32),
            "m": jax.random.normal(k2, (512, 512), jax.numpy.float32),
        },
        "table": jax.device_put(
            jax.random.normal(k3, (table_rows, dim), jax.numpy.float32),
            sharding,
        ),
    }
    jax.block_until_ready(state)
    return state


def train_interval(state, step: int, frac: float, uniform: bool):
    """One save interval's worth of updates: adapter fully, table rows
    either clustered (hot region) or uniform (adversarial)."""
    table = state["table"]
    rows = table.shape[0]
    n = max(1, int(rows * frac))
    rng = np.random.default_rng(step)
    if uniform:
        idx = jax.numpy.asarray(rng.choice(rows, size=n, replace=False))
    else:
        start = int(rng.integers(0, max(1, rows - n)))
        idx = jax.numpy.arange(start, start + n)
    new_state = {
        "base": state["base"],  # frozen
        "adapter": {
            "w": state["adapter"]["w"] + 0.01,
            "m": state["adapter"]["m"] * 0.9,
        },
        "table": table.at[idx].add(0.01),
    }
    jax.block_until_ready(new_state)
    return new_state


class StagedBytesCounter:
    """Counts bytes through ArrayBufferStager._stage_sync — the actual
    device→host traffic a take causes."""

    def __init__(self) -> None:
        self.bytes = 0

    def __enter__(self):
        from torchsnapshot_tpu import io_preparer

        self._orig = io_preparer.ArrayBufferStager._stage_sync
        counter = self

        def counting(stager):
            buf = counter._orig(stager)
            counter.bytes += memoryview(buf).nbytes
            return buf

        io_preparer.ArrayBufferStager._stage_sync = counting
        return self

    def __exit__(self, *exc):
        from torchsnapshot_tpu import io_preparer

        io_preparer.ArrayBufferStager._stage_sync = self._orig
        return False


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--base-mib", type=int, default=64)
    p.add_argument("--table-rows", type=int, default=65536)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--update-frac", type=float, default=0.01)
    p.add_argument("--uniform-table", action="store_true")
    p.add_argument("--steps", type=int, default=4)
    p.add_argument(
        "--incremental-chunk-kib",
        type=int,
        default=512,
        help="skip-unit granularity (INCREMENTAL_CHUNK_BYTES knob)",
    )
    p.add_argument("--root", type=str, default=None)
    args = p.parse_args()

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("x",))
    state = make_state(mesh, args.base_mib, args.table_rows, args.dim, seed=0)
    state_gib = tree_bytes(state) / (1 << 30)
    print(
        f"state: {state_gib:.3f} GiB ({args.base_mib} MiB frozen base, "
        f"{args.table_rows}x{args.dim} table with "
        f"{'uniform' if args.uniform_table else 'clustered'} "
        f"{args.update_frac:.1%} row updates, 2 MiB trainable adapter) "
        f"on {len(devices)} {devices[0].platform} devices; "
        f"skip unit {args.incremental_chunk_kib} KiB"
    )

    root = args.root or tempfile.mkdtemp(prefix="ts-incremental-bench-")
    shutil.rmtree(root, ignore_errors=True)

    from torchsnapshot_tpu.knobs import override_incremental_chunk_size_bytes

    mgr_full = ts.CheckpointManager(root + "/full")
    mgr_incr = ts.CheckpointManager(root + "/incr", incremental=True)

    rows = []
    with override_incremental_chunk_size_bytes(
        args.incremental_chunk_kib * 1024
    ):
        for step in range(args.steps):
            if step > 0:
                state = train_interval(
                    state, step, args.update_frac, args.uniform_table
                )

            with StagedBytesCounter() as cf:
                t0 = time.perf_counter()
                mgr_full.save(step, {"m": ts.PyTreeState(state)})
                t_full = time.perf_counter() - t0
            b_full = dir_bytes(os.path.join(root, "full", f"step_{step:010d}"))

            with StagedBytesCounter() as ci:
                t0 = time.perf_counter()
                mgr_incr.save(step, {"m": ts.PyTreeState(state)})
                t_incr = time.perf_counter() - t0
            b_incr = dir_bytes(os.path.join(root, "incr", f"step_{step:010d}"))

            rows.append(
                (step, t_full, b_full, cf.bytes, t_incr, b_incr, ci.bytes)
            )
            print(
                f"step {step}: full {t_full:6.2f}s {b_full / 1e6:8.1f} MB "
                f"written {cf.bytes / 1e6:8.1f} MB staged | incremental "
                f"{t_incr:6.2f}s {b_incr / 1e6:8.1f} MB written "
                f"{ci.bytes / 1e6:8.1f} MB staged"
            )

        # Steady-state = mean over the sparse-update steps (step 0 is the
        # unavoidable full base for both modes).
        if len(rows) > 1:
            ss = rows[1:]
            f_t = sum(r[1] for r in ss) / len(ss)
            i_t = sum(r[4] for r in ss) / len(ss)
            f_b = sum(r[2] for r in ss) / len(ss)
            i_b = sum(r[5] for r in ss) / len(ss)
            f_s = sum(r[3] for r in ss) / len(ss)
            i_s = sum(r[6] for r in ss) / len(ss)
            print(
                f"steady-state means: save time {f_t:.2f}s -> {i_t:.2f}s "
                f"({f_t / max(i_t, 1e-9):.1f}x), bytes written "
                f"{f_b / 1e6:.1f} -> {i_b / 1e6:.1f} MB "
                f"({f_b / max(i_b, 1):.1f}x), bytes staged (D2H) "
                f"{f_s / 1e6:.1f} -> {i_s / 1e6:.1f} MB "
                f"({f_s / max(i_s, 1):.1f}x)"
            )

        # Correctness: restore the newest incremental step and compare.
        dest_state = make_state(
            mesh, args.base_mib, args.table_rows, args.dim, seed=1
        )
        dest = {"m": ts.PyTreeState(dest_state)}
        t0 = time.perf_counter()
        mgr_incr.restore_latest(dest)
        t_restore = time.perf_counter() - t0
        got = jax.tree_util.tree_leaves(dest["m"].tree)
        want = jax.tree_util.tree_leaves(state)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
        print(f"restore(latest incremental): {t_restore:.2f}s, byte-identical")

    if args.root is None:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
