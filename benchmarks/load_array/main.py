"""Budget-bounded single-array load (reference benchmarks/load_tensor/main.py).

Writes one large array, then reads it back with and without a memory
budget while sampling RSS — demonstrating that ranged chunk reads keep host
memory bounded at the budget rather than the array size.

    python benchmarks/load_array/main.py --gb 2 --budget-mb 100
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402

import numpy as np  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402
from torchsnapshot_tpu.utils import RSSDeltas, measure_rss_deltas  # noqa: E402


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--gb", type=float, default=2.0)
    p.add_argument("--budget-mb", type=int, default=100)
    args = p.parse_args()

    n = int(args.gb * (1 << 30) / 4)
    side = int(np.sqrt(n))
    arr = np.random.default_rng(0).standard_normal((side, side)).astype(np.float32)
    print(f"array: {arr.nbytes / (1 << 30):.2f} GiB")

    work_dir = tempfile.mkdtemp(prefix="ts_bench_load_")
    try:
        path = os.path.join(work_dir, "snap")
        ts.Snapshot.take(path, {"t": ts.PyTreeState({"x": arr})})
        snapshot = ts.Snapshot(path)

        for budget in (None, args.budget_mb * (1 << 20)):
            out = np.zeros_like(arr)
            rss = RSSDeltas()
            t0 = time.perf_counter()
            with measure_rss_deltas(rss):
                snapshot.read_object("0/t/x", obj_out=out, memory_budget_bytes=budget)
            elapsed = time.perf_counter() - t0
            np.testing.assert_array_equal(out, arr)
            label = "unbounded" if budget is None else f"{args.budget_mb} MB budget"
            print(
                f"load ({label}): {elapsed:.2f}s "
                f"({arr.nbytes / (1 << 30) / elapsed:.2f} GB/s), "
                f"peak RSS delta {rss.peak_bytes / (1 << 20):.0f} MB"
            )
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
