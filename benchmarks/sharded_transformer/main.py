"""Sharded-transformer checkpoint benchmark (reference benchmarks/fsdp/main.py).

Builds the flagship transformer over the visible device mesh, runs one
training step, then times Snapshot save and restore of the full sharded
train state (params + adam moments).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/sharded_transformer/main.py --d-model 512 --layers 8
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402
from torchsnapshot_tpu.models import (  # noqa: E402
    TransformerConfig,
    init_train_state,
    make_mesh,
    make_train_step,
)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--experts", type=int, default=0)
    p.add_argument("--async-take", action="store_true")
    p.add_argument(
        "--json",
        action="store_true",
        help="append one JSON line with the measurements (bench.py "
        "consumes this; human-readable lines go to stderr)",
    )
    args = p.parse_args()
    out = sys.stderr if args.json else sys.stdout

    def say(msg: str) -> None:
        print(msg, file=out)

    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=max(4, args.d_model // 64),
        n_layers=args.layers,
        d_ff=args.d_model * 4,
        n_experts=args.experts,
    )
    mesh = make_mesh()
    say(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    state = init_train_state(cfg, seed=0, mesh=mesh)
    step_fn = make_train_step(cfg, mesh=mesh)
    tokens = jax.device_put(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 128)).astype(np.int32),
        NamedSharding(mesh, P("dp", None)),
    )
    state, _ = step_fn(state, tokens)
    jax.block_until_ready(state.params)

    tree = state.as_pytree()
    nbytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "nbytes")
    )
    say(f"train state: {nbytes / (1 << 30):.2f} GiB")

    record = {"state_gib": round(nbytes / (1 << 30), 3)}
    work_dir = tempfile.mkdtemp(prefix="ts_bench_fsdp_")
    try:
        path = os.path.join(work_dir, "snap")
        t0 = time.perf_counter()
        if args.async_take:
            pending = ts.Snapshot.async_take(path, {"train": ts.PyTreeState(tree)})
            blocked = time.perf_counter() - t0
            pending.wait(phase="staged")
            staged = time.perf_counter() - t0
            pending.wait()
            total = time.perf_counter() - t0
            say(
                f"async save: blocked {blocked:.3f}s, staged {staged:.2f}s, "
                f"total {total:.2f}s ({nbytes / (1 << 30) / total:.2f} GB/s)"
            )
            record["stall_ms"] = round(blocked * 1000, 1)
            record["staged_ms"] = round(staged * 1000, 1)
            record["save_total_s"] = round(total, 2)
        else:
            ts.Snapshot.take(path, {"train": ts.PyTreeState(tree)})
            total = time.perf_counter() - t0
            say(
                f"sync save: {total:.2f}s ({nbytes / (1 << 30) / total:.2f} GB/s)"
            )
            record["save_total_s"] = round(total, 2)

        dest = ts.PyTreeState(state.as_pytree())
        t0 = time.perf_counter()
        ts.Snapshot(path).restore({"train": dest})
        total = time.perf_counter() - t0
        say(f"restore: {total:.2f}s ({nbytes / (1 << 30) / total:.2f} GB/s)")
        record["restore_s"] = round(total, 2)
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)
    if args.json:
        print(json.dumps(record))


if __name__ == "__main__":
    main()
