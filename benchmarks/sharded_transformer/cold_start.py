"""Restore-to-step0 cold start: sync restore vs async restore overlapped
with train-step compilation.

The north-star breakdown (BENCH.md) shows a cold start is dominated by
XLA compilation, with the checkpoint restore serialized before it. Async
restore (Snapshot.async_restore) hides the restore I/O under the compile:

    pending = snapshot.async_restore(app_state)   # reads stream in
    compiled = step.lower(state, batch).compile()  # compile overlaps
    pending.wait()                                 # apply

Run each mode in a fresh process (jit caches would poison the compile
timing):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/sharded_transformer/cold_start.py --mode sync
    ... --mode async
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402
from torchsnapshot_tpu.models import (  # noqa: E402
    TransformerConfig,
    init_train_state,
    make_mesh,
    make_train_step,
)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["sync", "async"], required=True)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--snap", type=str, default=None,
                   help="existing snapshot dir (created if absent)")
    p.add_argument("--prep-only", action="store_true",
                   help="create the snapshot and exit (no timing)")
    p.add_argument("--json", action="store_true",
                   help="print a final machine-readable JSON line")
    args = p.parse_args()

    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=max(4, args.d_model // 64),
        n_layers=args.layers,
        d_ff=args.d_model * 4,
    )
    mesh = make_mesh()
    tokens = jax.device_put(
        np.random.default_rng(0)
        .integers(0, cfg.vocab_size, (8, 128))
        .astype(np.int32),
        NamedSharding(mesh, P("dp", None)),
    )

    snap_dir = args.snap or os.path.join(
        tempfile.gettempdir(), "ts-cold-start-snap"
    )
    if not os.path.exists(os.path.join(snap_dir, ".snapshot_metadata")):
        shutil.rmtree(snap_dir, ignore_errors=True)
        state = init_train_state(cfg, seed=7, mesh=mesh)
        ts.Snapshot.take(snap_dir, {"train": ts.PyTreeState(state.as_pytree())})
        print(f"(snapshot created at {snap_dir}; re-run for timing)")
    if args.prep_only:
        if args.json:
            print(json.dumps({"prep": "done", "snap": snap_dir}))
        return

    t_start = time.perf_counter()
    state = init_train_state(cfg, seed=0, mesh=mesh)
    jax.block_until_ready(state.params)
    t_init = time.perf_counter() - t_start
    step_fn = make_train_step(cfg, mesh=mesh)
    dest = ts.PyTreeState(state.as_pytree())

    if args.mode == "sync":
        t0 = time.perf_counter()
        ts.Snapshot(snap_dir).restore({"train": dest})
        t_restore = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = step_fn.lower(state, tokens).compile()
        t_compile = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        pending = ts.Snapshot(snap_dir).async_restore({"train": dest})
        compiled = step_fn.lower(state, tokens).compile()
        t_compile = time.perf_counter() - t0
        t0 = time.perf_counter()
        pending.wait()
        t_restore = time.perf_counter() - t0  # the part NOT hidden

    # Rebuild the train state around the restored pytree and take step 0.
    from torchsnapshot_tpu.models.transformer import TrainState

    restored = TrainState(
        params=dest.tree["params"],
        opt_state=dest.tree["opt_state"],
        step=dest.tree["step"],
        rng=dest.tree["rng"],
    )
    t0 = time.perf_counter()
    new_state, loss = compiled(restored, tokens)
    jax.block_until_ready(new_state.params)
    t_step = time.perf_counter() - t0
    total = time.perf_counter() - t_start

    print(
        f"mode={args.mode}: init {t_init:.2f}s, "
        f"{'restore' if args.mode == 'sync' else 'restore-not-hidden'} "
        f"{t_restore:.2f}s, compile {t_compile:.2f}s, step0 {t_step:.2f}s, "
        f"TOTAL {total:.2f}s (loss {float(loss):.3f})"
    )
    if args.json:
        # restore_visible_s is the restore wall the application actually
        # waits on: the full restore in sync mode, only the part not
        # hidden under compilation in async mode.
        print(
            json.dumps(
                {
                    "mode": args.mode,
                    "init_s": round(t_init, 3),
                    "restore_visible_s": round(t_restore, 3),
                    "compile_s": round(t_compile, 3),
                    "step0_s": round(t_step, 3),
                    "total_s": round(total, 3),
                }
            )
        )


if __name__ == "__main__":
    main()
