"""Row-wise-sharded embedding-table checkpoint benchmark.

Reference parity: benchmarks/torchrec/main.py — large RW-sharded embedding
tables (the torchrec DLRM workload), measuring sync vs async take wall
time, the async *blocked* time (how long training is actually stalled,
reference :115-153), and peak host RSS under the scheduler's memory budget
(reference :211-231).

TPU-native shape: each table is one ``jax.Array`` sharded ``P("x", None)``
over the device mesh — the GSPMD analog of torchrec's row-wise
ShardingSpec. Restore goes into a differently-seeded destination to keep
the comparison honest.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/embedding_tables/main.py --tables 8 --rows 65536
"""

import argparse
import contextlib
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402
from torchsnapshot_tpu.utils.rss_profiler import (  # noqa: E402
    RSSDeltas,
    measure_rss_deltas,
)


def make_tables(mesh: Mesh, n_tables: int, rows: int, dim: int, seed: int):
    """RW-sharded embedding tables + fp32 per-row optimizer momentum (the
    fused-optimizer state torchrec checkpoints alongside the tables)."""
    sharding = NamedSharding(mesh, P("x", None))
    tables = {}
    key = jax.random.PRNGKey(seed)
    for i in range(n_tables):
        key, k1, k2 = jax.random.split(key, 3)
        tables[f"table_{i}"] = {
            "weight": jax.device_put(
                jax.random.normal(k1, (rows, dim), jax.numpy.float32), sharding
            ),
            "momentum": jax.device_put(
                jax.random.normal(k2, (rows, 1), jax.numpy.float32), sharding
            ),
        }
    jax.block_until_ready(tables)
    return tables


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--tables", type=int, default=8)
    p.add_argument("--rows", type=int, default=65536)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--memory-budget-mb", type=int, default=None)
    args = p.parse_args()

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("x",))
    print(f"mesh: {len(devices)} devices on axis 'x'")

    tables = make_tables(mesh, args.tables, args.rows, args.dim, seed=0)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(tables))
    print(f"{args.tables} tables x {args.rows} rows x {args.dim} dim = "
          f"{nbytes / (1 << 30):.2f} GiB")

    budget_ctx = (
        ts.override_per_rank_memory_budget_bytes(args.memory_budget_mb << 20)
        if args.memory_budget_mb
        else contextlib.nullcontext()
    )

    work_dir = tempfile.mkdtemp(prefix="ts_bench_emb_")
    try:
        with budget_ctx:
            # Sync take
            sync_path = os.path.join(work_dir, "sync")
            rss = RSSDeltas()
            t0 = time.perf_counter()
            with measure_rss_deltas(rss):
                ts.Snapshot.take(sync_path, {"emb": ts.PyTreeState(tables)})
            sync_s = time.perf_counter() - t0
            print(
                f"sync take:  {sync_s:.2f}s ({nbytes / (1 << 30) / sync_s:.2f} GB/s), "
                f"peak RSS delta {rss.peak_bytes / (1 << 20):.0f} MB"
            )

            # Async take: the blocked time is what training actually pays
            async_path = os.path.join(work_dir, "async")
            rss = RSSDeltas()
            t0 = time.perf_counter()
            with measure_rss_deltas(rss):
                pending = ts.Snapshot.async_take(
                    async_path, {"emb": ts.PyTreeState(tables)}
                )
                blocked_s = time.perf_counter() - t0
                pending.wait()
            total_s = time.perf_counter() - t0
            print(
                f"async take: blocked {blocked_s:.2f}s of {total_s:.2f}s total "
                f"({100 * blocked_s / total_s:.0f}% stall), "
                f"peak RSS delta {rss.peak_bytes / (1 << 20):.0f} MB"
            )

            # Restore into differently-seeded tables; verify a couple of leaves.
            dest = make_tables(mesh, args.tables, args.rows, args.dim, seed=1)
            dest_state = ts.PyTreeState(dest)
            t0 = time.perf_counter()
            ts.Snapshot(sync_path).restore({"emb": dest_state})
            restore_s = time.perf_counter() - t0
            print(
                f"restore:    {restore_s:.2f}s ({nbytes / (1 << 30) / restore_s:.2f} GB/s)"
            )
            np.testing.assert_array_equal(
                np.asarray(dest_state.tree["table_0"]["weight"]),
                np.asarray(tables["table_0"]["weight"]),
            )
            print("restore verified bitwise on table_0")
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
