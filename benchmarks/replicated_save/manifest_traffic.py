"""Metadata-plane traffic measurement: per-rank coordinator bytes for a
distributed take with a torchrec-scale manifest (default 4 ranks x 25k
leaves/rank = 1e5 total).

Round-3 review finding: the manifest all-exchange funneled
O(world x manifest) bytes through rank 0's store socket *per rank*.
Round 4 gathers to the leader only (non-leaders lazy-load committed
metadata from storage), so each non-leader's coordinator ingress drops
from O(world x manifest) to control traffic. This script measures both
columns of that claim with :class:`ByteCountingStore`.

    JAX_PLATFORMS=cpu python benchmarks/replicated_save/manifest_traffic.py \
        [--nproc 4] [--leaves-per-rank 25000]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402, F401  (pins JAX_PLATFORMS=cpu)


def _worker(pg, root: str, leaves: int):
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.dist_store import ProcessGroup
    from torchsnapshot_tpu.test_utils import ByteCountingStore

    counting = ByteCountingStore(pg.store) if pg.store is not None else None
    cpg = (
        ProcessGroup(store=counting, rank=pg.rank, world_size=pg.world_size)
        if counting is not None
        else None
    )
    state = {
        f"t{i:06d}": np.full((4,), pg.rank * 1_000_000 + i, np.float32)
        for i in range(leaves)
    }
    t0 = time.perf_counter()
    ts.Snapshot.take(root, {"m": ts.PyTreeState(state)}, pg=cpg)
    take_s = time.perf_counter() - t0
    return {
        "rank": pg.rank,
        "take_s": round(take_s, 2),
        "sent_mib": round((counting.sent_bytes if counting else 0) / (1 << 20), 2),
        "received_mib": round(
            (counting.received_bytes if counting else 0) / (1 << 20), 2
        ),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--nproc", type=int, default=4)
    p.add_argument("--leaves-per-rank", type=int, default=25_000)
    args = p.parse_args()

    from torchsnapshot_tpu.test_utils import run_multiprocess

    work_dir = tempfile.mkdtemp(prefix="ts_manifest_traffic_")
    try:
        rows = run_multiprocess(
            _worker,
            args.nproc,
            args=(os.path.join(work_dir, "snap"), args.leaves_per_rank),
            timeout=1200.0,
        )
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)
    for row in rows:
        print(
            f"manifest_traffic: rank={row['rank']} take={row['take_s']}s "
            f"sent={row['sent_mib']} MiB received={row['received_mib']} MiB",
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "nproc": args.nproc,
                "leaves_per_rank": args.leaves_per_rank,
                "rows": rows,
            }
        )
    )


if __name__ == "__main__":
    main()
