"""Protocol-overhead benchmark: what the distributed protocol costs as
rank count grows, separated from storage I/O (reference scaling evidence:
benchmarks/ddp/main.py:48-68 + the published 1->8->32-GPU table).

Two measurements per rank count N (1/2/4 spawned processes on the CPU
backend, TCPStore rendezvous):

- **per-rank bytes written** of an N-GiB fully-replicated state: the
  write-load partitioner must hand each rank ~1/N of the bytes (the
  mechanism behind the reference's aggregate-throughput scaling column —
  on one box aggregate GB/s can't scale, but the per-rank write load
  halving at 2 ranks is the same property, observable here).
- **protocol wall time** of a take whose payload is negligible (many
  tiny leaves): all six metadata rounds (key gather, replication
  verification, partitioning, manifest gather, budget gather, commit
  barrier) plus planning, with I/O amortized to ~0. Must stay ~flat in
  N.

Prints ONE JSON line; ``bench.py`` shells out to this on the CPU backend
and merges the result into the driver-recorded metric line.

    JAX_PLATFORMS=cpu python benchmarks/replicated_save/protocol_overhead.py \
        [--gb 0.25] [--nprocs 1 2 4]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402  (pins JAX_PLATFORMS=cpu)


def _worker(pg, work_dir: str, gb: float, tiny_leaves: int):
    """One rank: replicated take with byte counting, then a tiny-payload
    take timing the protocol itself."""
    from unittest import mock

    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    counters = {"bytes": 0}

    class CountingFSStoragePlugin(FSStoragePlugin):
        # Both write paths must count: with the native runtime active the
        # scheduler routes data writes through write_with_checksum (the
        # fused write+CRC path), and a counter that hooks only write()
        # records 0 bytes on such hosts (round-3 driver record).
        async def write(self, write_io):
            counters["bytes"] += memoryview(write_io.buf).cast("B").nbytes
            await super().write(write_io)

        async def write_with_checksum(self, write_io):
            entry = await super().write_with_checksum(write_io)
            if entry is not None:  # None = declined; scheduler falls back
                counters["bytes"] += memoryview(write_io.buf).cast("B").nbytes
            return entry

    patch = mock.patch(
        "torchsnapshot_tpu.snapshot.url_to_storage_plugin",
        side_effect=lambda url: CountingFSStoragePlugin(
            root=url.split("://")[-1]
        ),
    )

    # Replicated payload: identical on every rank by construction.
    block = 32 * 1024 * 1024
    n_blocks = max(1, int(gb * (1 << 30)) // block)
    state = {
        f"w{i}": jnp.asarray(
            np.full((block // 4,), float(i), np.float32)
        )
        for i in range(n_blocks)
    }
    jax.block_until_ready(state)
    with patch:
        t0 = time.perf_counter()
        ts.Snapshot.take(
            os.path.join(work_dir, "payload"),
            {"m": ts.PyTreeState(state)},
            pg=pg,
            replicated=["**"],
        )
        payload_s = time.perf_counter() - t0
    payload_bytes = counters["bytes"]
    del state

    # Protocol-dominated take: many tiny replicated leaves, ~zero I/O.
    tiny = {
        f"t{i}": np.full((16,), float(i), np.float32)
        for i in range(tiny_leaves)
    }
    counters["bytes"] = 0
    with patch:
        t0 = time.perf_counter()
        ts.Snapshot.take(
            os.path.join(work_dir, "tiny"),
            {"m": ts.PyTreeState(tiny)},
            pg=pg,
            replicated=["**"],
        )
        protocol_s = time.perf_counter() - t0
    return {
        "payload_bytes_written": payload_bytes,
        "payload_s": payload_s,
        "protocol_s": protocol_s,
    }


def run(nproc: int, gb: float, tiny_leaves: int) -> dict:
    work_dir = tempfile.mkdtemp(prefix=f"ts_proto_{nproc}_")
    try:
        if nproc == 1:
            results = [_worker(None, work_dir, gb, tiny_leaves)]
        else:
            from torchsnapshot_tpu.test_utils import run_multiprocess

            results = run_multiprocess(
                _worker,
                nproc,
                args=(work_dir, gb, tiny_leaves),
                timeout=600.0,
            )
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)
    return {
        "nproc": nproc,
        "per_rank_mib_written": [
            round(r["payload_bytes_written"] / (1 << 20), 1) for r in results
        ],
        "payload_s": round(max(r["payload_s"] for r in results), 2),
        "protocol_s": round(max(r["protocol_s"] for r in results), 2),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--gb", type=float, default=0.25)
    p.add_argument("--tiny-leaves", type=int, default=256)
    p.add_argument("--nprocs", type=int, nargs="+", default=[1, 2, 4])
    args = p.parse_args()
    rows = [run(n, args.gb, args.tiny_leaves) for n in args.nprocs]
    for row in rows:
        print(
            f"protocol_overhead: nproc={row['nproc']} "
            f"per-rank MiB written={row['per_rank_mib_written']} "
            f"payload={row['payload_s']}s protocol={row['protocol_s']}s",
            file=sys.stderr,
        )
    print(json.dumps({"gb": args.gb, "rows": rows}))


if __name__ == "__main__":
    main()
