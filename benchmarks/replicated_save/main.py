"""Replicated-state save benchmark (reference benchmarks/ddp/main.py).

A DDP-equivalent workload: every process holds the same N-GiB state; the
write-load partitioner splits the bytes across ranks so aggregate
throughput scales with process count. Single-process by default; pass
--nproc to fan out with the multiprocess harness.

    python benchmarks/replicated_save/main.py --gb 4 [--nproc 2] [--work-dir D]
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
from benchmarks.common import jax  # noqa: E402

import jax.numpy as jnp  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402


def make_state(total_bytes: int):
    block = 64 * 1024 * 1024  # 64 MiB fp32 blocks
    n = max(1, total_bytes // block)
    key = jax.random.PRNGKey(0)
    out = {}
    for i in range(n):
        key, sub = jax.random.split(key)
        out[f"w{i}"] = jax.random.normal(sub, (block // 4,), jnp.float32)
    jax.block_until_ready(out)
    return out


def run_rank(pg, work_dir: str, gb: float) -> None:
    state = make_state(int(gb * (1 << 30)))
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    app_state = {"model": ts.PyTreeState(state)}

    t0 = time.perf_counter()
    ts.Snapshot.take(work_dir, app_state, pg=pg, replicated=["**"])
    elapsed = time.perf_counter() - t0
    rank = pg.rank if pg is not None else 0
    if rank == 0:
        print(
            f"replicated save: {nbytes / (1 << 30):.2f} GiB in {elapsed:.2f}s "
            f"= {nbytes / (1 << 30) / elapsed:.2f} GB/s"
        )


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--gb", type=float, default=4.0)
    p.add_argument("--nproc", type=int, default=1)
    p.add_argument("--work-dir", default=None)
    args = p.parse_args()

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="ts_bench_repl_")
    try:
        if args.nproc == 1:
            run_rank(None, work_dir, args.gb)
        else:
            from torchsnapshot_tpu.test_utils import run_multiprocess

            run_multiprocess(run_rank, args.nproc, args=(work_dir, args.gb))
    finally:
        if args.work_dir is None:
            shutil.rmtree(work_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
