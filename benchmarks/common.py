"""Shared benchmark bootstrap: repo-root import path and platform pinning."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
