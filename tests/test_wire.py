"""Wire observatory (telemetry/wire.py, docs/observability.md).

Unit coverage for the context-propagation codec (round trip plus every
degraded shape: torn, crc-damaged, version-skewed — always context-free,
never a protocol error), chaos interop on the real framing seam
(``install_wire_chaos`` corrupting/dropping frames leaves transfers
correct with ``engine.fired`` pinned), the cross-process trace stitch
(a real-socket peer pull merges into one parent->child span pair), the
fleet metrics plane (bounded crc-guarded ``__obs/`` snapshots, torn and
stale entries skipped, publisher keys reaped on close), and the
fleet-scope doctor rules — including the acceptance pin that a
peer-server listen backlog clamped to 5 produces the whole-second
quantized dial latencies ``wire-dial-stalled`` fires on, while the
default backlog of 128 stays quiet.
"""

import pickle
import socket
import threading
import time

import pytest

from torchsnapshot_tpu import telemetry
from torchsnapshot_tpu.chaos.engine import (
    ChaosEngine,
    install_wire_chaos,
    uninstall_wire_chaos,
)
from torchsnapshot_tpu.chaos.plan import FaultPlan, FaultSpec
from torchsnapshot_tpu.dist_store import (
    InProcessStore,
    recv_frame,
    send_frame,
)
from torchsnapshot_tpu.integrity import compute_checksum_entry
from torchsnapshot_tpu.scheduler import PeerCacheBudget
from torchsnapshot_tpu.telemetry import doctor, names, trace, wire
from torchsnapshot_tpu.telemetry.registry import series_key
from torchsnapshot_tpu.telemetry.trace import (
    chrome_trace,
    merge_traces,
    stitched_wire_pairs,
    write_trace_file,
)
from torchsnapshot_tpu.telemetry.watchdog import reset_watchdog
from torchsnapshot_tpu.tiered import peer


@pytest.fixture(autouse=True)
def _fresh_wire():
    """Wire tests read process-global state (registry, recorder, the
    recent-dial ring, the chaos hook): isolate every test."""
    telemetry.reset_metrics()
    telemetry.reset_trace()
    reset_watchdog()
    wire.reset_recent_dials()
    wire.set_received_context(None)
    yield
    uninstall_wire_chaos()
    reset_watchdog()
    telemetry.reset_metrics()
    telemetry.reset_trace()
    wire.reset_recent_dials()
    wire.set_received_context(None)


def _degraded(reason):
    counters = telemetry.metrics().counters_snapshot()
    return counters.get(
        series_key(names.WIRE_CONTEXT_DEGRADED_TOTAL, {"reason": reason}), 0.0
    )


# ---------------------------------------------------------------------------
# Codec: round trip + every degraded shape
# ---------------------------------------------------------------------------


def test_codec_round_trip():
    ctx = wire.WireContext(wire.new_id(), wire.new_id(), names.RPC_PEER_PULL)
    framed = wire.encode_frame(ctx, b"body-bytes")
    assert len(framed) == wire.HEADER_LEN + len(b"body-bytes")
    decoded, body = wire.decode_frame(framed)
    assert body == b"body-bytes"
    assert decoded == ctx


def test_codec_context_free_passthrough():
    # No magic: the payload is untouched and nothing is counted.
    payload = b"\x00plain frame with no header"
    assert wire.decode_frame(payload) == (None, payload)
    assert _degraded("torn") == _degraded("crc") == 0.0


def test_codec_torn_header_passes_raw_payload():
    ctx = wire.WireContext(wire.new_id(), wire.new_id(), names.RPC_PEER_PING)
    torn = wire.encode_frame(ctx, b"")[: wire.HEADER_LEN - 1]
    decoded, body = wire.decode_frame(torn)
    assert decoded is None and body == torn
    assert _degraded("torn") == 1.0


def test_codec_crc_damage_strips_header_keeps_body():
    ctx = wire.WireContext(wire.new_id(), wire.new_id(), names.RPC_PEER_PULL)
    framed = bytearray(wire.encode_frame(ctx, b"intact-body"))
    framed[10] ^= 0xFF  # damage inside the op field
    decoded, body = wire.decode_frame(bytes(framed))
    assert decoded is None and body == b"intact-body"
    assert _degraded("crc") == 1.0


def test_codec_version_skew_strips_header_keeps_body():
    import struct
    import zlib

    ctx = wire.WireContext(wire.new_id(), wire.new_id(), names.RPC_PEER_PING)
    framed = bytearray(wire.encode_frame(ctx, b"vbody"))
    framed[4] = 99  # future version...
    head = bytes(framed[: wire.HEADER_LEN - 4])
    framed[wire.HEADER_LEN - 4 : wire.HEADER_LEN] = struct.pack(
        "<I", zlib.crc32(head)
    )  # ...with a VALID crc, so only the version gate trips
    decoded, body = wire.decode_frame(bytes(framed))
    assert decoded is None and body == b"vbody"
    assert _degraded("version") == 1.0


def test_propagate_nests_under_one_trace():
    assert wire.current_context() is None
    with wire.propagate(names.RPC_CDN_SYNC) as outer:
        with wire.propagate(names.RPC_PEER_PULL) as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.span_id != outer.span_id
            assert wire.current_context() is inner
        assert wire.current_context() is outer
    assert wire.current_context() is None


# ---------------------------------------------------------------------------
# Framing seam: context rides send_frame/recv_frame
# ---------------------------------------------------------------------------


def test_send_recv_frame_carries_context_across_socket():
    a, b = socket.socketpair()
    try:
        with wire.propagate(names.RPC_PEER_PING) as ctx:
            send_frame(a, b"ping-body", endpoint="peer")
        b.settimeout(5)
        got = recv_frame(b, endpoint="peer")
    finally:
        a.close()
        b.close()
    assert got == b"ping-body"
    received = wire.last_received_context()
    assert received is not None
    assert received.op == names.RPC_PEER_PING
    assert received.trace_id == ctx.trace_id
    assert received.span_id == ctx.span_id


def test_send_frame_without_context_is_headerless():
    a, b = socket.socketpair()
    try:
        send_frame(a, b"bare", endpoint="peer")
        b.settimeout(5)
        got = recv_frame(b, endpoint="peer")
    finally:
        a.close()
        b.close()
    assert got == b"bare"
    assert wire.last_received_context() is None
    counters = telemetry.metrics().counters_snapshot()
    sent = counters[
        series_key(names.WIRE_FRAMES_TOTAL, {"endpoint": "peer", "dir": "send"})
    ]
    recvd = counters[
        series_key(names.WIRE_FRAMES_TOTAL, {"endpoint": "peer", "dir": "recv"})
    ]
    assert sent == recvd == 1.0


# ---------------------------------------------------------------------------
# Chaos interop: corruption/drops degrade context, never the transfer
# ---------------------------------------------------------------------------


def test_wire_chaos_corrupt_header_degrades_context_not_payload():
    engine = ChaosEngine(
        FaultPlan(seed=0, faults=[FaultSpec(point="wire-send", mode="corrupt")])
    )
    install_wire_chaos(engine)
    # Body short enough that the corrupt hook's middle-byte bit flip
    # lands inside the 50-byte context header, not the body.
    body = b"x" * 30
    a, b = socket.socketpair()
    try:
        with wire.propagate(names.RPC_PEER_PULL):
            send_frame(a, body, endpoint="peer")
        b.settimeout(5)
        got = recv_frame(b, endpoint="peer")
    finally:
        uninstall_wire_chaos()
        a.close()
        b.close()
    assert got == body  # the transfer is CORRECT...
    assert wire.last_received_context() is None  # ...just context-free
    assert engine.fired == [
        ("wire-send", str(wire.HEADER_LEN + len(body)), "corrupt")
    ]
    assert _degraded("crc") == 1.0


def test_wire_chaos_drop_swallows_frame_and_the_retry_lands():
    engine = ChaosEngine(
        FaultPlan(faults=[FaultSpec(point="wire-send", mode="drop", times=1)])
    )
    install_wire_chaos(engine)
    a, b = socket.socketpair()
    try:
        with wire.propagate(names.RPC_PEER_PING):
            send_frame(a, b"first", endpoint="peer")  # vanishes on the floor
            send_frame(a, b"retry", endpoint="peer")
        b.settimeout(5)
        got = recv_frame(b, endpoint="peer")
    finally:
        uninstall_wire_chaos()
        a.close()
        b.close()
    # The receiver waited the dropped frame out and saw only the retry,
    # context intact (the retry's header was not damaged).
    assert got == b"retry"
    received = wire.last_received_context()
    assert received is not None and received.op == names.RPC_PEER_PING
    assert [(point, mode) for point, _, mode in engine.fired] == [
        ("wire-send", "drop")
    ]


def _serve(cache):
    server = peer._PeerServer(("127.0.0.1", 0), cache)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def test_peer_rpc_survives_corrupted_context_header():
    """End-to-end over the real peer transport: chaos flips a header
    bit on the request frame; the serving peer still answers correctly
    (the header degrades, the pickled body never does)."""
    engine = ChaosEngine(
        FaultPlan(faults=[FaultSpec(point="wire-send", mode="corrupt", times=1)])
    )
    cache = peer.PeerCache(budget=PeerCacheBudget(1 << 20))
    server = _serve(cache)
    install_wire_chaos(engine)
    try:
        client = peer.PeerClient(
            "127.0.0.1", server.server_address[1], timeout=5
        )
        assert client.request(names.RPC_PEER_PING) == "pong"
        client.close()
    finally:
        uninstall_wire_chaos()
        server.shutdown()
        server.server_close()
    expected_len = wire.HEADER_LEN + len(
        pickle.dumps((names.RPC_PEER_PING, ()))
    )
    assert engine.fired == [("wire-send", str(expected_len), "corrupt")]
    assert _degraded("crc") == 1.0


# ---------------------------------------------------------------------------
# Cross-process stitch: client RPC span <-> serving handler span
# ---------------------------------------------------------------------------


def test_peer_pull_stitches_client_and_handler_spans(tmp_path):
    """A clean real-socket peer pull exports a client-side ``wire:rpc``
    span and a server-side ``wire:handler`` span; merged as two ranks,
    they form one parent->child pair under one trace id, and the merge
    appends the Perfetto flow arrows."""
    rec = trace.get_recorder()
    mark = rec.mark()
    cache = peer.PeerCache(budget=PeerCacheBudget(1 << 20))
    server = _serve(cache)
    try:
        client = peer.PeerClient(
            "127.0.0.1", server.server_address[1], timeout=5
        )
        entry = compute_checksum_entry(b"payload")
        assert client.push("s", 0, "blob", entry, b"payload")[0]
        client.commit("s", 0)
        got = client.pull("s", "blob")
        assert got is not None and bytes(got[1]) == b"payload"
        client.close()
    finally:
        server.shutdown()
        server.server_close()
    events = rec.events_since(mark)
    tids = rec.tid_names()
    client_events = [e for e in events if e["name"] == names.SPAN_WIRE_RPC]
    handler_events = [e for e in events if e["name"] == names.SPAN_WIRE_HANDLER]
    assert client_events and handler_events
    # Export each side as its own rank file — the 2-process shape the
    # merge CLI sees.
    p0 = str(tmp_path / ".trace-restore-rank0.json")
    p1 = str(tmp_path / ".trace-restore-rank1.json")
    write_trace_file(p0, chrome_trace(client_events, tids, rank=0))
    write_trace_file(p1, chrome_trace(handler_events, tids, rank=1))
    merged = merge_traces([p0, p1], {0: 0.0, 1: 0.0})
    pairs = stitched_wire_pairs(merged)
    assert merged["otherData"]["wire_stitched"] == len(pairs) >= 1
    pull_pairs = [
        (c, h)
        for c, h in pairs
        if c["args"].get("op") == names.RPC_PEER_PULL
    ]
    assert pull_pairs
    client_span, handler_span = pull_pairs[0]
    assert client_span["pid"] == 0 and handler_span["pid"] == 1
    assert handler_span["args"]["trace_id"] == client_span["args"]["trace_id"]
    assert (
        handler_span["args"]["parent_span_id"]
        == client_span["args"]["span_id"]
    )
    flows = [e for e in merged["traceEvents"] if e.get("cat") == "wire"]
    assert {e["ph"] for e in flows} == {"s", "f"}


# ---------------------------------------------------------------------------
# Per-endpoint metric folds
# ---------------------------------------------------------------------------


def test_local_wire_summary_folds_endpoint_series():
    wire.observe_frame("peer", "send", 100)
    wire.observe_frame("peer", "recv", 50)
    wire.observe_rpc("peer", names.RPC_PEER_PULL, 0.2)
    wire.observe_dial("peer", 0.01)
    wire.observe_dial("peer", 0.0, ok=False)  # errors stay out of the ring
    wire.observe_pool_checkout("peer", "reused")
    with wire.rpc_inflight("peer"):
        pass  # balanced enter/exit must never throw
    telemetry.metrics().counter_inc(
        names.COORD_STORE_SHARD_REQUESTS_TOTAL, 7, shard="0"
    )
    summary = wire.local_wire_summary()
    ep = summary["endpoints"]["peer"]
    assert ep["frames"] == 2 and ep["bytes"] == 150
    assert ep["rpcs"] == 1 and ep["dials"] == 2
    assert summary["dials_s"] == [0.01]
    assert summary["store_shards"] == {"0": 7.0}
    assert "context_degraded" not in summary  # only rendered when nonzero


def test_quantized_dial_fraction_signature():
    # Whole-second clustering (SYN retransmits) vs. a smeared tail.
    slow, frac = wire.quantized_dial_fraction([0.01, 0.02, 1.01, 1.98, 3.0])
    assert (slow, frac) == (3, 1.0)
    slow, frac = wire.quantized_dial_fraction([0.6, 0.7, 1.4])
    assert (slow, frac) == (3, 0.0)
    assert wire.quantized_dial_fraction([0.001, 0.002]) == (0, 0.0)


# ---------------------------------------------------------------------------
# Fleet metrics plane: bounded, crc-guarded, reaped
# ---------------------------------------------------------------------------


def test_fleet_entry_round_trip_bounds_and_shedding():
    snap = wire.fleet_snapshot(
        "trainer",
        3,
        7,
        phase="write",
        written_bytes=1234,
        extra={"bulk": "x" * (2 * wire.SNAPSHOT_MAX_BYTES)},
    )
    raw = wire.encode_fleet_entry(snap)
    # "<crc32-hex>:" prefix is 9 bytes; the json body itself is bounded.
    assert len(raw) - 9 <= wire.SNAPSHOT_MAX_BYTES
    entry = wire.decode_fleet_entry(raw)
    assert entry is not None
    assert entry["role"] == "trainer" and entry["id"] == "3"
    assert entry["seq"] == 7 and entry["written_bytes"] == 1234
    assert "extra" not in entry  # shed first to fit the bound...
    assert "wire" in entry  # ...keeping the wire summary


def test_fleet_entry_torn_and_stale_are_skipped():
    snap = wire.fleet_snapshot("trainer", 0, 1)
    raw = wire.encode_fleet_entry(snap)
    assert wire.decode_fleet_entry(None) is None
    assert wire.decode_fleet_entry(b"not-a-fleet-entry") is None
    assert wire.decode_fleet_entry(raw[:-3]) is None  # torn write
    assert wire.decode_fleet_entry(raw, now=snap["t"] + 1e6) is None  # stale
    fresh = wire.decode_fleet_entry(raw, now=snap["t"] + 1.0)
    assert fresh is not None and 0.0 <= fresh["age_s"] <= 2.0


def test_fleet_reporter_paces_publishes_and_reaps_on_close():
    store = InProcessStore()
    reporter = wire.FleetReporter(store, "trainer", 3, interval_s=3600)
    assert reporter.publish(phase="a") is True
    assert reporter.publish(phase="b") is False  # paced out
    assert reporter.publish(phase="c", force=True) is True
    # Torn and stale residue on the same prefix is skipped by readers.
    store.multi_set({f"{wire.OBS_PREFIX}/trainer/9": b"garbage"})
    stale = wire.fleet_snapshot("trainer", 8, 1)
    stale["t"] -= 10_000
    store.multi_set(
        {f"{wire.OBS_PREFIX}/trainer/8": wire.encode_fleet_entry(stale)}
    )
    entries = wire.collect_fleet(store)
    assert [e["id"] for e in entries] == ["3"]
    assert entries[0]["seq"] == 2 and entries[0]["phase"] == "c"
    table = wire.render_fleet_table(entries)
    assert "ROLE" in table and "trainer" in table
    reporter.close()
    assert reporter.key not in store.scan(wire.OBS_PREFIX + "/")
    assert wire.collect_fleet(store) == []
    assert wire.render_fleet_table([]).startswith("(no live fleet entries")


def test_fleet_reporter_swallows_store_errors():
    class _ExplodingStore(InProcessStore):
        def multi_set(self, items):
            raise ConnectionError("store down")

        def multi_delete(self, keys):
            raise ConnectionError("store down")

    reporter = wire.FleetReporter(_ExplodingStore(), "trainer", 0, interval_s=0)
    assert reporter.publish(force=True) is False
    reporter.close()  # reap failure is swallowed too


def test_publish_interval_scales_with_world():
    assert wire.publish_interval_for_world(1) == 0.25
    assert wire.publish_interval_for_world(1000) == 5.0
    assert (
        wire.publish_interval_for_world(64)
        <= wire.publish_interval_for_world(512)
    )


def test_fleet_endpoint_file_round_trip(tmp_path):
    wire.write_fleet_endpoint(str(tmp_path), "10.0.0.7", 29400)
    assert wire.read_fleet_endpoint(str(tmp_path)) == ("10.0.0.7", 29400)


def test_render_fleet_table_flags_stragglers_and_stale():
    entries = [
        {"role": "trainer", "id": "0", "seq": 9, "age_s": 1.0, "wire": {}},
        {"role": "trainer", "id": "1", "seq": 9, "age_s": 1.0, "wire": {}},
        {"role": "trainer", "id": "2", "seq": 3, "age_s": 9.0, "wire": {}},
    ]
    table = wire.render_fleet_table(entries)
    row = [line for line in table.splitlines() if line.startswith("trainer  2")]
    assert row and "straggler" in row[0] and "stale" in row[0]


# ---------------------------------------------------------------------------
# Fleet doctor rules
# ---------------------------------------------------------------------------


def _entry(ident, wire_summary):
    return {"role": "trainer", "id": str(ident), "seq": 1, "wire": wire_summary}


def test_wire_hot_endpoint_rule_flags_byte_skew():
    hot = _entry(
        0,
        {
            "endpoints": {
                "peer-7": {"bytes": 8 * 1024 * 1024},
                "peer-1": {"bytes": 40_000},
                "peer-2": {"bytes": 40_000},
                "peer-3": {"bytes": 40_000},
                "peer-4": {"bytes": 40_000},
                "peer-5": {"bytes": 40_000},
            }
        },
    )
    verdicts = doctor.diagnose_fleet([hot])
    hits = [v for v in verdicts if v.rule == names.RULE_WIRE_HOT_ENDPOINT]
    assert len(hits) == 1
    assert hits[0].evidence["endpoint"] == "peer-7"
    # Balanced traffic stays quiet.
    balanced = _entry(
        0,
        {
            "endpoints": {
                f"peer-{i}": {"bytes": 2 * 1024 * 1024} for i in range(6)
            }
        },
    )
    assert not [
        v
        for v in doctor.diagnose_fleet([balanced])
        if v.rule == names.RULE_WIRE_HOT_ENDPOINT
    ]


def test_store_hot_shard_rule_flags_request_skew():
    skewed = _entry(
        0,
        {"store_shards": {"0": 2000.0, "1": 10.0, "2": 10.0, "3": 10.0, "4": 10.0}},
    )
    verdicts = doctor.diagnose_fleet([skewed])
    hits = [v for v in verdicts if v.rule == names.RULE_STORE_HOT_SHARD]
    assert len(hits) == 1
    assert hits[0].evidence["shard"] == "0"
    # Low-volume or balanced shard maps stay quiet.
    quiet = _entry(0, {"store_shards": {"0": 30.0, "1": 28.0}})
    assert not [
        v
        for v in doctor.diagnose_fleet([quiet])
        if v.rule == names.RULE_STORE_HOT_SHARD
    ]


def test_wire_dial_stalled_rule_reads_fleet_entries():
    stalled = _entry(
        0,
        {"dials_s": [0.01, 1.02, 0.99, 2.03, 0.02], "dial_p95_s": 2.03},
    )
    verdicts = doctor.diagnose_fleet([stalled])
    hits = [v for v in verdicts if v.rule == names.RULE_WIRE_DIAL_STALLED]
    assert len(hits) == 1
    assert hits[0].severity == "critical"
    assert hits[0].source == "trainer/0"
    # Slow but smeared (no whole-second clustering) stays quiet: slow
    # storage is not the backlog signature.
    smeared = _entry(1, {"dials_s": [0.6, 0.7, 1.4, 1.6]})
    assert not [
        v
        for v in doctor.diagnose_fleet([smeared])
        if v.rule == names.RULE_WIRE_DIAL_STALLED
    ]


# ---------------------------------------------------------------------------
# Acceptance: a clamped listen backlog produces the stall signature
# ---------------------------------------------------------------------------


def _dial_burst(monkeypatch, backlog, dials=12, accept_delay_s=0.8):
    """Burst-dial a peer server whose accept loop starts late: with the
    backlog clamped to 5 the excess SYNs ride kernel retransmits and
    the dials quantize at whole seconds; with the default 128 the
    backlog absorbs the whole burst and every dial is fast."""
    monkeypatch.setattr(peer._PeerServer, "request_queue_size", backlog)
    cache = peer.PeerCache(budget=PeerCacheBudget(1 << 20))
    server = peer._PeerServer(("127.0.0.1", 0), cache)
    port = server.server_address[1]
    wire.reset_recent_dials()
    clients = [
        peer.PeerClient("127.0.0.1", port, timeout=15) for _ in range(dials)
    ]

    def dial(client):
        try:
            client._connect()
        except OSError:
            pass

    threads = [
        threading.Thread(target=dial, args=(c,), daemon=True) for c in clients
    ]
    try:
        for t in threads:
            t.start()
        time.sleep(accept_delay_s)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        for t in threads:
            t.join(timeout=20)
    finally:
        for c in clients:
            c.close()
        server.shutdown()
        server.server_close()
    return wire.recent_dial_seconds("peer")


def test_wire_dial_stalled_fires_on_clamped_backlog_only(monkeypatch):
    """The PR-15 bug class end-to-end: backlog 5 -> dropped SYNs ->
    whole-second dial quanta -> ``wire-dial-stalled`` fires from the
    fleet plane; the default backlog of 128 stays quiet."""
    dials = _dial_burst(monkeypatch, backlog=5)
    assert len(dials) >= 8  # most dials eventually succeeded
    entry = wire.decode_fleet_entry(
        wire.encode_fleet_entry(wire.fleet_snapshot("trainer", 0, 1))
    )
    verdicts = doctor.diagnose_fleet([entry])
    hits = [v for v in verdicts if v.rule == names.RULE_WIRE_DIAL_STALLED]
    assert hits and hits[0].severity == "critical"

    dials = _dial_burst(monkeypatch, backlog=128)
    assert len(dials) >= 8
    entry = wire.decode_fleet_entry(
        wire.encode_fleet_entry(wire.fleet_snapshot("trainer", 0, 2))
    )
    assert not [
        v
        for v in doctor.diagnose_fleet([entry])
        if v.rule == names.RULE_WIRE_DIAL_STALLED
    ]
