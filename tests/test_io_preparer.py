"""Pure-unit preparer tests: read reqs fulfilled directly from write reqs
in memory, no storage plugin involved.

Reference parity: tests/test_tensor_io_preparer.py:32-56
(``_fulfill_read_reqs_with_write_reqs``) and
tests/test_chunked_tensor_io_preparer.py.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List

import numpy as np
import pytest

from torchsnapshot_tpu import knobs
from torchsnapshot_tpu.io_preparer import (
    ArrayIOPreparer,
    ChunkedArrayIOPreparer,
    chunk_shapes,
    prepare_read,
    prepare_write,
)
from torchsnapshot_tpu.io_types import ReadReq, WriteReq
from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    ObjectEntry,
    PrimitiveEntry,
)
from torchsnapshot_tpu.test_utils import rand_array


def fulfill_read_reqs_with_write_reqs(
    read_reqs: List[ReadReq], write_reqs: List[WriteReq]
) -> None:
    """Stage every write request's buffer, then feed each read request's
    consumer from the staged bytes (honoring byte ranges)."""
    loop = asyncio.new_event_loop()
    try:
        staged: Dict[str, bytes] = {}
        for wr in write_reqs:
            staged[wr.path] = bytes(
                loop.run_until_complete(wr.buffer_stager.stage_buffer())
            )
        for rr in read_reqs:
            buf = staged[rr.path]
            if rr.byte_range is not None:
                begin, end = rr.byte_range
                buf = buf[begin:end]
            loop.run_until_complete(rr.buffer_consumer.consume_buffer(buf))
    finally:
        loop.close()


@pytest.mark.parametrize(
    "dtype",
    ["float32", "float64", "float16", "bfloat16", "int8", "uint8", "int16",
     "int32", "int64", "bool", "complex64", "complex128"],
)
def test_array_write_read_roundtrip(dtype: str) -> None:
    import jax.numpy as jnp

    if dtype == "bfloat16":
        src = jnp.asarray(rand_array((13, 7), "float32", seed=3), dtype=jnp.bfloat16)
        src = np.asarray(src)
    else:
        src = rand_array((13, 7), dtype, seed=3)
    entry, write_reqs = prepare_write(src, "foo/bar", rank=0, replicated=False)
    assert isinstance(entry, ArrayEntry)
    assert entry.location == "0/foo/bar"
    dst = ArrayIOPreparer.empty_array_from_entry(entry)
    read_reqs = prepare_read(entry, obj_out=dst)
    fulfill_read_reqs_with_write_reqs(read_reqs, write_reqs)
    np.testing.assert_array_equal(dst, src)


def test_jax_array_roundtrip() -> None:
    import jax.numpy as jnp

    src = jnp.arange(64, dtype=jnp.float32).reshape(8, 8) * 0.5
    entry, write_reqs = prepare_write(src, "w", rank=2, replicated=False)
    assert isinstance(entry, ArrayEntry)
    assert entry.location == "2/w"
    dst = ArrayIOPreparer.empty_array_from_entry(entry)
    read_reqs = prepare_read(entry, obj_out=dst)
    fulfill_read_reqs_with_write_reqs(read_reqs, write_reqs)
    np.testing.assert_array_equal(dst, np.asarray(src))


def test_replicated_storage_path() -> None:
    src = rand_array((4,), "float32")
    entry, write_reqs = prepare_write(src, "p/q", rank=1, replicated=True)
    assert entry.location == "replicated/p/q"
    assert entry.replicated
    assert write_reqs[0].path == "replicated/p/q"


@pytest.mark.parametrize("limit", [16, 64, 1000])
def test_ranged_reads_under_buffer_limit(limit: int) -> None:
    """With a buffer size limit, a large entry becomes multiple ranged reads
    whose byte ranges tile the payload (reference io_preparer.py:706-752)."""
    src = rand_array((32, 8), "float32", seed=9)
    entry, write_reqs = prepare_write(src, "big", rank=0)
    dst = ArrayIOPreparer.empty_array_from_entry(entry)
    read_reqs = prepare_read(entry, obj_out=dst, buffer_size_limit_bytes=limit)
    if limit < src.nbytes:
        assert len(read_reqs) > 1
        for rr in read_reqs:
            begin, end = rr.byte_range
            assert end - begin <= max(limit, src.itemsize)
        # Ranges tile [0, nbytes) exactly.
        spans = sorted(rr.byte_range for rr in read_reqs)
        assert spans[0][0] == 0 and spans[-1][1] == src.nbytes
        for (b0, e0), (b1, e1) in zip(spans, spans[1:]):
            assert e0 == b1
    fulfill_read_reqs_with_write_reqs(read_reqs, write_reqs)
    np.testing.assert_array_equal(dst, src)


def test_noncontiguous_dest_falls_back_to_whole_read() -> None:
    src = rand_array((8, 8), "float32", seed=1)
    entry, write_reqs = prepare_write(src, "x", rank=0)
    backing = np.zeros((8, 16), dtype=np.float32)
    dst = backing[:, ::2]  # non-contiguous view
    assert not dst.flags.c_contiguous
    read_reqs = prepare_read(entry, obj_out=dst, buffer_size_limit_bytes=16)
    assert len(read_reqs) == 1
    fulfill_read_reqs_with_write_reqs(read_reqs, write_reqs)
    np.testing.assert_array_equal(dst, src)


def test_can_load_inplace() -> None:
    src = rand_array((4, 4), "float32")
    entry, _ = prepare_write(src, "x", rank=0)
    ok = np.empty((4, 4), dtype=np.float32)
    assert ArrayIOPreparer.can_load_inplace(entry, ok)
    wrong_shape = np.empty((4, 5), dtype=np.float32)
    assert not ArrayIOPreparer.can_load_inplace(entry, wrong_shape)
    wrong_dtype = np.empty((4, 4), dtype=np.float64)
    assert not ArrayIOPreparer.can_load_inplace(entry, wrong_dtype)
    ro = np.empty((4, 4), dtype=np.float32)
    ro.flags.writeable = False
    assert not ArrayIOPreparer.can_load_inplace(entry, ro)
    assert not ArrayIOPreparer.can_load_inplace(entry, [[0.0] * 4] * 4)


# ---------------------------------------------------------------------------
# Chunked arrays
# ---------------------------------------------------------------------------


def test_chunk_shapes_tile_dim0() -> None:
    shapes = chunk_shapes([100, 16], "float32", max_chunk_size_bytes=1024)
    # 16 fp32 per row = 64 bytes; 1024 bytes => 16 rows per chunk.
    assert shapes[0] == (0, 16)
    assert shapes[-1][1] == 100
    covered = []
    for start, stop in shapes:
        assert stop > start
        covered.extend(range(start, stop))
    assert covered == list(range(100))


def test_chunk_shapes_row_larger_than_budget_stays_whole() -> None:
    shapes = chunk_shapes([4, 1024], "float64", max_chunk_size_bytes=16)
    assert shapes == [(0, 1), (1, 2), (2, 3), (3, 4)]


def test_chunked_roundtrip_and_entry_layout() -> None:
    src = rand_array((64, 4), "float32", seed=5)
    with knobs.override_max_chunk_size_bytes(256):
        entry, write_reqs = prepare_write(src, "big", rank=0)
    assert isinstance(entry, ChunkedArrayEntry)
    assert len(entry.chunks) == len(write_reqs) > 1
    for chunk, wr in zip(entry.chunks, write_reqs):
        assert chunk.array.location == wr.path
        assert chunk.array.location.startswith("0/big_")
        assert chunk.sizes[1:] == [4]
    dst = ArrayIOPreparer.empty_array_from_entry(entry)
    read_reqs = prepare_read(entry, obj_out=dst)
    fulfill_read_reqs_with_write_reqs(read_reqs, write_reqs)
    np.testing.assert_array_equal(dst, src)


def test_chunked_roundtrip_with_buffer_limit() -> None:
    src = rand_array((64, 4), "float32", seed=6)
    with knobs.override_max_chunk_size_bytes(512):
        entry, write_reqs = prepare_write(src, "big", rank=0)
    dst = ArrayIOPreparer.empty_array_from_entry(entry)
    read_reqs = prepare_read(entry, obj_out=dst, buffer_size_limit_bytes=128)
    assert len(read_reqs) > len(entry.chunks)
    fulfill_read_reqs_with_write_reqs(read_reqs, write_reqs)
    np.testing.assert_array_equal(dst, src)


def test_should_chunk_respects_knob() -> None:
    arr = rand_array((1024,), "float32")
    assert not ChunkedArrayIOPreparer.should_chunk(arr)
    with knobs.override_max_chunk_size_bytes(64):
        assert ChunkedArrayIOPreparer.should_chunk(arr)
        # 0-d and single-row arrays are never chunked.
        assert not ChunkedArrayIOPreparer.should_chunk(np.float32(1.0))
        assert not ChunkedArrayIOPreparer.should_chunk(
            rand_array((1, 1024), "float32")
        )


# ---------------------------------------------------------------------------
# Objects & primitives
# ---------------------------------------------------------------------------


def test_object_roundtrip_via_callback() -> None:
    src = {"a": [1, 2, 3], "b": ("x", 4.5)}
    entry, write_reqs = prepare_write(src, "obj", rank=0)
    assert isinstance(entry, ObjectEntry)
    assert entry.obj_type == "dict"
    box: List[Any] = []
    read_reqs = prepare_read(entry, callback=box.append)
    fulfill_read_reqs_with_write_reqs(read_reqs, write_reqs)
    assert box == [src]


def test_primitives_inline_no_write_reqs() -> None:
    for val in (3, 3.25, "s", True, b"\x00\x01"):
        entry, write_reqs = prepare_write(val, "p", rank=0)
        assert isinstance(entry, PrimitiveEntry)
        assert write_reqs == []
        assert entry.get_value() == val
        assert type(entry.get_value()) is type(val)
        assert prepare_read(entry) == []


def test_prepare_read_requires_destination_or_callback() -> None:
    arr_entry, _ = prepare_write(rand_array((2,), "float32"), "a", rank=0)
    with pytest.raises(ValueError, match="destination"):
        prepare_read(arr_entry)
    obj_entry, _ = prepare_write(object(), "o", rank=0)
    with pytest.raises(ValueError, match="callback"):
        prepare_read(obj_entry)


def test_staging_cost_matches_payload() -> None:
    src = rand_array((16, 16), "float64")
    _, write_reqs = prepare_write(src, "c", rank=0)
    assert write_reqs[0].buffer_stager.get_staging_cost_bytes() == src.nbytes
    with knobs.override_max_chunk_size_bytes(512):
        _, chunked_reqs = prepare_write(src, "c", rank=0)
    assert (
        sum(wr.buffer_stager.get_staging_cost_bytes() for wr in chunked_reqs)
        == src.nbytes
    )
