"""Seeded structure-fuzz round-trips: random nested app state through
take → restore → exact comparison.

Property-style widening of the reference's property-matrix layer
(SURVEY.md §4 item 2): instead of hand-picked fixtures, each seed
generates a random pytree mixing dense/sharded jax arrays, numpy
arrays (bf16 included), primitives, opaque pickled objects, and hostile
keys. Deterministic seeds keep failures reproducible.
"""

import string

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.test_utils import tree_eq

_DTYPES = ["float32", "bfloat16", "int32", "uint8", "bool"]
_KEY_CHARS = string.ascii_lowercase + "0123456789" + "/%._- "


def _rand_key(rng) -> str:
    n = int(rng.integers(1, 12))
    return "".join(rng.choice(list(_KEY_CHARS), size=n))


def _rand_leaf(rng, mesh):
    kind = rng.integers(0, 8)
    if kind == 7:
        # Opaque object leaf (pickled-blob path).
        return {"frozen": frozenset([int(rng.integers(0, 9))])}
    if kind == 0:
        return int(rng.integers(-(2**40), 2**40))
    if kind == 1:
        return float(rng.standard_normal())
    if kind == 2:
        return _rand_key(rng)
    if kind == 3:
        return bool(rng.integers(0, 2))
    shape = tuple(int(s) for s in rng.integers(1, 9, size=int(rng.integers(0, 3))))
    dtype = _DTYPES[int(rng.integers(0, len(_DTYPES)))]
    if dtype == "bool":
        arr = rng.integers(0, 2, shape).astype(bool)
    elif np.dtype(dtype).kind in "iu":
        arr = rng.integers(0, 100, shape).astype(dtype)
    else:
        arr = rng.standard_normal(shape).astype(np.float32)
    if kind == 4:
        if dtype == "bfloat16":
            import ml_dtypes

            return arr.astype(ml_dtypes.bfloat16)
        return arr  # numpy leaf
    if kind == 5:
        if dtype == "bfloat16":
            return jnp.asarray(arr, dtype=jnp.bfloat16)
        return jnp.asarray(arr.astype(dtype if dtype != "bool" else bool))
    # kind == 6: sharded over the mesh when the leading dim divides
    x = jnp.asarray(arr.astype("float32"))
    if x.ndim >= 1 and x.shape[0] % len(mesh.devices) == 0 and x.shape[0] > 0:
        return jax.device_put(x, NamedSharding(mesh, P("x")))
    return x


def _rand_tree(rng, mesh, depth: int):
    if depth == 0 or rng.random() < 0.4:
        return _rand_leaf(rng, mesh)
    if rng.random() < 0.5:
        return {
            _rand_key(rng): _rand_tree(rng, mesh, depth - 1)
            for _ in range(int(rng.integers(1, 4)))
        }
    return [_rand_tree(rng, mesh, depth - 1) for _ in range(int(rng.integers(1, 4)))]


def _zeros_like_tree(tree):
    if isinstance(tree, dict):
        return {k: _zeros_like_tree(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_zeros_like_tree(v) for v in tree]
    if isinstance(tree, jax.Array):
        return jax.device_put(jnp.zeros_like(tree), tree.sharding)
    if isinstance(tree, np.ndarray):
        return np.zeros_like(tree)
    return type(tree)()  # neutral primitive of the same type


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_roundtrip(tmp_path, seed) -> None:
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs), ("x",))
    rng = np.random.default_rng(seed)
    tree = {"root": _rand_tree(rng, mesh, depth=3)}

    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState(tree)})
    dst = {"s": ts.PyTreeState(_zeros_like_tree(tree))}
    ts.Snapshot(str(tmp_path)).restore(dst)
    assert tree_eq(
        jax.tree_util.tree_map(np.asarray, dst["s"].tree),
        jax.tree_util.tree_map(np.asarray, tree),
    ), f"seed {seed} round-trip mismatch"


def _mutate_tree(rng, tree):
    """Randomly mutate ~30% of array leaves (bit-level changes included),
    leaving the rest byte-identical — the incremental-take fuzz input."""
    if isinstance(tree, dict):
        return {k: _mutate_tree(rng, v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_mutate_tree(rng, v) for v in tree]
    if isinstance(tree, jax.Array) and rng.random() < 0.3:
        host = np.asarray(tree)
        if host.size == 0:
            return tree
        flat = np.ascontiguousarray(host).reshape(-1).copy()
        idx = int(rng.integers(0, flat.size))
        raw = flat.view(np.uint8)
        raw[idx * flat.dtype.itemsize] ^= 0x01  # single-bit flip
        out = jnp.asarray(flat.reshape(host.shape), dtype=tree.dtype)
        if hasattr(tree, "sharding") and len(tree.sharding.device_set) > 1:
            out = jax.device_put(out, tree.sharding)
        return out
    if isinstance(tree, np.ndarray) and rng.random() < 0.3 and tree.size:
        flat = np.ascontiguousarray(tree).reshape(-1).copy()
        raw = flat.view(np.uint8)
        raw[int(rng.integers(0, raw.size))] ^= 0x01
        return flat.reshape(tree.shape)
    if isinstance(tree, (int, float)) and not isinstance(tree, bool):
        return tree + 1 if rng.random() < 0.3 else tree
    return tree


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_incremental_roundtrip(tmp_path, seed) -> None:
    """Random tree, random single-bit mutations, incremental take against
    the base: restore must be byte-exact, and every mutated array leaf
    must have been rewritten (digests catch single-bit flips)."""
    from torchsnapshot_tpu.knobs import override_incremental_chunk_size_bytes

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs), ("x",))
    rng = np.random.default_rng(1000 + seed)
    tree = {"root": _rand_tree(rng, mesh, depth=3)}

    p0 = str(tmp_path / "s0")
    p1 = str(tmp_path / "s1")
    with override_incremental_chunk_size_bytes(64):
        ts.Snapshot.take(p0, {"s": ts.PyTreeState(tree)}, record_digests=True)
        mutated = _mutate_tree(rng, tree)
        ts.Snapshot.take(
            p1, {"s": ts.PyTreeState(mutated)}, incremental_base=p0
        )

    dst = {"s": ts.PyTreeState(_zeros_like_tree(tree))}
    ts.Snapshot(p1).restore(dst)
    assert tree_eq(
        jax.tree_util.tree_map(np.asarray, dst["s"].tree),
        jax.tree_util.tree_map(np.asarray, mutated),
    ), f"seed {seed} incremental round-trip mismatch"

    # And the chain keeps working: a third take against p1.
    p2 = str(tmp_path / "s2")
    mutated2 = _mutate_tree(rng, mutated)
    with override_incremental_chunk_size_bytes(64):
        ts.Snapshot.take(
            p2, {"s": ts.PyTreeState(mutated2)}, incremental_base=p1
        )
    dst2 = {"s": ts.PyTreeState(_zeros_like_tree(tree))}
    ts.Snapshot(p2).restore(dst2)
    assert tree_eq(
        jax.tree_util.tree_map(np.asarray, dst2["s"].tree),
        jax.tree_util.tree_map(np.asarray, mutated2),
    ), f"seed {seed} chained incremental mismatch"
