"""PGWrapper object collectives: world-1 fast paths and multi-rank
semantics over a thread-shared store.

Reference parity: tests/test_pg_wrapper.py (pg_wrapper.py:15-89). Threads
over an InProcessStore replace process fan-out: the collectives only move
pickled metadata, so thread-level concurrency exercises the same paths.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List

import pytest

from torchsnapshot_tpu.dist_store import InProcessStore
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import ProcessGroup


def run_ranks(world_size: int, fn: Callable[[PGWrapper], Any]) -> List[Any]:
    """Run ``fn(pg)`` concurrently for every rank over one shared store."""
    store = InProcessStore()
    pgs = [
        PGWrapper(ProcessGroup(store=store, rank=r, world_size=world_size))
        for r in range(world_size)
    ]
    with ThreadPoolExecutor(max_workers=world_size) as ex:
        futs = [ex.submit(fn, pg) for pg in pgs]
        return [f.result(timeout=60) for f in futs]


def test_world1_noops() -> None:
    pg = PGWrapper(None)
    assert pg.get_rank() == 0
    assert pg.get_world_size() == 1
    pg.barrier()
    assert pg.all_gather_object("x") == ["x"]
    assert pg.broadcast_object({"a": 1}) == {"a": 1}
    assert pg.scatter_object_list(["only"]) == "only"


def test_wrap_existing_pgwrapper() -> None:
    inner = PGWrapper(None)
    outer = PGWrapper(inner)
    assert outer.get_rank() == 0 and outer.get_world_size() == 1


@pytest.mark.parametrize("world_size", [2, 4])
def test_all_gather_object(world_size: int) -> None:
    results = run_ranks(
        world_size, lambda pg: pg.all_gather_object({"rank": pg.get_rank()})
    )
    expected = [{"rank": r} for r in range(world_size)]
    for res in results:
        assert res == expected  # rank order preserved


def test_broadcast_object_nondefault_src() -> None:
    def fn(pg: PGWrapper) -> Any:
        obj = f"from-{pg.get_rank()}" if pg.get_rank() == 1 else None
        return pg.broadcast_object(obj, src=1)

    assert run_ranks(3, fn) == ["from-1"] * 3


def test_agree_object_rank0_decides() -> None:
    """agree_object: rank 0's value reaches every rank (the blessed
    knob-to-job-decision laundering primitive — snaplint treats its
    result as rank-uniform); world-1 passes through."""
    out = run_ranks(3, lambda pg: pg.agree_object(f"rank{pg.get_rank()}"))
    assert out == ["rank0"] * 3
    assert PGWrapper(None).agree_object("solo") == "solo"


def test_scatter_object_list() -> None:
    def fn(pg: PGWrapper) -> Any:
        objs = (
            [f"item-{i}" for i in range(pg.get_world_size())]
            if pg.get_rank() == 0
            else None
        )
        return pg.scatter_object_list(objs)

    assert run_ranks(4, fn) == [f"item-{i}" for i in range(4)]


def test_sequenced_collectives_do_not_collide() -> None:
    """Back-to-back collectives on the same wrapper get distinct key
    prefixes, so a fast rank's round N+1 can't consume round N keys."""

    def fn(pg: PGWrapper) -> Any:
        out = []
        for i in range(5):
            out.append(pg.all_gather_object((pg.get_rank(), i)))
            pg.barrier()
        return out

    results = run_ranks(2, fn)
    for res in results:
        for i, gathered in enumerate(res):
            assert gathered == [(0, i), (1, i)]


def test_barrier_releases_all_ranks() -> None:
    import threading

    arrived = []
    lock = threading.Lock()

    def fn(pg: PGWrapper) -> int:
        with lock:
            arrived.append(pg.get_rank())
        pg.barrier()
        with lock:
            # Nobody passes the barrier until everyone arrived.
            assert len(arrived) == pg.get_world_size()
        return pg.get_rank()

    assert sorted(run_ranks(4, fn)) == [0, 1, 2, 3]


def test_counter_shared_across_pgs_on_same_store() -> None:
    """Two distinct ProcessGroup objects over the same store must share one
    op-seq counter: store-key collisions are scoped to the store, so
    independent counters could alias ``__pg/*`` keys (e.g. one pg handed to
    CheckpointManager and another to Snapshot)."""
    store = InProcessStore()
    pg_a = ProcessGroup(store=store, rank=0, world_size=2)
    pg_b = ProcessGroup(store=store, rank=0, world_size=2)
    wa = PGWrapper(pg_a)
    wb = PGWrapper(pg_b)
    assert wa._op_seq_ref is wb._op_seq_ref
    p1 = wa._next_prefix("ag")
    p2 = wb._next_prefix("ag")
    assert p1 != p2
