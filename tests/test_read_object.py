"""Random-access ``Snapshot.read_object`` coverage.

Reference parity: tests/test_read_object.py (snapshot.py:507-612): primitive
inline return, object entries, dense/chunked arrays with ``obj_out`` and
``memory_budget_bytes``, sharded entries, and error paths.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import Snapshot, knobs
from torchsnapshot_tpu.test_utils import rand_array


@pytest.fixture()
def snap(tmp_path):
    app_state = {
        "model": ts.PyTreeState(
            {
                "w": jnp.asarray(rand_array((32, 8), "float32", seed=1)),
                "big": jnp.asarray(rand_array((64, 8), "float32", seed=2)),
            }
        ),
        "meta": ts.StateDict(
            step=17,
            lr=0.125,
            name="run-a",
            flag=True,
            blob={1, 2, 3},  # sets aren't flattenable → ObjectEntry
        ),
    }
    with knobs.override_max_chunk_size_bytes(1024):  # force "big" chunked
        yield Snapshot.take(str(tmp_path), app_state), app_state


def test_read_primitives_inline(snap) -> None:
    s, _ = snap
    assert s.read_object("0/meta/step") == 17
    assert s.read_object("0/meta/lr") == 0.125
    assert s.read_object("0/meta/name") == "run-a"
    assert s.read_object("0/meta/flag") is True


def test_read_pickled_object(snap) -> None:
    s, _ = snap
    assert s.read_object("0/meta/blob") == {1, 2, 3}


def test_read_dense_array(snap) -> None:
    s, state = snap
    got = s.read_object("0/model/w")
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(state["model"].tree["w"])
    )


def test_read_dense_array_into_obj_out(snap) -> None:
    s, state = snap
    dst = np.zeros((32, 8), dtype=np.float32)
    got = s.read_object("0/model/w", obj_out=dst)
    assert got is dst  # loaded in place
    np.testing.assert_array_equal(dst, np.asarray(state["model"].tree["w"]))


def test_read_chunked_array_with_memory_budget(snap) -> None:
    s, state = snap
    got = s.read_object("0/model/big", memory_budget_bytes=512)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(state["model"].tree["big"])
    )


def test_read_object_bad_rank_prefix(snap) -> None:
    s, _ = snap
    with pytest.raises(ValueError, match="rank"):
        s.read_object("notarank/model/w")


def test_read_object_unknown_path(snap) -> None:
    s, _ = snap
    with pytest.raises(ValueError, match="not a valid entry"):
        s.read_object("0/model/nope")


def test_read_object_container_path_rejected(snap) -> None:
    s, _ = snap
    with pytest.raises(ValueError, match="container"):
        s.read_object("0/model")


def test_read_sharded_array(tmp_path) -> None:
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs[:2]), ("x",))
    src = rand_array((16, 4), "float32", seed=9)
    arr = jax.device_put(jnp.asarray(src), NamedSharding(mesh, P("x", None)))
    s = Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": arr})})
    got = s.read_object("0/m/w")
    np.testing.assert_array_equal(np.asarray(got), src)
    # And with a tight memory budget (ranged reads).
    got2 = s.read_object("0/m/w", memory_budget_bytes=64)
    np.testing.assert_array_equal(np.asarray(got2), src)
