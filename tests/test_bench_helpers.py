"""Unit pins for bench.py's measurement helpers — the shared
bracketed-efficiency epistemics (one definition for save AND restore)
and the link-scaled probe sizing. Imported without running any leg."""

import importlib.util
import pathlib
import sys


def _load_bench():
    path = pathlib.Path(__file__).parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("ts_bench_module", path)
    mod = importlib.util.module_from_spec(spec)
    # bench.py installs nothing at import time (handlers install in
    # main()); importing is safe and side-effect-free beyond jax import.
    sys.modules["ts_bench_module"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_bracketed_efficiency_uses_best_bracket_side():
    bench = _load_bench()
    # 1 GiB moved in 10 s = 0.1 GB/s achieved; brackets are the max of
    # the adjacent probes.
    brackets, ratios, eff, unstable = bench._bracketed_efficiency(
        [10.0, 20.0], [0.1, 0.2, 0.1], gib=1.0
    )
    assert brackets == [0.2, 0.2]
    assert abs(ratios[0] - 0.5) < 1e-9  # 0.1 achieved / 0.2 bracket
    assert abs(ratios[1] - 0.25) < 1e-9  # 0.05 achieved / 0.2 bracket
    assert abs(eff - 0.375) < 1e-9  # median of the two
    # 0.1 -> 0.2 adjacent disagreement is exactly 2x > 1.5x.
    assert unstable


def test_bracketed_efficiency_stable_link_not_flagged():
    bench = _load_bench()
    _, _, eff, unstable = bench._bracketed_efficiency(
        [10.0], [0.1, 0.12], gib=1.0
    )
    assert not unstable
    assert abs(eff - (0.1 / 0.12)) < 1e-9


def test_scaled_chunk_targets_probe_seconds_within_clamp():
    bench = _load_bench()
    # 0.015 GB/s link, 4 streams, 12 s target -> ~46 MiB per stream.
    mib = bench._scaled_chunk_mib(0.015, 4)
    assert 32 <= mib <= 64
    # Fast link clamps at the pipeline's real 256 MiB leaf size.
    assert bench._scaled_chunk_mib(10.0, 4) == 256
    # Degenerate/slow links clamp at the bandwidth-bound floor.
    assert bench._scaled_chunk_mib(0.0005, 4) == 32
    assert bench._scaled_chunk_mib(0.0, 4) == 32
