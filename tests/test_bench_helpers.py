"""Unit pins for bench.py's measurement helpers — the shared
bracketed-efficiency epistemics (one definition for save AND restore)
and the link-scaled probe sizing. Imported without running any leg."""

import importlib.util
import pathlib
import sys


def _load_bench():
    path = pathlib.Path(__file__).parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("ts_bench_module", path)
    mod = importlib.util.module_from_spec(spec)
    # bench.py installs nothing at import time (handlers install in
    # main()); importing is safe and side-effect-free beyond jax import.
    sys.modules["ts_bench_module"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_bracketed_efficiency_uses_best_bracket_side():
    bench = _load_bench()
    # 1 GiB moved in 10 s = 0.1 GB/s achieved; brackets are the max of
    # the adjacent probes.
    brackets, ratios, eff, unstable = bench._bracketed_efficiency(
        [10.0, 20.0], [0.1, 0.2, 0.1], gib=1.0
    )
    assert brackets == [0.2, 0.2]
    assert abs(ratios[0] - 0.5) < 1e-9  # 0.1 achieved / 0.2 bracket
    assert abs(ratios[1] - 0.25) < 1e-9  # 0.05 achieved / 0.2 bracket
    assert abs(eff - 0.375) < 1e-9  # median of the two
    # 0.1 -> 0.2 adjacent disagreement is exactly 2x > 1.5x.
    assert unstable


def test_bracketed_efficiency_stable_link_not_flagged():
    bench = _load_bench()
    _, _, eff, unstable = bench._bracketed_efficiency(
        [10.0], [0.1, 0.12], gib=1.0
    )
    assert not unstable
    assert abs(eff - (0.1 / 0.12)) < 1e-9


def test_bracketed_efficiency_warmup_exclusion():
    """warmup=1 drops the first (compile/warm-up) take from the MEDIAN
    and the instability check, but the raw ratio list keeps it; with
    nothing to spare (single trial) the full series is used."""
    bench = _load_bench()
    # First take 0.429-style slow, the rest steady: warm-up noise.
    times = [23.3, 10.0, 10.0]
    probes = [0.1, 0.2, 0.1, 0.1]
    _, ratios_all, eff_all, unstable_all = bench._bracketed_efficiency(
        times, probes, gib=1.0
    )
    _, ratios, eff, unstable = bench._bracketed_efficiency(
        times, probes, gib=1.0, warmup=1
    )
    assert ratios == ratios_all  # raw per-take list keeps the warm-up
    assert len(ratios) == 3
    # Full-series median is dragged to 0.5 by the warm-up take; the
    # steady-state median over takes 1..2 is 0.75.
    assert abs(eff_all - 0.5) < 1e-9
    assert abs(eff - 0.75) < 1e-9
    assert unstable_all  # the 0.1 -> 0.2 warm-up swing trips it...
    assert unstable  # ...and this tail genuinely moves 2x, still flagged
    # A steady post-warm-up tail is NOT flagged even when the warm-up
    # probe pair alone would have tripped the check.
    _, _, _, unstable_steady = bench._bracketed_efficiency(
        [23.3, 10.0, 10.0], [0.2, 0.11, 0.1, 0.11], gib=1.0, warmup=1
    )
    assert not unstable_steady
    _, _, _, unstable_full = bench._bracketed_efficiency(
        [23.3, 10.0, 10.0], [0.2, 0.11, 0.1, 0.11], gib=1.0
    )
    assert unstable_full
    # Single trial: warm-up cannot be spared; full series used.
    _, r1, e1, _ = bench._bracketed_efficiency(
        [10.0], [0.1, 0.12], gib=1.0, warmup=1
    )
    assert abs(e1 - r1[0]) < 1e-9


def test_final_line_round_trips_json_and_json_out(tmp_path, capsys, monkeypatch):
    """The final stdout line must json.loads cleanly (BENCH_r04/r05
    parsed null on a truncated prose-adjacent tail), and --json-out
    mirrors the same record to a file the driver can read even when
    stdout capture is lossy."""
    import json

    bench = _load_bench()
    out_path = tmp_path / "record.json"
    monkeypatch.setattr(bench, "_FINAL_EMITTED", False)
    monkeypatch.setattr(bench, "_JSON_OUT", str(out_path))
    monkeypatch.setattr(bench, "_PARTIAL_PATH", tmp_path / "partial.json")
    # Non-default-run marker: the helper must not rewrite BENCH.md.
    monkeypatch.setattr(bench, "_OVERRIDES", ["TS_BENCH_GB"])
    bench.RESULT["value"] = 1.23
    bench._emit_final(True)
    out_lines = capsys.readouterr().out.strip().splitlines()
    record = json.loads(out_lines[-1])  # the round-trip contract
    assert record["value"] == 1.23
    assert record["complete"] is True
    assert "\n" not in out_lines[-1]
    file_record = json.loads(out_path.read_text())
    assert file_record["value"] == record["value"]
    assert file_record["complete"] is True


def test_scaled_chunk_targets_probe_seconds_within_clamp():
    bench = _load_bench()
    # 0.015 GB/s link, 4 streams, 12 s target -> ~46 MiB per stream.
    mib = bench._scaled_chunk_mib(0.015, 4)
    assert 32 <= mib <= 64
    # Fast link clamps at the pipeline's real 256 MiB leaf size.
    assert bench._scaled_chunk_mib(10.0, 4) == 256
    # Degenerate/slow links clamp at the bandwidth-bound floor.
    assert bench._scaled_chunk_mib(0.0005, 4) == 32
    assert bench._scaled_chunk_mib(0.0, 4) == 32
