"""Scale-model storms: correctness under world sizes the 2-proc suite
cannot reach, and the coordination-scaling acceptance instruments.

Fast lane: a ≤256-simulated-rank storm smoke test (clean run and
injected rank death) over the REAL dist_store/pg_wrapper/fanout code
paths, batching/request-count pins via the counting store, and the
``coordination-bound`` doctor rule / report plumbing. Slow lane: the
1000-rank sweep asserting the tree barrier's coordination cost grows
sub-linearly (hot-key fan-in stays O(fanout)) where the linear
barrier's concentrates O(world·polls) on its leader keys.

Wall-clock notes: with hundreds of simulated ranks in ONE process the
thread scheduler, not the coordination protocol, dominates wall time —
so these tests pin *structural* quantities (request counts, per-key
fan-in, completion, abort latency bounds) and leave the wall curves to
``benchmarks/coordination_scaling.py`` at worlds where scheduler noise
stays bounded.
"""

import time

import pytest

from torchsnapshot_tpu import knobs
from torchsnapshot_tpu.dist_store import InProcessStore, lookup_endpoints, publish_endpoint
from torchsnapshot_tpu.scalemodel import (
    CountingStore,
    PerKeyStore,
    StormConfig,
    StormResult,
    run_storm,
)
from torchsnapshot_tpu.telemetry import names
from torchsnapshot_tpu.telemetry.doctor import diagnose_reports
from torchsnapshot_tpu.telemetry.report import build_report


# ---------------------------------------------------------------------------
# Storm smoke (fast lane)
# ---------------------------------------------------------------------------


def test_storm_smoke_256_ranks():
    """256 simulated ranks drive save + restore + endpoint storms to
    completion on the shipped defaults: every rank's exchanged bytes
    verify, nobody errors, nobody hangs."""
    result = run_storm(
        StormConfig(world_size=256, steps=1, timeout_s=120.0)
    )
    assert result.errors == {}
    assert result.hung_ranks == 0
    assert result.verified_ranks == 256
    # The exchange and barrier keys are transient: per-key touches must
    # exist (the storm really ran) and the coordination counters must
    # have observed it.
    assert result.store_requests > 0
    assert result.max_s["barrier_s"] > 0
    assert result.max_s["exchange_s"] > 0


def test_storm_rank_death_aborts_survivors_fast():
    """Injected rank death mid-round: every survivor abandons via the
    poisoned round barrier (BarrierError/FanoutError) well inside the
    round timeout — the production fail-fast contract at a world size
    the 2-proc sweep cannot exercise."""
    t0 = time.monotonic()
    result = run_storm(
        StormConfig(
            world_size=96,
            steps=1,
            kill_ranks=frozenset({7, 41}),
            timeout_s=60.0,
        )
    )
    elapsed = time.monotonic() - t0
    assert result.survivors_aborted_cleanly()
    # Victims recorded their injected fault; survivors their aborts.
    assert len(result.errors) == 96
    assert "SimulatedPreemption" in result.errors[7]
    # Fail-fast, not timeout-bound: the whole storm (including victim
    # detection on every survivor) must resolve far below the 60 s
    # round timeout.
    assert elapsed < 30.0


def test_storm_linear_barrier_and_per_key_baseline_complete():
    """The baseline axes (LinearBarrier, per-key store ops, legacy
    fixed polling) still complete correctly at a modest world — the
    bench compares their cost, not their correctness."""
    result = run_storm(
        StormConfig(
            world_size=32,
            steps=1,
            barrier="linear",
            batched=False,
            legacy_poll=True,
            timeout_s=60.0,
        )
    )
    assert result.errors == {}
    assert result.verified_ranks == 32


def test_batched_storm_issues_fewer_store_requests():
    """The batching pin: the same storm over the same store issues
    materially fewer wire requests with multi-key ops than with the
    per-key baseline (each multi_* is ONE request; per-key degrades to
    one per key)."""
    batched = run_storm(
        StormConfig(world_size=48, steps=2, timeout_s=60.0)
    )
    per_key = run_storm(
        StormConfig(world_size=48, steps=2, batched=False, timeout_s=60.0)
    )
    assert batched.errors == {} and per_key.errors == {}
    assert batched.store_requests < per_key.store_requests


def test_sharded_store_storm_completes():
    result = run_storm(
        StormConfig(world_size=48, steps=1, store_shards=4, timeout_s=60.0)
    )
    assert result.errors == {}
    assert result.verified_ranks == 48


def test_tree_hot_key_fanin_bounded_vs_linear():
    """The structural claim at fast-lane scale: the tree barrier's
    hottest data key sees O(fanout) touches while the linear barrier
    concentrates O(world·polls) on its leader keys."""
    common = dict(
        steps=3,
        warmup_steps=1,
        save_collectives=False,
        restore_storm=False,
        endpoint_round=False,
        timeout_s=60.0,
    )
    tree = run_storm(StormConfig(world_size=128, **common))
    linear = run_storm(
        StormConfig(world_size=128, barrier="linear", **common)
    )
    assert tree.errors == {} and linear.errors == {}
    assert tree.hot_data_key_touches < linear.hot_data_key_touches
    # Fanout 16, 3 timed steps, 2 phases: the root counter is touched
    # ~fanout times per phase plus a few polls — two orders of
    # magnitude under 128 ranks' worth.
    assert tree.hot_data_key_touches < 128 * 3


# ---------------------------------------------------------------------------
# Endpoint batching pin (satellite: one round trip, not world lookups)
# ---------------------------------------------------------------------------


def test_endpoint_resolution_is_one_round_trip():
    inner = InProcessStore()
    for rank in range(64):
        publish_endpoint(inner, "svc", rank, "host", 9000 + rank)
    store = CountingStore(inner)
    endpoints = lookup_endpoints(store, "svc", range(64))
    assert len(endpoints) == 64
    assert endpoints[5] == ("host", 9005)
    assert store.counts == {"multi_get": 1}


def test_endpoint_resolution_per_key_baseline_pays_world_requests():
    # Counting at the wire, per-key adapter above it: the baseline's
    # one logical resolve fans into world sequential requests.
    inner = InProcessStore()
    for rank in range(64):
        publish_endpoint(inner, "svc", rank, "host", 9000 + rank)
    counting = CountingStore(inner)
    endpoints = lookup_endpoints(PerKeyStore(counting), "svc", range(64))
    assert len(endpoints) == 64
    assert counting.total_requests == 64


# ---------------------------------------------------------------------------
# Report / doctor plumbing
# ---------------------------------------------------------------------------


def _coord_report(barrier_s=2.0, store_s=1.0, exchange_s=0.0, wall_s=1.0):
    report = build_report(
        kind="restore",
        path="/tmp/snap",
        rank=0,
        world_size=256,
        pipeline={"phases": {"loading": wall_s}},
        counter_deltas={
            f"{names.COORD_BARRIER_WAIT_SECONDS_TOTAL}"
            '{impl="tree",phase="arrive"}': barrier_s,
            f"{names.COORD_STORE_SECONDS_TOTAL}" '{op="multi_get"}': store_s,
            f"{names.COORD_STORE_REQUESTS_TOTAL}"
            '{op="multi_get"}': 1000.0,
            names.COORD_EXCHANGE_SECONDS_TOTAL: exchange_s,
        },
    ).to_dict()
    return report


def test_report_coordination_field_from_counter_deltas():
    report = _coord_report()
    assert report["coordination"]["barrier_wait_s"] == pytest.approx(2.0)
    assert report["coordination"]["store_s"] == pytest.approx(1.0)
    assert report["coordination"]["store_ops"] == pytest.approx(1000.0)
    # No coordination traffic at all -> schema-light None.
    empty = build_report(
        kind="take",
        path="/tmp/snap",
        rank=0,
        world_size=1,
        pipeline={},
        counter_deltas={},
    )
    assert empty.coordination is None


def test_coordination_bound_rule_fires_and_cites_split():
    verdicts = diagnose_reports(
        [_coord_report(barrier_s=2.0, store_s=1.0, wall_s=1.0)]
    )
    hits = [v for v in verdicts if v.rule == names.RULE_COORDINATION_BOUND]
    assert len(hits) == 1
    ev = hits[0].evidence
    assert ev["barrier_wait_s"] == pytest.approx(2.0)
    assert ev["coordination_fraction"] > 0.5
    assert names.SPAN_BARRIER_ARRIVE in ev["spans"]


def test_coordination_bound_rule_quiet_when_coordination_small():
    # 2% of the wall: healthy.
    verdicts = diagnose_reports(
        [_coord_report(barrier_s=0.1, store_s=0.1, wall_s=10.0)]
    )
    assert not any(
        v.rule == names.RULE_COORDINATION_BOUND for v in verdicts
    )
    # Sub-floor absolute coordination never flags (ms-scale local ops).
    verdicts = diagnose_reports(
        [_coord_report(barrier_s=0.01, store_s=0.01, wall_s=0.01)]
    )
    assert not any(
        v.rule == names.RULE_COORDINATION_BOUND for v in verdicts
    )


def test_history_summary_carries_coordination_seconds():
    from torchsnapshot_tpu.telemetry.history import summarize_report
    from torchsnapshot_tpu.telemetry.report import SnapshotReport

    report = SnapshotReport.from_dict(_coord_report())
    summary = summarize_report(report, step=3)
    assert summary["coordination_s"] == pytest.approx(3.0)
    no_coord = SnapshotReport(kind="take", path="/tmp/x")
    assert summarize_report(no_coord)["coordination_s"] is None


# ---------------------------------------------------------------------------
# 1000-rank sweep (slow lane)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_thousand_rank_sweep_tree_sublinear_vs_linear():
    """The tentpole's acceptance sweep: barrier-only storms at world 64
    and 1000. The tree barrier completes at 1000 simulated ranks with
    zero errors, and its coordination cost grows SUB-linearly — the
    hot-key fan-in (the serialized per-key work a real store pays; wall
    time at 1000 threads in one process measures the host scheduler,
    see module docstring) stays O(fanout) while the world grew 15.6x —
    where the linear barrier's leader keys absorb orders of magnitude
    more."""
    common = dict(
        steps=3,
        warmup_steps=1,
        save_collectives=False,
        restore_storm=False,
        endpoint_round=False,
        timeout_s=300.0,
    )
    tree_64 = run_storm(StormConfig(world_size=64, **common))
    tree_1000 = run_storm(StormConfig(world_size=1000, **common))
    linear_1000 = run_storm(
        StormConfig(world_size=1000, barrier="linear", **common)
    )
    for result in (tree_64, tree_1000, linear_1000):
        assert result.errors == {}
        assert result.hung_ranks == 0
    # Sub-linear: the world grew 15.6x; the tree's hottest data key
    # must not grow anywhere near that (it is bounded by the fanout
    # plus poll jitter — measured ~2x).
    assert (
        tree_1000.hot_data_key_touches
        < tree_64.hot_data_key_touches * 8
    )
    # ...while the linear barrier's leader keys concentrate orders of
    # magnitude more serialized work at the same world.
    assert (
        linear_1000.hot_data_key_touches
        > tree_1000.hot_data_key_touches * 20
    )


def test_storm_result_shape():
    """The bench leg consumes these fields; pin the contract."""
    result = run_storm(StormConfig(world_size=4, steps=1, timeout_s=30.0))
    assert isinstance(result, StormResult)
    for key in ("collective_s", "barrier_s", "exchange_s", "endpoint_s"):
        assert key in result.max_s and key in result.mean_s
    assert result.coordination_s >= 0
    assert result.counters  # coordination_* deltas observed
    assert any(
        k.startswith("coordination_barrier_wait_seconds_total")
        for k in result.counters
    )
