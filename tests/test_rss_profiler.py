"""RSS profiler sanity: the sampler observes a large transient allocation.

Reference parity: tests/test_rss_profiler.py (rss_profiler.py:20-56).
"""

from __future__ import annotations

import numpy as np

from torchsnapshot_tpu.utils.rss_profiler import RSSDeltas, measure_rss_deltas


def test_measures_peak_allocation() -> None:
    deltas = RSSDeltas()
    nbytes = 256 * 1024 * 1024
    with measure_rss_deltas(deltas, sample_period_seconds=0.01):
        blob = np.ones(nbytes // 8, dtype=np.float64)
        blob += 1.0  # touch every page
        s = float(blob.sum())
        del blob
    assert s > 0
    assert len(deltas.deltas) >= 1
    # Peak should see most of the 256 MB allocation.
    assert deltas.peak_bytes > nbytes // 2


def test_no_allocation_small_peak() -> None:
    deltas = RSSDeltas()
    with measure_rss_deltas(deltas, sample_period_seconds=0.01):
        x = sum(range(1000))
    assert x == 499500
    # A final sample is always appended at exit.
    assert len(deltas.deltas) >= 1
    assert deltas.peak_bytes < 64 * 1024 * 1024
