"""Multi-process distributed take/restore: per-rank state, replicated
write-load partitioning, elastic world-size changes.

Structural model: reference tests/test_ddp.py + test_replication_glob.py +
test_partitioner.py distributed cases, on the TCP-store harness instead of
gloo.
"""

import os
import tempfile

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.test_utils import multiprocess_test


@multiprocess_test(nproc=2)
def test_restore_peer_failure_fails_fast(pg) -> None:
    """Rank 1's DATA reads fail mid-restore: the error propagates through
    the inter-stateful barrier so rank 0 raises within seconds instead of
    blocking out the 300 s store timeout, and a clean retry restores
    per-rank values correctly afterwards."""
    import time
    from unittest import mock

    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    path = os.path.join(tempfile.gettempdir(), "restore-fail-fast-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    state = {
        "m": ts.PyTreeState(
            {"w": np.full(4096, 1.0 + pg.rank, np.float32)}
        )
    }
    ts.Snapshot.take(path, state, pg=pg)

    from torchsnapshot_tpu.test_utils import (
        faulty_fs_plugin,
        patch_storage_plugin,
    )

    # Data blobs only: metadata/checksum-table reads precede any
    # cross-rank coordination.
    FaultyDataRead = faulty_fs_plugin(
        lambda p: "/m/" in p, ops=("read",), exc_msg="injected read failure"
    )
    cls = FaultyDataRead if pg.rank == 1 else FSStoragePlugin
    patch = patch_storage_plugin(cls)
    dst = {"m": ts.PyTreeState({"w": np.zeros(4096, np.float32)})}
    t0 = time.monotonic()
    with patch, pytest.raises(Exception):
        ts.Snapshot(path, pg=pg).restore(dst)
    assert time.monotonic() - t0 < 60.0, "survivor blocked to store timeout"

    dst2 = {"m": ts.PyTreeState({"w": np.zeros(4096, np.float32)})}
    ts.Snapshot(path, pg=pg).restore(dst2)
    assert float(dst2["m"].tree["w"][0]) == 1.0 + pg.rank


@multiprocess_test(nproc=2)
def test_distributed_take_and_manifest(pg) -> None:
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "dist-take-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    app_state = {
        "params": ts.PyTreeState(
            {"w": jnp.full((64, 8), 7.5, jnp.float32), "b": jnp.arange(8.0)}
        ),
        "progress": ts.StateDict(rank_steps=100 + pg.rank),
    }
    snap = ts.Snapshot.take(path, app_state, pg=pg, replicated=["params/**"])

    md = snap.metadata
    assert md.world_size == 2
    # Replicated entries live under rank 0 only; per-rank entries per rank.
    assert md.manifest["0/params/w"].replicated
    assert "1/params/w" not in md.manifest
    assert "0/progress/rank_steps" in md.manifest
    assert "1/progress/rank_steps" in md.manifest

    # Write-load partitioning: replicated blobs exist exactly once on disk,
    # and both ranks' write loads were used (w and b should not both land
    # on rank 0 given b is tiny... the invariant that matters: one copy).
    w_file = os.path.join(path, "replicated", "params", "w")
    b_file = os.path.join(path, "replicated", "params", "b")
    assert os.path.exists(w_file) and os.path.exists(b_file)

    # Restore on both ranks into fresh state.
    fresh = {
        "params": ts.PyTreeState(
            {"w": jnp.zeros((64, 8)), "b": jnp.zeros(8)}
        ),
        "progress": ts.StateDict(rank_steps=-1),
    }
    ts.Snapshot(path, pg=pg).restore(fresh)
    assert float(fresh["params"].tree["w"][0, 0]) == 7.5
    assert float(fresh["params"].tree["b"][5]) == 5.0
    assert fresh["progress"]["rank_steps"] == 100 + pg.rank


@multiprocess_test(nproc=2)
def test_replicated_glob_must_match_everywhere(pg) -> None:
    """A glob only some ranks declare is not treated as replicated
    (reference _coalesce_path_and_replicated intersection semantics)."""
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "dist-glob-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    app_state = {"p": ts.PyTreeState({"w": jnp.ones(4)})}
    replicated = ["p/**"] if pg.rank == 0 else []
    snap = ts.Snapshot.take(path, app_state, pg=pg, replicated=replicated)
    md = snap.metadata
    # Not replicated anywhere -> per-rank entries on both ranks.
    assert not md.manifest["0/p/w"].replicated
    assert "1/p/w" in md.manifest


@multiprocess_test(nproc=2)
def test_elastic_restore_world2_to_world1_replicated(pg) -> None:
    """World-size-2 snapshot restored by a single process: replicated state
    is available; per-rank state of rank 1 is not visible to rank 0."""
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "dist-elastic-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    app_state = {
        "params": ts.PyTreeState({"w": jnp.full(16, 3.0)}),
        "progress": ts.StateDict(steps=pg.rank),
    }
    ts.Snapshot.take(path, app_state, pg=pg, replicated=["params/**"])

    if pg.rank == 0:
        # Single-process restore (no pg): world-size 1 vs snapshot world 2.
        fresh = {
            "params": ts.PyTreeState({"w": jnp.zeros(16)}),
            "progress": ts.StateDict(steps=-1),
        }
        ts.Snapshot(path).restore(fresh)
        assert float(fresh["params"].tree["w"][0]) == 3.0
        assert fresh["progress"]["steps"] == 0  # rank 0's own value


def test_partitioner_balances_loads() -> None:
    """Unit-level: greedy assignment spreads replicated bytes by argmin load."""
    from torchsnapshot_tpu.io_types import BufferStager, WriteReq
    from torchsnapshot_tpu.partitioner import partition_write_reqs
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    class FakeStager(BufferStager):
        def __init__(self, n):
            self.n = n

        async def stage_buffer(self, executor=None):
            return b"x" * self.n

        def get_staging_cost_bytes(self):
            return self.n

    class FakePG(PGWrapper):
        """Rank 0 of a two-rank world simulated in one process: gathers
        return symmetric data because replicated inputs are identical, and
        rank 0's broadcast is the identity."""

        def __init__(self, rank):
            self.store = None
            self.rank = rank
            self.world_size = 2
            self._op_seq = 0

        def all_gather_object(self, obj):
            return [obj, obj]

        def gather_object(self, obj, dst=0):
            return [obj, obj] if self.rank == dst else None

        def broadcast_object(self, obj, src=0):
            assert self.rank == src
            return obj

    reqs = [
        WriteReq("replicated/a", FakeStager(100)),
        WriteReq("replicated/b", FakeStager(90)),
        WriteReq("replicated/c", FakeStager(10)),
        WriteReq("0/own", FakeStager(5)),
    ]
    pg0 = FakePG(0)
    _, kept0 = partition_write_reqs({}, list(reqs), pg0)
    kept0_paths = {r.path for r in kept0}
    assert "0/own" in kept0_paths
    # Greedy: a(100)->r0? loads start [5,5]; a->rank0(or 1, tie -> 0),
    # b(90)->other rank, c(10)-> lighter rank.
    assert "replicated/a" in kept0_paths
    assert "replicated/b" not in kept0_paths


@multiprocess_test(nproc=2)
def test_multiprocess_sharded_array(pg) -> None:
    """True multi-host semantics: a global array sharded across two
    *processes* (non-fully-addressable), each writing only its own shards,
    restored with the roles reversed."""
    import jax

    coord_port = 29500 + (os.getpid() % 500) if pg.rank == 0 else None
    coord_port = PGWrapper_bcast(pg, coord_port)
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=pg.world_size,
        process_id=pg.rank,
    )
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # One device per process (workers inherit the 8-virtual-device flag, so
    # pick explicitly across process indices).
    dev_by_proc = [
        next(d for d in jax.devices() if d.process_index == p) for p in (0, 1)
    ]
    mesh = Mesh(np.array(dev_by_proc), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    full = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    xs = jax.make_array_from_callback((16, 4), sharding, lambda idx: full[idx])
    assert not xs.is_fully_addressable

    path = os.path.join(tempfile.gettempdir(), "dist-sharded-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    snap = ts.Snapshot.take(path, {"m": ts.PyTreeState({"w": xs})}, pg=pg)
    md = snap.metadata
    # Each rank contributed its own shard(s) under its own rank key.
    all_shards = [
        s for k, e in md.manifest.items() if e.type == "ShardedArray" for s in e.shards
    ]
    assert sorted(tuple(s.offsets) for s in all_shards) == [(0, 0), (8, 0)]

    # Restore into a reversed device order (different box per process).
    mesh2 = Mesh(np.array(dev_by_proc[::-1]), ("x",))
    sharding2 = NamedSharding(mesh2, P("x"))
    target = jax.make_array_from_callback(
        (16, 4), sharding2, lambda idx: np.zeros((8, 4), np.float32)
    )
    fresh = {"m": ts.PyTreeState({"w": target})}
    ts.Snapshot(path, pg=pg).restore(fresh)
    w = fresh["m"].tree["w"]
    local = {tuple(int(x) for x in s.index[0].indices(16)[:2]): np.asarray(s.data) for s in w.addressable_shards}
    for (start, stop), data in local.items():
        np.testing.assert_array_equal(data, full[start:stop])


def PGWrapper_bcast(pg, value):
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    return PGWrapper(pg).broadcast_object(value)


@multiprocess_test(nproc=4)
def test_four_rank_protocol_roundtrip(pg) -> None:
    """The full distributed protocol at 4 ranks (reference exercises
    4-rank partitioning, tests/test_partitioner.py:103-119): replicated
    verification + bin-packing + chunk sub-partitioning + manifest gather
    + commit barrier, then a 4-rank restore."""
    import jax.numpy as jnp

    from torchsnapshot_tpu import knobs

    path = os.path.join(tempfile.gettempdir(), "dist-4rank-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    app_state = {
        # 64x64 fp32 with 16-row chunks -> 4 sub-partitionable chunks.
        "params": ts.PyTreeState(
            {
                "big": jnp.arange(64.0 * 64).reshape(64, 64),
                "small": jnp.full((32,), 1.5, jnp.float32),
            }
        ),
        "progress": ts.StateDict(steps=100 + pg.rank),
    }
    with knobs.override_max_chunk_size_bytes(64 * 4 * 16):
        snap = ts.Snapshot.take(path, app_state, pg=pg, replicated=["params/**"])

    md = snap.metadata
    assert md.world_size == 4
    assert md.manifest["0/params/big"].replicated
    for r in (1, 2, 3):
        assert f"{r}/params/big" not in md.manifest
        assert f"{r}/progress/steps" in md.manifest
    # Consolidation restored the complete chunk list on the gathered entry.
    assert len(md.manifest["0/params/big"].chunks) == 4

    fresh = {
        "params": ts.PyTreeState(
            {"big": jnp.zeros((64, 64)), "small": jnp.zeros(32)}
        ),
        "progress": ts.StateDict(steps=-1),
    }
    ts.Snapshot(path, pg=pg).restore(fresh)
    np.testing.assert_array_equal(
        np.asarray(fresh["params"].tree["big"]),
        np.arange(64.0 * 64, dtype=np.float32).reshape(64, 64),
    )
    assert float(fresh["params"].tree["small"][0]) == 1.5
    assert fresh["progress"]["steps"] == 100 + pg.rank


def _elastic_shard_worker(pg, path: str, devices_per_proc: int, mode: str):
    """take: write a globally-sharded array from this world size.
    restore: read it back into this world's (different) sharding."""
    import jax

    from torchsnapshot_tpu.test_utils import get_free_port

    coord_port = PGWrapper_bcast(
        pg, get_free_port() if pg.rank == 0 else None
    )
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=pg.world_size,
        process_id=pg.rank,
    )
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = []
    for p in range(pg.world_size):
        devs.extend(
            [d for d in jax.devices() if d.process_index == p][:devices_per_proc]
        )
    mesh = Mesh(np.array(devs), ("x",))
    rows = 32  # divisible by 2, 4, and 8 shard counts
    full = np.arange(rows * 4, dtype=np.float32).reshape(rows, 4)
    sharding = NamedSharding(mesh, P("x"))
    rows_per_shard = rows // len(devs)

    if mode == "take":
        arr = jax.make_array_from_callback((rows, 4), sharding, lambda i: full[i])
        assert not arr.is_fully_addressable
        snap = ts.Snapshot.take(path, {"m": ts.PyTreeState({"w": arr})}, pg=pg)
        assert snap.metadata.world_size == pg.world_size
        return pg.world_size
    assert mode == "restore"
    target = jax.make_array_from_callback(
        (rows, 4),
        sharding,
        lambda i: np.zeros((rows_per_shard, 4), np.float32),
    )
    dest = {"m": ts.PyTreeState({"w": target})}
    ts.Snapshot(path, pg=pg).restore(dest)
    w = dest["m"].tree["w"]
    for s in w.addressable_shards:
        start, stop, _ = s.index[0].indices(rows)
        np.testing.assert_array_equal(np.asarray(s.data), full[start:stop])
    return pg.world_size


@pytest.mark.parametrize("take_world,restore_world", [(4, 2), (2, 4)])
def test_elastic_sharded_restore_across_world_sizes(
    tmp_path, take_world, restore_world
) -> None:
    """Elastic resharding through the full multiprocess protocol: a
    snapshot taken at one world size restores at another, with shards
    merged across ranks and overlap-read into the new sharding
    (reference io_preparer.py:317-391 + manifest.py:333-371)."""
    from torchsnapshot_tpu.test_utils import run_multiprocess

    path = str(tmp_path / "elastic")
    assert run_multiprocess(
        _elastic_shard_worker,
        nproc=take_world,
        args=(path, 2, "take"),
        timeout=300.0,
    ) == [take_world] * take_world
    assert run_multiprocess(
        _elastic_shard_worker,
        nproc=restore_world,
        args=(path, 2, "restore"),
        timeout=300.0,
    ) == [restore_world] * restore_world


@multiprocess_test(nproc=2)
def test_take_rng_on_one_rank_keeps_barrier_schedule(pg) -> None:
    """An RngState present on only one rank must not reorder the gathered
    key list at take time (the RNG capture happens out of band; its key
    keeps its sorted barrier slot). Regression: rng_first used to move
    the key to the front on the holding rank only."""
    import jax
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "dist-take-rng-asym")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    state = {
        "aa": ts.StateDict(v=pg.rank),
        "zz": ts.StateDict(w=10 + pg.rank),
    }
    if pg.rank == 0:
        state["mm_rng"] = ts.RngState(jax.random.key(5))
    snap = ts.Snapshot.take(path, state, pg=pg)
    md = snap.metadata
    assert "0/mm_rng/keys" in md.manifest
    assert "1/aa/v" in md.manifest

    dest = {"aa": ts.StateDict(v=-1), "zz": ts.StateDict(w=-1)}
    if pg.rank == 0:
        dest["mm_rng"] = ts.RngState(jax.random.key(9))
    ts.Snapshot(path, pg=pg).restore(dest)
    assert dest["aa"]["v"] == pg.rank
    assert dest["zz"]["w"] == 10 + pg.rank


@multiprocess_test(nproc=2)
def test_restore_setup_failure_fails_fast(pg) -> None:
    """Rank 1 fails in restore SETUP (the manifest read — the
    pre-coordination phase): round 5 hoists the restore's collectives
    before the setup reads and reports setup failures into key barrier
    0, so rank 0 abandons there in seconds instead of stranding inside
    an op-seq collective poll for the full store timeout."""
    import contextlib
    import time
    from unittest import mock

    import numpy as np

    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    path = os.path.join(tempfile.gettempdir(), "restore-setup-fail")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    PGWrapper(pg).barrier()
    state = {"m": ts.PyTreeState({"w": np.full(2048, 2.0 + pg.rank)})}
    ts.Snapshot.take(path, state, pg=pg)

    dest = {"m": ts.PyTreeState({"w": np.zeros(2048)})}
    ctx = (
        mock.patch(
            "torchsnapshot_tpu.snapshot.get_manifest_for_rank",
            side_effect=OSError("injected manifest read failure"),
        )
        if pg.rank == 1
        else contextlib.nullcontext()
    )
    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        ts.Snapshot(path, pg=pg).restore(dest)
    assert time.monotonic() - t0 < 60.0, "peer blocked to store timeout"

    dest2 = {"m": ts.PyTreeState({"w": np.zeros(2048)})}
    ts.Snapshot(path, pg=pg).restore(dest2)
    assert float(dest2["m"].tree["w"][0]) == 2.0 + pg.rank
