"""Sharded-array checkpointing: write dedup, shard subdivision, and the
elastic resharding matrix.

Structural model: reference tests/test_sharded_tensor_resharding.py — write
with one spec, restore into another, compare the full array; crossed over a
matrix of source × destination shardings on the 8-device virtual mesh.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.knobs import override_max_shard_size_bytes
from torchsnapshot_tpu.resharding import (
    Box,
    box_overlap,
    plan_row_slab_reads,
    row_slab_byte_window,
    subdivide_box,
    target_boxes_for_sharding,
)


def _mesh(shape, names):
    devs = jax.devices()
    needed = int(np.prod(shape))
    if len(devs) < needed:
        pytest.skip(
            f"needs {needed} devices, backend has {len(devs)} "
            f"(CPU runs force an 8-device virtual mesh via conftest)"
        )
    return Mesh(np.array(devs[:needed]).reshape(shape), names)


def _shardings():
    """A spread of GSPMD layouts over 8 devices: 1-d, 2-d, replicated mixes,
    and uneven divisions."""
    m8 = _mesh((8,), ("x",))
    m42 = _mesh((4, 2), ("a", "b"))
    m24 = _mesh((2, 4), ("a", "b"))
    return {
        "row8": NamedSharding(m8, P("x")),
        "col8": NamedSharding(m8, P(None, "x")),
        "grid42": NamedSharding(m42, P("a", "b")),
        "grid24": NamedSharding(m24, P("a", "b")),
        "rowrep": NamedSharding(m42, P("a")),  # replicated over b
        "colrep": NamedSharding(m42, P(None, "b")),  # replicated over a
        "full_replicated_grid": NamedSharding(m42, P()),
    }


_MATRIX = list(itertools.permutations(["row8", "grid42", "colrep"], 2)) + [
    ("row8", "row8"),
    ("grid42", "grid24"),
    ("col8", "rowrep"),
    ("rowrep", "col8"),
    ("grid24", "full_replicated_grid"),
]


@pytest.mark.parametrize("src_name,dst_name", _MATRIX)
def test_resharding_matrix(tmp_path, src_name, dst_name) -> None:
    shardings = _shardings()
    x = jnp.arange(32 * 24, dtype=jnp.float32).reshape(32, 24)
    xs = jax.device_put(x, shardings[src_name])
    ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})

    target = jax.device_put(jnp.zeros((32, 24)), shardings[dst_name])
    fresh = {"m": ts.PyTreeState({"w": target})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    w = fresh["m"].tree["w"]
    assert w.sharding.is_equivalent_to(shardings[dst_name], 2)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(x))


_FUZZ_MESH_SHAPES = [(8,), (4, 2), (2, 4), (2, 2, 2), (4,), (2,), (1,)]


def _rand_mesh(rng):
    shape = _FUZZ_MESH_SHAPES[rng.integers(0, len(_FUZZ_MESH_SHAPES))]
    devs = jax.devices()
    n = int(np.prod(shape))
    names = tuple(f"ax{i}" for i in range(len(shape)))
    return Mesh(np.array(devs[:n]).reshape(shape), names)


def _rand_valid_spec(rng, mesh, shape):
    """A random PartitionSpec each of whose sharded dims is divisible by
    its mesh axis (device_put's constraint — the framework itself also
    handles misaligned boundaries; see the dedicated test above)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    names = list(mesh.axis_names)
    rng.shuffle(names)
    spec = []
    for dim in shape:
        picked = None
        if rng.random() < 0.6:
            for i, n in enumerate(names):
                if dim % sizes[n] == 0:
                    picked = names.pop(i)
                    break
        spec.append(picked)
    return P(*spec)


@pytest.mark.parametrize("seed", range(16))
def test_resharding_fuzz(tmp_path, seed) -> None:
    """Property widening of the hand-picked matrix: random array shape,
    random source mesh/spec, restored under an independently random
    destination mesh/spec (different device counts included — elastic
    up and down), byte-compared. A 100-case sweep of this generator
    passed during round 4; these 16 deterministic seeds pin it."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    rng = np.random.default_rng(9000 + seed)
    src_mesh = _rand_mesh(rng)
    dst_mesh = _rand_mesh(rng)
    ndim = int(rng.integers(1, 4))
    shape = tuple(
        int(rng.choice([1, 2, 3, 4, 6, 8, 16, 24, 40])) for _ in range(ndim)
    )
    src_spec = _rand_valid_spec(rng, src_mesh, shape)
    dst_spec = _rand_valid_spec(rng, dst_mesh, shape)
    data = np.arange(np.prod(shape), dtype=np.float32).reshape(shape) + seed

    x = jax.device_put(jnp.asarray(data), NamedSharding(src_mesh, src_spec))
    ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": x})})
    dest = jax.device_put(
        jnp.zeros(shape, jnp.float32), NamedSharding(dst_mesh, dst_spec)
    )
    dp = ts.PyTreeState({"w": dest})
    ts.Snapshot(str(tmp_path)).restore({"m": dp})
    np.testing.assert_array_equal(
        np.asarray(dp.tree["w"]),
        data,
        err_msg=f"{shape} {src_spec} -> {dst_spec}",
    )


def test_misaligned_shard_boundaries(tmp_path) -> None:
    """Save 5-way, restore 3-way: 6-row saved shards vs 10-row destination
    boxes — every destination draws from two saved shards with non-aligned
    boundaries (the general-overlap case the reference's 1-d chunk walk
    cannot express)."""
    devs = jax.devices()
    src = NamedSharding(Mesh(np.array(devs[:5]), ("x",)), P("x"))
    dst = NamedSharding(Mesh(np.array(devs[:3]), ("x",)), P("x"))
    x = jnp.arange(30 * 3, dtype=jnp.float32).reshape(30, 3)
    xs = jax.device_put(x, src)
    ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    fresh = {"m": ts.PyTreeState({"w": jax.device_put(jnp.zeros((30, 3)), dst)})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    w = fresh["m"].tree["w"]
    assert w.sharding.is_equivalent_to(dst, 2)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(x))


def test_replica_dedup_writes_each_box_once(tmp_path) -> None:
    sharding = NamedSharding(_mesh((4, 2), ("a", "b")), P(None, "b"))
    x = jnp.ones((16, 8), jnp.float32)
    xs = jax.device_put(x, sharding)
    snap = ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    entry = snap.get_manifest()["0/m/w"]
    # 2-way column sharding replicated 4x: exactly 2 boxes on disk.
    assert len(entry.shards) == 2
    offsets = sorted(tuple(s.offsets) for s in entry.shards)
    assert offsets == [(0, 0), (0, 4)]


def test_shard_subdivision_knob(tmp_path) -> None:
    sharding = NamedSharding(_mesh((2, 4), ("a", "b")), P("a"))
    x = jnp.arange(64 * 16, dtype=jnp.float32).reshape(64, 16)
    xs = jax.device_put(x, sharding)
    with override_max_shard_size_bytes(1024):
        snap = ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    entry = snap.get_manifest()["0/m/w"]
    # Each 32x16 f32 box is 2 KiB -> split into 2x 16-row pieces.
    assert len(entry.shards) == 4
    for shard in entry.shards:
        assert shard.sizes[0] <= 16
    fresh = {"m": ts.PyTreeState({"w": jax.device_put(jnp.zeros((64, 16)), sharding)})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["m"].tree["w"]), np.asarray(x))


def test_sharded_read_object_full_assembly(tmp_path) -> None:
    sharding = NamedSharding(_mesh((8,), ("x",)), P("x", None))
    x = jnp.arange(16 * 6, dtype=jnp.bfloat16).reshape(16, 6)
    xs = jax.device_put(x, sharding)
    ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    out = ts.Snapshot(str(tmp_path)).read_object("0/m/w")
    assert isinstance(out, np.ndarray)
    assert out.dtype == np.dtype("bfloat16")
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(x, np.float32)
    )


def test_sharded_restore_shape_mismatch_raises(tmp_path) -> None:
    sharding = NamedSharding(_mesh((8,), ("x",)), P("x"))
    xs = jax.device_put(jnp.ones((16, 4)), sharding)
    ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    bad_target = jax.device_put(jnp.zeros((8, 4)), sharding)
    with pytest.raises(ValueError, match="reshard"):
        ts.Snapshot(str(tmp_path)).restore(
            {"m": ts.PyTreeState({"w": bad_target})}
        )


def test_box_overlap_math() -> None:
    a = Box((0, 0), (4, 4))
    b = Box((2, 2), (4, 4))
    ov = box_overlap(a, b)
    assert ov.src_slices == (slice(2, 4), slice(2, 4))
    assert ov.dst_slices == (slice(0, 2), slice(0, 2))
    assert box_overlap(Box((0,), (4,)), Box((4,), (4,))) is None
    with pytest.raises(ValueError, match="Rank mismatch"):
        box_overlap(Box((0,), (4,)), Box((0, 0), (4, 4)))


def test_subdivide_box() -> None:
    box = Box((8, 0), (10, 4))
    pieces = subdivide_box(box, max_bytes=4 * 4 * 4, itemsize=4)  # 4 rows/piece
    assert [p.offsets[0] for p in pieces] == [8, 12, 16]
    assert sum(p.sizes[0] for p in pieces) == 10
    # 0-d / tiny boxes stay whole.
    assert subdivide_box(Box((), ()), 10, 4) == [Box((), ())]


def test_plan_row_slab_reads_geometry() -> None:
    """The shared row-band planner: trailing-sliced overlaps still ride
    a banded ranged read (the amplification fix), buffer limits split
    the band, and whole-shard bands return None (caller's whole read)."""
    shard = (32, 24)
    itemsize = 4
    row_nbytes = 24 * itemsize
    # A column-partial overlap of rows [8, 16): the band is those rows.
    ov = box_overlap(Box((0, 0), shard), Box((8, 12), (8, 12)))
    plan = plan_row_slab_reads(shard, [ov], row_nbytes)
    assert plan is not None and len(plan) == 1
    (read,) = plan
    assert read.rows == (8, 16)
    assert read.byte_range == (8 * row_nbytes, 16 * row_nbytes)
    assert read.buf_shape == (8, 24)
    (copy,) = read.copies
    assert copy.dst_rows == slice(0, 8)
    assert copy.src_slices == (slice(0, 8), slice(12, 24))
    # The strict-slab window helper refuses a trailing-sliced overlap
    # (the compat bridge's per-piece loads cannot column-slice)...
    assert row_slab_byte_window(shard, ov, row_nbytes) is None
    # ...but accepts a full-trailing one, composing with a base offset.
    full = box_overlap(Box((0, 0), shard), Box((8, 0), (8, 24)))
    assert row_slab_byte_window(shard, full, row_nbytes, base=100) == (
        100 + 8 * row_nbytes,
        100 + 16 * row_nbytes,
    )
    # Whole-shard band with no limit: None (one whole read is optimal).
    whole = box_overlap(Box((0, 0), shard), Box((0, 0), shard))
    assert plan_row_slab_reads(shard, [whole], row_nbytes) is None
    # ...unless a buffer limit forces splitting.
    split = plan_row_slab_reads(
        shard, [whole], row_nbytes, buffer_limit_bytes=8 * row_nbytes
    )
    assert split is not None
    assert [r.rows for r in split] == [(0, 8), (8, 16), (16, 24), (24, 32)]
    # 0-d shards never range.
    assert plan_row_slab_reads((), [whole], itemsize) is None


def test_plan_row_slab_reads_roundtrip_matches_direct_copy() -> None:
    """Property pin: executing a plan's copies against a fake blob
    reproduces exactly what direct whole-shard slicing would."""
    rng = np.random.default_rng(7)
    for _ in range(24):
        ndim = int(rng.integers(1, 4))
        shard = tuple(int(rng.integers(1, 9)) for _ in range(ndim))
        src = rng.standard_normal(shard).astype(np.float32)
        overlaps = []
        views = []
        for _ in range(int(rng.integers(1, 4))):
            offs = tuple(int(rng.integers(0, s)) for s in shard)
            sizes = tuple(
                int(rng.integers(1, s - o + 1)) for s, o in zip(shard, offs)
            )
            ov = box_overlap(Box(tuple(0 for _ in shard), shard), Box(offs, sizes))
            overlaps.append(ov)
            views.append(np.zeros(sizes, np.float32))
        row_nbytes = int(np.prod(shard[1:], dtype=np.int64)) * 4
        plan = plan_row_slab_reads(
            shard,
            overlaps,
            row_nbytes,
            buffer_limit_bytes=int(rng.integers(1, 5)) * row_nbytes,
        )
        if plan is None:
            for view, ov in zip(views, overlaps):
                view[...] = src[ov.src_slices]
        else:
            blob = src.tobytes()
            for read in plan:
                a, b = read.byte_range
                buf = np.frombuffer(blob[a:b], np.float32).reshape(
                    read.buf_shape
                )
                for copy in read.copies:
                    views[copy.overlap_index][copy.dst_rows] = buf[
                        copy.src_slices
                    ]
        for view, ov in zip(views, overlaps):
            np.testing.assert_array_equal(view, src[ov.src_slices])


def test_column_partial_destinations_use_ranged_reads(tmp_path) -> None:
    """A partial destination that slices a saved shard's rows AND
    columns (the per-rank view of an elastic multi-process restore)
    must pay a row-banded ranged read, not the whole shard — the read
    amplification the fan-out path's needed-window math rides on.
    Before the shared planner, any trailing-sliced overlap fell back to
    a whole-shard read."""
    from torchsnapshot_tpu.manifest import ShardedArrayEntry
    from torchsnapshot_tpu.sharded_io_preparer import ShardedArrayIOPreparer
    from torchsnapshot_tpu.serialization import array_size_bytes

    sharding = NamedSharding(_mesh((2,), ("x",)), P(None, "x"))  # 2 col shards
    x = jnp.arange(32 * 24, dtype=jnp.float32).reshape(32, 24)
    xs = jax.device_put(x, sharding)
    snap = ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    entry = snap.get_manifest()["0/m/w"]
    assert isinstance(entry, ShardedArrayEntry)

    # One rank's destination box: rows [8, 16) of columns [0, 6) — a
    # row- and column-partial window of the first 32x12 saved shard.
    saved = entry.shards[0]
    saved_box = Box(tuple(saved.offsets), tuple(saved.sizes))
    dst_box = Box((8, 0), (8, 6))
    ov = box_overlap(saved_box, dst_box)
    view = np.zeros((8, 6), np.float32)
    reqs = ShardedArrayIOPreparer._reqs_for_saved_shard(
        saved, saved_box, [(view, ov)]
    )
    assert reqs and all(r.byte_range is not None for r in reqs)
    fetched = sum(r.byte_range[1] - r.byte_range[0] for r in reqs)
    whole = array_size_bytes(saved.sizes, saved.array.dtype)
    # 8 of 32 rows: a quarter of the shard's bytes, not all of them.
    assert fetched == whole // 4
    # And the ranged read reconstructs the exact window.
    import asyncio

    from torchsnapshot_tpu.scheduler import sync_execute_read_reqs
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

    loop = asyncio.new_event_loop()
    sync_execute_read_reqs(
        reqs, url_to_storage_plugin(str(tmp_path)), 10**7, 0, loop
    )
    loop.close()
    np.testing.assert_array_equal(view, np.asarray(x)[8:16, 0:6])


def test_target_boxes_for_sharding_groups_replicas() -> None:
    sharding = NamedSharding(_mesh((4, 2), ("a", "b")), P(None, "b"))
    groups = target_boxes_for_sharding(sharding, (16, 8))
    assert len(groups) == 2  # 2-way column split, replicated 4x
    assert all(len(devs) == 4 for devs in groups.values())


def test_sharded_read_respects_buffer_limit(tmp_path) -> None:
    """Regression (review finding): a memory budget must split sharded
    reads into ranged row reads rather than admitting whole-shard buffers."""
    from torchsnapshot_tpu.manifest import ShardedArrayEntry
    from torchsnapshot_tpu.sharded_io_preparer import ShardedArrayIOPreparer

    sharding = NamedSharding(_mesh((2, 4), ("a", "b")), P("a"))
    x = jnp.arange(64 * 16, dtype=jnp.float32).reshape(64, 16)
    xs = jax.device_put(x, sharding)
    snap = ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    entry = snap.get_manifest()["0/m/w"]
    assert isinstance(entry, ShardedArrayEntry)

    out = np.zeros((64, 16), np.float32)
    # Each saved shard is 32x16x4B = 2 KiB; a 512B limit must split reads.
    reqs = ShardedArrayIOPreparer.prepare_read(
        entry, out, buffer_size_limit_bytes=512
    )
    assert len(reqs) > len(entry.shards)
    for req in reqs:
        assert req.byte_range is not None
        assert req.byte_range[1] - req.byte_range[0] <= 512
    # And the reads actually reconstruct the array.
    import asyncio

    from torchsnapshot_tpu.scheduler import sync_execute_read_reqs
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

    loop = asyncio.new_event_loop()
    sync_execute_read_reqs(
        reqs, url_to_storage_plugin(str(tmp_path)), 10**6, 0, loop
    )
    loop.close()
    np.testing.assert_array_equal(out, np.asarray(x))


def test_sharded_prepare_read_requires_np_destination(tmp_path) -> None:
    from torchsnapshot_tpu.io_preparer import prepare_read
    from torchsnapshot_tpu.manifest import ShardedArrayEntry

    sharding = NamedSharding(_mesh((8,), ("x",)), P("x"))
    xs = jax.device_put(jnp.ones((16, 4)), sharding)
    snap = ts.Snapshot.take(str(tmp_path), {"m": ts.PyTreeState({"w": xs})})
    entry = snap.get_manifest()["0/m/w"]
    with pytest.raises(ValueError, match="np.ndarray destination"):
        prepare_read(entry, obj_out=None)
