"""Cloud storage plugins, offline-testable parts: the collective-progress
retry strategy, the transient-error taxonomy, URL/root parsing,
dependency gating, and the full incremental take -> restore -> fsck
chain over the ``s3://`` scheme against an in-memory S3 client (the
``gs://`` chain runs against the live fake server in
test_gcs_emulator.py). Live bucket round-trips are env-gated the way the
reference gates them (TORCHSNAPSHOT_ENABLE_*_TEST).
"""

import asyncio
import io
import os
import sys
import types

import pytest

from torchsnapshot_tpu.event_loop import run_in_fresh_event_loop
from torchsnapshot_tpu.storage_plugins.retry import (
    CollectiveProgressRetryStrategy,
    RetriesExhausted,
)


class Transient(Exception):
    pass


def test_retry_succeeds_after_transient_failures() -> None:
    strategy = CollectiveProgressRetryStrategy(progress_window_seconds=30)
    attempts = 0

    async def op():
        nonlocal attempts
        attempts += 1
        if attempts < 3:
            raise Transient()
        return "ok"

    result = run_in_fresh_event_loop(strategy.run(op, (Transient,)))
    assert result == "ok"
    assert attempts == 3


def test_retry_gives_up_when_nobody_progresses() -> None:
    strategy = CollectiveProgressRetryStrategy(progress_window_seconds=0.01)

    async def op():
        raise Transient()

    with pytest.raises(RetriesExhausted):
        run_in_fresh_event_loop(strategy.run(op, (Transient,)))


def test_decorrelated_backoff_schedules_diverge() -> None:
    """The mirror-lockstep bug: N ranks losing the durable tier at the
    same instant must NOT retry on near-identical schedules. Under
    decorrelated jitter, two strategies' backoff sequences draw each
    step's range from their own previous draw, so the schedules diverge
    after the first sleep and stay diverged — unlike the old
    exponential-with-bounded-jitter scheme, whose attempt-k draws all
    landed in the same narrow [2^k/2, 2^k] band."""
    import random

    from torchsnapshot_tpu.storage_plugins.retry import (
        _BACKOFF_BASE_SECONDS,
        _BACKOFF_MAX_SECONDS,
        decorrelated_backoff,
    )

    def schedule(seed: int, n: int = 8):
        rng = random.Random(seed)
        prev = _BACKOFF_BASE_SECONDS
        out = []
        for _ in range(n):
            prev = decorrelated_backoff(prev, rng=rng)
            out.append(prev)
        return out

    a, b = schedule(1), (schedule(2))
    assert a != b
    # Diverged means diverged: no step of the two schedules should
    # agree to within the old scheme's band width fraction.
    assert sum(1 for x, y in zip(a, b) if abs(x - y) > 1e-9) >= 6
    # Bounds hold: every draw within [base, cap].
    for s in a + b:
        assert _BACKOFF_BASE_SECONDS <= s <= _BACKOFF_MAX_SECONDS
    # Same seed -> same schedule (the seam tests rely on).
    assert schedule(7) == schedule(7)


def test_retry_run_uses_decorrelated_backoff_rng_seam() -> None:
    """Two strategies retrying the same failing op under different RNG
    seeds must sleep different amounts — pinned via the per-instance
    rng seam and the recorded backoff totals."""
    import random

    totals = []
    for seed in (11, 12):
        strategy = CollectiveProgressRetryStrategy(
            progress_window_seconds=30, rng=random.Random(seed)
        )
        attempts = 0

        async def op():
            nonlocal attempts
            attempts += 1
            if attempts < 3:
                raise Transient()
            return "ok"

        async def main():
            # Patch out the real sleep: the schedules, not the wall
            # clock, are under test.
            orig = asyncio.sleep

            async def fake_sleep(_s):
                await orig(0)

            asyncio.sleep, restore = fake_sleep, orig
            try:
                return await strategy.run(op, (Transient,))
            finally:
                asyncio.sleep = restore

        assert run_in_fresh_event_loop(main()) == "ok"
        totals.append(strategy.backoff_s_total)
    assert totals[0] != totals[1]


def test_retry_nonretriable_raises_immediately() -> None:
    strategy = CollectiveProgressRetryStrategy(progress_window_seconds=30)

    async def op():
        raise ValueError("hard failure")

    with pytest.raises(ValueError):
        run_in_fresh_event_loop(strategy.run(op, (Transient,)))


def test_concurrent_progress_extends_straggler_deadline() -> None:
    """A straggler keeps retrying while a sibling makes progress — the
    collective-deadline semantics (reference gcs.py:214-270)."""
    strategy = CollectiveProgressRetryStrategy(progress_window_seconds=0.6)
    straggler_attempts = 0

    async def straggler():
        nonlocal straggler_attempts
        straggler_attempts += 1
        if straggler_attempts < 3:
            raise Transient()
        return "eventually"

    async def sibling():
        # Refresh until cancelled: decorrelated backoff jitter (PR 10)
        # makes the straggler's two sleeps unbounded-ish (each uniform up
        # to 3x the previous), so a fixed refresh count can lapse the
        # window mid-backoff and flake the test. The straggler's own
        # window (0.6 s vs ~1 s+ backoffs) still carries the assertion.
        while True:
            await asyncio.sleep(0.1)
            strategy.record_progress()

    async def main():
        sib = asyncio.ensure_future(sibling())
        try:
            # Backoff between straggler attempts is ~1s+, far beyond the
            # 0.6 s window: only the sibling's refreshes keep it alive.
            return await strategy.run(straggler, (Transient,))
        finally:
            sib.cancel()

    assert run_in_fresh_event_loop(main()) == "eventually"
    assert straggler_attempts == 3


def test_retry_window_starts_at_first_failure_not_construction() -> None:
    """A long quiet period between plugin construction and the first storage
    op must not consume the retry budget: the first transient failure still
    gets retried. Discriminating setup: the sleep exceeds the whole window,
    so a construction-time deadline would already have lapsed and the old
    code raises RetriesExhausted on the very first failure."""
    import time as _time

    strategy = CollectiveProgressRetryStrategy(progress_window_seconds=2.0)
    _time.sleep(2.1)  # quiet period longer than the window
    attempts = 0

    async def op():
        nonlocal attempts
        attempts += 1
        if attempts < 2:
            raise Transient()
        return "ok"

    assert run_in_fresh_event_loop(strategy.run(op, (Transient,))) == "ok"
    assert attempts == 2


def test_s3_transient_taxonomy() -> None:
    pytest.importorskip("botocore")
    import botocore.exceptions as be

    from torchsnapshot_tpu.storage_plugins.s3 import _is_transient_s3

    def client_error(code=None, status=None):
        resp = {"Error": {}, "ResponseMetadata": {}}
        if code is not None:
            resp["Error"]["Code"] = code
        if status is not None:
            resp["ResponseMetadata"]["HTTPStatusCode"] = status
        return be.ClientError(resp, "PutObject")

    assert _is_transient_s3(client_error(code="SlowDown", status=503))
    assert _is_transient_s3(client_error(code="Throttling"))
    assert _is_transient_s3(client_error(status=500))
    assert _is_transient_s3(client_error(status=429))
    assert not _is_transient_s3(client_error(code="AccessDenied", status=403))
    assert not _is_transient_s3(client_error(code="NoSuchKey", status=404))
    assert _is_transient_s3(ConnectionResetError())
    assert not _is_transient_s3(ValueError())


def test_gcs_transient_taxonomy() -> None:
    pytest.importorskip("google.resumable_media")
    import requests
    from google.resumable_media import common

    from torchsnapshot_tpu.storage_plugins.gcs import _is_transient

    class FakeResp:
        def __init__(self, code):
            self.status_code = code

    for code in (408, 429, 500, 503):
        assert _is_transient(common.InvalidResponse(FakeResp(code)), common)
    for code in (400, 403, 404):
        assert not _is_transient(common.InvalidResponse(FakeResp(code)), common)
    assert _is_transient(requests.ConnectionError(), common)
    assert _is_transient(requests.Timeout(), common)
    assert not _is_transient(ValueError(), common)


def test_s3_plugin_gates_missing_dependency() -> None:
    try:
        import aiobotocore  # noqa: F401

        pytest.skip("aiobotocore installed; gating not exercised")
    except ImportError:
        pass
    from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin

    with pytest.raises(RuntimeError, match="aiobotocore"):
        S3StoragePlugin(root="bucket/prefix")


def test_gcs_root_parsing_rejects_empty_bucket() -> None:
    pytest.importorskip("google.resumable_media")
    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin

    # Bucket validation happens before the credentials lookup, so this must
    # be the ValueError itself, not some auth failure.
    with pytest.raises(ValueError, match="Invalid GCS root"):
        GCSStoragePlugin(root="")


def test_registry_dispatches_schemes(tmp_path) -> None:
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    assert isinstance(url_to_storage_plugin(str(tmp_path)), FSStoragePlugin)
    assert isinstance(
        url_to_storage_plugin(f"fs://{tmp_path}"), FSStoragePlugin
    )
    assert isinstance(url_to_storage_plugin("memory://x"), MemoryStoragePlugin)
    with pytest.raises(RuntimeError, match="Unsupported storage scheme"):
        url_to_storage_plugin("bogus://whatever")


@pytest.mark.skipif(
    "TORCHSNAPSHOT_TPU_ENABLE_GCS_TEST" not in os.environ,
    reason="live GCS test not enabled",
)
def test_gcs_live_roundtrip() -> None:
    from torchsnapshot_tpu.io_types import ReadIO, WriteIO
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

    plugin = url_to_storage_plugin(os.environ["TORCHSNAPSHOT_TPU_GCS_URL"])

    async def go():
        data = os.urandom(1 << 20)
        await plugin.write(WriteIO(path="smoke/blob", buf=data))
        io_ = ReadIO(path="smoke/blob", byte_range=(100, 1100))
        await plugin.read(io_)
        assert bytes(io_.buf) == data[100:1100]
        await plugin.delete("smoke/blob")
        await plugin.close()

    run_in_fresh_event_loop(go())


def test_s3_missing_key_normalized_to_file_not_found() -> None:
    """Missing blobs surface as FileNotFoundError (the FS plugin contract)
    so callers — e.g. checksum-table probing — can distinguish absent from
    unreadable, and the retry layer never spins on a definitive 404."""
    pytest.importorskip("botocore")
    import botocore.exceptions as be

    from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin, _is_transient_s3
    from torchsnapshot_tpu.io_types import ReadIO

    assert not _is_transient_s3(FileNotFoundError("x"))

    class FakeClient:
        async def get_object(self, Bucket, Key, **kw):
            raise be.ClientError(
                {"Error": {"Code": "NoSuchKey"}, "ResponseMetadata": {}},
                "GetObject",
            )

    plugin = S3StoragePlugin.__new__(S3StoragePlugin)
    plugin.bucket = "b"
    plugin.prefix = "p"

    async def fake_get_client():
        return FakeClient()

    plugin._get_client = fake_get_client
    from torchsnapshot_tpu.storage_plugins.retry import (
        CollectiveProgressRetryStrategy,
    )

    plugin._retry = CollectiveProgressRetryStrategy(progress_window_seconds=1.0)

    async def go():
        with pytest.raises(FileNotFoundError):
            await plugin.read(ReadIO(path="missing"))

    run_in_fresh_event_loop(go())


def test_gcs_missing_blob_normalized_to_file_not_found() -> None:
    pytest.importorskip("google.resumable_media")
    from google.resumable_media import common

    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin, _is_transient

    assert not _is_transient(FileNotFoundError("x"), common)

    class FakeResp:
        status_code = 404

    class FakeDownload:
        def __init__(self, *a, **kw):
            self.finished = False

        def consume_next_chunk(self, session):
            raise common.InvalidResponse(FakeResp(), "not found")

    plugin = GCSStoragePlugin.__new__(GCSStoragePlugin)
    plugin._common = common
    plugin._chunked_download_cls = FakeDownload
    plugin._session = None
    plugin._base_url = "https://storage.example"
    plugin.bucket = "b"
    plugin.prefix = "p"

    with pytest.raises(FileNotFoundError):
        plugin._download_sync("missing", None)


def test_s3_put_body_streams_without_copy() -> None:
    """put_object receives a seekable file-like body whose drained content
    equals the staged buffer — the upload path botocore exercises (length
    probe via seek/tell, chunked reads, retry rewind) must round-trip.
    Needs no botocore: the fake client IS the consumer."""
    import io

    import numpy as np

    from torchsnapshot_tpu.io_types import WriteIO
    from torchsnapshot_tpu.storage_plugins.retry import (
        CollectiveProgressRetryStrategy,
    )
    from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin

    payload = np.arange(100000, dtype=np.float32)
    captured = {}

    class FakeClient:
        async def put_object(self, Bucket, Key, Body):
            assert Body.seekable() and Body.readable()
            Body.seek(0, io.SEEK_END)
            length = Body.tell()
            Body.seek(0)
            chunks = []
            while True:
                c = Body.read(64 * 1024)
                if not len(c):
                    break
                chunks.append(bytes(c))
            captured["key"] = Key
            captured["data"] = b"".join(chunks)
            assert len(captured["data"]) == length

    plugin = S3StoragePlugin.__new__(S3StoragePlugin)
    plugin.bucket = "b"
    plugin.prefix = "p"
    plugin._retry = CollectiveProgressRetryStrategy(progress_window_seconds=1.0)

    async def fake_get_client():
        return FakeClient()

    plugin._get_client = fake_get_client

    async def go():
        await plugin.write(WriteIO(path="blob", buf=memoryview(payload)))

    run_in_fresh_event_loop(go())
    assert captured["key"] == "p/blob"
    assert captured["data"] == payload.tobytes()


def _ensure_botocore_exceptions():
    """The S3 plugin's error taxonomy imports ``botocore.exceptions`` at
    call time. On images without botocore (TPU images ship GCS deps only),
    install a minimal stub with the classes the plugin touches so the
    plugin's own code — key normalization, Range math, retry routing,
    NoSuchKey normalization — can run against a fake client."""
    try:
        import botocore.exceptions  # noqa: F401

        return
    except ImportError:
        pass

    exceptions = types.ModuleType("botocore.exceptions")

    class ClientError(Exception):
        def __init__(self, response, operation_name):
            super().__init__(response.get("Error", {}).get("Code", "?"))
            self.response = response
            self.operation_name = operation_name

    for name in (
        "EndpointConnectionError",
        "ConnectionError",
        "HTTPClientError",
        "ReadTimeoutError",
        "ConnectTimeoutError",
    ):
        setattr(exceptions, name, type(name, (Exception,), {}))
    exceptions.ClientError = ClientError
    botocore = types.ModuleType("botocore")
    botocore.exceptions = exceptions
    sys.modules.setdefault("botocore", botocore)
    sys.modules["botocore.exceptions"] = exceptions


class _FakeS3Body:
    """get_object response body: async context manager + async read()."""

    def __init__(self, data: bytes) -> None:
        self._data = data

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False

    async def read(self) -> bytes:
        return self._data


class FakeS3Client:
    """In-memory S3: the exact call surface S3StoragePlugin exercises
    (put_object with a seekable streaming Body, get_object with inclusive
    Range headers and NoSuchKey errors, delete_object)."""

    def __init__(self, store: dict) -> None:
        self.store = store

    async def put_object(self, Bucket, Key, Body):
        Body.seek(0, io.SEEK_END)
        length = Body.tell()
        Body.seek(0)
        data = bytes(Body.read())
        assert len(data) == length
        self.store[(Bucket, Key)] = data

    async def get_object(self, Bucket, Key, Range=None):
        import botocore.exceptions as be

        if (Bucket, Key) not in self.store:
            raise be.ClientError(
                {"Error": {"Code": "NoSuchKey"}, "ResponseMetadata": {}},
                "GetObject",
            )
        data = self.store[(Bucket, Key)]
        if Range is not None:
            spec = Range.removeprefix("bytes=")
            start_s, _, end_s = spec.partition("-")
            data = data[int(start_s) : int(end_s) + 1]  # inclusive end
        return {"Body": _FakeS3Body(data)}

    async def delete_object(self, Bucket, Key):
        self.store.pop((Bucket, Key), None)


@pytest.fixture()
def fake_s3(monkeypatch):
    """Route ``s3://`` through the real S3StoragePlugin backed by one
    shared in-memory client (every plugin instance a take/restore/fsck
    builds must see the same objects)."""
    _ensure_botocore_exceptions()
    from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin

    store: dict = {}

    def fake_init(self, root: str) -> None:
        bucket, _, prefix = root.partition("/")
        if not bucket:
            raise ValueError(f"Invalid S3 root {root!r}")
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self._client_ctx = None
        self._client = None
        self._client_lock = asyncio.Lock()
        self._retry = CollectiveProgressRetryStrategy()

    async def fake_get_client(self):
        return FakeS3Client(store)

    monkeypatch.setattr(S3StoragePlugin, "__init__", fake_init)
    monkeypatch.setattr(S3StoragePlugin, "_get_client", fake_get_client)
    return store


def test_incremental_refs_resolve_over_s3(fake_s3) -> None:
    """Incremental ``../step_X`` refs over the s3:// scheme end to end:
    take -> incremental take -> restore -> deep fsck -> read_object, with
    checksum inheritance, through the plugin's own key handling (object
    keys are flat — ``..`` must collapse lexically via
    normalize_object_key, never reach the store)."""
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.fsck import verify_snapshot

    w = jnp.arange(128, dtype=jnp.float32)
    b = jnp.ones((16,), jnp.float32)
    base = "s3://bkt/run/step_0"
    incr = "s3://bkt/run/step_1"
    ts.Snapshot.take(
        base, {"m": ts.PyTreeState({"w": w, "b": b})}, record_digests=True
    )
    ts.Snapshot.take(
        incr,
        {"m": ts.PyTreeState({"w": w, "b": b * 2})},
        incremental_base=base,
    )

    manifest = ts.Snapshot(incr).get_manifest()
    assert manifest["0/m/w"].location == "../step_0/0/m/w"
    # The ref collapsed lexically into a flat key: no stored key may
    # contain a parent component.
    assert all(".." not in k for _, k in fake_s3)
    assert any(k.startswith("run/step_0/") for _, k in fake_s3)

    dest = {
        "m": ts.PyTreeState({"w": jnp.zeros_like(w), "b": jnp.zeros_like(b)})
    }
    ts.Snapshot(incr).restore(dest)
    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["w"]), np.asarray(w)
    )
    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["b"]), np.asarray(b * 2)
    )

    # Deep fsck walks the chain (checksum inheritance included).
    report = verify_snapshot(incr, deep=True)
    assert report.ok and report.crcs_verified == report.blobs_checked

    # read_object resolves through the ref as well.
    out = ts.Snapshot(incr).read_object("0/m/w")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(w))


@pytest.mark.s3_integration_test
@pytest.mark.skipif(
    "TORCHSNAPSHOT_TPU_ENABLE_AWS_TEST" not in os.environ
    or "TORCHSNAPSHOT_TPU_S3_URL" not in os.environ,
    reason="live/emulated S3 test not enabled (set both "
    "TORCHSNAPSHOT_TPU_ENABLE_AWS_TEST and TORCHSNAPSHOT_TPU_S3_URL; a "
    "default bucket name would be attacker-squattable on real AWS)",
)
def test_s3_live_roundtrip() -> None:
    """Write/ranged-read/delete against real S3 or a MinIO endpoint
    (TORCHSNAPSHOT_TPU_S3_ENDPOINT — the CI service-container lane)."""
    pytest.importorskip("botocore")
    from torchsnapshot_tpu.io_types import ReadIO, WriteIO
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

    url = os.environ["TORCHSNAPSHOT_TPU_S3_URL"]
    plugin = url_to_storage_plugin(url)

    async def go():
        data = os.urandom(1 << 20)
        await plugin.write(WriteIO(path="smoke/blob", buf=data))
        io_ = ReadIO(path="smoke/blob", byte_range=(100, 1100))
        await plugin.read(io_)
        assert bytes(io_.buf) == data[100:1100]
        await plugin.delete("smoke/blob")
        await plugin.close()

    run_in_fresh_event_loop(go())
