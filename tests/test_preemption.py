"""Preemption-aware checkpointing: signal capture, whole-world step
agreement (sound with drifted rank steps), rendezvous timeout, and the
save-on-evict -> resume flow. No reference counterpart (it relies on
torchelastic restarts); the TPU analog is orbax's preemption sync."""

import os
import signal
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.dist_store import InProcessStore, ProcessGroup
from torchsnapshot_tpu.preemption import PreemptionSaver


def test_single_process_signal_triggers_next_should_save():
    saver = PreemptionSaver(signals=(signal.SIGUSR1,))
    try:
        assert not saver.should_save(0)
        os.kill(os.getpid(), signal.SIGUSR1)
        assert saver.preempted
        assert saver.should_save(1)
        assert not saver.should_save(2)  # one save, not a save loop
    finally:
        saver.uninstall()


def test_request_save_without_signals():
    saver = PreemptionSaver(signals=())
    assert not saver.should_save(0)
    saver.request_save()
    assert saver.should_save(1)


def test_chained_handler_still_runs():
    hits = []
    prev = signal.signal(signal.SIGUSR2, lambda s, f: hits.append(s))
    try:
        saver = PreemptionSaver(signals=(signal.SIGUSR2,))
        try:
            os.kill(os.getpid(), signal.SIGUSR2)
            assert saver.preempted
            assert hits == [signal.SIGUSR2]
        finally:
            saver.uninstall()
    finally:
        signal.signal(signal.SIGUSR2, prev)


def test_agreement_with_drifted_ranks_in_process():
    """Two ranks drifted by several steps agree on one target step: the
    rendezvous takes max(published)+1, so the laggard catches up instead
    of the leader saving in the past."""
    store = InProcessStore()
    s0 = PreemptionSaver(ProcessGroup(store, 0, 2), signals=())
    s1 = PreemptionSaver(ProcessGroup(store, 1, 2), signals=())

    # Rank 1 is signaled at step 7 while rank 0's host loop is at step 4
    # (async dispatch drift). Pre-seed rank 0's rendezvous entry so the
    # blocking agreement completes instantly in one process; rank 0 is
    # also signaled (the single-rank-signaled propagation path runs in
    # the 2-process e2e test below, via the background poller).
    s1.request_save()
    s0.request_save()
    # Seeding a fake rank entry means also joining the counter the
    # rendezvous waits on (one RPC per tick instead of per-rank polls).
    store.set("__preemption//step/0", b"4")  # default session "" in the key
    store.add("__preemption//step_count", 1)
    assert not s1.should_save(7)  # agreement runs; target = max(4,7)+1 = 8
    assert s1._target_step == 8
    # Rank 0's own rendezvous (at step 4, matching the seed) agrees.
    saves_0 = [step for step in range(4, 10) if s0.should_save(step)]
    assert saves_0 == [8]
    assert s0._target_step == 8
    # Rank 1 reaches the same target.
    saves_1 = [step for step in range(8, 10) if s1.should_save(step)]
    assert saves_1 == [8]


def test_done_peer_abandons_rendezvous_fast():
    """A peer that finished training (close()) makes the rendezvous
    abandon immediately instead of waiting out the full timeout."""
    import time

    store = InProcessStore()
    s0 = PreemptionSaver(ProcessGroup(store, 0, 2), signals=())
    s1 = PreemptionSaver(
        ProcessGroup(store, 1, 2), signals=(), rendezvous_timeout=30.0
    )
    s0.close()  # rank 0's loop ended before any notice
    s1.request_save()
    t0 = time.monotonic()
    assert not s1.should_save(5)
    assert time.monotonic() - t0 < 5.0  # done marker, not the 30s timeout
    assert s1._gave_up


def test_rendezvous_timeout_gives_up_loudly():
    """A missing peer must abort the coordinated save (a lone save would
    deadlock inside the distributed take), permanently."""
    store = InProcessStore()
    saver = PreemptionSaver(
        ProcessGroup(store, 0, 2), signals=(), rendezvous_timeout=0.3
    )
    saver.request_save()
    assert not saver.should_save(3)  # peer never publishes
    assert saver._gave_up
    assert not saver.should_save(4)


def test_timeout_publishes_abandoned_marker_peers_give_up():
    """A timed-out rank leaves its step key behind; a late peer must NOT
    complete the rendezvous against it and save alone — the abandoned
    marker makes it give up symmetrically."""
    store = InProcessStore()
    s0 = PreemptionSaver(
        ProcessGroup(store, 0, 2), signals=(), rendezvous_timeout=0.2
    )
    s0.request_save()
    assert not s0.should_save(3)  # times out; publishes abandoned + step/0
    # Rank 1 arrives late: flag set, both step keys would be visible —
    # but the abandoned marker forces it to give up symmetrically.
    s1 = PreemptionSaver(ProcessGroup(store, 1, 2), signals=())
    s1.request_save()
    assert not s1.should_save(5)
    assert s1._gave_up


def test_pending_save_when_target_past_loop_end():
    """Agreed target beyond the final step: every rank exits the loop
    unsaved and pending_save() fires once on each."""
    store = InProcessStore()
    s0 = PreemptionSaver(ProcessGroup(store, 0, 2), signals=())
    s1 = PreemptionSaver(ProcessGroup(store, 1, 2), signals=())
    last_step = 9
    s1.request_save()
    s0.request_save()
    store.set("__preemption//step/0", str(last_step).encode())
    store.add("__preemption//step_count", 1)
    assert not s1.should_save(last_step)  # target = 10 > last step
    assert s1._target_step == last_step + 1
    assert not s0.should_save(last_step)  # same agreement on rank 0
    assert s0._target_step == last_step + 1
    # Loops end; both ranks save the final step via pending_save.
    assert s0.pending_save() and s1.pending_save()
    assert not s0.pending_save()  # one-shot


def test_session_namespacing_isolates_stale_state():
    """A fresh saver lifetime over the same store must not observe a
    previous session's flag/step keys."""
    store = InProcessStore()
    # Leftovers from a previous incarnation ("run1").
    store.set("__preemption/run1/flag", b"1")
    store.set("__preemption/run1/step/0", b"7")
    store.set("__preemption/run1/step/1", b"7")
    store.add("__preemption/run1/step_count", 2)

    fresh = PreemptionSaver(
        ProcessGroup(store, 0, 2), signals=(), session="run2",
        rendezvous_timeout=0.2,
    )
    assert not fresh.should_save(0)  # run1's flag is invisible to run2
    assert fresh._target_step is None and not fresh._gave_up

    # The same keys ARE visible to a saver of the matching session.
    stale = PreemptionSaver(
        ProcessGroup(store, 0, 2), signals=(), session="run1"
    )
    stale.request_save()
    assert not stale.should_save(7)  # completes run1's rendezvous: target 8
    assert stale._target_step == 8


def _preempt_e2e_worker(pg, root: str, evict_rank: int = 1):
    """One rank is 'evicted' mid-loop; every rank must save the SAME
    step through the manager and the checkpoint must resume correctly.
    The exact agreed step depends on when the other ranks' polls observe
    the flag — sameness is the invariant, not the number."""
    from torchsnapshot_tpu.pg_wrapper import PGWrapper
    from torchsnapshot_tpu.test_utils import drive_preemption_loop

    PGWrapper(pg).barrier()
    mgr = ts.CheckpointManager(root, pg=pg)
    saver = PreemptionSaver(pg, signals=(), poll_interval=0.1)

    def save(step: int) -> None:
        # Step ``s`` has applied s+1 increments to the zero-initialized w.
        state = {"w": jnp.full((8,), float(step + 1)), "step": step}
        mgr.save(
            step,
            {"train": ts.PyTreeState(state), "prog": ts.StateDict(r=pg.rank)},
        )

    saved_at = drive_preemption_loop(pg, saver, save, evict_rank=evict_rank)
    assert saved_at is not None, "world never agreed on a save step"

    dest = {
        "train": ts.PyTreeState({"w": jnp.zeros((8,)), "step": 0}),
        "prog": ts.StateDict(r=-1),
    }
    assert mgr.restore_latest(dest) == saved_at
    np.testing.assert_array_equal(
        np.asarray(dest["train"].tree["w"]), np.full((8,), float(saved_at + 1))
    )
    assert dest["prog"]["r"] == pg.rank
    return saved_at


def test_preemption_save_and_resume_two_ranks(tmp_path) -> None:
    from torchsnapshot_tpu.test_utils import run_multiprocess

    saved = run_multiprocess(
        _preempt_e2e_worker, nproc=2, args=(str(tmp_path / "preempt"),)
    )
    assert saved[0] == saved[1], saved  # the invariant: one agreed step
    assert saved[0] is not None and saved[0] >= 3, saved


def test_preemption_four_ranks_one_agreed_step(tmp_path) -> None:
    """Pod-shaped world: 4 ranks, notice on rank 2 only — every rank
    saves the same step and the checkpoint resumes on all of them."""
    from torchsnapshot_tpu.test_utils import run_multiprocess

    saved = run_multiprocess(
        _preempt_e2e_worker,
        nproc=4,
        args=(str(tmp_path / "preempt4"),),
        kwargs={"evict_rank": 2},
        timeout=300.0,
    )
    assert len(set(saved)) == 1 and saved[0] is not None, saved


@pytest.mark.parametrize("seed", range(4))
def test_agreement_timing_fuzz(seed) -> None:
    """Randomized timing: eviction at a random step on a random rank,
    random poll interval, asymmetric step pacing across two thread-ranks.
    The agreement property must hold regardless: both ranks save the SAME
    step. A 12-case sweep of this generator passed during round 4."""
    import numpy as np

    rng = np.random.default_rng(8000 + seed)
    store = InProcessStore()
    evict_rank = int(rng.integers(0, 2))
    evict_step = int(rng.integers(0, 60))
    poll = float(rng.choice([0.01, 0.03, 0.05]))
    paces = [float(rng.choice([0.001, 0.004, 0.01])) for _ in range(2)]
    saved = {}

    def loop(rank: int) -> None:
        pg = ProcessGroup(store=store, rank=rank, world_size=2)
        saver = PreemptionSaver(
            pg,
            signals=(),
            poll_interval=poll,
            rendezvous_timeout=30.0,
            peer_grace=0.1,
            session=f"fuzz{seed}",
        )
        for step in range(5000):
            if rank == evict_rank and step == evict_step:
                saver.request_save()
            if saver.should_save(step):
                saved[rank] = step
                return
            time.sleep(paces[rank])
        saved[rank] = None

    threads = [threading.Thread(target=loop, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert saved.get(0) is not None, saved
    assert saved.get(0) == saved.get(1), saved
