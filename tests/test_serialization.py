"""Per-dtype zero-copy round-trips.

Structural model: reference tests/test_serialization.py:32-101.
"""

import numpy as np
import pytest

from torchsnapshot_tpu.serialization import (
    STRING_TO_DTYPE,
    Serializer,
    array_as_memoryview,
    array_from_memoryview,
    array_size_bytes,
    dtype_to_string,
    obj_type_name,
    pickle_load_from_bytes,
    pickle_save_as_bytes,
    string_to_dtype,
)


def _rand_array(dtype: np.dtype, shape=(16, 9)) -> np.ndarray:
    rng = np.random.default_rng(0)
    if dtype.kind in ("i", "u") or dtype.name in ("int4", "uint4"):
        return rng.integers(0, 4, size=shape).astype(dtype)
    if dtype.kind == "b":
        return rng.integers(0, 2, size=shape).astype(bool)
    if dtype.kind == "c":
        return (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
            dtype
        )
    return rng.standard_normal(shape).astype(dtype)


@pytest.mark.parametrize("dtype_str", sorted(STRING_TO_DTYPE))
def test_roundtrip_every_dtype(dtype_str: str) -> None:
    dtype = string_to_dtype(dtype_str)
    arr = _rand_array(dtype)
    mv = array_as_memoryview(arr)
    assert mv.nbytes == array_size_bytes(arr.shape, dtype_str)
    restored = array_from_memoryview(mv, dtype_str, arr.shape)
    assert restored.dtype == arr.dtype
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(arr))
    # Zero-copy both ways.
    assert np.shares_memory(arr, restored)


@pytest.mark.parametrize("dtype_str", ["float32", "bfloat16"])
def test_roundtrip_0d(dtype_str: str) -> None:
    arr = np.array(1.5, dtype=string_to_dtype(dtype_str))
    mv = array_as_memoryview(arr)
    restored = array_from_memoryview(mv, dtype_str, ())
    assert restored.shape == ()
    assert restored == arr


def test_dtype_string_mapping_is_bijective() -> None:
    for s, dt in STRING_TO_DTYPE.items():
        assert dtype_to_string(dt) == s
        assert string_to_dtype(s) == dt


def test_unsupported_dtype_raises() -> None:
    with pytest.raises(ValueError):
        dtype_to_string(np.dtype([("a", np.int32)]))


def test_non_contiguous_rejected() -> None:
    arr = np.arange(16, dtype=np.float32).reshape(4, 4)[:, ::2]
    with pytest.raises(ValueError):
        array_as_memoryview(arr)


def test_wrong_buffer_size_rejected() -> None:
    arr = np.zeros(4, dtype=np.float32)
    with pytest.raises(ValueError):
        array_from_memoryview(array_as_memoryview(arr), "float32", (5,))


def test_pickle_roundtrip() -> None:
    obj = {"a": [1, 2, (3, "x")], "b": {4, 5}}
    assert pickle_load_from_bytes(pickle_save_as_bytes(obj)) == obj


def test_serializer_enum_values() -> None:
    assert Serializer.BUFFER_PROTOCOL.value == "buffer_protocol"
    assert Serializer.PICKLE.value == "pickle"


def test_obj_type_name() -> None:
    assert obj_type_name({}) == "dict"
    assert obj_type_name(np.zeros(1)) == "numpy.ndarray"


def test_jax_array_to_numpy_roundtrip() -> None:
    import jax.numpy as jnp

    x = jnp.arange(12, dtype=jnp.bfloat16).reshape(3, 4)
    host = np.asarray(x)
    mv = array_as_memoryview(np.ascontiguousarray(host))
    restored = array_from_memoryview(mv, "bfloat16", (3, 4))
    np.testing.assert_array_equal(
        np.asarray(restored, dtype=np.float32), np.asarray(host, dtype=np.float32)
    )


def test_zero_size_array_roundtrip() -> None:
    """Arrays with a zero dimension serialize to empty blobs and restore
    (latent crash: memoryview cannot cast views with zeros in shape)."""
    from torchsnapshot_tpu.serialization import (
        array_as_memoryview,
        array_from_memoryview,
        try_writable_byte_view,
    )

    src = np.ones((0, 3), dtype=np.float32)
    mv = array_as_memoryview(src)
    assert mv.nbytes == 0
    back = array_from_memoryview(bytes(mv), "float32", (0, 3))
    assert back.shape == (0, 3)
    assert try_writable_byte_view(np.empty((0, 3), np.float32)) is None


def test_zero_size_array_snapshot_roundtrip(tmp_path) -> None:
    import torchsnapshot_tpu as ts

    state = ts.StateDict(empty=np.ones((0, 3), np.float32), full=np.arange(4.0))
    ts.Snapshot.take(str(tmp_path), {"s": state})
    dest = ts.StateDict(
        empty=np.zeros((0, 3), np.float32), full=np.zeros(4)
    )
    ts.Snapshot(str(tmp_path)).restore({"s": dest})
    assert dest["empty"].shape == (0, 3)
    np.testing.assert_array_equal(dest["full"], np.arange(4.0))
