"""Regression: the protocol-overhead benchmark's byte counter must count
writes routed through the fused write+CRC path.

Round 3's driver record showed ``per_rank_mib_written: [0.0]`` at every
rank count because ``CountingFSStoragePlugin`` hooked only ``write()``
while the scheduler routes data writes through ``write_with_checksum()``
whenever the plugin provides it (scheduler.py fused path). The benchmark
now hooks both; this pins that.
"""

import importlib
import os
import sys

import pytest

from torchsnapshot_tpu import _native

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

protocol_overhead = importlib.import_module(
    "benchmarks.replicated_save.protocol_overhead"
)


def test_counter_nonzero_single_rank():
    row = protocol_overhead.run(nproc=1, gb=1 / 32, tiny_leaves=4)
    # One 32 MiB block; the counter must see every payload byte no matter
    # which write path (plain or fused write+CRC) the scheduler picked.
    assert row["per_rank_mib_written"] == [32.0]


@pytest.mark.skipif(
    _native.lib() is None, reason="native runtime unavailable on this host"
)
def test_fused_write_path_is_active_here():
    # The regression only has teeth if this host actually routes writes
    # through the fused path — assert the precondition explicitly so a
    # native-lib build break can't silently turn the test above into a
    # plain-path-only check.
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    assert FSStoragePlugin(root="/tmp")._native
