"""Slab batching: write coalescing with manifest rewriting, spanning reads,
and end-to-end round-trips with the knob enabled.

Structural model: reference tests/test_batcher.py — plus the replicated ×
batching distributed case, which exercises the consolidation rule that the
batch-rewritten entry (the one actually written) wins across ranks.
"""

import os
import tempfile

import jax.numpy as jnp
import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.batcher import batch_read_requests, batch_write_requests
from torchsnapshot_tpu.io_preparer import prepare_read, prepare_write
from torchsnapshot_tpu.knobs import (
    enable_batching,
    override_slab_size_threshold_bytes,
)
from torchsnapshot_tpu.manifest import ArrayEntry
from torchsnapshot_tpu.test_utils import multiprocess_test


def _prepare(arrs):
    entries, reqs = [], []
    for i, a in enumerate(arrs):
        entry, wr = prepare_write(a, f"t/{i}", rank=0)
        entries.append(entry)
        reqs.extend(wr)
    return entries, reqs


def test_write_batching_rewrites_entries() -> None:
    arrs = [np.arange(16, dtype=np.float32) * i for i in range(4)]  # 64 B each
    entries, reqs = _prepare(arrs)
    with override_slab_size_threshold_bytes(1024):
        entries, batched = batch_write_requests(entries, reqs)
    assert len(batched) == 1
    slab_path = batched[0].path
    assert slab_path.startswith("batched/")
    offsets = []
    for entry in entries:
        assert isinstance(entry, ArrayEntry)
        assert entry.location == slab_path
        assert entry.byte_range is not None
        offsets.append(tuple(entry.byte_range))
    # Disjoint, contiguous, in plan order.
    assert offsets == [(0, 64), (64, 128), (128, 192), (192, 256)]


def test_write_batching_respects_threshold() -> None:
    arrs = [np.zeros(16, dtype=np.float32) for _ in range(4)]  # 64 B each
    entries, reqs = _prepare(arrs)
    with override_slab_size_threshold_bytes(128):
        entries, batched = batch_write_requests(entries, reqs)
    # 64+64 fits per slab; 4 members -> 2 slabs.
    assert len(batched) == 2
    assert len({r.path for r in batched}) == 2


def test_large_writes_left_alone() -> None:
    big = np.zeros(1024, dtype=np.float32)  # 4 KiB > threshold
    small = np.zeros(4, dtype=np.float32)
    entries, reqs = _prepare([big, small])
    with override_slab_size_threshold_bytes(256):
        entries, batched = batch_write_requests(entries, reqs)
    # Nothing to coalesce (one big, one small) -> untouched.
    assert {r.path for r in batched} == {"0/t/0", "0/t/1"}
    assert entries[0].location == "0/t/0"


def test_slab_roundtrip_through_storage(tmp_path) -> None:
    """Stage the slab, write it via the FS plugin, read members back via
    batched spanning reads."""
    from torchsnapshot_tpu.event_loop import run_in_fresh_event_loop
    from torchsnapshot_tpu.io_types import ReadIO, WriteIO
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    rng = np.random.default_rng(0)
    arrs = [rng.standard_normal(8).astype(np.float32) for _ in range(3)]
    entries, reqs = _prepare(arrs)
    with override_slab_size_threshold_bytes(4096):
        entries, batched = batch_write_requests(entries, reqs)
    assert len(batched) == 1

    async def go():
        plugin = FSStoragePlugin(root=str(tmp_path))
        buf = await batched[0].buffer_stager.stage_buffer()
        await plugin.write(WriteIO(path=batched[0].path, buf=buf))

        outs = [np.zeros(8, dtype=np.float32) for _ in arrs]
        read_reqs = []
        for entry, out in zip(entries, outs):
            read_reqs.extend(prepare_read(entry, obj_out=out))
        merged = batch_read_requests(read_reqs)
        assert len(merged) == 1  # one spanning read for the slab
        io = ReadIO(path=merged[0].path, byte_range=merged[0].byte_range)
        await plugin.read(io)
        await merged[0].buffer_consumer.consume_buffer(io.buf)
        await plugin.close()
        return outs

    outs = run_in_fresh_event_loop(go())
    for a, b in zip(arrs, outs):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize(
    "dtype", [np.float32, np.float16, np.int8, np.uint32, "bfloat16"]
)
def test_snapshot_roundtrip_with_batching(tmp_path, dtype) -> None:
    if dtype == "bfloat16":
        arrs = {f"a{i}": jnp.arange(32, dtype=jnp.bfloat16) + i for i in range(5)}
    else:
        arrs = {
            f"a{i}": np.arange(32).astype(dtype) + i for i in range(5)
        }
    with enable_batching(), override_slab_size_threshold_bytes(4096):
        ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState(dict(arrs))})
        # Everything under threshold -> exactly one batched blob on disk.
        batched_dir = os.path.join(str(tmp_path), "batched")
        assert len(os.listdir(batched_dir)) == 1

        dest = ts.PyTreeState(
            {k: (jnp.zeros_like(v) if dtype == "bfloat16" else np.zeros_like(v)) for k, v in arrs.items()}
        )
        ts.Snapshot(str(tmp_path)).restore({"s": dest})
    for k, v in arrs.items():
        np.testing.assert_array_equal(np.asarray(dest.tree[k]), np.asarray(v))


def test_batching_roundtrip_without_knob_reads_back(tmp_path) -> None:
    """A snapshot taken with batching restores fine with the knob off —
    the manifest byte ranges carry everything."""
    arrs = {f"a{i}": np.full((16,), float(i), np.float32) for i in range(3)}
    with enable_batching(), override_slab_size_threshold_bytes(4096):
        ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState(dict(arrs))})
    dest = ts.PyTreeState({k: np.zeros_like(v) for k, v in arrs.items()})
    ts.Snapshot(str(tmp_path)).restore({"s": dest})
    for k, v in arrs.items():
        np.testing.assert_array_equal(dest.tree[k], v)


@multiprocess_test(nproc=2)
def test_replicated_with_batching(pg) -> None:
    """Replicated state + batching: the batch-rewritten entry from the
    write-owning rank must win consolidation, and restore must succeed."""
    import jax.numpy as jnp

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.knobs import (
        enable_batching,
        override_slab_size_threshold_bytes,
    )

    path = os.path.join(tempfile.gettempdir(), "batch-repl-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()

    arrs = {f"w{i}": jnp.full((64,), 1.0 + i, jnp.float32) for i in range(6)}
    app_state = {"params": ts.PyTreeState(dict(arrs))}
    with enable_batching(), override_slab_size_threshold_bytes(512):
        snap = ts.Snapshot.take(path, app_state, pg=pg, replicated=["params/**"])
        dest = ts.PyTreeState({k: jnp.zeros_like(v) for k, v in arrs.items()})
        snap.restore({"params": dest})
    for k, v in arrs.items():
        np.testing.assert_array_equal(np.asarray(dest.tree[k]), np.asarray(v))
