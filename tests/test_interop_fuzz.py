"""Randomized interop fuzz: reference-written snapshots → our reader.

The structured tests pin known corners; this sweep generates random
nested app states (mixed dtypes, containers, primitives, hostile keys),
saves each with the ACTUAL reference library, reads it back with our
bridge, and compares leaf-for-leaf. Seeded, so failures replay.
"""

import string
from collections import OrderedDict

import numpy as np
import pytest

from interop_utils import import_reference

from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
    read_reference_snapshot,
)

ml_dtypes = pytest.importorskip("ml_dtypes")


def _rand_key(rng) -> object:
    kind = rng.integers(0, 4)
    if kind == 0:
        return int(rng.integers(-50, 50))
    chars = string.ascii_lowercase + "/%. -"
    return "".join(
        rng.choice(list(chars)) for _ in range(int(rng.integers(1, 8)))
    )


def _rand_leaf(rng, torch):
    kind = int(rng.integers(0, 8))
    if kind == 0:
        return int(rng.integers(-(2**40), 2**40))
    if kind == 1:
        return float(rng.standard_normal())
    if kind == 2:
        return bool(rng.integers(0, 2))
    if kind == 3:
        return "".join(rng.choice(list(string.printable[:60])) for _ in range(5))
    if kind == 4:
        return bytes(rng.integers(0, 256, int(rng.integers(0, 9)), dtype=np.uint8))
    shape = tuple(
        int(d) for d in rng.integers(1, 5, size=int(rng.integers(0, 3)))
    )
    tdtype = [torch.float32, torch.bfloat16, torch.int64, torch.float16][
        int(rng.integers(0, 4))
    ]
    if tdtype == torch.bfloat16 and shape == ():
        # The reference destroys 0-d bf16 at save time (writes an empty
        # blob; its own restore fails) — nothing to round-trip. Pinned
        # separately in test_zero_dim_bf16_reference_bug_is_diagnosed.
        tdtype = torch.float32
    return (
        torch.from_numpy(rng.standard_normal(shape).astype(np.float32))
        .to(tdtype)
    )


def _rand_tree(rng, torch, depth: int):
    if depth <= 0 or rng.integers(0, 3) == 0:
        return _rand_leaf(rng, torch)
    kind = int(rng.integers(0, 3))
    n = int(rng.integers(1, 5))
    if kind == 0:
        return [_rand_tree(rng, torch, depth - 1) for _ in range(n)]
    cls = OrderedDict if kind == 1 else dict
    out = cls()
    for _ in range(n):
        out[_rand_key(rng)] = _rand_tree(rng, torch, depth - 1)
    return out


def _compare(ours, theirs, torch, path="") -> None:
    if isinstance(theirs, torch.Tensor):
        t = theirs.detach()
        if t.dtype == torch.bfloat16:
            assert ours.dtype == ml_dtypes.bfloat16, path
            np.testing.assert_array_equal(
                ours.view(np.uint16), t.view(torch.uint16).numpy(), err_msg=path
            )
        else:
            np.testing.assert_array_equal(ours, t.numpy(), err_msg=path)
        return
    if isinstance(theirs, dict):
        assert list(ours.keys()) == list(theirs.keys()), path
        for k in theirs:
            _compare(ours[k], theirs[k], torch, f"{path}/{k!r}")
        return
    if isinstance(theirs, list):
        assert len(ours) == len(theirs), path
        for i, (a, b) in enumerate(zip(ours, theirs)):
            _compare(a, b, torch, f"{path}/{i}")
        return
    assert ours == theirs, f"{path}: {ours!r} != {theirs!r}"


def test_zero_dim_bf16_reference_bug_is_diagnosed(tmp_path):
    """The reference writes an EMPTY blob for 0-d bfloat16 tensors (its
    zero-copy bf16 path, serialization.py:216-233) and cannot restore
    them itself — verified directly against the library. Our reader must
    fail with a diagnosis naming that bug, not a reshape traceback."""
    torch = pytest.importorskip("torch")
    torchsnapshot = import_reference()
    snap = str(tmp_path / "zd")
    torchsnapshot.Snapshot.take(
        snap,
        {"s": torchsnapshot.StateDict(z=torch.tensor(1.5, dtype=torch.bfloat16))},
    )
    with pytest.raises(ValueError, match="known reference bug"):
        read_reference_snapshot(snap)


@pytest.mark.parametrize("seed", range(8))
def test_reference_fuzz_roundtrip(tmp_path, seed):
    torch = pytest.importorskip("torch")
    torchsnapshot = import_reference()
    rng = np.random.default_rng(1000 + seed)

    tree = {"root": _rand_tree(rng, torch, depth=3)}
    app_state = {"s": torchsnapshot.StateDict(**tree)}
    snap = str(tmp_path / f"fuzz{seed}")
    torchsnapshot.Snapshot.take(snap, app_state)

    state = read_reference_snapshot(snap)
    _compare(state["s"]["root"], tree["root"], torch)
