"""GCS plugin against a real local HTTP server (tests/fake_gcs.py), the
fake-gcs-server role: resumable-upload chunking and RECOVER, ranged
chunked downloads, 404 normalization, and the transient-retry taxonomy —
previously verified only against hand-rolled mocks. Full Snapshot
round-trips ride the gs:// scheme end to end."""

import asyncio
import os

import numpy as np
import pytest

import torchsnapshot_tpu.storage_plugins.gcs as gcs_mod
from torchsnapshot_tpu.io_types import ReadIO, WriteIO

from fake_gcs import FakeGCSServer


@pytest.fixture()
def emulator(monkeypatch):
    srv = FakeGCSServer()
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", srv.start())
    yield srv
    srv.stop()


def _run(coro):
    return asyncio.run(coro)


def test_write_read_delete_roundtrip(emulator) -> None:
    async def main():
        p = gcs_mod.GCSStoragePlugin("bkt/prefix")
        data = bytes(range(256)) * 64
        await p.write(WriteIO(path="x/y", buf=data))
        rio = ReadIO(path="x/y")
        await p.read(rio)
        assert bytes(rio.buf) == data
        rio = ReadIO(path="x/y", byte_range=(10, 5000))
        await p.read(rio)
        assert bytes(rio.buf) == data[10:5000]
        with pytest.raises(FileNotFoundError):
            await p.read(ReadIO(path="nope"))
        await p.delete("x/y")
        with pytest.raises(FileNotFoundError):
            await p.read(ReadIO(path="x/y"))
        await p.close()

    _run(main())


def test_resumable_upload_recovers_mid_upload(emulator, monkeypatch) -> None:
    """A 503 on a middle chunk must trigger ResumableUpload.recover (a
    'bytes */N' probe answered 308+Range) and resume from the confirmed
    offset — not restart from byte 0, not fail the write."""
    monkeypatch.setattr(gcs_mod, "_UPLOAD_CHUNK_SIZE", 256 * 1024)
    data = os.urandom(1280 * 1024)  # 5 chunks of 256 KiB

    async def main():
        p = gcs_mod.GCSStoragePlugin("bkt")
        emulator.fail_next(1, status=503, where="chunk")
        await p.write(WriteIO(path="big", buf=data))
        rio = ReadIO(path="big")
        await p.read(rio)
        assert bytes(rio.buf) == data
        await p.close()

    _run(main())
    # 5 data chunks + the failed attempt; recover probed the offset.
    assert emulator.request_counts["chunk"] >= 6
    assert emulator.request_counts["probe"] >= 1


def test_initiate_5xx_retried_by_collective_strategy(emulator) -> None:
    """A 503 storm on initiate is transient: the collective-progress retry
    re-runs the op and the write lands."""
    emulator.fail_next(2, status=503, where="initiate")

    async def main():
        p = gcs_mod.GCSStoragePlugin("bkt")
        await p.write(WriteIO(path="k", buf=b"payload"))
        rio = ReadIO(path="k")
        await p.read(rio)
        assert bytes(rio.buf) == b"payload"
        await p.close()

    _run(main())
    assert emulator.request_counts["initiate"] == 3


def test_download_5xx_retried(emulator) -> None:
    async def main():
        p = gcs_mod.GCSStoragePlugin("bkt")
        await p.write(WriteIO(path="k", buf=b"v" * 1000))
        emulator.fail_next(1, status=500, where="download")
        rio = ReadIO(path="k")
        await p.read(rio)
        assert bytes(rio.buf) == b"v" * 1000
        await p.close()

    _run(main())


def test_nonretriable_4xx_raises(emulator) -> None:
    async def main():
        p = gcs_mod.GCSStoragePlugin("bkt")
        emulator.fail_next(1, status=403, where="initiate")
        with pytest.raises(Exception) as ei:
            await p.write(WriteIO(path="k", buf=b"x"))
        assert "403" in str(ei.value) or "InvalidResponse" in type(ei.value).__name__
        await p.close()

    _run(main())
    assert emulator.request_counts["initiate"] == 1  # no retry on 403


def test_snapshot_roundtrip_over_gs_scheme(emulator) -> None:
    """The whole checkpointer over gs://: take -> commit marker -> restore
    byte-identically, all through the live HTTP server."""
    import torchsnapshot_tpu as ts

    tree = {"w": np.arange(4096, dtype=np.float32).reshape(64, 64), "step": 3}
    ts.Snapshot.take("gs://bkt/ckpt", {"s": ts.PyTreeState(tree)})
    assert any(b.endswith(".snapshot_metadata") for b in emulator.blobs)
    dst = {"w": np.zeros((64, 64), np.float32), "step": 0}
    wrapped = ts.PyTreeState(dst)
    ts.Snapshot("gs://bkt/ckpt").restore({"s": wrapped})
    np.testing.assert_array_equal(wrapped.tree["w"], tree["w"])
    assert wrapped.tree["step"] == 3


def test_incremental_refs_resolve_over_gcs(emulator) -> None:
    """Incremental ../step_X refs resolve through the emulator's flat
    object namespace (lexical key normalization against a real HTTP
    server, not just the unit-tested string math), including checksum
    inheritance and deep fsck of the chain."""
    import jax.numpy as jnp

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.fsck import verify_snapshot

    w = jnp.arange(128, dtype=jnp.float32)
    b = jnp.ones((16,), jnp.float32)
    base = "gs://bkt/run/step_0"
    incr = "gs://bkt/run/step_1"
    ts.Snapshot.take(
        base, {"m": ts.PyTreeState({"w": w, "b": b})}, record_digests=True
    )
    ts.Snapshot.take(
        incr,
        {"m": ts.PyTreeState({"w": w, "b": b * 2})},
        incremental_base=base,
    )

    manifest = ts.Snapshot(incr).get_manifest()
    assert manifest["0/m/w"].location == "../step_0/0/m/w"

    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w), "b": jnp.zeros_like(b)})}
    ts.Snapshot(incr).restore(dest)
    np.testing.assert_array_equal(np.asarray(dest["m"].tree["w"]), np.asarray(w))
    np.testing.assert_array_equal(np.asarray(dest["m"].tree["b"]), np.asarray(b * 2))

    report = verify_snapshot(incr, deep=True)
    assert report.ok and report.crcs_verified == report.blobs_checked

    # read_object through the ref as well.
    out = ts.Snapshot(incr).read_object("0/m/w")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(w))
