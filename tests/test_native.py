"""Native I/O runtime (_native.py / native/ts_io.cpp).

The reference has no native code to mirror (SURVEY.md §2.9); these tests
pin down the contract our C++ layer adds: exact ranged reads/writes,
scatter-pack, CRC32-C known answers, errno propagation as OSError, and
byte-identical behavior between the native and pure-Python FS plugin
paths (the fallback must be indistinguishable).
"""

import os

import pytest

from torchsnapshot_tpu import _native
from torchsnapshot_tpu.event_loop import run_in_fresh_event_loop
from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.knobs import _override_env
from torchsnapshot_tpu.knobs import disable_native as _disable_native
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

native_only = pytest.mark.skipif(
    _native.lib() is None, reason="native runtime unavailable on this host"
)


@native_only
def test_write_read_roundtrip(tmp_path) -> None:
    p = str(tmp_path / "blob")
    data = os.urandom(1 << 16)
    assert _native.write_file(p, data)
    assert _native.file_size(p) == len(data)
    out = bytearray(len(data))
    assert _native.pread_into(p, out)
    assert bytes(out) == data


@native_only
def test_ranged_pread(tmp_path) -> None:
    p = str(tmp_path / "blob")
    data = bytes(range(256)) * 16
    _native.write_file(p, data)
    out = bytearray(100)
    _native.pread_into(p, out, offset=300)
    assert bytes(out) == data[300:400]


@native_only
def test_pread_past_eof_raises(tmp_path) -> None:
    p = str(tmp_path / "blob")
    _native.write_file(p, b"short")
    with pytest.raises(OSError):
        _native.pread_into(p, bytearray(100), offset=0)


@native_only
def test_missing_file_raises_enoent(tmp_path) -> None:
    with pytest.raises(OSError) as ei:
        _native.pread_into(str(tmp_path / "nope"), bytearray(1))
    assert ei.value.errno == 2


@native_only
def test_gather_memcpy_scatter_and_bounds(tmp_path) -> None:
    dst = bytearray(64)
    parts = [(b"aaaa", 0), (b"bb", 62), (b"cccccc", 20)]
    assert _native.gather_memcpy(dst, parts, n_threads=2)
    assert bytes(dst[0:4]) == b"aaaa"
    assert bytes(dst[62:64]) == b"bb"
    assert bytes(dst[20:26]) == b"cccccc"
    with pytest.raises(ValueError):
        _native.gather_memcpy(dst, [(b"xx", 63)])


@native_only
def test_gather_memcpy_large_multithreaded() -> None:
    import numpy as np

    rng = np.random.default_rng(0)
    srcs = [rng.integers(0, 256, size=n, dtype=np.uint8) for n in (1 << 20, 3 << 20, 1 << 10)]
    total = sum(s.nbytes for s in srcs)
    dst = bytearray(total)
    off, parts = 0, []
    for s in srcs:
        parts.append((s, off))
        off += s.nbytes
    _native.gather_memcpy(dst, parts, n_threads=4)
    assert bytes(dst) == b"".join(s.tobytes() for s in srcs)


@native_only
def test_crc32c_known_answer() -> None:
    # RFC 3720 test vector.
    assert _native.crc32c(b"123456789") == 0xE3069283
    assert _native.crc32c(b"") == 0


def _fs_roundtrip(root: str) -> bytes:
    plugin = FSStoragePlugin(root)

    async def go():
        data = os.urandom(1 << 16)
        await plugin.write(WriteIO(path="a/b/blob", buf=data))
        whole = ReadIO(path="a/b/blob")
        await plugin.read(whole)
        assert bytes(whole.buf) == data
        ranged = ReadIO(path="a/b/blob", byte_range=(100, 1100))
        await plugin.read(ranged)
        assert bytes(ranged.buf) == data[100:1100]
        await plugin.close()
        return data

    return run_in_fresh_event_loop(go())


def test_fs_plugin_native_and_fallback_parity(tmp_path) -> None:
    _fs_roundtrip(str(tmp_path / "native"))
    with _disable_native():
        plugin = FSStoragePlugin(str(tmp_path / "fallback"))
        assert plugin._native is False
        _fs_roundtrip(str(tmp_path / "fallback"))


@pytest.mark.parametrize("disable_native", [False, True])
def test_fs_ranged_read_past_eof_raises_both_paths(
    tmp_path, disable_native
) -> None:
    """Short blobs are corruption: ranged reads past EOF must fail the same
    way (OSError) whether or not the native lib is in play."""
    ctx = (
        _disable_native()
        if disable_native
        else _override_env("_TS_NOOP", None)
    )
    with ctx:
        plugin = FSStoragePlugin(str(tmp_path))

        async def go():
            await plugin.write(WriteIO(path="blob", buf=b"short"))
            with pytest.raises(OSError):
                await plugin.read(ReadIO(path="blob", byte_range=(0, 100)))
            await plugin.close()

        run_in_fresh_event_loop(go())


def test_fs_write_falls_back_when_native_vanishes_mid_process(
    tmp_path,
) -> None:
    """A plugin constructed with native available must still write correctly
    if the disable knob flips afterwards (lib() re-checks env every call)."""
    plugin = FSStoragePlugin(str(tmp_path))
    with _disable_native():

        async def go():
            data = os.urandom(4096)
            await plugin.write(WriteIO(path="blob", buf=data))
            rio = ReadIO(path="blob")
            await plugin.read(rio)
            assert bytes(rio.buf) == data
            await plugin.close()

        run_in_fresh_event_loop(go())


def test_user_owned_destination_never_direct_read(tmp_path) -> None:
    """A failed read must not tear a user-owned in-place destination:
    direct (zero-copy) reads are gated to framework-allocated buffers, so
    an in-place numpy restore keeps copy-on-success semantics."""
    import os

    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.io_preparer import prepare_read

    path = str(tmp_path)
    arr = np.arange(16.0).reshape(4, 4)
    ts.Snapshot.take(path, {"s": ts.PyTreeState({"w": arr})})
    entry = ts.Snapshot(path).get_manifest()["0/s/w"]

    [user_req] = prepare_read(entry, obj_out=np.zeros((4, 4)), dest_owned=False)
    assert user_req.buffer_consumer.direct_destination() is None

    [owned_req] = prepare_read(entry, obj_out=np.zeros((4, 4)), dest_owned=True)
    assert owned_req.buffer_consumer.direct_destination() is not None

    # End-to-end: truncate the blob; the in-place restore fails but the
    # user's array is untouched (no half-old/half-new bytes).
    blob = os.path.join(path, "0", "s", "w")
    data = open(blob, "rb").read()
    with open(blob, "wb") as f:
        f.write(data[: len(data) // 2])
    dst = {"s": ts.PyTreeState({"w": np.full((4, 4), 7.0)})}
    with pytest.raises(Exception):
        ts.Snapshot(path).restore(dst)
    np.testing.assert_array_equal(
        np.asarray(dst["s"].tree["w"]), np.full((4, 4), 7.0)
    )
