"""Seeded randomized distributed crash sweep — the suite-resident slice
of the round-5 fail-fast validation (the full sweep ran 22 cases; these
seeds pin one of each injection family under schedule variation).

Each case injects one failure at a random covered point — take side:
storage write on a random rank, rank-0 metadata write in the commit
window, rank-0 replication consolidation during staging; restore side:
setup (manifest read), data read, async planning on a random rank —
over random state shapes and sync/async modes, asserting every rank
raises well under the 300 s store timeout, no commit marker survives a
failed take, and a clean retry succeeds after a failed restore. This is
the regression net for the collectives-before-failure-points rule
(docs/design.md): peers must abandon at an error-aware barrier, never
inside an op-seq collective poll."""

import contextlib
import os
import shutil
import tempfile
import time
from unittest import mock

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.chaos import ChaosEngine, chaotic_plugin_type
from torchsnapshot_tpu.chaos.plan import FaultPlan, FaultSpec
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME, Snapshot
from torchsnapshot_tpu.test_utils import (
    multiprocess_test,
    patch_storage_plugin,
)


def _data_blob(path: str) -> bool:
    return "/m/" in path or "batched" in path


def _chaotic_fs_patch(plan: FaultPlan):
    """Fault-plan injection through the one chaos mechanism (the
    migration of this sweep's legacy faulty_fs_plugin closures): the
    plan line is what a red case prints to replay."""
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    return patch_storage_plugin(
        chaotic_plugin_type(FSStoragePlugin, ChaosEngine(plan))
    )


def _data_blob_fault(seed: int, point: str, exc_msg: str) -> FaultPlan:
    return FaultPlan(
        seed=seed,
        faults=[
            FaultSpec(
                point=point,
                mode="fail",
                times=None,
                predicate=_data_blob,
                exc_msg=exc_msg,
            )
        ],
    )


def _rand_state(rng, n_leaves: int, rank: int) -> dict:
    return {
        "m": ts.PyTreeState(
            {
                f"l{i}": rng.standard_normal(
                    int(rng.integers(64, 4096))
                ).astype(np.float32)
                + rank
                for i in range(n_leaves)
            }
        )
    }


def _take_case(pg, seed: int) -> None:
    rng = np.random.default_rng(seed)
    mode = rng.choice(["sync", "async"])
    fail_point = rng.choice(["write", "metadata", "consolidate"])
    fail_rank = int(rng.integers(0, 2)) if fail_point == "write" else 0
    path = os.path.join(tempfile.gettempdir(), f"crash-sweep-take-{seed}")
    if pg.rank == 0:
        shutil.rmtree(path, ignore_errors=True)
    PGWrapper(pg).barrier()
    state = _rand_state(rng, int(rng.integers(1, 5)), pg.rank)

    ctx = contextlib.nullcontext()
    if fail_point == "write" and pg.rank == fail_rank:
        ctx = _chaotic_fs_patch(
            _data_blob_fault(
                seed, "storage-write", f"injected write failure ({seed})"
            )
        )
    elif fail_point == "metadata" and pg.rank == 0:
        ctx = mock.patch.object(
            Snapshot,
            "_write_snapshot_metadata",
            side_effect=RuntimeError(f"injected metadata failure ({seed})"),
        )
    elif fail_point == "consolidate" and pg.rank == 0:
        ctx = mock.patch(
            "torchsnapshot_tpu.partitioner.consolidate_replicated_entries",
            side_effect=RuntimeError(f"injected consolidate failure ({seed})"),
        )

    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        if mode == "sync":
            ts.Snapshot.take(path, state, pg=pg, replicated=["m/**"])
        else:
            ts.Snapshot.async_take(
                path, state, pg=pg, replicated=["m/**"]
            ).wait()
    assert time.monotonic() - t0 < 60.0, (
        f"seed {seed} rank {pg.rank} blocked to store timeout "
        f"({mode}/{fail_point}/rank{fail_rank})"
    )
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


def _restore_case(pg, seed: int) -> None:
    rng = np.random.default_rng(1000 + seed)
    mode = rng.choice(["sync", "async"])
    fail_point = rng.choice(["setup", "read", "plan"])
    fail_rank = int(rng.integers(0, 2))
    n_leaves = int(rng.integers(1, 4))
    path = os.path.join(tempfile.gettempdir(), f"crash-sweep-restore-{seed}")
    if pg.rank == 0:
        shutil.rmtree(path, ignore_errors=True)
    PGWrapper(pg).barrier()
    state = _rand_state(rng, n_leaves, pg.rank)
    ts.Snapshot.take(path, state, pg=pg)

    def dest():
        return {
            "m": ts.PyTreeState(
                {
                    f"l{i}": np.zeros_like(state["m"].tree[f"l{i}"])
                    for i in range(n_leaves)
                }
            )
        }

    ctx = contextlib.nullcontext()
    if pg.rank == fail_rank:
        if fail_point == "setup":
            ctx = mock.patch(
                "torchsnapshot_tpu.snapshot.get_manifest_for_rank",
                side_effect=OSError(f"injected setup failure ({seed})"),
            )
        elif fail_point == "read":
            ctx = _chaotic_fs_patch(
                _data_blob_fault(
                    seed, "storage-read", f"injected read failure ({seed})"
                )
            )
        else:
            ctx = mock.patch.object(
                Snapshot,
                "_plan_stateful_load",
                side_effect=RuntimeError(f"injected plan failure ({seed})"),
            )

    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        if mode == "sync":
            ts.Snapshot(path, pg=pg).restore(dest())
        else:
            ts.Snapshot(path, pg=pg).async_restore(dest()).wait()
    assert time.monotonic() - t0 < 60.0, (
        f"seed {seed} rank {pg.rank} blocked to store timeout "
        f"({mode}/{fail_point}/rank{fail_rank})"
    )
    d2 = dest()
    if mode == "sync":
        ts.Snapshot(path, pg=pg).restore(d2)
    else:
        ts.Snapshot(path, pg=pg).async_restore(d2).wait()
    for i in range(n_leaves):
        np.testing.assert_array_equal(
            d2["m"].tree[f"l{i}"], state["m"].tree[f"l{i}"]
        )


@multiprocess_test(nproc=2)
def test_take_crash_sweep(pg) -> None:
    # async/metadata, async/write, sync/consolidate, sync/write
    for seed in (0, 2, 9, 11):
        _take_case(pg, seed)


@multiprocess_test(nproc=2)
def test_restore_crash_sweep(pg) -> None:
    # sync/read, async/setup, sync/plan, async/plan
    for seed in (0, 4, 13, 17):
        _restore_case(pg, seed)


# ---------------------------------------------------------------------------
# Peer-tier sweep (docs/peer.md degradation matrix): kill the peer at
# every stage of the push/pull lifecycle; the restore must fall through
# the peer -> storage ladder to CORRECT bytes — bounded, never a hang —
# and the ledger must record which tier served the shards.
# ---------------------------------------------------------------------------


def _peer_case(pg, scenario: str) -> None:
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu import telemetry
    from torchsnapshot_tpu.pg_wrapper import PGWrapper
    from torchsnapshot_tpu.tiered import peer

    os.environ["TORCHSNAPSHOT_TPU_PEER_TIER"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_PEER_TRANSFER_TIMEOUT_SECONDS"] = "1.5"
    os.environ["TORCHSNAPSHOT_TPU_LEDGER"] = "1"

    root = os.path.join(tempfile.gettempdir(), f"peer-sweep-{scenario}")
    wrapper = PGWrapper(pg)
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    wrapper.barrier()

    # Fresh tier per scenario: the previous scenario may have killed
    # this rank's server; a replacement always re-announces.
    peer.reset_peer_tier()
    n = 50_000
    state = {
        "m": ts.PyTreeState(
            {"w": np.arange(n, dtype=np.float32) + pg.rank}
        )
    }
    mgr = ts.CheckpointManager(root, pg=pg)
    assert peer.get_replicator().configured
    wrapper.barrier()

    def _kill_own_server() -> None:
        rep = peer.get_replicator()
        rep._server.shutdown()
        rep._server.server_close()

    if scenario == "dead-mid-push" and pg.rank == 1:
        # Rank 0's ring target dies before/while rank 0 pushes: the
        # push job must time out, degrade, and never wedge the save.
        _kill_own_server()
    wrapper.barrier()

    t0 = time.monotonic()
    mgr.save(0, state)
    if scenario == "dead-between-commit-and-drain" and pg.rank == 1:
        # The commit landed; the peer dies before the drain settles.
        _kill_own_server()
    assert peer.maybe_drain(timeout=60), "peer drain wedged"
    assert time.monotonic() - t0 < 90.0, f"{scenario}: push path wedged"
    wrapper.barrier()

    if scenario == "dead-mid-pull" and pg.rank == 1:
        # Healthy push, then the peer dies before the restore pulls.
        _kill_own_server()
    wrapper.barrier()

    dest = {"m": ts.PyTreeState({"w": np.zeros(n, dtype=np.float32)})}
    t0 = time.monotonic()
    assert mgr.restore_latest(dest) == 0
    assert time.monotonic() - t0 < 90.0, f"{scenario}: restore wedged"
    # Bytes match durable truth on EVERY rank, whatever tier served.
    np.testing.assert_array_equal(
        dest["m"].tree["w"], np.arange(n, dtype=np.float32) + pg.rank
    )
    report = telemetry.last_report("restore", path=mgr.step_path(0))
    if report is not None and report.tier_split is not None:
        # Whatever the ladder served must account for real bytes; the
        # dead-peer side contributes durable/fast bytes only.
        assert sum(report.tier_split.values()) > 0
    wrapper.barrier()
    if pg.rank == 0:
        from torchsnapshot_tpu.telemetry.ledger import (
            ledger_path_for,
            load_ledger,
        )

        records = load_ledger(ledger_path_for(root))
        served = [
            r for r in records if r.get("event") == "restore-served"
        ]
        assert served, f"{scenario}: no restore-served ledger record"
        if scenario == "dead-mid-pull":
            # Rank 1's SERVER died, but rank 1's shards live in rank
            # 0's surviving cache: rank 1's restore still rides the
            # peer tier, so the world split must show peer bytes
            # (rank 0's own shards fall through to storage — its ring
            # target was the dead server).
            tier_split = served[-1].get("tier_split") or {}
            assert tier_split.get("peer", 0) > 0, served[-1]
    wrapper.barrier()
    peer.reset_peer_tier()


@multiprocess_test(nproc=2)
def test_peer_tier_crash_sweep(pg) -> None:
    for scenario in (
        "dead-mid-push",
        "dead-mid-pull",
        "dead-between-commit-and-drain",
    ):
        _peer_case(pg, scenario)
