"""Seeded randomized distributed crash sweep — the suite-resident slice
of the round-5 fail-fast validation (the full sweep ran 22 cases; these
seeds pin one of each injection family under schedule variation).

Each case injects one failure at a random covered point — take side:
storage write on a random rank, rank-0 metadata write in the commit
window, rank-0 replication consolidation during staging; restore side:
setup (manifest read), data read, async planning on a random rank —
over random state shapes and sync/async modes, asserting every rank
raises well under the 300 s store timeout, no commit marker survives a
failed take, and a clean retry succeeds after a failed restore. This is
the regression net for the collectives-before-failure-points rule
(docs/design.md): peers must abandon at an error-aware barrier, never
inside an op-seq collective poll."""

import contextlib
import os
import shutil
import tempfile
import time
from unittest import mock

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME, Snapshot
from torchsnapshot_tpu.test_utils import (
    faulty_fs_plugin,
    multiprocess_test,
    patch_storage_plugin,
)


def _data_blob(path: str) -> bool:
    return "/m/" in path or "batched" in path


def _rand_state(rng, n_leaves: int, rank: int) -> dict:
    return {
        "m": ts.PyTreeState(
            {
                f"l{i}": rng.standard_normal(
                    int(rng.integers(64, 4096))
                ).astype(np.float32)
                + rank
                for i in range(n_leaves)
            }
        )
    }


def _take_case(pg, seed: int) -> None:
    rng = np.random.default_rng(seed)
    mode = rng.choice(["sync", "async"])
    fail_point = rng.choice(["write", "metadata", "consolidate"])
    fail_rank = int(rng.integers(0, 2)) if fail_point == "write" else 0
    path = os.path.join(tempfile.gettempdir(), f"crash-sweep-take-{seed}")
    if pg.rank == 0:
        shutil.rmtree(path, ignore_errors=True)
    PGWrapper(pg).barrier()
    state = _rand_state(rng, int(rng.integers(1, 5)), pg.rank)

    ctx = contextlib.nullcontext()
    if fail_point == "write" and pg.rank == fail_rank:
        ctx = patch_storage_plugin(
            faulty_fs_plugin(
                _data_blob, exc_msg=f"injected write failure ({seed})"
            )
        )
    elif fail_point == "metadata" and pg.rank == 0:
        ctx = mock.patch.object(
            Snapshot,
            "_write_snapshot_metadata",
            side_effect=RuntimeError(f"injected metadata failure ({seed})"),
        )
    elif fail_point == "consolidate" and pg.rank == 0:
        ctx = mock.patch(
            "torchsnapshot_tpu.partitioner.consolidate_replicated_entries",
            side_effect=RuntimeError(f"injected consolidate failure ({seed})"),
        )

    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        if mode == "sync":
            ts.Snapshot.take(path, state, pg=pg, replicated=["m/**"])
        else:
            ts.Snapshot.async_take(
                path, state, pg=pg, replicated=["m/**"]
            ).wait()
    assert time.monotonic() - t0 < 60.0, (
        f"seed {seed} rank {pg.rank} blocked to store timeout "
        f"({mode}/{fail_point}/rank{fail_rank})"
    )
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


def _restore_case(pg, seed: int) -> None:
    rng = np.random.default_rng(1000 + seed)
    mode = rng.choice(["sync", "async"])
    fail_point = rng.choice(["setup", "read", "plan"])
    fail_rank = int(rng.integers(0, 2))
    n_leaves = int(rng.integers(1, 4))
    path = os.path.join(tempfile.gettempdir(), f"crash-sweep-restore-{seed}")
    if pg.rank == 0:
        shutil.rmtree(path, ignore_errors=True)
    PGWrapper(pg).barrier()
    state = _rand_state(rng, n_leaves, pg.rank)
    ts.Snapshot.take(path, state, pg=pg)

    def dest():
        return {
            "m": ts.PyTreeState(
                {
                    f"l{i}": np.zeros_like(state["m"].tree[f"l{i}"])
                    for i in range(n_leaves)
                }
            )
        }

    ctx = contextlib.nullcontext()
    if pg.rank == fail_rank:
        if fail_point == "setup":
            ctx = mock.patch(
                "torchsnapshot_tpu.snapshot.get_manifest_for_rank",
                side_effect=OSError(f"injected setup failure ({seed})"),
            )
        elif fail_point == "read":
            ctx = patch_storage_plugin(
                faulty_fs_plugin(
                    _data_blob,
                    ops=("read",),
                    exc_msg=f"injected read failure ({seed})",
                )
            )
        else:
            ctx = mock.patch.object(
                Snapshot,
                "_plan_stateful_load",
                side_effect=RuntimeError(f"injected plan failure ({seed})"),
            )

    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        if mode == "sync":
            ts.Snapshot(path, pg=pg).restore(dest())
        else:
            ts.Snapshot(path, pg=pg).async_restore(dest()).wait()
    assert time.monotonic() - t0 < 60.0, (
        f"seed {seed} rank {pg.rank} blocked to store timeout "
        f"({mode}/{fail_point}/rank{fail_rank})"
    )
    d2 = dest()
    if mode == "sync":
        ts.Snapshot(path, pg=pg).restore(d2)
    else:
        ts.Snapshot(path, pg=pg).async_restore(d2).wait()
    for i in range(n_leaves):
        np.testing.assert_array_equal(
            d2["m"].tree[f"l{i}"], state["m"].tree[f"l{i}"]
        )


@multiprocess_test(nproc=2)
def test_take_crash_sweep(pg) -> None:
    # async/metadata, async/write, sync/consolidate, sync/write
    for seed in (0, 2, 9, 11):
        _take_case(pg, seed)


@multiprocess_test(nproc=2)
def test_restore_crash_sweep(pg) -> None:
    # sync/read, async/setup, sync/plan, async/plan
    for seed in (0, 4, 13, 17):
        _restore_case(pg, seed)
