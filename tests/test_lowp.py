"""Low-precision codec round-trips (lowp.py) — the analog of the
reference's per-dtype serialization tests (tests/test_serialization.py)
applied to the q8 layouts (reference serialization.py:257-456)."""

import numpy as np
import pytest

from torchsnapshot_tpu import lowp


@pytest.mark.parametrize("dtype", ["float32", "float16", "bfloat16"])
def test_per_tensor_roundtrip_error_bound(dtype) -> None:
    import ml_dtypes

    dt = np.dtype(dtype) if dtype != "bfloat16" else np.dtype(ml_dtypes.bfloat16)
    rng = np.random.default_rng(0)
    arr = (rng.standard_normal((64, 33)) * 3).astype(dt)
    buf = lowp.encode_per_tensor(arr)
    assert len(buf) == arr.size + 16
    out = lowp.decode_per_tensor(buf, arr.shape)
    span = float(np.max(arr.astype(np.float32)) - np.min(arr.astype(np.float32)))
    # Affine int8: max error is half a quantization step.
    assert np.max(np.abs(out - arr.astype(np.float32))) <= span / 255 + 1e-6


def test_per_tensor_zero_exactness() -> None:
    arr = np.zeros((10, 10), dtype=np.float32)
    arr[3, 4] = 5.0
    out = lowp.decode_per_tensor(lowp.encode_per_tensor(arr), arr.shape)
    assert np.all(out[arr == 0.0] == 0.0)


def test_per_tensor_constant_array() -> None:
    arr = np.full((7,), 2.5, dtype=np.float32)
    out = lowp.decode_per_tensor(lowp.encode_per_tensor(arr), arr.shape)
    assert np.max(np.abs(out - arr)) <= (2.5 / 255) + 1e-6


def test_per_tensor_wrong_size_raises() -> None:
    with pytest.raises(ValueError, match="bytes"):
        lowp.decode_per_tensor(b"\x00" * 10, (64,))


def test_per_tensor_rejects_int_arrays() -> None:
    with pytest.raises(ValueError, match="float"):
        lowp.encode_per_tensor(np.arange(10, dtype=np.int32))


@pytest.mark.parametrize("axis", [0, 1, -1])
def test_per_channel_roundtrip(axis) -> None:
    rng = np.random.default_rng(1)
    # Per-channel shines when channel ranges differ wildly.
    arr = rng.standard_normal((8, 16, 4)).astype(np.float32)
    scale_per_c = 10.0 ** np.arange(arr.shape[axis])
    arr = np.moveaxis(
        np.moveaxis(arr, axis, 0) * scale_per_c[:, None, None], 0, axis
    ).astype(np.float32)
    buf = lowp.encode_per_channel(arr, axis)
    out = lowp.decode_per_channel(buf, arr.shape)
    moved_in = np.moveaxis(arr, axis, 0)
    moved_out = np.moveaxis(out, axis, 0)
    for c in range(moved_in.shape[0]):
        span = float(np.max(moved_in[c]) - np.min(moved_in[c]))
        span = max(span, abs(float(np.max(moved_in[c]))), 1e-6)
        assert np.max(np.abs(moved_out[c] - moved_in[c])) <= span / 255 + 1e-6


def test_per_channel_beats_per_tensor_on_mixed_scales() -> None:
    rng = np.random.default_rng(2)
    arr = np.stack(
        [rng.standard_normal(256) * s for s in (0.01, 100.0)]
    ).astype(np.float32)
    pt = lowp.decode_per_tensor(lowp.encode_per_tensor(arr), arr.shape)
    pc = lowp.decode_per_channel(lowp.encode_per_channel(arr, 0), arr.shape)
    err_pt = np.max(np.abs(pt[0] - arr[0]))  # small-scale channel suffers
    err_pc = np.max(np.abs(pc[0] - arr[0]))
    assert err_pc < err_pt / 100


def test_per_channel_layout_is_documented_format() -> None:
    import struct

    arr = np.ones((2, 3), dtype=np.float32)
    buf = lowp.encode_per_channel(arr, 1)
    (axis,) = struct.unpack("<q", buf[:8])
    assert axis == 1
    assert len(buf) == 8 + arr.size + 3 * 16


def test_per_channel_bad_axis_raises() -> None:
    with pytest.raises(ValueError, match="axis"):
        lowp.quantize_per_channel(np.ones((2, 2), dtype=np.float32), 5)
