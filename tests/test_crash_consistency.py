"""Randomized crash-consistency: storage fails at an arbitrary write
index (plain and fused write paths both hooked); the snapshot must leave
no commit marker, and a clean retake over the partial directory must
succeed and restore byte-exact.

Property widening of test_async_take's fixed-point failure injection
(reference analog: the no-commit-marker-on-failure invariant,
snapshot.py commit-after-barrier). A 60-case sweep of this generator
passed during round 4; these 8 deterministic seeds pin it.
"""

import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.test_utils import faulty_fs_plugin, patch_storage_plugin


@pytest.mark.parametrize("seed", range(4))
def test_read_failure_raises_then_clean_retry_succeeds(tmp_path, seed) -> None:
    """Reads failing at an arbitrary index (plain and fused paths) must
    surface as an exception — never a silent partial success — and a
    clean retry of the same snapshot restores byte-exact. (Destination
    partiality after a raised restore is the documented contract; see
    fs.py's direct-read note.) A 40-case sweep of this generator passed
    during round 4."""
    rng = np.random.default_rng(7000 + seed)
    n_leaves = int(rng.integers(2, 16))
    state = {
        f"l{i}": rng.standard_normal(int(rng.integers(1, 4000))).astype(
            np.float32
        )
        for i in range(n_leaves)
    }
    path = str(tmp_path / "s")
    ts.Snapshot.take(path, {"m": ts.PyTreeState(dict(state))})
    fail_at = int(rng.integers(0, n_leaves))
    counter = {"n": 0}

    def _crash_after(_path: str) -> bool:
        counter["n"] += 1
        return counter["n"] > fail_at

    patch = patch_storage_plugin(
        faulty_fs_plugin(
            _crash_after, ops=("read",), exc_msg="injected read failure"
        )
    )
    dst = ts.PyTreeState(
        {f"l{i}": np.zeros_like(state[f"l{i}"]) for i in range(n_leaves)}
    )
    with patch, pytest.raises(OSError, match="injected read failure"):
        ts.Snapshot(path).restore({"m": dst})

    dst2 = ts.PyTreeState(
        {f"l{i}": np.zeros_like(state[f"l{i}"]) for i in range(n_leaves)}
    )
    ts.Snapshot(path).restore({"m": dst2})
    for i in range(n_leaves):
        np.testing.assert_array_equal(dst2.tree[f"l{i}"], state[f"l{i}"])


@pytest.mark.parametrize("seed", range(8))
def test_crash_at_random_write_index(tmp_path, seed) -> None:
    rng = np.random.default_rng(4000 + seed)
    n_leaves = int(rng.integers(2, 20))
    state = {
        f"l{i}": rng.standard_normal(int(rng.integers(1, 5000))).astype(
            np.float32
        )
        for i in range(n_leaves)
    }
    fail_at = int(rng.integers(0, n_leaves + 2))
    counter = {"n": 0}

    def _crash_after(_path: str) -> bool:
        counter["n"] += 1
        return counter["n"] > fail_at

    patch = patch_storage_plugin(
        faulty_fs_plugin(_crash_after, exc_msg="injected failure")
    )
    path = str(tmp_path / "s")
    crashed = False
    try:
        with patch:
            ts.Snapshot.take(path, {"m": ts.PyTreeState(dict(state))})
    except OSError:
        crashed = True
    if crashed:
        assert not os.path.exists(os.path.join(path, ".snapshot_metadata"))

    # Clean retake over whatever partial state the crash left behind.
    ts.Snapshot.take(path, {"m": ts.PyTreeState(dict(state))})
    dst = ts.PyTreeState(
        {f"l{i}": np.zeros_like(state[f"l{i}"]) for i in range(n_leaves)}
    )
    ts.Snapshot(path).restore({"m": dst})
    for i in range(n_leaves):
        np.testing.assert_array_equal(dst.tree[f"l{i}"], state[f"l{i}"])
