"""Randomized crash-consistency: storage fails at an arbitrary write
index (plain and fused write paths both hooked); the snapshot must leave
no commit marker, and a clean retake over the partial directory must
succeed and restore byte-exact.

Property widening of test_async_take's fixed-point failure injection
(reference analog: the no-commit-marker-on-failure invariant,
snapshot.py commit-after-barrier). A 60-case sweep of this generator
passed during round 4; these 8 deterministic seeds pin it.

Since the chaos engine landed, each case is driven by a declarative
:class:`~torchsnapshot_tpu.chaos.FaultPlan` (fail the ``fail_at+1``-th
matching storage op) wrapped over the fs plugin — the same mechanism
the crash matrix and the distributed sweep replay through — and every
case asserts its plan round-trips through the one-line JSON form that a
red run would print.
"""

import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.chaos import ChaosEngine, FaultPlan, chaotic_plugin_type
from torchsnapshot_tpu.chaos.plan import seeded_failure_plan
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import patch_storage_plugin


def _chaotic_fs(plan: FaultPlan):
    """The fault-plan analog of the legacy faulty_fs_plugin shim: a
    class for patch_storage_plugin, plus the engine whose ``fired`` log
    pins the schedule."""
    engine = ChaosEngine(plan)
    cls = chaotic_plugin_type(FSStoragePlugin, engine)
    return cls, engine


@pytest.mark.parametrize("seed", range(4))
def test_read_failure_raises_then_clean_retry_succeeds(tmp_path, seed) -> None:
    """Reads failing at an arbitrary index (plain and fused paths) must
    surface as an exception — never a silent partial success — and a
    clean retry of the same snapshot restores byte-exact. (Destination
    partiality after a raised restore is the documented contract; see
    fs.py's direct-read note.) A 40-case sweep of this generator passed
    during round 4."""
    rng = np.random.default_rng(7000 + seed)
    n_leaves = int(rng.integers(2, 16))
    state = {
        f"l{i}": rng.standard_normal(int(rng.integers(1, 4000))).astype(
            np.float32
        )
        for i in range(n_leaves)
    }
    path = str(tmp_path / "s")
    ts.Snapshot.take(path, {"m": ts.PyTreeState(dict(state))})
    fail_at = int(rng.integers(0, n_leaves))
    plan = seeded_failure_plan(
        seed, "storage-read", fail_at, exc_msg="injected read failure"
    )
    # The plan IS the adversary: it must survive the replay round-trip.
    assert FaultPlan.from_json(plan.to_json()).to_json() == plan.to_json()
    cls, engine = _chaotic_fs(plan)

    dst = ts.PyTreeState(
        {f"l{i}": np.zeros_like(state[f"l{i}"]) for i in range(n_leaves)}
    )
    with patch_storage_plugin(cls), pytest.raises(
        OSError, match="injected read failure"
    ):
        ts.Snapshot(path).restore({"m": dst})
    assert engine.fired and all(
        point == "storage-read" for point, _, _ in engine.fired
    )

    dst2 = ts.PyTreeState(
        {f"l{i}": np.zeros_like(state[f"l{i}"]) for i in range(n_leaves)}
    )
    ts.Snapshot(path).restore({"m": dst2})
    for i in range(n_leaves):
        np.testing.assert_array_equal(dst2.tree[f"l{i}"], state[f"l{i}"])


@pytest.mark.parametrize("seed", range(8))
def test_crash_at_random_write_index(tmp_path, seed) -> None:
    rng = np.random.default_rng(4000 + seed)
    n_leaves = int(rng.integers(2, 20))
    state = {
        f"l{i}": rng.standard_normal(int(rng.integers(1, 5000))).astype(
            np.float32
        )
        for i in range(n_leaves)
    }
    fail_at = int(rng.integers(0, n_leaves + 2))
    plan = seeded_failure_plan(
        seed, "storage-write", fail_at, exc_msg="injected failure"
    )
    cls, engine = _chaotic_fs(plan)
    path = str(tmp_path / "s")
    crashed = False
    try:
        with patch_storage_plugin(cls):
            ts.Snapshot.take(path, {"m": ts.PyTreeState(dict(state))})
    except OSError:
        crashed = True
    if crashed:
        assert not os.path.exists(os.path.join(path, ".snapshot_metadata"))

    # Replay: a fresh engine over the SAME plan JSON fired at the same
    # op index, so a run that crashed crashes again (the trigger-
    # identity unit pin lives in test_chaos.py — concurrent pipelines
    # may cancel a different suffix of ops after the shared trigger).
    if crashed:
        replay_cls, replay_engine = _chaotic_fs(
            FaultPlan.from_json(plan.to_json())
        )
        with patch_storage_plugin(replay_cls), pytest.raises(OSError):
            ts.Snapshot.take(
                str(tmp_path / "replay"),
                {"m": ts.PyTreeState(dict(state))},
            )
        assert replay_engine.fired[0][2] == engine.fired[0][2] == "fail"

    # Clean retake over whatever partial state the crash left behind.
    ts.Snapshot.take(path, {"m": ts.PyTreeState(dict(state))})
    dst = ts.PyTreeState(
        {f"l{i}": np.zeros_like(state[f"l{i}"]) for i in range(n_leaves)}
    )
    ts.Snapshot(path).restore({"m": dst})
    for i in range(n_leaves):
        np.testing.assert_array_equal(dst.tree[f"l{i}"], state[f"l{i}"])
