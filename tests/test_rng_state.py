"""RngState capture/restore semantics.

Reference parity: tests/test_rng_state.py — taking a snapshot must have no
RNG side effect, and restore must reproduce the checkpointed stream.
"""

from __future__ import annotations

import numpy as np

import jax

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import RngState, Snapshot


def test_raw_key_roundtrip(tmp_path) -> None:
    key = jax.random.PRNGKey(42)
    rng = RngState(key)
    Snapshot.take(str(tmp_path), {"rng": rng})

    # The live key is unchanged by take.
    np.testing.assert_array_equal(np.asarray(rng.keys), np.asarray(key))

    dest = RngState(jax.random.PRNGKey(7))
    Snapshot(str(tmp_path)).restore({"rng": dest})
    np.testing.assert_array_equal(np.asarray(dest.keys), np.asarray(key))
    # Restored key produces the same stream.
    a = jax.random.normal(dest.keys, (4,))
    b = jax.random.normal(key, (4,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_typed_key_roundtrip(tmp_path) -> None:
    key = jax.random.key(123)
    Snapshot.take(str(tmp_path), {"rng": RngState(key)})
    dest = RngState(jax.random.key(0))
    Snapshot(str(tmp_path)).restore({"rng": dest})
    restored = dest.keys
    assert jax.dtypes.issubdtype(restored.dtype, jax.dtypes.prng_key)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(restored)),
        np.asarray(jax.random.key_data(key)),
    )
    a = jax.random.uniform(restored, (3,))
    b = jax.random.uniform(key, (3,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_key_pytree_roundtrip(tmp_path) -> None:
    keys = {
        "data": jax.random.PRNGKey(1),
        "dropout": {"layer0": jax.random.key(2), "layer1": jax.random.key(3)},
    }
    Snapshot.take(str(tmp_path), {"rng": RngState(keys)})
    dest = RngState(
        {
            "data": jax.random.PRNGKey(0),
            "dropout": {"layer0": jax.random.key(0), "layer1": jax.random.key(0)},
        }
    )
    Snapshot(str(tmp_path)).restore({"rng": dest})
    np.testing.assert_array_equal(
        np.asarray(dest.keys["data"]), np.asarray(keys["data"])
    )
    for name in ("layer0", "layer1"):
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(dest.keys["dropout"][name])),
            np.asarray(jax.random.key_data(keys["dropout"][name])),
        )


def test_rng_saved_alongside_other_state(tmp_path) -> None:
    """At most one RngState rides with arbitrary app state; the combined
    snapshot round-trips both (reference snapshot.py:340-346)."""
    key = jax.random.PRNGKey(5)
    params = ts.StateDict(w=np.arange(8, dtype=np.float32))
    Snapshot.take(str(tmp_path), {"rng": RngState(key), "params": params})

    dest_params = ts.StateDict(w=np.zeros(8, dtype=np.float32))
    dest_rng = RngState(jax.random.PRNGKey(0))
    Snapshot(str(tmp_path)).restore({"rng": dest_rng, "params": dest_params})
    np.testing.assert_array_equal(dest_params["w"], params["w"])
    np.testing.assert_array_equal(np.asarray(dest_rng.keys), np.asarray(key))


def test_rngstate_alias() -> None:
    assert ts.RNGState is ts.RngState
