"""Elastic fan-out restore: cross-world elasticity through the native
``Snapshot.restore`` path (no bridge), and the single-reader fan-out
distribution — exactly one storage read per unique saved shard, peers
fed over the coordination store, kill-switch parity with the
every-rank-reads fallback.

World-2 snapshots are synthesized by taking a sharded snapshot at
world 1 and splitting its ShardedArray shards across two rank
manifests (the exact on-disk shape a real 2-process take commits:
same blobs, same entry schema, ``world_size: 2``) — the CPU test
backend cannot host one jax array spanning two processes, but the
restore path only ever sees the committed manifest either way.
"""

import collections
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.knobs import override_max_shard_size_bytes
from torchsnapshot_tpu.manifest import (
    ShardedArrayEntry,
    SnapshotMetadata,
    is_container_entry,
    sharded_blob_windows,
)
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.resharding import assign_shard_owners
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import (
    patch_storage_plugin,
    run_multiprocess,
)

SNAPSHOT_METADATA_FNAME = ".snapshot_metadata"


def _mesh(n, name="x"):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, backend has {len(devs)}")
    return Mesh(np.array(devs[:n]), (name,))


def _take_sharded(path, rows=32, cols=8, ways=4, max_shard_bytes=None):
    """World-1 snapshot of one row-sharded array; returns the data."""
    x = jnp.arange(rows * cols, dtype=jnp.float32).reshape(rows, cols)
    xs = jax.device_put(x, NamedSharding(_mesh(ways), P("x")))
    if max_shard_bytes is not None:
        with override_max_shard_size_bytes(max_shard_bytes):
            ts.Snapshot.take(str(path), {"m": ts.PyTreeState({"w": xs})})
    else:
        ts.Snapshot.take(str(path), {"m": ts.PyTreeState({"w": xs})})
    return np.asarray(x)


def _split_to_world2(path) -> None:
    """Rewrite a world-1 snapshot's metadata as the world-2 equivalent:
    ShardedArray shards alternate between rank manifests (so both rank
    views are non-trivial), containers are duplicated per rank — the
    shape a real 2-process take commits. Blobs are untouched."""
    snap = ts.Snapshot(str(path))
    md = snap.metadata
    new_manifest = {}
    for key, entry in md.manifest.items():
        rank_str, _, logical = key.partition("/")
        assert rank_str == "0", "expected a world-1 snapshot"
        if isinstance(entry, ShardedArrayEntry) and len(entry.shards) > 1:
            new_manifest[key] = ShardedArrayEntry(
                dtype=entry.dtype, shape=entry.shape, shards=entry.shards[0::2]
            )
            new_manifest[f"1/{logical}"] = ShardedArrayEntry(
                dtype=entry.dtype, shape=entry.shape, shards=entry.shards[1::2]
            )
        else:
            new_manifest[key] = entry
            if is_container_entry(entry):
                new_manifest[f"1/{logical}"] = entry
    doc = SnapshotMetadata(
        version=md.version, world_size=2, manifest=new_manifest
    )
    with open(os.path.join(str(path), SNAPSHOT_METADATA_FNAME), "w") as f:
        f.write(doc.to_json())


# ---------------------------------------------------------------------------
# Cross-world elasticity through the native restore path (no bridge)
# ---------------------------------------------------------------------------


def test_world2_snapshot_restores_at_world1(tmp_path) -> None:
    """A checkpoint saved at world=2 restores correctly at world=1:
    rank 0's per-rank view merges the peer manifest's shards."""
    data = _take_sharded(tmp_path, ways=4)
    _split_to_world2(tmp_path)
    snap = ts.Snapshot(str(tmp_path))
    assert snap.metadata.world_size == 2

    dest = jax.device_put(
        jnp.zeros(data.shape, jnp.float32),
        NamedSharding(_mesh(8), P("x")),
    )
    fresh = {"m": ts.PyTreeState({"w": dest})}
    snap.restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["m"].tree["w"]), data)


def test_world2_snapshot_restores_into_numpy_at_world1(tmp_path) -> None:
    data = _take_sharded(tmp_path, ways=4)
    _split_to_world2(tmp_path)
    fresh = {"m": ts.PyTreeState({"w": np.zeros(data.shape, np.float32)})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    np.testing.assert_array_equal(fresh["m"].tree["w"], data)


def test_world2_uneven_snapshot_restores_at_world1(tmp_path) -> None:
    """Misaligned splits across the world boundary: 6-row saved shards
    vs 10-row destination boxes — every destination draws from two
    saved shards owned by different manifest ranks."""
    data = _take_sharded(tmp_path, rows=30, cols=3, ways=5)
    _split_to_world2(tmp_path)
    dest = jax.device_put(
        jnp.zeros(data.shape, jnp.float32),
        NamedSharding(_mesh(3), P("x")),
    )
    fresh = {"m": ts.PyTreeState({"w": dest})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["m"].tree["w"]), data)


def test_read_object_with_target_sharding(tmp_path) -> None:
    """Template-free reshard-on-read: place one saved entry directly
    under an arbitrary target sharding at a different world size."""
    data = _take_sharded(tmp_path, ways=4)
    _split_to_world2(tmp_path)
    target = NamedSharding(_mesh(8), P("x", None))
    out = ts.Snapshot(str(tmp_path)).read_object("0/m/w", sharding=target)
    assert out.sharding.is_equivalent_to(target, 2)
    np.testing.assert_array_equal(np.asarray(out), data)
    # obj_out and sharding define conflicting destinations: loud error,
    # never a silently-unfilled obj_out.
    with pytest.raises(ValueError, match="not both"):
        ts.Snapshot(str(tmp_path)).read_object(
            "0/m/w", obj_out=np.zeros_like(data), sharding=target
        )


def _worker_restore_world1_at_world2(pg, path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    if pg.rank == 0:
        x = jnp.arange(32 * 8, dtype=jnp.float32).reshape(32, 8)
        sharding = NamedSharding(
            Mesh(np.array(jax.devices()[:4]), ("x",)), P("x")
        )
        xs = jax.device_put(x, sharding)
        ts.Snapshot.take(path, {"m": ts.PyTreeState({"w": xs})})
    PGWrapper(pg).barrier()
    dest = {"m": ts.PyTreeState({"w": jnp.zeros((32, 8), jnp.float32)})}
    ts.Snapshot(path, pg=pg).restore(dest)
    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["w"]),
        np.arange(32 * 8, dtype=np.float32).reshape(32, 8),
    )


def test_world1_snapshot_restores_at_world2(tmp_path) -> None:
    """...and vice versa: a world-1 snapshot restores under a 2-process
    group (every rank materializes the full array)."""
    run_multiprocess(
        _worker_restore_world1_at_world2, nproc=2, args=(str(tmp_path),)
    )


def _worker_restore_world2_resharded(pg, path):
    """World-2 snapshot restored at world 2 under a DIFFERENT sharding
    (column-wise vs the saved row shards), fan-out on."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts

    os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = "1"
    sharding = NamedSharding(
        Mesh(np.array(jax.devices()[:4]), ("x",)), P(None, "x")
    )
    dest = {
        "m": ts.PyTreeState(
            {"w": jax.device_put(jnp.zeros((32, 8), jnp.float32), sharding)}
        )
    }
    ts.Snapshot(path, pg=pg).restore(dest)
    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["w"]),
        np.arange(32 * 8, dtype=np.float32).reshape(32, 8),
    )


def test_world2_snapshot_resharded_at_world2(tmp_path) -> None:
    _take_sharded(tmp_path, ways=4)
    _split_to_world2(tmp_path)
    run_multiprocess(
        _worker_restore_world2_resharded, nproc=2, args=(str(tmp_path),)
    )


def test_replicated_to_sharded_and_back(tmp_path) -> None:
    """Replication transitions: a replicated save restores into a
    sharded destination, and a sharded save into a fully-replicated
    one (the reshard-on-read degenerate cases)."""
    x = jnp.arange(16 * 6, dtype=jnp.float32).reshape(16, 6)
    mesh = _mesh(8)
    replicated = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("x"))

    rep_path = tmp_path / "rep"
    xs = jax.device_put(x, replicated)
    ts.Snapshot.take(str(rep_path), {"m": ts.PyTreeState({"w": xs})})
    fresh = {
        "m": ts.PyTreeState({"w": jax.device_put(jnp.zeros((16, 6)), row)})
    }
    ts.Snapshot(str(rep_path)).restore(fresh)
    w = fresh["m"].tree["w"]
    assert w.sharding.is_equivalent_to(row, 2)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(x))

    shard_path = tmp_path / "shard"
    xs = jax.device_put(x, row)
    ts.Snapshot.take(str(shard_path), {"m": ts.PyTreeState({"w": xs})})
    fresh = {
        "m": ts.PyTreeState(
            {"w": jax.device_put(jnp.zeros((16, 6)), replicated)}
        )
    }
    ts.Snapshot(str(shard_path)).restore(fresh)
    w = fresh["m"].tree["w"]
    assert w.sharding.is_equivalent_to(replicated, 2)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(x))


# ---------------------------------------------------------------------------
# Fan-out distribution: one plugin read per unique saved shard
# ---------------------------------------------------------------------------


class _CountingFS(FSStoragePlugin):
    """Records every inner-plugin read (path, byte_range) — the
    instrumentation the one-read-per-shard pin counts. Class-level so a
    worker process accumulates across plugin instances."""

    reads = []  # noqa: RUF012 - per-process accumulator by design

    async def read(self, read_io):
        type(self).reads.append((read_io.path, read_io.byte_range))
        await super().read(read_io)

    async def read_with_checksum(self, read_io):
        type(self).reads.append((read_io.path, read_io.byte_range))
        return await super().read_with_checksum(read_io)


def _worker_fanout_counted(pg, path, fanout):
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu import telemetry
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = "1" if fanout else "0"
    _CountingFS.reads = []
    dest = {"m": ts.PyTreeState({"w": jnp.zeros((32, 8), jnp.float32)})}
    with patch_storage_plugin(_CountingFS):
        ts.Snapshot(path, pg=pg).restore(dest)
    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["w"]),
        np.arange(32 * 8, dtype=np.float32).reshape(32, 8),
    )
    sharded_reads = [p for p, _ in _CountingFS.reads if "sharded/" in p]
    report = telemetry.last_report("restore", path=path)
    assert report is not None
    all_reads = PGWrapper(pg).all_gather_object(sharded_reads)
    return {
        "rank": pg.rank,
        "sharded_reads": sharded_reads,
        "all_sharded_reads": [p for reads in all_reads for p in reads],
        "bytes_fetched": report.bytes_fetched,
        "bytes_received": report.bytes_received,
        "bytes_needed": report.bytes_needed,
    }


def test_fanout_fetches_each_unique_shard_exactly_once(tmp_path) -> None:
    """With fan-out on in a 2-proc restore, each unique saved shard is
    fetched from the storage plugin exactly once ACROSS the fleet, the
    non-owner side of every rank's ledger shows bytes_fetched <
    bytes_needed with the gap arriving as bytes_received, and the
    restored bytes are identical to the fallback's."""
    data = _take_sharded(tmp_path, ways=4)
    snap = ts.Snapshot(str(tmp_path))
    expected_locs = sorted(sharded_blob_windows(snap.metadata.manifest))
    assert len(expected_locs) == 4
    owners = assign_shard_owners(expected_locs, 2)
    assert set(owners.values()) == {0, 1}, "both ranks should own shards"

    rows = run_multiprocess(
        _worker_fanout_counted, nproc=2, args=(str(tmp_path), True)
    )
    counts = collections.Counter(rows[0]["all_sharded_reads"])
    assert sorted(counts) == expected_locs
    assert all(c == 1 for c in counts.values()), counts
    needed = data.size * data.itemsize
    for row in rows:
        assert row["bytes_needed"] == needed
        # Each rank owns only part of the shard set: the rest arrived
        # from its peer, not from storage.
        assert row["bytes_fetched"] < row["bytes_needed"], row
        assert row["bytes_received"] > 0
        assert row["bytes_fetched"] + row["bytes_received"] >= needed


def test_fanout_kill_switch_restores_every_rank_reads(tmp_path) -> None:
    """TORCHSNAPSHOT_TPU_FANOUT_RESTORE=0: every rank fetches every
    shard itself (2 reads per unique shard at world 2), nothing is
    received from peers, and the restored bytes match."""
    _take_sharded(tmp_path, ways=4)
    rows = run_multiprocess(
        _worker_fanout_counted, nproc=2, args=(str(tmp_path), False)
    )
    counts = collections.Counter(rows[0]["all_sharded_reads"])
    assert len(counts) == 4
    assert all(c == 2 for c in counts.values()), counts
    for row in rows:
        assert not row["bytes_received"]
        assert row["bytes_fetched"] >= row["bytes_needed"]


def _worker_fanout_async(pg, path):
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts

    os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = "1"
    _CountingFS.reads = []
    dest = {"m": ts.PyTreeState({"w": jnp.zeros((32, 8), jnp.float32)})}
    with patch_storage_plugin(_CountingFS):
        pending = ts.Snapshot(path, pg=pg).async_restore(dest)
        pending.wait()
    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["w"]),
        np.arange(32 * 8, dtype=np.float32).reshape(32, 8),
    )
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    sharded_reads = [p for p, _ in _CountingFS.reads if "sharded/" in p]
    all_reads = PGWrapper(pg).all_gather_object(sharded_reads)
    return [p for reads in all_reads for p in reads]


def test_fanout_async_restore_single_read_per_shard(tmp_path) -> None:
    """async_restore fans out too: the exchange runs on the calling
    thread (collective ordering), the background pipeline reads from
    the exchanged cache."""
    _take_sharded(tmp_path, ways=4)
    rows = run_multiprocess(
        _worker_fanout_async, nproc=2, args=(str(tmp_path),)
    )
    counts = collections.Counter(rows[0])
    assert len(counts) == 4
    assert all(c == 1 for c in counts.values()), counts


def _worker_fanout_uneven(pg, path):
    import jax.numpy as jnp
    import numpy as np

    import torchsnapshot_tpu as ts

    os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = "1"
    sharding = NamedSharding(
        Mesh(np.array(jax.devices()[:3]), ("x",)), P("x")
    )
    dest = {
        "m": ts.PyTreeState(
            {"w": jax.device_put(jnp.zeros((30, 3), jnp.float32), sharding)}
        )
    }
    ts.Snapshot(path, pg=pg).restore(dest)
    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["w"]),
        np.arange(30 * 3, dtype=np.float32).reshape(30, 3),
    )


def test_fanout_handles_uneven_shards(tmp_path) -> None:
    """6-row saved shards, 10-row destination boxes, split manifests:
    the fan-out byte windows are partial row bands of the saved blobs."""
    _take_sharded(tmp_path, rows=30, cols=3, ways=5)
    _split_to_world2(tmp_path)
    run_multiprocess(_worker_fanout_uneven, nproc=2, args=(str(tmp_path),))


def _worker_fanout_owner_read_failure(pg, path):
    import time

    import jax.numpy as jnp

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.test_utils import faulty_fs_plugin

    os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = "1"
    # Every rank's sharded reads fail: whichever rank owns a shard
    # fails its exchange fetch; the error marker must reach the peer
    # within the round instead of stranding it to the store timeout.
    Faulty = faulty_fs_plugin(
        lambda p: "sharded/" in p, ops=("read",), exc_msg="injected"
    )
    dest = {"m": ts.PyTreeState({"w": jnp.zeros((32, 8), jnp.float32)})}
    t0 = time.monotonic()
    with patch_storage_plugin(Faulty), pytest.raises(Exception):
        ts.Snapshot(path, pg=pg).restore(dest)
    assert time.monotonic() - t0 < 60.0, "peer blocked to store timeout"


def test_fanout_owner_read_failure_fails_fast(tmp_path) -> None:
    _take_sharded(tmp_path, ways=4)
    run_multiprocess(
        _worker_fanout_owner_read_failure, nproc=2, args=(str(tmp_path),)
    )


def test_fanout_failed_round_leaves_no_store_keys() -> None:
    """The store-key teardown discipline on the ERROR path (snaplint's
    store-key-leak class): when an owner's fetch fails, the surviving
    peer consumes the error marker AND reaps the window it had already
    published for the failed rank — a failed round leaves zero keys
    under its nonce prefix, same as a successful one."""
    import asyncio
    import threading

    from torchsnapshot_tpu.dist_store import InProcessStore
    from torchsnapshot_tpu.fanout import FanoutError, FanoutRestoreContext
    from torchsnapshot_tpu.io_types import ReadReq

    store = InProcessStore()
    owners = {"sharded/a": 0, "sharded/b": 1}
    windows = {"sharded/a": (0, 8), "sharded/b": (0, 8)}

    class _Storage:
        def __init__(self, fail_path):
            self.fail_path = fail_path

        async def read(self, read_io):
            if read_io.path == self.fail_path:
                raise RuntimeError("injected owner read failure")
            read_io.buf = memoryview(b"x" * 8)

    errors = {}

    def _rank(rank, need, fail_path):
        ctx = FanoutRestoreContext(owners, windows, store, rank, 2)
        loop = asyncio.new_event_loop()
        try:
            ctx.exchange(
                [ReadReq(path=need, buffer_consumer=None)],
                _Storage(fail_path),
                loop,
                "nonce",
                timeout=30.0,
            )
        except BaseException as e:  # noqa: BLE001 - collected per rank
            errors[rank] = e
        finally:
            loop.close()

    # Rank 0 owns blob a (needed by rank 1) and its fetch fails; rank 1
    # owns blob b (needed by rank 0) and publishes it successfully.
    t0 = threading.Thread(target=_rank, args=(0, "sharded/b", "sharded/a"))
    t1 = threading.Thread(target=_rank, args=(1, "sharded/a", "sharded/a"))
    t0.start(), t1.start()
    t0.join(60), t1.join(60)

    assert isinstance(errors.get(0), RuntimeError)
    assert isinstance(errors.get(1), FanoutError)
    assert store.scan("nonce") == []


# ---------------------------------------------------------------------------
# Owner assignment unit pins
# ---------------------------------------------------------------------------


def test_assign_shard_owners_is_deterministic_and_balanced() -> None:
    locs = [f"sharded/m/w_{i * 8}_0" for i in range(8)]
    table = assign_shard_owners(locs, 4)
    assert table == assign_shard_owners(list(reversed(locs)), 4)
    counts = collections.Counter(table.values())
    # Round-robin over sorted locations: perfectly balanced here.
    assert all(c == 2 for c in counts.values())
    assert assign_shard_owners([], 4) == {}
    assert set(assign_shard_owners(locs, 1).values()) == {0}


def test_sharded_blob_windows_shape(tmp_path) -> None:
    _take_sharded(tmp_path, ways=4)
    manifest = ts.Snapshot(str(tmp_path)).metadata.manifest
    windows = sharded_blob_windows(manifest)
    assert len(windows) == 4
    for loc, (lo, hi) in windows.items():
        assert "sharded/" in loc
        assert lo == 0
        assert hi == 8 * 8 * 4  # 8 rows x 8 cols x f32 per 4-way shard


def test_fanout_report_fields_absent_without_fanout(tmp_path) -> None:
    """A single-process restore still reports bytes_fetched ~= needed
    (the amplification denominator) and no received bytes."""
    data = _take_sharded(tmp_path, ways=4)
    dest = {"m": ts.PyTreeState({"w": jnp.zeros((32, 8), jnp.float32)})}
    ts.Snapshot(str(tmp_path)).restore(dest)
    report = telemetry.last_report("restore", path=str(tmp_path))
    assert report is not None
    assert report.bytes_needed == data.size * data.itemsize
    assert report.bytes_fetched >= report.bytes_needed
    assert not report.bytes_received
    np.testing.assert_array_equal(np.asarray(dest["m"].tree["w"]), data)
