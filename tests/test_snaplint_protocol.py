"""Protocol model checker tests: extractor pins (key-template
normalization, cross-module writer/reader resolution, the namespace
table), a bad/fixed fixture pair per protocol rule, the suppression and
baseline round-trips, CLI surfaces (``--protocol``, ``--protocol-dump``,
``--jobs``), and the clean-on-HEAD lane pins that keep the shipped
baseline for the family empty.

Fixtures live under ``tmp_path/torchsnapshot_tpu/`` because the
protocol rules are project-level over the *package*: the model
extractor sweeps every module under that prefix (with a disk fallback),
exactly like the names-lint rules.
"""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.snaplint import Analyzer  # noqa: E402
from tools.snaplint.core import load_project, write_baseline  # noqa: E402
from tools.snaplint.core import load_baseline  # noqa: E402
from tools.snaplint.protocol import PROTOCOL_RULE_NAMES  # noqa: E402
from tools.snaplint.protocol import model as pm  # noqa: E402


def _write_pkg(tmp_path, files):
    pkg = tmp_path / "torchsnapshot_tpu"
    pkg.mkdir(exist_ok=True)
    for relname, source in files.items():
        path = pkg / relname
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    return pkg


def _run(tmp_path, files, rule, baseline=None):
    pkg = _write_pkg(tmp_path, files)
    analyzer = Analyzer(root=tmp_path, select=[rule])
    return analyzer.run([pkg], baseline=baseline)


def _model(tmp_path, files):
    pkg = _write_pkg(tmp_path, files)
    return pm.get_model(load_project([pkg], tmp_path))


def _messages(result):
    return [f.message for f in result.new_findings]


# ---------------------------------------------------------------------------
# model extractor pins
# ---------------------------------------------------------------------------


def test_extractor_normalizes_fstring_format_and_concat_keys(tmp_path):
    mdl = _model(
        tmp_path,
        {
            "mod.py": """
PREFIX = "__fam"

def writes(store, topic, seq):
    store.set(f"{PREFIX}/{topic}/head", b"1")
    store.set("__fam/{}/announce/{}".format(topic, seq), b"2")
    store.set(PREFIX + "/" + topic + "/tail", b"3")
"""
        },
    )
    templates = {s.template for s in mdl.key_sites}
    assert "__fam/{*}/head" in templates
    assert "__fam/{*}/announce/{*}" in templates
    assert "__fam/{*}/tail" in templates


def test_extractor_resolves_cross_module_key_helpers(tmp_path):
    """A single-return key helper in one module normalizes call sites in
    ANOTHER module to the same template — writer and reader resolve to
    one family."""
    mdl = _model(
        tmp_path,
        {
            "keys.py": """
TOPIC_PREFIX = "__topic"

def head_key(topic):
    return f"{TOPIC_PREFIX}/{topic}/head"
""",
            "writer.py": """
from .keys import head_key

def publish(store, topic):
    store.set(head_key(topic), b"1")
""",
            "reader.py": """
from .keys import head_key

def wait(store, topic):
    return store.get(head_key(topic), 5.0)
""",
        },
    )
    fams = mdl.families()
    sites = fams["__topic/{*}/head"]
    assert {s.role for s in sites} == {"set", "wait"}
    assert {s.relpath for s in sites} == {
        "torchsnapshot_tpu/writer.py",
        "torchsnapshot_tpu/reader.py",
    }


def test_extractor_namespace_table_and_dump_schema(tmp_path):
    mdl = _model(
        tmp_path,
        {
            "mod.py": """
def go(store, r):
    store.set(f"__alpha/{r}/x", b"1")
    store.delete(f"__alpha/{r}/x")
    store.set("__beta/flag", b"1")
    store.delete("__beta/flag")
    store.set(f"unprefixed/{r}", b"1")
    store.delete(f"unprefixed/{r}")
"""
        },
    )
    # Only dunder first segments are namespaces (caller-scoped prefixes
    # like barrier/fanout nonces are not).
    assert mdl.namespaces() == ["__alpha", "__beta"]
    dump = mdl.as_dict()
    for key in (
        "version",
        "namespaces",
        "key_families",
        "opaque_deletes",
        "rpc_ops",
        "declared_rpc_ops",
        "crashpoints",
    ):
        assert key in dump, key


def test_extractor_store_annotated_params_count_as_store(tmp_path):
    """The bootstrap idiom: ``base: Store`` / ``kv: Store`` receivers
    are store traffic even though the name has no 'store' in it."""
    mdl = _model(
        tmp_path,
        {
            "mod.py": """
from .dist_store import Store

def bootstrap(kv: Store, rank):
    kv.set("__boot/addr", b"hp")

def unrelated(d, rank):
    d.set("not/a/store/key", b"1")
"""
        },
    )
    templates = {s.template for s in mdl.key_sites}
    assert "__boot/addr" in templates
    assert not any("not/a" in t for t in templates)


# ---------------------------------------------------------------------------
# store-key-leak
# ---------------------------------------------------------------------------

_LEAK_BAD = {
    "mod.py": """
def publish(store, topic, seq):
    store.set(f"__t/{topic}/announce/{seq}", b"1")
"""
}

# The fix shape: a delete somewhere in the project covers the family —
# here in a DIFFERENT module, resolved cross-module.
_LEAK_FIXED = {
    "mod.py": """
def publish(store, topic, seq):
    store.set(f"__t/{topic}/announce/{seq}", b"1")
""",
    "reaper.py": """
def reap(store, topic, seq):
    store.delete(f"__t/{topic}/announce/{seq}")
""",
}


def test_store_key_leak_detects_and_accepts_cross_module_fix(tmp_path):
    bad = _run(tmp_path, _LEAK_BAD, "store-key-leak")
    assert len(bad.new_findings) == 1
    assert "__t/{*}/announce/{*}" in bad.new_findings[0].message
    fixed = _run(tmp_path, _LEAK_FIXED, "store-key-leak")
    assert fixed.new_findings == []


def test_store_key_leak_opaque_delete_in_module_excuses(tmp_path):
    """An untraceable delete (computed key list) in the same module is
    conservative cover: the analyzer cannot prove the leak."""
    result = _run(
        tmp_path,
        {
            "mod.py": """
def round_trip(store, prefix, keys):
    store.set(f"__r/{prefix}/data", b"1")
    store.multi_delete(keys)
"""
        },
        "store-key-leak",
    )
    assert result.new_findings == []


def test_store_key_leak_inline_suppression(tmp_path):
    result = _run(
        tmp_path,
        {
            "mod.py": """
def register(store, service, rank):
    # Registry semantics: survivors stay discoverable for the run.
    # snaplint: disable=store-key-leak
    store.set(f"__reg/{service}/{rank}", b"hp")
"""
        },
        "store-key-leak",
    )
    assert result.new_findings == []
    assert len(result.suppressed) == 1


def test_store_key_leak_baseline_round_trip(tmp_path):
    bad = _run(tmp_path, _LEAK_BAD, "store-key-leak")
    assert len(bad.new_findings) == 1
    baseline_file = tmp_path / "baseline.json"
    write_baseline(baseline_file, bad.new_findings)
    again = _run(
        tmp_path,
        _LEAK_BAD,
        "store-key-leak",
        baseline=load_baseline(baseline_file),
    )
    assert again.new_findings == [] and len(again.findings) == 1


# ---------------------------------------------------------------------------
# rank-asymmetric-protocol
# ---------------------------------------------------------------------------

_ASYM_KNOB_BAD = {
    "mod.py": """
from torchsnapshot_tpu import knobs

def publish(store, sess):
    if knobs.is_cdn_enabled():
        store.set(f"__sess/{sess}/ready", b"1")
    store.delete(f"__sess/{sess}/ready")

def wait(store, sess):
    return store.get(f"__sess/{sess}/ready", 5.0)
"""
}

_ASYM_KNOB_FIXED = {
    "mod.py": """
from torchsnapshot_tpu import knobs

def publish(store, sess):
    store.set(f"__sess/{sess}/ready", b"1")
    store.delete(f"__sess/{sess}/ready")

def wait(store, sess):
    return store.get(f"__sess/{sess}/ready", 5.0)
"""
}


def test_rank_asym_knob_guarded_set_with_unguarded_wait(tmp_path):
    bad = _run(tmp_path, _ASYM_KNOB_BAD, "rank-asymmetric-protocol")
    assert len(bad.new_findings) == 1
    assert "knob/env guard" in bad.new_findings[0].message
    fixed = _run(tmp_path, _ASYM_KNOB_FIXED, "rank-asymmetric-protocol")
    assert fixed.new_findings == []


_ASYM_CHAIN_BAD = {
    "mod.py": """
def _commit_metadata(store, rank, world):
    store.barrier("commit", rank, world)

def save(store, rank, world):
    if rank == 0:
        _commit_metadata(store, rank, world)
"""
}

_ASYM_CHAIN_FIXED = {
    "mod.py": """
def _commit_metadata(store, rank, world):
    store.barrier("commit", rank, world)

def save(store, rank, world):
    _commit_metadata(store, rank, world)
"""
}


def test_rank_asym_collective_reached_through_call_chain(tmp_path):
    """The PR 2 bug class across a function boundary: the direct rule
    cannot see it (the collective itself is unconditional inside the
    helper), the model's call graph can."""
    direct = _run(tmp_path, _ASYM_CHAIN_BAD, "collective-under-conditional")
    assert direct.new_findings == []
    bad = _run(tmp_path, _ASYM_CHAIN_BAD, "rank-asymmetric-protocol")
    assert len(bad.new_findings) == 1
    assert "_commit_metadata" in bad.new_findings[0].message
    fixed = _run(tmp_path, _ASYM_CHAIN_FIXED, "rank-asymmetric-protocol")
    assert fixed.new_findings == []


def test_rank_asym_ambiguous_callee_names_do_not_convict(tmp_path):
    """`get`-shaped names defined more than once never enter the call
    graph — a name-based edge through them would convict half the
    codebase."""
    result = _run(
        tmp_path,
        {
            "a.py": """
def helper(store, rank, world):
    store.barrier("x", rank, world)
""",
            "b.py": """
def helper(value):
    return value

def save(store, rank, world):
    if rank == 0:
        helper(store)
""",
        },
        "rank-asymmetric-protocol",
    )
    assert result.new_findings == []


# ---------------------------------------------------------------------------
# wait-without-error-poll
# ---------------------------------------------------------------------------

_WAIT_BAD = {
    "mod.py": """
import time

def wait(store, key):
    while True:
        val = store.try_get(key)
        if val is not None:
            return val
        time.sleep(0.05)
"""
}

# Two blessed shapes: poll the round's error key in the same batched
# read, or ride the shared exponential pacer.
_WAIT_FIXED_ERROR = {
    "mod.py": """
import time

def wait(store, key, prefix):
    while True:
        got = store.multi_get([key, f"{prefix}/error"])
        if got.get(key) is not None:
            return got[key]
        time.sleep(0.05)
"""
}

_WAIT_FIXED_PACER = {
    "mod.py": """
def wait(store, key, pacer, deadline):
    while True:
        val = store.try_get(key)
        if val is not None:
            return val
        pacer.sleep(deadline)
"""
}


def test_wait_without_error_poll_detects_and_accepts_fixes(tmp_path):
    bad = _run(tmp_path, _WAIT_BAD, "wait-without-error-poll")
    assert len(bad.new_findings) == 1
    assert "error key" in bad.new_findings[0].message
    for fixed in (_WAIT_FIXED_ERROR, _WAIT_FIXED_PACER):
        assert _run(tmp_path, fixed, "wait-without-error-poll").new_findings == []


# ---------------------------------------------------------------------------
# rpc-unpaired
# ---------------------------------------------------------------------------

_RPC_BAD = {
    "client.py": """
from .telemetry import names as metric_names

class Client:
    def request(self, cmd, *args):
        return None

    def evict(self, step):
        return self.request(metric_names.RPC_TIER_EVICT, step)
""",
    "server.py": """
from .telemetry import names as metric_names

def dispatch(cmd, args):
    if cmd == metric_names.RPC_TIER_PUSH:
        return "pushed"
    return None
""",
}

_RPC_FIXED = {
    "client.py": """
from .telemetry import names as metric_names

class Client:
    def request(self, cmd, *args):
        return None

    def evict(self, step):
        return self.request(metric_names.RPC_TIER_EVICT, step)

    def push(self, step):
        return self.request(metric_names.RPC_TIER_PUSH, step)
""",
    "server.py": """
from .telemetry import names as metric_names

def dispatch(cmd, args):
    if cmd == metric_names.RPC_TIER_PUSH:
        return "pushed"
    if cmd == metric_names.RPC_TIER_EVICT:
        return "evicted"
    return None
""",
}


def test_rpc_unpaired_both_directions_and_fix(tmp_path):
    bad = _run(tmp_path, _RPC_BAD, "rpc-unpaired")
    msgs = _messages(bad)
    assert len(msgs) == 2
    assert any("RPC_TIER_EVICT" in m and "no server dispatch" in m for m in msgs)
    assert any("RPC_TIER_PUSH" in m and "no client call site" in m for m in msgs)
    fixed = _run(tmp_path, _RPC_FIXED, "rpc-unpaired")
    assert fixed.new_findings == []


_FRAME_BAD = {
    "mod.py": """
from .framing import send_frame, recv_frame

def talk(sock, payload):
    send_frame(sock, payload)
    return recv_frame(sock)
"""
}

_FRAME_FIXED = {
    "mod.py": """
from .framing import send_frame, recv_frame
from .telemetry import wire
from .telemetry import names as metric_names

def talk(sock, payload):
    with wire.propagate(metric_names.RPC_TIER_PUSH):
        send_frame(sock, payload)
        return recv_frame(sock)

def serve(sock, ctx):
    wire.set_received_context(ctx)
    send_frame(sock, b"reply")
""",
    "server2.py": """
from .telemetry import names as metric_names

class Client:
    def request(self, cmd):
        return None

    def push(self):
        return self.request(metric_names.RPC_TIER_PUSH)

def dispatch(cmd):
    if cmd == metric_names.RPC_TIER_PUSH:
        return True
""",
}


def test_rpc_frames_outside_propagate_scope(tmp_path):
    bad = _run(tmp_path, _FRAME_BAD, "rpc-unpaired")
    msgs = _messages(bad)
    assert len(msgs) == 2  # send + recv
    assert all("wire.propagate" in m for m in msgs)
    # In a propagate scope (client) or adopting the received context
    # (server): invisible-to-observatory findings clear.
    fixed = _run(tmp_path, _FRAME_FIXED, "rpc-unpaired")
    assert fixed.new_findings == []


# ---------------------------------------------------------------------------
# commit-ordering
# ---------------------------------------------------------------------------

_ORDER_BAD_MARKER_FIRST = {
    "mod.py": """
def publish(store, topic, seq):
    store.set(f"__t/{topic}/head", str(seq).encode())
    store.set(f"__t/{topic}/announce/{seq}", b"payload")
    store.delete(f"__t/{topic}/head")
    store.delete(f"__t/{topic}/announce/{seq}")
"""
}

_ORDER_BAD_NO_CRASHPOINT = {
    "mod.py": """
def publish(store, topic, seq):
    store.set(f"__t/{topic}/announce/{seq}", b"payload")
    store.set(f"__t/{topic}/head", str(seq).encode())
    store.delete(f"__t/{topic}/head")
    store.delete(f"__t/{topic}/announce/{seq}")
"""
}

_ORDER_FIXED = {
    "mod.py": """
from .chaos import crashpoint
from .telemetry import names as metric_names

def publish(store, topic, seq):
    store.set(f"__t/{topic}/announce/{seq}", b"payload")
    crashpoint(metric_names.CRASH_PUBLISH_ANNOUNCED)
    store.set(f"__t/{topic}/head", str(seq).encode())
    store.delete(f"__t/{topic}/head")
    store.delete(f"__t/{topic}/announce/{seq}")
""",
    "telemetry/names.py": """
CRASH_PUBLISH_ANNOUNCED = "publish_announced"
""",
}


def test_commit_ordering_marker_before_payload(tmp_path):
    bad = _run(tmp_path, _ORDER_BAD_MARKER_FIRST, "commit-ordering")
    assert len(bad.new_findings) == 1
    assert "written before payload" in bad.new_findings[0].message


def test_commit_ordering_marker_last_needs_crashpoint(tmp_path):
    bad = _run(tmp_path, _ORDER_BAD_NO_CRASHPOINT, "commit-ordering")
    assert len(bad.new_findings) == 1
    assert "no crashpoint()" in bad.new_findings[0].message
    fixed = _run(tmp_path, _ORDER_FIXED, "commit-ordering")
    assert fixed.new_findings == []


def test_commit_ordering_flags_unthreaded_crash_declaration(tmp_path):
    result = _run(
        tmp_path,
        {
            "telemetry/names.py": """
CRASH_NEVER_THREADED = "never_threaded"
"""
        },
        "commit-ordering",
    )
    assert len(result.new_findings) == 1
    finding = result.new_findings[0]
    assert finding.path == "torchsnapshot_tpu/telemetry/names.py"
    assert "CRASH_NEVER_THREADED" in finding.message


# ---------------------------------------------------------------------------
# store-namespace-docs
# ---------------------------------------------------------------------------


def _run_ns_docs(tmp_path, table_rows):
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    lines = ["# scaling", "", "| namespace | owner |", "|---|---|"]
    lines += [f"| `{ns}/...` | x |" for ns in table_rows]
    (tmp_path / "docs" / "scaling.md").write_text("\n".join(lines) + "\n")
    return _run(
        tmp_path,
        {
            "mod.py": """
def go(store, r):
    store.set(f"__real/{r}", b"1")
    store.delete(f"__real/{r}")
"""
        },
        "store-namespace-docs",
    )


def test_namespace_docs_sync_both_directions(tmp_path):
    missing = _run_ns_docs(tmp_path, [])
    assert len(missing.new_findings) == 1
    assert "'__real/' is used in the code but missing" in (
        missing.new_findings[0].message
    )

    stale = _run_ns_docs(tmp_path, ["__real", "__ghost"])
    assert len(stale.new_findings) == 1
    assert "'__ghost/'" in stale.new_findings[0].message

    in_sync = _run_ns_docs(tmp_path, ["__real"])
    assert in_sync.new_findings == []


# ---------------------------------------------------------------------------
# performance satellites: shared parse cache + --jobs parity
# ---------------------------------------------------------------------------


def test_jobs_parallel_findings_match_serial(tmp_path):
    """``--jobs N`` must be a pure speedup: identical findings, same
    order, over a project with violations for several rule families."""
    files = dict(_LEAK_BAD)
    files.update(_WAIT_BAD)
    files["rpc.py"] = _RPC_BAD["client.py"]
    _write_pkg(tmp_path, files)
    analyzer = Analyzer(root=tmp_path)
    serial = analyzer.run([tmp_path / "torchsnapshot_tpu"], baseline=set())
    parallel = Analyzer(root=tmp_path).run(
        [tmp_path / "torchsnapshot_tpu"], baseline=set(), jobs=4
    )
    assert [f.render() for f in serial.new_findings] == [
        f.render() for f in parallel.new_findings
    ]
    assert serial.new_findings  # the parity check is not vacuous


def test_shared_parse_cache_reuses_modules(tmp_path):
    from tools.snaplint.core import load_module_cached

    f = tmp_path / "m.py"
    f.write_text("x = 1\n")
    first = load_module_cached(f, tmp_path)
    assert load_module_cached(f, tmp_path) is first
    # An edit invalidates by (mtime_ns, size).
    f.write_text("x = 2  # changed\n")
    assert load_module_cached(f, tmp_path) is not first


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


def test_cli_protocol_flag_selects_family_and_is_clean_on_head():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "tools.snaplint",
            "--protocol",
            "torchsnapshot_tpu",
            "--json",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["new_findings"] == []


def test_cli_protocol_dump_is_machine_readable():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "tools.snaplint",
            "--protocol-dump",
            "torchsnapshot_tpu",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    dump = json.loads(proc.stdout)
    # The real coordination plane's namespace inventory (mirrored by the
    # docs/scaling.md table, kept in sync by store-namespace-docs).
    for ns in ("__cdn", "__endpoint", "__obs", "__preemption", "__ts"):
        assert ns in dump["namespaces"], ns
    templates = {f["template"] for f in dump["key_families"]}
    assert "__cdn/{*}/head" in templates
    assert "__cdn/{*}/announce/{*}" in templates
    assert any(op.startswith("RPC_PEER_") for op in dump["rpc_ops"])


def test_list_rules_includes_protocol_family():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.snaplint", "--list-rules"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0
    for name in PROTOCOL_RULE_NAMES:
        assert name in proc.stdout, name


# ---------------------------------------------------------------------------
# clean-on-HEAD lane pins
# ---------------------------------------------------------------------------


def test_protocol_family_clean_on_head_with_empty_baseline():
    """The acceptance gate: every protocol rule over the real package
    with NO baseline. A finding here is a real protocol defect (fix it
    in source) or a justified exception (inline suppression with a
    comment) — never a baseline entry."""
    analyzer = Analyzer(root=REPO, select=list(PROTOCOL_RULE_NAMES))
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == [], "\n".join(
        f.render() for f in result.new_findings
    )


def test_crash_registry_fully_threaded_on_head():
    """Every declared CRASH_* id is threaded through at least one
    crashpoint() call site — the chaos matrix has no rows that can
    never fire (and the declared registry is non-trivial)."""
    project = load_project([REPO / "torchsnapshot_tpu"], REPO)
    mdl = pm.get_model(project)
    declared = set(mdl.declared_crashpoints)
    threaded = {s.const for s in mdl.crash_sites}
    assert declared, "no declared CRASH_* ids extracted"
    assert declared <= threaded, sorted(declared - threaded)


def test_head_model_knows_the_coordination_plane():
    """Spot pins against the real package: the extracted model sees the
    plane's load-bearing families and RPC surface."""
    project = load_project([REPO / "torchsnapshot_tpu"], REPO)
    mdl = pm.get_model(project)
    fams = mdl.families()
    # CDN announce family: written by the publisher, reaped by its
    # retention delete (the PR's store-key-leak fix).
    announce = fams["__cdn/{*}/announce/{*}"]
    assert {s.role for s in announce} >= {"set", "delete"}
    # Peer RPC ops pair: every request op has a handler and vice versa.
    by_op = {}
    for site in mdl.rpc_sites:
        by_op.setdefault(site.op, set()).add(site.role)
    peer_ops = {
        op: roles for op, roles in by_op.items() if op.startswith("RPC_PEER_")
    }
    assert peer_ops
    for op, roles in peer_ops.items():
        if "request" in roles or "handler" in roles:
            assert {"request", "handler"} <= roles, (op, roles)
