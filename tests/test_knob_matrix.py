"""Knob-combination matrix: the incremental chain (take -> incremental
take -> restore -> deep fsck) must hold under every combination of slab
batching, checksum disable, and a starvation-level memory budget.

Pairwise knob interactions are where configuration bugs live (e.g.
incremental refs into batched slab locations, budget admission around
slab-sized buffers); the per-knob tests cover each in isolation only.
"""

import contextlib

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.fsck import verify_snapshot
from torchsnapshot_tpu.knobs import (
    disable_checksums,
    enable_batching,
    override_incremental_chunk_size_bytes,
    override_per_rank_memory_budget_bytes,
)
from torchsnapshot_tpu.manager import _entry_locations
from torchsnapshot_tpu.test_utils import assert_tree_eq


@pytest.mark.parametrize("batching", [False, True])
@pytest.mark.parametrize("no_checksums", [False, True])
@pytest.mark.parametrize("tiny_budget", [False, True])
def test_incremental_chain_under_knob_combo(
    tmp_path, batching, no_checksums, tiny_budget
) -> None:
    rng = np.random.default_rng(0)
    state = {
        f"l{i}": rng.standard_normal(2000 + i).astype(np.float32)
        for i in range(24)
    }
    stack = contextlib.ExitStack()
    with stack:
        if batching:
            stack.enter_context(enable_batching())
        if no_checksums:
            stack.enter_context(disable_checksums())
        if tiny_budget:
            stack.enter_context(
                override_per_rank_memory_budget_bytes(65536)
            )
        p0, p1 = str(tmp_path / "s0"), str(tmp_path / "s1")
        with override_incremental_chunk_size_bytes(256):
            ts.Snapshot.take(
                p0, {"m": ts.PyTreeState(dict(state))}, record_digests=True
            )
            state2 = dict(state)
            state2["l3"] = state["l3"] + 1.0
            ts.Snapshot.take(
                p1, {"m": ts.PyTreeState(state2)}, incremental_base=p0
            )
        # The incremental take must actually have deduplicated against
        # the base — the pairwise interaction under test. A silent
        # degrade to full rewrite would still restore and fsck clean.
        manifest = ts.Snapshot(p1).get_manifest()
        ref_locations = [
            loc
            for entry in manifest.values()
            for loc in _entry_locations(entry)
            if loc is not None and loc.startswith("../s0")
        ]
        assert len(ref_locations) > 10, (
            "incremental take rewrote everything instead of referencing "
            f"the base (refs: {len(ref_locations)})"
        )

        dst = ts.PyTreeState({k: np.zeros_like(v) for k, v in state.items()})
        ts.Snapshot(p1).restore({"m": dst})
        assert_tree_eq(dst.tree, state2)
        report = verify_snapshot(p1, deep=True)
        assert report.ok
        if not no_checksums:
            # FsckReport exposes crcs_verified so "deep OK" can never be
            # silently hollow — enforce that here.
            assert report.crcs_verified > 0
