"""SLO engine: burn-window math pins, edge-triggered breach events,
gauge export, and the CLI judgment.

The burn arithmetic tests use the packaged geometry (fast 8 @ 2.0,
slow 64 @ 1.0, error budget 0.1) so the numbers here double as the
documented examples: a cliff burns the fast window at 10.0, a 1-in-7
drift burns the slow window at ~1.41 while the fast window sits at
1.25 (below its 2.0 bar).
"""

import pytest

from torchsnapshot_tpu import knobs
from torchsnapshot_tpu.telemetry import ledger, names, slo
import torchsnapshot_tpu.telemetry as telemetry

T0 = 1_700_000_000.0


@pytest.fixture(autouse=True)
def _fresh_state():
    telemetry.reset_metrics()
    ledger.reset_owned_roots()
    slo.reset_slo_state()
    yield
    telemetry.reset_metrics()
    ledger.reset_owned_roots()
    slo.reset_slo_state()


def _stall_records(values):
    """Synthetic visible-stall ledger records, one second apart."""
    return [
        {
            "event": names.EVENT_VISIBLE_STALL,
            "unix_ts": T0 + i,
            "step": i,
            "visible_s": v,
        }
        for i, v in enumerate(values)
    ]


def _entry(results, slo_id):
    return next(o for o in results if o["objective"] == slo_id)


# ---------------------------------------------------------------------------
# burn-window arithmetic
# ---------------------------------------------------------------------------


def test_cliff_fires_the_fast_window():
    """60 healthy samples then 8 bad ones: the fast window burns at
    (8/8)/0.1 = 10.0 >= 2.0 and the objective breaches immediately."""
    records = _stall_records([0.1] * 60 + [10.0] * 8)
    entry = _entry(slo.evaluate(records), names.SLO_TAKE_VISIBLE_STALL)
    assert not entry["disabled"]
    assert entry["samples"] == 68
    assert entry["last_value"] == 10.0
    assert entry["fast"]["bad"] == 8
    assert entry["fast"]["burn"] == 10.0
    assert entry["breaching"]
    assert entry["burn_rate"] == 10.0


def test_drift_fires_the_slow_window_only():
    """One bad take in seven, sustained for 64 samples: the slow
    window burns at (9/64)/0.1 ~ 1.41 >= 1.0, while the fast window's
    single bad sample burns at 1.25 < 2.0 — exactly the shape a short
    window averages away."""
    values = [10.0 if i % 7 == 6 else 0.1 for i in range(64)]
    entry = _entry(
        slo.evaluate(_stall_records(values)), names.SLO_TAKE_VISIBLE_STALL
    )
    assert entry["slow"]["bad"] == 9
    assert entry["slow"]["burn"] == pytest.approx(1.4062, abs=1e-3)
    assert entry["fast"]["bad"] == 1
    assert entry["fast"]["burn"] == 1.25
    assert entry["breaching"]
    # The breach is the slow window's alone.
    assert entry["fast"]["burn"] < entry["fast"]["threshold"]
    assert entry["slow"]["burn"] >= entry["slow"]["threshold"]


def test_healthy_run_reports_zero_burn():
    records = _stall_records([0.1] * 100)
    entry = _entry(slo.evaluate(records), names.SLO_TAKE_VISIBLE_STALL)
    assert entry["burn_rate"] == 0.0
    assert not entry["breaching"]
    # No evidence is not a breach either.
    empty = _entry(slo.evaluate([]), names.SLO_TAKE_VISIBLE_STALL)
    assert empty["samples"] == 0
    assert not empty["breaching"]


def test_nonpositive_target_disables_one_objective():
    """<= 0 target disables that objective alone — the rest keep being
    judged (here restore-wall goes dark while take-visible-stall still
    breaches)."""
    records = _stall_records([10.0] * 8) + [
        {
            "event": names.EVENT_RESTORE_SERVED,
            "unix_ts": T0 + 100,
            "restore_s": 1e6,
        }
    ]
    with knobs.override_slo_restore_seconds(0):
        results = slo.evaluate(records)
    restore = _entry(results, names.SLO_RESTORE_WALL)
    assert restore["disabled"]
    assert not restore["breaching"]
    assert restore["fast"] is None and restore["slow"] is None
    assert _entry(results, names.SLO_TAKE_VISIBLE_STALL)["breaching"]


def test_window_knobs_reshape_the_judgment():
    """A <= 0 window is disabled outright; shrunk windows change what
    counts as recent."""
    records = _stall_records([10.0] * 2 + [0.1] * 6)
    with knobs.override_slo_windows(2, 0):
        entry = _entry(
            slo.evaluate(records), names.SLO_TAKE_VISIBLE_STALL
        )
    assert entry["slow"] is None
    assert entry["fast"]["samples"] == 2  # the two newest are healthy
    assert entry["fast"]["bad"] == 0
    assert not entry["breaching"]


def test_overhead_samples_reset_at_run_start():
    """The goodput-overhead extractor charges visible stall + restore
    wall to the commit interval that paid it — and a run restart's gap
    is never an interval."""
    records = [
        {"event": names.EVENT_RUN_START, "unix_ts": T0},
        # Interval 1: 5s of stall over 10s of wall = 0.5 overhead.
        {
            "event": names.EVENT_VISIBLE_STALL,
            "unix_ts": T0 + 4,
            "visible_s": 5.0,
        },
        {"event": names.EVENT_STEP_COMMITTED, "unix_ts": T0 + 10, "step": 1},
        # Restart: the 1000s gap must not appear as an interval.
        {"event": names.EVENT_RUN_START, "unix_ts": T0 + 1000},
        # Interval 2: clean 10s interval = 0.0 overhead.
        {
            "event": names.EVENT_STEP_COMMITTED,
            "unix_ts": T0 + 1010,
            "step": 2,
        },
    ]
    samples = slo._overhead_samples(records, [])
    assert samples == [(T0 + 10, 0.5), (T0 + 1010, 0.0)]


def test_coordination_samples_come_from_history():
    history = [
        {"kind": "take", "unix_ts": T0, "take_s": 10.0, "coordination_s": 4.0},
        {"kind": "restore", "unix_ts": T0 + 1, "take_s": 9.0},
        {
            "kind": "async_take",
            "unix_ts": T0 + 2,
            "take_s": 2.0,
            "coordination_s": 1.0,
        },
    ]
    samples = slo._coordination_samples([], history)
    assert samples == [(T0, 0.4), (T0 + 2, 0.5)]


# ---------------------------------------------------------------------------
# evaluate_step: gauges + edge-triggered breach events
# ---------------------------------------------------------------------------


def _breach_ready_root(tmp_path):
    """A real ledger (written through the API) whose visible stalls
    blow the 5s async visible budget — take-visible-stall burns."""
    root = str(tmp_path)
    assert ledger.open_run(root) is not None
    for i in range(8):
        ledger.post_event(
            root,
            names.EVENT_VISIBLE_STALL,
            step=i,
            kind="async_take",
            visible_s=50.0,
            unix_ts=T0 + i,
        )
    return root


def test_evaluate_step_posts_one_breach_event_per_episode(tmp_path):
    with knobs.enable_ledger(), knobs.enable_slo():
        root = _breach_ready_root(tmp_path)
        first = slo.evaluate_step(root, step=8)
        assert names.SLO_TAKE_VISIBLE_STALL in first["breaching"]
        # Still breaching on the next step: edge-triggered, no new event.
        ledger.post_event(
            root,
            names.EVENT_VISIBLE_STALL,
            step=8,
            kind="async_take",
            visible_s=50.0,
            unix_ts=T0 + 8,
        )
        second = slo.evaluate_step(root, step=9)
        assert names.SLO_TAKE_VISIBLE_STALL in second["breaching"]
        records = ledger.load_ledger(ledger.ledger_path_for(root))
        breaches = [
            r for r in records if r.get("event") == names.EVENT_SLO_BREACH
        ]
        assert len(breaches) == 1
        breach = breaches[0]
        assert breach["objective"] == names.SLO_TAKE_VISIBLE_STALL
        assert breach["step"] == 8
        assert breach["fast_burn"] == 10.0
        assert breach["last_value"] == 50.0


def test_evaluate_step_exports_burn_gauges_and_counter(tmp_path):
    with knobs.enable_ledger(), knobs.enable_slo():
        root = _breach_ready_root(tmp_path)
        slo.evaluate_step(root, step=8)
    collected = telemetry.metrics().collect()
    key = telemetry.series_key(
        names.OBJECTIVE_BURN_RATE,
        {"objective": names.SLO_TAKE_VISIBLE_STALL},
    )
    assert collected["gauges"][key] == 10.0
    counter_key = telemetry.series_key(
        names.OBJECTIVE_BREACHES_TOTAL,
        {"objective": names.SLO_TAKE_VISIBLE_STALL},
    )
    assert collected["counters"][counter_key] == 1.0
    # The fleet plane's published burn is the max across objectives.
    assert slo.current_burn() == 10.0


def test_slo_cli_exit_codes(tmp_path, capsys):
    with knobs.enable_ledger(), knobs.enable_slo():
        root = _breach_ready_root(tmp_path)
        assert slo.main([root]) == 2  # burning
        out = capsys.readouterr().out
        assert "BURNING" in out
        assert names.SLO_TAKE_VISIBLE_STALL in out
    assert slo.main([str(tmp_path / "nowhere")]) == 1  # no ledger
