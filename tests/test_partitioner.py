"""Partitioner unit tests: greedy write-load balancing of replicated state
and post-gather consolidation.

Reference parity: tests/test_partitioner.py (partitioner.py:42-79, :169-233,
:236-292). Multi-rank execution is simulated with threads over an
InProcessStore — the partitioner only exchanges metadata.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Tuple

import pytest

from torchsnapshot_tpu import knobs
from torchsnapshot_tpu.dist_store import InProcessStore
from torchsnapshot_tpu.io_preparer import prepare_write
from torchsnapshot_tpu.io_types import WriteReq
from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    Shard,
)
from torchsnapshot_tpu.partitioner import (
    consolidate_replicated_entries,
    partition_write_reqs,
)
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import ProcessGroup, rand_array


def _rank_reqs(
    rank: int, personal_rows: int, replicated_specs: Dict[str, int]
) -> Tuple[Dict[str, Entry], List[WriteReq]]:
    """Build one rank's manifest + write reqs: a personal array plus the
    shared replicated arrays (identical across ranks by construction)."""
    entries: Dict[str, Entry] = {}
    reqs: List[WriteReq] = []
    entry, wrs = prepare_write(
        rand_array((personal_rows, 256), "float32", seed=rank),
        "personal",
        rank=rank,
        replicated=False,
    )
    entries["personal"] = entry
    reqs.extend(wrs)
    for name, rows in replicated_specs.items():
        entry, wrs = prepare_write(
            rand_array((rows, 256), "float32", seed=100),
            name,
            rank=rank,
            replicated=True,
        )
        entries[name] = entry
        reqs.extend(wrs)
    return entries, reqs


def _run_partition(
    world_size: int, personal_rows_by_rank: List[int], replicated_specs: Dict[str, int]
) -> List[List[WriteReq]]:
    store = InProcessStore()

    def fn(rank: int) -> List[WriteReq]:
        pg = PGWrapper(ProcessGroup(store=store, rank=rank, world_size=world_size))
        entries, reqs = _rank_reqs(
            rank, personal_rows_by_rank[rank], replicated_specs
        )
        _, kept = partition_write_reqs(entries, reqs, pg)
        return kept

    with ThreadPoolExecutor(max_workers=world_size) as ex:
        futs = [ex.submit(fn, r) for r in range(world_size)]
        return [f.result(timeout=60) for f in futs]


def test_each_replicated_path_written_exactly_once() -> None:
    replicated = {"a": 8, "b": 16, "c": 24, "d": 4, "e": 12}
    kept_by_rank = _run_partition(3, [4, 4, 4], replicated)
    seen: Dict[str, int] = {}
    for rank, kept in enumerate(kept_by_rank):
        # Every rank keeps its own personal write.
        personal = [r for r in kept if r.path == f"{rank}/personal"]
        assert len(personal) == 1
        for req in kept:
            if req.path.startswith("replicated/"):
                assert req.path not in seen, "path assigned to two ranks"
                seen[req.path] = rank
    assert sorted(seen) == sorted(f"replicated/{k}" for k in replicated)


def test_greedy_assignment_balances_loads() -> None:
    """A rank with a heavy unavoidable personal load receives less
    replicated work (reference _partition_write_loads, partitioner.py:42-79)."""
    replicated = {f"r{i}": 8 for i in range(8)}
    kept_by_rank = _run_partition(2, [512, 4], replicated)
    rep_bytes = [
        sum(
            r.buffer_stager.get_staging_cost_bytes()
            for r in kept
            if r.path.startswith("replicated/")
        )
        for kept in kept_by_rank
    ]
    # Rank 0's personal array (512x256 fp32 = 512 KB) dwarfs the total
    # replicated volume (8 * 8 KB); everything replicated goes to rank 1.
    assert rep_bytes[0] == 0
    assert rep_bytes[1] > 0


def test_world1_keeps_everything() -> None:
    pg = PGWrapper(None)
    entries, reqs = _rank_reqs(0, 4, {"a": 8})
    _, kept = partition_write_reqs(entries, reqs, pg)
    assert kept == reqs


def test_disable_partitioner_raises() -> None:
    store = InProcessStore()
    pg = PGWrapper(ProcessGroup(store=store, rank=0, world_size=2))
    entries, reqs = _rank_reqs(0, 4, {"a": 8})
    import os

    os.environ["TORCHSNAPSHOT_TPU_DISABLE_PARTITIONER"] = "1"
    try:
        with pytest.raises(NotImplementedError):
            partition_write_reqs(entries, reqs, pg)
    finally:
        del os.environ["TORCHSNAPSHOT_TPU_DISABLE_PARTITIONER"]


def test_chunked_replicated_chunks_spread_across_ranks() -> None:
    """Chunked entries are sub-partitionable: with one large replicated
    chunked array and equal base loads, both ranks get some chunks."""
    with knobs.override_max_chunk_size_bytes(256 * 64):  # 16 rows per chunk
        kept_by_rank = _run_partition(2, [1, 1], {"big": 64})  # 4 chunks
    rep_counts = [
        sum(1 for r in kept if r.path.startswith("replicated/"))
        for kept in kept_by_rank
    ]
    assert sum(rep_counts) == 4
    assert rep_counts[0] > 0 and rep_counts[1] > 0


def test_four_rank_uneven_loads_balance() -> None:
    """4-rank bin-packing with uneven pre-loads (reference
    tests/test_partitioner.py:103-119): every replicated path lands on
    exactly one rank, and the heavily pre-loaded rank receives the least
    replicated volume."""
    replicated = {f"r{i}": 8 + 4 * i for i in range(12)}
    kept_by_rank = _run_partition(4, [256, 2, 2, 2], replicated)
    seen: Dict[str, int] = {}
    rep_bytes = []
    for rank, kept in enumerate(kept_by_rank):
        total = 0
        for req in kept:
            if req.path.startswith("replicated/"):
                assert req.path not in seen, "path assigned to two ranks"
                seen[req.path] = rank
                total += req.buffer_stager.get_staging_cost_bytes()
        rep_bytes.append(total)
    assert sorted(seen) == sorted(f"replicated/{k}" for k in replicated)
    # Greedy argmin balances per rank: the 360 replicated rows split
    # ~evenly over the three light ranks (~120 each), never catching up
    # to rank 0's 256-row pre-load — so rank 0 receives nothing.
    assert rep_bytes[0] == 0
    assert all(b > 0 for b in rep_bytes[1:])


def test_four_rank_chunked_subpartition_spreads_all_ranks() -> None:
    """A sub-partitionable chunked replicated entry spreads chunk-wise
    over all 4 ranks when base loads are equal."""
    with knobs.override_max_chunk_size_bytes(256 * 16):  # 4 rows per chunk
        kept_by_rank = _run_partition(4, [1, 1, 1, 1], {"big": 64})  # 16 chunks
    rep_counts = [
        sum(1 for r in kept if r.path.startswith("replicated/"))
        for kept in kept_by_rank
    ]
    assert sum(rep_counts) == 16
    assert all(c > 0 for c in rep_counts), rep_counts


# ---------------------------------------------------------------------------
# consolidate_replicated_entries
# ---------------------------------------------------------------------------


def _arr_entry(location: str, replicated: bool = True) -> ArrayEntry:
    return ArrayEntry(
        location=location,
        serializer="buffer_protocol",
        dtype="float32",
        shape=[4],
        replicated=replicated,
    )


def test_consolidate_identical_entries() -> None:
    m0 = {"x": _arr_entry("replicated/x"), "y": _arr_entry("0/y", replicated=False)}
    m1 = {"x": _arr_entry("replicated/x")}
    merged = consolidate_replicated_entries([m0, m1])
    assert sorted(merged) == ["x"]
    assert merged["x"] == _arr_entry("replicated/x")


def test_consolidate_prefers_batch_rewritten_entry() -> None:
    plain = _arr_entry("replicated/x")
    rewritten = ArrayEntry(
        location="batched/u-u-i-d",
        serializer="buffer_protocol",
        dtype="float32",
        shape=[4],
        replicated=True,
        byte_range=[0, 16],
    )
    for order in ([{"x": plain}, {"x": rewritten}], [{"x": rewritten}, {"x": plain}]):
        merged = consolidate_replicated_entries(order)
        assert merged["x"].location == "batched/u-u-i-d"


def test_consolidate_mismatch_raises() -> None:
    a = _arr_entry("replicated/x")
    b = ArrayEntry(
        location="replicated/x",
        serializer="buffer_protocol",
        dtype="float64",  # genuine payload mismatch
        shape=[4],
        replicated=True,
    )
    with pytest.raises(AssertionError, match="mismatch"):
        consolidate_replicated_entries([{"x": a}, {"x": b}])


def test_consolidate_unions_chunked_entries() -> None:
    def chunk(start: int) -> Shard:
        return Shard(
            offsets=[start],
            sizes=[4],
            array=_arr_entry(f"replicated/big_{start}"),
        )

    def chunked(chunks: List[Shard]) -> ChunkedArrayEntry:
        return ChunkedArrayEntry(
            dtype="float32", shape=[8], chunks=chunks, replicated=True
        )

    m0 = {"big": chunked([chunk(0), chunk(4)])}
    m1 = {"big": chunked([chunk(0), chunk(4)])}
    merged = consolidate_replicated_entries([m0, m1])
    offs = [c.offsets[0] for c in merged["big"].chunks]
    assert offs == [0, 4]
