"""snaplint framework tests: one failing fixture per rule (the
detection that would have caught the bug class before its paired fix),
the suppression and baseline round-trips, and the repo-wide "analyzer
is clean on HEAD" lane check that keeps it that way.

Each rule's fixture pair is (bad, fixed): the bad snippet must produce
a finding and the fixed snippet must not — proving the rule detects the
violation AND accepts the repo's blessed idiom for it.
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.snaplint import Analyzer  # noqa: E402
from tools.snaplint.core import load_baseline, write_baseline  # noqa: E402


def _run(tmp_path, source, rule, baseline=None, filename="mod.py"):
    f = tmp_path / filename
    f.write_text(source)
    analyzer = Analyzer(root=tmp_path, select=[rule])
    return analyzer.run([f], baseline=baseline)


def _messages(result):
    return [f.message for f in result.new_findings]


# ---------------------------------------------------------------------------
# collective-under-conditional
# ---------------------------------------------------------------------------

_COLLECTIVE_BAD = """
from torchsnapshot_tpu import knobs

def emit_report(store, rank, world, payload):
    if knobs.is_telemetry_sink_enabled():
        store.gather("reports", rank, world, payload)
    if rank == 0:
        store.barrier("commit", rank, world)
"""

# The PR 2 fix shape: the collective is unconditional; only the payload
# (and the sink write) stay knob-gated.
_COLLECTIVE_FIXED = """
from torchsnapshot_tpu import knobs

def emit_report(store, rank, world, payload):
    gathered = store.gather("reports", rank, world, payload)
    store.barrier("commit", rank, world)
    if knobs.is_telemetry_sink_enabled() and gathered is not None:
        write_out(gathered)
"""


def test_collective_under_conditional_detects_and_accepts_fix(tmp_path):
    bad = _run(tmp_path, _COLLECTIVE_BAD, "collective-under-conditional")
    assert len(bad.new_findings) == 2
    assert any("knob/env" in m for m in _messages(bad))
    assert any("rank" in m for m in _messages(bad))
    fixed = _run(
        tmp_path, _COLLECTIVE_FIXED, "collective-under-conditional"
    )
    assert fixed.new_findings == []


def test_collective_rule_tracks_taint_through_assignment(tmp_path):
    source = """
import os

def sync(store, rank, world):
    enabled = os.environ.get("TORCHSNAPSHOT_TPU_X") is not None
    flag = enabled
    if flag:
        store.exchange("e", rank, world, None)
"""
    result = _run(tmp_path, source, "collective-under-conditional")
    assert len(result.new_findings) == 1


# The fan-out restore idiom: a knob gates collective work only through
# a broadcast-agreed value — rank 0's reading reaches every rank, so
# the guard cannot skew, even though the broadcast's ARGUMENT is a knob
# read. Agreement results launder both knob and rank taint.
_COLLECTIVE_AGREED = """
from torchsnapshot_tpu import knobs

def restore(pg, store, rank, world):
    if pg.agree_object(knobs.is_fanout_restore_enabled()):
        store.exchange("fanout/needs", rank, world, {})
    enabled = pg.broadcast_object(knobs.is_fanout_restore_enabled())
    if enabled:
        store.exchange("fanout/blobs", rank, world, None)
    leader = pg.broadcast_object(rank)
    if leader:
        store.barrier("cleanup", rank, world)
"""


def test_collective_rule_launders_broadcast_agreed_guards(tmp_path):
    agreed = _run(tmp_path, _COLLECTIVE_AGREED, "collective-under-conditional")
    assert agreed.new_findings == []
    # ...but a knob guarding the agreement collective itself (or raw
    # knob taint beside an agreement call) still flags.
    bad = """
from torchsnapshot_tpu import knobs

def restore(pg, store, rank, world):
    if knobs.is_fanout_restore_enabled():
        pg.broadcast_object({"owners": {}})
    flag = pg.agree_object(knobs.is_fanout_restore_enabled())
    if flag and knobs.is_batching_enabled():
        store.exchange("x", rank, world, None)
"""
    result = _run(tmp_path, bad, "collective-under-conditional")
    assert len(result.new_findings) == 2


def test_collective_rule_ignores_uniform_and_unrelated_guards(tmp_path):
    source = """
def sync(store, rank, world, barrier):
    if world > 1:
        store.barrier("b", rank, world)
    if barrier is not None:
        barrier.arrive()
    import asyncio
    async def go(tasks):
        if some_flag():
            await asyncio.gather(*tasks)
"""
    result = _run(tmp_path, source, "collective-under-conditional")
    assert result.new_findings == []


# ---------------------------------------------------------------------------
# async-blocking-call
# ---------------------------------------------------------------------------

_ASYNC_BAD = """
import time
import subprocess

async def drain(fut):
    time.sleep(0.1)
    out = subprocess.run(["true"])
    return fut.result()
"""

_ASYNC_FIXED = """
import asyncio

async def drain(fut, loop, executor):
    await asyncio.sleep(0.1)
    out = await loop.run_in_executor(executor, run_child)
    return await fut


def sync_helper(fut):
    # Blocking calls in SYNC functions are fine (executor work).
    import time
    time.sleep(0.1)
    return fut.result()
"""


def test_async_blocking_call_detects_and_accepts_fix(tmp_path):
    bad = _run(tmp_path, _ASYNC_BAD, "async-blocking-call")
    msgs = _messages(bad)
    assert len(bad.new_findings) == 3
    assert any("time.sleep" in m for m in msgs)
    assert any(".result()" in m for m in msgs)
    assert any("subprocess.run" in m for m in msgs)
    fixed = _run(tmp_path, _ASYNC_FIXED, "async-blocking-call")
    assert fixed.new_findings == []


def test_async_rule_allows_result_with_timeout(tmp_path):
    source = """
async def bounded(fut):
    return fut.result(timeout=5)
"""
    assert _run(tmp_path, source, "async-blocking-call").new_findings == []


# The round-6 background-drain bug class: threading primitives (staged
# Events, the commit thread) living right next to the drain's
# coroutines — a non-awaited .wait()/.join() inside one either blocks
# the loop (threading) or silently drops a coroutine (asyncio).
_ASYNC_WAIT_BAD = """
async def drain(staged_event, commit_thread):
    staged_event.wait()
    commit_thread.join()
"""

_ASYNC_WAIT_FIXED = """
import asyncio
import os

async def drain(staged_event, commit_thread, loop, executor):
    await staged_event.wait()
    await loop.run_in_executor(executor, commit_thread.join)
    # String building and path building are not synchronization:
    label = ", ".join(["a", "b"])
    path = os.path.join("/tmp", "x")
    return label, path
"""


def test_async_rule_flags_non_awaited_wait_and_join(tmp_path):
    bad = _run(tmp_path, _ASYNC_WAIT_BAD, "async-blocking-call")
    msgs = _messages(bad)
    assert len(bad.new_findings) == 2
    assert any(".wait()" in m for m in msgs)
    assert any(".join()" in m for m in msgs)
    fixed = _run(tmp_path, _ASYNC_WAIT_FIXED, "async-blocking-call")
    assert fixed.new_findings == []


# ---------------------------------------------------------------------------
# span-and-budget-balance
# ---------------------------------------------------------------------------

_SPAN_BAD = """
def timed(recorder):
    tok = recorder.begin("layer:op")
    work()
    recorder.end(tok)


async def admit(budget, cost):
    await budget.acquire(cost)
    await stage()
    await budget.release(cost)
"""

_SPAN_FIXED = """
def timed(recorder):
    tok = recorder.begin("layer:op")
    try:
        work()
    finally:
        recorder.end(tok)


def timed_except_idiom(recorder):
    # The scheduler's stage/except/re-raise shape is also balanced.
    tok = recorder.begin("layer:op")
    try:
        work()
    except BaseException:
        recorder.end(tok)
        raise
    recorder.end(tok)


async def admit(budget, cost):
    await budget.acquire(cost)
    try:
        await stage()
    finally:
        await budget.release(cost)


async def transfer(budget, cost, tasks):
    # Acquire-only: ownership moves to a completion task that releases.
    await budget.acquire(cost)
    tasks.append(spawn(cost))
"""


def test_span_budget_balance_detects_and_accepts_fix(tmp_path):
    bad = _run(tmp_path, _SPAN_BAD, "span-and-budget-balance")
    msgs = _messages(bad)
    assert len(bad.new_findings) == 2
    assert any("span 'tok'" in m for m in msgs)
    assert any("budget.acquire()" in m for m in msgs)
    fixed = _run(tmp_path, _SPAN_FIXED, "span-and-budget-balance")
    assert fixed.new_findings == []


def test_span_rule_flags_begin_with_no_end_at_all(tmp_path):
    source = """
def leaky(recorder):
    tok = recorder.begin("layer:op")
    work()
"""
    result = _run(tmp_path, source, "span-and-budget-balance")
    assert len(result.new_findings) == 1
    assert "never end()ed" in result.new_findings[0].message


# ---------------------------------------------------------------------------
# knob-env-literal
# ---------------------------------------------------------------------------

_ENV_BAD = """
import os

_FLAG_ENV = "TORCHSNAPSHOT_TPU_MY_FLAG"

def enabled():
    return _FLAG_ENV in os.environ

def value():
    return os.environ.get("TORCHSNAPSHOT_TPU_MY_VALUE", "0")

def via_getenv():
    return os.getenv("TORCHSNAPSHOT_TPU_OTHER")
"""

_ENV_FIXED = """
import os
from torchsnapshot_tpu import knobs

def enabled():
    return knobs.is_native_disabled()

def unrelated():
    # Non-knob env vars are out of scope for this rule.
    return os.environ.get("JAX_PLATFORMS")
"""


def test_knob_env_literal_detects_and_accepts_fix(tmp_path):
    bad = _run(tmp_path, _ENV_BAD, "knob-env-literal")
    msgs = _messages(bad)
    assert len(bad.new_findings) == 3
    assert any("TORCHSNAPSHOT_TPU_MY_FLAG" in m for m in msgs)
    assert any("TORCHSNAPSHOT_TPU_MY_VALUE" in m for m in msgs)
    assert any("TORCHSNAPSHOT_TPU_OTHER" in m for m in msgs)
    fixed = _run(tmp_path, _ENV_FIXED, "knob-env-literal")
    assert fixed.new_findings == []


_ENV_OVERRIDE_BAD = """
import os
from torchsnapshot_tpu import knobs
from torchsnapshot_tpu import knobs as ts_knobs
from torchsnapshot_tpu.knobs import _STAGING_THREADS_ENV

def threads():
    # Bypasses the tuner override layer: reads only the env half of
    # env > set_tuner_override > default.
    return os.environ.get(knobs._STAGING_THREADS_ENV, "4")

def threads_imported():
    return os.getenv(_STAGING_THREADS_ENV)

def threads_aliased():
    # An aliased knobs import must not slip past the rule.
    return os.environ.get(ts_knobs._STAGING_THREADS_ENV)

def pinned():
    return knobs._PER_RANK_IO_CONCURRENCY_ENV in os.environ
"""

_ENV_OVERRIDE_FIXED = """
import os
from torchsnapshot_tpu import knobs

def threads():
    return knobs.get_staging_threads()

def subprocess_env():
    # Writes stay exempt: shipping the constant to a child env is how
    # the override context managers legitimately work.
    os.environ[knobs._STAGING_THREADS_ENV] = "8"

def unrelated_suffix():
    # A non-knobs _ENV name is out of scope.
    MY_ENV = "SOMETHING_ELSE"
    return os.environ.get(MY_ENV)
"""


def test_knob_env_literal_covers_override_layer_constants(tmp_path):
    """The tuner extension: an env read keyed by a knobs ``_*_ENV``
    constant (attribute or imported name) outside knobs.py forks the
    env > tuner-override > default precedence chain."""
    bad = _run(tmp_path, _ENV_OVERRIDE_BAD, "knob-env-literal")
    msgs = _messages(bad)
    assert len(bad.new_findings) == 4, msgs
    assert any("knobs._STAGING_THREADS_ENV" in m for m in msgs)
    assert any("_STAGING_THREADS_ENV bypasses" in m for m in msgs)
    assert any("ts_knobs._STAGING_THREADS_ENV" in m for m in msgs)
    assert any("_PER_RANK_IO_CONCURRENCY_ENV" in m for m in msgs)
    assert all("override-aware getter" in m for m in msgs)
    fixed = _run(tmp_path, _ENV_OVERRIDE_FIXED, "knob-env-literal")
    assert fixed.new_findings == []


def test_knob_env_literal_exempts_knobs_py_and_writes(tmp_path):
    knobs_src = """
import os
_X = "TORCHSNAPSHOT_TPU_X"
def get_x():
    return os.environ.get(_X)
"""
    assert (
        _run(
            tmp_path, knobs_src, "knob-env-literal", filename="knobs.py"
        ).new_findings
        == []
    )
    writes = """
import os
def set_for_subprocess():
    os.environ["TORCHSNAPSHOT_TPU_X"] = "1"
"""
    assert _run(tmp_path, writes, "knob-env-literal").new_findings == []


# ---------------------------------------------------------------------------
# executor-thread-leak
# ---------------------------------------------------------------------------

_LEAK_BAD = """
import threading
from concurrent.futures import ThreadPoolExecutor

def stage_all(reqs):
    ex = ThreadPoolExecutor(max_workers=4)
    for r in reqs:
        ex.submit(r.run)

def watch():
    t = threading.Thread(target=poll)
    t.start()
"""

_LEAK_FIXED = """
import threading
from concurrent.futures import ThreadPoolExecutor

def stage_all(reqs):
    ex = ThreadPoolExecutor(max_workers=4)
    try:
        for r in reqs:
            ex.submit(r.run)
    finally:
        ex.shutdown(wait=False)

def stage_with(reqs):
    with ThreadPoolExecutor(max_workers=4) as ex:
        for r in reqs:
            ex.submit(r.run)

def stage_transfer(reqs):
    # Ownership escapes to the handle that completes the drain.
    ex = ThreadPoolExecutor(max_workers=4)
    return PendingWork(executor=ex)

def watch():
    t = threading.Thread(target=poll, daemon=True)
    t.start()

class Owner:
    def __init__(self):
        # Attribute storage: lifecycle owned by the object.
        self._thread = threading.Thread(target=poll)
"""


def test_executor_thread_leak_detects_and_accepts_fix(tmp_path):
    bad = _run(tmp_path, _LEAK_BAD, "executor-thread-leak")
    msgs = _messages(bad)
    assert len(bad.new_findings) == 2
    assert any("ThreadPoolExecutor 'ex'" in m for m in msgs)
    assert any("Thread 't'" in m for m in msgs)
    fixed = _run(tmp_path, _LEAK_FIXED, "executor-thread-leak")
    assert fixed.new_findings == []


# ---------------------------------------------------------------------------
# suppressions & baseline
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# doctor-rule-ids
# ---------------------------------------------------------------------------

_DOCTOR_NAMES_BAD = """
RULE_FOO = "Not_Kebab"
RULE_FOO_AGAIN = "Not_Kebab"
"""

_DOCTOR_NAMES_FIXED = """
RULE_FOO = "foo-too-slow"
"""

_DOCTOR_EMIT_BAD = """
from torchsnapshot_tpu.telemetry.doctor import Verdict, doctor_rule

@doctor_rule("literal-id")
def _check(report):
    return None

def emit():
    return Verdict(rule="another-literal", summary="x")
"""

_DOCTOR_EMIT_FIXED = """
from torchsnapshot_tpu.telemetry import names
from torchsnapshot_tpu.telemetry.doctor import Verdict, doctor_rule

@doctor_rule(names.RULE_FOO)
def _check(report):
    return None

def emit():
    return Verdict(rule=names.RULE_FOO, summary="x")
"""


def _doctor_layout(tmp_path, names_src, emit_src):
    """The doctor-rule-ids rule is project-level: it needs the package
    layout (telemetry/names.py) to exist under the analyzer root."""
    pkg = tmp_path / "torchsnapshot_tpu" / "telemetry"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "names.py").write_text(names_src)
    emitter = pkg / "emitter.py"
    emitter.write_text(emit_src)
    return emitter


def test_doctor_rule_ids_detects_and_accepts_fix(tmp_path):
    emitter = _doctor_layout(tmp_path, _DOCTOR_NAMES_BAD, _DOCTOR_EMIT_BAD)
    analyzer = Analyzer(root=tmp_path, select=["doctor-rule-ids"])
    bad = analyzer.run([emitter], baseline=None)
    msgs = _messages(bad)
    assert any("not\nkebab-case".replace("\n", " ") in m for m in msgs)
    assert any("registered twice" in m for m in msgs)
    assert any("'literal-id'" in m and "doctor_rule" in m for m in msgs)
    assert any("'another-literal'" in m and "Verdict" in m for m in msgs)

    emitter = _doctor_layout(
        tmp_path, _DOCTOR_NAMES_FIXED, _DOCTOR_EMIT_FIXED
    )
    analyzer = Analyzer(root=tmp_path, select=["doctor-rule-ids"])
    fixed = analyzer.run([emitter], baseline=None)
    assert fixed.new_findings == []


def test_doctor_rule_ids_requires_declarations(tmp_path):
    """An empty RULE_ registry is itself a finding (the catalogue must
    exist), mirroring the metric/span declaration checks."""
    emitter = _doctor_layout(tmp_path, "X = 1\n", "def noop():\n    pass\n")
    analyzer = Analyzer(root=tmp_path, select=["doctor-rule-ids"])
    result = analyzer.run([emitter], baseline=None)
    assert any(
        "no doctor rule ids declared" in m for m in _messages(result)
    )


# ---------------------------------------------------------------------------
# rpc-op-ids
# ---------------------------------------------------------------------------

_RPC_NAMES_BAD = """
RPC_FOO = "Not_Kebab"
RPC_FOO_AGAIN = "Not_Kebab"
"""

_RPC_NAMES_FIXED = """
RPC_FOO = "peer-pull"
"""

_RPC_EMIT_BAD = """
from torchsnapshot_tpu.telemetry import wire

def pull(client):
    with wire.propagate("literal-op"):
        client.request("another-literal", "step_7")
    wire.observe_rpc("peer", "third-literal", 0.5)
"""

_RPC_EMIT_FIXED = """
from torchsnapshot_tpu.telemetry import names, wire

def pull(client):
    with wire.propagate(names.RPC_FOO):
        client.request(names.RPC_FOO, "step_7")
    wire.observe_rpc("peer", names.RPC_FOO, 0.5)
"""


def test_rpc_op_ids_detects_and_accepts_fix(tmp_path):
    emitter = _doctor_layout(tmp_path, _RPC_NAMES_BAD, _RPC_EMIT_BAD)
    analyzer = Analyzer(root=tmp_path, select=["rpc-op-ids"])
    bad = analyzer.run([emitter], baseline=None)
    msgs = _messages(bad)
    assert any("kebab-case" in m for m in msgs)
    assert any("registered twice" in m for m in msgs)
    assert any("'literal-op'" in m and "propagate" in m for m in msgs)
    assert any("'another-literal'" in m and "request" in m for m in msgs)
    assert any("'third-literal'" in m and "observe_rpc" in m for m in msgs)

    emitter = _doctor_layout(tmp_path, _RPC_NAMES_FIXED, _RPC_EMIT_FIXED)
    analyzer = Analyzer(root=tmp_path, select=["rpc-op-ids"])
    fixed = analyzer.run([emitter], baseline=None)
    assert fixed.new_findings == []


def test_rpc_op_ids_requires_declarations(tmp_path):
    """An empty RPC_ registry is itself a finding: the on-the-wire op
    namespace must be catalogued before anything propagates one."""
    emitter = _doctor_layout(tmp_path, "X = 1\n", "def noop():\n    pass\n")
    analyzer = Analyzer(root=tmp_path, select=["rpc-op-ids"])
    result = analyzer.run([emitter], baseline=None)
    assert any("no rpc op ids declared" in m for m in _messages(result))


def test_rpc_op_ids_clean_on_head():
    """The package's own frame-send sites all cite RPC_ constants."""
    analyzer = Analyzer(root=REPO, select=["rpc-op-ids"])
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == []


# ---------------------------------------------------------------------------
# slo-ids
# ---------------------------------------------------------------------------

_SLO_NAMES_BAD = """
SLO_FOO = "Not_Kebab"
SLO_FOO_AGAIN = "Not_Kebab"
"""

_SLO_NAMES_FIXED = """
SLO_FOO = "foo-promised"
"""

_SLO_EMIT_BAD = """
from torchsnapshot_tpu.telemetry.slo import Objective

OBJ = Objective("literal-id", "d", "s", lambda: 1.0, lambda lr, hr: [])
OBJ2 = Objective(slo_id="another-literal", description="d", unit="s",
                 target=lambda: 1.0, samples=lambda lr, hr: [])
"""

_SLO_EMIT_FIXED = """
from torchsnapshot_tpu.telemetry import names
from torchsnapshot_tpu.telemetry.slo import Objective

OBJ = Objective(names.SLO_FOO, "d", "s", lambda: 1.0, lambda lr, hr: [])
"""


def test_slo_ids_detects_and_accepts_fix(tmp_path):
    emitter = _doctor_layout(tmp_path, _SLO_NAMES_BAD, _SLO_EMIT_BAD)
    analyzer = Analyzer(root=tmp_path, select=["slo-ids"])
    bad = analyzer.run([emitter], baseline=None)
    msgs = _messages(bad)
    assert any("kebab-case" in m for m in msgs)
    assert any("registered twice" in m for m in msgs)
    assert any("'literal-id'" in m and "Objective" in m for m in msgs)
    assert any("'another-literal'" in m and "Objective" in m for m in msgs)

    emitter = _doctor_layout(tmp_path, _SLO_NAMES_FIXED, _SLO_EMIT_FIXED)
    analyzer = Analyzer(root=tmp_path, select=["slo-ids"])
    fixed = analyzer.run([emitter], baseline=None)
    assert fixed.new_findings == []


def test_slo_ids_requires_declarations(tmp_path):
    """An empty SLO_ registry is itself a finding: the promised
    objectives must be catalogued before the engine judges any."""
    emitter = _doctor_layout(tmp_path, "X = 1\n", "def noop():\n    pass\n")
    analyzer = Analyzer(root=tmp_path, select=["slo-ids"])
    result = analyzer.run([emitter], baseline=None)
    assert any("no slo ids declared" in m for m in _messages(result))


def test_slo_ids_clean_on_head():
    """The package's own Objective declarations all cite SLO_
    constants."""
    analyzer = Analyzer(root=REPO, select=["slo-ids"])
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == []


# ---------------------------------------------------------------------------
# ledger-event-ids
# ---------------------------------------------------------------------------

_LEDGER_NAMES_BAD = """
EVENT_FOO = "Not_Kebab"
EVENT_FOO_AGAIN = "Not_Kebab"
"""

_LEDGER_NAMES_FIXED = """
EVENT_FOO = "foo-happened"
"""

_LEDGER_EMIT_BAD = """
from torchsnapshot_tpu.telemetry.ledger import (
    post_event,
    post_event_for_snapshot,
)

def emit(root, path):
    post_event(root, "literal-event", step=1)
    post_event_for_snapshot(path, event="another-literal")
"""

_LEDGER_EMIT_FIXED = """
from torchsnapshot_tpu.telemetry import names
from torchsnapshot_tpu.telemetry.ledger import (
    post_event,
    post_event_for_snapshot,
)

def emit(root, path):
    post_event(root, names.EVENT_FOO, step=1)
    post_event_for_snapshot(path, event=names.EVENT_FOO)
"""


def test_ledger_event_ids_detects_and_accepts_fix(tmp_path):
    emitter = _doctor_layout(tmp_path, _LEDGER_NAMES_BAD, _LEDGER_EMIT_BAD)
    analyzer = Analyzer(root=tmp_path, select=["ledger-event-ids"])
    bad = analyzer.run([emitter], baseline=None)
    msgs = _messages(bad)
    assert any("not kebab-case" in m for m in msgs)
    assert any("registered twice" in m for m in msgs)
    assert any("'literal-event'" in m and "post_event" in m for m in msgs)
    assert any(
        "'another-literal'" in m and "post_event_for_snapshot" in m
        for m in msgs
    )
    # The ROOT argument (first positional) is never mistaken for an
    # event id — only the second positional / event= keyword lints.
    assert not any("'/some/root'" in m for m in msgs)

    emitter = _doctor_layout(
        tmp_path, _LEDGER_NAMES_FIXED, _LEDGER_EMIT_FIXED
    )
    analyzer = Analyzer(root=tmp_path, select=["ledger-event-ids"])
    fixed = analyzer.run([emitter], baseline=None)
    assert fixed.new_findings == []


def test_ledger_event_ids_requires_declarations(tmp_path):
    emitter = _doctor_layout(tmp_path, "X = 1\n", "def noop():\n    pass\n")
    analyzer = Analyzer(root=tmp_path, select=["ledger-event-ids"])
    result = analyzer.run([emitter], baseline=None)
    assert any(
        "no ledger event ids declared" in m for m in _messages(result)
    )


def test_ledger_event_ids_repo_clean_on_head():
    analyzer = Analyzer(root=REPO, select=["ledger-event-ids"])
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == []


# ---------------------------------------------------------------------------
# crashpoint-ids
# ---------------------------------------------------------------------------

_CRASH_NAMES_BAD = """
CRASH_FOO = "Not_Kebab"
CRASH_FOO_AGAIN = "Not_Kebab"
"""

_CRASH_NAMES_FIXED = """
CRASH_FOO = "foo-durable"
"""

_CRASH_EMIT_BAD = """
from torchsnapshot_tpu.chaos import arm, crashpoint

def take():
    crashpoint("literal-point")

def matrix():
    arm(name="another-literal")
"""

_CRASH_EMIT_FIXED = """
from torchsnapshot_tpu.chaos import arm, crashpoint
from torchsnapshot_tpu.telemetry import names

def take():
    crashpoint(names.CRASH_FOO)

def matrix():
    arm(name=names.CRASH_FOO)
"""


def test_crashpoint_ids_detects_and_accepts_fix(tmp_path):
    emitter = _doctor_layout(tmp_path, _CRASH_NAMES_BAD, _CRASH_EMIT_BAD)
    analyzer = Analyzer(root=tmp_path, select=["crashpoint-ids"])
    bad = analyzer.run([emitter], baseline=None)
    msgs = _messages(bad)
    assert any("not kebab-case" in m for m in msgs)
    assert any("registered twice" in m for m in msgs)
    assert any("'literal-point'" in m and "crashpoint" in m for m in msgs)
    assert any("'another-literal'" in m and "arm" in m for m in msgs)

    emitter = _doctor_layout(tmp_path, _CRASH_NAMES_FIXED, _CRASH_EMIT_FIXED)
    analyzer = Analyzer(root=tmp_path, select=["crashpoint-ids"])
    fixed = analyzer.run([emitter], baseline=None)
    assert fixed.new_findings == []


def test_crashpoint_ids_requires_declarations(tmp_path):
    emitter = _doctor_layout(tmp_path, "X = 1\n", "def noop():\n    pass\n")
    analyzer = Analyzer(root=tmp_path, select=["crashpoint-ids"])
    result = analyzer.run([emitter], baseline=None)
    assert any(
        "no crash point ids declared" in m for m in _messages(result)
    )


def test_crashpoint_ids_repo_clean_on_head():
    analyzer = Analyzer(root=REPO, select=["crashpoint-ids"])
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == []


def test_inline_suppression_silences_one_rule(tmp_path):
    source = """
import time

async def wait_out():
    time.sleep(0.1)  # snaplint: disable=async-blocking-call
"""
    result = _run(tmp_path, source, "async-blocking-call")
    assert result.new_findings == []
    assert len(result.suppressed) == 1
    # The wrong rule name does NOT suppress.
    source_wrong = source.replace("async-blocking-call", "some-other-rule")
    result = _run(tmp_path, source_wrong, "async-blocking-call")
    assert len(result.new_findings) == 1


def test_preceding_line_suppression(tmp_path):
    source = """
import time

async def wait_out():
    # snaplint: disable=async-blocking-call
    time.sleep(0.1)
"""
    result = _run(tmp_path, source, "async-blocking-call")
    assert result.new_findings == []
    assert len(result.suppressed) == 1


def test_baseline_round_trip(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(_ENV_BAD)
    analyzer = Analyzer(root=tmp_path, select=["knob-env-literal"])
    first = analyzer.run([f])
    assert len(first.new_findings) == 3

    baseline_file = tmp_path / "baseline.json"
    write_baseline(baseline_file, first.findings)
    baseline = load_baseline(baseline_file)
    assert len(baseline) == 3

    # Grandfathered findings no longer fail the run...
    second = analyzer.run([f], baseline=baseline)
    assert second.new_findings == []
    assert second.exit_code == 0

    # ...but a NEW violation still does, alone.
    f.write_text(
        _ENV_BAD + '\ndef fresh():\n    import os\n'
        '    return os.getenv("TORCHSNAPSHOT_TPU_BRAND_NEW")\n'
    )
    third = analyzer.run([f], baseline=baseline)
    assert len(third.new_findings) == 1
    assert "TORCHSNAPSHOT_TPU_BRAND_NEW" in third.new_findings[0].message
    assert third.exit_code == 1


def test_baseline_is_a_multiset_not_a_set(tmp_path):
    """One grandfathered finding excuses exactly one occurrence: a NEW
    identical violation in the same file (same rule, same message, a
    different line) still fails the run."""
    f = tmp_path / "mod.py"
    one = (
        "import os\n"
        'def a():\n    return os.getenv("TORCHSNAPSHOT_TPU_X")\n'
    )
    f.write_text(one)
    analyzer = Analyzer(root=tmp_path, select=["knob-env-literal"])
    baseline_file = tmp_path / "baseline.json"
    write_baseline(baseline_file, analyzer.run([f]).findings)
    baseline = load_baseline(baseline_file)

    f.write_text(
        one + 'def b():\n    return os.getenv("TORCHSNAPSHOT_TPU_X")\n'
    )
    result = analyzer.run([f], baseline=baseline)
    assert len(result.new_findings) == 1  # the duplicate is NOT masked


def test_baseline_key_survives_line_shifts(tmp_path):
    """Finding keys exclude line numbers — including line references
    embedded in messages ("guard (line 42)") — so a comment added above
    a grandfathered finding doesn't churn the baseline."""
    f = tmp_path / "mod.py"
    f.write_text(_COLLECTIVE_BAD)
    analyzer = Analyzer(root=tmp_path, select=["collective-under-conditional"])
    first = analyzer.run([f])
    assert len(first.new_findings) == 2
    assert any("(line " in m for m in _messages(first))

    baseline_file = tmp_path / "baseline.json"
    write_baseline(baseline_file, first.findings)
    f.write_text("# pushed down\n# two lines\n" + _COLLECTIVE_BAD)
    shifted = analyzer.run([f], baseline=load_baseline(baseline_file))
    assert shifted.new_findings == []


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    result = _run(tmp_path, "def broken(:\n", "knob-env-literal")
    assert len(result.new_findings) == 1
    assert result.new_findings[0].rule == "parse-error"


# ---------------------------------------------------------------------------
# native-decl-sync
# ---------------------------------------------------------------------------

_NATIVE_DECLARE_OK = """
import ctypes

def _declare(l):
    l.ts_write_file.argtypes = [ctypes.c_char_p]
    l.ts_write_file.restype = ctypes.c_int
    l.ts_crc32c.argtypes = [ctypes.c_void_p]
    l.ts_crc32c.restype = ctypes.c_uint32
"""

_CPP_OK = """
extern "C" {
int ts_write_file(const char* path) { return 0; }
uint32_t ts_crc32c(const void* buf) { return 0; }
}
"""

# Declared on the Python side, missing from the C ABI — the segfault case.
_CPP_MISSING_ONE = """
extern "C" {
int ts_write_file(const char* path) { return 0; }
}
"""

# Exported from C, never declared — the drift case.
_CPP_EXTRA_ONE = """
extern "C" {
int ts_write_file(const char* path) { return 0; }
uint32_t ts_crc32c(const void* buf) { return 0; }
int ts_orphan(const void* buf) { return 0; }
}
"""


def _native_sync_errors(tmp_path, py_src, cpp_src):
    from tools.snaplint.rules.native_decl_sync import check

    py = tmp_path / "_native.py"
    cpp = tmp_path / "ts_io.cpp"
    py.write_text(py_src)
    cpp.write_text(cpp_src)
    return check(py, cpp)


def test_native_decl_sync_detects_and_accepts_fix(tmp_path):
    assert _native_sync_errors(tmp_path, _NATIVE_DECLARE_OK, _CPP_OK) == []
    missing = _native_sync_errors(
        tmp_path, _NATIVE_DECLARE_OK, _CPP_MISSING_ONE
    )
    assert len(missing) == 1 and "ts_crc32c" in missing[0]
    assert "segfault" in missing[0]
    extra = _native_sync_errors(tmp_path, _NATIVE_DECLARE_OK, _CPP_EXTRA_ONE)
    assert len(extra) == 1 and "ts_orphan" in extra[0]
    assert "never declared" in extra[0]


def test_native_decl_sync_ignores_calls_and_helpers(tmp_path):
    """C-side calls to ts_ functions and non-prefixed helpers are not
    definitions; a one-symbol surface with an internal call stays clean."""
    cpp = """
namespace {
int write_all(int fd) { return 0; }
}
extern "C" {
int ts_write_file(const char* path) {
  return write_all(0) + ts_write_file(path);
}
uint32_t ts_crc32c(const void* buf) { return 0; }
}
"""
    assert _native_sync_errors(tmp_path, _NATIVE_DECLARE_OK, cpp) == []


def test_native_decl_sync_repo_clean_on_head():
    analyzer = Analyzer(root=REPO, select=["native-decl-sync"])
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == [], "\n".join(
        f.render() for f in result.new_findings
    )


# ---------------------------------------------------------------------------
# repo-wide lane: the analyzer is clean on HEAD and wired into CI
# ---------------------------------------------------------------------------


def test_analyzer_clean_on_head_with_empty_baseline():
    """Every rule, whole package, no baseline: stays clean. A finding
    here is either a real concurrency/correctness bug (fix it) or a
    justified exception (suppress inline with a comment)."""
    analyzer = Analyzer(root=REPO)
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == [], "\n".join(
        f.render() for f in result.new_findings
    )


def test_shipped_baseline_is_empty():
    baseline = load_baseline(REPO / "tools" / "snaplint" / "baseline.json")
    assert baseline == []


def test_cli_default_lane_invocation():
    """The exact command the default lane runs: module entry point over
    the package, exit 0, stdlib-only (no jax import needed)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.snaplint", "torchsnapshot_tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "snaplint: clean" in proc.stdout


def test_cli_json_output_and_rule_listing():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "tools.snaplint",
            "torchsnapshot_tpu",
            "--json",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["new_findings"] == []

    listing = subprocess.run(
        [sys.executable, "-m", "tools.snaplint", "--list-rules"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert listing.returncode == 0
    for rule in (
        "collective-under-conditional",
        "async-blocking-call",
        "span-and-budget-balance",
        "knob-env-literal",
        "executor-thread-leak",
        "metric-name-literal",
        "span-name-literal",
        "doctor-rule-ids",
        "ledger-event-ids",
        "crashpoint-ids",
        "rpc-op-ids",
        "slo-ids",
        "tiered-test-markers",
        "native-decl-sync",
        # The protocol family (tools/snaplint/protocol/).
        "store-key-leak",
        "rank-asymmetric-protocol",
        "wait-without-error-poll",
        "rpc-unpaired",
        "commit-ordering",
        "store-namespace-docs",
    ):
        assert rule in listing.stdout


def test_unknown_rule_name_is_an_error():
    with pytest.raises(ValueError, match="unknown rule"):
        Analyzer(root=REPO, select=["no-such-rule"])


def test_legacy_rules_run_inside_the_framework():
    """The three pre-snaplint checkers are rules in the same registry;
    their project-level checks execute in a default run (clean on
    HEAD)."""
    analyzer = Analyzer(
        root=REPO,
        select=[
            "metric-name-literal",
            "span-name-literal",
            "tiered-test-markers",
        ],
    )
    result = analyzer.run([REPO / "torchsnapshot_tpu"], baseline=set())
    assert result.new_findings == []
