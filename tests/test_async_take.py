"""Async take: staging-unblock semantics, background commit, and the
no-commit-marker-on-failure invariant.

Structural model: reference tests/test_async_take.py:25-115 — subclassed
slow/faulty FS plugins patched in, asserting a failed async take leaves no
``.snapshot_metadata``.
"""

import asyncio
import contextlib
import os
import tempfile
import time
from unittest import mock

import jax.numpy as jnp
import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.io_types import WriteIO
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import multiprocess_test


class SlowFSStoragePlugin(FSStoragePlugin):
    DELAY_S = 0.3

    async def write(self, write_io: WriteIO) -> None:
        if write_io.path != SNAPSHOT_METADATA_FNAME:
            await asyncio.sleep(self.DELAY_S)
        await super().write(write_io)


from torchsnapshot_tpu.test_utils import faulty_fs_plugin
from torchsnapshot_tpu.test_utils import patch_storage_plugin as _patch_plugin

FaultyFSStoragePlugin = faulty_fs_plugin(
    lambda path: path != SNAPSHOT_METADATA_FNAME, delay_s=0.05
)


def test_async_take_roundtrip(tmp_path) -> None:
    app_state = {
        "p": ts.PyTreeState({"w": jnp.arange(128.0)}),
        "prog": ts.StateDict(step=9),
    }
    pending = ts.Snapshot.async_take(str(tmp_path), app_state)
    snapshot = pending.wait()
    assert pending.done()
    fresh = {"p": ts.PyTreeState({"w": jnp.zeros(128)}), "prog": ts.StateDict(step=0)}
    snapshot.restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["p"].tree["w"]), np.arange(128.0))
    assert fresh["prog"]["step"] == 9


def test_async_take_unblocks_before_io(tmp_path) -> None:
    with _patch_plugin(SlowFSStoragePlugin):
        app_state = {"p": ts.PyTreeState({"w": jnp.ones(64)})}
        t0 = time.monotonic()
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        returned_at = time.monotonic() - t0
        # Returned before the (deliberately slow) storage write finished...
        assert returned_at < SlowFSStoragePlugin.DELAY_S
        assert not os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)
        # ...and the commit marker appears only after wait().
        pending.wait()
    assert os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)


def test_failed_async_take_leaves_no_commit_marker(tmp_path) -> None:
    with _patch_plugin(FaultyFSStoragePlugin):
        app_state = {"p": ts.PyTreeState({"w": jnp.ones(64)})}
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        with pytest.raises(OSError, match="injected storage failure"):
            pending.wait()
    assert not os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)
    # The failed location is restorable-from never: metadata access fails.
    with pytest.raises(FileNotFoundError):
        _ = ts.Snapshot(str(tmp_path)).metadata


def test_async_take_numpy_mutation_consistency(tmp_path) -> None:
    """Mutable (numpy) leaves must be snapshotted at async_take time even if
    the application mutates them before I/O completes (reference defensive
    copy semantics, io_preparer.py:555-565)."""
    arr = np.full((32,), 1.0)
    app_state = {"s": ts.StateDict(arr=arr)}
    with _patch_plugin(SlowFSStoragePlugin):
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        arr[:] = -1.0  # mutate after staging returned
        snapshot = pending.wait()
    fresh = {"s": ts.StateDict(arr=np.zeros(32))}
    snapshot.restore(fresh)
    np.testing.assert_array_equal(fresh["s"]["arr"], np.full((32,), 1.0))


@multiprocess_test(nproc=2)
def test_async_take_peer_failure_no_commit(pg) -> None:
    """Rank 1's storage fails; the store-barrier propagates the error so
    rank 0 must not write the commit marker."""
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "async-fail-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()

    plugin_cls = FaultyFSStoragePlugin if pg.rank == 1 else FSStoragePlugin
    app_state = {"prog": ts.StateDict(rank=pg.rank), "p": ts.PyTreeState({"w": jnp.ones(8)})}
    with _patch_plugin(plugin_cls):
        pending = ts.Snapshot.async_take(path, app_state, pg=pg)
        with pytest.raises(Exception):
            pending.wait()
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


@multiprocess_test(nproc=2)
def test_async_take_rank0_staging_failure_fails_fast(pg) -> None:
    """Rank 0 fails during STAGING in a rank-0-only step (replication
    consolidation, after the non-leader manifest gather): its error must
    reach rank 1's commit thread through the commit-nonce barrier, so
    rank 1's wait() raises in seconds instead of stranding for the 300 s
    store timeout. Pins two round-5 changes together: async_take
    constructs the error-reporting barrier handle BEFORE _take_impl, and
    the memory-budget all-gather runs BEFORE the manifest gather (a peer
    must have no wrapped collective left between its gather send and the
    commit barrier — it cannot see the reported error from inside an
    op-seq poll loop)."""
    import time

    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "async-rank0-staging-fail")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    app_state = {"p": ts.PyTreeState({"w": jnp.ones(4096)})}
    t0 = time.monotonic()
    if pg.rank == 0:
        with mock.patch(
            "torchsnapshot_tpu.partitioner.consolidate_replicated_entries",
            side_effect=RuntimeError("injected staging failure"),
        ), pytest.raises(RuntimeError, match="injected staging failure"):
            ts.Snapshot.async_take(path, app_state, pg=pg, replicated=["p/**"])
    else:
        pending = ts.Snapshot.async_take(
            path, app_state, pg=pg, replicated=["p/**"]
        )
        with pytest.raises(Exception):
            pending.wait()
        assert time.monotonic() - t0 < 60.0, (
            "peer blocked to store timeout despite reported staging error"
        )
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


@multiprocess_test(nproc=2)
def test_sync_take_commit_window_failure_fails_fast(pg) -> None:
    """Rank 0's metadata write fails INSIDE the commit window (between
    barrier arrive and depart): the round-5 _reporting_to wrap means
    peers polling at depart() observe the error and abandon in seconds
    (they used to block out the full store timeout), and no commit
    marker exists."""
    import time

    import jax.numpy as jnp

    from torchsnapshot_tpu.snapshot import Snapshot

    path = os.path.join(tempfile.gettempdir(), "sync-commit-window-fail")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    app_state = {"p": ts.PyTreeState({"w": jnp.ones(1024) * pg.rank})}
    ctx = (
        mock.patch.object(
            Snapshot,
            "_write_snapshot_metadata",
            side_effect=RuntimeError("injected metadata-write failure"),
        )
        if pg.rank == 0
        else contextlib.nullcontext()
    )
    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        ts.Snapshot.take(path, app_state, pg=pg)
    assert time.monotonic() - t0 < 60.0, "peer blocked to store timeout"
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


@multiprocess_test(nproc=2)
def test_sync_take_peer_failure_fails_fast_no_commit(pg) -> None:
    """SYNC take symmetry of the async case above: rank 1's storage
    fails; rank 0 must observe the reported error at the commit barrier
    and raise well before the store timeout (it used to block the full
    300 s), and no commit marker may exist on either rank."""
    import time

    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "sync-fail-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()

    plugin_cls = FaultyFSStoragePlugin if pg.rank == 1 else FSStoragePlugin
    app_state = {
        "prog": ts.StateDict(rank=pg.rank),
        "p": ts.PyTreeState({"w": jnp.ones(8) * pg.rank}),
    }
    t0 = time.monotonic()
    with _patch_plugin(plugin_cls), pytest.raises(Exception):
        ts.Snapshot.take(path, app_state, pg=pg)
    assert time.monotonic() - t0 < 60.0, "survivor blocked to store timeout"
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


# ---------------------------------------------------------------------------
# Device-snapshot deferral (round 6): size-independent visible span,
# wait(phase=), mutation-after-return, drain-failure semantics.
# ---------------------------------------------------------------------------


def _sleepy_stage(delay_s: float):
    """Patch ArrayBufferStager's staging kernel to sleep first — makes
    'did staging run inside async_take?' observable on a fast CPU."""
    from torchsnapshot_tpu.io_preparer import ArrayBufferStager

    orig = ArrayBufferStager._stage_sync_impl

    def slow(self):
        time.sleep(delay_s)
        return orig(self)

    return mock.patch.object(ArrayBufferStager, "_stage_sync_impl", slow)


def test_async_take_returns_before_staging(tmp_path) -> None:
    """The device-snapshot default: async_take returns after capture
    dispatch; the (deliberately slow) staging runs on the background
    drain, observable at wait(phase="staged")."""
    app_state = {"p": ts.PyTreeState({"w": jnp.arange(512.0)})}
    with _sleepy_stage(0.4):
        t0 = time.monotonic()
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        returned_at = time.monotonic() - t0
        assert returned_at < 0.4, "staging ran inside the visible span"
        assert pending.wait(phase="staged") is None
        assert pending.staged()
        snapshot = pending.wait()
    fresh = {"p": ts.PyTreeState({"w": jnp.zeros(512)})}
    snapshot.restore(fresh)
    np.testing.assert_array_equal(
        np.asarray(fresh["p"].tree["w"]), np.arange(512.0)
    )


def test_async_take_device_snapshot_disabled_stages_before_return(
    tmp_path,
) -> None:
    """The kill-switch restores the pre-deferral contract: staging
    completes before async_take returns."""
    from torchsnapshot_tpu import knobs

    app_state = {"p": ts.PyTreeState({"w": jnp.arange(64.0)})}
    with knobs.disable_async_device_snapshot(), _sleepy_stage(0.3):
        t0 = time.monotonic()
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        returned_at = time.monotonic() - t0
        assert returned_at >= 0.3, "staging was deferred despite the knob"
        assert pending.staged()  # staged at construction
        pending.wait()


def test_async_take_wait_phase_validation_and_ordering(tmp_path) -> None:
    """wait(phase="staged") precedes the commit marker (storage writes
    still draining); wait() produces it; bogus phases are rejected."""
    with _patch_plugin(SlowFSStoragePlugin):
        app_state = {"p": ts.PyTreeState({"w": jnp.ones(64)})}
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        with pytest.raises(ValueError, match="staged"):
            pending.wait(phase="flushed")
        assert pending.wait(phase="staged") is None
        # Staged is the D2H boundary, not the commit: the slow writes
        # (>= DELAY_S each) are still draining behind it.
        assert not os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)
        snapshot = pending.wait(phase="committed")
        assert snapshot is not None
    assert os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)


@pytest.mark.parametrize(
    "shape", [(64,), (513, 257), (128, 1024)], ids=["tiny", "odd", "wide"]
)
def test_async_take_mutation_after_return_roundtrip(tmp_path, shape) -> None:
    """Train-step-style in-place donation/update of the live arrays
    immediately after async_take returns must not corrupt the restored
    bytes (the on-device clone is the consistency point)."""
    import jax

    key = jax.random.PRNGKey(0)
    original = jax.random.normal(key, shape, dtype=jnp.float32)
    expected = np.array(np.asarray(original))  # pre-mutation truth
    counter = np.arange(8.0)  # mutable host leaf
    app_state = {
        "p": ts.PyTreeState({"w": original}),
        "s": ts.StateDict(counter=counter),
    }
    pending = ts.Snapshot.async_take(str(tmp_path), app_state)
    # Donation-shaped mutation the moment control returns: the donated
    # buffer may be reused by XLA for the output; the numpy leaf is
    # overwritten in place.
    donate = jax.jit(lambda x: x * -2.0 + 1.0, donate_argnums=0)
    clobbered = donate(original)
    jax.block_until_ready(clobbered)
    del original
    counter[:] = -1.0
    snapshot = pending.wait()
    fresh = {
        "p": ts.PyTreeState({"w": jnp.zeros(shape, jnp.float32)}),
        "s": ts.StateDict(counter=np.zeros(8)),
    }
    snapshot.restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["p"].tree["w"]), expected)
    np.testing.assert_array_equal(fresh["s"]["counter"], np.arange(8.0))


def test_async_take_mutation_after_return_incremental(tmp_path) -> None:
    """The incremental variant: unchanged chunks reference the base (no
    clone, no write), changed chunks are captured — mutation after
    return corrupts neither."""
    import jax

    from torchsnapshot_tpu import knobs

    base_w = jnp.arange(4096.0)
    base_path = str(tmp_path / "base")
    with knobs.override_incremental_chunk_size_bytes(4096):
        ts.Snapshot.take(
            base_path,
            {"p": ts.PyTreeState({"w": base_w})},
            record_digests=True,
        )
        # Change one region; the rest of the chunks match the base.
        changed = base_w.at[:512].set(-3.0)
        expected = np.array(np.asarray(changed))
        pending = ts.Snapshot.async_take(
            str(tmp_path / "incr"),
            {"p": ts.PyTreeState({"w": changed})},
            incremental_base=base_path,
        )
        donate = jax.jit(lambda x: x * 0.0, donate_argnums=0)
        jax.block_until_ready(donate(changed))
        del changed
        snapshot = pending.wait()
    fresh = {"p": ts.PyTreeState({"w": jnp.zeros(4096)})}
    snapshot.restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["p"].tree["w"]), expected)


def test_async_take_drain_failure_surfaces_on_wait_heartbeat_terminal(
    tmp_path,
) -> None:
    """A background-drain failure AFTER async_take returned must (a)
    surface on wait() — once recorded, every wait observes the same
    error, staged and committed alike — and (b) settle the progress
    heartbeat TERMINAL ("failed"), never a crash-shaped non-terminal
    leftover the doctor would misread as interrupted-take."""
    import json

    from torchsnapshot_tpu import knobs

    fail_after = [0]

    def should_fail(path: str) -> bool:
        # Let a couple of writes through so the failure lands mid-drain.
        if path == SNAPSHOT_METADATA_FNAME:
            return False
        fail_after[0] += 1
        return fail_after[0] > 2

    plugin_cls = faulty_fs_plugin(should_fail, delay_s=0.02)
    state = {
        f"w{i}": jnp.full((256,), float(i)) for i in range(8)
    }
    with knobs.override_progress_interval_seconds(0.01), _patch_plugin(
        plugin_cls
    ):
        pending = ts.Snapshot.async_take(
            str(tmp_path), {"p": ts.PyTreeState(state)}
        )
        with pytest.raises(OSError, match="injected storage failure") as e1:
            pending.wait()
        # Idempotent re-raise: the SAME recorded failure, both phases.
        with pytest.raises(OSError) as e2:
            pending.wait()
        with pytest.raises(OSError):
            pending.wait(phase="staged")
        assert e2.value is e1.value
    assert not os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)
    heartbeat = tmp_path / ".progress-rank0.json"
    assert heartbeat.exists(), "failed op must leave a terminal heartbeat"
    doc = json.loads(heartbeat.read_text())
    assert doc["terminal"] == "failed"
    assert "injected storage failure" in (doc["error"] or "")


def test_async_take_staging_failure_unblocks_staged_wait(tmp_path) -> None:
    """A failure BEFORE the staged boundary must not strand
    wait(phase="staged"): the drain settles and the wait raises."""
    from torchsnapshot_tpu.io_preparer import ArrayBufferStager

    def boom(self):
        raise RuntimeError("injected staging failure")

    app_state = {"p": ts.PyTreeState({"w": jnp.ones(256)})}
    with mock.patch.object(ArrayBufferStager, "_stage_sync_impl", boom):
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        with pytest.raises(RuntimeError, match="injected staging failure"):
            pending.wait(phase="staged")
        with pytest.raises(RuntimeError, match="injected staging failure"):
            pending.wait()
    assert not os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)


def test_async_take_visible_staged_split_in_report(tmp_path) -> None:
    """The emitted async_take SnapshotReport carries the visible/staged
    phase split (the doctor's async-visible-stall evidence)."""
    from torchsnapshot_tpu import knobs, telemetry

    with knobs.enable_telemetry():
        pending = ts.Snapshot.async_take(
            str(tmp_path), {"p": ts.PyTreeState({"w": jnp.ones(512)})}
        )
        pending.wait()
        events_path = telemetry.events_path_for(str(tmp_path))
    events = telemetry.load_events(events_path)
    reports = [e for e in events if e.get("kind") == "async_take"]
    assert reports, "async_take must emit a report"
    report = reports[-1]
    assert report["visible_s"] is not None and report["visible_s"] >= 0
    assert report["staged_s"] is not None
    assert report["staged_s"] >= report["visible_s"]
    # The pool geometry that bounded the drain rides along (the context
    # for reading peak_staged_bytes on a pool-bounded pipeline).
    assert report["staging_pool"]["slabs"] >= 1
    assert report["staging_pool"]["capacity_bytes"] >= 1


@multiprocess_test(nproc=2)
def test_async_take_distributed_commit(pg) -> None:
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "async-ok-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    app_state = {"prog": ts.StateDict(rank=pg.rank)}
    pending = ts.Snapshot.async_take(path, app_state, pg=pg)
    snapshot = pending.wait()
    assert os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))
    fresh = {"prog": ts.StateDict(rank=-1)}
    snapshot.restore(fresh)
    assert fresh["prog"]["rank"] == pg.rank
