"""Async take: staging-unblock semantics, background commit, and the
no-commit-marker-on-failure invariant.

Structural model: reference tests/test_async_take.py:25-115 — subclassed
slow/faulty FS plugins patched in, asserting a failed async take leaves no
``.snapshot_metadata``.
"""

import asyncio
import contextlib
import os
import tempfile
import time
from unittest import mock

import jax.numpy as jnp
import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.io_types import WriteIO
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import multiprocess_test


class SlowFSStoragePlugin(FSStoragePlugin):
    DELAY_S = 0.3

    async def write(self, write_io: WriteIO) -> None:
        if write_io.path != SNAPSHOT_METADATA_FNAME:
            await asyncio.sleep(self.DELAY_S)
        await super().write(write_io)


from torchsnapshot_tpu.test_utils import faulty_fs_plugin
from torchsnapshot_tpu.test_utils import patch_storage_plugin as _patch_plugin

FaultyFSStoragePlugin = faulty_fs_plugin(
    lambda path: path != SNAPSHOT_METADATA_FNAME, delay_s=0.05
)


def test_async_take_roundtrip(tmp_path) -> None:
    app_state = {
        "p": ts.PyTreeState({"w": jnp.arange(128.0)}),
        "prog": ts.StateDict(step=9),
    }
    pending = ts.Snapshot.async_take(str(tmp_path), app_state)
    snapshot = pending.wait()
    assert pending.done()
    fresh = {"p": ts.PyTreeState({"w": jnp.zeros(128)}), "prog": ts.StateDict(step=0)}
    snapshot.restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["p"].tree["w"]), np.arange(128.0))
    assert fresh["prog"]["step"] == 9


def test_async_take_unblocks_before_io(tmp_path) -> None:
    with _patch_plugin(SlowFSStoragePlugin):
        app_state = {"p": ts.PyTreeState({"w": jnp.ones(64)})}
        t0 = time.monotonic()
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        returned_at = time.monotonic() - t0
        # Returned before the (deliberately slow) storage write finished...
        assert returned_at < SlowFSStoragePlugin.DELAY_S
        assert not os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)
        # ...and the commit marker appears only after wait().
        pending.wait()
    assert os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)


def test_failed_async_take_leaves_no_commit_marker(tmp_path) -> None:
    with _patch_plugin(FaultyFSStoragePlugin):
        app_state = {"p": ts.PyTreeState({"w": jnp.ones(64)})}
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        with pytest.raises(OSError, match="injected storage failure"):
            pending.wait()
    assert not os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)
    # The failed location is restorable-from never: metadata access fails.
    with pytest.raises(FileNotFoundError):
        _ = ts.Snapshot(str(tmp_path)).metadata


def test_async_take_numpy_mutation_consistency(tmp_path) -> None:
    """Mutable (numpy) leaves must be snapshotted at async_take time even if
    the application mutates them before I/O completes (reference defensive
    copy semantics, io_preparer.py:555-565)."""
    arr = np.full((32,), 1.0)
    app_state = {"s": ts.StateDict(arr=arr)}
    with _patch_plugin(SlowFSStoragePlugin):
        pending = ts.Snapshot.async_take(str(tmp_path), app_state)
        arr[:] = -1.0  # mutate after staging returned
        snapshot = pending.wait()
    fresh = {"s": ts.StateDict(arr=np.zeros(32))}
    snapshot.restore(fresh)
    np.testing.assert_array_equal(fresh["s"]["arr"], np.full((32,), 1.0))


@multiprocess_test(nproc=2)
def test_async_take_peer_failure_no_commit(pg) -> None:
    """Rank 1's storage fails; the store-barrier propagates the error so
    rank 0 must not write the commit marker."""
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "async-fail-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()

    plugin_cls = FaultyFSStoragePlugin if pg.rank == 1 else FSStoragePlugin
    app_state = {"prog": ts.StateDict(rank=pg.rank), "p": ts.PyTreeState({"w": jnp.ones(8)})}
    with _patch_plugin(plugin_cls):
        pending = ts.Snapshot.async_take(path, app_state, pg=pg)
        with pytest.raises(Exception):
            pending.wait()
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


@multiprocess_test(nproc=2)
def test_async_take_rank0_staging_failure_fails_fast(pg) -> None:
    """Rank 0 fails during STAGING in a rank-0-only step (replication
    consolidation, after the non-leader manifest gather): its error must
    reach rank 1's commit thread through the commit-nonce barrier, so
    rank 1's wait() raises in seconds instead of stranding for the 300 s
    store timeout. Pins two round-5 changes together: async_take
    constructs the error-reporting barrier handle BEFORE _take_impl, and
    the memory-budget all-gather runs BEFORE the manifest gather (a peer
    must have no wrapped collective left between its gather send and the
    commit barrier — it cannot see the reported error from inside an
    op-seq poll loop)."""
    import time

    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "async-rank0-staging-fail")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    app_state = {"p": ts.PyTreeState({"w": jnp.ones(4096)})}
    t0 = time.monotonic()
    if pg.rank == 0:
        with mock.patch(
            "torchsnapshot_tpu.partitioner.consolidate_replicated_entries",
            side_effect=RuntimeError("injected staging failure"),
        ), pytest.raises(RuntimeError, match="injected staging failure"):
            ts.Snapshot.async_take(path, app_state, pg=pg, replicated=["p/**"])
    else:
        pending = ts.Snapshot.async_take(
            path, app_state, pg=pg, replicated=["p/**"]
        )
        with pytest.raises(Exception):
            pending.wait()
        assert time.monotonic() - t0 < 60.0, (
            "peer blocked to store timeout despite reported staging error"
        )
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


@multiprocess_test(nproc=2)
def test_sync_take_commit_window_failure_fails_fast(pg) -> None:
    """Rank 0's metadata write fails INSIDE the commit window (between
    barrier arrive and depart): the round-5 _reporting_to wrap means
    peers polling at depart() observe the error and abandon in seconds
    (they used to block out the full store timeout), and no commit
    marker exists."""
    import time

    import jax.numpy as jnp

    from torchsnapshot_tpu.snapshot import Snapshot

    path = os.path.join(tempfile.gettempdir(), "sync-commit-window-fail")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    app_state = {"p": ts.PyTreeState({"w": jnp.ones(1024) * pg.rank})}
    ctx = (
        mock.patch.object(
            Snapshot,
            "_write_snapshot_metadata",
            side_effect=RuntimeError("injected metadata-write failure"),
        )
        if pg.rank == 0
        else contextlib.nullcontext()
    )
    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        ts.Snapshot.take(path, app_state, pg=pg)
    assert time.monotonic() - t0 < 60.0, "peer blocked to store timeout"
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


@multiprocess_test(nproc=2)
def test_sync_take_peer_failure_fails_fast_no_commit(pg) -> None:
    """SYNC take symmetry of the async case above: rank 1's storage
    fails; rank 0 must observe the reported error at the commit barrier
    and raise well before the store timeout (it used to block the full
    300 s), and no commit marker may exist on either rank."""
    import time

    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "sync-fail-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()

    plugin_cls = FaultyFSStoragePlugin if pg.rank == 1 else FSStoragePlugin
    app_state = {
        "prog": ts.StateDict(rank=pg.rank),
        "p": ts.PyTreeState({"w": jnp.ones(8) * pg.rank}),
    }
    t0 = time.monotonic()
    with _patch_plugin(plugin_cls), pytest.raises(Exception):
        ts.Snapshot.take(path, app_state, pg=pg)
    assert time.monotonic() - t0 < 60.0, "survivor blocked to store timeout"
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))


@multiprocess_test(nproc=2)
def test_async_take_distributed_commit(pg) -> None:
    import jax.numpy as jnp

    path = os.path.join(tempfile.gettempdir(), "async-ok-test")
    if pg.rank == 0:
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    app_state = {"prog": ts.StateDict(rank=pg.rank)}
    pending = ts.Snapshot.async_take(path, app_state, pg=pg)
    snapshot = pending.wait()
    assert os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))
    fresh = {"prog": ts.StateDict(rank=-1)}
    snapshot.restore(fresh)
    assert fresh["prog"]["rank"] == pg.rank
