"""Incremental takes: unchanged chunks become base refs (no bytes
written), changed chunks rewrite, restores stay byte-exact — across
dense, chunked, and sharded leaves, with checksum inheritance and chained
bases. No reference counterpart (the reference rewrites all bytes every
take); see incremental.py."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.incremental import relative_ref_prefix
from torchsnapshot_tpu.knobs import (
    override_max_chunk_size_bytes,
    override_max_shard_size_bytes,
)
from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    ShardedArrayEntry,
)
from torchsnapshot_tpu.test_utils import assert_tree_eq


def _blob_files(root: str):
    """Relative paths of all data blobs under a snapshot dir (metadata,
    checksums excluded)."""
    out = set()
    for dirpath, _, files in os.walk(root):
        for f in files:
            rel = os.path.relpath(os.path.join(dirpath, f), root)
            if rel.startswith((".snapshot_metadata", "checksums")):
                continue
            out.add(rel)
    return out


def _take_pair(tmp_path, state0, state1, **take1_kwargs):
    """Full take of state0 at step0; incremental take of state1 at step1."""
    p0 = str(tmp_path / "step_0")
    p1 = str(tmp_path / "step_1")
    ts.Snapshot.take(p0, state0, record_digests=True)
    ts.Snapshot.take(p1, state1, incremental_base=p0, **take1_kwargs)
    return p0, p1


def test_relative_ref_prefix():
    assert relative_ref_prefix("/r/step_1", "/r/step_0") == "../step_0"
    assert relative_ref_prefix("s3://b/r/step_1", "s3://b/r/step_0") == "../step_0"
    assert relative_ref_prefix("/r/step_1", "s3://b/r/step_0") is None
    assert relative_ref_prefix("/r/a", "/r/a") is None


def test_relative_ref_prefix_mixed_relative_absolute(tmp_path, monkeypatch):
    """fs roots are anchored to absolute form before relpath: mixing a
    relative take path with an absolute base (or vice versa) must yield
    the same prefix as the all-absolute spelling, not one that depends on
    the cwd at take time."""
    monkeypatch.chdir(tmp_path)
    want = relative_ref_prefix(
        str(tmp_path / "r" / "step_1"), str(tmp_path / "r" / "step_0")
    )
    assert want == "../step_0"
    assert relative_ref_prefix("r/step_1", str(tmp_path / "r" / "step_0")) == want
    assert relative_ref_prefix(str(tmp_path / "r" / "step_1"), "r/step_0") == want
    assert relative_ref_prefix("r/step_1", "r/step_0") == want
    # Same-root detection also survives mixed spellings.
    assert relative_ref_prefix("r/a", str(tmp_path / "r" / "a")) is None
    # A bare '/' root rstrips to empty: still declined (never cwd-anchored).
    assert relative_ref_prefix("/", str(tmp_path / "r" / "step_0")) is None
    assert relative_ref_prefix(str(tmp_path / "r" / "step_1"), "/") is None


def test_dense_unchanged_is_not_rewritten(tmp_path):
    w = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    b = jnp.ones((8,), jnp.float32)
    state0 = {"m": ts.PyTreeState({"w": w, "b": b})}
    state1 = {"m": ts.PyTreeState({"w": w, "b": b + 1})}  # only b changes
    p0, p1 = _take_pair(tmp_path, state0, state1)

    files1 = _blob_files(p1)
    assert any("b" in f for f in files1)
    assert not any("/w" in f or f.endswith("w") for f in files1), files1

    manifest = ts.Snapshot(p1).get_manifest()
    w_entry = manifest["0/m/w"]
    assert isinstance(w_entry, ArrayEntry)
    assert w_entry.location == "../step_0/0/m/w"
    assert w_entry.digest is not None

    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w), "b": jnp.zeros_like(b)})}
    ts.Snapshot(p1).restore(dest)
    assert_tree_eq(dest["m"].tree, {"w": w, "b": b + 1})


def test_unchanged_leaf_skips_d2h(tmp_path, monkeypatch):
    """The whole point: an unchanged leaf's bytes never cross to the host.
    Patch the stager's staging entry point and count invocations."""
    from torchsnapshot_tpu import io_preparer

    w = jnp.arange(1024, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    p0 = str(tmp_path / "s0")
    ts.Snapshot.take(p0, state, record_digests=True)

    calls = []
    orig = io_preparer.ArrayBufferStager.__init__

    def counting_init(self, arr, *a, **k):
        calls.append(1)
        return orig(self, arr, *a, **k)

    monkeypatch.setattr(io_preparer.ArrayBufferStager, "__init__", counting_init)
    ts.Snapshot.take(str(tmp_path / "s1"), state, incremental_base=p0)
    assert calls == []  # no stager was even constructed


def test_chunked_partial_change(tmp_path):
    """A large dense array chunked at dim 0: mutate one chunk's rows; the
    other chunks must be refs."""
    with override_max_chunk_size_bytes(256):  # 8x8 f32 rows = 32B/row
        base = np.arange(32 * 8, dtype=np.float32).reshape(32, 8)
        changed = base.copy()
        changed[20, 3] += 1.0  # touches exactly one chunk
        state0 = {"m": ts.PyTreeState({"big": jnp.asarray(base)})}
        state1 = {"m": ts.PyTreeState({"big": jnp.asarray(changed)})}
        p0, p1 = _take_pair(tmp_path, state0, state1)

        manifest = ts.Snapshot(p1).get_manifest()
        entry = manifest["0/m/big"]
        assert isinstance(entry, ChunkedArrayEntry)
        ref_chunks = [
            c for c in entry.chunks if c.array.location.startswith("../")
        ]
        new_chunks = [
            c for c in entry.chunks if not c.array.location.startswith("../")
        ]
        assert len(new_chunks) == 1
        assert len(ref_chunks) == len(entry.chunks) - 1
        assert new_chunks[0].offsets[0] <= 20 < new_chunks[0].offsets[0] + new_chunks[0].sizes[0]

        dest = {"m": ts.PyTreeState({"big": jnp.zeros((32, 8), jnp.float32)})}
        ts.Snapshot(p1).restore(dest)
        np.testing.assert_array_equal(np.asarray(dest["m"].tree["big"]), changed)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_sharded_partial_change(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    sharding = NamedSharding(mesh, P("x", None))
    base = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    changed = base.copy()
    changed[9, 1] = -5.0  # third shard (rows 8..12)
    arr0 = jax.device_put(base, sharding)
    arr1 = jax.device_put(changed, sharding)
    p0, p1 = _take_pair(
        tmp_path, {"m": ts.PyTreeState({"t": arr0})}, {"m": ts.PyTreeState({"t": arr1})}
    )

    manifest = ts.Snapshot(p1).get_manifest()
    entry = manifest["0/m/t"]
    assert isinstance(entry, ShardedArrayEntry)
    refs = [s for s in entry.shards if s.array.location.startswith("../")]
    news = [s for s in entry.shards if not s.array.location.startswith("../")]
    assert len(news) == 1 and news[0].offsets == [8, 0]
    assert len(refs) == 3

    dest_arr = jax.device_put(np.zeros((16, 4), np.float32), sharding)
    dest = {"m": ts.PyTreeState({"t": dest_arr})}
    ts.Snapshot(p1).restore(dest)
    np.testing.assert_array_equal(np.asarray(dest["m"].tree["t"]), changed)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_sharded_subdivided_pieces(tmp_path):
    """Shards above the shard-size knob subdivide; piece-level skipping
    must work at that granularity too."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))
    sharding = NamedSharding(mesh, P("x", None))
    with override_max_shard_size_bytes(64):  # 4 f32 per row -> 4 rows/piece... 16B/row
        base = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
        changed = base.copy()
        changed[0, 0] = 99.0  # first piece of first shard
        arr0 = jax.device_put(base, sharding)
        arr1 = jax.device_put(changed, sharding)
        p0, p1 = _take_pair(
            tmp_path,
            {"m": ts.PyTreeState({"t": arr0})},
            {"m": ts.PyTreeState({"t": arr1})},
        )
        manifest = ts.Snapshot(p1).get_manifest()
        entry = manifest["0/m/t"]
        news = [s for s in entry.shards if not s.array.location.startswith("../")]
        refs = [s for s in entry.shards if s.array.location.startswith("../")]
        assert len(news) == 1 and news[0].offsets == [0, 0]
        assert len(refs) == len(entry.shards) - 1

        dest = {
            "m": ts.PyTreeState(
                {"t": jax.device_put(np.zeros((16, 4), np.float32), sharding)}
            )
        }
        ts.Snapshot(p1).restore(dest)
        np.testing.assert_array_equal(np.asarray(dest["m"].tree["t"]), changed)


def test_chained_refs_collapse_to_origin(tmp_path):
    """step2 references an unchanged blob written at step0 *directly*,
    through the chain step2 -> step1 -> step0."""
    w = jnp.arange(32, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    p0 = str(tmp_path / "step_0")
    p1 = str(tmp_path / "step_1")
    p2 = str(tmp_path / "step_2")
    ts.Snapshot.take(p0, state, record_digests=True)
    ts.Snapshot.take(p1, state, incremental_base=p0)
    ts.Snapshot.take(p2, state, incremental_base=p1)

    entry = ts.Snapshot(p2).get_manifest()["0/m/w"]
    assert entry.location == "../step_0/0/m/w"  # not ../step_1/...

    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w)})}
    ts.Snapshot(p2).restore(dest)
    assert_tree_eq(dest["m"].tree, {"w": w})


def test_checksum_inheritance_detects_base_corruption(tmp_path):
    """Refs inherit the base's CRC entries: corrupting the base blob makes
    the *incremental* snapshot's restore fail loudly."""
    from torchsnapshot_tpu.integrity import ChecksumError

    w = jnp.arange(64, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    p0, p1 = _take_pair(
        tmp_path, state, {"m": ts.PyTreeState({"w": w})}
    )
    blob = os.path.join(p0, "0", "m", "w")
    with open(blob, "r+b") as f:
        f.seek(8)
        f.write(b"\xff\xff\xff\xff")

    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w)})}
    with pytest.raises(ChecksumError):
        ts.Snapshot(p1).restore(dest)


def test_digest_recorded_on_full_take(tmp_path):
    p0 = str(tmp_path / "s")
    ts.Snapshot.take(
        p0, {"m": ts.PyTreeState({"w": jnp.ones(8)})}, record_digests=True
    )
    entry = ts.Snapshot(p0).get_manifest()["0/m/w"]
    assert entry.digest and entry.digest.startswith("mlh64:")


def test_no_digests_without_flag(tmp_path):
    p0 = str(tmp_path / "s")
    ts.Snapshot.take(p0, {"m": ts.PyTreeState({"w": jnp.ones(8)})})
    entry = ts.Snapshot(p0).get_manifest()["0/m/w"]
    assert entry.digest is None


def test_base_without_digests_falls_back_to_full(tmp_path):
    w = jnp.arange(16, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    p0 = str(tmp_path / "s0")
    ts.Snapshot.take(p0, state)  # no digests recorded
    p1 = str(tmp_path / "s1")
    ts.Snapshot.take(p1, state, incremental_base=p0)
    entry = ts.Snapshot(p1).get_manifest()["0/m/w"]
    assert not entry.location.startswith("../")  # full write
    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w)})}
    ts.Snapshot(p1).restore(dest)
    assert_tree_eq(dest["m"].tree, {"w": w})


def test_missing_base_falls_back_to_full(tmp_path):
    w = jnp.arange(16, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    p1 = str(tmp_path / "s1")
    ts.Snapshot.take(
        p1, state, incremental_base=str(tmp_path / "never_existed")
    )
    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w)})}
    ts.Snapshot(p1).restore(dest)
    assert_tree_eq(dest["m"].tree, {"w": w})


def test_dtype_change_forces_rewrite(tmp_path):
    """Same byte pattern, different dtype: must not ref."""
    a32 = jnp.asarray(np.zeros(16, np.float32))
    ai32 = jnp.asarray(np.zeros(16, np.int32))
    p0, p1 = _take_pair(
        tmp_path,
        {"m": ts.PyTreeState({"x": a32})},
        {"m": ts.PyTreeState({"x": ai32})},
    )
    entry = ts.Snapshot(p1).get_manifest()["0/m/x"]
    assert not entry.location.startswith("../")


def test_chunk_knob_change_forces_rewrite(tmp_path):
    base = np.arange(32 * 8, dtype=np.float32).reshape(32, 8)
    state = {"m": ts.PyTreeState({"big": jnp.asarray(base)})}
    p0 = str(tmp_path / "s0")
    with override_max_chunk_size_bytes(256):
        ts.Snapshot.take(p0, state, record_digests=True)
    p1 = str(tmp_path / "s1")
    with override_max_chunk_size_bytes(512):  # different chunk boundaries
        ts.Snapshot.take(p1, state, incremental_base=p0)
        entry = ts.Snapshot(p1).get_manifest()["0/m/big"]
        for chunk in entry.chunks:
            assert not chunk.array.location.startswith("../")
    dest = {"m": ts.PyTreeState({"big": jnp.zeros((32, 8), jnp.float32)})}
    ts.Snapshot(p1).restore(dest)
    np.testing.assert_array_equal(np.asarray(dest["m"].tree["big"]), base)


def test_incremental_async_take(tmp_path):
    w = jnp.arange(64, dtype=jnp.float32)
    b = jnp.ones((8,), jnp.float32)
    state0 = {"m": ts.PyTreeState({"w": w, "b": b})}
    p0 = str(tmp_path / "s0")
    ts.Snapshot.take(p0, state0, record_digests=True)

    state1 = {"m": ts.PyTreeState({"w": w, "b": b * 3})}
    pending = ts.Snapshot.async_take(
        str(tmp_path / "s1"), state1, incremental_base=p0
    )
    snap = pending.wait()
    entry = snap.get_manifest()["0/m/w"]
    assert entry.location == "../s0/0/m/w"
    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w), "b": jnp.zeros_like(b)})}
    snap.restore(dest)
    assert_tree_eq(dest["m"].tree, {"w": w, "b": b * 3})


def test_read_object_through_ref(tmp_path):
    w = jnp.arange(16, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    p0, p1 = _take_pair(tmp_path, state, {"m": ts.PyTreeState({"w": w})})
    out = ts.Snapshot(p1).read_object("0/m/w")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(w))


def test_np_host_leaves_incremental(tmp_path):
    """Host numpy leaves participate via the bit-identical host digest."""
    w = np.arange(24, dtype=np.float32)
    state0 = {"m": ts.StateDict(w=w.copy(), v=np.zeros(4, np.int32))}
    state1 = {"m": ts.StateDict(w=w.copy(), v=np.ones(4, np.int32))}
    p0, p1 = _take_pair(tmp_path, state0, state1)
    manifest = ts.Snapshot(p1).get_manifest()
    assert manifest["0/m/w"].location.startswith("../")
    assert not manifest["0/m/v"].location.startswith("../")
    dest = {"m": ts.StateDict(w=np.zeros_like(w), v=np.zeros(4, np.int32))}
    ts.Snapshot(p1).restore(dest)
    np.testing.assert_array_equal(dest["m"]["w"], w)
    np.testing.assert_array_equal(dest["m"]["v"], np.ones(4, np.int32))


# ---------------------------------------------------------------------------
# distributed
# ---------------------------------------------------------------------------

from torchsnapshot_tpu.test_utils import multiprocess_test  # noqa: E402


@multiprocess_test(nproc=2)
def test_distributed_incremental_replicated_and_per_rank(pg) -> None:
    """World-2 incremental take: replicated refs agree across ranks (the
    consolidation assert would trip otherwise), changed per-rank state
    rewrites, unchanged replicated state refs the base."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    root = os.path.join(tempfile.gettempdir(), "dist-incr-test")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    p0 = os.path.join(root, "step_0")
    p1 = os.path.join(root, "step_1")

    w = jnp.full((64, 8), 7.5, jnp.float32)
    state0 = {
        "params": ts.PyTreeState({"w": w, "b": jnp.arange(8.0)}),
        "progress": ts.StateDict(rank_steps=100 + pg.rank),
    }
    ts.Snapshot.take(p0, state0, pg=pg, replicated=["params/**"],
                     record_digests=True)

    state1 = {
        "params": ts.PyTreeState({"w": w, "b": jnp.arange(8.0) + 1}),
        "progress": ts.StateDict(rank_steps=200 + pg.rank),
    }
    snap = ts.Snapshot.take(
        p1, state1, pg=pg, replicated=["params/**"], incremental_base=p0
    )
    md = snap.metadata
    # Unchanged replicated leaf refs the base; changed one was rewritten.
    assert md.manifest["0/params/w"].location == "../step_0/replicated/params/w"
    assert md.manifest["0/params/b"].location == "replicated/params/b"
    assert not os.path.exists(os.path.join(p1, "replicated", "params", "w"))

    fresh = {
        "params": ts.PyTreeState({"w": jnp.zeros((64, 8)), "b": jnp.zeros(8)}),
        "progress": ts.StateDict(rank_steps=-1),
    }
    ts.Snapshot(p1, pg=pg).restore(fresh)
    assert float(fresh["params"].tree["w"][0, 0]) == 7.5
    assert float(fresh["params"].tree["b"][5]) == 6.0
    assert fresh["progress"]["rank_steps"] == 200 + pg.rank


def test_incremental_chunk_knob_refines_skip_unit(tmp_path):
    """Digest-enabled takes chunk at the incremental-chunk knob, so a
    sparse row update skips the untouched fine chunks even when the array
    is below the plain chunk threshold."""
    from torchsnapshot_tpu.knobs import override_incremental_chunk_size_bytes

    base = np.random.default_rng(0).standard_normal((256, 16)).astype(np.float32)
    changed = base.copy()
    changed[100] += 1.0
    with override_incremental_chunk_size_bytes(1024):  # 16 rows/chunk
        p0, p1 = _take_pair(
            tmp_path,
            {"m": ts.PyTreeState({"t": jnp.asarray(base)})},
            {"m": ts.PyTreeState({"t": jnp.asarray(changed)})},
        )
        entry = ts.Snapshot(p1).get_manifest()["0/m/t"]
        assert isinstance(entry, ChunkedArrayEntry)
        news = [c for c in entry.chunks if not c.array.location.startswith("../")]
        refs = [c for c in entry.chunks if c.array.location.startswith("../")]
        assert len(news) == 1 and len(refs) == len(entry.chunks) - 1
    dest = {"m": ts.PyTreeState({"t": jnp.zeros((256, 16), jnp.float32)})}
    ts.Snapshot(p1).restore(dest)
    np.testing.assert_array_equal(np.asarray(dest["m"].tree["t"]), changed)


def test_plain_take_chunking_unaffected_by_incremental_knob(tmp_path):
    """Without digests, the incremental-chunk knob must not change blob
    layout (a plain take of a 1 MiB array stays one blob)."""
    from torchsnapshot_tpu.knobs import override_incremental_chunk_size_bytes

    arr = jnp.asarray(np.zeros((256, 16), np.float32))
    with override_incremental_chunk_size_bytes(1024):
        p = str(tmp_path / "s")
        ts.Snapshot.take(p, {"m": ts.PyTreeState({"t": arr})})
        entry = ts.Snapshot(p).get_manifest()["0/m/t"]
        assert isinstance(entry, ArrayEntry)  # not chunked


def test_memory_scheme_refuses_refs(tmp_path):
    """memory:// stores are flat per-name namespaces: refs must be
    refused (full take) rather than written and then unrestorable."""
    assert relative_ref_prefix("memory://s1", "memory://s0") is None

    w = jnp.arange(16, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    ts.Snapshot.take("memory://incr-s0", state, record_digests=True)
    ts.Snapshot.take(
        "memory://incr-s1", state, incremental_base="memory://incr-s0"
    )
    entry = ts.Snapshot("memory://incr-s1").get_manifest()["0/m/w"]
    assert not entry.location.startswith("../")
    dest = {"m": ts.PyTreeState({"w": jnp.zeros_like(w)})}
    ts.Snapshot("memory://incr-s1").restore(dest)
    assert_tree_eq(dest["m"].tree, {"w": w})


def test_cross_bucket_refuses_refs():
    assert relative_ref_prefix("s3://b1/r/s1", "s3://b2/r/s0") is None
    assert relative_ref_prefix("gs://b/x/s1", "gs://b/y/s0") == "../../y/s0"


@multiprocess_test(nproc=2)
def test_distributed_degraded_base_agrees(pg) -> None:
    """If only one rank can read the base, no rank may emit refs for
    replicated leaves — the take degrades to full on every rank instead
    of tripping the consolidation assert."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    root = os.path.join(tempfile.gettempdir(), "dist-incr-degraded")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    p0 = os.path.join(root, "step_0")
    p1 = os.path.join(root, "step_1")
    w = jnp.full((16, 4), 2.0, jnp.float32)
    state = {"params": ts.PyTreeState({"w": w})}
    ts.Snapshot.take(p0, state, pg=pg, replicated=["params/**"],
                     record_digests=True)

    # Rank 1 is handed a nonexistent base: its build() falls back.
    base = p0 if pg.rank == 0 else os.path.join(root, "no_such_step")
    snap = ts.Snapshot.take(
        p1, state, pg=pg, replicated=["params/**"], incremental_base=base
    )
    entry = snap.metadata.manifest["0/params/w"]
    assert not entry.location.startswith("../")  # degraded to full everywhere

    fresh = {"params": ts.PyTreeState({"w": jnp.zeros((16, 4))})}
    ts.Snapshot(p1, pg=pg).restore(fresh)
    assert float(fresh["params"].tree["w"][3, 3]) == 2.0


@multiprocess_test(nproc=2)
def test_replication_promotion_forces_rewrite(pg) -> None:
    """A leaf saved per-rank at the base and replicated now must rewrite
    (per-rank base locations would diverge across ranks)."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    root = os.path.join(tempfile.gettempdir(), "dist-incr-promote")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    p0 = os.path.join(root, "step_0")
    p1 = os.path.join(root, "step_1")
    w = jnp.full((8,), 4.0, jnp.float32)
    state = {"params": ts.PyTreeState({"w": w})}
    ts.Snapshot.take(p0, state, pg=pg, record_digests=True)  # per-rank
    snap = ts.Snapshot.take(
        p1, state, pg=pg, replicated=["params/**"], incremental_base=p0
    )
    entry = snap.metadata.manifest["0/params/w"]
    assert entry.replicated and not entry.location.startswith("../")


# ---------------------------------------------------------------------------
# CheckpointManager integration: chained saves, pinning, cascade GC
# ---------------------------------------------------------------------------


def _mgr_state(v_w, v_t):
    return {
        "m": ts.PyTreeState(
            {
                "w": jnp.full((64,), float(v_w), jnp.float32),
                "t": jnp.full((32,), float(v_t), jnp.float32),
            }
        )
    }


def test_manager_incremental_chain_and_restore(tmp_path):
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root, incremental=True)
    mgr.save(0, _mgr_state(1, 1))
    mgr.save(1, _mgr_state(1, 2))  # only t changes
    mgr.save(2, _mgr_state(1, 3))

    man2 = ts.Snapshot(mgr.step_path(2)).get_manifest()
    assert man2["0/m/w"].location == "../step_0000000000/0/m/w"
    assert not man2["0/m/t"].location.startswith("../")

    dest = _mgr_state(0, 0)
    assert mgr.restore_latest(dest) == 2
    assert float(dest["m"].tree["w"][0]) == 1.0
    assert float(dest["m"].tree["t"][0]) == 3.0


def test_manager_retention_pins_referenced_base(tmp_path):
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root, keep_last_n=2, incremental=True)
    for step in range(4):
        mgr.save(step, _mgr_state(1, step))  # w never changes -> refs step 0

    index = json.loads(
        (tmp_path / "ckpts" / ".manager_index").read_text()
    )
    assert index["steps"] == [2, 3]
    assert index["pinned"] == [0]  # w blob origin, still referenced
    # Pinned step's blobs survive; its commit marker too (blobs readable).
    assert os.path.exists(os.path.join(mgr.step_path(0), "0", "m", "w"))
    # Step 1 was dropped and not referenced (its only novel blob was t).
    assert not os.path.exists(os.path.join(mgr.step_path(1), "0", "m", "t"))

    # Restore still works through the pin.
    dest = _mgr_state(0, 0)
    assert mgr.restore_latest(dest) == 3
    assert float(dest["m"].tree["w"][0]) == 1.0
    assert float(dest["m"].tree["t"][0]) == 3.0


def test_manager_cascade_deletes_unreferenced_pin(tmp_path):
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root, keep_last_n=2, incremental=True)
    for step in range(4):
        mgr.save(step, _mgr_state(1, step))  # pins step 0 (w origin)
    assert os.path.exists(os.path.join(mgr.step_path(0), "0", "m", "w"))

    # Change w: new steps stop referencing step 0; once no retained step
    # refs it, the pin is released and its blobs deleted.
    mgr.save(4, _mgr_state(2, 4))
    mgr.save(5, _mgr_state(2, 5))
    index = json.loads((tmp_path / "ckpts" / ".manager_index").read_text())
    assert index["steps"] == [4, 5]
    assert index.get("pinned", []) == [4] or index.get("pinned", []) == []
    assert not os.path.exists(os.path.join(mgr.step_path(0), "0", "m", "w"))

    dest = _mgr_state(0, 0)
    assert mgr.restore_latest(dest) == 5
    assert float(dest["m"].tree["w"][0]) == 2.0
    assert float(dest["m"].tree["t"][0]) == 5.0


def test_manager_async_incremental(tmp_path):
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root, incremental=True)
    mgr.async_save(0, _mgr_state(1, 1)).wait()
    pending = mgr.async_save(1, _mgr_state(1, 2))
    pending.wait()
    man1 = ts.Snapshot(mgr.step_path(1)).get_manifest()
    assert man1["0/m/w"].location.startswith("../step_0000000000")
    index = json.loads((tmp_path / "ckpts" / ".manager_index").read_text())
    assert index["refs"]["1"] == [0]


def test_manager_old_index_format_still_reads(tmp_path):
    root = tmp_path / "ckpts"
    mgr = ts.CheckpointManager(str(root))
    mgr.save(0, _mgr_state(1, 1))
    # Rewrite the index in the pre-incremental format.
    (root / ".manager_index").write_text(json.dumps({"steps": [0]}))
    (root / ".manager_index.backup").write_text(json.dumps({"steps": [0]}))
    assert mgr.all_steps() == [0]
    mgr.save(1, _mgr_state(1, 2))
    assert mgr.all_steps() == [0, 1]


def test_manager_non_incremental_unaffected(tmp_path):
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root)  # incremental off
    mgr.save(0, _mgr_state(1, 1))
    mgr.save(1, _mgr_state(1, 2))
    man1 = ts.Snapshot(mgr.step_path(1)).get_manifest()
    assert not man1["0/m/w"].location.startswith("../")
    index = json.loads((tmp_path / "ckpts" / ".manager_index").read_text())
    assert "refs" not in index and "pinned" not in index
