"""Test configuration: force an 8-device virtual CPU mesh.

Multi-device sharding semantics (the analog of the reference's
gloo-on-one-box trick, test_utils.py:205-238) are exercised without TPU pods
by asking XLA's host platform for 8 virtual devices. Must run before jax
initializes a backend, hence the env mutation at import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
