"""Test configuration: force an 8-device virtual CPU mesh.

Multi-device sharding semantics (the analog of the reference's
gloo-on-one-box trick, test_utils.py:205-238) are exercised without TPU pods
by asking XLA's host platform for 8 virtual devices.

This environment pre-imports jax at interpreter startup with the TPU
platform pinned via JAX_PLATFORMS, so mutating the env here is too late for
this process — the platform is switched through jax.config instead (the
backend itself is created lazily, so this works as long as no test ran yet).
The env vars are still set for the benefit of subprocesses spawned by
multi-process tests. Set TS_TEST_ON_TPU=1 to run the suite against the real
chip instead.
"""

import os

# Stall watchdog off by default in the suite (0 disables): the fast
# lane must never pay for (or get flagged by) a 60 s-deadline scanner.
# Tests that exercise the watchdog opt back in via
# knobs.override_watchdog_deadline_seconds().
os.environ.setdefault("TORCHSNAPSHOT_TPU_WATCHDOG_SECONDS", "0")

# Live-progress heartbeat files and the per-manager step history are
# likewise off by default (0 disables both): tier-1 snapshot/manager
# dirs must hold exactly the files the code under test wrote. Tests
# that exercise them opt back in via
# knobs.override_progress_interval_seconds() /
# knobs.override_history_max_records(). The in-memory
# telemetry.current_progress() view stays on regardless.
os.environ.setdefault("TORCHSNAPSHOT_TPU_PROGRESS_SECONDS", "0")
os.environ.setdefault("TORCHSNAPSHOT_TPU_HISTORY_MAX_RECORDS", "0")

# The run-level goodput ledger is pinned off for the same reason
# ("0" = no .ledger.jsonl reads/writes anywhere): tier-1 manager tests
# assert about exactly the files their saves produce. Ledger/goodput
# tests opt back in via knobs.enable_ledger().
os.environ.setdefault("TORCHSNAPSHOT_TPU_LEDGER", "0")

# Fan-out restore is pinned off in the suite ("0" = every rank reads
# its own bytes from storage): tier-1 distributed restore tests assert
# about the exact pre-fan-out read path (which plugin reads happen
# where, fail-fast windows). Fan-out tests opt back in via
# knobs.enable_fanout_restore() / an env override in their workers.
os.environ.setdefault("TORCHSNAPSHOT_TPU_FANOUT_RESTORE", "0")

# The peer-RAM checkpoint tier is pinned off in the suite ("0" = no
# cache server, no pushes, no restore-ladder pulls): tier-1 manager and
# restore tests assert about the exact pre-peer read/write paths and
# file sets. Peer-tier tests opt back in via knobs.enable_peer_tier()
# or an env override in their multiprocess workers.
os.environ.setdefault("TORCHSNAPSHOT_TPU_PEER_TIER", "0")

# O_DIRECT fs writes are pinned off in the suite ("0" = buffered; also
# the packaged default): CI filesystems vary — some support O_DIRECT,
# some decline with EINVAL — and tier-1 write-path assertions must not
# depend on which one this container mounts. Direct-I/O tests opt back
# in via knobs.enable_fs_direct_io() and assert BOTH outcomes. The
# zero-pack vectorized write stays at its packaged default (ON) so the
# tier-1 batching lane exercises the production slab path.
os.environ.setdefault("TORCHSNAPSHOT_TPU_FS_DIRECT_IO", "0")

# The write-path autotuner is likewise off by default in the suite
# ("0" = kill switch): tier-1 manager tests must run the exact
# hand-set/default knob geometry they assert about, with no
# .tuner-state.json appearing in their roots. Tuner tests opt back in
# via knobs.enable_autotune().
os.environ.setdefault("TORCHSNAPSHOT_TPU_AUTOTUNE", "0")

# The coordination store stays a single hub in the suite (1 = no shard
# servers; also the packaged default): tier-1 distributed tests assert
# about exact store traffic and must not depend on key->shard spread.
# Scale-model tests build ShardedStore members explicitly. The tree
# barrier stays at its packaged default (ON) so the tier-1 distributed
# lane exercises the production rendezvous topology.
os.environ.setdefault("TORCHSNAPSHOT_TPU_STORE_SHARDS", "1")

# The content-addressed chunk store is pinned off in the suite ("0" =
# the legacy per-step layout; also the packaged default): tier-1
# snapshot/manager tests assert about the exact per-step file sets and
# byte placement. CAS tests opt back in via knobs.enable_cas() or an
# env override in their multiprocess workers.
os.environ.setdefault("TORCHSNAPSHOT_TPU_CAS", "0")

# The checkpoint-CDN publish hook is pinned off in the suite ("0";
# also the packaged default): tier-1 manager tests assert about exact
# store traffic and per-save side effects, and must not depend on
# announce writes. CDN tests opt back in via env override or by
# setting TORCHSNAPSHOT_TPU_CDN=1 around the manager hook under test.
os.environ.setdefault("TORCHSNAPSHOT_TPU_CDN", "0")

# The fleet metrics plane is pinned off in the suite ("0"; also the
# packaged default): tier-1 distributed tests assert about exact store
# traffic and must not see __obs/ publish writes. Fleet-plane tests
# opt back in via knobs.enable_fleet_obs() or an env override in their
# multiprocess workers.
os.environ.setdefault("TORCHSNAPSHOT_TPU_FLEET_OBS", "0")

# The SLO engine is pinned off in the suite ("0"): tier-1 manager
# tests run with tiny synthetic budgets where normal operations would
# look like breaches, and must not see slo-breach ledger events or
# burn gauges they didn't ask for. SLO tests opt back in via
# knobs.enable_slo(). Incident-bundle capture is likewise disabled
# (max bytes 0 = no capture) so tier-1 roots never grow a .bundles/
# dir from an injected failure; bundle tests opt back in via
# knobs.override_bundle_max_bytes().
os.environ.setdefault("TORCHSNAPSHOT_TPU_SLO", "0")
os.environ.setdefault("TORCHSNAPSHOT_TPU_BUNDLE_MAX_BYTES", "0")

if os.environ.get("TS_TEST_ON_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
        os.environ["XLA_FLAGS"] = _flags

    import jax

    jax.config.update("jax_platforms", "cpu")
