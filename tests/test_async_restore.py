"""Async restore: reads on a background thread, state applied at wait().

No reference counterpart (its restore is synchronous); the TPU use case
is overlapping restore I/O with train-step compilation — the dominant
term in restore-to-step0 (BENCH.md)."""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.test_utils import assert_tree_eq, multiprocess_test


def _state(seed: float):
    return {
        "params": ts.PyTreeState(
            {
                "w": jnp.full((32, 16), seed, jnp.float32),
                "b": jnp.full((16,), seed * 2, jnp.bfloat16),
            }
        ),
        "progress": ts.StateDict(step=int(seed * 10), lr=0.5),
        "rng": ts.RngState(jax.random.key(int(seed))),
    }


def test_async_restore_matches_sync(tmp_path):
    src = _state(3.0)
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, src)

    dest_sync = _state(0.0)
    ts.Snapshot(p).restore(dest_sync)

    dest_async = _state(0.0)
    pending = ts.Snapshot(p).async_restore(dest_async)
    pending.wait()

    assert_tree_eq(dest_async["params"].tree, dest_sync["params"].tree)
    assert dict(dest_async["progress"]) == dict(dest_sync["progress"])
    np.testing.assert_array_equal(
        jax.random.key_data(dest_async["rng"].keys),
        jax.random.key_data(dest_sync["rng"].keys),
    )


def test_jax_leaves_untouched_until_wait(tmp_path):
    """Until wait() returns, the destination's jax leaves must hold their
    pre-restore values (reads land in fresh buffers)."""
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, _state(5.0))

    dest = _state(1.0)
    before = np.asarray(dest["params"].tree["w"]).copy()
    pending = ts.Snapshot(p).async_restore(dest)
    # Regardless of background progress, the leaf object is immutable and
    # still bound: the application sees old state until wait().
    np.testing.assert_array_equal(np.asarray(dest["params"].tree["w"]), before)
    pending.wait()
    assert float(dest["params"].tree["w"][0, 0]) == 5.0


def test_wait_idempotent(tmp_path):
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, _state(2.0))
    dest = _state(0.0)
    pending = ts.Snapshot(p).async_restore(dest)
    pending.wait()
    pending.wait()  # second wait is a no-op, not a double-apply
    assert float(dest["params"].tree["w"][0, 0]) == 2.0


def test_error_propagates_and_state_unmodified(tmp_path):
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, _state(4.0))
    # Corrupt storage: remove one blob after take.
    os.remove(os.path.join(p, "0", "params", "w"))

    dest = _state(1.0)
    pending = ts.Snapshot(p).async_restore(dest)
    with pytest.raises(FileNotFoundError):
        pending.wait()
    # Nothing was applied: jax leaves still hold pre-restore values.
    assert float(dest["params"].tree["w"][0, 0]) == 1.0
    assert dest["progress"]["step"] == 10


def test_done_flips_after_reads(tmp_path):
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, _state(2.0))
    dest = _state(0.0)
    pending = ts.Snapshot(p).async_restore(dest)
    pending.wait()
    assert pending.done()


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_async_restore_sharded(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    sharding = NamedSharding(mesh, P("x", None))
    host = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    arr = jax.device_put(host, sharding)
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, {"m": ts.PyTreeState({"t": arr})})

    dest_arr = jax.device_put(np.zeros((16, 8), np.float32), sharding)
    dest = {"m": ts.PyTreeState({"t": dest_arr})}
    pending = ts.Snapshot(p).async_restore(dest)
    pending.wait()
    restored = dest["m"].tree["t"]
    assert restored.sharding == sharding
    np.testing.assert_array_equal(np.asarray(restored), host)


def test_overlap_with_computation(tmp_path):
    """The intended pattern: kick off restore, compile/compute, wait."""
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, _state(7.0))
    dest = _state(0.0)
    pending = ts.Snapshot(p).async_restore(dest)
    # Simulate compilation work on the main thread while reads proceed.
    f = jax.jit(lambda x: jnp.tanh(x) @ jnp.tanh(x).T)
    _ = f(jnp.ones((64, 64))).block_until_ready()
    pending.wait()
    assert float(dest["params"].tree["w"][0, 0]) == 7.0


def test_manager_async_restore_latest(tmp_path):
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root)
    assert mgr.async_restore_latest(_state(0.0)) is None  # fresh run
    mgr.save(0, _state(1.0))
    mgr.save(5, _state(6.0))
    dest = _state(0.0)
    out = mgr.async_restore_latest(dest)
    assert out is not None
    step, pending = out
    assert step == 5
    pending.wait()
    assert float(dest["params"].tree["w"][0, 0]) == 6.0


def test_async_restore_incremental_chain(tmp_path):
    """Async restore reads through ../ refs like the sync path."""
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root, incremental=True)
    mgr.save(0, _state(1.0))
    s = _state(1.0)
    s["progress"] = ts.StateDict(step=99, lr=0.25)
    mgr.save(1, s)
    dest = _state(0.0)
    step, pending = mgr.async_restore_latest(dest)
    pending.wait()
    assert step == 1
    assert float(dest["params"].tree["w"][0, 0]) == 1.0
    assert dest["progress"]["step"] == 99


@multiprocess_test(nproc=2)
def test_distributed_async_restore(pg) -> None:
    import shutil

    root = os.path.join(tempfile.gettempdir(), "dist-async-restore")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    state = {
        "params": ts.PyTreeState({"w": jnp.full((8, 4), 3.0, jnp.float32)}),
        "progress": ts.StateDict(rank_steps=10 + pg.rank),
    }
    ts.Snapshot.take(root, state, pg=pg, replicated=["params/**"])

    dest = {
        "params": ts.PyTreeState({"w": jnp.zeros((8, 4), jnp.float32)}),
        "progress": ts.StateDict(rank_steps=-1),
    }
    pending = ts.Snapshot(root, pg=pg).async_restore(dest)
    pending.wait()
    assert float(dest["params"].tree["w"][1, 1]) == 3.0
    assert dest["progress"]["rank_steps"] == 10 + pg.rank


@multiprocess_test(nproc=2)
def test_distributed_async_restore_asymmetric_keys(pg) -> None:
    """Ranks holding plans for different key subsets must not diverge on
    barrier counts (one barrier per gathered key, plan or no plan)."""
    import shutil

    root = os.path.join(tempfile.gettempdir(), "dist-async-asym")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    state = {
        "progress": ts.StateDict(rank_steps=10 + pg.rank),
    }
    if pg.rank == 0:
        state["extra"] = ts.StateDict(only_on_rank0=42)
    ts.Snapshot.take(root, state, pg=pg)

    dest = {"progress": ts.StateDict(rank_steps=-1)}
    if pg.rank == 0:
        dest["extra"] = ts.StateDict(only_on_rank0=-1)
    pending = ts.Snapshot(root, pg=pg).async_restore(dest)
    pending.wait()
    assert dest["progress"]["rank_steps"] == 10 + pg.rank
    if pg.rank == 0:
        assert dest["extra"]["only_on_rank0"] == 42


@multiprocess_test(nproc=2)
def test_distributed_async_restore_rng_on_one_rank(pg) -> None:
    """An RngState present on only one rank must not perturb the shared
    barrier schedule (the RNG key keeps its sorted slot; only its apply
    is deferred)."""
    import shutil

    root = os.path.join(tempfile.gettempdir(), "dist-async-rng")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    state = {
        "aa": ts.StateDict(v=1 + pg.rank),
        "zz": ts.StateDict(w=100 + pg.rank),
    }
    if pg.rank == 0:
        state["mm_rng"] = ts.RngState(jax.random.key(3))
    ts.Snapshot.take(root, state, pg=pg)

    dest = {
        "aa": ts.StateDict(v=-1),
        "zz": ts.StateDict(w=-1),
    }
    if pg.rank == 0:
        dest["mm_rng"] = ts.RngState(jax.random.key(9))
    pending = ts.Snapshot(root, pg=pg).async_restore(dest)
    pending.wait()
    assert dest["aa"]["v"] == 1 + pg.rank
    assert dest["zz"]["w"] == 100 + pg.rank
    if pg.rank == 0:
        np.testing.assert_array_equal(
            jax.random.key_data(dest["mm_rng"].keys),
            jax.random.key_data(jax.random.key(3)),
        )


@multiprocess_test(nproc=2)
def test_async_restore_peer_planning_failure_fails_fast(pg) -> None:
    """Rank 1 fails during async-restore PLANNING (a pre-read setup
    phase): round 5 keys the plan loop with error-propagating barriers
    (agreed before any storage read), so rank 0 abandons at the plan
    barrier in seconds — previously it stranded in a plain op-seq
    barrier, where a reported error is invisible, for the full store
    timeout."""
    import shutil
    import time
    from unittest import mock

    from torchsnapshot_tpu.pg_wrapper import PGWrapper
    from torchsnapshot_tpu.snapshot import Snapshot

    path = os.path.join(tempfile.gettempdir(), "async-restore-plan-fail")
    if pg.rank == 0:
        shutil.rmtree(path, ignore_errors=True)
    PGWrapper(pg).barrier()
    state = {"m": ts.PyTreeState({"w": np.full(2048, 1.0 + pg.rank)})}
    ts.Snapshot.take(path, state, pg=pg)

    dest = {"m": ts.PyTreeState({"w": np.zeros(2048)})}
    import contextlib

    ctx = (
        mock.patch.object(
            Snapshot,
            "_plan_stateful_load",
            side_effect=RuntimeError("injected planning failure"),
        )
        if pg.rank == 1
        else contextlib.nullcontext()
    )
    t0 = time.monotonic()
    with ctx, pytest.raises(Exception):
        pending = ts.Snapshot(path, pg=pg).async_restore(dest)
        pending.wait()
    assert time.monotonic() - t0 < 60.0, "peer blocked to store timeout"

    # A clean retry still restores correctly.
    dest2 = {"m": ts.PyTreeState({"w": np.zeros(2048)})}
    ts.Snapshot(path, pg=pg).async_restore(dest2).wait()
    assert float(np.asarray(dest2["m"].tree["w"])[0]) == 1.0 + pg.rank
