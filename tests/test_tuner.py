"""Write-path autotuner: tunable bounds/steps, env-always-wins
precedence, policy verdict->direction mapping, decision-log
round-trips, revert-on-regression, the manager's closed loop, the
kill switch, and cross-rank decision consistency.

Acceptance pins (ISSUE 7): all ranks apply the same decided values for
a given step (broadcast via dist_store); a tuner move that makes the
take worse is reverted to the prior known-good vector on the next step
(fault-injection); TORCHSNAPSHOT_TPU_AUTOTUNE=0 means no tuner
reads/writes at all.
"""

import json
import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.manager import CheckpointManager
from torchsnapshot_tpu.telemetry import names
from torchsnapshot_tpu.test_utils import run_multiprocess
from torchsnapshot_tpu.tuner import (
    Autotuner,
    TUNABLES,
    TunerState,
    autotuner as autotuner_mod,
    policy,
    state as tuner_state,
    tunables,
)


@pytest.fixture(autouse=True)
def _clean_overrides():
    telemetry.reset_metrics()
    knobs.clear_tuner_overrides()
    yield
    knobs.clear_tuner_overrides()
    telemetry.reset_metrics()


def _state(seed=0, n=2048):
    rng = np.random.default_rng(seed)
    return {"w": rng.standard_normal(n).astype(np.float32)}


# ---------------------------------------------------------------------------
# Tunables: bounds, steps, env pinning
# ---------------------------------------------------------------------------


def test_tunable_move_is_bounded_and_clamped():
    t = TUNABLES["staging_threads"]
    assert t.move(4, +1) == 8
    assert t.move(4, -1) == 2
    assert t.move(32, +1) == 32  # clamped at hi
    assert t.saturated(32, +1)
    assert t.move(1, -1) == 1  # clamped at lo
    assert t.saturated(1, -1)
    # int tunables always move by at least 1 (no rounding stall).
    slabs = TUNABLES["staging_pool_slabs"]
    assert slabs.move(3, +1) >= 4
    frac = TUNABLES["memory_budget_fraction"]
    assert frac.move(0.6, +1) == pytest.approx(0.75)
    assert frac.move(0.9, +1) == pytest.approx(0.9)


def test_apply_vector_respects_env_and_budget_clamp():
    with knobs.override_staging_threads(3):
        applied = tunables.apply_vector(
            {"staging_threads": 16, "io_concurrency": 32}
        )
        # Env-pinned tunable keeps the operator's value; the other
        # entry lands through the override layer.
        assert applied["staging_threads"] == 3
        assert applied["io_concurrency"] == 32
        assert knobs.get_per_rank_io_concurrency() == 32
    # Pool geometry never exceeds the process budget it is clamped to.
    applied = tunables.apply_vector(
        {
            "staging_pool_slabs": 4,
            "staging_pool_slab_bytes": 512 * tunables.MIB,
        },
        memory_budget_bytes=256 * tunables.MIB,
    )
    assert (
        applied["staging_pool_slabs"] * applied["staging_pool_slab_bytes"]
        <= 256 * tunables.MIB
    )
    # A budget below slabs x slab-bytes-floor shrinks the slab COUNT
    # too (the slab-bytes lower bound must not re-overcommit the pool).
    clamped = tunables.clamp_vector(
        {
            "staging_pool_slabs": 4,
            "staging_pool_slab_bytes": 512 * tunables.MIB,
        },
        memory_budget_bytes=40 * tunables.MIB,
    )
    assert clamped["staging_pool_slab_bytes"] == 16 * tunables.MIB
    assert clamped["staging_pool_slabs"] == 2
    assert (
        clamped["staging_pool_slabs"] * clamped["staging_pool_slab_bytes"]
        <= 40 * tunables.MIB
    )


# ---------------------------------------------------------------------------
# Policy: verdict -> direction table
# ---------------------------------------------------------------------------


def test_policy_maps_verdicts_to_directions():
    vec = tunables.current_vector()
    d, _ = policy.decide([names.RULE_BUDGET_STARVED], vec, {}, 0, 0)
    assert (d.tunable, d.direction) == ("memory_budget_fraction", +1)
    d, _ = policy.decide([names.RULE_WRITE_TAIL_STALL], vec, {}, 0, 0)
    assert (d.tunable, d.direction) == ("io_concurrency", +1)
    d, _ = policy.decide([names.RULE_RETRY_STORM], vec, {}, 0, 0)
    assert (d.tunable, d.direction) == ("io_concurrency", -1)
    d, _ = policy.decide([names.RULE_D2H_BOUND], vec, {}, 0, 0)
    assert d.action == "hold"  # at the ceiling: back off
    # Priority: a starved take gets its budget fix even when also
    # d2h-bound.
    d, _ = policy.decide(
        [names.RULE_D2H_BOUND, names.RULE_BUDGET_STARVED], vec, {}, 0, 0
    )
    assert d.tunable == "memory_budget_fraction"


def test_policy_falls_through_saturated_and_cooling_candidates():
    vec = dict(tunables.current_vector())
    vec["memory_budget_fraction"] = 0.9  # saturated up
    d, _ = policy.decide([names.RULE_BUDGET_STARVED], vec, {}, 0, 0)
    assert d.tunable == "staging_pool_slab_bytes"  # next candidate
    # A cooling-down move is skipped; beyond the cooldown it is legal
    # again.
    cooldowns = {policy.move_key("io_concurrency", +1): 0}
    d, _ = policy.decide([names.RULE_WRITE_TAIL_STALL], vec, cooldowns, 1, 0)
    assert (d.tunable, d.direction) == ("max_chunk_size_bytes", -1)
    d, _ = policy.decide(
        [names.RULE_WRITE_TAIL_STALL],
        vec,
        cooldowns,
        policy.COOLDOWN_DECISIONS + 1,
        0,
    )
    assert (d.tunable, d.direction) == ("io_concurrency", +1)


def test_policy_exploration_round_robin_and_convergence():
    vec = dict(tunables.current_vector())
    d, idx = policy.decide([], vec, {}, 0, 0)
    assert (d.reason, d.tunable) == ("explore", "staging_threads")
    d, idx = policy.decide([], vec, {}, 1, idx)
    assert d.tunable == "io_concurrency"
    d, idx = policy.decide([], vec, {}, 2, idx)
    assert d.tunable == "staging_pool_slab_bytes"
    # Everything saturated -> converged hold.
    maxed = dict(vec)
    for name in tunables.explore_order():
        maxed[name] = TUNABLES[name].hi
    d, _ = policy.decide([], maxed, {}, 3, 0)
    assert d.action == "hold"
    assert "converged" in d.reason


# ---------------------------------------------------------------------------
# State: crash-safe decision log
# ---------------------------------------------------------------------------


def test_state_round_trips_and_bounds(tmp_path):
    root = str(tmp_path)
    st = TunerState(vector={"staging_threads": 8}, known_good={})
    for i in range(tuner_state.MAX_DECISIONS + 5):
        st.record_decision({"step": i, "decision": {"action": "hold"}})
    path = tuner_state.save_state(root, st)
    assert path is not None and os.path.basename(path) == ".tuner-state.json"
    loaded = tuner_state.load_state(root)
    assert loaded.vector == {"staging_threads": 8}
    assert len(loaded.decisions) == tuner_state.MAX_DECISIONS
    assert loaded.decision_count == tuner_state.MAX_DECISIONS + 5
    # Corrupt state restarts the climb instead of failing a save.
    with open(path, "w") as f:
        f.write("{torn")
    assert tuner_state.load_state(root) is None
    # Object-store roots have no local decision log.
    assert tuner_state.state_path_for("s3://bucket/ckpt") is None


# ---------------------------------------------------------------------------
# Autotuner: observe -> decide -> revert-on-regression
# ---------------------------------------------------------------------------


def _fake_report(take_s, mb=128):
    nbytes = mb * 1024 * 1024
    return {
        "kind": "take",
        "rank": 0,
        "phases": {"staging": round(take_s * 0.4, 3), "writing": take_s},
        "bytes_moved": nbytes,
        "budget_wait_s": 0.0,
        "retries": {},
        "mirror": {},
        "tunables": knobs.tunable_snapshot(),
    }


def test_autotuner_reverts_on_regression_and_cools_down(tmp_path):
    """Fault injection: the tuner makes a move, the next take is far
    worse -> the prior known-good vector is restored and the offending
    move goes on cooldown (the MAD trend math doctor --trend ships)."""
    root = str(tmp_path)
    at = Autotuner(root)
    for step in range(3):
        at._decide(step, _fake_report(take_s=1.0))
    st = tuner_state.load_state(root)
    last = st.decisions[-1]["decision"]
    assert last["action"] == "adjust"
    known_good = dict(st.known_good)
    adjusted_vector = dict(st.vector)
    assert adjusted_vector != known_good

    vec = at._decide(3, _fake_report(take_s=3.0))  # injected regression
    st = tuner_state.load_state(root)
    reverted = st.decisions[-1]["decision"]
    assert reverted["action"] == "revert"
    assert reverted["tunable"] == last["tunable"]
    assert "regression" in reverted["reason"]
    assert vec == known_good  # the prior known-good vector is back
    assert st.vector == known_good
    key = policy.move_key(last["tunable"], last["direction"])
    assert key in st.cooldowns


def test_autotuner_survives_restart_from_state_file(tmp_path):
    root = str(tmp_path)
    at = Autotuner(root)
    at._decide(0, _fake_report(take_s=1.0))
    saved = tuner_state.load_state(root)
    fresh = Autotuner(root)  # new process, same root
    vec = fresh._decide(1, _fake_report(take_s=1.0))
    st = tuner_state.load_state(root)
    assert len(st.decisions) == 2
    assert st.decisions[0]["step"] == 0 and st.decisions[1]["step"] == 1
    # The climb resumed from the persisted vector (step 0's adjustment
    # is still present in step 1's decided vector), and the exploration
    # round-robin continued instead of restarting.
    first = saved.decisions[-1]["decision"]
    assert vec[first["tunable"]] == first["to_value"]
    second = st.decisions[-1]["decision"]
    assert (second.get("tunable"), second.get("action")) != (
        first["tunable"],
        "adjust",
    ) or second["from_value"] == first["to_value"]


# ---------------------------------------------------------------------------
# Manager closed loop
# ---------------------------------------------------------------------------


def test_manager_closed_loop_records_decisions_and_knob_trajectory(
    tmp_path,
):
    root = str(tmp_path / "ckpt")
    with knobs.enable_autotune(), knobs.override_history_max_records(16):
        mgr = CheckpointManager(root, keep_last_n=2)
        for step in range(3):
            mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
        state_path = os.path.join(root, ".tuner-state.json")
        assert os.path.exists(state_path)
        doc = json.load(open(state_path))
        assert [d["step"] for d in doc["decisions"]] == [0, 1, 2]
        for d in doc["decisions"]:
            assert d["decision"]["action"] in ("adjust", "hold", "revert")
            assert d["vector"]  # replayable: every record carries it
        # The take reports and history rows carry the knob snapshot the
        # step ran under.
        report = telemetry.last_report("take")
        assert report.tunables["staging_threads"] >= 1
        from torchsnapshot_tpu.telemetry import history

        rows = history.load_history(history.history_path_for(root))
        assert len(rows) == 3
        assert all(r.get("tunables") for r in rows)


def test_kill_switch_means_no_tuner_reads_or_writes(tmp_path):
    """TORCHSNAPSHOT_TPU_AUTOTUNE=0 (the suite default): no
    .tuner-state.json, no overrides installed, no autotuner object —
    the only schema addition anywhere is the report's knob snapshot."""
    assert not knobs.is_autotune_enabled()
    root = str(tmp_path / "ckpt")
    mgr = CheckpointManager(root, keep_last_n=2)
    for step in range(2):
        mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
    assert not os.path.exists(os.path.join(root, ".tuner-state.json"))
    assert knobs.get_tuner_overrides() == {}
    assert mgr._autotuner is None
    # The knob snapshot field is recorded either way.
    assert telemetry.last_report("take").tunables is not None


def test_autotuner_holds_without_a_report(tmp_path):
    at = Autotuner(str(tmp_path))
    vec = at._decide(0, None)
    assert vec == tunables.current_vector()
    assert tuner_state.load_state(str(tmp_path)) is None  # nothing observed


# ---------------------------------------------------------------------------
# Cross-rank consistency (broadcast via dist_store)
# ---------------------------------------------------------------------------


def _rank_consistency_worker(pg, root: str):
    from torchsnapshot_tpu import knobs as _knobs
    from torchsnapshot_tpu.tuner import (
        state as _tuner_state,
        tunables as _tunables,
    )

    with _knobs.enable_autotune():
        mgr = CheckpointManager(root, pg=pg)
        rng = np.random.default_rng(pg.rank)
        state = {"w": rng.standard_normal(2048).astype(np.float32)}
        applied = []
        for step in range(3):
            mgr.save(step, {"s": ts.PyTreeState(state)})
            applied.append(dict(_tunables.current_vector()))
        st = _tuner_state.load_state(root) if pg.rank == 0 else None
        decided_steps = [d["step"] for d in st.decisions] if st else None
        return applied, decided_steps


def test_all_ranks_apply_the_same_decided_vector(tmp_path):
    """Rank 0 decides; the decision is broadcast over the dist_store
    coordinator and applied identically — ranks never run mixed
    geometries."""
    results = run_multiprocess(
        _rank_consistency_worker, nproc=2, args=(str(tmp_path / "ckpt"),)
    )
    assert len(results) == 2
    vectors = [r[0] for r in results]
    for step_idx in range(3):
        assert vectors[0][step_idx] == vectors[1][step_idx], (
            f"rank vectors diverged at step {step_idx}"
        )
    # The loop really ran: rank 0's decision log names every step.
    assert results[0][1] == [0, 1, 2]
