"""GPipe schedule over a pp mesh axis (parallel/pipeline.py): correctness
vs unpipelined sequential application, differentiability, and the
checkpoint round-trip of stacked per-stage state — the one state layout
the GSPMD flagship model never produces (SURVEY.md §2.12)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.parallel import (
    pipeline_stage_shardings,
    pipelined_apply,
    stack_stage_params,
)

# The GPipe schedule itself (pipelined_apply) rides
# utils.shard_map_compat: top-level jax.shard_map where it exists, the
# jax.experimental spelling on pre-promotion 0.4.x releases (this
# container's included). Skip only when NEITHER spelling exists.
def _has_shard_map() -> bool:
    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map  # noqa: F401

        return True
    except ImportError:
        return False


needs_shard_map = pytest.mark.skipif(
    not _has_shard_map(),
    reason="this jax has neither jax.shard_map nor "
    "jax.experimental.shard_map; pipelined_apply requires one",
)


def _pp_mesh(n: int) -> Mesh:
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.asarray(jax.devices()[:n]).reshape(n), ("pp",))


def _stage_fn(params, x):
    h = jnp.tanh(x @ params["w"] + params["b"])
    return h + x  # residual keeps the hopping shape


def _make_stages(n_stages: int, d: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.standard_normal((d, d)) * 0.1, jnp.float32),
            "b": jnp.asarray(rng.standard_normal((d,)) * 0.1, jnp.float32),
        }
        for _ in range(n_stages)
    ]


@needs_shard_map
def test_pipeline_matches_sequential():
    n_stages, d = 4, 16
    mesh = _pp_mesh(n_stages)
    per_stage = _make_stages(n_stages, d)
    stacked = stack_stage_params(per_stage, mesh=mesh)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((8, d)), jnp.float32
    )
    out = pipelined_apply(
        _stage_fn, stacked, x, mesh=mesh, n_microbatches=4
    )
    ref = x
    for p in per_stage:
        ref = _stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


@needs_shard_map
def test_pipeline_bubble_only_schedule():
    """n_microbatches == 1 (pure bubble) still yields the right answer."""
    n_stages, d = 2, 8
    mesh = _pp_mesh(n_stages)
    per_stage = _make_stages(n_stages, d, seed=3)
    stacked = stack_stage_params(per_stage, mesh=mesh)
    x = jnp.ones((2, d), jnp.float32)
    out = pipelined_apply(_stage_fn, stacked, x, mesh=mesh, n_microbatches=1)
    ref = x
    for p in per_stage:
        ref = _stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


@needs_shard_map
def test_pipeline_grad():
    """Reverse-mode through the schedule (the backward pipeline) matches
    the unpipelined gradient."""
    n_stages, d = 2, 8
    mesh = _pp_mesh(n_stages)
    per_stage = _make_stages(n_stages, d, seed=5)
    stacked = stack_stage_params(per_stage, mesh=mesh)
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((4, d)), jnp.float32
    )

    def loss_pipe(params):
        return jnp.sum(
            pipelined_apply(_stage_fn, params, x, mesh=mesh, n_microbatches=2)
            ** 2
        )

    def loss_seq(per_stage_params):
        y = x
        for p in per_stage_params:
            y = _stage_fn(p, y)
        return jnp.sum(y**2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(per_stage)
    g_seq_stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *g_seq
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        g_pipe,
        g_seq_stacked,
    )


def test_pipeline_state_checkpoint_roundtrip(tmp_path):
    """Per-stage state through the checkpointer: stacked pp-sharded params
    save and restore byte-identically, including into a DIFFERENT pp
    degree (elastic resharding of the stage dim)."""
    n_stages, d = 4, 16
    mesh = _pp_mesh(n_stages)
    stacked = stack_stage_params(_make_stages(n_stages, d, seed=7), mesh=mesh)
    path = str(tmp_path / "snap")
    ts.Snapshot.take(path, {"pp": ts.PyTreeState(stacked)})

    # Same pp degree.
    dest = jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            jnp.zeros_like(leaf), leaf.sharding
        ),
        stacked,
    )
    wrapped = ts.PyTreeState(dest)
    ts.Snapshot(path).restore({"pp": wrapped})
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        wrapped.tree,
        stacked,
    )

    # Elastic: restore into pp=2 (stage dim resharded via overlap math).
    mesh2 = _pp_mesh(2)
    sh2 = pipeline_stage_shardings(stacked, mesh2)
    dest2 = jax.tree_util.tree_map(
        lambda leaf, s: jax.device_put(jnp.zeros_like(leaf), s),
        stacked,
        sh2,
    )
    wrapped2 = ts.PyTreeState(dest2)
    ts.Snapshot(path).restore({"pp": wrapped2})
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        wrapped2.tree,
        stacked,
    )


def test_pipeline_rejects_stage_mesh_mismatch():
    mesh = _pp_mesh(2)
    stacked = stack_stage_params(_make_stages(4, 8), mesh=None)
    with pytest.raises(ValueError, match="4 stages.*2 devices"):
        pipelined_apply(
            _stage_fn, stacked, jnp.ones((4, 8)), mesh=mesh, n_microbatches=2
        )
