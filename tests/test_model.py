"""Flagship transformer: sharded training + checkpoint integration.

The reference ships model-free, but its benchmarks/tests exercise the
checkpointer against DDP/FSDP/torchrec workloads (SURVEY.md §2.12); this is
the TPU analog — a dp/sp/tp(+ep)-sharded transformer whose train state
round-trips through Snapshot, including elastic restore onto a different
mesh shape.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.models import (
    TransformerConfig,
    init_train_state,
    make_mesh,
    make_train_step,
)


def _cfg(n_experts: int = 0) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=64,
        d_model=32,
        n_heads=4,
        n_layers=2,
        d_ff=64,
        n_experts=n_experts,
        moe_every=2,
        learning_rate=1e-2,
    )


def _tokens(cfg: TransformerConfig, mesh=None, batch: int = 4, seq: int = 16):
    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq))
    toks = toks.astype(np.int32)
    if mesh is None:
        return jnp.asarray(toks)
    return jax.device_put(toks, NamedSharding(mesh, P("dp", None)))


def test_train_step_reduces_loss() -> None:
    cfg = _cfg()
    state = init_train_state(cfg, seed=0)
    step = make_train_step(cfg)
    toks = _tokens(cfg)
    losses = []
    for _ in range(8):
        state, loss = step(state, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 8


@pytest.mark.parametrize("n_experts", [0, 4])
def test_sharded_train_step_matches_single_device(n_experts: int) -> None:
    cfg = _cfg(n_experts=n_experts)
    mesh = make_mesh(8)
    sharded = init_train_state(cfg, seed=0, mesh=mesh)
    single = init_train_state(cfg, seed=0)
    _, loss_sharded = make_train_step(cfg, mesh=mesh)(
        sharded, _tokens(cfg, mesh)
    )
    _, loss_single = make_train_step(cfg)(single, _tokens(cfg))
    np.testing.assert_allclose(
        float(loss_sharded), float(loss_single), rtol=2e-2
    )


def test_sharded_state_checkpoint_roundtrip(tmp_path) -> None:
    cfg = _cfg(n_experts=4)
    mesh = make_mesh(8)
    state = init_train_state(cfg, seed=3, mesh=mesh)
    state, _ = make_train_step(cfg, mesh=mesh)(state, _tokens(cfg, mesh))
    ts.Snapshot.take(str(tmp_path), {"train": ts.PyTreeState(state.as_pytree())})

    # Destination from a different seed so a silent no-op restore fails.
    dest = ts.PyTreeState(init_train_state(cfg, seed=11, mesh=mesh).as_pytree())
    ts.Snapshot(str(tmp_path)).restore({"train": dest})
    for a, b in zip(
        jax.tree_util.tree_leaves(state.as_pytree()),
        jax.tree_util.tree_leaves(dest.tree),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_elastic_restore_to_different_mesh(tmp_path) -> None:
    """Save on an 8-device (2,2,2) mesh, restore onto a 4-device (1,2,2)
    mesh — shard layouts differ, bytes must not."""
    cfg = _cfg(n_experts=4)
    mesh8 = make_mesh(8)
    state = init_train_state(cfg, seed=5, mesh=mesh8)
    ts.Snapshot.take(str(tmp_path), {"train": ts.PyTreeState(state.as_pytree())})

    mesh4 = make_mesh(4)
    dest_state = init_train_state(cfg, seed=9, mesh=mesh4)
    dest = ts.PyTreeState(dest_state.as_pytree())
    ts.Snapshot(str(tmp_path)).restore({"train": dest})
    for a, b in zip(
        jax.tree_util.tree_leaves(state.as_pytree()),
        jax.tree_util.tree_leaves(dest.tree),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restored_state_reenters_train_step(tmp_path):
    """Value equality is not enough: the restored train state must be
    USABLE — re-enter the jitted train step next to mesh-committed params.
    Regression: mesh-replicated scalars (optax counts) restored into an
    uncommitted destination used to come back committed to device 0,
    making the first post-restore step fail with incompatible devices."""
    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.models.transformer import TrainState

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=0,
    )
    mesh = make_mesh(8)
    state = init_train_state(cfg, seed=0, mesh=mesh)
    step_fn = make_train_step(cfg, mesh=mesh)
    tokens = jax.device_put(
        np.random.default_rng(0).integers(0, 128, (4, 16)).astype(np.int32),
        NamedSharding(mesh, P("dp", None)),
    )
    state, _ = step_fn(state, tokens)  # counts become mesh-committed

    path = str(tmp_path / "snap")
    ts.Snapshot.take(path, {"train": ts.PyTreeState(state.as_pytree())})
    dest = init_train_state(cfg, seed=1, mesh=mesh)
    wrapped = ts.PyTreeState(dest.as_pytree())
    ts.Snapshot(path).restore({"train": wrapped})
    t = wrapped.tree
    restored = TrainState(
        params=t["params"], opt_state=t["opt_state"], step=t["step"], rng=t["rng"]
    )
    next_state, loss = step_fn(restored, tokens)  # must not raise
    assert np.isfinite(float(loss))
    assert int(next_state.step) == 2
