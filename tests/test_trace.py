"""Checkpoint flight recorder: span ring semantics, Chrome-trace export
determinism, cross-rank merge + clock-offset alignment, the stall
watchdog, and the per-operation export wiring through take/restore.

Acceptance pins (ISSUE 3):

- ``python -m torchsnapshot_tpu.telemetry trace <snapshot>`` merges
  per-rank ``.trace-*.json`` files into one Chrome trace-event JSON
  that the validator below confirms is well-formed (sorted ts, balanced
  B/E pairs per track);
- an injected >= deadline stall produces a ``watchdog:stall`` instant
  carrying the open-span tree and bumps ``watchdog_stalls_total``
  exactly once;
- ring-buffer eviction keeps the newest spans.
"""

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.telemetry import names, trace
from torchsnapshot_tpu.telemetry.trace import (
    SpanRecorder,
    chrome_trace,
    longest_spans,
    merge_traces,
    spans_from_chrome,
    summarize_merged,
    write_trace_file,
)
from torchsnapshot_tpu.telemetry.watchdog import reset_watchdog


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Trace tests read the process-global recorder and registry:
    isolate them, and make sure no test leaves a watchdog running."""
    telemetry.reset_metrics()
    telemetry.reset_trace()
    reset_watchdog()
    yield
    reset_watchdog()
    telemetry.reset_metrics()
    telemetry.reset_trace()


def _state(n=3, size=512, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n)
    }


def validate_chrome(doc):
    """The acceptance validator: JSON-shaped trace events, ts sorted
    non-decreasing, and per-(pid, tid) B/E pairs balanced with proper
    stack discipline."""
    events = doc["traceEvents"]
    last_ts = None
    stacks = {}
    for ev in events:
        assert ev["ph"] in ("M", "B", "E", "i"), ev
        if ev["ph"] == "M":
            continue
        assert isinstance(ev["ts"], int)
        if last_ts is not None:
            assert ev["ts"] >= last_ts, "timestamps not sorted"
        last_ts = ev["ts"]
        key = (ev["pid"], ev["tid"])
        if ev["ph"] == "B":
            assert "name" in ev
            stacks.setdefault(key, []).append(ev["name"])
        elif ev["ph"] == "E":
            assert stacks.get(key), f"E without matching B on {key}"
            stacks[key].pop()
    dangling = {k: v for k, v in stacks.items() if v}
    assert not dangling, f"unbalanced B/E: {dangling}"


# ---------------------------------------------------------------------------
# Recorder semantics
# ---------------------------------------------------------------------------


def test_span_nesting_and_args_recorded():
    rec = SpanRecorder(capacity=64)
    with rec.span(names.SPAN_TAKE, path="/x"):
        with rec.span(names.SPAN_STORAGE_WRITE, plugin="fs", blob="0/a"):
            pass
        rec.instant(names.INSTANT_STORAGE_RETRY, scope="s3")
    events = rec.events_since(0)
    assert [e["name"] for e in events] == [
        names.SPAN_STORAGE_WRITE,
        names.INSTANT_STORAGE_RETRY,
        names.SPAN_TAKE,
    ]  # completion order: inner span first, envelope last
    by_name = {e["name"]: e for e in events}
    assert by_name[names.SPAN_STORAGE_WRITE]["args"]["blob"] == "0/a"
    assert by_name[names.SPAN_TAKE]["args"]["path"] == "/x"
    assert by_name[names.INSTANT_STORAGE_RETRY]["ph"] == "i"
    # The envelope's span contains the inner span on the timeline.
    outer, inner = by_name[names.SPAN_TAKE], by_name[names.SPAN_STORAGE_WRITE]
    assert outer["ts"] <= inner["ts"]
    assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]


def test_ring_eviction_keeps_newest_spans():
    rec = SpanRecorder(capacity=8)
    for i in range(30):
        with rec.span(names.SPAN_PIPELINE_STAGE, blob=f"b{i}"):
            pass
    events = rec.events_since(0)
    assert len(events) == 8
    assert rec.dropped == 22
    # Newest survive, oldest evicted.
    blobs = [e["args"]["blob"] for e in events]
    assert blobs == [f"b{i}" for i in range(22, 30)]


def test_mark_windows_the_export():
    rec = SpanRecorder(capacity=64)
    with rec.span(names.SPAN_TAKE, path="old"):
        pass
    mark = rec.mark()
    with rec.span(names.SPAN_TAKE, path="new"):
        pass
    events = rec.events_since(mark)
    assert [e["args"]["path"] for e in events] == ["new"]


def test_mark_carries_dropped_baseline_for_window_local_drops():
    rec = SpanRecorder(capacity=4)
    for _ in range(10):
        with rec.span(names.SPAN_PIPELINE_STAGE):
            pass
    mark = rec.mark()
    assert mark.dropped == rec.dropped == 6
    for _ in range(6):
        with rec.span(names.SPAN_PIPELINE_STAGE):
            pass
    # What export_op_trace stamps into the file: this window's
    # evictions, not the recorder's lifetime total.
    assert rec.dropped - mark.dropped == 6


def test_open_spans_and_stall_flag():
    rec = SpanRecorder(capacity=64)
    token = rec.begin(names.SPAN_STORAGE_WRITE, plugin="fs", blob="0/a")
    spans = rec.open_spans()
    assert len(spans) == 1 and spans[0]["name"] == names.SPAN_STORAGE_WRITE
    assert rec.flag_stalled(spans[0]["token"])
    assert not rec.flag_stalled(spans[0]["token"])  # fire-once latch
    rec.end(token)
    assert rec.open_spans() == []
    assert not rec.flag_stalled(token)  # closed span: gone


def test_end_is_noop_for_unknown_token():
    rec = SpanRecorder(capacity=8)
    rec.end(12345)  # never raises; double-close is a silent no-op
    assert rec.events_since(0) == []


# ---------------------------------------------------------------------------
# Chrome export: determinism + validity under concurrent writers
# ---------------------------------------------------------------------------


def test_concurrent_writers_export_valid_and_deterministic():
    rec = SpanRecorder(capacity=4096)

    def worker(i):
        for j in range(40):
            with rec.span(names.SPAN_PIPELINE_STAGE, blob=f"t{i}/{j}"):
                with rec.span(
                    names.SPAN_STORAGE_WRITE, plugin="fs", blob=f"t{i}/{j}"
                ):
                    pass

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = rec.events_since(0)
    assert len(events) == 8 * 40 * 2
    doc = chrome_trace(events, rec.tid_names(), rank=0)
    validate_chrome(doc)
    # Deterministic: exporting the same recorder twice yields the same
    # document (stable event ordering), and it round-trips JSON.
    doc2 = chrome_trace(rec.events_since(0), rec.tid_names(), rank=0)
    assert doc["traceEvents"] == doc2["traceEvents"]
    assert json.loads(json.dumps(doc))["traceEvents"] == doc["traceEvents"]


def test_asyncio_tasks_get_distinct_tracks():
    """Interleaved coroutine spans on ONE thread must not cross B/E
    stacks: each task is its own track."""
    rec = SpanRecorder(capacity=256)

    async def op(i):
        with rec.span(names.SPAN_STORAGE_WRITE, plugin="s3", blob=f"b{i}"):
            await asyncio.sleep(0.001 * (i % 3))

    async def main():
        await asyncio.gather(*(op(i) for i in range(16)))

    asyncio.new_event_loop().run_until_complete(main())
    events = rec.events_since(0)
    assert len(events) == 16
    validate_chrome(chrome_trace(events, rec.tid_names(), rank=0))


# ---------------------------------------------------------------------------
# Take / restore wiring: per-op export
# ---------------------------------------------------------------------------


def test_take_and_restore_export_traces(tmp_path):
    snap = str(tmp_path / "snap")
    app_state = {"s": ts.PyTreeState(_state())}
    with knobs.enable_trace():
        ts.Snapshot.take(snap, app_state)
        snapshot = ts.Snapshot(snap)
        snapshot.restore(app_state)
    take_trace = os.path.join(snap, ".trace-take-rank0.json")
    restore_trace = os.path.join(snap, ".trace-restore-rank0.json")
    assert os.path.exists(take_trace) and os.path.exists(restore_trace)
    with open(take_trace) as f:
        doc = json.load(f)
    validate_chrome(doc)
    span_names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "B"}
    # The take envelope, the pipeline stages, and the fs writes all
    # landed on one timeline.
    assert names.SPAN_TAKE in span_names
    assert names.SPAN_PIPELINE_STAGE in span_names
    assert names.SPAN_STORAGE_WRITE in span_names
    with open(restore_trace) as f:
        rdoc = json.load(f)
    validate_chrome(rdoc)
    rnames = {e["name"] for e in rdoc["traceEvents"] if e["ph"] == "B"}
    assert names.SPAN_RESTORE in rnames
    assert names.SPAN_STORAGE_READ in rnames


def test_trace_dir_knob_takes_precedence(tmp_path):
    snap = str(tmp_path / "snap")
    trace_dir = str(tmp_path / "traces")
    with knobs.override_trace_dir(trace_dir):
        ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state())})
    assert os.path.exists(
        os.path.join(trace_dir, "trace-take-rank0.json")
    )
    assert not os.path.exists(os.path.join(snap, ".trace-take-rank0.json"))


def test_trace_sink_disabled_writes_nothing(tmp_path):
    snap = str(tmp_path / "snap")
    ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state())})
    assert not [
        f for f in os.listdir(snap) if f.startswith(".trace-")
    ]


def test_async_take_exports_trace(tmp_path):
    snap = str(tmp_path / "snap")
    with knobs.enable_trace():
        pending = ts.Snapshot.async_take(snap, {"s": ts.PyTreeState(_state())})
        pending.wait()
    with open(os.path.join(snap, ".trace-async_take-rank0.json")) as f:
        doc = json.load(f)
    validate_chrome(doc)
    span_names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "B"}
    assert names.SPAN_ASYNC_TAKE_STAGE in span_names
    assert names.SPAN_ASYNC_TAKE_COMMIT in span_names


# ---------------------------------------------------------------------------
# Cross-rank merge
# ---------------------------------------------------------------------------


def _fake_rank_trace(tmp_path, rank, t0_us, span_dur_us):
    rec = SpanRecorder(capacity=64)
    with rec.span(names.SPAN_TAKE, path="/snap", rank=rank):
        with rec.span(names.SPAN_STORAGE_WRITE, plugin="fs", blob=f"{rank}/a"):
            pass
    events = rec.events_since(0)
    # Rebase onto a synthetic clock so offsets are exact.
    base = min(e["ts"] for e in events)
    for e in events:
        e["ts"] = t0_us + (e["ts"] - base)
        if e["ph"] == "X":
            e["dur"] = span_dur_us
    doc = chrome_trace(events, rec.tid_names(), rank=rank)
    path = str(tmp_path / f".trace-take-rank{rank}.json")
    write_trace_file(path, doc)
    return path


def test_merge_sorts_and_keeps_balance(tmp_path):
    p0 = _fake_rank_trace(tmp_path, 0, 1_000_000, 500)
    p1 = _fake_rank_trace(tmp_path, 1, 1_000_200, 900)
    merged = merge_traces([p0, p1])
    validate_chrome(merged)
    pids = {e["pid"] for e in merged["traceEvents"] if e["ph"] != "M"}
    assert pids == {0, 1}


def test_merge_separates_files_claiming_the_same_rank(tmp_path):
    """Two co-hosted processes' mirror exports both claim rank 0; the
    merge must give each file its own pid — overlaying them on one pid
    would interleave their tracks and tear the B/E stacks."""
    p0 = _fake_rank_trace(tmp_path, 0, 1_000_000, 500)
    sub = tmp_path / "other"
    sub.mkdir()
    p1 = _fake_rank_trace(sub, 0, 1_000_100, 900)
    merged = merge_traces([p0, p1])
    validate_chrome(merged)
    pids = {e["pid"] for e in merged["traceEvents"] if e["ph"] != "M"}
    assert len(pids) == 2


def test_merge_applies_clock_offsets(tmp_path):
    # Rank 1's clock runs 0.5 s ahead; with the offset applied its
    # events shift back into rank 0's frame.
    p0 = _fake_rank_trace(tmp_path, 0, 1_000_000, 500)
    p1 = _fake_rank_trace(tmp_path, 1, 1_500_000, 500)
    plain = merge_traces([p0, p1])
    aligned = merge_traces([p0, p1], {0: 0.0, 1: 0.5})
    validate_chrome(aligned)

    def rank_min_ts(doc, pid):
        return min(
            e["ts"]
            for e in doc["traceEvents"]
            if e["ph"] == "B" and e["pid"] == pid
        )

    assert rank_min_ts(plain, 1) - rank_min_ts(plain, 0) == 500_000
    assert rank_min_ts(aligned, 1) == rank_min_ts(aligned, 0)


def test_merge_degrades_when_a_rank_has_no_clock_offset(tmp_path, caplog):
    """A rank missing from the offsets map (older schema, or it never
    reached the gather) merges UNCORRECTED with a warning and an
    ``unaligned_ranks`` flag — never a failed merge or a dropped pid."""
    p0 = _fake_rank_trace(tmp_path, 0, 1_000_000, 500)
    p1 = _fake_rank_trace(tmp_path, 1, 1_500_000, 500)
    with caplog.at_level("WARNING", logger="torchsnapshot_tpu.telemetry.trace"):
        merged = merge_traces([p0, p1], {0: 0.0})
    validate_chrome(merged)
    assert merged["otherData"]["unaligned_ranks"] == [1]
    assert any("no clock offset" in r.message for r in caplog.records)

    def rank_min_ts(doc, pid):
        return min(
            e["ts"]
            for e in doc["traceEvents"]
            if e["ph"] == "B" and e["pid"] == pid
        )

    # Rank 1's events are present and verbatim (unshifted), not dropped.
    pids = {e["pid"] for e in merged["traceEvents"] if e["ph"] != "M"}
    assert pids == {0, 1}
    assert rank_min_ts(merged, 1) - rank_min_ts(merged, 0) == 500_000


def test_merge_cli_end_to_end(tmp_path, capsys):
    """The acceptance path: python -m torchsnapshot_tpu.telemetry trace
    <dir> merges per-rank files, writes well-formed JSON, and renders a
    straggler summary."""
    _fake_rank_trace(tmp_path, 0, 1_000_000, 500)
    _fake_rank_trace(tmp_path, 1, 1_000_100, 2_000)
    from torchsnapshot_tpu.telemetry.stats import main as stats_main

    rc = stats_main(["trace", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "longest spans" in out
    assert "straggler" in out
    merged_path = tmp_path / ".trace.merged.json"
    assert merged_path.exists()
    with open(merged_path) as f:
        validate_chrome(json.load(f))
    # Rank 1's write span is 4x rank 0's: straggler attribution names it.
    assert "rank 1" in out


def test_merge_cli_no_traces(tmp_path, capsys):
    from torchsnapshot_tpu.telemetry.trace import main as trace_main

    assert trace_main([str(tmp_path)]) == 1
    assert "no trace files" in capsys.readouterr().out


def test_clock_offsets_from_gather():
    gathered = [
        {"gather_unix_ts": 100.0},
        {"gather_unix_ts": 100.25},
        {"gather_unix_ts": 99.9},
        {},  # older-schema peer: degrades to 0
    ]
    assert telemetry.clock_offsets_from_gather(gathered) == [
        0.0,
        0.25,
        -0.1,
        0.0,
    ]
    assert telemetry.clock_offsets_from_gather([{}]) is None
    assert telemetry.clock_offsets_from_gather([]) is None


def test_longest_spans_reads_exported_file(tmp_path):
    rec = SpanRecorder(capacity=64)
    with rec.span(names.SPAN_TAKE, path="/snap"):
        with rec.span(names.SPAN_STORAGE_WRITE, plugin="fs", blob="0/big"):
            time.sleep(0.02)
    path = str(tmp_path / ".trace-take-rank0.json")
    write_trace_file(
        path, chrome_trace(rec.events_since(0), rec.tid_names(), rank=0)
    )
    tops = longest_spans(path, 2)
    assert [t["name"] for t in tops] == [
        names.SPAN_TAKE,
        names.SPAN_STORAGE_WRITE,
    ]
    assert tops[1]["blob"] == "0/big"
    assert tops[0]["dur_ms"] >= 20


# ---------------------------------------------------------------------------
# Stall watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_exactly_once_on_injected_slow_plugin(
    tmp_path, monkeypatch, caplog
):
    """A write held >= deadline stalls the whole take; the watchdog must
    fire exactly once for the episode, emit the stall instant with the
    open-span tree, and log thread stacks."""
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    orig_write = FSStoragePlugin.write
    injected = []

    async def slow_write(self, write_io):
        # Exactly ONE hung write: the take's later writes (checksum
        # table, commit marker) proceed normally, so a second counter
        # bump here would mean the episode latch is broken, not that a
        # second stall was injected.
        if not injected:
            injected.append(write_io.path)
            await asyncio.sleep(0.7)
        await orig_write(self, write_io)

    monkeypatch.setattr(FSStoragePlugin, "write", slow_write)
    registry = telemetry.metrics()
    baseline = registry.counters_snapshot()
    snap = str(tmp_path / "snap")
    with knobs.override_watchdog_deadline_seconds(0.15), knobs.enable_trace():
        with caplog.at_level("ERROR"):
            ts.Snapshot.take(
                snap, {"s": ts.PyTreeState(_state(n=1, size=64))}
            )
    # Grace period: were the watchdog NOT edge-triggered, further scans
    # would keep bumping the counter here.
    time.sleep(0.3)
    deltas = registry.counters_delta_since(baseline)
    assert deltas.get(names.WATCHDOG_STALLS_TOTAL) == 1.0
    # The stall instant rode the take's exported timeline.
    with open(os.path.join(snap, ".trace-take-rank0.json")) as f:
        doc = json.load(f)
    stalls = [
        e
        for e in doc["traceEvents"]
        if e["ph"] == "i" and e["name"] == names.INSTANT_WATCHDOG_STALL
    ]
    assert len(stalls) == 1
    args = stalls[0]["args"]
    assert args["age_s"] >= 0.15
    assert args["open_spans"]  # the open-span tree snapshot
    assert any(names.SPAN_TAKE in s for s in args["open_spans"])
    # The stall instant carries the live-progress snapshot: how far the
    # wedged op got (bytes/items), not just which spans are open.
    assert any(
        "take rank0" in row and "items" in row for row in args["progress"]
    )
    # The stall instant names the blocking chain: the culprit's track
    # prefix plus the segment the wedged span charges to, so a stalled
    # fleet is diagnosable from the instant alone.
    from torchsnapshot_tpu.telemetry import critpath

    assert args["critical_path"]
    assert any(args["span"] in entry for entry in args["critical_path"])
    assert args["gating_segment"] == critpath.segment_for(args["span"])
    # The log carried the tree and the faulthandler-style stacks.
    log_text = caplog.text
    assert "open-span tree" in log_text
    assert "thread stacks" in log_text
    assert "Thread" in log_text


def test_watchdog_ignores_long_spans_with_ongoing_progress():
    """A healthy long take keeps its envelope span open well past the
    deadline while per-blob events complete underneath; the watchdog
    must key on forward progress, not open-span age, and stay silent."""
    rec = trace.get_recorder()
    registry = telemetry.metrics()
    baseline = registry.counters_snapshot()
    with knobs.override_watchdog_deadline_seconds(0.1):
        with rec.span(names.SPAN_TAKE, path="/healthy-but-long"):
            deadline = time.monotonic() + 0.5
            while time.monotonic() < deadline:
                with rec.span(names.SPAN_STORAGE_WRITE, plugin="fs", blob="b"):
                    pass
                time.sleep(0.02)
    deltas = registry.counters_delta_since(baseline)
    assert names.WATCHDOG_STALLS_TOTAL not in deltas


def test_watchdog_rearms_for_a_new_stall_episode():
    rec = trace.get_recorder()
    registry = telemetry.metrics()
    baseline = registry.counters_snapshot()
    with knobs.override_watchdog_deadline_seconds(0.1):
        with rec.span(names.SPAN_MIRROR_BLOB, blob="a"):
            time.sleep(0.3)
        time.sleep(0.2)  # episode ends: no open spans over deadline
        with rec.span(names.SPAN_MIRROR_BLOB, blob="b"):
            time.sleep(0.3)
        time.sleep(0.1)
    deltas = registry.counters_delta_since(baseline)
    assert deltas.get(names.WATCHDOG_STALLS_TOTAL) == 2.0


def test_watchdog_silent_on_fast_work(tmp_path):
    """The default suite environment (deadline 0 via conftest) plus a
    normal fast take must never start the watchdog or count stalls."""
    assert knobs.get_watchdog_deadline_seconds() == 0.0
    registry = telemetry.metrics()
    baseline = registry.counters_snapshot()
    ts.Snapshot.take(str(tmp_path / "snap"), {"s": ts.PyTreeState(_state())})
    deltas = registry.counters_delta_since(baseline)
    assert names.WATCHDOG_STALLS_TOTAL not in deltas
    from torchsnapshot_tpu.telemetry import watchdog as watchdog_mod

    assert watchdog_mod._WATCHDOG is None  # never even started


# ---------------------------------------------------------------------------
# Satellites: rss instants, report schema, fsck
# ---------------------------------------------------------------------------


def test_rss_profiler_emits_peak_instant():
    from torchsnapshot_tpu.utils.rss_profiler import (
        RSSDeltas,
        measure_rss_deltas,
    )

    rec = trace.get_recorder()
    mark = rec.mark()
    deltas = RSSDeltas()
    with measure_rss_deltas(deltas, sample_period_seconds=0.005):
        ballast = np.ones(8 << 20, dtype=np.uint8)  # 8 MiB
        ballast[::4096] = 2  # touch pages
        time.sleep(0.02)
    events = [
        e
        for e in rec.events_since(mark)
        if e["name"] == names.INSTANT_RSS_PEAK
    ]
    assert events, "no rss:peak instant recorded"
    assert all(e["args"]["delta_bytes"] > 0 for e in events)
    # Peaks are monotonically increasing — only NEW peaks emit.
    peaks = [e["args"]["delta_bytes"] for e in events]
    assert peaks == sorted(peaks)
    del ballast


def test_report_carries_clock_offsets_field():
    report = telemetry.SnapshotReport(kind="take", path="/x")
    assert report.clock_offsets_s is None
    d = report.to_dict()
    assert "clock_offsets_s" in d
    # Round-trips (and tolerates the gather-side stamp key).
    d["clock_offsets_s"] = [0.0, 0.1]
    d["gather_unix_ts"] = 123.0
    restored = telemetry.SnapshotReport.from_dict(d)
    assert restored.clock_offsets_s == [0.0, 0.1]


def test_fsck_stats_lists_trace_files(tmp_path, capsys):
    from torchsnapshot_tpu.fsck import main as fsck_main

    snap = str(tmp_path / "snap")
    with knobs.enable_trace(), knobs.enable_telemetry():
        ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state())})
    rc = fsck_main([snap, "--stats"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "flight-recorder traces" in out
    assert ".trace-take-rank0.json" in out
    assert names.SPAN_TAKE in out  # top spans named inline


def test_spans_from_chrome_tolerates_torn_window():
    """An E whose B fell outside the export window (ring eviction /
    op-boundary overlap) is skipped, not a crash."""
    doc = {
        "traceEvents": [
            {"ph": "E", "name": "x", "pid": 0, "tid": 0, "ts": 10},
            {"ph": "B", "name": "y", "pid": 0, "tid": 0, "ts": 20},
            {"ph": "E", "name": "y", "pid": 0, "tid": 0, "ts": 30},
        ]
    }
    spans = spans_from_chrome(doc)
    assert [s["name"] for s in spans] == ["y"]
    assert summarize_merged(doc)  # renders without the torn E
