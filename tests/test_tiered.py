"""Tiered checkpointing: fast-commit latency decoupled from durable-tier
bandwidth, crash-consistent mirror resume, per-blob durable fallback.

The two acceptance properties pinned here:

- With the durable tier throttled, ``Snapshot.take`` completes at
  fast-tier bandwidth (durable bytes still pending at return) and
  ``wait_durable`` later observes the step passing fsck + CRC
  verification on the durable tier.
- A kill between fast-tier commit and mirror completion is never
  unrecoverable: restore works from the fast tier, and a restarted
  Mirror drives the step durable using only the journal — completed
  blobs are not re-uploaded.
"""

import asyncio
import json
import os
import shutil
from unittest import mock

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, verify_snapshot
from torchsnapshot_tpu.scheduler import last_phase_timings
from torchsnapshot_tpu.storage_plugin import (
    join_path,
    split_tiered_url,
    url_to_storage_plugin,
)
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import faulty_fs_plugin
from torchsnapshot_tpu.tiered import (
    Mirror,
    TieredStoragePlugin,
    get_mirror,
    reset_mirror,
    wait_durable,
)
from torchsnapshot_tpu.tiered.journal import JOURNAL_BLOB, MirrorJournal
from torchsnapshot_tpu.tiered.mirror import is_durable


@pytest.fixture(autouse=True)
def _fresh_mirror():
    """Each test gets its own process-wide mirror (the worker thread and
    its job list outlive plugin instances by design)."""
    reset_mirror()
    yield
    reset_mirror()


def _tiers(tmp_path):
    fast = str(tmp_path / "fast")
    durable = str(tmp_path / "durable")
    return fast, durable, f"tiered://{fast}|{durable}"


def _state(n_leaves=4, size=2048, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n_leaves)
    }


def _mirror_factory(durable_root: str, plugin_cls):
    """Patch target for the mirror's plugin construction: durable-root
    URLs get ``plugin_cls``, everything else the real registry."""

    def factory(url):
        if url.startswith(durable_root):
            return plugin_cls(root=url)
        return url_to_storage_plugin(url)

    return factory


def _slow_fs(delay_s: float):
    class _Slow(FSStoragePlugin):
        async def write(self, write_io):
            await asyncio.sleep(delay_s)
            await super().write(write_io)

        async def write_with_checksum(self, write_io):
            await asyncio.sleep(delay_s)
            return await super().write_with_checksum(write_io)

    return _Slow


def _recording_fs(record: list):
    class _Recording(FSStoragePlugin):
        async def write(self, write_io):
            record.append(write_io.path)
            await super().write(write_io)

        async def write_with_checksum(self, write_io):
            record.append(write_io.path)
            return await super().write_with_checksum(write_io)

    return _Recording


# ---------------------------------------------------------------------------
# URL grammar
# ---------------------------------------------------------------------------


def test_tiered_url_dispatch_and_join(tmp_path):
    fast, durable, url = _tiers(tmp_path)
    assert split_tiered_url(url) == (fast, durable)
    assert split_tiered_url("/plain/path") is None
    assert split_tiered_url("gs://bucket/x") is None
    with pytest.raises(ValueError, match="tiered://"):
        split_tiered_url("tiered://only-one-side")
    with pytest.raises(ValueError, match="nests"):
        split_tiered_url(f"tiered://tiered://a|b|{durable}")
    joined = join_path(url, "step_0000000007")
    assert joined == (
        f"tiered://{fast}/step_0000000007|{durable}/step_0000000007"
    )
    plugin = url_to_storage_plugin(url)
    assert isinstance(plugin, TieredStoragePlugin)
    assert isinstance(plugin.fast, FSStoragePlugin)
    assert isinstance(plugin.durable, FSStoragePlugin)


def test_plugin_requires_tier_specs():
    with pytest.raises(ValueError, match="fast"):
        TieredStoragePlugin()


# ---------------------------------------------------------------------------
# Acceptance: fast commit under a throttled durable tier
# ---------------------------------------------------------------------------


def test_take_commits_at_fast_tier_bandwidth_then_wait_durable(tmp_path):
    """The tentpole latency property: the durable tier is slow, the take
    is not — durable bytes are still pending when take returns, and
    wait_durable later finds the mirrored step fsck- and CRC-clean on
    the durable tier alone."""
    fast, durable, url = _tiers(tmp_path)
    state = _state()
    with mock.patch(
        "torchsnapshot_tpu.tiered.mirror.url_to_storage_plugin",
        side_effect=_mirror_factory(durable, _slow_fs(0.25)),
    ):
        ts.Snapshot.take(url, {"m": ts.PyTreeState(dict(state))})
        # The take committed on the fast tier...
        assert os.path.exists(os.path.join(fast, ".snapshot_metadata"))
        # ...while the durable tier has not seen the commit marker yet —
        # the mirror's first throttled upload alone outlasts this check.
        assert not os.path.exists(os.path.join(durable, ".snapshot_metadata"))
        assert not is_durable(url)
        wait_durable(url, timeout=60)
    assert os.path.exists(os.path.join(durable, ".snapshot_metadata"))
    report = verify_snapshot(url, deep=True, tier="durable")
    assert report.ok and report.crcs_verified > 0
    # The journal records full completion.
    journal = json.loads((tmp_path / "fast" / JOURNAL_BLOB).read_text())
    assert journal["durable_committed"] is True
    assert sorted(journal["done"]) == sorted(journal["blobs"])
    # Machine-readable surfaces: mirror metrics + the scheduler's
    # phase-timing channel.
    metrics = get_mirror().metrics()
    assert metrics["blobs_done"] == len(journal["blobs"])
    assert metrics["bytes_mirrored"] > 0
    assert metrics["snapshots_pending"] == 0
    assert "mirroring" in last_phase_timings()


def test_async_take_unblocks_before_durable_completes(tmp_path):
    fast, durable, url = _tiers(tmp_path)
    state = _state(n_leaves=3)
    with mock.patch(
        "torchsnapshot_tpu.tiered.mirror.url_to_storage_plugin",
        side_effect=_mirror_factory(durable, _slow_fs(0.25)),
    ):
        pending = ts.Snapshot.async_take(
            url, {"m": ts.PyTreeState(dict(state))}
        )
        snapshot = pending.wait()  # fast-tier commit only
        assert os.path.exists(os.path.join(fast, ".snapshot_metadata"))
        assert not os.path.exists(os.path.join(durable, ".snapshot_metadata"))
        wait_durable(url, timeout=60)
    dst = ts.PyTreeState({k: np.zeros_like(v) for k, v in state.items()})
    snapshot.restore({"m": dst})
    for k, v in state.items():
        np.testing.assert_array_equal(dst.tree[k], v)


# ---------------------------------------------------------------------------
# Acceptance: kill between fast commit and mirror completion
# ---------------------------------------------------------------------------


def test_interrupted_mirror_resumes_from_journal_without_reupload(tmp_path):
    fast, durable, url = _tiers(tmp_path)
    state = _state(n_leaves=6, seed=3)
    fail_after = 2
    counter = {"n": 0}

    def _fail_after(_path: str) -> bool:
        counter["n"] += 1
        return counter["n"] > fail_after

    faulty = faulty_fs_plugin(
        _fail_after, ops=("write",), exc_msg="injected durable outage"
    )
    with knobs.override_mirror_progress_window_seconds(0.2), mock.patch(
        "torchsnapshot_tpu.tiered.mirror.url_to_storage_plugin",
        side_effect=_mirror_factory(durable, faulty),
    ):
        ts.Snapshot.take(url, {"m": ts.PyTreeState(dict(state))})
        (job,) = get_mirror().jobs_for(fast)
        assert job.wait(60)
        assert job.error is not None  # the "kill": mirror died mid-upload
        with pytest.raises(RuntimeError, match="mirror of"):
            wait_durable(url, timeout=60)

    # Never unrecoverable: the fast tier restores in full...
    dst = ts.PyTreeState({k: np.zeros_like(v) for k, v in state.items()})
    ts.Snapshot(url).restore({"m": dst})
    for k, v in state.items():
        np.testing.assert_array_equal(dst.tree[k], v)
    # ...the durable tier has no commit marker...
    assert not os.path.exists(os.path.join(durable, ".snapshot_metadata"))
    # ...and fsck names the partial mirror instead of a bare missing
    # marker.
    report = verify_snapshot(url, tier="durable")
    assert not report.ok
    assert report.problems[0].kind == "unmirrored"
    assert "mirror in progress" in report.problems[0].detail

    journal_before = json.loads(
        (tmp_path / "fast" / JOURNAL_BLOB).read_text()
    )
    done_before = set(journal_before["done"])
    assert done_before  # progress survived the failure
    assert journal_before["durable_committed"] is False

    # "Restarted" mirror (fresh instance, journal is the only state):
    # finishes the upload without re-sending completed blobs.
    resumed_writes: list = []
    restarted = Mirror()
    try:
        with mock.patch(
            "torchsnapshot_tpu.tiered.mirror.url_to_storage_plugin",
            side_effect=_mirror_factory(durable, _recording_fs(resumed_writes)),
        ):
            job = restarted.resume(url)
            assert job is not None
            assert job.wait(60)
            assert job.error is None
    finally:
        restarted.stop()
    assert not (set(resumed_writes) & done_before), resumed_writes
    # Commit marker strictly last on the durable tier.
    assert resumed_writes[-1] == ".snapshot_metadata"
    assert is_durable(url)
    report = verify_snapshot(url, deep=True, tier="durable")
    assert report.ok and report.crcs_verified > 0
    # A second resume is a no-op: the journal says complete.
    assert Mirror().resume(url) is None
    # The process-wide mirror still remembers its FAILED job for this
    # path; now that the step is actually durable, the barrier must see
    # durability first — a stale failure must not poison it.
    wait_durable(url, timeout=10)


def test_resume_without_journal_remirrors_from_manifest(tmp_path):
    """The narrowest crash window — killed after the fast commit but
    before the first journal write — falls back to a manifest-driven full
    re-mirror."""
    fast, durable, url = _tiers(tmp_path)
    state = _state(n_leaves=2)
    ts.Snapshot.take(url, {"m": ts.PyTreeState(dict(state))})
    wait_durable(url, timeout=60)
    # Simulate the window: durable wiped, journal lost.
    shutil.rmtree(durable)
    os.remove(os.path.join(fast, JOURNAL_BLOB))
    os.remove(os.path.join(fast, JOURNAL_BLOB + ".backup"))
    restarted = Mirror()
    try:
        job = restarted.resume(url)
        assert job is not None
        assert job.wait(60)
        assert job.error is None
    finally:
        restarted.stop()
    assert is_durable(url)
    assert verify_snapshot(url, deep=True, tier="durable").ok


# ---------------------------------------------------------------------------
# Per-blob fallback reads
# ---------------------------------------------------------------------------


def test_restore_falls_back_per_blob_when_fast_partially_evicted(tmp_path):
    fast, durable, url = _tiers(tmp_path)
    state = _state(n_leaves=5, seed=11)
    ts.Snapshot.take(url, {"m": ts.PyTreeState(dict(state))})
    wait_durable(url, timeout=60)
    # Knock individual data blobs (not the marker) out of the fast tier:
    # restore must source exactly those from the durable tier.
    dropped = 0
    for dirpath, _, files in os.walk(os.path.join(fast, "0")):
        for name in files:
            if dropped < 3:
                os.remove(os.path.join(dirpath, name))
                dropped += 1
    assert dropped == 3
    dst = ts.PyTreeState({k: np.zeros_like(v) for k, v in state.items()})
    ts.Snapshot(url).restore({"m": dst})
    for k, v in state.items():
        np.testing.assert_array_equal(dst.tree[k], v)


def test_restore_and_fsck_from_durable_after_total_fast_loss(tmp_path):
    fast, durable, url = _tiers(tmp_path)
    state = _state(n_leaves=3, seed=5)
    ts.Snapshot.take(url, {"m": ts.PyTreeState(dict(state))})
    wait_durable(url, timeout=60)
    shutil.rmtree(fast)
    assert verify_snapshot(url, deep=True).ok  # composed view
    dst = ts.PyTreeState({k: np.zeros_like(v) for k, v in state.items()})
    ts.Snapshot(url).restore({"m": dst})
    for k, v in state.items():
        np.testing.assert_array_equal(dst.tree[k], v)


def test_wait_durable_is_a_noop_for_plain_urls(tmp_path):
    path = str(tmp_path / "plain")
    ts.Snapshot.take(path, {"m": ts.PyTreeState(_state(n_leaves=1))})
    wait_durable(path, timeout=1)  # returns immediately


def test_wait_durable_rejects_uncommitted_paths(tmp_path):
    _, _, url = _tiers(tmp_path)
    with pytest.raises(FileNotFoundError):
        wait_durable(url, timeout=1)


# ---------------------------------------------------------------------------
# CheckpointManager integration
# ---------------------------------------------------------------------------


def test_manager_tiered_retention_eviction_and_fallback(tmp_path):
    fast, durable, root = _tiers(tmp_path)
    mgr = ts.CheckpointManager(root, keep_last_n=5, keep_fast_last_n=1)
    values = {}
    for step in (1, 2, 3):
        arr = np.full(256, float(step), dtype=np.float32)
        values[step] = arr
        mgr.save(step, {"m": ts.PyTreeState({"w": arr.copy()})})
        mgr.wait_durable(step, timeout=60)
    assert mgr.all_steps() == [1, 2, 3]

    def fast_meta(step):
        return os.path.exists(
            os.path.join(fast, f"step_{step:010d}", ".snapshot_metadata")
        )

    def durable_meta(step):
        return os.path.exists(
            os.path.join(durable, f"step_{step:010d}", ".snapshot_metadata")
        )

    # Steps beyond keep_fast_last_n were evicted from the fast tier only
    # — every step remains durable and committed.
    assert [fast_meta(s) for s in (1, 2, 3)] == [False, False, True]
    assert all(durable_meta(s) for s in (1, 2, 3))
    # The durable tier's index names every step (mirrored after the
    # step's own blobs).
    durable_index = json.loads(
        (tmp_path / "durable" / ".manager_index").read_text()
    )
    assert durable_index["steps"] == [1, 2, 3]
    assert durable_index["evicted"] == [1, 2]
    # Evicted steps restore through the per-blob durable fallback.
    dst = ts.PyTreeState({"w": np.zeros(256, np.float32)})
    mgr.restore(1, {"m": dst})
    np.testing.assert_array_equal(dst.tree["w"], values[1])
    dst = ts.PyTreeState({"w": np.zeros(256, np.float32)})
    assert mgr.restore_latest({"m": dst}) == 3
    np.testing.assert_array_equal(dst.tree["w"], values[3])


def test_manager_keep_fast_requires_tiered_root(tmp_path):
    with pytest.raises(ValueError, match="tiered"):
        ts.CheckpointManager(str(tmp_path), keep_fast_last_n=1)


def test_manager_never_evicts_undurable_steps(tmp_path):
    """Eviction is gated on the durable commit marker: with the mirror
    broken, every step keeps its fast copy no matter the policy."""
    fast, durable, root = _tiers(tmp_path)
    always_fail = faulty_fs_plugin(
        lambda _p: True, ops=("write",), exc_msg="durable down"
    )
    with knobs.override_mirror_progress_window_seconds(0.1), mock.patch(
        "torchsnapshot_tpu.tiered.mirror.url_to_storage_plugin",
        side_effect=_mirror_factory(durable, always_fail),
    ):
        mgr = ts.CheckpointManager(root, keep_last_n=5, keep_fast_last_n=1)
        for step in (1, 2, 3):
            mgr.save(
                step,
                {"m": ts.PyTreeState({"w": np.ones(64, np.float32)})},
            )
        get_mirror().drain(timeout=60)
        for step in (1, 2, 3):
            assert os.path.exists(
                os.path.join(
                    fast, f"step_{step:010d}", ".snapshot_metadata"
                )
            )
        index = json.loads((tmp_path / "fast" / ".manager_index").read_text())
        assert index.get("evicted", []) == []


def test_manager_resume_mirrors_after_restart(tmp_path):
    fast, durable, root = _tiers(tmp_path)
    always_fail = faulty_fs_plugin(
        lambda _p: True, ops=("write",), exc_msg="durable down"
    )
    with knobs.override_mirror_progress_window_seconds(0.1), mock.patch(
        "torchsnapshot_tpu.tiered.mirror.url_to_storage_plugin",
        side_effect=_mirror_factory(durable, always_fail),
    ):
        mgr = ts.CheckpointManager(root, keep_last_n=3)
        mgr.save(1, {"m": ts.PyTreeState({"w": np.ones(64, np.float32)})})
        get_mirror().drain(timeout=60)
    assert not is_durable(mgr.step_path(1))
    # Process "restart": fresh mirror; the restarted manager resumes the
    # interrupted upload from the journal.
    reset_mirror()
    mgr2 = ts.CheckpointManager(root, keep_last_n=3)
    assert mgr2.resume_mirrors() == [1]
    mgr2.wait_durable(1, timeout=60)
    assert is_durable(mgr2.step_path(1))
    assert verify_snapshot(mgr2.step_path(1), deep=True, tier="durable").ok


# ---------------------------------------------------------------------------
# Preemption drain hook
# ---------------------------------------------------------------------------


def test_preemption_drain_hook_runs_mirror_drain(tmp_path):
    _, _, url = _tiers(tmp_path)
    ts.Snapshot.take(url, {"m": ts.PyTreeState(_state(n_leaves=2))})
    saver = ts.PreemptionSaver(signals=())
    drained = []
    saver.register_drain(
        lambda: drained.append(get_mirror().drain(timeout=60))
    )
    saver.close()
    assert drained == [True]
    assert is_durable(url)


# ---------------------------------------------------------------------------
# Slow end-to-end sweep
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_slow_end_to_end_tiered_training_loop(tmp_path):
    """Multi-step training-loop shape against a throttled durable tier:
    periodic saves at fast-tier latency, background mirroring, fast-tier
    eviction, a mid-run mirror restart, and a final restore_latest served
    by the durable tier alone."""
    fast, durable, root = _tiers(tmp_path)
    rng = np.random.default_rng(0)
    with mock.patch(
        "torchsnapshot_tpu.tiered.mirror.url_to_storage_plugin",
        side_effect=_mirror_factory(durable, _slow_fs(0.05)),
    ):
        mgr = ts.CheckpointManager(root, keep_last_n=4, keep_fast_last_n=2)
        arrs = {}
        for step in range(1, 6):
            arrs[step] = rng.standard_normal(4096).astype(np.float32)
            mgr.save(step, {"m": ts.PyTreeState({"w": arrs[step].copy()})})
            if step == 3:
                # Simulated mid-run process bounce.
                reset_mirror()
                mgr.resume_mirrors()
        for step in mgr.all_steps():
            mgr.wait_durable(step, timeout=120)
        # wait_durable returns once the DURABLE tier is self-sufficient;
        # the mirror may still be writing fast-tier journal bookkeeping.
        # Quiesce it before yanking the fast tier out from under it
        # (a live mirror plus a vanishing fast tier only co-occur in
        # tests — a real fast-tier loss takes the process with it).
        assert get_mirror().drain(timeout=120)
    shutil.rmtree(fast)
    mgr2 = ts.CheckpointManager(root, keep_last_n=4, keep_fast_last_n=2)
    dst = ts.PyTreeState({"w": np.zeros(4096, np.float32)})
    latest = mgr2.restore_latest({"m": dst})
    assert latest == 5
    np.testing.assert_array_equal(dst.tree["w"], arrs[5])
    for step in mgr2.all_steps():
        assert verify_snapshot(
            mgr2.step_path(step), deep=True, tier="durable"
        ).ok


# ---------------------------------------------------------------------------
# wait_durable default deadline (snaplint satellite: no unbounded polls)
# ---------------------------------------------------------------------------


def test_wait_durable_default_timeout_is_knob_bounded(tmp_path, monkeypatch):
    """timeout=None is no longer an unbounded poll: it resolves to the
    TORCHSNAPSHOT_TPU_WAIT_DURABLE_TIMEOUT_SECONDS knob and surfaces a
    clear TimeoutError when durability never arrives."""
    import threading
    import time as time_mod

    from torchsnapshot_tpu.tiered import mirror as mirror_mod

    _, _, url = _tiers(tmp_path)

    class _SettledFailureFreeJob:
        def __init__(self):
            self.done_evt = threading.Event()
            self.done_evt.set()
            self.error = None

    class _StubMirror:
        def jobs_for(self, fast_url):
            return [_SettledFailureFreeJob()]

        def metrics(self):
            return {}

    monkeypatch.setattr(mirror_mod, "is_durable", lambda p: False)
    monkeypatch.setattr(mirror_mod, "get_mirror", lambda: _StubMirror())
    with knobs.override_wait_durable_timeout_seconds(0.3):
        t0 = time_mod.monotonic()
        with pytest.raises(TimeoutError, match="not durable within"):
            mirror_mod.wait_durable(url, timeout=None)
        assert time_mod.monotonic() - t0 < 10.0


def test_manager_wait_durable_default_deadline_is_knob_bounded(tmp_path):
    """Manager-level durability barrier with no explicit timeout: a
    durable index that never names the step times out at the knob
    deadline with an error naming the step — the watchdog is no longer
    the only escape hatch."""
    fast, durable, root = _tiers(tmp_path)
    mgr = ts.CheckpointManager(root, keep_last_n=3)
    arr = np.arange(16, dtype=np.float32)
    mgr.save(1, {"m": ts.PyTreeState({"w": arr})})
    mgr.wait_durable(1, timeout=60)
    # Sabotage: the durable tier's index vanishes (misconfigured remote
    # GC); the step's own blobs stay durable, so only the index poll
    # can block.
    os.remove(os.path.join(durable, ".manager_index"))
    os.remove(os.path.join(durable, ".manager_index.backup"))
    with knobs.override_wait_durable_timeout_seconds(0.4):
        with pytest.raises(TimeoutError, match="does not name it"):
            mgr.wait_durable(1)  # no explicit timeout: the knob bounds it
