"""Reference-snapshot → native-snapshot conversion CLI.

After conversion the full native feature set must apply: the converted
snapshot restores through the native path, passes the native fsck, and
chains as an incremental base.
"""

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.tricks.convert import convert, main, verify_source
from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
    ReferenceSnapshotReader,
)
from torchsnapshot_tpu.tricks.torchsnapshot_writer import (
    write_reference_snapshot,
)

ml_dtypes = pytest.importorskip("ml_dtypes")


def _reference_snapshot(path) -> dict:
    state = {
        "model": {
            "w": np.random.default_rng(0).standard_normal((8, 4)).astype(
                np.float32
            ),
            "emb": np.arange(16, dtype=np.float32).astype(ml_dtypes.bfloat16),
        },
        "progress": {"step": 7, "tag": "run-a"},
    }
    write_reference_snapshot(str(path), state)
    return state


def test_convert_then_native_restore_and_fsck(tmp_path):
    src = tmp_path / "old"
    dst = tmp_path / "new"
    state = _reference_snapshot(src)

    assert main([str(src), str(dst), "--verify"]) == 0

    # Native restore of the converted snapshot.
    dest = {
        "model": ts.PyTreeState(
            {
                "w": np.zeros((8, 4), np.float32),
                "emb": np.zeros(16, ml_dtypes.bfloat16),
            }
        ),
        "progress": ts.PyTreeState({"step": 0, "tag": ""}),
    }
    ts.Snapshot(str(dst)).restore(dest)
    np.testing.assert_array_equal(dest["model"].tree["w"], state["model"]["w"])
    np.testing.assert_array_equal(
        np.asarray(dest["model"].tree["emb"]).view(np.uint16),
        state["model"]["emb"].view(np.uint16),
    )
    assert dest["progress"].tree["step"] == 7
    assert dest["progress"].tree["tag"] == "run-a"

    # Native deep fsck accepts it.
    from torchsnapshot_tpu.fsck import verify_snapshot

    report = verify_snapshot(str(dst), deep=True)
    assert not report.problems

    # The converted snapshot is a valid incremental base: an unchanged
    # next take chains off it and rewrites (next to) nothing.
    nxt = tmp_path / "next"
    ts.Snapshot.take(
        str(nxt),
        {
            "model": ts.PyTreeState(
                {
                    "w": state["model"]["w"],
                    "emb": state["model"]["emb"],
                }
            ),
            "progress": ts.PyTreeState({"step": 7, "tag": "run-a"}),
        },
        incremental_base=str(dst),
    )
    next_report = verify_snapshot(str(nxt), deep=True)
    assert not next_report.problems
    # Chained entries use parent-ref locations into the base snapshot
    # (manifest.py ArrayEntry.location contract) — their presence proves
    # the converted snapshot's recorded digests made chunks skippable.
    meta_text = (nxt / ".snapshot_metadata").read_text()
    assert "../" in meta_text, "next take did not chain off the converted base"


def test_verify_catches_missing_and_truncated_blobs(tmp_path):
    src = tmp_path / "old"
    _reference_snapshot(src)

    # Truncate one blob, delete another.
    w_blob = src / "0" / "model" / "w"
    w_blob.write_bytes(w_blob.read_bytes()[:10])
    (src / "0" / "model" / "emb").unlink()

    reader = ReferenceSnapshotReader(str(src))
    problems = verify_source(reader, rank=0)
    reader.close()
    assert any("missing blob" in p for p in problems)
    assert any("bytes" in p and "w" in p for p in problems)

    # CLI fails fast and leaves no destination commit marker.
    dst = tmp_path / "new"
    assert main([str(src), str(dst), "--verify"]) == 1
    assert not (dst / ".snapshot_metadata").exists()


def test_dropped_rank_warning(tmp_path, capsys):
    """A multi-rank source with per-rank private state: converting rank
    0's view must warn loudly that other ranks' entries are not carried."""
    import yaml

    src = tmp_path / "old"
    _reference_snapshot(src)
    # Graft a rank-1 private tensor entry into the metadata (world 2).
    meta_path = src / ".snapshot_metadata"
    doc = yaml.safe_load(meta_path.read_text())
    doc["world_size"] = 2
    blob = np.ones(4, np.float32)
    (src / "1" / "opt").mkdir(parents=True)
    (src / "1" / "opt" / "m").write_bytes(blob.tobytes())
    doc["manifest"]["1/opt"] = {"type": "dict", "keys": ["m"]}
    doc["manifest"]["1/opt/m"] = {
        "type": "Tensor",
        "location": "1/opt/m",
        "serializer": "buffer_protocol",
        "dtype": "torch.float32",
        "shape": [4],
        "replicated": False,
        "byte_range": None,
    }
    meta_path.write_text(yaml.safe_dump(doc, sort_keys=False))

    dst = tmp_path / "new"
    assert main([str(src), str(dst), "--verify"]) == 0
    err = capsys.readouterr().err
    assert "WARNING" in err and "rank 1" in err and "opt/m" in err
    # Converting rank 1 instead carries its private state and warns
    # about rank 0's.
    dst1 = tmp_path / "new_rank1"
    assert main([str(src), str(dst1), "--rank", "1"]) == 0
    err = capsys.readouterr().err
    assert "rank 0" in err
    dest = {"opt": ts.PyTreeState({"m": np.zeros(4, np.float32)})}
    ts.Snapshot(str(dst1)).restore(dest)
    np.testing.assert_array_equal(dest["opt"].tree["m"], blob)


def test_convert_without_verify_still_fails_cleanly(tmp_path):
    src = tmp_path / "old"
    _reference_snapshot(src)
    (src / "0" / "model" / "w").unlink()
    dst = tmp_path / "new"
    with pytest.raises(FileNotFoundError):
        convert(str(src), str(dst))
    assert not (dst / ".snapshot_metadata").exists()


def test_verify_reports_unreadable_blobs_instead_of_crashing(tmp_path):
    """Backend errors that are neither FileNotFoundError nor the
    normalized OSError(EIO) truncation contract (e.g. an object store's
    auth/throttle exception escaping retries) must land in the problem
    list the caller was promised — not crash verify_source."""
    src = tmp_path / "old"
    _reference_snapshot(src)

    class _Boom(Exception):
        pass

    reader = ReferenceSnapshotReader(str(src))
    try:
        reader.metadata  # manifest loads fine; only blob probes explode

        def _raise(location, byte_range):
            raise _Boom("backend exploded")

        reader._read_blob = _raise
        problems = verify_source(reader, rank=0)
    finally:
        reader.close()
    assert problems
    assert all("unreadable" in p and "_Boom" in p for p in problems)
