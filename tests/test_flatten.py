"""Flatten/inflate round-trips, including hostile keys.

Structural model: reference tests/test_flatten.py.
"""

from collections import OrderedDict

import numpy as np
import pytest

from torchsnapshot_tpu.flatten import _decode, _encode, flatten, inflate


def _roundtrip(obj, prefix="my/prefix"):
    manifest, flattened = flatten(obj, prefix=prefix)
    return manifest, flattened, inflate(manifest, flattened, prefix=prefix)


def test_docstring_example() -> None:
    collection = {"foo": [1, 2, OrderedDict(bar=3, baz=4)]}
    manifest, flattened = flatten(collection, prefix="my/prefix")
    assert set(manifest.keys()) == {
        "my%2Fprefix",
        "my%2Fprefix/foo",
        "my%2Fprefix/foo/2",
    }
    assert manifest["my%2Fprefix"].type == "dict"
    assert manifest["my%2Fprefix/foo"].type == "list"
    assert manifest["my%2Fprefix/foo/2"].type == "OrderedDict"
    assert manifest["my%2Fprefix/foo/2"].keys == ["bar", "baz"]
    assert flattened == {
        "my%2Fprefix/foo/0": 1,
        "my%2Fprefix/foo/1": 2,
        "my%2Fprefix/foo/2/bar": 3,
        "my%2Fprefix/foo/2/baz": 4,
    }
    assert inflate(manifest, flattened, prefix="my/prefix") == collection


def test_nested_roundtrip() -> None:
    obj = {
        "a": [1, "two", 3.0, [4, {"five": 6}]],
        "b": OrderedDict(x={"deep": {"deeper": [None, True]}}, y=b"bytes"),
        7: "int key",
        "empty_list": [],
        "empty_dict": {},
    }
    _, _, out = _roundtrip(obj)
    assert out == obj
    assert type(out["b"]) is OrderedDict
    assert 7 in out  # int key recovered as int


def test_key_collision_keeps_dict_opaque() -> None:
    obj = {"outer": {1: "int one", "1": "str one"}}
    manifest, flattened, out = _roundtrip(obj)
    # The colliding dict must be kept as a single opaque leaf.
    assert "my%2Fprefix/outer" in flattened
    assert out == obj


def test_non_str_int_keys_keep_dict_opaque() -> None:
    obj = {"outer": {(1, 2): "tuple key"}}
    manifest, flattened, out = _roundtrip(obj)
    assert flattened["my%2Fprefix/outer"] == {(1, 2): "tuple key"}
    assert out == obj


def test_slash_and_percent_in_keys() -> None:
    obj = {"a/b": {"c%d": 1, "e%2Ff": 2, "%": 3}}
    _, flattened, out = _roundtrip(obj)
    assert out == obj
    # No raw slash from user keys may survive in path components beyond
    # hierarchy separators.
    for path in flattened:
        assert "a/b" not in path


def test_list_subclass_and_dict_subclass_are_leaves() -> None:
    class MyList(list):
        pass

    class MyDict(dict):
        pass

    obj = {"l": MyList([1, 2]), "d": MyDict(a=1)}
    _, flattened, out = _roundtrip(obj)
    assert isinstance(out["l"], MyList)
    assert isinstance(out["d"], MyDict)
    assert out == obj


def test_negative_int_keys() -> None:
    obj = {"d": {-3: "neg", "+4": "plus-string-stays-str-if-no-collision"}}
    _, _, out = _roundtrip(obj)
    # -3 parses back to int; "+4" parses to int 4 only if absent from keys —
    # here "+4" was the original key so it must be preserved.
    assert -3 in out["d"]
    assert "+4" in out["d"]


def test_array_leaves_pass_through_identically() -> None:
    arr = np.arange(6).reshape(2, 3)
    obj = {"w": arr}
    _, flattened, out = _roundtrip(obj)
    assert out["w"] is arr


def test_non_flattenable_root() -> None:
    manifest, flattened = flatten(42, prefix="x")
    assert manifest == {}
    assert flattened == {"x": 42}
    assert inflate(manifest, flattened, prefix="x") == 42


def test_inflate_missing_prefix_raises() -> None:
    with pytest.raises(AssertionError):
        inflate({}, {}, prefix="nope")


def test_encode_decode_inverse() -> None:
    for s in ["plain", "a/b", "a%2Fb", "%", "%25", "a%b/c%2F", ""]:
        assert _decode(_encode(s)) == s


def test_order_preserved() -> None:
    obj = {"z": 1, "a": 2, "m": 3}
    _, _, out = _roundtrip(obj)
    assert list(out.keys()) == ["z", "a", "m"]


def test_bool_keyed_dict_stays_opaque() -> None:
    """Regression: bool keys can't survive path stringification; the dict
    must be kept as an opaque leaf (review finding)."""
    obj = {"outer": {True: "x", False: "y"}}
    manifest, flattened, = flatten(obj, prefix="p")
    assert "p/outer" in flattened
    assert inflate(manifest, flattened, prefix="p") == obj
