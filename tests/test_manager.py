"""CheckpointManager: retention, latest-step resume, async saves, and the
uncommitted-step invisibility invariant.

The reference ships only the single-snapshot primitives and its examples
hand-roll this loop (examples/simple_example.py:59-76); the manager is
the packaged version, so the tests assert the loop's guarantees rather
than reference parity.
"""

import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.manager import INDEX_BLOB, _step_dirname
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME


def _state(value: float):
    return {"s": ts.PyTreeState({"w": np.full((8,), value)})}


def test_save_restore_latest_roundtrip(tmp_path) -> None:
    mgr = ts.CheckpointManager(str(tmp_path))
    assert mgr.latest_step() is None
    assert mgr.restore_latest(_state(0.0)) is None  # fresh run

    mgr.save(10, _state(10.0))
    mgr.save(20, _state(20.0))
    assert mgr.all_steps() == [10, 20]

    dst = _state(0.0)
    assert mgr.restore_latest(dst) == 20
    np.testing.assert_array_equal(dst["s"].tree["w"], np.full((8,), 20.0))

    dst = _state(0.0)
    mgr.restore(10, dst)
    np.testing.assert_array_equal(dst["s"].tree["w"], np.full((8,), 10.0))


def test_retention_deletes_old_steps(tmp_path) -> None:
    mgr = ts.CheckpointManager(str(tmp_path), keep_last_n=2)
    for step in (1, 2, 3, 4):
        mgr.save(step, _state(float(step)))
    assert mgr.all_steps() == [3, 4]

    # Dropped steps lose their commit marker AND their blobs.
    for dropped in (1, 2):
        step_dir = tmp_path / _step_dirname(dropped)
        assert not (step_dir / SNAPSHOT_METADATA_FNAME).exists()
        assert not (step_dir / "0" / "s" / "w").exists()
    # Retained steps restore.
    dst = _state(0.0)
    mgr.restore(3, dst)
    np.testing.assert_array_equal(dst["s"].tree["w"], np.full((8,), 3.0))


def test_async_save_commits_on_wait(tmp_path) -> None:
    mgr = ts.CheckpointManager(str(tmp_path), keep_last_n=1)
    pending = mgr.async_save(5, _state(5.0))
    pending.wait()
    pending2 = mgr.async_save(6, _state(6.0))
    pending2.wait()
    assert mgr.all_steps() == [6]
    dst = _state(0.0)
    assert mgr.restore_latest(dst) == 6


def test_async_save_staged_wait_does_not_index(tmp_path) -> None:
    """wait(phase="staged") observes D2H completion only: the step must
    not enter the index (a half-drained step must never be visible to
    restore_latest); the committed wait indexes it exactly once."""
    mgr = ts.CheckpointManager(str(tmp_path))
    pending = mgr.async_save(3, _state(3.0))
    assert pending.wait(phase="staged") is None
    assert pending.staged()
    assert 3 not in mgr.all_steps()
    # A typo'd phase must not silently become a committed wait with
    # index/retention side effects (same contract as PendingSnapshot).
    with pytest.raises(ValueError, match="staged"):
        pending.wait(phase="stagd")
    snapshot = pending.wait()
    assert snapshot is not None
    assert mgr.all_steps() == [3]


def test_uncommitted_step_invisible(tmp_path) -> None:
    """A step directory without a commit marker (crashed take) must never
    appear in the index or be restored."""
    mgr = ts.CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    # Simulate a crash mid-take of step 2: files exist, no marker, no index
    # update (the index is only written after Snapshot.take returns).
    fake = tmp_path / _step_dirname(2) / "0" / "s"
    fake.mkdir(parents=True)
    (fake / "w").write_bytes(b"\x00" * 64)
    assert mgr.all_steps() == [1]
    dst = _state(0.0)
    assert mgr.restore_latest(dst) == 1


def test_sharded_and_checksums_gced(tmp_path) -> None:
    """Retention walks every manifest entry kind: sharded shard blobs and
    checksum tables go too."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs), ("x",))

    def sharded_state(v: float):
        arr = jax.device_put(
            jnp.full((8 * len(devs), 4), v), NamedSharding(mesh, P("x", None))
        )
        return {"s": ts.PyTreeState({"emb": arr})}

    mgr = ts.CheckpointManager(str(tmp_path), keep_last_n=1)
    mgr.save(1, sharded_state(1.0))
    step1 = tmp_path / _step_dirname(1)
    assert (step1 / "checksums" / "0").exists()
    shard_blobs = list((step1 / "sharded").rglob("*")) if (step1 / "sharded").exists() else []
    assert shard_blobs

    mgr.save(2, sharded_state(2.0))
    assert mgr.all_steps() == [2]
    assert not (step1 / SNAPSHOT_METADATA_FNAME).exists()
    assert not (step1 / "checksums" / "0").exists()
    remaining = [
        p for p in (step1 / "sharded").rglob("*") if p.is_file()
    ] if (step1 / "sharded").exists() else []
    assert remaining == []


def test_index_blob_location(tmp_path) -> None:
    mgr = ts.CheckpointManager(str(tmp_path))
    mgr.save(7, _state(7.0))
    assert (tmp_path / INDEX_BLOB).exists()


def test_memory_backend(tmp_path) -> None:
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    try:
        mgr = ts.CheckpointManager("memory://mgrtest", keep_last_n=1)
        mgr.save(1, _state(1.0))
        mgr.save(2, _state(2.0))
        assert mgr.all_steps() == [2]
        dst = _state(0.0)
        assert mgr.restore_latest(dst) == 2
        np.testing.assert_array_equal(dst["s"].tree["w"], np.full((8,), 2.0))
    finally:
        for name in list(
            n for n in __import__(
                "torchsnapshot_tpu.storage_plugins.memory",
                fromlist=["_STORES"],
            )._STORES
            if n.startswith("mgrtest")
        ):
            MemoryStoragePlugin.drop_store(name)


def test_corrupt_index_falls_back_to_backup(tmp_path) -> None:
    """A crash mid-index-write must not brick the manager: the backup slot
    (written after the primary) still lists the previous steps."""
    from torchsnapshot_tpu.manager import INDEX_BACKUP_BLOB

    mgr = ts.CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    assert (tmp_path / INDEX_BACKUP_BLOB).exists()
    (tmp_path / INDEX_BLOB).write_text("{trunc")  # torn primary write
    assert mgr.all_steps() == [1, 2]
    dst = _state(0.0)
    assert mgr.restore_latest(dst) == 2


def test_saving_older_step_is_never_deleted(tmp_path) -> None:
    """Retention keeps the newest N numerically, but the just-saved
    checkpoint survives even when its number is older (step-counter
    rollback) — save() must never return a dangling snapshot."""
    mgr = ts.CheckpointManager(str(tmp_path), keep_last_n=2)
    mgr.save(9, _state(9.0))
    mgr.save(10, _state(10.0))
    mgr.save(5, _state(5.0))
    assert 5 in mgr.all_steps()
    dst = _state(0.0)
    mgr.restore(5, dst)
    np.testing.assert_array_equal(dst["s"].tree["w"], np.full((8,), 5.0))


def test_multiprocess_fresh_restore_then_save(tmp_path) -> None:
    """The aliasing regression: restore_latest on a fresh run (broadcast,
    early return, NO trailing barrier) immediately followed by save's
    internal broadcasts — shared op sequencing must keep every store key
    unique, or a slow rank reads the wrong object."""
    import os
    import tempfile

    from torchsnapshot_tpu.test_utils import run_multiprocess

    path = os.path.join(tempfile.gettempdir(), "mgr-mp-test")
    results = run_multiprocess(_mgr_worker, nproc=2, args=(path,))
    assert results == [3, 3]


def _mgr_worker(pg, root: str):
    import shutil

    import numpy as np

    import torchsnapshot_tpu as ts

    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()  # both ranks see the clean root
    mgr = ts.CheckpointManager(root, keep_last_n=2, pg=pg)
    state = {"s": ts.PyTreeState({"w": np.full((4,), float(pg.rank))})}
    assert mgr.restore_latest(state) is None  # fresh: broadcast + early return
    mgr.save(3, state)
    PGWrapper(pg).barrier()  # rank 0's index write is durable
    dst = {"s": ts.PyTreeState({"w": np.zeros(4)})}
    resumed = mgr.restore_latest(dst)
    assert float(dst["s"].tree["w"][0]) == float(pg.rank)  # per-rank state
    return resumed


def test_multiprocess_async_save_and_retention(tmp_path) -> None:
    """async_save in a multiprocess world: the background commits of both
    ranks coordinate through the store barrier, retention runs on rank 0
    inside wait(), and the next resume sees exactly the retained steps."""
    from torchsnapshot_tpu.test_utils import run_multiprocess

    results = run_multiprocess(
        _mgr_async_worker, nproc=2, args=(str(tmp_path / "root"),)
    )
    assert results == [[2, 3], [2, 3]]


def _mgr_async_worker(pg, root: str):
    import shutil

    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    PGWrapper(pg).barrier()
    mgr = ts.CheckpointManager(root, keep_last_n=2, pg=pg)
    for step in (1, 2, 3):
        state = {
            "s": ts.PyTreeState({"w": np.full((4,), float(step))}),
            "progress": ts.StateDict(rank=pg.rank),
        }
        pending = mgr.async_save(step, state)
        pending.wait()
    PGWrapper(pg).barrier()  # rank 0's index write is durable everywhere
    steps = sorted(mgr.all_steps())
    dst = {
        "s": ts.PyTreeState({"w": np.zeros(4)}),
        "progress": ts.StateDict(rank=-1),
    }
    resumed = mgr.restore_latest(dst)
    assert resumed == 3
    assert float(dst["s"].tree["w"][0]) == 3.0
    assert dst["progress"]["rank"] == pg.rank  # per-rank state stayed per-rank
    return steps


def test_unreadable_index_fails_save_instead_of_orphaning(tmp_path) -> None:
    """Transiently unreadable index slots must not be treated as an empty
    step list: a save in that state would rewrite the index as just the new
    step, silently orphaning every previously committed step."""
    import unittest.mock as mock

    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    mgr = ts.CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))

    real_read = FSStoragePlugin.read

    async def flaky_read(self, read_io):
        if read_io.path.endswith(".index") or "index" in read_io.path:
            raise OSError("transient storage blip")
        return await real_read(self, read_io)

    with mock.patch.object(FSStoragePlugin, "read", flaky_read):
        with pytest.raises(Exception, match="index unreadable|transient"):
            mgr.save(3, _state(3.0))
    # The blip healed: the earlier steps are still indexed and restorable.
    assert mgr.all_steps() == [1, 2]
    dst = _state(0.0)
    assert mgr.restore_latest(dst) == 2


def test_torn_first_index_write_self_recovers(tmp_path) -> None:
    """Corrupt primary + absent backup = the very first index write tore
    before the backup slot existed; nothing was ever committed to the
    index, so the manager must self-recover, not brick."""
    (tmp_path / INDEX_BLOB).write_text("{torn")
    mgr = ts.CheckpointManager(str(tmp_path))
    assert mgr.all_steps() == []
    mgr.save(1, _state(1.0))
    assert mgr.all_steps() == [1]


def test_both_index_slots_corrupt_raises(tmp_path) -> None:
    from torchsnapshot_tpu.manager import INDEX_BACKUP_BLOB

    mgr = ts.CheckpointManager(str(tmp_path))
    mgr.save(1, _state(1.0))
    (tmp_path / INDEX_BLOB).write_text("{torn")
    (tmp_path / INDEX_BACKUP_BLOB).write_text("{torn")
    with pytest.raises(RuntimeError, match="index unreadable"):
        mgr.all_steps()


# ---------------------------------------------------------------------------
# metric-based retention (keep_best_n)
# ---------------------------------------------------------------------------


def _mstate(v: float):
    import jax.numpy as jnp

    return {"m": ts.PyTreeState({"w": jnp.full((8,), float(v))})}


def test_keep_best_n_retains_best_and_last(tmp_path):
    mgr = ts.CheckpointManager(
        str(tmp_path), keep_last_n=1, keep_best_n=2, best_mode="min"
    )
    losses = {0: 5.0, 1: 1.0, 2: 4.0, 3: 0.5, 4: 9.0}
    for step, loss in losses.items():
        mgr.save(step, _mstate(step), metric=loss)
    # best two: steps 3 (0.5) and 1 (1.0); last one: step 4.
    assert mgr.all_steps() == [1, 3, 4]
    assert mgr.best_step() == 3

    dest = _mstate(-1)
    assert mgr.restore_best(dest) == 3
    import numpy as np

    assert float(np.asarray(dest["m"].tree["w"])[0]) == 3.0


def test_keep_best_max_mode_and_ties(tmp_path):
    mgr = ts.CheckpointManager(
        str(tmp_path), keep_best_n=1, best_mode="max"
    )
    mgr.save(0, _mstate(0), metric=0.9)
    mgr.save(1, _mstate(1), metric=0.9)  # tie: newest wins
    mgr.save(2, _mstate(2), metric=0.1)
    # step 2 survives only as the just-saved step of its own commit; the
    # next save drops it.
    mgr.save(3, _mstate(3), metric=0.2)
    assert mgr.best_step() == 1
    assert 1 in mgr.all_steps()
    assert 0 not in mgr.all_steps()
    assert 2 not in mgr.all_steps()


def test_metricless_steps_protected_only_by_last_n(tmp_path):
    mgr = ts.CheckpointManager(str(tmp_path), keep_last_n=2, keep_best_n=1)
    mgr.save(0, _mstate(0), metric=1.0)
    mgr.save(1, _mstate(1))  # no metric
    mgr.save(2, _mstate(2))  # no metric
    mgr.save(3, _mstate(3))  # no metric
    # best: 0; last two: 2, 3; step 1 dropped.
    assert mgr.all_steps() == [0, 2, 3]
    assert mgr.best_step() == 0


def test_keep_best_alone_never_gcs_unscored_steps(tmp_path):
    """With keep_best_n and no keep_last_n, only scored steps compete for
    deletion — enabling metric retention must not GC metric-less saves."""
    mgr = ts.CheckpointManager(str(tmp_path), keep_best_n=1)
    mgr.save(0, _mstate(0))  # unscored
    mgr.save(1, _mstate(1), metric=2.0)
    mgr.save(2, _mstate(2))  # unscored
    mgr.save(3, _mstate(3), metric=1.0)  # new best: step 1 drops
    assert mgr.all_steps() == [0, 2, 3]
    assert mgr.best_step() == 3


def test_best_step_none_without_metrics(tmp_path):
    mgr = ts.CheckpointManager(str(tmp_path))
    mgr.save(0, _mstate(0))
    assert mgr.best_step() is None
    assert mgr.restore_best(_mstate(-1)) is None


def test_async_save_metric_recorded(tmp_path):
    mgr = ts.CheckpointManager(str(tmp_path), keep_best_n=1)
    mgr.async_save(0, _mstate(0), metric=3.0).wait()
    mgr.async_save(1, _mstate(1), metric=2.0).wait()
    assert mgr.best_step() == 1


def test_best_retention_composes_with_incremental_pins(tmp_path):
    """A best-kept step referencing an origin keeps the origin pinned."""
    import jax.numpy as jnp

    def st(t):
        return {
            "m": ts.PyTreeState(
                {"frozen": jnp.arange(32.0), "t": jnp.full((4,), float(t))}
            )
        }

    mgr = ts.CheckpointManager(
        str(tmp_path), keep_last_n=1, keep_best_n=1, incremental=True
    )
    mgr.save(0, st(0), metric=5.0)
    mgr.save(1, st(1), metric=0.1)  # the best; refs step 0's frozen blob
    mgr.save(2, st(2), metric=7.0)
    mgr.save(3, st(3), metric=8.0)
    steps = mgr.all_steps()
    assert 1 in steps and 3 in steps and 2 not in steps
    # Restoring the best still works through the pinned origin.
    dest = st(-1)
    assert mgr.restore_best(dest) == 1
    import numpy as np

    np.testing.assert_array_equal(
        np.asarray(dest["m"].tree["frozen"]), np.arange(32.0)
    )


def test_nonfinite_metric_rejected(tmp_path):
    mgr = ts.CheckpointManager(str(tmp_path), keep_best_n=1)
    with pytest.raises(ValueError, match="finite"):
        mgr.save(0, _mstate(0), metric=float("nan"))
    with pytest.raises(ValueError, match="finite"):
        mgr.async_save(0, _mstate(0), metric=float("inf"))
    assert mgr.all_steps() == []  # nothing committed


@pytest.mark.parametrize("seed", range(3))
def test_retention_gc_fuzz_every_indexed_step_restores(tmp_path, seed):
    """Randomized save sequences (incremental on/off, random metrics,
    random keep_last_n/keep_best_n): after EVERY save, every step still
    in the index must restore byte-exact and deep-fsck clean — retention
    with ref-pinning GC must never delete blobs a live step references.
    A 10-run sweep of this generator passed during round 4."""
    from torchsnapshot_tpu.fsck import verify_snapshot
    from torchsnapshot_tpu.knobs import override_incremental_chunk_size_bytes

    rng = np.random.default_rng(6000 + seed)
    keep_last = int(rng.integers(1, 4)) if rng.random() < 0.7 else None
    keep_best = int(rng.integers(1, 3)) if rng.random() < 0.5 else None
    incremental = bool(rng.random() < 0.6)
    mgr = ts.CheckpointManager(
        str(tmp_path / "root"),
        keep_last_n=keep_last,
        keep_best_n=keep_best,
        incremental=incremental,
    )
    base = rng.standard_normal(3000).astype(np.float32)
    states = {}
    with override_incremental_chunk_size_bytes(256):
        for step in range(8):
            arr = base.copy()
            idx = rng.integers(0, arr.size, 20)  # sparse: refs chain
            arr[idx] = rng.standard_normal(20)
            base = arr
            states[step] = arr.copy()
            metric = (
                float(rng.standard_normal()) if rng.random() < 0.7 else None
            )
            mgr.save(step, {"m": ts.PyTreeState({"w": arr})}, metric=metric)

            for s in mgr.all_steps():
                dst = ts.PyTreeState({"w": np.zeros(3000, np.float32)})
                ts.Snapshot(mgr.step_path(s)).restore({"m": dst})
                np.testing.assert_array_equal(dst.tree["w"], states[s])
                report = verify_snapshot(mgr.step_path(s), deep=True)
                assert report.ok, (s, report)
