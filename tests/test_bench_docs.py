"""BENCH.md must quote the driver-recorded signal of record — the local
enforcement of the CI docs-consistency lane (committed-number drift like
round 2's 0.92-vs-0.646 efficiency headline fails here)."""

import importlib.util
import pathlib


def test_bench_docs_match_signal_of_record(capsys):
    tools = pathlib.Path(__file__).parent.parent / "tools" / "check_bench_docs.py"
    spec = importlib.util.spec_from_file_location("check_bench_docs", tools)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main()
    assert rc == 0, capsys.readouterr().out
