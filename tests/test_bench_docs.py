"""Docs-consistency lanes, enforced locally too: BENCH.md must quote the
driver-recorded signal of record (committed-number drift like round 2's
0.92-vs-0.646 efficiency headline fails here), and every relative doc
link must resolve. A timed-out driver run records ``parsed: null``
(round 4 did) — the checker must fall back to the newest round that
parsed, never pass vacuously.

The metric-name, span-name, and tiered-marker checkers are now thin
shims over ``tools.snaplint`` rules; their behavioral tests below
exercise the shared implementations, and the snaplint lane test runs
the whole framework over the package."""

import importlib.util
import json
import pathlib
import sys

_REPO = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))


def _load_tool(name: str):
    tools = pathlib.Path(__file__).parent.parent / "tools" / name
    spec = importlib.util.spec_from_file_location(name[:-3], tools)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_tool(name: str) -> int:
    return _load_tool(name).main()


def test_bench_docs_match_signal_of_record(capsys):
    rc = _run_tool("check_bench_docs.py")
    assert rc == 0, capsys.readouterr().out


def test_doc_links_resolve(capsys):
    rc = _run_tool("check_doc_links.py")
    assert rc == 0, capsys.readouterr().out


def _bench_md_for(record: dict) -> str:
    return (
        "# bench\n<!-- BENCH_SIGNAL_OF_RECORD: generated -->\n```json\n"
        + json.dumps(record, indent=2)
        + "\n```\n"
    )


def _write_round(root, n, parsed):
    (root / f"BENCH_r{n:02d}.json").write_text(
        json.dumps({"n": n, "rc": 124 if parsed is None else 0, "parsed": parsed})
    )


def test_null_parsed_newest_falls_back_to_older_record(tmp_path, capsys):
    """A timed-out newest round must not green the check by itself: the
    checker skips it (with a warning naming it) and verifies BENCH.md
    against the newest round that actually parsed."""
    mod = _load_tool("check_bench_docs.py")
    good = {"metric": "x", "value": 1.0}
    _write_round(tmp_path, 3, good)
    _write_round(tmp_path, 4, None)
    (tmp_path / "BENCH.md").write_text(_bench_md_for(good))
    assert mod.main(root=tmp_path) == 0
    out = capsys.readouterr().out
    assert "BENCH_r04.json" in out and "BENCH_r03.json" in out

    # ...and against that older record the check still has teeth:
    (tmp_path / "BENCH.md").write_text(_bench_md_for({"metric": "x", "value": 2.0}))
    assert mod.main(root=tmp_path) == 1


def test_all_null_parsed_with_block_fails_loudly(tmp_path, capsys):
    """Deleting every parsed record while BENCH.md still carries a block
    must flip the checker red — the vacuous-pass regression (round 4's
    ``data.get("parsed", data)`` returned None and the lane greened)."""
    mod = _load_tool("check_bench_docs.py")
    _write_round(tmp_path, 3, None)
    _write_round(tmp_path, 4, None)
    (tmp_path / "BENCH.md").write_text(_bench_md_for({"metric": "x"}))
    assert mod.main(root=tmp_path) == 1
    assert "non-null" in capsys.readouterr().out


def test_corrupt_older_record_does_not_crash_the_check(tmp_path):
    """A truncated BENCH_r*.json (killed mid-write) is skipped like a
    null-parsed one, not allowed to crash the lane with a traceback."""
    mod = _load_tool("check_bench_docs.py")
    good = {"metric": "x", "value": 1.0}
    (tmp_path / "BENCH_r01.json").write_text("{truncated")
    _write_round(tmp_path, 2, good)
    (tmp_path / "BENCH.md").write_text(_bench_md_for(good))
    assert mod.main(root=tmp_path) == 0
    # ...and when the corrupt record is the only one, the block fails loudly.
    (tmp_path / "BENCH_r02.json").unlink()
    assert mod.main(root=tmp_path) == 1


def test_no_records_and_no_block_is_clean(tmp_path):
    mod = _load_tool("check_bench_docs.py")
    (tmp_path / "BENCH.md").write_text("# bench\nno block here\n")
    assert mod.main(root=tmp_path) == 0


def test_snaplint_lane_is_clean(capsys):
    """The default-lane analyzer run: every snaplint rule (the five
    concurrency/correctness rules plus the metric/span/tiered checkers
    it absorbed) over the whole package, empty baseline, exit 0."""
    from tools.snaplint.__main__ import main

    rc = main(["torchsnapshot_tpu"])
    assert rc == 0, capsys.readouterr().out


def test_snaplint_protocol_lane_is_clean(capsys):
    """The protocol lane next to the bench-docs checks: the
    coordination-plane model rules over the package, nonzero exit on
    any new finding. Unlike ``tools/bench_diff.py`` this needs no
    stub-parent-package import trick — snaplint is stdlib-``ast`` only
    and never imports ``torchsnapshot_tpu`` (whose ``__init__`` pulls
    jax), so the jax-free CI box runs it as-is."""
    from tools.snaplint.__main__ import main

    rc = main(["--protocol", "torchsnapshot_tpu"])
    assert rc == 0, capsys.readouterr().out


def test_checkers_are_snaplint_shims():
    """The three pre-snaplint checkers must stay thin shims over the
    framework's rule implementations — one implementation, two entry
    points, no drift."""
    from tools.snaplint.rules import names_lint, tiered_markers

    metric = _load_tool("check_metric_names.py")
    span = _load_tool("check_span_names.py")
    tiered = _load_tool("check_tiered_markers.py")
    assert metric.check_names_file is names_lint.check_metric_names_file
    assert metric.check_call_sites is names_lint.check_metric_call_sites
    assert span.check_names_file is names_lint.check_span_names_file
    assert tiered.check is tiered_markers.check


def test_tiered_tests_are_lane_correct(capsys):
    """The tiered crash/latency tests must reach the default
    -m 'not slow' lane, with the end-to-end sweep marked slow."""
    rc = _run_tool("check_tiered_markers.py")
    assert rc == 0, capsys.readouterr().out


def test_metric_names_are_lane_correct(capsys):
    """Metric names: snake_case, declared exactly once in
    telemetry/names.py, call sites use the constants."""
    rc = _run_tool("check_metric_names.py")
    assert rc == 0, capsys.readouterr().out


def test_span_names_are_lane_correct(capsys):
    """Flight-recorder span/instant names: colon-case, declared exactly
    once in telemetry/names.py, call sites use the constants."""
    rc = _run_tool("check_span_names.py")
    assert rc == 0, capsys.readouterr().out


def test_span_name_check_catches_violations(tmp_path):
    mod = _load_tool("check_span_names.py")
    names = tmp_path / "names.py"
    # non-colon-case value + duplicate value + duplicate constant; the
    # metric constant is ignored by this checker.
    names.write_text(
        'SPAN_GOOD = "layer:op"\n'
        'SPAN_BAD = "no_colons_here"\n'
        'SPAN_DUP = "layer:op"\n'
        'SPAN_GOOD = "other:op"\n'
        'SOME_METRIC = "a_metric"\n'
    )
    errors = mod.check_names_file(names)
    assert any("colon-case" in e for e in errors)
    assert any("registered twice" in e for e in errors)
    assert any("assigned twice" in e for e in errors)
    assert mod.check_names_file(tmp_path / "absent.py") == [
        "absent.py: missing (span names must be declared here)"
    ]
    # A literal span name at a call site is flagged; constants are not,
    # and non-trace callables are ignored.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'with trace_annotation("literal:name"):\n    pass\n'
        "with trace_annotation(names.SPAN_GOOD):\n    pass\n"
        'rec.span("another:literal")\n'
        'rec.instant(names.SPAN_GOOD, note="x")\n'
        'other.method("not:checked")\n'
    )
    errors = mod.check_call_sites(pkg, exempt=set())
    assert len(errors) == 2
    assert any("literal:name" in e for e in errors)
    assert any("another:literal" in e for e in errors)


def test_metric_name_check_accepts_colon_case_span_constants(tmp_path):
    """check_metric_names shares names.py with the span constants: a
    SPAN_/INSTANT_ value is linted colon-case, not snake_case."""
    mod = _load_tool("check_metric_names.py")
    names = tmp_path / "names.py"
    names.write_text(
        'GOOD = "good_metric"\n'
        'SPAN_OK = "layer:op"\n'
        'SPAN_BAD = "NotColonCase"\n'
    )
    errors = mod.check_names_file(names)
    assert len(errors) == 1 and "colon-case" in errors[0]


def test_metric_name_check_catches_violations(tmp_path):
    mod = _load_tool("check_metric_names.py")
    names = tmp_path / "names.py"
    # camelCase value + duplicate value + duplicate constant.
    names.write_text(
        'GOOD = "good_metric"\n'
        'BAD = "BadMetric"\n'
        'DUP = "good_metric"\n'
        'GOOD = "another_metric"\n'
    )
    errors = mod.check_names_file(names)
    assert any("snake_case" in e for e in errors)
    assert any("registered twice" in e for e in errors)
    assert any("assigned twice" in e for e in errors)
    assert mod.check_names_file(tmp_path / "absent.py") == [
        "absent.py: missing (metric names must be declared here)"
    ]
    # A literal metric name at a call site is flagged; a constant is not.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'reg.counter_inc("literal_name", 1)\n'
        "reg.counter_inc(names.GOOD, 1)\n"
    )
    errors = mod.check_call_sites(pkg, names)
    assert len(errors) == 1 and "literal_name" in errors[0]


def test_tiered_marker_check_catches_lane_drift(tmp_path):
    mod = _load_tool("check_tiered_markers.py")
    bad = tmp_path / "test_tiered.py"
    bad.write_text(
        "import pytest\n"
        "def test_slow_end_to_end_sweep():\n    pass\n"
    )
    errors = mod.check(bad)
    assert any("end-to-end" in e for e in errors)
    bad.write_text(
        "import pytest\n"
        "pytestmark = pytest.mark.slow\n"
        "@pytest.mark.slow\ndef test_only():\n    pass\n"
    )
    errors = mod.check(bad)
    assert any("pytestmark" in e for e in errors)
    assert any("every test is marked slow" in e for e in errors)
    assert mod.check(tmp_path / "absent.py") == [
        "absent.py: missing (tiered tests are tier-1 signal)"
    ]
