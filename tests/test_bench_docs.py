"""Docs-consistency lanes, enforced locally too: BENCH.md must quote the
driver-recorded signal of record (committed-number drift like round 2's
0.92-vs-0.646 efficiency headline fails here), and every relative doc
link must resolve."""

import importlib.util
import pathlib


def _run_tool(name: str) -> int:
    tools = pathlib.Path(__file__).parent.parent / "tools" / name
    spec = importlib.util.spec_from_file_location(name[:-3], tools)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main()


def test_bench_docs_match_signal_of_record(capsys):
    rc = _run_tool("check_bench_docs.py")
    assert rc == 0, capsys.readouterr().out


def test_doc_links_resolve(capsys):
    rc = _run_tool("check_doc_links.py")
    assert rc == 0, capsys.readouterr().out
