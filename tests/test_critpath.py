"""Critical-path engine + differential analysis (telemetry/critpath.py).

Sweep-line attribution unit coverage (innermost-frame gating, envelope
unions, exhaustive partition), the acceptance bar end-to-end (every
SnapshotReport's ``critical_path`` segments sum to >= 95% of op wall on
real single- and 2-process takes/restores, including the peer-served
path), the stitched-wire descent over a merged Chrome doc, the diff CLI
(injected storage slowdown attributed to write-drain with span
citations; bench-record mode quiet on real rounds and firing on a
doctored pair), and the trend integrations (``critical-path-shifted``,
``bench-regression``, ``critpath_<segment>_s`` series).
"""

import asyncio
import json
import os
import re
from pathlib import Path

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.telemetry import critpath, names
from torchsnapshot_tpu.telemetry.doctor import (
    diagnose_trend,
    registered_rule_ids,
)
from torchsnapshot_tpu.telemetry.history import detect_trend_regressions
from torchsnapshot_tpu.telemetry.stats import main as stats_main
from torchsnapshot_tpu.test_utils import run_multiprocess

REPO_ROOT = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Sweep-line attribution (unit, synthetic recorder windows)
# ---------------------------------------------------------------------------


def _ev(name, ts_us, dur_us, bseq, args=None):
    return {
        "ph": "X",
        "name": name,
        "ts": ts_us,
        "dur": dur_us,
        "bseq": bseq,
        "args": args or {},
    }


def test_sweep_charges_innermost_frame_and_partitions_exactly():
    """Nested spans: each elementary interval goes to the most recently
    begun open span; envelope-only time lands in ``other``; the
    partition sums to the wall exactly (coverage 1.0)."""
    events = [
        _ev(names.SPAN_TAKE, 0, 1_000_000, 0),
        _ev(names.SPAN_PIPELINE_STAGE, 0, 400_000, 1),
        _ev(names.SPAN_STORAGE_WRITE, 100_000, 200_000, 2, {"blob": "0/w"}),
    ]
    cp = critpath.critical_path_from_events(events, "take")
    assert cp is not None
    assert cp["wall_s"] == pytest.approx(1.0)
    assert cp["coverage"] == pytest.approx(1.0)
    # [0,100ms) + [300,400ms) staging; [100,300ms) write inside stage
    # gates (innermost); [400ms,1s) envelope-only -> other.
    assert cp["segments"]["staging"] == pytest.approx(0.2, abs=1e-6)
    assert cp["segments"]["write_drain"] == pytest.approx(0.2, abs=1e-6)
    assert cp["segments"]["other"] == pytest.approx(0.6, abs=1e-6)
    assert sum(cp["segments"].values()) == pytest.approx(cp["wall_s"])
    assert cp["dominant"] == "other"
    write = [c for c in cp["chain"] if c["span"] == names.SPAN_STORAGE_WRITE]
    assert write and write[0]["blob"] == "0/w"
    assert write[0]["gated_s"] == pytest.approx(0.2, abs=1e-6)


def test_async_take_attributes_over_envelope_union():
    """Async takes have two envelopes (visible stage + background
    commit); the sweep partitions their union and ignores span time
    outside both windows."""
    events = [
        _ev(names.SPAN_ASYNC_TAKE_STAGE, 0, 100_000, 0),
        _ev(names.SPAN_ASYNC_TAKE_COMMIT, 200_000, 300_000, 1),
        _ev(names.SPAN_PIPELINE_STAGE, 0, 100_000, 2),
        # Straddles the inter-envelope gap: only the in-window part
        # (200ms..250ms) may be charged.
        _ev(names.SPAN_STORAGE_WRITE, 150_000, 100_000, 3),
    ]
    cp = critpath.critical_path_from_events(events, "async_take")
    assert cp["wall_s"] == pytest.approx(0.4)
    assert cp["segments"]["staging"] == pytest.approx(0.1, abs=1e-6)
    assert cp["segments"]["write_drain"] == pytest.approx(0.05, abs=1e-6)
    assert sum(cp["segments"].values()) == pytest.approx(0.4)


def test_no_envelope_yields_none():
    assert critpath.critical_path_from_events([], "take") is None
    assert critpath.critical_path_from_events(
        [_ev(names.SPAN_STORAGE_WRITE, 0, 10, 0)], "take"
    ) is None
    assert critpath.critical_path_from_events(
        [_ev(names.SPAN_TAKE, 0, 100, 0)], "no_such_kind"
    ) is None


def test_foreign_envelope_bounds_but_never_gates():
    """Another op's envelope overlapping the window (async commit
    draining into the next take) must not absorb attribution."""
    events = [
        _ev(names.SPAN_TAKE, 0, 100_000, 0),
        _ev(names.SPAN_ASYNC_TAKE_COMMIT, 0, 100_000, 1),
    ]
    cp = critpath.critical_path_from_events(events, "take")
    assert cp["segments"] == {"other": pytest.approx(0.1)}


# ---------------------------------------------------------------------------
# End-to-end: reports carry critical_path meeting the coverage bar
# ---------------------------------------------------------------------------


def _assert_coverage(ev):
    cp = ev.get("critical_path")
    assert cp, f"{ev.get('kind')} report carries no critical_path"
    assert cp["coverage"] >= critpath.MIN_COVERAGE
    assert sum(cp["segments"].values()) >= 0.95 * cp["wall_s"]
    assert cp["dominant"] in cp["segments"]
    return cp


def test_single_process_take_and_restore_meet_coverage_bar(tmp_path):
    path = str(tmp_path / "snap")
    with knobs.enable_telemetry():
        state = {
            "m": ts.PyTreeState(
                {"w": np.arange(1 << 20, dtype=np.float32)}
            )
        }
        ts.Snapshot.take(path, state)
        dest = {
            "m": ts.PyTreeState(
                {"w": np.zeros(1 << 20, dtype=np.float32)}
            )
        }
        ts.Snapshot(path).restore(dest)
    events = telemetry.load_events(os.path.join(path, ".telemetry.jsonl"))
    by_kind = {e["kind"]: e for e in events}
    take_cp = _assert_coverage(by_kind["take"])
    restore_cp = _assert_coverage(by_kind["restore"])
    # The chains cite real storage spans, not just envelope residue.
    assert any(
        c["segment"] == "write_drain" for c in take_cp["chain"]
    )
    assert any(
        c["segment"] == "read_drain" for c in restore_cp["chain"]
    )


def _worker_take_restore_critpath(pg, path):
    import os

    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu import knobs, telemetry
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = "1"
    with knobs.enable_telemetry():
        state = {
            "m": ts.PyTreeState(
                {"w": np.arange(200_000, dtype=np.float32)}
            )
        }
        ts.Snapshot.take(path, state, pg=pg, replicated=["**"])
        PGWrapper(pg).barrier()
        dest = {
            "m": ts.PyTreeState(
                {"w": np.zeros(200_000, dtype=np.float32)}
            )
        }
        ts.Snapshot(path, pg=pg).restore(dest)
        np.testing.assert_array_equal(
            dest["m"].tree["w"], np.arange(200_000, dtype=np.float32)
        )
    if pg.rank != 0:
        return
    events = telemetry.load_events(os.path.join(path, ".telemetry.jsonl"))
    takes = [e for e in events if e.get("kind") == "take"]
    restores = [e for e in events if e.get("kind") == "restore"]
    assert takes and restores
    for ev in takes + restores:
        cp = ev.get("critical_path")
        assert cp, f"rank {ev.get('rank')} {ev['kind']} lacks critical_path"
        assert cp["coverage"] >= 0.95
        assert sum(cp["segments"].values()) >= 0.95 * cp["wall_s"]
    # A coordinated 2-proc take spends wall in the commit barrier: the
    # coordination segment must be attributed somewhere in the window.
    agg = [e for e in takes if e.get("aggregated")]
    assert agg, "rank 0's take report carries no cross-rank aggregate"
    folded = agg[-1]["aggregated"]
    critpath_keys = [k for k in folded if k.startswith("critpath_")]
    assert critpath_keys, f"no critpath fold in {sorted(folded)}"
    spread = folded[critpath_keys[0]]
    assert {"min", "median", "max", "straggler"} <= set(spread)


@pytest.mark.slow
def test_two_process_take_and_fanout_restore_meet_coverage_bar(tmp_path):
    run_multiprocess(
        _worker_take_restore_critpath, nproc=2, args=(str(tmp_path / "s"),)
    )


def test_peer_served_restore_attributes_peer_segment(tmp_path):
    """The peer -> fast -> durable ladder, peer-served: blob reads gated
    by ``peer:pull`` must attribute to the ``peer`` segment (and still
    meet the coverage bar)."""
    import glob as _glob
    import threading

    from torchsnapshot_tpu.dist_store import (
        InProcessStore,
        publish_endpoint,
    )
    from torchsnapshot_tpu.scheduler import PeerCacheBudget
    from torchsnapshot_tpu.tiered import peer

    path = str(tmp_path / "snap")
    with knobs.enable_peer_tier(), knobs.enable_telemetry():
        store = InProcessStore()
        rep = peer.get_replicator()
        assert rep.configure(store, rank=0, world_size=2)
        rank1_cache = peer.PeerCache(budget=PeerCacheBudget(1 << 30))
        server = peer._PeerServer(("127.0.0.1", 0), rank1_cache)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            publish_endpoint(
                store,
                peer.PEER_SERVICE,
                1,
                "127.0.0.1",
                server.server_address[1],
            )
            state = {
                "m": ts.PyTreeState(
                    {"w": np.arange(50_000, dtype=np.float32)}
                )
            }
            ts.Snapshot.take(path, state)
            assert rep.drain(timeout=60)
            for blob in _glob.glob(os.path.join(path, "m", "*")):
                os.remove(blob)
            dest = {
                "m": ts.PyTreeState(
                    {"w": np.zeros(50_000, dtype=np.float32)}
                )
            }
            ts.Snapshot(path).restore(dest)
            np.testing.assert_array_equal(
                dest["m"].tree["w"], np.arange(50_000, dtype=np.float32)
            )
        finally:
            peer.reset_peer_tier()
            server.shutdown()
            server.server_close()
    events = telemetry.load_events(os.path.join(path, ".telemetry.jsonl"))
    restore = [e for e in events if e.get("kind") == "restore"][-1]
    cp = _assert_coverage(restore)
    assert cp["segments"].get("peer", 0.0) > 0.0
    assert any(c["segment"] == "peer" for c in cp["chain"])


# ---------------------------------------------------------------------------
# Merged-doc attribution: stitched wire descent
# ---------------------------------------------------------------------------


def test_doc_attribution_descends_stitched_wire_to_peer_frames():
    """An interval gated by ``wire:rpc`` resolves to whatever the
    serving peer's handler was inside (here its disk read) — a 'slow
    RPC' names the peer's storage, not the socket."""

    def B(pid, tid, name, ts_us, args=None):
        return {
            "ph": "B",
            "pid": pid,
            "tid": tid,
            "name": name,
            "ts": ts_us,
            "args": args or {},
        }

    def E(pid, tid, ts_us):
        return {"ph": "E", "pid": pid, "tid": tid, "ts": ts_us}

    rpc_args = {"span_id": "s1", "trace_id": "t1", "op": "fetch"}
    handler_args = {"parent_span_id": "s1", "trace_id": "t1"}
    doc = {
        "traceEvents": [
            B(0, 1, names.SPAN_TAKE, 0),
            B(0, 1, names.SPAN_WIRE_RPC, 1_000, rpc_args),
            B(1, 7, names.SPAN_WIRE_HANDLER, 1_500, handler_args),
            B(1, 7, names.SPAN_STORAGE_READ, 2_000, {"blob": "0/w"}),
            E(1, 7, 8_000),
            E(1, 7, 8_500),
            E(0, 1, 9_000),
            E(0, 1, 10_000),
        ]
    }
    cp = critpath.critical_path_from_doc(doc, "take")
    assert cp is not None
    assert cp["dominant"] == "read_drain"
    assert cp["segments"]["read_drain"] > 0.0
    assert "wire" not in cp["segments"] or (
        cp["segments"]["wire"] < cp["segments"]["read_drain"]
    )
    cited = [c for c in cp["chain"] if c["span"] == names.SPAN_STORAGE_READ]
    assert cited and cited[0]["blob"] == "0/w"


def test_doc_attribution_without_stitch_keeps_wire_segment():
    doc = {
        "traceEvents": [
            {"ph": "B", "pid": 0, "tid": 1, "name": names.SPAN_TAKE, "ts": 0},
            {
                "ph": "B",
                "pid": 0,
                "tid": 1,
                "name": names.SPAN_WIRE_RPC,
                "ts": 100,
                "args": {"span_id": "sX", "trace_id": "tX"},
            },
            {"ph": "E", "pid": 0, "tid": 1, "ts": 900},
            {"ph": "E", "pid": 0, "tid": 1, "ts": 1_000},
        ]
    }
    cp = critpath.critical_path_from_doc(doc, "take")
    assert cp["segments"]["wire"] == pytest.approx(0.0008)


# ---------------------------------------------------------------------------
# Self-time (trace summary satellite)
# ---------------------------------------------------------------------------


def test_spans_from_chrome_reports_self_time():
    from torchsnapshot_tpu.telemetry.trace import (
        longest_spans_from_doc,
        spans_from_chrome,
        summarize_merged,
    )

    doc = {
        "traceEvents": [
            {"ph": "B", "pid": 0, "tid": 1, "name": "parent", "ts": 0},
            {"ph": "B", "pid": 0, "tid": 1, "name": "child", "ts": 10_000},
            {"ph": "E", "pid": 0, "tid": 1, "ts": 90_000},
            {"ph": "E", "pid": 0, "tid": 1, "ts": 100_000},
        ]
    }
    by = {s["name"]: s for s in spans_from_chrome(doc)}
    assert by["parent"]["dur_us"] == 100_000
    assert by["parent"]["self_us"] == 20_000
    assert by["child"]["self_us"] == 80_000
    tops = longest_spans_from_doc(doc, 2)
    assert tops[0]["name"] == "parent"
    assert tops[0]["dur_ms"] == 100.0
    assert tops[0]["self_ms"] == 20.0
    summary = summarize_merged(doc)
    assert "self" in summary
    # The self-time listing surfaces the real culprit (child), not the
    # envelope that merely contains it.
    assert "top self-time spans" in summary


# ---------------------------------------------------------------------------
# Diff CLI: injected slow plugin -> write_drain, with span citations
# ---------------------------------------------------------------------------


async def _none_coro():
    # Stands in for write_with_checksum: None routes the scheduler to
    # the two-step fallback, which lands in write() -> _write_impl.
    return None


def test_diff_cli_attributes_injected_storage_slowdown(
    tmp_path, monkeypatch, capsys
):
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    before = str(tmp_path / "before")
    after = str(tmp_path / "after")
    state = {
        "m": ts.PyTreeState({"w": np.arange(100_000, dtype=np.float32)})
    }
    with knobs.enable_telemetry():
        ts.Snapshot.take(before, state)
        # Patch below the accounting boundary: write() opens the
        # storage:write span and delegates to _write_impl, so a sleep
        # here is a slowdown *inside* the instrumented storage layer —
        # exactly what the diff CLI must pin on write_drain.
        orig_write = FSStoragePlugin._write_impl

        async def slow_write(self, write_io):
            await asyncio.sleep(0.1)
            await orig_write(self, write_io)

        monkeypatch.setattr(FSStoragePlugin, "_write_impl", slow_write)
        monkeypatch.setattr(
            FSStoragePlugin,
            "write_with_checksum",
            lambda self, write_io: _none_coro(),
        )
        ts.Snapshot.take(after, state)
    rc = stats_main(["diff", before, after, "--kind", "take"])
    out = capsys.readouterr().out
    assert rc == 2
    assert "write_drain" in out
    assert "REGRESSED" in out
    # Span-level evidence citation for the regressed segment.
    assert "gating spans" in out
    assert "storage:" in out
    # JSON mode carries the same verdict machine-readably.
    rc = stats_main(["diff", before, after, "--kind", "take", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert doc["regressed"][0]["segment"] == "write_drain"
    assert doc["evidence"]


def test_diff_cli_unusable_operand_exits_1(tmp_path, capsys):
    assert stats_main(["diff", str(tmp_path), str(tmp_path)]) == 1
    assert "no report found" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Bench differential: quiet on real rounds, fires on a doctored pair
# ---------------------------------------------------------------------------


def _bench_parsed(name):
    p = REPO_ROOT / name
    if not p.exists():
        pytest.skip(f"{name} not present")
    parsed = json.loads(p.read_text()).get("parsed")
    if not isinstance(parsed, dict):
        pytest.skip(f"{name} has no parsed block")
    return parsed


def test_bench_regressions_quiet_on_real_r06_vs_r07():
    """r06 -> r07 is pure round-to-round link drift (no code change
    moved the legs); the declared tolerances must keep it quiet."""
    r06, r07 = _bench_parsed("BENCH_r06.json"), _bench_parsed(
        "BENCH_r07.json"
    )
    assert critpath.bench_regressions([("r06", r06), ("r07", r07)]) == []


def test_bench_regression_fires_on_doctored_pair(tmp_path, capsys):
    r06 = _bench_parsed("BENCH_r06.json")
    r07 = _bench_parsed("BENCH_r07.json")
    doctored = dict(r07)
    doctored["value"] = round(r07["value"] * 0.2, 4)  # 5x slowdown
    rows = critpath.bench_regressions([("r06", r06), ("doctored", doctored)])
    assert [r["leg"] for r in rows] == ["value"]
    assert rows[0]["baseline_records"] == ["r06"]
    verdicts = critpath.bench_verdicts(rows)
    assert verdicts[0].rule == names.RULE_BENCH_REGRESSION

    # CLI bench mode end-to-end on temp records.
    a = tmp_path / "BENCH_r90.json"
    b = tmp_path / "BENCH_r91.json"
    ok = tmp_path / "BENCH_r92.json"
    a.write_text(json.dumps({"parsed": r06}))
    b.write_text(json.dumps({"parsed": doctored}))
    ok.write_text(json.dumps({"parsed": r07}))
    assert stats_main(["diff", str(a), str(b)]) == 2
    out = capsys.readouterr().out
    assert "REGRESSED" in out and names.RULE_BENCH_REGRESSION in out
    assert stats_main(["diff", str(a), str(ok)]) == 0


def test_bench_skipped_leg_zero_is_not_a_regression():
    """A leg recorded 0.0 (budget-gated / failed leg) is absent, not a
    collapse to zero — in the newest record AND in baselines."""
    base = {"value": 0.2, "pipeline_efficiency": 0.6}
    rows = critpath.bench_regressions(
        [("a", base), ("b", {"value": 0.2, "pipeline_efficiency": 0.0})]
    )
    assert rows == []
    rows = critpath.bench_regressions(
        [
            ("a", {"value": 0.0}),
            ("b", {"value": 0.2}),
            ("c", {"value": 0.21}),
        ]
    )
    assert rows == []


# ---------------------------------------------------------------------------
# Trend integration: shifted dominants, critpath series, doctor rules
# ---------------------------------------------------------------------------


def _hist_row(kind, dominant, step, seconds=1.0):
    return {
        "kind": kind,
        "step": step,
        "path": f"/root/step_{step}",
        "critpath": {
            "dominant": dominant,
            "coverage": 1.0,
            "segments": {dominant: seconds},
        },
    }


def test_detect_critical_path_shifts_flags_moved_dominant():
    records = [_hist_row("take", "write_drain", i) for i in range(4)]
    records.append(_hist_row("take", "coordination", 4, seconds=2.5))
    rows = critpath.detect_critical_path_shifts(records)
    assert len(rows) == 1
    row = rows[0]
    assert row["dominant"] == "coordination"
    assert row["previous_dominant"] == "write_drain"
    assert row["baseline_share"] == 1.0
    assert row["dominant_s"] == 2.5
    # Stable history: quiet.
    stable = [_hist_row("take", "write_drain", i) for i in range(6)]
    assert critpath.detect_critical_path_shifts(stable) == []
    # Kinds are separate populations: a restore dominated by read_drain
    # must not count against the take baseline.
    mixed = [_hist_row("take", "write_drain", i) for i in range(4)]
    mixed.append(_hist_row("restore", "read_drain", 4))
    assert critpath.detect_critical_path_shifts(mixed) == []


def test_doctor_trend_emits_critical_path_shifted_verdict():
    records = [_hist_row("take", "write_drain", i) for i in range(4)]
    records.append(_hist_row("take", "coordination", 4))
    verdicts = diagnose_trend(records)
    shifted = [
        v for v in verdicts if v.rule == names.RULE_CRITICAL_PATH_SHIFTED
    ]
    assert len(shifted) == 1
    assert "coordination" in shifted[0].summary
    assert shifted[0].evidence["previous_dominant"] == "write_drain"


def test_trend_series_cover_critpath_segments():
    """History rows' critical-path segments feed ``critpath_<seg>_s``
    trend series — a segment that balloons regresses even when the
    total wall is absorbed elsewhere."""
    records = [
        {
            "kind": "take",
            "step": i,
            "take_s": 2.0,
            "critpath": {
                "dominant": "write_drain",
                "segments": {"write_drain": 1.0, "staging": 0.5},
            },
        }
        for i in range(4)
    ]
    records.append(
        {
            "kind": "take",
            "step": 4,
            "take_s": 2.0,
            "critpath": {
                "dominant": "write_drain",
                "segments": {"write_drain": 1.9, "staging": 0.5},
            },
        }
    )
    rows = detect_trend_regressions(records)
    metrics = {r["metric"] for r in rows}
    assert "critpath_write_drain_s" in metrics
    assert "critpath_staging_s" not in metrics


def test_new_rule_ids_are_registered_and_kebab_case():
    ids = registered_rule_ids()
    for rid in (
        names.RULE_CRITICAL_PATH_SHIFTED,
        names.RULE_BENCH_REGRESSION,
    ):
        assert rid in ids
        assert re.fullmatch(r"[a-z0-9]+(-[a-z0-9]+)*", rid)


def test_history_rows_carry_critpath_summary(tmp_path):
    """summarize_report folds the report's critical_path into the
    history row (dominant + coverage + rounded segments)."""
    from torchsnapshot_tpu.telemetry.history import summarize_report
    from torchsnapshot_tpu.telemetry.report import SnapshotReport

    report = SnapshotReport(kind="take", path=str(tmp_path), rank=0)
    report.critical_path = {
        "wall_s": 1.0,
        "coverage": 1.0,
        "segments": {"write_drain": 0.75, "other": 0.25},
        "dominant": "write_drain",
        "chain": [],
    }
    row = summarize_report(report, step=7)
    assert row["critpath"]["dominant"] == "write_drain"
    assert row["critpath"]["segments"]["write_drain"] == 0.75
    none_report = SnapshotReport(kind="take", path=str(tmp_path), rank=0)
    assert summarize_report(none_report, step=8)["critpath"] is None
