"""Checkpoint doctor: rule registry, evidence-cited verdicts over real
snapshot artifacts, bench-trial epistemics, per-manager step history,
and trend regression detection.

Acceptance pins (ISSUE 5): ``python -m torchsnapshot_tpu.telemetry
doctor <snapshot>`` on a synthetic slow-storage take (fake plugin with
injected latency) emits at least one correct, evidence-cited verdict;
``doctor --trend`` over >= 3 manager steps with one injected regression
flags exactly that step.
"""

import asyncio
import json
import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.telemetry import doctor, history, names
from torchsnapshot_tpu.telemetry.stats import main as stats_main


@pytest.fixture(autouse=True)
def _fresh_registry():
    telemetry.reset_metrics()
    yield
    telemetry.reset_metrics()


def _state(n=4, size=2048, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n)
    }


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------


def test_every_registered_rule_id_is_declared_in_names():
    declared = {
        v
        for k, v in vars(names).items()
        if k.startswith("RULE_") and isinstance(v, str)
    }
    registered = set(doctor.registered_rule_ids())
    assert registered <= declared
    # The headline rules from the issue all exist.
    for rule_id in (
        names.RULE_D2H_BOUND,
        names.RULE_BUDGET_STARVED,
        names.RULE_STRAGGLER_RANK,
        names.RULE_STORAGE_TIER_SLOW,
        names.RULE_MIRROR_LAGGING,
        names.RULE_WRITE_TAIL_STALL,
        names.RULE_INTERRUPTED_TAKE,
    ):
        assert rule_id in registered


# ---------------------------------------------------------------------------
# Report-scope rules over synthetic reports (threshold unit tests)
# ---------------------------------------------------------------------------


def _report(**over):
    base = {
        "kind": "take",
        "rank": 0,
        "phases": {"staging": 1.0, "writing": 2.0},
        "bytes_moved": 100 * 1024**2,
        "budget_wait_s": 0.0,
        "retries": {},
        "mirror": {},
    }
    base.update(over)
    return base


def _rules_for(reports):
    return {v.rule for v in doctor.diagnose_reports(reports)}


def test_storage_tier_slow_vs_d2h_bound():
    # Write drain (wall - staging) dominates -> storage-tier-slow.
    slow_storage = _report(phases={"staging": 0.2, "writing": 3.0})
    assert _rules_for([slow_storage]) == {names.RULE_STORAGE_TIER_SLOW}
    # Staging dominates -> d2h-bound, not storage.
    d2h = _report(phases={"staging": 2.8, "writing": 3.0})
    assert _rules_for([d2h]) == {names.RULE_D2H_BOUND}
    # Balanced take below both thresholds -> silence.
    ok = _report(phases={"staging": 1.5, "writing": 3.0})
    assert _rules_for([ok]) == set()


def test_budget_starved_cites_wait_fraction():
    starved = _report(budget_wait_s=1.5, phases={"staging": 1.5, "writing": 3.0})
    verdicts = doctor.diagnose_reports([starved])
    budget = [v for v in verdicts if v.rule == names.RULE_BUDGET_STARVED]
    assert len(budget) == 1
    assert budget[0].evidence["wait_frac"] == 0.5
    assert budget[0].evidence["budget_wait_s"] == 1.5


def test_straggler_rank_names_the_rank():
    report = _report(
        aggregated={
            "phase_writing_s": {
                "min": 1.0,
                "median": 1.1,
                "max": 9.0,
                "straggler": 3,
            },
            "bytes_moved": {
                "min": 1.0,
                "median": 1.0,
                "max": 1.0,
                "straggler": 0,
            },
        },
        phases={"staging": 1.5, "writing": 3.0},
    )
    verdicts = [
        v
        for v in doctor.diagnose_reports([report])
        if v.rule == names.RULE_STRAGGLER_RANK
    ]
    assert len(verdicts) == 1
    assert verdicts[0].evidence["straggler_rank"] == 3
    assert verdicts[0].evidence["metric"] == "phase_writing_s"


def test_async_visible_stall_rule(tmp_path):
    """The async-visible-stall rule: fires on an async_take whose
    visible span exceeds the knob budget, citing stage-span evidence;
    silent for fast takes, other kinds, missing fields, and a disabled
    (<= 0) budget."""
    from torchsnapshot_tpu import knobs

    regressed = _report(
        kind="async_take",
        visible_s=99.7,
        staged_s=99.8,
        phases={"staging": 99.6, "writing": 101.1},
    )
    healthy = _report(
        kind="async_take",
        visible_s=0.02,
        staged_s=1.4,
        phases={"staging": 1.4, "writing": 2.0},
    )
    sync_take = _report(kind="take", visible_s=None)
    legacy = _report(kind="async_take")  # pre-round-6 report: no field
    assert names.RULE_ASYNC_VISIBLE_STALL in _rules_for([regressed])
    assert names.RULE_ASYNC_VISIBLE_STALL not in _rules_for([healthy])
    assert names.RULE_ASYNC_VISIBLE_STALL not in _rules_for([sync_take])
    assert names.RULE_ASYNC_VISIBLE_STALL not in _rules_for([legacy])
    verdict = [
        v
        for v in doctor.diagnose_reports([regressed])
        if v.rule == names.RULE_ASYNC_VISIBLE_STALL
    ][0]
    assert verdict.evidence["visible_s"] == 99.7
    assert verdict.evidence["staging_s"] == 99.6
    assert verdict.evidence["budget_s"] == 5.0
    with knobs.override_async_visible_budget_seconds(0.0):
        assert names.RULE_ASYNC_VISIBLE_STALL not in _rules_for([regressed])
    with knobs.override_async_visible_budget_seconds(0.01):
        assert names.RULE_ASYNC_VISIBLE_STALL in _rules_for([healthy])


def test_async_visible_stall_end_to_end(tmp_path):
    """diagnose_snapshot over a real recorded async take: the
    device-snapshot default stays under the budget (no verdict); an
    injected synchronous-staging regression (deferral knob off + a
    sub-visible budget) makes the same diagnosis fire."""
    import jax.numpy as jnp

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu import knobs

    state = {"w": jnp.ones((256, 64))}
    with knobs.enable_telemetry():
        pending = ts.Snapshot.async_take(
            str(tmp_path / "ok"), {"p": ts.PyTreeState(state)}
        )
        pending.wait()
    rules = {v.rule for v in doctor.diagnose_snapshot(str(tmp_path / "ok"))}
    assert names.RULE_ASYNC_VISIBLE_STALL not in rules

    # Regression injection: staging back in the visible span, budget
    # below any real visible time.
    with knobs.enable_telemetry(), knobs.disable_async_device_snapshot(), (
        knobs.override_async_visible_budget_seconds(1e-9)
    ):
        pending = ts.Snapshot.async_take(
            str(tmp_path / "bad"), {"p": ts.PyTreeState(state)}
        )
        pending.wait()
        verdicts = doctor.diagnose_snapshot(str(tmp_path / "bad"))
    fired = [
        v for v in verdicts if v.rule == names.RULE_ASYNC_VISIBLE_STALL
    ]
    assert fired, f"expected async-visible-stall, got {verdicts}"
    assert fired[0].evidence["visible_s"] > 0


def test_mirror_lagging_and_retry_storm_thresholds():
    lagging = _report(
        mirror={"upload_lag_s": 120.0, "snapshots_pending": 1},
        phases={"staging": 1.5, "writing": 3.0},
    )
    assert names.RULE_MIRROR_LAGGING in _rules_for([lagging])
    storm = _report(
        retries={"attempts": 5.0, "backoff_s": 2.0, "exhausted": 0.0},
        phases={"staging": 1.5, "writing": 3.0},
    )
    assert names.RULE_RETRY_STORM in _rules_for([storm])
    quiet = _report(
        mirror={"upload_lag_s": 0.5, "snapshots_pending": 1},
        retries={"attempts": 1.0},
        phases={"staging": 1.5, "writing": 3.0},
    )
    assert _rules_for([quiet]) == set()


# ---------------------------------------------------------------------------
# Acceptance: synthetic slow-storage take -> evidence-cited verdict
# ---------------------------------------------------------------------------


def test_restore_read_amplified_rule():
    """restore-read-amplified fires when storage reads exceed the
    manifest-needed bytes by >1.5x (whole-shard reads serving partial
    destinations, or a dead fan-out), citing the report fields."""
    amplified = _report(
        kind="restore",
        phases={"loading": 1.0},
        bytes_needed=100 * 1024**2,
        bytes_fetched=200 * 1024**2,
    )
    verdicts = [
        v
        for v in doctor.diagnose_reports([amplified])
        if v.rule == names.RULE_RESTORE_READ_AMPLIFIED
    ]
    assert verdicts
    ev = verdicts[0].evidence
    assert ev["amplification"] == 2.0
    assert ev["bytes_fetched"] == 200 * 1024**2
    assert ev["bytes_needed"] == 100 * 1024**2
    assert ev["threshold_factor"] == doctor.READ_AMPLIFIED_FACTOR

    # ~1x restores (ranged reads / fan-out working) stay quiet; so do
    # takes with the same numbers (write pipelines never amplify reads).
    healthy = _report(
        kind="restore",
        phases={"loading": 1.0},
        bytes_needed=100 * 1024**2,
        bytes_fetched=110 * 1024**2,
    )
    assert names.RULE_RESTORE_READ_AMPLIFIED not in _rules_for([healthy])
    take = dict(amplified, kind="take")
    assert names.RULE_RESTORE_READ_AMPLIFIED not in _rules_for([take])
    # Fan-out ledgers are exempt: an owner rank fetches its peers'
    # windows on top of its own needs (healthy skew, judged at fleet
    # level), so received > 0 must suppress the per-rank ratio.
    fanout_owner = dict(amplified, bytes_received=1024)
    assert names.RULE_RESTORE_READ_AMPLIFIED not in _rules_for(
        [fanout_owner]
    )
    # Reports with no needed-bytes denominator (pre-field schema) skip.
    legacy = _report(kind="restore", phases={"loading": 1.0})
    assert names.RULE_RESTORE_READ_AMPLIFIED not in _rules_for([legacy])


def test_restore_read_amplified_falls_back_to_plugin_counters():
    """Older reports without bytes_fetched amplify off the per-plugin
    read-byte counters, and the evidence says so."""
    report = _report(
        kind="async_restore",
        phases={"loading": 1.0},
        bytes_needed=10 * 1024**2,
        plugins={"fs": {"read_bytes": 40 * 1024**2, "read_ops": 12}},
    )
    verdicts = [
        v
        for v in doctor.diagnose_reports([report])
        if v.rule == names.RULE_RESTORE_READ_AMPLIFIED
    ]
    assert verdicts
    assert verdicts[0].evidence["fetched_from"] == "plugin-counters"
    assert verdicts[0].evidence["amplification"] == 4.0


def test_restore_read_amplified_cli_end_to_end(tmp_path, capsys):
    """CLI end-to-end: a recorded restore report whose fetched bytes
    dwarf its needed bytes surfaces the verdict with cited evidence."""
    snap = str(tmp_path / "snap")
    with knobs.enable_telemetry():
        ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state(n=2, size=256))})
        dest = {"s": ts.PyTreeState(_state(n=2, size=256, seed=1))}
        ts.Snapshot(snap).restore(dest)
    # A healthy local restore reads ~what it needs: no verdict.
    rc = stats_main(["doctor", snap, "--json"])
    out = capsys.readouterr().out
    assert names.RULE_RESTORE_READ_AMPLIFIED not in out
    # Inject an amplified restore report into the recorded events and
    # re-diagnose: the rule keys off the recorded fields alone.
    events = os.path.join(snap, ".telemetry.jsonl")
    with open(events, "a", encoding="utf-8") as f:
        f.write(
            json.dumps(
                _report(
                    kind="restore",
                    path=snap,
                    phases={"loading": 2.0},
                    bytes_needed=1024**2,
                    bytes_fetched=4 * 1024**2,
                )
            )
            + "\n"
        )
    rc = stats_main(["doctor", snap])
    out = capsys.readouterr().out
    assert rc == 2
    assert names.RULE_RESTORE_READ_AMPLIFIED in out
    assert "amplification=4.0" in out


def test_doctor_cli_on_synthetic_slow_storage_take(
    tmp_path, monkeypatch, capsys
):
    """Inject storage latency, take with the JSONL sink on, and ask the
    CLI: the storage-tier-slow verdict must appear with the phase
    evidence that triggered it."""
    orig = FSStoragePlugin.write

    async def slow_write(self, write_io):
        await asyncio.sleep(0.3)
        await orig(self, write_io)

    async def decline_fused(self, write_io):
        return None  # fused fast path declines -> slow plain writes

    monkeypatch.setattr(FSStoragePlugin, "write", slow_write)
    monkeypatch.setattr(
        FSStoragePlugin, "write_with_checksum", decline_fused
    )
    snap = str(tmp_path / "snap")
    with knobs.enable_telemetry():
        ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state(n=3, size=256))})

    rc = stats_main(["doctor", snap])
    out = capsys.readouterr().out
    assert rc == 2  # findings present
    assert names.RULE_STORAGE_TIER_SLOW in out
    assert "write_drain_s=" in out  # evidence cited

    # Library API agrees and carries machine-readable evidence.
    verdicts = doctor.diagnose_snapshot(snap)
    slow = [v for v in verdicts if v.rule == names.RULE_STORAGE_TIER_SLOW]
    assert slow
    ev = slow[0].evidence
    assert ev["write_drain_s"] > ev["staging_s"]
    assert ev["wall_s"] >= ev["write_drain_s"]


def test_doctor_flags_interrupted_take_from_leftover_heartbeat(tmp_path):
    """A non-terminal progress leftover (crashed op) becomes
    interrupted-take evidence instead of a silently ignored dotfile."""
    snap = tmp_path / "snap"
    snap.mkdir()
    (snap / ".progress-rank0.json").write_text(
        json.dumps(
            {
                "kind": "take",
                "rank": 0,
                "phase": "writing",
                "written_bytes": 1024,
                "planned_bytes": 4096,
                "items_done": 1,
                "planned_items": 4,
                "terminal": None,
            }
        )
    )
    verdicts = doctor.diagnose_snapshot(str(snap))
    interrupted = [
        v for v in verdicts if v.rule == names.RULE_INTERRUPTED_TAKE
    ]
    assert len(interrupted) == 1
    assert interrupted[0].severity == "critical"
    assert interrupted[0].evidence["written_bytes"] == 1024
    assert interrupted[0].evidence["planned_bytes"] == 4096
    # Ranked most-severe first.
    assert verdicts[0].rule == names.RULE_INTERRUPTED_TAKE


def test_doctor_spares_fresh_heartbeat_of_live_op(tmp_path):
    """A fresh non-terminal heartbeat is a healthy RUNNING op — the
    doctor must not raise a false critical when diagnosing a snapshot
    mid-take; only a stale heartbeat (10x the writer's own interval,
    >= 30 s) is crash evidence."""
    import time as _time

    snap = tmp_path / "snap"
    snap.mkdir()
    doc = {
        "kind": "take",
        "rank": 0,
        "phase": "writing",
        "written_bytes": 1024,
        "planned_bytes": 4096,
        "items_done": 1,
        "planned_items": 4,
        "terminal": None,
        "interval_s": 1.0,
        "updated_unix_ts": _time.time(),
    }
    (snap / ".progress-rank0.json").write_text(json.dumps(doc))
    assert [
        v
        for v in doctor.diagnose_snapshot(str(snap))
        if v.rule == names.RULE_INTERRUPTED_TAKE
    ] == []
    # The same document gone stale IS the crash evidence.
    doc["updated_unix_ts"] = _time.time() - 3600
    (snap / ".progress-rank0.json").write_text(json.dumps(doc))
    assert [
        v
        for v in doctor.diagnose_snapshot(str(snap))
        if v.rule == names.RULE_INTERRUPTED_TAKE
    ]


def test_fsck_stats_lists_progress_leftovers(tmp_path, capsys):
    """fsck --stats surfaces heartbeat leftovers and doctor verdicts."""
    from torchsnapshot_tpu.fsck import main as fsck_main

    snap = str(tmp_path / "snap")
    ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state(n=2, size=128))})
    with open(
        os.path.join(snap, ".progress-rank0.json"), "w", encoding="utf-8"
    ) as f:
        json.dump(
            {
                "kind": "take",
                "rank": 0,
                "phase": "writing",
                "written_bytes": 10,
                "planned_bytes": 100,
                "items_done": 0,
                "planned_items": 2,
                "terminal": None,
            },
            f,
        )
    rc = fsck_main([snap, "--stats"])
    out = capsys.readouterr().out
    assert rc == 0  # the snapshot itself is sound
    assert "progress heartbeats" in out
    assert "NOT TERMINAL" in out
    assert names.RULE_INTERRUPTED_TAKE in out


# ---------------------------------------------------------------------------
# tuner-thrashing (ISSUE 7): oscillating knob adjustments in the
# .tuner-state.json decision log
# ---------------------------------------------------------------------------


def _tuner_decisions(values, tunable="io_concurrency", action="adjust"):
    """Decision-log records whose applied vector walks ``values``."""
    return [
        {
            "step": i,
            "decision": {"action": action, "tunable": tunable},
            "vector": {tunable: v, "staging_threads": 4},
        }
        for i, v in enumerate(values)
    ]


def test_tuner_thrashing_rule_flags_a_b_a_cycles():
    """A -> B -> A inside the thrash window fires, citing the concrete
    decision-log entries; monotone trajectories and short logs stay
    silent."""
    osc = doctor.Evidence(
        path="x",
        tuner_state={"decisions": _tuner_decisions([16, 32, 16, 32])},
        tuner_state_file="/root/.tuner-state.json",
    )
    verdicts = [
        v
        for v in doctor.diagnose_evidence(osc)
        if v.rule == names.RULE_TUNER_THRASHING
    ]
    assert len(verdicts) == 1
    ev = verdicts[0].evidence
    assert ev["tunable"] == "io_concurrency"
    assert ev["values"] == [16, 32, 16]
    assert ev["steps"] == [0, 1, 2]
    assert verdicts[0].source == ".tuner-state.json"

    monotone = doctor.Evidence(
        path="x",
        tuner_state={"decisions": _tuner_decisions([16, 32, 64, 64])},
    )
    assert [
        v
        for v in doctor.diagnose_evidence(monotone)
        if v.rule == names.RULE_TUNER_THRASHING
    ] == []
    # A single adjust -> revert cycle is the revert-on-regression guard
    # rail working once (the move cools down) — not thrashing. The same
    # revert-closed cycle RECURRING is.
    one_revert = _tuner_decisions([16, 32])
    one_revert += [
        {
            "step": 2,
            "decision": {"action": "revert", "tunable": "io_concurrency"},
            "vector": {"io_concurrency": 16, "staging_threads": 4},
        }
    ]
    healthy = doctor.Evidence(
        path="x", tuner_state={"decisions": list(one_revert)}
    )
    assert [
        v
        for v in doctor.diagnose_evidence(healthy)
        if v.rule == names.RULE_TUNER_THRASHING
    ] == []
    repeated = list(one_revert) + [
        {
            "step": 3,
            "decision": {"action": "adjust", "tunable": "io_concurrency"},
            "vector": {"io_concurrency": 32, "staging_threads": 4},
        },
        {
            "step": 4,
            "decision": {"action": "revert", "tunable": "io_concurrency"},
            "vector": {"io_concurrency": 16, "staging_threads": 4},
        },
    ]
    rep_ev = doctor.Evidence(
        path="x", tuner_state={"decisions": repeated}
    )
    rep_verdicts = [
        v
        for v in doctor.diagnose_evidence(rep_ev)
        if v.rule == names.RULE_TUNER_THRASHING
    ]
    assert rep_verdicts and rep_verdicts[0].evidence["cycles_in_window"] >= 2
    short = doctor.Evidence(
        path="x", tuner_state={"decisions": _tuner_decisions([16, 32])}
    )
    assert [
        v
        for v in doctor.diagnose_evidence(short)
        if v.rule == names.RULE_TUNER_THRASHING
    ] == []
    # Oscillation older than the thrash window no longer fires.
    aged = doctor.Evidence(
        path="x",
        tuner_state={
            "decisions": _tuner_decisions(
                [16, 32, 16] + [64] * doctor.TUNER_THRASH_WINDOW
            )
        },
    )
    assert [
        v
        for v in doctor.diagnose_evidence(aged)
        if v.rule == names.RULE_TUNER_THRASHING
    ] == []


def test_tuner_thrashing_end_to_end_injection(tmp_path, capsys):
    """diagnose over a real manager step: an injected oscillating
    decision log at the manager root makes the CLI fire with the
    decision-log evidence; a healthy log stays silent."""
    root = tmp_path / "ckpt"
    from torchsnapshot_tpu.manager import CheckpointManager

    mgr = CheckpointManager(str(root))
    mgr.save(0, {"s": ts.PyTreeState(_state(n=2, size=128))})
    snap = os.path.join(str(root), "step_0000000000")

    # Healthy (monotone) log at the manager root: silent.
    (root / ".tuner-state.json").write_text(
        json.dumps({"decisions": _tuner_decisions([16, 32, 64, 64])})
    )
    rules = {v.rule for v in doctor.diagnose_snapshot(snap)}
    assert names.RULE_TUNER_THRASHING not in rules

    # Injected oscillation: the step-dir diagnosis finds the ROOT's
    # decision log (parent lookup) and cites it.
    (root / ".tuner-state.json").write_text(
        json.dumps({"decisions": _tuner_decisions([16, 32, 16, 32, 16])})
    )
    rc = stats_main(["doctor", snap])
    out = capsys.readouterr().out
    assert rc == 2
    assert names.RULE_TUNER_THRASHING in out
    assert "io_concurrency" in out
    verdicts = [
        v
        for v in doctor.diagnose_snapshot(snap)
        if v.rule == names.RULE_TUNER_THRASHING
    ]
    assert verdicts and verdicts[0].evidence["values"][:2] == [16, 32]


# ---------------------------------------------------------------------------
# Bench-trial epistemics (shared with bench.py)
# ---------------------------------------------------------------------------


def test_diagnose_take_trial_matches_bench_semantics():
    # Stable bracket, achieved well below half -> in-take stall.
    verdicts = doctor.diagnose_take_trial(
        take_s=10.0,
        gib=1.0,
        probe_before_gbps=1.0,
        probe_after_gbps=1.1,
        phases={"staging": 9.5, "writing": 10.0},
    )
    assert [v.rule for v in verdicts] == [names.RULE_IN_TAKE_STALL]
    ev = verdicts[0].evidence
    assert ev["ratio"] < doctor.STALL_EFFICIENCY_RATIO
    assert ev["staging_done_s"] == 9.5
    # Unstable bracket -> link-unstable, and NO stall verdict (the
    # bench's old behavior: an unstable bracket never flags a stall).
    verdicts = doctor.diagnose_take_trial(1.0, 1.0, 0.4, 1.0)
    assert [v.rule for v in verdicts] == [names.RULE_LINK_UNSTABLE]
    # Healthy trial -> silence.
    assert doctor.diagnose_take_trial(1.0, 1.0, 1.0, 1.05) == []


def test_probes_unstable_series():
    assert not doctor.probes_unstable([1.0, 1.2, 1.1])
    assert doctor.probes_unstable([1.0, 2.0, 1.9])
    assert not doctor.probes_unstable([])


def test_bench_diagnostics_embed_doctor_verdicts():
    """bench.py's take_diagnostics keep their JSON keys and gain the
    doctor's verdict ids (satellite: shared stall definition)."""
    import bench

    brackets, ratios, eff, unstable = bench._bracketed_efficiency(
        [10.0], [1.0, 1.1], 1.0
    )
    assert unstable is False
    trial = doctor.diagnose_take_trial(10.0, 1.0, 1.0, 1.1)
    assert names.RULE_IN_TAKE_STALL in [v.rule for v in trial]
    assert ratios[0] == pytest.approx(
        trial[0].evidence["ratio"], rel=1e-2
    )


# ---------------------------------------------------------------------------
# History + trend
# ---------------------------------------------------------------------------


def _summary(step, take_s, mb_s=100.0, wait=0.01):
    return {
        "step": step,
        "kind": "take",
        "path": f"/snaps/step_{step:010d}",
        "unix_ts": 0.0,
        "take_s": take_s,
        "phases": {"staging": take_s * 0.4, "writing": take_s},
        "bytes_moved": 1024,
        "blobs": 4,
        "mb_s": mb_s,
        "budget_wait_s": wait,
    }


def test_trend_flags_exactly_the_injected_regression(tmp_path):
    """>= 3 steps, one injected 3x take-time regression: the doctor
    flags that step and no other."""
    records = [
        _summary(0, 1.0),
        _summary(1, 1.05),
        _summary(2, 0.95),
        _summary(3, 3.2),  # injected regression
        _summary(4, 1.0),
    ]
    verdicts = doctor.diagnose_trend(records)
    flagged_steps = {v.evidence["step"] for v in verdicts}
    assert flagged_steps == {3}
    assert all(v.rule == names.RULE_TREND_REGRESSION for v in verdicts)
    take_rows = [
        v for v in verdicts if v.evidence["metric"] == "take_s"
    ]
    assert take_rows and take_rows[0].evidence["value"] == 3.2


def test_trend_quiet_on_flat_history():
    records = [_summary(i, 1.0 + 0.01 * (i % 3)) for i in range(10)]
    assert doctor.diagnose_trend(records) == []


def test_manager_saves_append_bounded_history(tmp_path):
    """Each committed step appends one summary; the file is bounded by
    the knob; doctor --trend reads it through the CLI."""
    root = str(tmp_path / "ckpts")
    state = {"s": ts.PyTreeState(_state(n=2, size=256))}
    with knobs.override_history_max_records(3), knobs.enable_telemetry():
        mgr = ts.CheckpointManager(root)
        for step in range(5):
            mgr.save(step, state)
    path = history.history_path_for(root)
    records = history.load_history(path)
    # Bounded to the newest 3 of the 5 saves.
    assert [r["step"] for r in records] == [2, 3, 4]
    assert all(r["kind"] == "take" for r in records)
    assert all(r["take_s"] >= 0 for r in records)


def test_async_save_records_history_too(tmp_path):
    root = str(tmp_path / "ckpts")
    state = {"s": ts.PyTreeState(_state(n=2, size=256))}
    with knobs.override_history_max_records(10), knobs.enable_telemetry():
        mgr = ts.CheckpointManager(root)
        mgr.async_save(0, state).wait()
    records = history.load_history(history.history_path_for(root))
    assert [r["step"] for r in records] == [0]
    assert records[0]["kind"] == "async_take"


def test_history_disabled_by_default_in_suite(tmp_path):
    """conftest zeroes the bound: no history file appears unless a test
    opts in (tier-1 determinism)."""
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root)
    mgr.save(0, {"s": ts.PyTreeState(_state(n=1, size=64))})
    assert not os.path.exists(os.path.join(root, ".telemetry-history.jsonl"))


def test_doctor_trend_cli_over_manager_history(tmp_path, capsys):
    """snapshot_stats `trend <root>` and `doctor --trend <root>` find
    the history file and flag the injected regression."""
    root = tmp_path / "ckpts"
    root.mkdir()
    records = [_summary(i, 1.0) for i in range(4)] + [_summary(4, 5.0)]
    with open(root / ".telemetry-history.jsonl", "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    rc = stats_main(["doctor", "--trend", str(root)])
    out = capsys.readouterr().out
    assert rc == 2
    assert "step 4" in out
    assert names.RULE_TREND_REGRESSION in out
    # The `trend` shorthand routes to the same diagnosis.
    rc = stats_main(["trend", str(root), "--json"])
    rows = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert {r["evidence"]["step"] for r in rows} == {4}


def test_trend_end_to_end_over_real_manager_steps(
    tmp_path, monkeypatch, capsys
):
    """The full acceptance path: >= 3 real manager saves feed the
    rolling history, one step suffers injected storage latency, and
    ``doctor --trend`` flags exactly that step."""
    orig = FSStoragePlugin.write
    slow_steps = {2}
    current = {"step": None}

    async def maybe_slow(self, write_io):
        if current["step"] in slow_steps:
            await asyncio.sleep(0.25)
        await orig(self, write_io)

    async def decline_fused(self, write_io):
        return None

    monkeypatch.setattr(FSStoragePlugin, "write", maybe_slow)
    monkeypatch.setattr(
        FSStoragePlugin, "write_with_checksum", decline_fused
    )
    root = str(tmp_path / "ckpts")
    state = {"s": ts.PyTreeState(_state(n=2, size=256))}
    with knobs.override_history_max_records(10), knobs.enable_telemetry():
        mgr = ts.CheckpointManager(root)
        for step in range(4):
            current["step"] = step
            mgr.save(step, state)
    rc = stats_main(["doctor", "--trend", root])
    out = capsys.readouterr().out
    assert rc == 2, out
    verdicts = doctor.diagnose_trend(
        history.load_history(history.history_path_for(root))
    )
    assert {v.evidence["step"] for v in verdicts} == slow_steps


def test_doctor_trend_cli_without_history(tmp_path, capsys):
    rc = stats_main(["doctor", "--trend", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "no step history" in out


def test_history_append_is_bounded_and_atomic(tmp_path):
    with knobs.override_history_max_records(2):
        for i in range(4):
            history.append_summary(str(tmp_path), _summary(i, 1.0))
    records = history.load_history(history.history_path_for(str(tmp_path)))
    assert [r["step"] for r in records] == [2, 3]
    # Corrupt line resilience.
    with open(
        history.history_path_for(str(tmp_path)), "a", encoding="utf-8"
    ) as f:
        f.write("{torn\n")
    assert len(history.load_history(history.history_path_for(str(tmp_path)))) == 2


def test_restore_cold_start_slow_rule():
    """restore-cold-start-slow fires when the recorded cold_start_s
    dominates the op wall beyond the knob budget, citing the
    {event_loop_s, plugin_open_s, native_load_s} split (the r06
    first-trial-restore soft spot as a ranked verdict)."""
    cold = _report(
        kind="restore",
        phases={"loading": 2.0},
        cold_start_s=8.0,
        cold_start={
            "event_loop_s": 1.0,
            "plugin_open_s": 2.5,
            "native_load_s": 4.5,
        },
    )
    verdicts = [
        v
        for v in doctor.diagnose_reports([cold])
        if v.rule == names.RULE_RESTORE_COLD_START_SLOW
    ]
    assert verdicts
    ev = verdicts[0].evidence
    # cold_start_s runs before the phase clocks: wall = phases + cold.
    assert ev["wall_s"] == 10.0
    assert ev["cold_fraction"] == 0.8
    assert ev["budget_fraction"] == 0.5
    assert ev["plugin_open_s"] == 2.5
    assert ev["native_load_s"] == 4.5

    # Warm restores (sub-second cold start) stay quiet even at a high
    # fraction — the floor keeps trivial ops out of the report.
    warm = _report(kind="restore", phases={"loading": 0.1}, cold_start_s=0.4)
    assert names.RULE_RESTORE_COLD_START_SLOW not in _rules_for([warm])
    # Cold-but-within-budget restores stay quiet.
    within = _report(kind="restore", phases={"loading": 9.0}, cold_start_s=2.0)
    assert names.RULE_RESTORE_COLD_START_SLOW not in _rules_for([within])
    # Takes never fire it, and <= 0 budget disables the rule outright.
    take = dict(cold, kind="take")
    assert names.RULE_RESTORE_COLD_START_SLOW not in _rules_for([take])
    with knobs.override_cold_start_budget_fraction(0):
        assert names.RULE_RESTORE_COLD_START_SLOW not in _rules_for([cold])
