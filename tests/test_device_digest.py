"""Device-digest properties: host/device bit-identity, sensitivity,
blockwise exactness. The host↔device identity is what lets a leaf move
between numpy and jax across steps without a spurious full rewrite."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from torchsnapshot_tpu.ops import device_digest as dd  # noqa: E402
from torchsnapshot_tpu.test_utils import rand_array  # noqa: E402

# Every digestable dtype in the serialization table, by lane width.
DTYPES = [
    "float32",
    "float16",
    "bfloat16",
    "float64",
    "int8",
    "uint8",
    "int16",
    "int32",
    "uint32",
    "int64",
    "bool",
    "float8_e4m3fn",
]


def _np_array(shape, dtype, seed=0):
    if dtype in ("bfloat16", "float8_e4m3fn"):
        import ml_dtypes

        return rand_array(shape, "float32", seed).astype(
            np.dtype(getattr(ml_dtypes, dtype))
        )
    if dtype in ("float64", "int64"):
        return rand_array(shape, "float32", seed).astype(dtype)
    return rand_array(shape, dtype, seed)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", [(7,), (4, 5), (1,), (), (3, 2, 2)])
def test_host_device_identity(dtype, shape):
    host = _np_array(shape, dtype, seed=3)
    d_host = dd.digest_host(host)
    if dtype in ("float64", "int64") and not jax.config.read("jax_enable_x64"):
        pytest.skip("64-bit device arrays require x64")
    dev = jnp.asarray(host)
    d_dev = dd.materialize(dd.digest_device_async(dev))
    assert d_host == d_dev


def test_digest_sensitivity_single_bit():
    base = _np_array((64, 64), "float32", seed=1)
    d0 = dd.digest_host(base)
    flipped = base.copy()
    raw = flipped.reshape(-1).view(np.uint8)
    raw[12345 % raw.size] ^= 1
    assert dd.digest_host(flipped) != d0


def test_digest_depends_on_position():
    a = np.array([1, 2, 3, 4], dtype=np.uint32)
    b = np.array([2, 1, 3, 4], dtype=np.uint32)
    assert dd.digest_host(a) != dd.digest_host(b)


def test_digest_depends_on_length():
    a = np.zeros(8, dtype=np.uint8)
    b = np.zeros(9, dtype=np.uint8)
    assert dd.digest_host(a) != dd.digest_host(b)


def test_blockwise_matches_whole(monkeypatch):
    arr = _np_array((3, 1 << 12), "float32", seed=5)
    whole = dd.digest_host(arr)
    monkeypatch.setattr(dd, "_HOST_BLOCK_LANES", 1000)  # force many blocks
    assert dd.digest_host(arr) == whole


def test_row_range_matches_slice():
    host = _np_array((16, 8), "float32", seed=7)
    dev = jnp.asarray(host)
    ranged = dd.materialize(dd.digest_device_async(dev, row_range=(4, 12)))
    assert ranged == dd.digest_host(host[4:12])


def test_noncontiguous_host_input():
    base = _np_array((10, 10), "float32", seed=9)
    view = base[:, ::2]
    assert dd.digest_host(view) == dd.digest_host(np.ascontiguousarray(view))


def test_format_digest_roundtrippable_string():
    s = dd.format_digest((0x1234ABCD, 0x00FF00FF))
    assert s == "mlh64:1234abcd00ff00ff"
    assert s.startswith(dd.DIGEST_PREFIX)


def test_unsupported_dtypes_rejected():
    assert not dd.digest_supported(np.complex64)
    with pytest.raises(TypeError):
        dd.digest_host(np.zeros(3, dtype=np.complex64))


def test_digest_ignores_shape_keeps_bytes():
    # Same memory image, different shape: digest is over bytes, so equal.
    # (Shape/dtype identity is enforced by the chunk-key comparison in
    # incremental.py, not by the digest.)
    a = _np_array((4, 6), "float32", seed=11)
    b = a.reshape(6, 4)
    assert dd.digest_host(a) == dd.digest_host(b)


def test_sharded_array_shard_digests_match_host():
    """Digesting each addressable shard of a sharded device array equals
    digesting the corresponding host slice."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    devs = jax.devices()[:2]
    mesh = Mesh(np.array(devs), ("x",))
    host = _np_array((8, 4), "float32", seed=13)
    arr = jax.device_put(host, NamedSharding(mesh, P("x", None)))
    for shard in arr.addressable_shards:
        expect = dd.digest_host(np.asarray(host[shard.index]))
        got = dd.materialize(dd.digest_device_async(shard.data))
        assert got == expect


def test_subbyte_dtypes_rejected():
    try:
        import ml_dtypes
    except ImportError:
        pytest.skip("ml_dtypes unavailable")
    assert not dd.digest_supported(ml_dtypes.int4)
    assert not dd.digest_supported(ml_dtypes.uint4)
