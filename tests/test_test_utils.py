"""The test harness itself: equality helpers, random data, and
multiprocess error propagation.

Reference parity: tests/test_test_utils.py (test_utils.py:72-290).
"""

from __future__ import annotations

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import (
    assert_tree_eq,
    multiprocess_test,
    rand_array,
    run_multiprocess,
    tree_eq,
)


def test_tree_eq_nested() -> None:
    a = {"x": [np.arange(3), {"y": 1.5}], "z": "s"}
    b = {"x": [np.arange(3), {"y": 1.5}], "z": "s"}
    assert tree_eq(a, b)
    b["x"][0] = np.array([0, 1, 3])
    assert not tree_eq(a, b)


def test_tree_eq_dtype_and_shape_sensitive() -> None:
    assert not tree_eq(np.zeros(3, np.float32), np.zeros(3, np.float64))
    assert not tree_eq(np.zeros((3, 1)), np.zeros(3))
    assert tree_eq(np.zeros(3), np.zeros(3))


def test_tree_eq_key_mismatch() -> None:
    assert not tree_eq({"a": 1}, {"b": 1})
    assert not tree_eq([1, 2], [1, 2, 3])


def test_tree_eq_jax_leaves() -> None:
    import jax.numpy as jnp

    assert tree_eq({"w": jnp.ones(4)}, {"w": np.ones(4, np.float32)})


def test_assert_tree_eq_raises_with_context() -> None:
    with pytest.raises(AssertionError, match="Trees differ"):
        assert_tree_eq({"a": 1}, {"a": 2})


@pytest.mark.parametrize(
    "dtype", ["float32", "float16", "int8", "uint8", "int32", "bool", "complex64"]
)
def test_rand_array_dtypes(dtype: str) -> None:
    arr = rand_array((4, 3), dtype, seed=1)
    assert arr.shape == (4, 3)
    assert arr.dtype == np.dtype(dtype)
    again = rand_array((4, 3), dtype, seed=1)
    np.testing.assert_array_equal(arr, again)


def _failing_rank_fn(pg) -> int:
    if pg.rank == 1:
        raise RuntimeError("rank 1 exploded")
    return pg.rank


def test_run_multiprocess_propagates_worker_error() -> None:
    with pytest.raises(AssertionError, match="rank 1 exploded"):
        run_multiprocess(_failing_rank_fn, nproc=2)


def _rank_result_fn(pg, base: int) -> int:
    return base + pg.rank


def test_run_multiprocess_returns_rank_ordered_results() -> None:
    assert run_multiprocess(_rank_result_fn, nproc=2, args=(10,)) == [10, 11]


def test_multiprocess_test_decorator_metadata() -> None:
    @multiprocess_test(nproc=2)
    def my_test(pg) -> None:  # pragma: no cover - not executed here
        pass

    assert my_test.__name__ == "my_test"
    assert callable(my_test._ts_inner_fn)
