"""Single-process Snapshot take/restore/read_object round-trips.

Structural model: reference tests/test_snapshot.py:25-145 — property-matrix
round-trips verified by exact equality, plus chunked-path coverage via
shrunken knobs.
"""

import math
import os

import chex
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.knobs import override_max_chunk_size_bytes
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME


def _make_app_state():
    params = {
        "dense": {"w": jnp.ones((8, 16), jnp.bfloat16) * 0.5, "b": jnp.zeros(16)},
        "emb": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
    }
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    return {
        "params": ts.PyTreeState(params),
        "opt": ts.PyTreeState(opt_state),
        "progress": ts.StateDict(epoch=3, step=1234, lr=0.125, name="run", done=False),
        "rng": ts.RngState(jax.random.key(7)),
        "extra": ts.StateDict(
            blob={"nested": [1, 2, {"x": np.arange(5)}]}, opaque={10, 20}
        ),
    }, params, opt_state


def _fresh_app_state():
    params = {
        "dense": {
            "w": jnp.zeros((8, 16), jnp.bfloat16),
            "b": jnp.full((16,), -1.0),
        },
        "emb": jnp.zeros((8, 8), jnp.float32),
    }
    opt = optax.adam(1e-3)
    opt_state = opt.init(jax.tree_util.tree_map(lambda x: x * 0, params))
    return {
        "params": ts.PyTreeState(params),
        "opt": ts.PyTreeState(opt_state),
        "progress": ts.StateDict(epoch=0, step=0, lr=0.0, name="", done=True),
        "rng": ts.RngState(jax.random.key(0)),
        "extra": ts.StateDict(blob=None, opaque=None),
    }


def test_take_restore_roundtrip(tmp_path) -> None:
    app_state, params, opt_state = _make_app_state()
    snapshot = ts.Snapshot.take(str(tmp_path), app_state)
    assert os.path.exists(tmp_path / SNAPSHOT_METADATA_FNAME)

    fresh = _fresh_app_state()
    ts.Snapshot(str(tmp_path)).restore(fresh)

    chex.assert_trees_all_equal(fresh["params"].tree, params)
    chex.assert_trees_all_equal(fresh["opt"].tree, opt_state)
    assert dict(fresh["progress"]) == {
        "epoch": 3,
        "step": 1234,
        "lr": 0.125,
        "name": "run",
        "done": False,
    }
    # Restored leaves keep their flavor: jax stays jax, numpy stays numpy.
    assert isinstance(fresh["params"].tree["dense"]["w"], jax.Array)
    assert fresh["params"].tree["dense"]["w"].dtype == jnp.bfloat16
    restored_blob = fresh["extra"]["blob"]
    np.testing.assert_array_equal(restored_blob["nested"][2]["x"], np.arange(5))
    # RNG restored: same key -> same draw.
    expected = jax.random.normal(jax.random.key(7), (3,))
    actual = jax.random.normal(fresh["rng"].keys, (3,))
    np.testing.assert_array_equal(np.asarray(expected), np.asarray(actual))
    assert snapshot.metadata.world_size == 1


def test_take_restore_chunked(tmp_path) -> None:
    """Shrunken chunk knob forces the chunked path on small arrays
    (reference fixture pattern: tests/test_ddp.py:35-59)."""
    arr = jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)
    app_state = {"s": ts.PyTreeState({"big": arr})}
    with override_max_chunk_size_bytes(1024):
        snap = ts.Snapshot.take(str(tmp_path), app_state)
    manifest = snap.get_manifest()
    entry = manifest["0/s/big"]
    assert entry.type == "ChunkedArray"
    assert len(entry.chunks) == math.ceil(4096 * 4 / 1024)

    fresh = {"s": ts.PyTreeState({"big": jnp.zeros((64, 64), jnp.float32)})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    np.testing.assert_array_equal(np.asarray(fresh["s"].tree["big"]), np.asarray(arr))


@pytest.mark.parametrize(
    "dtype",
    [
        "float32",
        "bfloat16",
        "float16",
        "int8",
        "int32",
        "uint8",
        "bool",
        "complex64",
        "float8_e4m3fn",
        "float8_e5m2",
    ],
)
def test_roundtrip_dtypes(tmp_path, dtype) -> None:
    from torchsnapshot_tpu.test_utils import backend_materializes_dtype

    if not backend_materializes_dtype(dtype):
        pytest.skip(f"{dtype} not materializable on this jax backend")
    rng = np.random.default_rng(0)
    if dtype.startswith("float8"):
        import ml_dtypes

        arr = rng.standard_normal((16, 4)).astype(getattr(ml_dtypes, dtype))
    elif dtype == "bool":
        arr = rng.integers(0, 2, (16, 4)).astype(bool)
    elif dtype == "complex64":
        arr = (rng.standard_normal((16, 4)) + 1j * rng.standard_normal((16, 4))).astype(
            np.complex64
        )
    elif np.dtype(dtype).kind in "iu":
        arr = rng.integers(0, 100, (16, 4)).astype(dtype)
    else:
        arr = rng.standard_normal((16, 4)).astype(dtype)
    x = jnp.asarray(arr)
    app_state = {"t": ts.PyTreeState({"x": x})}
    ts.Snapshot.take(str(tmp_path), app_state)
    fresh = {"t": ts.PyTreeState({"x": jnp.zeros_like(x)})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    np.testing.assert_array_equal(
        np.ascontiguousarray(np.asarray(fresh["t"].tree["x"])).view(np.uint8),
        np.ascontiguousarray(np.asarray(x)).view(np.uint8),
    )


def test_read_object(tmp_path) -> None:
    app_state, params, _ = _make_app_state()
    ts.Snapshot.take(str(tmp_path), app_state)
    snap = ts.Snapshot(str(tmp_path))

    # Primitive: inline value, no I/O.
    assert snap.read_object("0/progress/step") == 1234
    assert snap.read_object("0/progress/lr") == 0.125

    # Array.
    emb = snap.read_object("0/params/emb")
    np.testing.assert_array_equal(np.asarray(emb), np.asarray(params["emb"]))

    # Array with memory budget -> chunked ranged reads.
    emb2 = snap.read_object("0/params/emb", memory_budget_bytes=64)
    np.testing.assert_array_equal(np.asarray(emb2), np.asarray(params["emb"]))

    # In-place destination.
    out = np.zeros((8, 8), np.float32)
    got = snap.read_object("0/params/emb", obj_out=out)
    assert got is out
    np.testing.assert_array_equal(out, np.asarray(params["emb"]))

    # Leaf inside a nested container.
    x = snap.read_object("0/extra/blob/nested/2/x")
    np.testing.assert_array_equal(x, np.arange(5))

    # Object entry (sets are not flattenable -> pickled whole).
    opaque = snap.read_object("0/extra/opaque")
    assert opaque == {10, 20}

    # Errors.
    with pytest.raises(ValueError, match="not a valid entry"):
        snap.read_object("0/nope")
    with pytest.raises(ValueError, match="rank"):
        snap.read_object("progress/step")
    with pytest.raises(ValueError, match="container"):
        snap.read_object("0/progress")


def test_restore_into_missing_keys_warns_not_crashes(tmp_path) -> None:
    app_state = {"a": ts.StateDict(x=1)}
    ts.Snapshot.take(str(tmp_path), app_state)
    fresh = {"a": ts.StateDict(x=0), "b": ts.StateDict(y=9)}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    assert fresh["a"]["x"] == 1
    assert fresh["b"]["y"] == 9  # untouched


def test_no_commit_marker_means_no_snapshot(tmp_path) -> None:
    with pytest.raises(FileNotFoundError):
        _ = ts.Snapshot(str(tmp_path / "nothing")).metadata


def test_take_validates_app_state(tmp_path) -> None:
    with pytest.raises(TypeError, match="Stateful"):
        ts.Snapshot.take(str(tmp_path), {"bad": {"plain": "dict"}})
    with pytest.raises(TypeError, match="app_state keys"):
        ts.Snapshot.take(str(tmp_path), {7: ts.StateDict(x=1)})


def test_memory_url_roundtrip() -> None:
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    try:
        app_state = {"p": ts.PyTreeState({"w": jnp.ones(4)})}
        ts.Snapshot.take("memory://snaptest", app_state)
        fresh = {"p": ts.PyTreeState({"w": jnp.zeros(4)})}
        ts.Snapshot("memory://snaptest").restore(fresh)
        np.testing.assert_array_equal(np.asarray(fresh["p"].tree["w"]), np.ones(4))
    finally:
        MemoryStoragePlugin.drop_store("snaptest")


def test_manifest_yaml_on_disk_is_loadable(tmp_path) -> None:
    app_state, _, _ = _make_app_state()
    ts.Snapshot.take(str(tmp_path), app_state)
    from torchsnapshot_tpu.manifest import SnapshotMetadata

    text = (tmp_path / SNAPSHOT_METADATA_FNAME).read_text()
    md = SnapshotMetadata.from_yaml(text)
    assert "0/params/dense/w" in md.manifest
    assert md.manifest["0/params/dense/w"].dtype == "bfloat16"
