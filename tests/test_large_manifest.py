"""Large-manifest scale: the torchrec regime that motivated the
reference's JSON-for-huge-manifests escape hatch (reference
manifest.py:19-22). A 1e5-leaf app state must plan, commit, and restore
in seconds with bounded metadata, not minutes of per-leaf overhead.

Measured on this repo's CI-class CPU (1 core, 2026-07-30), batching on:
take ~5 s, restore ~4 s, metadata ~23 MB committed as JSON. The three
scale enablers, each load-bearing: slab batching (1e5 files -> 3),
inline staging/consuming of sub-1MiB buffers (no executor round-trip per
tiny leaf), and shallow manifest encoding (no dataclasses.asdict deep
recursion)."""

import json
import os
import time

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.knobs import enable_batching

N_LEAVES = 100_000


@pytest.fixture(scope="module")
def big_tree():
    return {
        f"table_{i // 1000}/row_{i % 1000}": np.full((4,), i % 97, np.float32)
        for i in range(N_LEAVES)
    }


@pytest.mark.slow
def test_1e5_leaf_take_restore(tmp_path, big_tree) -> None:
    path = str(tmp_path / "snap")
    with enable_batching():
        t0 = time.perf_counter()
        ts.Snapshot.take(path, {"emb": ts.PyTreeState(big_tree)})
        t_take = time.perf_counter() - t0

        # Metadata stays JSON-parseable (the huge-manifest invariant) and
        # bounded: ~230 B/leaf, not KBs of YAML ceremony.
        meta_path = os.path.join(path, ".snapshot_metadata")
        meta_bytes = os.path.getsize(meta_path)
        with open(meta_path) as f:
            manifest = json.load(f)["manifest"]
        assert len(manifest) > N_LEAVES  # leaves + container entries
        assert meta_bytes < 400 * N_LEAVES

        # Slab batching collapsed 1e5 tiny blobs into a handful of files.
        n_files = sum(len(fs) for _, _, fs in os.walk(path))
        assert n_files < 50, f"{n_files} files for {N_LEAVES} leaves"

        dst = {k: np.zeros((4,), np.float32) for k in big_tree}
        wrapped = ts.PyTreeState(dst)
        t0 = time.perf_counter()
        ts.Snapshot(path).restore({"emb": wrapped})
        t_restore = time.perf_counter() - t0

    np.testing.assert_array_equal(
        wrapped.tree["table_5/row_500"], np.full((4,), 5500 % 97, np.float32)
    )
    np.testing.assert_array_equal(
        wrapped.tree[f"table_{N_LEAVES // 1000 - 1}/row_999"],
        np.full((4,), (N_LEAVES - 1) % 97, np.float32),
    )
    # Generous CI bounds (~10x of measured) — regressions to per-leaf
    # executor hops or asdict recursion blow through them immediately.
    assert t_take < 60, f"take took {t_take:.1f}s"
    assert t_restore < 60, f"restore took {t_restore:.1f}s"


@pytest.mark.slow
def test_1e5_leaf_read_object(tmp_path, big_tree) -> None:
    """Random access into a huge snapshot must not pay the full restore."""
    path = str(tmp_path / "snap")
    with enable_batching():
        ts.Snapshot.take(path, {"emb": ts.PyTreeState(big_tree)})
        snap = ts.Snapshot(path)
        t0 = time.perf_counter()
        val = snap.read_object("0/emb/table_7%2Frow_123")
        t_read = time.perf_counter() - t0
    np.testing.assert_array_equal(val, np.full((4,), 7123 % 97, np.float32))
    assert t_read < 30, f"read_object took {t_read:.1f}s"


@pytest.mark.slow
def test_1e5_leaf_incremental_take(tmp_path, big_tree) -> None:
    """Digest-enabled takes must stay in the same time class at 1e5
    leaves (host digests of tiny leaves are vectorized numpy, not
    per-leaf device dispatches), and an unchanged-state incremental take
    must skip essentially all data bytes while planning in seconds."""
    p0 = str(tmp_path / "step_0")
    p1 = str(tmp_path / "step_1")
    with enable_batching():
        t0 = time.perf_counter()
        ts.Snapshot.take(
            p0, {"emb": ts.PyTreeState(big_tree)}, record_digests=True
        )
        t_base = time.perf_counter() - t0

        t0 = time.perf_counter()
        ts.Snapshot.take(
            p1, {"emb": ts.PyTreeState(big_tree)}, incremental_base=p0
        )
        t_incr = time.perf_counter() - t0

    # Data bytes: step 1 should hold (almost) none — every leaf refs the
    # base. Only metadata/checksums remain.
    data_bytes = 0
    for dirpath, _, files in os.walk(p1):
        for f in files:
            if f.startswith(".snapshot_metadata") or "checksums" in dirpath:
                continue
            data_bytes += os.path.getsize(os.path.join(dirpath, f))
    assert data_bytes == 0, f"{data_bytes} unexpected data bytes"

    manifest = json.load(open(os.path.join(p1, ".snapshot_metadata")))[
        "manifest"
    ]
    refs = sum(
        1
        for e in manifest.values()
        if isinstance(e.get("location"), str)
        and e["location"].startswith("../")
    )
    assert refs >= N_LEAVES

    dst = {k: np.zeros((4,), np.float32) for k in big_tree}
    wrapped = ts.PyTreeState(dst)
    ts.Snapshot(p1).restore({"emb": wrapped})
    np.testing.assert_array_equal(
        wrapped.tree["table_5/row_500"], np.full((4,), 5500 % 97, np.float32)
    )
    # Same generous CI bounds as the plain take.
    assert t_base < 90, f"digest-enabled base take took {t_base:.1f}s"
    assert t_incr < 60, f"incremental take took {t_incr:.1f}s"
