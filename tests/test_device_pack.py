"""Device-slab packing: bit-exactness against the serialization path and
end-to-end batched snapshots staging device members through one packed
transfer (the reference's GPUBatchedBufferStager analog, as an XLA
program)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402
from torchsnapshot_tpu.knobs import (  # noqa: E402
    enable_batching,
    enable_device_pack,
    override_slab_size_threshold_bytes,
)
from torchsnapshot_tpu.ops import device_pack as dp  # noqa: E402
from torchsnapshot_tpu.serialization import array_as_memoryview  # noqa: E402
from torchsnapshot_tpu.test_utils import assert_tree_eq, rand_array  # noqa: E402

DTYPES = [
    "float32",
    "float16",
    "bfloat16",
    "int8",
    "uint8",
    "int32",
    "bool",
    "float8_e4m3fn",
]


def _np_array(shape, dtype, seed=0):
    if dtype in ("bfloat16", "float8_e4m3fn"):
        import ml_dtypes

        return rand_array(shape, "float32", seed).astype(
            np.dtype(getattr(ml_dtypes, dtype))
        )
    return rand_array(shape, dtype, seed)


@pytest.mark.parametrize("dtype", DTYPES)
def test_pack_matches_serialization_bytes(dtype):
    from torchsnapshot_tpu.test_utils import backend_materializes_dtype

    if not backend_materializes_dtype(dtype):
        pytest.skip(f"backend cannot materialize {dtype}")
    hosts = [_np_array((5, 3), dtype, seed=i) for i in range(3)]
    devs = [jnp.asarray(h) for h in hosts]
    packed = np.asarray(dp.pack_async([(d, None) for d in devs]))
    expect = b"".join(bytes(array_as_memoryview(h)) for h in hosts)
    assert packed.tobytes() == expect


def test_pack_row_slices():
    host = _np_array((16, 4), "float32", seed=1)
    dev = jnp.asarray(host)
    packed = np.asarray(dp.pack_async([(dev, (2, 7)), (dev, (10, 12))]))
    expect = host[2:7].tobytes() + host[10:12].tobytes()
    assert packed.tobytes() == expect


def test_pack_supported_excludes_subbyte_and_complex():
    assert not dp.pack_supported(np.complex64)
    try:
        import ml_dtypes

        assert not dp.pack_supported(ml_dtypes.int4)
    except ImportError:
        pass
    assert dp.pack_supported(np.float32)


def test_batched_snapshot_uses_device_pack(tmp_path, monkeypatch):
    """With batching on, device members of a slab must stage through ONE
    pack call (not per-member np.asarray), and the snapshot must restore
    bit-exactly."""
    from torchsnapshot_tpu.ops import device_pack

    calls = []
    orig = device_pack.pack_async

    def counting(specs):
        calls.append(len(specs))
        return orig(specs)

    monkeypatch.setattr(device_pack, "pack_async", counting)

    tree = {
        f"leaf_{i}": jnp.asarray(_np_array((32, 8), "float32", seed=i))
        for i in range(6)
    }
    tree["host_leaf"] = _np_array((16,), "float32", seed=99)
    p = str(tmp_path / "snap")
    with enable_batching(), enable_device_pack(), \
            override_slab_size_threshold_bytes(1 << 20):
        ts.Snapshot.take(p, {"m": ts.PyTreeState(tree)})
    # All 6 device leaves are below the threshold and on one device group:
    # exactly one pack call with 6 members.
    assert calls == [6]

    dest = {
        "m": ts.PyTreeState(
            {
                **{
                    f"leaf_{i}": jnp.zeros((32, 8), jnp.float32)
                    for i in range(6)
                },
                "host_leaf": np.zeros(16, np.float32),
            }
        )
    }
    ts.Snapshot(p).restore(dest)
    assert_tree_eq(dest["m"].tree, tree)


def test_batched_snapshot_mixed_dtypes_roundtrip(tmp_path):
    tree = {}
    for i, dtype in enumerate(DTYPES):
        from torchsnapshot_tpu.test_utils import backend_materializes_dtype

        if not backend_materializes_dtype(dtype):
            continue
        tree[f"a_{dtype}"] = jnp.asarray(_np_array((7, 3), dtype, seed=i))
    p = str(tmp_path / "snap")
    with enable_batching(), enable_device_pack(), \
            override_slab_size_threshold_bytes(1 << 20):
        ts.Snapshot.take(p, {"m": ts.PyTreeState(tree)})
    dest = {
        "m": ts.PyTreeState(
            {k: jnp.zeros_like(v) for k, v in tree.items()}
        )
    }
    ts.Snapshot(p).restore(dest)
    for k, v in tree.items():
        got = np.asarray(dest["m"].tree[k])
        want = np.asarray(v)
        assert got.tobytes() == want.tobytes(), k


def test_pack_failure_falls_back(tmp_path, monkeypatch):
    """A failing pack degrades to per-member staging, not a failed take."""
    from torchsnapshot_tpu.ops import device_pack

    def boom(specs):
        raise RuntimeError("injected pack failure")

    monkeypatch.setattr(device_pack, "pack_async", boom)
    tree = {
        f"leaf_{i}": jnp.asarray(_np_array((8, 8), "float32", seed=i))
        for i in range(4)
    }
    p = str(tmp_path / "snap")
    with enable_batching(), enable_device_pack(), \
            override_slab_size_threshold_bytes(1 << 20):
        ts.Snapshot.take(p, {"m": ts.PyTreeState(tree)})
    dest = {
        "m": ts.PyTreeState(
            {f"leaf_{i}": jnp.zeros((8, 8), jnp.float32) for i in range(4)}
        )
    }
    ts.Snapshot(p).restore(dest)
    assert_tree_eq(dest["m"].tree, tree)


def test_pack_fallback_skips_already_scattered_members(monkeypatch):
    """A mid-scatter failure falls back per-member but must skip members
    whose bytes already landed in the slab (their arr was cleared) —
    re-staging them would hit np.asarray(None)."""
    from torchsnapshot_tpu import batcher
    from torchsnapshot_tpu.io_preparer import ArrayBufferStager
    from torchsnapshot_tpu.io_types import WriteReq
    from torchsnapshot_tpu.ops import device_pack

    def boom(specs):
        raise RuntimeError("injected pack failure")

    monkeypatch.setattr(device_pack, "pack_async", boom)

    a = jnp.asarray(_np_array((4, 4), "float32", seed=0))
    b = jnp.asarray(_np_array((4, 4), "float32", seed=1))
    sa = ArrayBufferStager(a, is_async_snapshot=False)
    sb = ArrayBufferStager(b, is_async_snapshot=False)
    size = a.nbytes
    items = [
        (WriteReq(path="x", buffer_stager=sa), 0, size),
        (WriteReq(path="y", buffer_stager=sb), size, size),
    ]
    stager = batcher.BatchedBufferStager(items)
    # Simulate a scatter that already copied member 'a' into the slab.
    sa.arr = None
    slab = bytearray(2 * size)
    stager._pack_group_sync(items, memoryview(slab))
    assert bytes(slab[size:]) == np.asarray(b).tobytes()
    assert bytes(slab[:size]) == bytes(size)  # a's region left alone


def test_batched_stager_cost_stable_across_staging():
    """The staging cost is fixed at construction: staging clears
    stager.arr, and a post-staging re-read (budget release/adjust paths)
    must see the admission-time value, not a recomputation over mutated
    state."""
    import asyncio

    from torchsnapshot_tpu import batcher
    from torchsnapshot_tpu.io_preparer import ArrayBufferStager
    from torchsnapshot_tpu.io_types import WriteReq

    arrs = [jnp.asarray(_np_array((8, 8), "float32", seed=i)) for i in range(2)]
    size = arrs[0].nbytes
    items = [
        (
            WriteReq(path=f"p{i}", buffer_stager=ArrayBufferStager(a, False)),
            i * size,
            size,
        )
        for i, a in enumerate(arrs)
    ]
    stager = batcher.BatchedBufferStager(items)
    cost_before = stager.get_staging_cost_bytes()
    buf = asyncio.run(stager.stage_buffer())
    assert len(buf) == 2 * size
    assert stager.get_staging_cost_bytes() == cost_before


def test_device_pack_off_by_default(tmp_path, monkeypatch):
    """Without the knob, batching stages members individually (no pack)."""
    from torchsnapshot_tpu.ops import device_pack

    calls = []
    orig = device_pack.pack_async

    def counting(specs):
        calls.append(len(specs))
        return orig(specs)

    monkeypatch.setattr(device_pack, "pack_async", counting)
    tree = {
        f"leaf_{i}": jnp.asarray(_np_array((8, 8), "float32", seed=i))
        for i in range(4)
    }
    p = str(tmp_path / "snap")
    with enable_batching(), override_slab_size_threshold_bytes(1 << 20):
        ts.Snapshot.take(p, {"m": ts.PyTreeState(tree)})
    assert calls == []


def test_pack_group_cap_splits_dispatches(tmp_path, monkeypatch):
    from torchsnapshot_tpu import batcher
    from torchsnapshot_tpu.ops import device_pack

    monkeypatch.setattr(batcher.BatchedBufferStager, "_PACK_GROUP_MAX", 3)
    calls = []
    orig = device_pack.pack_async

    def counting(specs):
        calls.append(len(specs))
        return orig(specs)

    monkeypatch.setattr(device_pack, "pack_async", counting)
    tree = {
        f"leaf_{i}": jnp.asarray(_np_array((8, 8), "float32", seed=i))
        for i in range(7)
    }
    p = str(tmp_path / "snap")
    with enable_batching(), enable_device_pack(), \
            override_slab_size_threshold_bytes(1 << 20):
        ts.Snapshot.take(p, {"m": ts.PyTreeState(tree)})
    assert sorted(calls) == [3, 3]  # 7 -> [3, 3] + 1 individually
    dest = {
        "m": ts.PyTreeState(
            {f"leaf_{i}": jnp.zeros((8, 8), jnp.float32) for i in range(7)}
        )
    }
    ts.Snapshot(p).restore(dest)
    assert_tree_eq(dest["m"].tree, tree)
