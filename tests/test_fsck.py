"""Snapshot fsck: shallow existence/length audit, deep CRC audit,
incremental-chain awareness (a GC'd base is caught before any restore)."""

import os

import jax.numpy as jnp
import numpy as np

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.fsck import main as fsck_main, verify_snapshot
from torchsnapshot_tpu.knobs import override_max_chunk_size_bytes


def _take(tmp_path, name="snap", **kwargs):
    state = {
        "m": ts.PyTreeState(
            {
                "w": jnp.arange(256, dtype=jnp.float32).reshape(16, 16),
                "b": np.arange(8, dtype=np.int32),
            }
        ),
        "meta": ts.StateDict(step=7, blob={10, 20}),  # opaque pickled leaf
    }
    p = str(tmp_path / name)
    ts.Snapshot.take(p, state, **kwargs)
    return p


def test_sound_snapshot_passes_both_levels(tmp_path):
    p = _take(tmp_path)
    shallow = verify_snapshot(p)
    assert shallow.ok and shallow.blobs_checked >= 3
    deep = verify_snapshot(p, deep=True)
    assert deep.ok and deep.bytes_verified > 0


def test_missing_blob_detected(tmp_path):
    p = _take(tmp_path)
    os.remove(os.path.join(p, "0", "m", "w"))
    report = verify_snapshot(p)
    assert not report.ok
    assert any(
        pr.kind == "missing" and pr.location == "0/m/w"
        for pr in report.problems
    )


def test_truncated_blob_detected_shallow(tmp_path):
    p = _take(tmp_path)
    blob = os.path.join(p, "0", "m", "w")
    with open(blob, "r+b") as f:
        f.truncate(100)  # manifest implies 1024 bytes
    report = verify_snapshot(p)
    assert not report.ok
    assert any(pr.kind == "truncated" for pr in report.problems)


def test_bitrot_detected_deep_only(tmp_path):
    p = _take(tmp_path)
    blob = os.path.join(p, "0", "m", "w")
    with open(blob, "r+b") as f:
        f.seek(64)
        f.write(b"\x00\x00\x00\x00" if open(blob, "rb").read()[64:68] != b"\x00\x00\x00\x00" else b"\xff\xff\xff\xff")
    assert verify_snapshot(p).ok  # same length: shallow cannot see it
    deep = verify_snapshot(p, deep=True)
    assert not deep.ok
    assert any(pr.kind == "checksum" for pr in deep.problems)


def test_uncommitted_directory_fails(tmp_path):
    p = _take(tmp_path)
    os.remove(os.path.join(p, ".snapshot_metadata"))
    report = verify_snapshot(p)
    assert not report.ok
    assert report.problems[0].kind == "missing"


def test_incremental_chain_audited_through_refs(tmp_path):
    w = jnp.arange(64, dtype=jnp.float32)
    state = {"m": ts.PyTreeState({"w": w})}
    p0 = str(tmp_path / "s0")
    p1 = str(tmp_path / "s1")
    ts.Snapshot.take(p0, state, record_digests=True)
    ts.Snapshot.take(p1, state, incremental_base=p0)

    assert verify_snapshot(p1, deep=True).ok

    # Destroy the base blob: the incremental snapshot's audit must fail
    # even though its own directory is untouched.
    os.remove(os.path.join(p0, "0", "m", "w"))
    report = verify_snapshot(p1)
    assert not report.ok
    assert any("../s0/0/m/w" == pr.location for pr in report.problems)


def test_chunked_entries_checked_per_chunk(tmp_path):
    with override_max_chunk_size_bytes(256):
        big = jnp.asarray(
            np.arange(32 * 8, dtype=np.float32).reshape(32, 8)
        )
        p = str(tmp_path / "snap")
        ts.Snapshot.take(p, {"m": ts.PyTreeState({"big": big})})
    report = verify_snapshot(p, deep=True)
    assert report.ok and report.blobs_checked >= 4
    # Remove one chunk only.
    chunks = [
        f for f in os.listdir(os.path.join(p, "0", "m")) if f.startswith("big")
    ]
    os.remove(os.path.join(p, "0", "m", sorted(chunks)[1]))
    assert not verify_snapshot(p).ok


def test_cli_exit_codes(tmp_path, capsys):
    p = _take(tmp_path)
    assert fsck_main([p]) == 0
    assert "OK (shallow)" in capsys.readouterr().out
    assert fsck_main([p, "--deep"]) == 0
    assert "OK (deep)" in capsys.readouterr().out
    os.remove(os.path.join(p, "0", "m", "w"))
    assert fsck_main([p]) == 1
    out = capsys.readouterr().out
    assert "FSCK missing: 0/m/w" in out and "FAILED" in out


def test_deep_streams_across_chunk_boundaries(tmp_path, monkeypatch):
    """Deep CRC verification chains across ranged-read chunks (bounded
    memory); a flip in the SECOND chunk is still caught."""
    from torchsnapshot_tpu import fsck

    monkeypatch.setattr(fsck, "_DEEP_CHUNK_BYTES", 256)
    p = _take(tmp_path)  # w is 1024 bytes -> 4 chunks
    assert verify_snapshot(p, deep=True).ok
    blob = os.path.join(p, "0", "m", "w")
    with open(blob, "r+b") as f:
        f.seek(700)
        f.write(b"\xaa")
    deep = verify_snapshot(p, deep=True)
    assert not deep.ok
    assert any(pr.kind == "checksum" for pr in deep.problems)


def test_deep_counts_crc_verified_blobs(tmp_path):
    p = _take(tmp_path)
    report = verify_snapshot(p, deep=True)
    assert report.crcs_verified == report.blobs_checked
    assert report.bytes_verified > 0


def test_deep_without_tables_is_visibly_hollow(tmp_path, capsys):
    from torchsnapshot_tpu.knobs import disable_checksums

    with disable_checksums():
        p = _take(tmp_path, name="nocrc")
        report = verify_snapshot(p, deep=True)
        assert report.ok and report.crcs_verified == 0
        assert fsck_main([p, "--deep"]) == 0
        out = capsys.readouterr().out
        assert "WARNING: 0 blobs CRC-verified" in out


def test_shallow_transient_error_is_unreadable_not_truncated(tmp_path, monkeypatch):
    """A non-OSError storage failure must be reported as 'unreadable'
    (retryable), never as snapshot damage."""
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    p = _take(tmp_path)
    orig = FSStoragePlugin.read

    async def flaky(self, read_io):
        if read_io.path == "0/m/w":
            raise RuntimeError("injected transient storage error")
        return await orig(self, read_io)

    monkeypatch.setattr(FSStoragePlugin, "read", flaky)
    report = verify_snapshot(p)
    assert not report.ok
    [prob] = [pr for pr in report.problems if pr.location == "0/m/w"]
    assert prob.kind == "unreadable"


def test_memory_store_truncation_detected_shallow():
    """Plugins that slice past EOF silently (the in-memory store) must
    still surface truncation via the read-length check."""
    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    url = "memory://fsck-trunc"
    ts.Snapshot.take(
        url, {"m": ts.PyTreeState({"w": np.arange(16, dtype=np.float32)})}
    )

    plugin = MemoryStoragePlugin(name="fsck-trunc")
    blob = plugin._blobs["0/m/w"]
    plugin._blobs["0/m/w"] = blob[: len(blob) // 2]
    report = verify_snapshot(url)
    assert not report.ok
    assert any(pr.kind == "truncated" for pr in report.problems)
