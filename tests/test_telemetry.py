"""Unified checkpoint telemetry: registry semantics, per-snapshot
reports through the JSONL sink, retry/recover counter surfacing, the
snapshot-stats CLI, and the phase-timing compatibility shim.

Acceptance pin (ISSUE 2): a take with the JSONL sink enabled emits a
SnapshotReport carrying per-phase durations, per-plugin byte counts and
a retry counter; ``tools/snapshot_stats.py`` parses that log and renders
a per-step summary; ``last_phase_timings()`` keeps its legacy keys.
"""

import asyncio
import json
import math
import os
import threading

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.scheduler import (
    last_phase_timings,
    reset_phase_timings,
    safe_rate_mb_s,
)
from torchsnapshot_tpu.storage_plugins.retry import (
    CollectiveProgressRetryStrategy,
    RetriesExhausted,
)
from torchsnapshot_tpu.telemetry import names
from torchsnapshot_tpu.telemetry.registry import (
    MetricsRegistry,
    parse_series_key,
    series_key,
)


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Telemetry tests read process-global counters: isolate them."""
    telemetry.reset_metrics()
    yield
    telemetry.reset_metrics()


def _state(n=3, size=512, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n)
    }


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter_inc(names.STORAGE_WRITE_BYTES_TOTAL, 100, plugin="fs")
    reg.counter_inc(names.STORAGE_WRITE_BYTES_TOTAL, 50, plugin="fs")
    reg.counter_inc(names.STORAGE_WRITE_BYTES_TOTAL, 7, plugin="s3")
    reg.gauge_set(names.MIRROR_UPLOAD_LAG_SECONDS, 1.5)
    reg.histogram_observe(names.MEMORY_BUDGET_WAIT_SECONDS, 0.01)
    reg.histogram_observe(names.MEMORY_BUDGET_WAIT_SECONDS, 100.0)
    data = reg.collect()
    assert data["counters"]['storage_write_bytes_total{plugin="fs"}'] == 150
    assert data["counters"]['storage_write_bytes_total{plugin="s3"}'] == 7
    assert data["gauges"][names.MIRROR_UPLOAD_LAG_SECONDS] == 1.5
    hist = data["histograms"][names.MEMORY_BUDGET_WAIT_SECONDS]
    assert hist["count"] == 2
    assert hist["sum"] == pytest.approx(100.01)
    # 0.01 lands at le=0.025; 100 lands only in the +Inf overflow.
    by_le = dict(hist["buckets"])
    assert by_le[0.025] == 1
    assert by_le[float("inf")] == 2


def test_registry_snapshot_delta():
    reg = MetricsRegistry()
    reg.counter_inc(names.MANAGER_SAVES_TOTAL, 2)
    base = reg.counters_snapshot()
    reg.counter_inc(names.MANAGER_SAVES_TOTAL, 3)
    reg.counter_inc(names.MANAGER_RESTORES_TOTAL, 1)
    delta = reg.counters_delta_since(base)
    assert delta == {
        names.MANAGER_SAVES_TOTAL: 3,
        names.MANAGER_RESTORES_TOTAL: 1,
    }


def test_series_key_roundtrip():
    key = series_key("metric_name", {"b": "2", "a": "1"})
    assert key == 'metric_name{a="1",b="2"}'
    assert parse_series_key(key) == ("metric_name", {"a": "1", "b": "2"})
    assert parse_series_key("bare") == ("bare", {})


def test_registry_thread_safety_smoke():
    reg = MetricsRegistry()

    def worker():
        for _ in range(1000):
            reg.counter_inc(names.MANAGER_SAVES_TOTAL)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counters_snapshot()[names.MANAGER_SAVES_TOTAL] == 8000


# ---------------------------------------------------------------------------
# Satellite: throughput guard for near-zero elapsed time
# ---------------------------------------------------------------------------


def test_safe_rate_guards_near_zero_elapsed():
    assert safe_rate_mb_s(10**9, 0.0) == 0.0
    assert safe_rate_mb_s(10**9, 1e-12) == 0.0  # would print ~inf MB/s
    rate = safe_rate_mb_s(1024**2, 1.0)
    assert rate == pytest.approx(1.0)
    assert math.isfinite(safe_rate_mb_s(10**12, 0.002))


# ---------------------------------------------------------------------------
# Satellite: retry strategy surfaces attempt/backoff counts
# ---------------------------------------------------------------------------


class _Flaky(Exception):
    pass


def test_retry_attempts_surface_in_registry():
    strategy = CollectiveProgressRetryStrategy(
        progress_window_seconds=60.0, scope="unit"
    )
    calls = [0]

    async def op():
        calls[0] += 1
        if calls[0] < 3:
            raise _Flaky()
        return "ok"

    async def run():
        return await strategy.run(op, retriable_exceptions=(_Flaky,))

    loop = asyncio.new_event_loop()
    try:
        assert loop.run_until_complete(run()) == "ok"
    finally:
        loop.close()
    # Per-instance totals (no registry arithmetic needed)...
    assert strategy.attempts_total == 2
    assert strategy.backoff_s_total > 0.0
    assert strategy.exhausted_total == 0
    # ...and the registry counters, labeled by scope.
    counters = telemetry.metrics().counters_snapshot()
    assert counters['storage_retry_attempts_total{scope="unit"}'] == 2
    assert counters['storage_retry_backoff_seconds_total{scope="unit"}'] > 0


def test_retry_exhaustion_counted():
    strategy = CollectiveProgressRetryStrategy(
        progress_window_seconds=0.0, scope="unit"
    )

    async def op():
        raise _Flaky()

    async def run():
        await strategy.run(op, retriable_exceptions=(_Flaky,))

    loop = asyncio.new_event_loop()
    try:
        with pytest.raises(RetriesExhausted):
            loop.run_until_complete(run())
    finally:
        loop.close()
    assert strategy.exhausted_total == 1
    counters = telemetry.metrics().counters_snapshot()
    assert counters['storage_retries_exhausted_total{scope="unit"}'] == 1


def test_gcs_recover_attempts_reach_registry(monkeypatch):
    """The in-thread resumable-upload recover loop (gcs.py) used to count
    recover_attempts locally and drop them; they must reach the registry."""
    gcs = pytest.importorskip("torchsnapshot_tpu.storage_plugins.gcs")
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", "http://localhost:1")
    plugin = gcs.GCSStoragePlugin(root="bucket/prefix")
    monkeypatch.setattr(gcs.time, "sleep", lambda s: None)

    class _Resp:
        status_code = 503

    class _FakeUpload:
        def __init__(self, url, chunk_size):
            self.finished = False
            self._failures_left = 2

        def initiate(self, *args, **kwargs):
            pass

        def transmit_next_chunk(self, session):
            if self._failures_left:
                self._failures_left -= 1
                raise plugin._common.InvalidResponse(_Resp(), "brownout")
            self.finished = True

        def recover(self, session):
            pass

    plugin._resumable_upload_cls = _FakeUpload
    try:
        plugin._upload_sync("blob", b"payload")
    finally:
        plugin._executor.shutdown(wait=False)
    counters = telemetry.metrics().counters_snapshot()
    assert counters[names.GCS_RECOVER_ATTEMPTS_TOTAL] == 2


# ---------------------------------------------------------------------------
# Satellite: phase-timing channel semantics across consecutive takes
# ---------------------------------------------------------------------------


def test_phase_timings_shim_and_reports_do_not_leak_across_takes(tmp_path):
    state = {"m": ts.PyTreeState(_state())}
    with knobs.enable_telemetry():
        ts.Snapshot.take(str(tmp_path / "take1"), state)
        timings1 = last_phase_timings()
        assert set(timings1) == {"staging", "writing"}  # legacy keys
        # An out-of-band phase (the tiered mirror's channel) recorded
        # between takes must not leak into take 2's REPORT, even though
        # the last-writer-wins global channel still shows it.
        from torchsnapshot_tpu.scheduler import record_phase_timing

        record_phase_timing("mirroring", 1.23)
        ts.Snapshot.take(str(tmp_path / "take2"), state)
        assert "mirroring" in last_phase_timings()  # global channel: yes
        events = telemetry.load_events(
            str(tmp_path / "take2" / ".telemetry.jsonl")
        )
        assert len(events) == 1
        assert set(events[0]["phases"]) == {"staging", "writing"}  # report: no
        # reset clears the global channel...
        reset_phase_timings()
        assert last_phase_timings() == {}
        # ...and the next take repopulates only its own phases.
        ts.Snapshot.take(str(tmp_path / "take3"), state)
        assert set(last_phase_timings()) == {"staging", "writing"}


# ---------------------------------------------------------------------------
# Acceptance: take with the JSONL sink + snapshot-stats CLI
# ---------------------------------------------------------------------------


def test_take_report_via_jsonl_sink_and_stats_cli(tmp_path, capsys):
    path = str(tmp_path / "step_0000000001")
    with knobs.enable_telemetry():
        ts.Snapshot.take(path, {"m": ts.PyTreeState(_state(size=4096))})
    events_file = os.path.join(path, ".telemetry.jsonl")
    events = telemetry.load_events(events_file)
    assert len(events) == 1
    report = events[0]
    assert report["kind"] == "take"
    # Per-phase durations...
    assert report["phases"]["staging"] >= 0.0
    assert report["phases"]["writing"] >= report["phases"]["staging"]
    # ...per-plugin byte counts...
    assert report["plugins"]["fs"]["write_bytes"] > 0
    assert report["plugins"]["fs"]["write_ops"] >= 3
    # ...and a retry counter (zero-filled on a healthy local take).
    assert report["retries"]["attempts"] == 0
    assert report["bytes_moved"] == 3 * 4096 * 4
    assert report["peak_staged_bytes"] > 0
    # The CLI parses the log and renders a per-step summary.
    from torchsnapshot_tpu.telemetry.stats import main as stats_main

    assert stats_main([events_file]) == 0
    out = capsys.readouterr().out
    assert "step_0000000001" in out
    assert "take" in out
    assert "per-plugin totals" in out and "fs" in out


def test_tools_snapshot_stats_wrapper(tmp_path, capsys):
    """The repo-tools entry point parses the same log (loaded the way
    the tools lane loads every checker)."""
    import importlib.util
    import pathlib

    path = str(tmp_path / "snap")
    with knobs.enable_telemetry():
        ts.Snapshot.take(path, {"m": ts.PyTreeState(_state())})
    tool = (
        pathlib.Path(__file__).parent.parent / "tools" / "snapshot_stats.py"
    )
    spec = importlib.util.spec_from_file_location("snapshot_stats", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([os.path.join(path, ".telemetry.jsonl")]) == 0
    assert "snap" in capsys.readouterr().out


def test_restore_report_emitted(tmp_path):
    path = str(tmp_path / "snap")
    state = _state()
    with knobs.enable_telemetry():
        ts.Snapshot.take(path, {"m": ts.PyTreeState(dict(state))})
        dst = {"m": ts.PyTreeState({k: np.zeros_like(v) for k, v in state.items()})}
        ts.Snapshot(path).restore(dst)
    events = telemetry.load_events(os.path.join(path, ".telemetry.jsonl"))
    kinds = [e["kind"] for e in events]
    assert kinds == ["take", "restore"]
    restore = events[1]
    assert "loading" in restore["phases"]
    assert restore["plugins"]["fs"]["read_bytes"] > 0
    assert restore["bytes_moved"] > 0
    # The restore envelope (serving cold-start soft spot): plugin
    # open, event-loop spin-up, and native-lib load are itemized so a
    # slow restore can be blamed on setup vs. byte movement.
    assert restore["cold_start_s"] >= 0.0
    assert set(restore["cold_start"]) == {
        "event_loop_s",
        "plugin_open_s",
        "native_load_s",
    }
    assert restore["cold_start_s"] == round(
        sum(restore["cold_start"].values()), 6
    )
    # Take reports carry no restore envelope.
    assert events[0].get("cold_start_s") is None


def test_async_take_report_emitted(tmp_path):
    path = str(tmp_path / "snap")
    with knobs.enable_telemetry():
        pending = ts.Snapshot.async_take(
            path, {"m": ts.PyTreeState(_state())}
        )
        pending.wait()
    events = telemetry.load_events(os.path.join(path, ".telemetry.jsonl"))
    assert [e["kind"] for e in events] == ["async_take"]
    assert set(events[0]["phases"]) == {"staging", "writing"}


def test_telemetry_dir_knob_takes_precedence(tmp_path):
    snap = str(tmp_path / "snap")
    tdir = str(tmp_path / "telemetry")
    with knobs.override_telemetry_dir(tdir):
        ts.Snapshot.take(snap, {"m": ts.PyTreeState(_state())})
    assert not os.path.exists(os.path.join(snap, ".telemetry.jsonl"))
    events = telemetry.load_events(os.path.join(tdir, "events.jsonl"))
    assert len(events) == 1 and events[0]["path"] == snap


def test_sink_disabled_writes_nothing(tmp_path):
    snap = str(tmp_path / "snap")
    ts.Snapshot.take(snap, {"m": ts.PyTreeState(_state())})
    assert not os.path.exists(os.path.join(snap, ".telemetry.jsonl"))
    # The registry still recorded the work.
    counters = telemetry.metrics().counters_snapshot()
    assert counters['storage_write_bytes_total{plugin="fs"}'] > 0
    assert counters['snapshot_reports_total{kind="take"}'] == 1


def test_events_path_resolution():
    from torchsnapshot_tpu.telemetry.sink import events_path_for, local_fs_root

    assert local_fs_root("/plain/dir") == "/plain/dir"
    assert local_fs_root("fs:///plain/dir") == "/plain/dir"
    assert local_fs_root("tiered:///fast|gs://bucket/x") == "/fast"
    assert local_fs_root("gs://bucket/x") is None
    assert local_fs_root("memory://name") is None
    # No knobs set -> no sink anywhere.
    assert events_path_for("/plain/dir") is None
    with knobs.enable_telemetry():
        assert events_path_for("/plain/dir") == "/plain/dir/.telemetry.jsonl"
        # Object-store path without a telemetry dir: nowhere to append.
        assert events_path_for("gs://bucket/x") is None
    with knobs.override_telemetry_dir("/tmp/t"):
        assert events_path_for("gs://bucket/x") == "/tmp/t/events.jsonl"


# ---------------------------------------------------------------------------
# Budget wait / peak staged instrumentation
# ---------------------------------------------------------------------------


def test_report_records_budget_wait_under_tight_budget(tmp_path):
    path = str(tmp_path / "snap")
    # Budget fits ~1.25 leaves: later stagers must wait on admission.
    with knobs.enable_telemetry(), knobs.override_per_rank_memory_budget_bytes(
        2600
    ):
        ts.Snapshot.take(
            path, {"m": ts.PyTreeState(_state(n=6, size=512))}
        )
    report = telemetry.load_events(os.path.join(path, ".telemetry.jsonl"))[0]
    assert report["budget_wait_s"] > 0.0
    assert 0 < report["peak_staged_bytes"] <= 2600 + 512 * 4


# ---------------------------------------------------------------------------
# Tiered mirror reports
# ---------------------------------------------------------------------------


def test_mirror_job_emits_report_and_gauges(tmp_path):
    from torchsnapshot_tpu.tiered import reset_mirror, wait_durable

    reset_mirror()
    try:
        fast = str(tmp_path / "fast")
        durable = str(tmp_path / "durable")
        url = f"tiered://{fast}|{durable}"
        with knobs.enable_telemetry():
            ts.Snapshot.take(url, {"m": ts.PyTreeState(_state())})
            wait_durable(url, timeout=60)
        events = telemetry.load_events(os.path.join(fast, ".telemetry.jsonl"))
        kinds = [e["kind"] for e in events]
        assert "take" in kinds and "mirror" in kinds
        take = next(e for e in events if e["kind"] == "take")
        # The take's report captured the durability backlog it created.
        assert take["mirror"] != {}
        mirror = next(e for e in events if e["kind"] == "mirror")
        assert mirror["blobs"] == mirror["mirror"]["blobs_total"]
        assert mirror["bytes_moved"] > 0
        assert mirror["mirror"]["lag_s"] >= 0.0
        assert mirror["error"] is None
        data = telemetry.metrics().collect()
        assert data["counters"][names.MIRROR_JOBS_DONE_TOTAL] == 1
        assert data["counters"][names.MIRROR_BYTES_TOTAL] > 0
        assert data["gauges"][names.MIRROR_SNAPSHOTS_PENDING] == 0
    finally:
        reset_mirror()


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def test_prometheus_textfile_written(tmp_path):
    prom = str(tmp_path / "metrics.prom")
    snap = str(tmp_path / "snap")
    with knobs.override_prometheus_textfile(prom):
        ts.Snapshot.take(snap, {"m": ts.PyTreeState(_state())})
    text = open(prom).read()
    assert 'storage_write_bytes_total{plugin="fs"}' in text
    assert 'snapshot_reports_total{kind="take"} 1' in text
    assert 'snapshot_phase_seconds_bucket{phase="writing",le="+Inf"}' in text
    assert "snapshot_phase_seconds_count" in text
    # Atomic rewrite: no tmp litter.
    assert os.listdir(tmp_path / "snap") is not None
    assert not [f for f in os.listdir(tmp_path) if f.startswith("metrics.prom.tmp")]


# ---------------------------------------------------------------------------
# Cross-rank aggregation (pure function; multi-process paths ride the
# distributed suites)
# ---------------------------------------------------------------------------


def test_aggregate_across_ranks_finds_straggler():
    ranks = [
        {"phases": {"writing": 1.0}, "bytes_moved": 100, "budget_wait_s": 0.0},
        {"phases": {"writing": 9.0}, "bytes_moved": 100, "budget_wait_s": 0.5},
        {"phases": {"writing": 2.0}, "bytes_moved": 300, "budget_wait_s": 0.1},
    ]
    agg = telemetry.aggregate_across_ranks(ranks)
    assert agg["phase_writing_s"] == {
        "min": 1.0,
        "median": 2.0,
        "max": 9.0,
        "straggler": 1,
    }
    assert agg["bytes_moved"]["straggler"] == 2
    assert agg["budget_wait_s"]["max"] == 0.5


# ---------------------------------------------------------------------------
# Satellite: rss profiler joins on exception paths + feeds the registry
# ---------------------------------------------------------------------------


def test_rss_profiler_joins_thread_on_exception_and_sets_gauge():
    from torchsnapshot_tpu.utils.rss_profiler import (
        RSSDeltas,
        measure_rss_deltas,
    )

    deltas = RSSDeltas()
    with pytest.raises(RuntimeError, match="boom"):
        with measure_rss_deltas(deltas, sample_period_seconds=0.01):
            raise RuntimeError("boom")
    # The sampler thread is gone (joined, not leaked)...
    assert not [
        t for t in threading.enumerate() if t.name == "rss-profiler"
    ]
    # ...the exit sample was still appended...
    assert len(deltas.deltas) >= 1
    # ...and the peak fed the registry gauge.
    gauges = telemetry.metrics().collect()["gauges"]
    assert gauges[names.RSS_PEAK_DELTA_BYTES] == deltas.peak_bytes


# ---------------------------------------------------------------------------
# fsck --stats
# ---------------------------------------------------------------------------


def test_fsck_stats_summarizes_snapshot_events(tmp_path, capsys):
    from torchsnapshot_tpu.fsck import main as fsck_main

    path = str(tmp_path / "snap")
    with knobs.enable_telemetry():
        ts.Snapshot.take(path, {"m": ts.PyTreeState(_state())})
    assert fsck_main([path, "--stats"]) == 0
    out = capsys.readouterr().out
    assert "OK (shallow)" in out
    assert "telemetry (1 event(s))" in out
    assert "take" in out
    # Without events, the summary degrades loudly but the audit passes.
    bare = str(tmp_path / "bare")
    ts.Snapshot.take(bare, {"m": ts.PyTreeState(_state())})
    assert fsck_main([bare, "--stats"]) == 0
    assert "no events recorded" in capsys.readouterr().out


def test_manager_gc_removes_snapshot_event_log(tmp_path):
    """The snapshot-adjacent .telemetry.jsonl is not manifest-named;
    retention must still remove it with the step it documents."""
    root = str(tmp_path / "ckpts")
    mgr = ts.CheckpointManager(root, keep_last_n=1)
    state = {"m": ts.PyTreeState(_state())}
    with knobs.enable_telemetry():
        mgr.save(0, state)
        step0 = os.path.join(root, "step_0000000000", ".telemetry.jsonl")
        assert os.path.exists(step0)
        mgr.save(1, state)
    assert not os.path.exists(step0)  # GC'd with the step
    assert os.path.exists(
        os.path.join(root, "step_0000000001", ".telemetry.jsonl")
    )


def test_find_events_for_consults_telemetry_dir(tmp_path):
    """fsck --stats must find events when the dir sink (higher
    precedence) received them instead of the snapshot dir."""
    from torchsnapshot_tpu.telemetry.stats import find_events_for

    snap = str(tmp_path / "snap")
    other = str(tmp_path / "other")
    tdir = str(tmp_path / "tdir")
    with knobs.override_telemetry_dir(tdir):
        ts.Snapshot.take(snap, {"m": ts.PyTreeState(_state())})
        ts.Snapshot.take(other, {"m": ts.PyTreeState(_state())})
        events = find_events_for(snap)
    assert len(events) == 1 and events[0]["path"] == snap


def test_stats_renderer_handles_empty_and_corrupt_lines(tmp_path):
    from torchsnapshot_tpu.telemetry.stats import render_summary

    assert render_summary([]) == "no telemetry events"
    log = tmp_path / "events.jsonl"
    log.write_text(
        json.dumps({"kind": "take", "path": "/x", "phases": {"writing": 1.0}})
        + "\n{torn-line\n"
    )
    events = telemetry.load_events(str(log))
    assert len(events) == 1  # corrupt line skipped, not raised
    assert "/x" in render_summary(events)
