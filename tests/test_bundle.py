"""Incident black-box bundles: bounded capture, rate limiting, the
snapshot-dir-shaped layout, watchdog one-bundle-per-episode, and the
acceptance reproduction — an injected SLO breach on a real manager run
yields exactly one breach event + one bundle, and a relocated copy of
that bundle reproduces the live doctor verdicts with the original root
deleted.
"""

import asyncio
import json
import os
import shutil
import time

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.telemetry import bundle, doctor, ledger, names, slo
from torchsnapshot_tpu.telemetry.watchdog import reset_watchdog


@pytest.fixture(autouse=True)
def _fresh_state():
    telemetry.reset_metrics()
    telemetry.reset_trace()
    reset_watchdog()
    ledger.reset_owned_roots()
    slo.reset_slo_state()
    bundle.reset_bundle_state()
    yield
    reset_watchdog()
    telemetry.reset_metrics()
    telemetry.reset_trace()
    ledger.reset_owned_roots()
    slo.reset_slo_state()
    bundle.reset_bundle_state()


def _state(n=2, size=256, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n)
    }


def _run_manager(root, steps=(0, 1)):
    mgr = ts.CheckpointManager(root, keep_last_n=4)
    for step in steps:
        mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
    return mgr


# ---------------------------------------------------------------------------
# capture mechanics
# ---------------------------------------------------------------------------


def test_capture_assembles_bounded_snapshot_shaped_dir(tmp_path):
    root = str(tmp_path)
    with knobs.enable_ledger(), knobs.enable_telemetry():
        _run_manager(root)
        with knobs.override_bundle_max_bytes(1 << 20):
            path = bundle.capture_bundle(
                root, trigger="manual", reason="unit test", step=1
            )
    assert path is not None and bundle.is_bundle(path)
    assert os.path.dirname(path) == os.path.join(root, ".bundles")
    manifest = bundle.load_manifest(path)
    assert manifest["trigger"] == "manual"
    assert manifest["reason"] == "unit test"
    assert manifest["step"] == 1
    assert manifest["root"] == root
    assert manifest["bytes"] <= manifest["max_bytes"]
    copied = {f["name"] for f in manifest["files"]}
    # The bundle mimics a snapshot dir: the run ledger and the
    # triggering op's reports land under their live basenames.
    assert ".ledger.jsonl" in copied
    assert ".telemetry.jsonl" in copied
    # The knob fingerprint records the operator surface verbatim, and
    # the tunable vector the effective values.
    assert any(k.startswith("TORCHSNAPSHOT_TPU_") for k in manifest["knobs"])
    assert "env" in manifest and manifest["env"]["pid"] == os.getpid()
    assert isinstance(manifest["tunables"], dict)
    assert isinstance(manifest["verdicts"], list)
    # The offline stack reads the bundle like a root: its own ledger
    # resolves first.
    assert ledger.find_ledger_for(path) == os.path.join(
        path, ".ledger.jsonl"
    )
    listed = bundle.list_bundles(root)
    assert [b["path"] for b in listed] == [path]


def test_capture_disabled_and_rate_limited(tmp_path):
    root = str(tmp_path)
    with knobs.enable_ledger():
        assert ledger.open_run(root) is not None
        # conftest pins max bytes to 0: capture is off.
        assert bundle.capture_bundle(root, trigger="manual") is None
        with knobs.override_bundle_max_bytes(1 << 20):
            first = bundle.capture_bundle(root, trigger="manual")
            assert first is not None
            # Default 5-minute rate limit: a breach storm produces one
            # black box.
            assert bundle.capture_bundle(root, trigger="manual") is None
            with knobs.override_bundle_min_interval_seconds(0.0):
                assert bundle.capture_bundle(root, trigger="manual")


def test_tiny_budget_keeps_the_newest_ledger_tail(tmp_path):
    root = str(tmp_path)
    with knobs.enable_ledger():
        assert ledger.open_run(root) is not None
        for i in range(200):
            ledger.post_event(
                root, names.EVENT_STEP_COMMITTED, step=i, bytes_new=1
            )
        with knobs.override_bundle_max_bytes(2048):
            path = bundle.capture_bundle(root, trigger="manual")
    assert path is not None
    manifest = bundle.load_manifest(path)
    entry = next(
        f for f in manifest["files"] if f["name"] == ".ledger.jsonl"
    )
    assert entry["truncated"]
    assert manifest["bytes"] <= 2048
    records = ledger.load_ledger(os.path.join(path, ".ledger.jsonl"))
    # Newest-last truncation: the tail ends at the newest record.
    assert records[-1]["step"] == 199


def test_step_dir_root_lands_at_the_manager_root(tmp_path):
    """The failed-op trigger hands in the op's own step dir; the bundle
    must land at the manager root (the step dir is what retention GC
    deletes)."""
    root = str(tmp_path)
    with knobs.enable_ledger(), knobs.enable_telemetry():
        _run_manager(root, steps=(3,))
        with knobs.override_bundle_max_bytes(1 << 20):
            path = bundle.capture_bundle(
                os.path.join(root, "step_3"), trigger="failed-op"
            )
    assert path is not None
    assert os.path.dirname(path) == os.path.join(root, ".bundles")
    manifest = bundle.load_manifest(path)
    assert manifest["root"] == root
    assert manifest["snapshot_path"] == os.path.join(root, "step_3")


# ---------------------------------------------------------------------------
# watchdog stall episodes
# ---------------------------------------------------------------------------


def test_watchdog_stall_captures_exactly_one_bundle(
    tmp_path, monkeypatch, caplog
):
    """A stall episode produces exactly one bundle, and both the log
    line and the ``watchdog:stall`` instant name it."""
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    orig_write = FSStoragePlugin.write
    injected = []

    async def slow_write(self, write_io):
        if not injected:
            injected.append(write_io.path)
            await asyncio.sleep(0.7)
        await orig_write(self, write_io)

    monkeypatch.setattr(FSStoragePlugin, "write", slow_write)
    root = str(tmp_path)
    snap = os.path.join(root, "snap")
    with knobs.enable_ledger(), knobs.override_bundle_max_bytes(
        1 << 20
    ), knobs.override_bundle_min_interval_seconds(0.0):
        assert ledger.open_run(root) is not None
        with knobs.override_watchdog_deadline_seconds(
            0.15
        ), knobs.enable_trace():
            with caplog.at_level("ERROR"):
                ts.Snapshot.take(
                    snap, {"s": ts.PyTreeState(_state(n=1, size=64))}
                )
        time.sleep(0.3)  # grace: further scans must not re-capture
    bundles = bundle.list_bundles(root)
    assert len(bundles) == 1
    assert bundles[0]["trigger"] == "watchdog-stall"
    assert "fs" in str(bundle.load_manifest(bundles[0]["path"])["reason"]) or (
        "span" in str(bundle.load_manifest(bundles[0]["path"])["reason"])
    )
    stall_logs = [
        r.message for r in caplog.records if "incident bundle" in r.message
    ]
    assert any(bundles[0]["path"] in m for m in stall_logs)
    with open(os.path.join(snap, ".trace-take-rank0.json")) as f:
        doc = json.load(f)
    stalls = [
        e
        for e in doc["traceEvents"]
        if e["ph"] == "i" and e["name"] == names.INSTANT_WATCHDOG_STALL
    ]
    assert len(stalls) == 1
    assert stalls[0]["args"]["bundle"] == bundles[0]["path"]


# ---------------------------------------------------------------------------
# acceptance: injected breach end-to-end + offline reproduction
# ---------------------------------------------------------------------------


def _breach_overrides():
    """The injection geometry: an impossible visible budget makes every
    take a bad sample; the overhead objective is disabled so exactly
    ONE objective breaches (real sleeps would make the overhead
    fraction nondeterministic)."""
    return (
        knobs.override_async_visible_budget_seconds(0.0001),
        knobs.override_slo_overhead_fraction(0),
    )


def test_injected_breach_posts_one_event_and_one_bundle(tmp_path):
    root = str(tmp_path)
    o1, o2 = _breach_overrides()
    with knobs.enable_ledger(), knobs.enable_telemetry(), knobs.enable_slo(), (
        knobs.override_bundle_max_bytes(1 << 20)
    ), knobs.override_bundle_min_interval_seconds(0.0), o1, o2:
        _run_manager(root, steps=(0, 1, 2))
    records = ledger.load_ledger(ledger.ledger_path_for(root))
    breaches = [
        r for r in records if r.get("event") == names.EVENT_SLO_BREACH
    ]
    # Edge-triggered: three breaching evaluations, ONE event.
    assert len(breaches) == 1
    assert breaches[0]["objective"] == names.SLO_TAKE_VISIBLE_STALL
    bundles = bundle.list_bundles(root)
    # One fresh-breach evaluation, ONE bundle (later evaluations saw a
    # level, not an edge).
    assert len(bundles) == 1
    assert bundles[0]["trigger"] == "slo-breach"
    manifest = bundle.load_manifest(bundles[0]["path"])
    assert names.SLO_TAKE_VISIBLE_STALL in manifest["reason"]
    # The bundle's own ledger tail contains the breach that triggered
    # it — the black box records its own cause.
    bundled = ledger.load_ledger(
        os.path.join(bundles[0]["path"], ".ledger.jsonl")
    )
    assert any(
        r.get("event") == names.EVENT_SLO_BREACH for r in bundled
    )


def test_relocated_bundle_reproduces_doctor_verdicts_offline(
    tmp_path, capsys
):
    """THE acceptance pin: capture on a real run, move the bundle away,
    delete the root, and ``doctor --bundle`` over the copy emits the
    same verdict ids the live capture-time diagnosis recorded."""
    root = str(tmp_path / "run")
    os.makedirs(root)
    o1, o2 = _breach_overrides()
    with knobs.enable_ledger(), knobs.enable_telemetry(), knobs.enable_slo(), (
        knobs.override_bundle_max_bytes(1 << 20)
    ), knobs.override_bundle_min_interval_seconds(0.0), o1, o2:
        _run_manager(root, steps=(0, 1))
        bundles = bundle.list_bundles(root)
        assert len(bundles) == 1
        live_ids = sorted(
            {
                v["rule"]
                for v in bundle.load_manifest(bundles[0]["path"])["verdicts"]
            }
        )
        assert names.RULE_SLO_BURNING in live_ids

        # Relocate the black box; destroy the run it came from.
        relocated = str(tmp_path / "evidence" / "incident")
        shutil.copytree(bundles[0]["path"], relocated)
        shutil.rmtree(root)

        # The SLO judgment reproduces offline (exit 2 = burning).
        assert slo.main([relocated]) == 2
        capsys.readouterr()

        # doctor --bundle over the copy: same verdict ids as live. The
        # judgment re-applies the recorded knob geometry — the
        # manifest's ``knobs`` map is exactly what an operator replays.
        rc = doctor.main(["--bundle", relocated, "--json"])
        assert rc == 2
        offline_ids = sorted(
            {v["rule"] for v in json.loads(capsys.readouterr().out)}
        )
        assert offline_ids == live_ids

    # Not-a-bundle paths are rejected with a pointer, not a traceback.
    assert doctor.main(["--bundle", str(tmp_path)]) == 1
    assert "not an incident bundle" in capsys.readouterr().out
