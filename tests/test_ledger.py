"""Run ledger: crash-safe appends, run-id resume, bounds, GC pruning,
and rank-0-only append consistency across processes.

Acceptance pins (ISSUE 9): a kill mid-append leaves a parseable ledger;
a restarted manager resumes the run id; a 2-process manager run writes
exactly one record stream (rank 0's)."""

import json
import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.telemetry import ledger, names


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset_metrics()
    yield
    telemetry.reset_metrics()


def _state(n=2, size=1024, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n)
    }


# ---------------------------------------------------------------------------
# Core mechanics
# ---------------------------------------------------------------------------


def test_disabled_ledger_writes_nothing(tmp_path):
    root = str(tmp_path / "off")
    mgr = ts.CheckpointManager(root)
    mgr.save(0, {"s": ts.PyTreeState(_state())})
    # The conftest pins TORCHSNAPSHOT_TPU_LEDGER=0: no file appears and
    # the read side returns None.
    assert not os.path.exists(os.path.join(root, ledger.LEDGER_BASENAME))
    assert ledger.find_ledger_for(root) is None


def test_post_event_without_open_run_creates_no_orphan(tmp_path):
    """Events only land where a manager opened a run — a bare post to a
    random directory must not scatter ledger files."""
    with knobs.enable_ledger():
        assert (
            ledger.post_event(str(tmp_path), names.EVENT_STEP_COMMITTED)
            is None
        )
        assert list(tmp_path.iterdir()) == []


def test_torn_final_line_is_skipped_and_run_id_resumes(tmp_path):
    """Kill mid-append: the ledger stays parseable (the torn tail is
    skipped) and a restarted manager resumes the same run id with an
    incremented segment."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        rid = ledger.open_run(root, world_size=1)
        assert rid is not None
        ledger.post_event(
            root, names.EVENT_STEP_COMMITTED, step=0, bytes_new=10,
            bytes_reused=0, bytes_total=10, blobs=1,
        )
        path = ledger.ledger_path_for(root)
        # Simulate the kill: a torn, non-JSON final line.
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"event": "step-com')
        records = ledger.load_ledger(path)
        assert [r["event"] for r in records] == [
            names.EVENT_RUN_START,
            names.EVENT_STEP_COMMITTED,
        ]
        rid2 = ledger.open_run(root, world_size=1)
        assert rid2 == rid
        starts = [
            r
            for r in ledger.load_ledger(path)
            if r["event"] == names.EVENT_RUN_START
        ]
        assert [s["segment"] for s in starts] == [1, 2]
        assert {s["run_id"] for s in starts} == {rid}


def test_manager_restart_resumes_run_id(tmp_path):
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        mgr = ts.CheckpointManager(root)
        mgr.save(0, {"s": ts.PyTreeState(_state())})
        first = mgr._ledger_run_id
        assert first is not None
        mgr2 = ts.CheckpointManager(root)
        assert mgr2._ledger_run_id == first
        records = ledger.load_ledger(ledger.ledger_path_for(root))
        segments = [
            r["segment"]
            for r in records
            if r["event"] == names.EVENT_RUN_START
        ]
        assert segments == [1, 2]


def test_bound_trims_oldest_but_keeps_newest_run_start(tmp_path):
    """The rolling bound trims oldest-first, but the newest run-start
    survives any trim — the active segment's attribution anchor."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger(), knobs.override_ledger_max_records(10):
        ledger.open_run(root)
        path = ledger.ledger_path_for(root)
        # Enough posts to cross several trim checks.
        for i in range(ledger.TRIM_CHECK_EVERY * 2 + 5):
            ledger.post_event(
                root, names.EVENT_VISIBLE_STALL, step=i, visible_s=0.01,
                wall_s=0.01, nbytes=1,
            )
        records = ledger.load_ledger(path)
        assert len(records) <= 10 + ledger.TRIM_CHECK_EVERY
        assert any(
            r["event"] == names.EVENT_RUN_START for r in records
        )
        # Newest events survived.
        steps = [
            r["step"]
            for r in records
            if r["event"] == names.EVENT_VISIBLE_STALL
        ]
        assert steps == sorted(steps)
        assert steps[-1] == ledger.TRIM_CHECK_EVERY * 2 + 4


def test_gc_prunes_step_committed_records(tmp_path):
    """Retention GC drops deleted steps' step-committed storage records
    and posts gc-reclaimed with the bytes freed; time-attribution
    events survive."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        mgr = ts.CheckpointManager(root, keep_last_n=2)
        for step in range(4):
            mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
        records = ledger.load_ledger(ledger.ledger_path_for(root))
        committed = [
            r["step"]
            for r in records
            if r["event"] == names.EVENT_STEP_COMMITTED
        ]
        assert committed == [2, 3]  # steps 0-1 GC'd and pruned
        reclaimed = [
            r
            for r in records
            if r["event"] == names.EVENT_GC_RECLAIMED
        ]
        assert [r["step"] for r in reclaimed] == [0, 1]
        assert all(r["bytes_reclaimed"] > 0 for r in reclaimed)
        # The GC'd steps' visible stalls still count toward overhead.
        stalls = [
            r["step"]
            for r in records
            if r["event"] == names.EVENT_VISIBLE_STALL
        ]
        assert stalls == [0, 1, 2, 3]


def test_incremental_saves_record_reuse_bytes(tmp_path):
    """Incremental steps' step-committed records split new vs.
    base-referenced bytes — the reuse ratio the storage curve reports."""
    root = str(tmp_path / "ckpts")
    state = _state(n=4, size=4096)
    with knobs.enable_ledger():
        mgr = ts.CheckpointManager(root, incremental=True)
        mgr.save(0, {"s": ts.PyTreeState(state)})
        mgr.save(1, {"s": ts.PyTreeState(state)})  # unchanged: all reuse
        records = ledger.load_ledger(ledger.ledger_path_for(root))
        by_step = {
            r["step"]: r
            for r in records
            if r["event"] == names.EVENT_STEP_COMMITTED
        }
        assert by_step[0]["bytes_reused"] == 0
        assert by_step[0]["bytes_new"] > 0
        assert by_step[1]["bytes_reused"] > 0
        from torchsnapshot_tpu.telemetry import goodput

        storage = goodput.analyze(records)["storage"]
        assert storage["incremental_reuse_ratio"] > 0.3


def test_tiered_save_posts_mirror_settled(tmp_path):
    """A tiered take's background mirror posts its settle event (lag +
    bytes) to the manager root's ledger."""
    fast = tmp_path / "fast"
    durable = tmp_path / "durable"
    root = f"tiered://{fast}|{durable}"
    with knobs.enable_ledger():
        mgr = ts.CheckpointManager(root)
        mgr.save(0, {"s": ts.PyTreeState(_state())})
        mgr.wait_durable(0, timeout=60.0)
        records = ledger.load_ledger(ledger.ledger_path_for(root))
        settled = [
            r for r in records if r["event"] == names.EVENT_MIRROR_SETTLED
        ]
        assert settled and settled[0]["step"] == 0
        assert settled[0]["nbytes"] > 0
        assert settled[0]["error"] is None


def test_preemption_saver_posts_agreement(tmp_path):
    """A single-process preemption notice records the step and target —
    the lost-work anchor."""
    from torchsnapshot_tpu.preemption import PreemptionSaver

    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        mgr = ts.CheckpointManager(root)
        mgr.save(0, {"s": ts.PyTreeState(_state())})
        saver = PreemptionSaver(signals=(), ledger_root=root)
        try:
            saver.request_save()
            assert saver.should_save(3)
        finally:
            saver.uninstall()
        records = ledger.load_ledger(ledger.ledger_path_for(root))
        preempts = [
            r for r in records if r["event"] == names.EVENT_PREEMPTION
        ]
        assert len(preempts) == 1
        assert preempts[0]["step"] == 3
        assert preempts[0]["target_step"] == 3


# ---------------------------------------------------------------------------
# 2-process rank-0-only consistency
# ---------------------------------------------------------------------------


def _two_rank_ledger_worker(pg, root: str):
    os.environ["TORCHSNAPSHOT_TPU_LEDGER"] = "1"
    from torchsnapshot_tpu.pg_wrapper import PGWrapper

    PGWrapper(pg).barrier()
    mgr = ts.CheckpointManager(root, pg=pg)
    for step in range(2):
        mgr.save(
            step,
            {
                "s": ts.PyTreeState(_state(seed=step)),
                "r": ts.StateDict(rank=pg.rank),
            },
        )
    PGWrapper(pg).barrier()
    path = os.path.join(root, ledger.LEDGER_BASENAME)
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def test_two_proc_rank0_only_appends(tmp_path):
    """Both ranks save through the manager; only rank 0's process ever
    appends — one run-start, one step-committed per step, one
    visible-stall per take, every line parseable, and both ranks read
    the identical stream."""
    from torchsnapshot_tpu.test_utils import run_multiprocess

    root = str(tmp_path / "ckpts")
    contents = run_multiprocess(
        _two_rank_ledger_worker, nproc=2, args=(root,)
    )
    assert contents[0] == contents[1]
    records = [
        json.loads(line)
        for line in contents[0].splitlines()
        if line.strip()
    ]
    events = [r["event"] for r in records]
    assert events.count(names.EVENT_RUN_START) == 1
    assert events.count(names.EVENT_STEP_COMMITTED) == 2
    assert events.count(names.EVENT_VISIBLE_STALL) == 2
    start = next(
        r for r in records if r["event"] == names.EVENT_RUN_START
    )
    assert start["world_size"] == 2
