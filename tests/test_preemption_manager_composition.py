"""Composition of the flagship subsystems in one flow: periodic
incremental saves through CheckpointManager → retention GC with
ref-pinning → a PreemptionSaver eviction save driven THROUGH the same
manager (chaining off the last periodic incremental step) → deep fsck of
every retained snapshot after GC → restart → resume.

Each feature is individually tested elsewhere (test_manager,
test_preemption, test_incremental, test_fsck); this test asserts their
*composition*: the eviction save participates in ref-aware GC, its
incremental chain stays intact across deletions, and a restarted manager
resumes from it. Structural model: the reference's layered test pyramid
(SURVEY.md §4) — e2e over the exact subsystem seams."""

import os
import shutil
import tempfile

import numpy as np

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.fsck import verify_snapshot
from torchsnapshot_tpu.manager import referenced_steps
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import drive_preemption_loop, multiprocess_test


def _state(rank: int, step: int) -> dict:
    # "frozen" never changes: every incremental save references step 0's
    # blob (chained refs collapse to the origin step at take time), so
    # GC must pin step 0's directory long after the index dropped it.
    # "hot" changes every step: every save writes a fresh blob.
    return {
        "train": ts.PyTreeState(
            {
                "frozen": np.arange(4096, dtype=np.float32) + rank,
                "hot": np.full(2048, float(step * 10 + rank), np.float32),
            }
        ),
        "progress": ts.StateDict(step=step),
    }


@multiprocess_test(nproc=2)
def test_preemption_save_through_incremental_manager_with_gc(pg) -> None:
    root = os.path.join(tempfile.gettempdir(), "preempt-mgr-comp-test")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    wrapper = PGWrapper(pg)
    wrapper.barrier()

    mgr = ts.CheckpointManager(root, keep_last_n=2, pg=pg, incremental=True)
    # Periodic training saves: 0 is the digest-recorded base, 1 and 2
    # chain off their predecessors.
    for step in (0, 1, 2):
        mgr.save(step, _state(pg.rank, step))
    wrapper.barrier()  # rank 0's index write + GC are durable
    # keep_last_n=2 dropped step 0 from the index, but steps 1/2 still
    # reference its unchanged "frozen" blob — pinned, not deleted.
    assert mgr.all_steps() == [1, 2]
    assert os.path.isdir(mgr.step_path(0)), "referenced base was deleted"

    # Eviction mid-training: both ranks agree on one step and save it
    # through the SAME manager — the save must chain incrementally off
    # the last periodic step like any other save.
    saver = ts.PreemptionSaver(
        pg,
        signals=(),
        poll_interval=0.02,
        rendezvous_timeout=30.0,
        session="mgr-comp",
    )
    saved_at = drive_preemption_loop(
        pg,
        saver,
        save_fn=lambda step: mgr.save(step, _state(pg.rank, step)),
        evict_rank=1,
        evict_step=5,
        steps=200,
    )
    assert saved_at is not None, "eviction save never triggered"
    agreed = wrapper.all_gather_object(saved_at)
    assert agreed[0] == agreed[1] == saved_at, agreed
    wrapper.barrier()  # rank 0's eviction-save commit + GC done

    # The eviction save participated in retention exactly like a periodic
    # save: index now [2, saved_at]; step 1 (unreferenced) was GC'd —
    # commit marker first, then every blob (empty dirs remain by design:
    # plugins cannot list) — while step 0, still referenced by both
    # retained manifests, stays pinned with its blobs intact.
    assert mgr.all_steps() == [2, saved_at]
    step1 = mgr.step_path(1)
    assert not os.path.exists(
        os.path.join(step1, ".snapshot_metadata")
    ), "dead step survived GC with a commit marker"
    leftover = [
        os.path.join(d, f)
        for d, _, fs in os.walk(step1)
        for f in fs
    ]
    assert not leftover, f"dead step's blobs survived GC: {leftover}"
    assert os.path.exists(
        os.path.join(mgr.step_path(0), ".snapshot_metadata")
    ), "pinned base was deleted"

    # The eviction snapshot is a real increment, not a full rewrite: its
    # manifest references the origin step of the unchanged leaf.
    snap = ts.Snapshot(mgr.step_path(saved_at), pg=pg)
    refs = referenced_steps(snap.metadata.manifest)
    assert 0 in refs, f"eviction save did not chain (refs: {sorted(refs)})"

    # Deep fsck (full CRC audit, chain-aware) on every retained step:
    # the incremental chains — including refs into the GC'd-but-pinned
    # step 0 — are fully intact after the deletions.
    for step in mgr.all_steps():
        report = verify_snapshot(mgr.step_path(step), deep=True)
        assert report.ok, (step, report.problems)
    wrapper.barrier()

    # Restart: a fresh manager (fresh process group state is the next
    # process's job; here a fresh instance) resumes from the eviction
    # step with the exact pre-eviction values.
    mgr2 = ts.CheckpointManager(root, pg=pg, incremental=True)
    dest = {
        "train": ts.PyTreeState(
            {
                "frozen": np.zeros(4096, np.float32),
                "hot": np.zeros(2048, np.float32),
            }
        ),
        "progress": ts.StateDict(step=-1),
    }
    resumed = mgr2.restore_latest(dest)
    assert resumed == saved_at
    assert dest["progress"]["step"] == saved_at
    np.testing.assert_array_equal(
        dest["train"].tree["frozen"],
        np.arange(4096, dtype=np.float32) + pg.rank,
    )
    np.testing.assert_array_equal(
        dest["train"].tree["hot"],
        np.full(2048, float(saved_at * 10 + pg.rank), np.float32),
    )
