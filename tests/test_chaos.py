"""Chaos engine + self-healing store (docs/chaos.md): fault-plan
replay determinism, the injection modes over real plugins and the wire,
the restore-time corruption ladder, and ``fsck --repair``'s
rewrite/quarantine semantics — the satellite-3 repair matrix included
(corrupt one CAS chunk per tier: fallthrough, repair, quarantine)."""

import glob
import json
import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.chaos import (
    ChaosEngine,
    FaultPlan,
    FaultSpec,
    SimulatedCrash,
    arm,
    chaotic_plugin_type,
    corrupt_bytes,
    crashpoint,
    declared_crashpoints,
    disarm,
    install_wire_chaos,
    uninstall_wire_chaos,
    wrap_plugin,
)
from torchsnapshot_tpu.integrity import ChecksumError
from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.telemetry import names


def _flip_middle_byte(path: str) -> None:
    """Size-preserving on-disk corruption: only a digest catches it."""
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 1]))


# ---------------------------------------------------------------------------
# fault plans + engine
# ---------------------------------------------------------------------------


def test_fault_plan_json_round_trip():
    plan = FaultPlan(
        seed=42,
        faults=[
            FaultSpec(point="storage-write", mode="torn", match="m/", after=2),
            FaultSpec(
                point="crashpoint",
                mode="crash",
                match="commit-marker",
                times=None,
                prob=0.25,
            ),
        ],
    )
    line = plan.to_json()
    assert "\n" not in line  # ONE line: the replay copy-paste contract
    again = FaultPlan.from_json(line)
    assert again.to_json() == line
    assert again.seed == 42
    assert [f.mode for f in again.faults] == ["torn", "crash"]


def test_unknown_mode_rejected():
    with pytest.raises(ValueError, match="unknown fault mode"):
        FaultSpec(point="storage-write", mode="explode")


def test_same_seed_same_schedule():
    """The acceptance pin: identical seed + fault plan over the same
    event stream reproduces the identical fault schedule; a different
    seed diverges (probabilistic specs)."""
    plan_line = FaultPlan(
        seed=7,
        faults=[
            FaultSpec(
                point="storage-write", mode="fail", prob=0.3, times=None
            )
        ],
    ).to_json()
    events = [("storage-write", f"blob-{i}") for i in range(200)]

    def schedule(line: str):
        engine = ChaosEngine(FaultPlan.from_json(line))
        for point, key in events:
            engine.on_event(point, key)
        return list(engine.fired)

    first = schedule(plan_line)
    assert first and first == schedule(plan_line)
    other = FaultPlan.from_json(plan_line)
    other.seed = 8
    assert schedule(other.to_json()) != first


def test_after_and_times_windows():
    engine = ChaosEngine(
        FaultPlan.single(point="storage-read", after=2, times=2)
    )
    outcomes = [
        engine.on_event("storage-read", "b") is not None for _ in range(6)
    ]
    assert outcomes == [False, False, True, True, False, False]


# ---------------------------------------------------------------------------
# storage injection modes
# ---------------------------------------------------------------------------


def _mem_plugin(plan: FaultPlan):
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    inner = MemoryStoragePlugin(name=f"chaos-{id(plan)}")
    return inner, wrap_plugin(inner, ChaosEngine(plan))


def _run(coro):
    from torchsnapshot_tpu.event_loop import run_in_fresh_event_loop

    return run_in_fresh_event_loop(coro)


def test_mode_fail_and_delay_and_drop():
    inner, plugin = _mem_plugin(
        FaultPlan(
            seed=0,
            faults=[
                FaultSpec(point="storage-write", mode="fail", match="dead"),
                FaultSpec(point="storage-write", mode="drop", match="lost"),
                FaultSpec(
                    point="storage-write",
                    mode="delay",
                    match="slow",
                    delay_s=0.01,
                ),
            ],
        )
    )

    async def body():
        with pytest.raises(OSError, match="chaos: injected fault"):
            await plugin.write(WriteIO(path="dead", buf=b"x"))
        await plugin.write(WriteIO(path="lost", buf=b"x"))  # reported ok
        await plugin.write(WriteIO(path="slow", buf=b"abc"))
        await plugin.write(WriteIO(path="fine", buf=b"def"))
        read = ReadIO(path="slow")
        await plugin.read(read)
        assert bytes(read.buf) == b"abc"
        with pytest.raises(FileNotFoundError):
            await plugin.read(ReadIO(path="lost"))  # the write was dropped

    _run(body())


def test_mode_corrupt_and_torn():
    inner, plugin = _mem_plugin(
        FaultPlan(
            seed=0,
            faults=[
                FaultSpec(
                    point="storage-write", mode="corrupt", match="bitrot"
                ),
                FaultSpec(point="storage-write", mode="torn", match="torn"),
                FaultSpec(point="storage-read", mode="corrupt", match="readrot"),
            ],
        )
    )

    async def body():
        payload = bytes(range(64))
        await plugin.write(WriteIO(path="bitrot", buf=payload))
        read = ReadIO(path="bitrot")
        await inner.read(read)
        stored = bytes(read.buf)
        assert len(stored) == len(payload) and stored != payload

        with pytest.raises(OSError, match="torn write"):
            await plugin.write(WriteIO(path="torn", buf=payload))
        read = ReadIO(path="torn")
        await inner.read(read)
        assert bytes(read.buf) == payload[: len(payload) // 2]

        await inner.write(WriteIO(path="readrot", buf=payload))
        read = ReadIO(path="readrot")
        await plugin.read(read)
        assert bytes(read.buf) != payload
        assert len(bytes(read.buf)) == len(payload)

    _run(body())


def test_corrupt_bytes_is_size_preserving():
    data = bytes(range(32))
    damaged = corrupt_bytes(data)
    assert len(damaged) == len(data) and damaged != data
    assert corrupt_bytes(b"") == b""


def test_faulty_fs_plugin_corrupt_mode_never_served_silently(tmp_path):
    """The shim's new corrupt-bytes mode on a single-tier root: the
    restore has no alternate source, so the damage surfaces as a
    ChecksumError — never silently-wrong arrays."""
    from torchsnapshot_tpu.test_utils import (
        faulty_fs_plugin,
        patch_storage_plugin,
    )

    state = {"m": ts.PyTreeState({"w": np.arange(5000, dtype=np.float32)})}
    path = str(tmp_path / "s")
    ts.Snapshot.take(path, state)
    cls = faulty_fs_plugin(
        lambda p: "/m/" in p, ops=("read",), mode="corrupt"
    )
    dst = {"m": ts.PyTreeState({"w": np.zeros(5000, dtype=np.float32)})}
    with patch_storage_plugin(cls), pytest.raises(ChecksumError):
        ts.Snapshot(path).restore(dst)
    assert cls.chaos_engine.fired  # the corruption actually ran


# ---------------------------------------------------------------------------
# wire chaos (send_frame/recv_frame: TCP store + peer transport)
# ---------------------------------------------------------------------------


def test_wire_chaos_fails_store_traffic_then_uninstalls():
    from torchsnapshot_tpu.dist_store import TCPStore
    from torchsnapshot_tpu.test_utils import get_free_port

    port = get_free_port()
    store = TCPStore("127.0.0.1", port, is_server=True)
    try:
        store.set("before", b"1")  # healthy baseline
        engine = ChaosEngine(
            FaultPlan.single(point="wire-send", mode="fail")
        )
        install_wire_chaos(engine)
        try:
            with pytest.raises((ConnectionError, OSError)):
                store.set("during", b"2")
            assert engine.fired and engine.fired[0][0] == "wire-send"
        finally:
            uninstall_wire_chaos()
        store.set("after", b"3")
        assert store.try_get("after") == b"3"
    finally:
        store.close()


# ---------------------------------------------------------------------------
# crash points
# ---------------------------------------------------------------------------


def test_crashpoint_arm_disarm_and_hits():
    point = names.CRASH_COMMIT_MARKER
    crashpoint(point)  # unarmed: no-op
    arm(point, at=2)
    try:
        crashpoint(point)  # first hit: survives
        with pytest.raises(SimulatedCrash):
            crashpoint(point)
        from torchsnapshot_tpu.chaos import hits

        assert hits(point) == 2
    finally:
        disarm()
    crashpoint(point)  # disarmed again


def test_declared_crashpoints_enumerates_names_registry():
    declared = declared_crashpoints()
    assert names.CRASH_COMMIT_MARKER in declared
    assert names.CRASH_CAS_CHUNK_WRITTEN in declared
    assert names.CRASH_INDEX_BACKUP_WRITTEN in declared
    assert len(declared) >= 13
    assert declared == sorted(declared)
    assert issubclass(SimulatedCrash, BaseException)
    assert not issubclass(SimulatedCrash, Exception)


# ---------------------------------------------------------------------------
# self-healing reads (the corruption ladder)
# ---------------------------------------------------------------------------


def _tiered_root(tmp_path):
    fast = str(tmp_path / "fast")
    durable = str(tmp_path / "durable")
    return f"tiered://{fast}|{durable}", fast, durable


def test_tiered_restore_heals_around_corrupt_fast_copy(tmp_path):
    """Corruption on the tier restores read FIRST falls through to the
    other tier: restore succeeds bit-identical, tier_split carries the
    rerouted bytes, and the storage-corruption doctor rule fires on the
    report."""
    from torchsnapshot_tpu.telemetry.doctor import diagnose_reports

    root, fast, durable = _tiered_root(tmp_path)
    want = np.arange(80_000, dtype=np.float32)
    mgr = ts.CheckpointManager(root, keep_last_n=2)
    mgr.save(0, {"m": ts.PyTreeState({"w": want.copy()})})
    mgr.wait_durable(0)
    blob = os.path.join(fast, "step_0000000000", "0", "m", "w")
    _flip_middle_byte(blob)

    dest = {"m": ts.PyTreeState({"w": np.zeros_like(want)})}
    assert mgr.restore_latest(dest) == 0
    np.testing.assert_array_equal(dest["m"].tree["w"], want)

    report = telemetry.last_report("restore", path=mgr.step_path(0))
    assert report.degraded_reads == {"blobs": 1, "bytes": want.nbytes}
    assert report.tier_split == {"durable": want.nbytes}
    rules = [v.rule for v in diagnose_reports([report.to_dict()])]
    assert names.RULE_STORAGE_CORRUPTION in rules


def test_peer_ladder_healing_does_not_double_count_tiers(tmp_path):
    """The peer ladder's read_degraded must take back the rejected
    serve's bytes: tier_split sums to the bytes actually restored, not
    restored + every corrupt attempt."""
    from torchsnapshot_tpu.event_loop import run_in_fresh_event_loop
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
    from torchsnapshot_tpu.tiered.peer import PeerRestoreContext
    from torchsnapshot_tpu.tiered.plugin import TieredStoragePlugin

    fast, durable = str(tmp_path / "f"), str(tmp_path / "d")
    payload = bytes(range(256)) * 4
    for tier in (fast, durable):
        os.makedirs(tier)
        with open(os.path.join(tier, "blob"), "wb") as f:
            f.write(payload)
    _flip_middle_byte(os.path.join(fast, "blob"))
    tiered = TieredStoragePlugin(
        fast=FSStoragePlugin(root=fast), durable=FSStoragePlugin(root=durable)
    )
    ctx = PeerRestoreContext(table={}, step_key="s", timeout=0.5)
    ladder = ctx.wrap(tiered)

    async def body():
        read = ReadIO(path="blob")
        await ladder.read(read)  # fast serves (corrupt; counted)
        assert read.served_by == "fast"
        assert await ladder.read_degraded(read)  # the scheduler's retry
        assert read.served_by == "durable"
        assert bytes(read.buf) == payload

    run_in_fresh_event_loop(body())
    split = ctx.pipeline_fields()["tier_split"]
    assert sum(split.values()) == len(payload), split
    assert split["durable"] == len(payload) and split["fast"] == 0


def test_single_tier_corruption_still_raises(tmp_path):
    """No alternate source, no silent serve: the plain-fs ladder is
    empty and the original ChecksumError stands."""
    path = str(tmp_path / "s")
    want = np.arange(50_000, dtype=np.float32)
    ts.Snapshot.take(path, {"m": ts.PyTreeState({"w": want.copy()})})
    _flip_middle_byte(os.path.join(path, "0", "m", "w"))
    dest = {"m": ts.PyTreeState({"w": np.zeros_like(want)})}
    with pytest.raises(ChecksumError):
        ts.Snapshot(path).restore(dest)


# ---------------------------------------------------------------------------
# CAS chunk repair (satellite 3: one corrupt chunk per tier)
# ---------------------------------------------------------------------------


def _cas_setup(tmp_path):
    root, fast, durable = _tiered_root(tmp_path)
    want = np.arange(60_000, dtype=np.float32)
    mgr = ts.CheckpointManager(root, keep_last_n=2)
    mgr.save(0, {"m": ts.PyTreeState({"w": want.copy()})})
    mgr.wait_durable(0)
    chunks = sorted(glob.glob(os.path.join(durable, "chunks", "cas-*")))
    assert chunks, "CAS layout did not engage"
    key = os.path.basename(chunks[0])
    return root, fast, durable, want, mgr, key


def test_cas_corrupt_fast_chunk_restores_via_fallthrough(tmp_path):
    """(a) restore succeeds via tier fallthrough, tier_split shows the
    rerouted bytes; (b) fsck --repair rewrites the chunk and a plain
    restore afterwards is clean (no degraded reads)."""
    from torchsnapshot_tpu.fsck import repair_cas_store, verify_cas_store

    with knobs.enable_cas():
        root, fast, durable, want, mgr, key = _cas_setup(tmp_path)
        _flip_middle_byte(os.path.join(fast, "chunks", key))

        dest = {"m": ts.PyTreeState({"w": np.zeros_like(want)})}
        assert mgr.restore_latest(dest) == 0
        np.testing.assert_array_equal(dest["m"].tree["w"], want)
        report = telemetry.last_report("restore", path=mgr.step_path(0))
        assert report.degraded_reads["blobs"] == 1
        assert report.tier_split.get("durable", 0) == want.nbytes

        pre = verify_cas_store(root, deep=True)
        assert any(p.kind == "checksum" for p in pre.problems)
        repair = repair_cas_store(root)
        assert any(key in loc for loc in repair.rewritten)
        assert not repair.quarantined
        assert verify_cas_store(root, deep=True).ok

        dest2 = {"m": ts.PyTreeState({"w": np.zeros_like(want)})}
        assert mgr.restore_latest(dest2) == 0
        np.testing.assert_array_equal(dest2["m"].tree["w"], want)
        report2 = telemetry.last_report("restore", path=mgr.step_path(0))
        assert report2.degraded_reads is None  # clean: nothing rerouted


def test_cas_corrupt_durable_chunk_repaired_from_fast(tmp_path):
    """The satellite's literal case: size-preserving damage on the
    DURABLE tier's chunk. A plain restore doesn't even notice (fast
    serves), the per-tier deep audit does, and --repair rebuilds the
    durable copy from the fast one."""
    from torchsnapshot_tpu.fsck import repair_cas_store, verify_cas_store

    with knobs.enable_cas():
        root, fast, durable, want, mgr, key = _cas_setup(tmp_path)
        _flip_middle_byte(os.path.join(durable, "chunks", key))

        pre = verify_cas_store(root, deep=True)
        assert any(
            p.kind == "checksum" and key in p.location for p in pre.problems
        )
        repair = repair_cas_store(root)
        rewritten = {
            loc: src for loc, src in repair.rewritten.items() if key in loc
        }
        assert rewritten and all(
            src.startswith(fast) for src in rewritten.values()
        )
        assert verify_cas_store(root, deep=True).ok
        assert _chunk_bytes(durable, key) == _chunk_bytes(fast, key)


def _chunk_bytes(tier_dir: str, key: str) -> bytes:
    with open(os.path.join(tier_dir, "chunks", key), "rb") as f:
        return f.read()


def test_cas_all_tiers_corrupt_quarantines_never_serves(tmp_path):
    """(c) every tier's copy bad: --repair quarantines
    (chunks/.quarantine/), the audit reports the dangling ref, and a
    restore fails loudly — corrupt bytes are never served."""
    from torchsnapshot_tpu.fsck import (
        QUARANTINE_DIRNAME,
        repair_cas_store,
        verify_cas_store,
    )

    with knobs.enable_cas(), knobs.enable_ledger():
        root, fast, durable, want, mgr, key = _cas_setup(tmp_path)
        _flip_middle_byte(os.path.join(fast, "chunks", key))
        _flip_middle_byte(os.path.join(durable, "chunks", key))

        repair = repair_cas_store(root)
        assert repair.quarantined == [key]
        for tier in (fast, durable):
            assert os.path.exists(
                os.path.join(tier, "chunks", QUARANTINE_DIRNAME, key)
            )
            assert not os.path.exists(os.path.join(tier, "chunks", key))
        post = verify_cas_store(root, deep=True)
        assert any(
            p.kind == "missing" and key in p.location for p in post.problems
        )

        dest = {"m": ts.PyTreeState({"w": np.zeros_like(want)})}
        with pytest.raises(Exception):
            mgr.restore_latest(dest)

        # The repair is a ledger fact the doctor cites (the root opened
        # a run, so the event landed).
        from torchsnapshot_tpu.telemetry.ledger import (
            ledger_path_for,
            load_ledger,
        )

        records = load_ledger(ledger_path_for(root))
        repairs = [
            r
            for r in records
            if r.get("event") == names.EVENT_REPAIR_PERFORMED
        ]
        assert repairs and repairs[-1]["quarantined"] == 1

        from torchsnapshot_tpu.telemetry.doctor import (
            diagnose_snapshot,
        )

        verdicts = diagnose_snapshot(mgr.step_path(0))
        hit = [
            v
            for v in verdicts
            if v.rule == names.RULE_STORAGE_CORRUPTION
        ]
        assert hit and hit[0].severity == "critical"
