"""Checkpoint CDN (docs/cdn.md): topic codec, publisher ordering,
subscriber diff/owner-election/pull tiers, hot swap, the manager's
publish hook, and the CAS lease pins that keep fleet-held chunks out
of the training job's GC."""

import os
import threading
import time
import zlib

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs
from torchsnapshot_tpu.cas import CASStore, digest_key
from torchsnapshot_tpu.cdn import (
    Announce,
    CdnPublisher,
    CdnSubscriber,
    CdnSyncError,
    WeightSwapper,
    announce_key,
    concat_assembler,
    durable_chunk_reader,
    head_key,
    manifest_digest,
    read_announce,
    read_head,
    verify_chunk_bytes,
)
from torchsnapshot_tpu.dist_store import InProcessStore


def _chunk(seed: int, nbytes: int = 512):
    data = (seed.to_bytes(8, "little") * (nbytes // 8 + 1))[:nbytes]
    return digest_key(("crc32", zlib.crc32(data), len(data))), data


def _announce(seq=1, step=10, nchunks=3):
    chunks = {}
    blobs = {}
    for i in range(nchunks):
        key, data = _chunk(i)
        chunks[key] = len(data)
        blobs[key] = data
    return (
        Announce(
            topic="t",
            seq=seq,
            step=step,
            digest=manifest_digest(step, chunks),
            chunks=chunks,
            published_ts=time.time(),
        ),
        blobs,
    )


# ---------------------------------------------------------------------------
# topic codec
# ---------------------------------------------------------------------------


def test_topic_keys_are_store_routable():
    assert head_key("t") == "__cdn/t/head"
    assert announce_key("t", 7) == "__cdn/t/announce/7"


def test_announce_round_trip():
    ann, _ = _announce()
    again = Announce.decode(ann.encode())
    assert again is not None
    assert again.seq == ann.seq and again.step == ann.step
    assert again.chunks == ann.chunks
    assert again.bytes_in_step == sum(ann.chunks.values())


def test_announce_decode_rejects_damage():
    ann, _ = _announce()
    raw = ann.encode()
    assert Announce.decode(b"not json") is None
    assert Announce.decode(b"{}") is None
    # A tampered chunk set no longer matches the embedded digest.
    tampered = raw.replace(b'"step": 10', b'"step": 11')
    assert Announce.decode(tampered) is None


def test_read_head_tolerates_missing_and_garbage():
    store = InProcessStore()
    assert read_head(store, "t") == 0
    store.set(head_key("t"), b"not-a-number")
    assert read_head(store, "t") == 0
    store.set(head_key("t"), b"3")
    assert read_head(store, "t") == 3


def test_verify_chunk_bytes():
    key, data = _chunk(1)
    assert verify_chunk_bytes(key, data)
    assert not verify_chunk_bytes(key, data[:-1])  # size mismatch
    flipped = bytes([data[0] ^ 1]) + data[1:]
    assert not verify_chunk_bytes(key, flipped)  # digest mismatch
    # Non-CAS keys are rejected outright.
    assert not verify_chunk_bytes("not-a-chunk", data)


# ---------------------------------------------------------------------------
# publisher
# ---------------------------------------------------------------------------


def test_publisher_bumps_head_and_announces():
    store = InProcessStore()
    pub = CdnPublisher(store, "t", publisher_id="trainer")
    key, data = _chunk(1)
    ann = pub.publish(100, {key: len(data)})
    assert ann is not None and ann.seq == 1
    assert read_head(store, "t") == 1
    got = read_announce(store, "t", 1)
    assert got is not None
    assert got.step == 100 and got.publisher == "trainer"
    # Seq is monotonic per topic.
    ann2 = pub.publish(200, {key: len(data)})
    assert ann2.seq == 2 and read_head(store, "t") == 2


def test_publisher_resumes_seq_from_store():
    store = InProcessStore()
    key, data = _chunk(1)
    CdnPublisher(store, "t").publish(1, {key: len(data)})
    # A restarted trainer picks up after the published head.
    ann = CdnPublisher(store, "t").publish(2, {key: len(data)})
    assert ann.seq == 2


def test_publisher_reaps_announces_past_retention():
    """The store-key-leak fix: a long-running topic holds a bounded
    number of announce records, not one per publish forever. The head's
    announce (the only one subscribers read) always survives."""
    from torchsnapshot_tpu.cdn.publisher import _ANNOUNCE_RETAIN

    store = InProcessStore()
    pub = CdnPublisher(store, "t")
    key, data = _chunk(1)
    total = _ANNOUNCE_RETAIN + 5
    for step in range(1, total + 1):
        assert pub.publish(step, {key: len(data)}) is not None
    live = [
        seq
        for seq in range(1, total + 1)
        if read_announce(store, "t", seq) is not None
    ]
    assert live == list(range(total - _ANNOUNCE_RETAIN + 1, total + 1))
    assert read_head(store, "t") == total
    # Retention survives a publisher restart: seq resumes from the head
    # and the reaper keeps walking the same continuous sequence.
    pub2 = CdnPublisher(store, "t")
    assert pub2.publish(total + 1, {key: len(data)}).seq == total + 1
    assert read_announce(store, "t", total + 1 - _ANNOUNCE_RETAIN) is None


# ---------------------------------------------------------------------------
# subscriber
# ---------------------------------------------------------------------------


def test_subscriber_syncs_only_novel_chunks():
    store = InProcessStore()
    pub = CdnPublisher(store, "t")
    reads = []

    def durable_fetch(key):
        reads.append(key)
        return blobs[key]

    ann, blobs = _announce(nchunks=3)
    sub = CdnSubscriber(store, "t", 0, 1, durable_fetch=durable_fetch)
    try:
        pub.publish(ann.step, ann.chunks)
        got = sub.track_once()
        assert got is not None and sub.applied_seq == 1
        assert sorted(reads) == sorted(ann.chunks)
        assert sub.stats.chunks_from_durable == 3

        # Rolling update: one churned chunk, two kept — only the novel
        # chunk is fetched, the rest re-serve from the held pool.
        new_key, new_data = _chunk(99)
        blobs[new_key] = new_data
        kept = dict(ann.chunks)
        kept.pop(sorted(kept)[0])
        kept[new_key] = len(new_data)
        reads.clear()
        pub.publish(ann.step + 1, kept)
        assert sub.track_once(timeout=5.0) is not None
        assert reads == [new_key]
        assert sub.stats.chunks_held == 2
    finally:
        sub.close()


def test_subscriber_fleet_amplification_and_tiers():
    """3 subscribers, 3 chunks: every chunk leaves durable storage
    exactly once (its elected owner), everyone else pulls peer-to-peer."""
    store = InProcessStore()
    ann, blobs = _announce(nchunks=3)
    lock = threading.Lock()
    reads = []

    def durable_fetch(key):
        with lock:
            reads.append(key)
        return blobs[key]

    os.environ["TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS"] = "10"
    subs = [
        CdnSubscriber(store, "t", i, 3, durable_fetch=durable_fetch)
        for i in range(3)
    ]
    try:
        CdnPublisher(store, "t").publish(ann.step, ann.chunks)
        threads = [
            threading.Thread(target=s.track_once, kwargs={"timeout": 10.0})
            for s in subs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert all(s.applied_seq == 1 for s in subs)
        # The ~1x pin: 3 durable reads for 3 chunks, fleet of 3.
        assert sorted(reads) == sorted(ann.chunks)
        assert sum(s.stats.chunks_from_peer for s in subs) == 6
        assert sum(s.stats.peer_fallbacks for s in subs) == 0
        for s in subs:
            assert s.stats.staleness_s and s.stats.staleness_s[0] >= 0.0
    finally:
        for s in subs:
            s.close()
        os.environ.pop("TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS", None)


def test_subscriber_falls_back_to_durable_on_dead_owner():
    """fleet_size=2 but rank 1 never exists: pulls aimed at the absent
    owner time out and degrade to durable reads, never to a stall."""
    store = InProcessStore()
    ann, blobs = _announce(nchunks=2)
    os.environ["TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS"] = "0.2"
    sub = CdnSubscriber(store, "t", 0, 2, durable_fetch=blobs.__getitem__)
    try:
        CdnPublisher(store, "t").publish(ann.step, ann.chunks)
        assert sub.track_once(timeout=5.0) is not None
        assert sub.stats.chunks_from_durable == 2
        assert sub.stats.peer_fallbacks >= 1
    finally:
        sub.close()
        os.environ.pop("TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS", None)


def test_subscriber_without_durable_fetch_raises():
    store = InProcessStore()
    ann, _ = _announce(nchunks=1)
    os.environ["TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS"] = "0.1"
    sub = CdnSubscriber(store, "t", 0, 1)
    try:
        CdnPublisher(store, "t").publish(ann.step, ann.chunks)
        with pytest.raises(CdnSyncError):
            sub.track_once(timeout=5.0)
        assert sub.applied_seq == 0  # nothing half-applied
    finally:
        sub.close()
        os.environ.pop("TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS", None)


def test_wait_for_update_times_out_quietly():
    store = InProcessStore()
    sub = CdnSubscriber(store, "t", 0, 1)
    try:
        assert sub.wait_for_update(timeout=0.05) is None
    finally:
        sub.close()


# ---------------------------------------------------------------------------
# swap
# ---------------------------------------------------------------------------


def _template_and_chunks(leaves):
    """Build chunk blobs whose sorted-key concatenation equals the
    sorted-leaf concatenation of ``leaves``."""
    payload = b"".join(
        np.ascontiguousarray(leaves[name]).tobytes()
        for name in sorted(leaves)
    )
    mid = len(payload) // 2
    chunks = {}
    for part in (payload[:mid], payload[mid:]):
        chunks[digest_key(("crc32", zlib.crc32(part), len(part)))] = part
    return chunks


def test_concat_assembler_reshapes_leaves():
    leaves = {
        "a": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": np.arange(4, dtype=np.int32),
    }
    chunks = _template_and_chunks(leaves)
    ann = Announce(
        topic="t",
        seq=1,
        step=1,
        digest="",
        chunks={k: len(v) for k, v in chunks.items()},
        published_ts=time.time(),
    )
    out = concat_assembler(leaves)(ann, chunks)
    np.testing.assert_array_equal(out["a"], leaves["a"])
    np.testing.assert_array_equal(out["b"], leaves["b"])


def test_weight_swapper_hot_swap():
    weights = {"w": np.zeros(8, dtype=np.float32)}
    swapper = WeightSwapper(weights)
    fresh = {"w": np.arange(8, dtype=np.float32)}
    chunks = _template_and_chunks(fresh)
    ann = Announce(
        topic="t",
        seq=1,
        step=42,
        digest="",
        chunks={k: len(v) for k, v in chunks.items()},
        published_ts=time.time(),
    )
    staged = swapper.stage(ann, chunks)
    # Staging alone must not move the served weights.
    np.testing.assert_array_equal(swapper.weights["w"], 0.0)
    swapper.swap(staged)
    np.testing.assert_array_equal(swapper.weights["w"], fresh["w"])
    assert swapper.swapped_step == 42


def test_weight_swapper_swaps_jax_arrays_with_donation():
    import jax
    import jax.numpy as jnp

    weights = {"w": jnp.zeros(16, dtype=jnp.float32)}
    swapper = WeightSwapper(weights)
    fresh = {"w": np.arange(16, dtype=np.float32)}
    chunks = _template_and_chunks(fresh)
    ann = Announce(
        topic="t",
        seq=1,
        step=7,
        digest="",
        chunks={k: len(v) for k, v in chunks.items()},
        published_ts=time.time(),
    )
    old = weights["w"]
    swapper.swap(swapper.stage(ann, chunks))
    got = swapper.weights["w"]
    assert isinstance(got, jax.Array)
    np.testing.assert_array_equal(np.asarray(got), fresh["w"])
    assert old.is_deleted()  # the stale buffer was donated back


def test_weight_swapper_survives_successive_jax_swaps():
    """The default assembler must not touch template leaves after the
    first swap donates (deletes) them — every later update would crash."""
    import jax.numpy as jnp

    swapper = WeightSwapper({"w": jnp.zeros(16, dtype=jnp.float32)})
    for seq, offset in enumerate([1.0, 2.0], start=1):
        chunks = _template_and_chunks(
            {"w": np.arange(16, dtype=np.float32) + offset}
        )
        ann = Announce(
            topic="t",
            seq=seq,
            step=seq,
            digest="",
            chunks={k: len(v) for k, v in chunks.items()},
            published_ts=time.time(),
        )
        swapper.swap(swapper.stage(ann, chunks))
        # The assembler's layout contract: sorted-key concatenation.
        expected = np.frombuffer(
            b"".join(chunks[k] for k in sorted(chunks)), np.float32
        )
        np.testing.assert_array_equal(
            np.asarray(swapper.weights["w"]), expected
        )
    assert swapper.swapped_step == 2


def test_weight_swapper_rejects_short_payload():
    swapper = WeightSwapper({"w": np.zeros(64, dtype=np.float32)})
    key, data = _chunk(1, nbytes=8)
    ann = Announce(
        topic="t",
        seq=1,
        step=1,
        digest="",
        chunks={key: len(data)},
        published_ts=time.time(),
    )
    with pytest.raises(Exception):
        swapper.stage(ann, {key: data})


# ---------------------------------------------------------------------------
# manager publish hook + end-to-end through a real snapshot root
# ---------------------------------------------------------------------------


def _state(n=1024, offset=0.0):
    return {"m": ts.PyTreeState({"w": np.arange(n, dtype=np.float32) + offset})}


def test_manager_publishes_committed_steps(tmp_path):
    root = str(tmp_path / "ckpt")
    store = InProcessStore()
    with knobs.enable_cas(), knobs.enable_cdn():
        mgr = ts.CheckpointManager(
            root, cdn_topic="run1", cdn_store=store
        )
        mgr.save(0, _state(offset=0.0))
        mgr.save(1, _state(offset=1.0))
    assert read_head(store, "run1") == 2
    ann = read_announce(store, "run1", 2)
    assert ann is not None and ann.step == 1
    # Every announced chunk exists under the root with matching bytes.
    fetch = durable_chunk_reader(root)
    for key in ann.chunks:
        assert verify_chunk_bytes(key, fetch(key))


def test_manager_hook_off_without_knob(tmp_path):
    store = InProcessStore()
    with knobs.enable_cas():  # CDN knob stays pinned off
        mgr = ts.CheckpointManager(
            str(tmp_path / "ckpt"), cdn_topic="run1", cdn_store=store
        )
        mgr.save(0, _state())
    assert read_head(store, "run1") == 0


def test_manager_without_cas_never_half_announces(tmp_path):
    """CAS off means no chunk refs — the manager must skip the publish
    rather than announce an empty chunk set subscribers can't serve."""
    store = InProcessStore()
    with knobs.enable_cdn():
        mgr = ts.CheckpointManager(
            str(tmp_path / "ckpt"), cdn_topic="run1", cdn_store=store
        )
        mgr.save(0, _state())
    assert read_head(store, "run1") == 0


def test_end_to_end_train_to_serve(tmp_path):
    """Trainer saves through the manager; a subscriber streams the
    chunks from the real root and hot-swaps a same-shape template."""
    root = str(tmp_path / "ckpt")
    store = InProcessStore()
    with knobs.enable_cas(), knobs.enable_cdn():
        mgr = ts.CheckpointManager(root, cdn_topic="run1", cdn_store=store)
        mgr.save(0, _state(offset=3.0))
    sub = CdnSubscriber(
        store, "run1", 0, 1, durable_fetch=durable_chunk_reader(root)
    )
    try:
        ann = sub.wait_for_update(timeout=5.0)
        assert ann is not None
        chunk_bytes = sub.sync(ann)
        assert set(chunk_bytes) == set(ann.chunks)
        payload = b"".join(chunk_bytes[k] for k in sorted(chunk_bytes))
        got = np.frombuffer(payload, dtype=np.float32)
        np.testing.assert_array_equal(
            got, np.arange(1024, dtype=np.float32) + 3.0
        )
    finally:
        sub.close()


# ---------------------------------------------------------------------------
# CAS leases (the fleet's GC pin)
# ---------------------------------------------------------------------------


def test_lease_round_trip_and_live_union(tmp_path):
    store = CASStore(str(tmp_path / "ckpt"))
    store.pin(1, {"cas-a": 10})
    store.lease("cdn/t/0", {"cas-b": 20})
    pins, orphans, leases = store.load_full()
    assert sorted(pins) == [1]
    assert leases == {"cdn/t/0": {"cas-b": 20}}
    live = store.live_chunks(pins, leases)
    assert live == {"cas-a", "cas-b"}
    # Re-lease replaces (drops cas-b, adds cas-c); unlease removes.
    store.lease("cdn/t/0", {"cas-c": 30})
    _, _, leases = store.load_full()
    assert leases == {"cdn/t/0": {"cas-c": 30}}
    store.unlease("cdn/t/0")
    _, _, leases = store.load_full()
    assert leases == {}
    # Legacy two-tuple load still works for existing callers.
    pins, orphans = store.load()
    assert sorted(pins) == [1] and not orphans


def test_compact_preserves_outstanding_leases(tmp_path):
    store = CASStore(str(tmp_path / "ckpt"))
    store.pin(1, {"cas-a": 10})
    store.lease("cdn/t/0", {"cas-b": 20})
    pins, orphans = store.load()
    store.compact(pins, orphans)  # lease-unaware caller
    _, _, leases = store.load_full()
    assert leases == {"cdn/t/0": {"cas-b": 20}}


def test_subscriber_leases_held_chunks(tmp_path):
    """A subscriber with a cas_store records its held set as a lease
    after each apply and releases it on close."""
    cas_store = CASStore(str(tmp_path / "ckpt"))
    store = InProcessStore()
    ann, blobs = _announce(nchunks=2)
    sub = CdnSubscriber(
        store,
        "t",
        0,
        1,
        durable_fetch=blobs.__getitem__,
        cas_store=cas_store,
    )
    try:
        CdnPublisher(store, "t").publish(ann.step, ann.chunks)
        assert sub.track_once(timeout=5.0) is not None
        _, _, leases = cas_store.load_full()
        assert leases == {sub.lease_id: dict(ann.chunks)}
    finally:
        sub.close()
    _, _, leases = cas_store.load_full()
    assert leases == {}


def test_manager_gc_spares_fleet_leased_chunks(tmp_path):
    """Retention drops a step whose unique chunk a subscriber still
    serves: the lease keeps the chunk file on disk through GC."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(0):
        mgr = ts.CheckpointManager(root, keep_last_n=1)
        mgr.save(0, _state(offset=0.0))
        store = CASStore(root)
        pins, _, _ = store.load_full()
        step0_chunks = pins[0]
        store.lease("cdn/t/0", dict(step0_chunks))
        mgr.save(1, _state(offset=1.0))  # retention drops step 0
        chunks_dir = os.path.join(root, "chunks")
        for key in step0_chunks:
            assert os.path.exists(os.path.join(chunks_dir, key)), key
        # Lease released -> the next GC pass reclaims.
        store.unlease("cdn/t/0")
        mgr.save(2, _state(offset=2.0))
        for key in step0_chunks:
            if key in store.live_chunks(store.load()[0]):
                continue
            assert not os.path.exists(os.path.join(chunks_dir, key)), key
