"""Pallas flash attention vs the dense einsum op.

Same strategy as test_ring_attention.py: numerical equivalence of two
implementations, no I/O. The kernel runs in Pallas interpreter mode
(CPU-safe; pallas_guide.md's interpret flag) — on real TPUs the same
kernel compiles natively.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu.ops import causal_attention, flash_causal_attention

# Interpreter-mode comparisons are CPU-path tests: Pallas's interpreter
# lowers the kernel body to plain jax ops on the ACTIVE backend, and on
# a TPU backend that hybrid diverges numerically from both the native
# kernel and the dense reference. The TPU claim is enforced by
# test_flash_compiles_natively_on_tpu (interpret=False, real chip).
_interpret_mode = pytest.mark.skipif(
    os.environ.get("TS_TEST_ON_TPU") == "1",
    reason="interpret-mode comparisons are CPU-backend tests; the "
    "native-compile test covers TPU",
)


def _qkv(seed, shape=(2, 256, 4, 32), dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@_interpret_mode
@pytest.mark.parametrize("block_q,block_k", [(64, 64), (128, 64), (64, 128)])
def test_flash_matches_dense_f32(block_q, block_k) -> None:
    q, k, v = _qkv(0)
    dense = causal_attention(q, k, v)
    flash = flash_causal_attention(
        q, k, v, block_q=block_q, block_k=block_k, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


@_interpret_mode
def test_flash_matches_dense_bf16() -> None:
    q, k, v = _qkv(1, dtype=jnp.bfloat16)
    dense = causal_attention(q, k, v)
    flash = flash_causal_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(flash).astype(np.float32),
        np.asarray(dense).astype(np.float32),
        rtol=2e-2,
        atol=2e-2,
    )


@_interpret_mode
def test_flash_causality() -> None:
    """Future tokens cannot influence outputs: perturbing position j only
    changes outputs at positions >= j."""
    q, k, v = _qkv(2, shape=(1, 128, 2, 16))
    base = flash_causal_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    j = 100
    k2 = k.at[:, j].set(k[:, j] + 10.0)
    v2 = v.at[:, j].set(v[:, j] - 3.0)
    pert = flash_causal_attention(q, k2, v2, block_q=64, block_k=64, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(pert[:, :j]), np.asarray(base[:, :j])
    )
    assert not np.allclose(np.asarray(pert[:, j:]), np.asarray(base[:, j:]))


@_interpret_mode
def test_flash_rejects_nondivisible_seq() -> None:
    q, k, v = _qkv(3, shape=(1, 96, 2, 16))
    with pytest.raises(ValueError, match="multiple"):
        flash_causal_attention(q, k, v, block_q=64, block_k=64, interpret=True)


@pytest.mark.skipif(
    os.environ.get("TS_TEST_ON_TPU") != "1",
    reason="native Mosaic compile needs a real TPU (TS_TEST_ON_TPU=1)",
)
def test_flash_compiles_natively_on_tpu() -> None:
    """The kernel's native-TPU claim, enforced: compile (interpret=False)
    on the real chip and match the dense path. Covers both the standalone
    causal kernel and the chunk variant the ring path uses."""
    assert jax.devices()[0].platform == "tpu"
    from torchsnapshot_tpu.ops.flash_attention import flash_attention_chunk

    rng = np.random.default_rng(0)
    b, s, h, d = 2, 512, 4, 128
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        for _ in range(3)
    )
    out = jax.jit(flash_causal_attention)(q, k, v)
    ref = causal_attention(q, k, v)
    err = float(
        jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
    )
    assert err < 0.05, err

    o, m, l = jax.jit(
        lambda q, k, v: flash_attention_chunk(q, k, v, causal=True)
    )(q, k, v)
    out2 = (o / l[..., None]).transpose(0, 2, 1, 3)
    err2 = float(jnp.max(jnp.abs(out2 - ref.astype(jnp.float32))))
    assert err2 < 0.05, err2


@_interpret_mode
def test_flash_grad_matches_dense() -> None:
    """Reverse-mode through the kernel (custom_vjp with the blockwise
    recompute backward) must match dense attention's gradients."""
    q, k, v = _qkv(7, shape=(1, 256, 2, 16))

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_causal_attention(q, k, v, interpret=True) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )
