"""Pallas flash attention vs the dense einsum op.

Same strategy as test_ring_attention.py: numerical equivalence of two
implementations, no I/O. The kernel runs in Pallas interpreter mode
(CPU-safe; pallas_guide.md's interpret flag) — on real TPUs the same
kernel compiles natively.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu.ops import causal_attention, flash_causal_attention


def _qkv(seed, shape=(2, 256, 4, 32), dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize("block_q,block_k", [(64, 64), (128, 64), (64, 128)])
def test_flash_matches_dense_f32(block_q, block_k) -> None:
    q, k, v = _qkv(0)
    dense = causal_attention(q, k, v)
    flash = flash_causal_attention(
        q, k, v, block_q=block_q, block_k=block_k, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


def test_flash_matches_dense_bf16() -> None:
    q, k, v = _qkv(1, dtype=jnp.bfloat16)
    dense = causal_attention(q, k, v)
    flash = flash_causal_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(
        np.asarray(flash).astype(np.float32),
        np.asarray(dense).astype(np.float32),
        rtol=2e-2,
        atol=2e-2,
    )


def test_flash_causality() -> None:
    """Future tokens cannot influence outputs: perturbing position j only
    changes outputs at positions >= j."""
    q, k, v = _qkv(2, shape=(1, 128, 2, 16))
    base = flash_causal_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    j = 100
    k2 = k.at[:, j].set(k[:, j] + 10.0)
    v2 = v.at[:, j].set(v[:, j] - 3.0)
    pert = flash_causal_attention(q, k2, v2, block_q=64, block_k=64, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(pert[:, :j]), np.asarray(base[:, :j])
    )
    assert not np.allclose(np.asarray(pert[:, j:]), np.asarray(base[:, j:]))


def test_flash_rejects_nondivisible_seq() -> None:
    q, k, v = _qkv(3, shape=(1, 96, 2, 16))
    with pytest.raises(ValueError, match="multiple"):
        flash_causal_attention(q, k, v, block_q=64, block_k=64, interpret=True)
