"""Ring attention (ops/ring_attention.py): exactness vs the dense op.

Strategy mirrors the reference's pure-unit layer (SURVEY.md §4): no I/O,
just numerical equivalence of two implementations — the sequence-sharded
ring computation must match dense causal attention up to f32 roundoff,
for outputs AND gradients, on an 8-device ('dp','sp','tp') CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu.models import (
    TransformerConfig,
    forward,
    init_params,
    make_mesh,
)
from torchsnapshot_tpu.ops import causal_attention, ring_causal_attention


def _mesh_or_skip(n: int):
    if len(jax.devices()) < n:
        pytest.skip(
            f"needs {n} devices, backend has {len(jax.devices())} "
            f"(CPU runs force an 8-device virtual mesh via conftest)"
        )
    return make_mesh(n)


def _rand_qkv(key, b=2, s=32, h=4, d=8, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype=dtype),
        jax.random.normal(kk, shape, dtype=dtype),
        jax.random.normal(kv, shape, dtype=dtype),
    )


def test_ring_matches_dense_forward():
    mesh = _mesh_or_skip(8)
    assert mesh.shape["sp"] > 1
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    dense = causal_attention(q, k, v)
    ring = ring_causal_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_ring_matches_dense_grad():
    mesh = _mesh_or_skip(8)
    q, k, v = _rand_qkv(jax.random.PRNGKey(1))

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring_causal_attention(q, k, v, mesh=mesh)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(causal_attention(q, k, v)))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


def test_ring_sp1_mesh_and_no_mesh():
    # Degenerate ring (sp=1) and the mesh=None fallback both reduce to dense.
    mesh = _mesh_or_skip(2)  # (dp=1, sp=1, tp=2)
    assert mesh.shape["sp"] == 1
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), s=16)
    dense = causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ring_causal_attention(q, k, v, mesh=mesh)),
        np.asarray(dense),
        rtol=1e-5,
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(ring_causal_attention(q, k, v, mesh=None)),
        np.asarray(dense),
        rtol=1e-6,
        atol=1e-6,
    )


@pytest.mark.parametrize("n_experts", [0, 4])
def test_transformer_ring_vs_ulysses(n_experts):
    # The full model must produce identical logits under either attention
    # parallelization — they are different schedules of the same math.
    mesh = _mesh_or_skip(8)
    base = dict(
        vocab_size=64,
        d_model=32,
        n_heads=4,
        n_layers=2,
        d_ff=64,
        n_experts=n_experts,
        dtype=jnp.float32,
    )
    cfg_u = TransformerConfig(**base, attn_impl="ulysses")
    cfg_r = TransformerConfig(**base, attn_impl="ring")
    params = init_params(cfg_u, jax.random.PRNGKey(0), mesh=mesh)
    tokens = jax.device_put(
        np.random.default_rng(0).integers(0, 64, (4, 32)).astype(np.int32)
    )
    out_u = forward(cfg_u, params, tokens, mesh=mesh)
    out_r = forward(cfg_r, params, tokens, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(out_r), np.asarray(out_u), rtol=2e-4, atol=2e-4
    )


def test_ring_flash_matches_dense_forward():
    """Flash-within-ring: every ring step's blockwise attention runs in
    the Pallas chunk kernel (interpreted on CPU), merged by the same
    online-softmax recurrence — must equal dense causal attention."""
    mesh = _mesh_or_skip(8)
    q, k, v = _rand_qkv(jax.random.PRNGKey(3))
    dense = causal_attention(q, k, v)
    ring = ring_causal_attention(
        q, k, v, mesh=mesh, use_flash=True, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("attn_impl", ["flash", "ring_flash"])
def test_transformer_flash_impls_match_ulysses(attn_impl):
    """attn_impl='flash'/'ring_flash' are selectable on the flagship model
    and agree with the dense ulysses path. seq=128 so the flash gate
    (seq % 128 == 0) is active."""
    mesh = _mesh_or_skip(8)
    kwargs = dict(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=0, dtype=jnp.float32,
    )
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 128)), jnp.int32
    )
    cfg_ref = TransformerConfig(attn_impl="ulysses", **kwargs)
    params = init_params(cfg_ref, jax.random.PRNGKey(1), mesh=mesh)
    ref = forward(cfg_ref, params, tokens, mesh=mesh)
    cfg = TransformerConfig(attn_impl=attn_impl, **kwargs)
    out = forward(cfg, params, tokens, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("attn_impl", ["flash", "ring_flash"])
def test_train_step_with_flash_impls(attn_impl):
    """The flagship purpose is TRAINING state: value_and_grad through the
    flash paths must work (custom_vjp), not just forward."""
    from torchsnapshot_tpu.models import init_train_state, make_train_step

    mesh = _mesh_or_skip(8)
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=0, dtype=jnp.float32, attn_impl=attn_impl,
    )
    state = init_train_state(cfg, seed=0, mesh=mesh)
    step_fn = make_train_step(cfg, mesh=mesh)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, (2, 128)), jnp.int32
    )
    state, loss = step_fn(state, tokens)
    assert np.isfinite(float(loss))
    assert int(state.step) == 1


def test_flash_rejects_bad_seq_loudly():
    """attn_impl='flash' with seq not divisible by 128 must raise, not
    silently fall back to the dense path the user chose flash to avoid."""
    mesh = _mesh_or_skip(8)
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        n_experts=0, dtype=jnp.float32, attn_impl="flash",
    )
    params = init_params(cfg, jax.random.PRNGKey(0), mesh=mesh)
    tokens = jnp.zeros((2, 96), jnp.int32)
    with pytest.raises(ValueError, match="seq % 128"):
        forward(cfg, params, tokens, mesh=mesh)
