"""Content-addressed chunk store (torchsnapshot_tpu/cas, docs/cas.md).

Covers the ISSUE-12 satellite matrix: digest-key derivation, dedup'd
take/restore round trips bit-identical to the legacy layout, refcounted
GC (shared chunks survive, dead chunks reclaim, grace-window deferral
protects in-flight takes), crash healing (torn journal tail, lost
journal rebuilt from manifests), legacy<->CAS mixed roots, incremental
refs collapsing onto chunks (base-step GC structurally safe), the
legacy-mode orphaned-base retention guard, 2-process replicated-rank
dedup (exactly one stored copy, pinned via a counting plugin), the
whole-store fsck audit, chunk-level mirror shipping, the peer cache's
chunk pool, and the dedup-ineffective doctor rule.
"""

import json
import os
import threading

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import cas, knobs
from torchsnapshot_tpu.cas import (
    CASStore,
    chunk_location,
    chunk_refs,
    digest_key,
    is_chunk_location,
    key_of_location,
    nbytes_of_key,
    parse_key,
)
from torchsnapshot_tpu.integrity import ChecksumError, compute_checksum_entry
from torchsnapshot_tpu.manager import referenced_steps
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import patch_storage_plugin, run_multiprocess


def _state(n=4096, offset=0.0, extra=None):
    tree = {
        "w": np.arange(n, dtype=np.float32) + offset,
        "frozen": np.ones(n // 4, dtype=np.float32),
    }
    if extra is not None:
        tree.update(extra)
    return {"m": ts.PyTreeState(tree)}


def _chunk_files(root):
    cdir = os.path.join(root, "chunks")
    if not os.path.isdir(cdir):
        return {}
    return {
        name: os.path.getsize(os.path.join(cdir, name))
        for name in os.listdir(cdir)
        if name.startswith("cas-")
    }


def _journal_records(root):
    path = os.path.join(root, "chunks", ".refcounts.jsonl")
    if not os.path.exists(path):
        return []
    return [
        json.loads(line)
        for line in open(path).read().splitlines()
        if line.strip()
    ]


# ---------------------------------------------------------------------------
# digest keys
# ---------------------------------------------------------------------------


def test_digest_key_derivation_and_parse():
    entry = compute_checksum_entry(b"hello chunk store")
    key = digest_key(entry)
    assert key.startswith("cas-")
    assert nbytes_of_key(key) == len(b"hello chunk store")
    alg, nbytes, crc = parse_key(key)
    assert alg == entry[0] and nbytes == entry[2] and crc == entry[1]
    # Same bytes -> same key; different bytes -> different key.
    assert key == digest_key(compute_checksum_entry(b"hello chunk store"))
    assert key != digest_key(compute_checksum_entry(b"hello chunk steve"))
    loc = chunk_location(key)
    assert is_chunk_location(loc) and key_of_location(loc) == key
    # Legacy refs and step-local paths are never chunk locations.
    assert not is_chunk_location("../step_0000000001/0/m/w")
    assert not is_chunk_location("0/m/w")
    assert key_of_location("../chunks/not-a-key") is None


def test_digest_key_paged_entries_fold_pages():
    from torchsnapshot_tpu.integrity import PAGE_SIZE

    big = np.arange(PAGE_SIZE // 4 * 2 + 999, dtype=np.int32).tobytes()
    entry = compute_checksum_entry(big)
    assert len(entry) >= 5  # paged
    key = digest_key(entry)
    assert "-p" in key
    assert nbytes_of_key(key) == len(big)
    # parse_key still exposes the whole-blob CRC (pages are an extension).
    assert parse_key(key)[2] == entry[1]


# ---------------------------------------------------------------------------
# take / restore round trip + dedup
# ---------------------------------------------------------------------------


def test_take_restore_roundtrip_bit_identical_to_legacy(tmp_path):
    legacy_root = str(tmp_path / "legacy")
    cas_root = str(tmp_path / "cas")
    state = _state(offset=3.0)
    ts.Snapshot.take(os.path.join(legacy_root, "step_0000000001"), state)
    with knobs.enable_cas():
        snap = ts.Snapshot.take(
            os.path.join(cas_root, "step_0000000001"), state
        )
    manifest = snap.metadata.manifest
    locs = {
        p: e.location
        for p, e in manifest.items()
        if getattr(e, "location", None)
    }
    assert locs and all(is_chunk_location(l) for l in locs.values())
    # The stored chunk bytes ARE the legacy blob bytes (same
    # serialization, different address): restore is bit-identical by
    # construction, pinned here at the byte level.
    legacy_w = open(
        os.path.join(legacy_root, "step_0000000001", "0", "m", "w"), "rb"
    ).read()
    w_chunk = key_of_location(locs["0/m/w"])
    cas_w = open(os.path.join(cas_root, "chunks", w_chunk), "rb").read()
    assert cas_w == legacy_w
    # And end-to-end through restore (checksum-verified: the rekeyed
    # table's keys match the chunk read paths).
    dest = _state(offset=0.0)
    ts.Snapshot(os.path.join(cas_root, "step_0000000001")).restore(dest)
    np.testing.assert_array_equal(
        dest["m"].tree["w"], state["m"].tree["w"]
    )


def test_second_identical_take_stores_nothing_new(tmp_path):
    root = str(tmp_path / "ckpt")
    state = _state()
    with knobs.enable_cas():
        ts.Snapshot.take(os.path.join(root, "step_0000000001"), state)
        before = _chunk_files(root)
        ts.Snapshot.take(os.path.join(root, "step_0000000002"), state)
        after = _chunk_files(root)
    assert before == after  # dedup across steps: zero new chunk bytes
    # Both manifests reference the same chunks.
    m1 = ts.Snapshot(os.path.join(root, "step_0000000001")).metadata.manifest
    m2 = ts.Snapshot(os.path.join(root, "step_0000000002")).metadata.manifest
    assert chunk_refs(m1) == chunk_refs(m2)


def test_restore_verifies_chunk_bytes(tmp_path):
    root = str(tmp_path / "ckpt")
    state = _state()
    with knobs.enable_cas():
        snap = ts.Snapshot.take(os.path.join(root, "step_0000000001"), state)
    key = key_of_location(snap.metadata.manifest["0/m/w"].location)
    with open(os.path.join(root, "chunks", key), "r+b") as f:
        f.seek(16)
        f.write(b"\xde\xad")
    with pytest.raises(ChecksumError):
        ts.Snapshot(os.path.join(root, "step_0000000001")).restore(_state())


def test_async_take_cas_roundtrip(tmp_path):
    root = str(tmp_path / "ckpt")
    state = _state(offset=11.0)
    with knobs.enable_cas():
        pending = ts.Snapshot.async_take(
            os.path.join(root, "step_0000000001"), state
        )
        snap = pending.wait()
    assert all(
        is_chunk_location(e.location)
        for e in snap.metadata.manifest.values()
        if getattr(e, "location", None)
    )
    dest = _state()
    snap.restore(dest)
    np.testing.assert_array_equal(
        dest["m"].tree["w"], state["m"].tree["w"]
    )


def test_ineligible_scheme_falls_back_to_legacy(tmp_path):
    with knobs.enable_cas():
        snap = ts.Snapshot.take("memory://casless/step_0000000001", _state())
    assert not any(
        is_chunk_location(e.location)
        for e in snap.metadata.manifest.values()
        if getattr(e, "location", None)
    )


# ---------------------------------------------------------------------------
# manager: refcounted GC
# ---------------------------------------------------------------------------


def test_manager_retention_refcount_gc(tmp_path):
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(0):
        mgr = ts.CheckpointManager(root, keep_last_n=2)
        for i in range(5):
            mgr.save(i, _state(offset=float(i)))
        files = _chunk_files(root)
        # Two live 'w' variants (steps 3, 4) + ONE shared 'frozen'
        # chunk: dense retention at ~1 step + deltas.
        assert len(files) == 3
        dest = _state()
        assert mgr.restore_latest(dest) == 4
        np.testing.assert_array_equal(
            dest["m"].tree["w"], _state(offset=4.0)["m"].tree["w"]
        )
        # The journal records pins for exactly the retained steps.
        store = CASStore(root)
        pins, orphans = store.load()
        assert sorted(pins) == [3, 4]
        assert not orphans


def test_gc_grace_defers_then_reclaims(tmp_path):
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas():
        with knobs.override_cas_gc_grace_seconds(3600):
            mgr = ts.CheckpointManager(root, keep_last_n=1)
            mgr.save(0, _state(offset=0.0))
            mgr.save(1, _state(offset=1.0))  # drops step 0
            files = _chunk_files(root)
            # Step 0's unique chunk is dead but FRESH: deferred as a
            # journaled orphan, not reclaimed (an in-flight take may
            # have just deduped against it).
            store = CASStore(root)
            pins, orphans = store.load()
            assert sorted(pins) == [1]
            assert len(orphans) == 1
            assert set(orphans) <= set(files)
        with knobs.override_cas_gc_grace_seconds(0):
            mgr.save(2, _state(offset=2.0))  # next pass reclaims
            store = CASStore(root)
            pins, orphans = store.load()
            assert not orphans
            dead = set(_chunk_files(root))
            assert not any(k in dead for k in orphans)
        dest = _state()
        assert mgr.restore_latest(dest) == 2


def test_concurrent_take_dedup_survives_gc_of_its_source(tmp_path):
    """The ISSUE's concurrent take + GC pin: an in-flight (not yet
    committed) async take dedups against step 0's chunks; a sync save
    then GCs step 0 — the grace window keeps the shared chunks on disk,
    and the async step commits restorable."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(3600):
        mgr = ts.CheckpointManager(root, keep_last_n=1)
        state_a = _state(offset=7.0)
        mgr.save(0, state_a)
        # In-flight take of the SAME state: its writes dedup against
        # step 0's chunks (touching them) but nothing is pinned until
        # wait().
        pending = mgr.async_save(1, state_a)
        pending._pending.wait(phase="staged")
        # A competing commit drops step 0 while step 1 is un-pinned.
        mgr.save(2, _state(offset=9.0))
        assert pending.wait() is not None  # commits + pins step 1
        dest = _state()
        mgr.restore(1, dest)
        np.testing.assert_array_equal(
            dest["m"].tree["w"], state_a["m"].tree["w"]
        )


def test_crash_between_chunk_write_and_refcount_append_heals(tmp_path):
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas():
        mgr = ts.CheckpointManager(root, keep_last_n=3)
        mgr.save(0, _state(offset=0.0))
        mgr.save(1, _state(offset=1.0))
        journal = os.path.join(root, "chunks", ".refcounts.jsonl")
        # Simulated crash: the chunks + index landed, the journal did
        # not survive at all.
        os.remove(journal)
        mgr2 = ts.CheckpointManager(root, keep_last_n=3)
        pins, _ = CASStore(root).load()
        assert sorted(pins) == [0, 1]
        assert pins[1] == chunk_refs(
            ts.Snapshot(mgr2.step_path(1)).metadata.manifest
        )
        dest = _state()
        assert mgr2.restore_latest(dest) == 1


def test_torn_journal_tail_is_skipped_and_healed(tmp_path):
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas():
        mgr = ts.CheckpointManager(root, keep_last_n=3)
        mgr.save(0, _state())
        store = CASStore(root)
        pins_before, _ = store.load()
        with open(store.journal_path, "a") as f:
            f.write('{"op": "pin", "step": 99, "chu')  # kill mid-append
        pins, _ = store.load()
        assert pins == pins_before  # torn tail skipped
        store.pin(42, {"cas-crc32c-1-00000000": 1})  # heals with newline
        pins, _ = store.load()
        assert 42 in pins and 99 not in pins and 0 in pins


# ---------------------------------------------------------------------------
# mixed layouts + incremental interplay
# ---------------------------------------------------------------------------


def test_mixed_legacy_and_cas_root_restores_both(tmp_path):
    root = str(tmp_path / "ckpt")
    mgr = ts.CheckpointManager(root, keep_last_n=10)
    mgr.save(0, _state(offset=0.0))  # legacy layout
    with knobs.enable_cas():
        mgr.save(1, _state(offset=1.0))  # CAS layout, same root
        dest = _state()
        mgr.restore(0, dest)
        np.testing.assert_array_equal(
            dest["m"].tree["w"], _state(offset=0.0)["m"].tree["w"]
        )
        mgr.restore(1, dest)
        np.testing.assert_array_equal(
            dest["m"].tree["w"], _state(offset=1.0)["m"].tree["w"]
        )
    # And with the knob back off (restore is layout-agnostic).
    dest = _state()
    mgr.restore(1, dest)
    np.testing.assert_array_equal(
        dest["m"].tree["w"], _state(offset=1.0)["m"].tree["w"]
    )


def test_incremental_refs_collapse_onto_chunks(tmp_path):
    """CAS supersedes the lexical ``../step_*`` base references: an
    incremental take over a CAS base lands every unchanged chunk at its
    ``../chunks/<key>`` address directly (normpath collapses the
    step-relative composition), so manifests carry NO step refs and
    base-step GC can never dangle a reference — the structural
    impossibility the ISSUE names."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(0):
        mgr = ts.CheckpointManager(root, keep_last_n=1, incremental=True)
        mgr.save(0, _state(offset=5.0))
        mgr.save(1, _state(offset=5.0))  # unchanged: all refs
        man1 = ts.Snapshot(mgr.step_path(1)).metadata.manifest
        assert referenced_steps(man1) == set()  # no ../step_* anywhere
        assert chunk_refs(man1)
        # keep_last_n=1 deleted step 0's blobs outright (GC leaves only
        # empty directories behind, as for any legacy step) — nothing
        # pins it, because nothing references it.
        step0 = os.path.join(root, "step_0000000000")
        leftover = [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(step0)
            for f in fs
        ]
        assert leftover == []
        index = json.loads(
            open(os.path.join(root, ".manager_index")).read()
        )
        assert "pinned" not in index
        dest = _state()
        assert mgr.restore_latest(dest) == 1
        np.testing.assert_array_equal(
            dest["m"].tree["w"], _state(offset=5.0)["m"].tree["w"]
        )


def test_incremental_skip_avoids_chunk_rewrites(tmp_path):
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas():
        mgr = ts.CheckpointManager(root, keep_last_n=5, incremental=True)
        mgr.save(0, _state(offset=2.0))
        before = _chunk_files(root)
        mgr.save(1, _state(offset=2.0))
        assert _chunk_files(root) == before


# ---------------------------------------------------------------------------
# legacy-mode retention guard (the orphaned-base bugfix)
# ---------------------------------------------------------------------------


def test_legacy_gc_rederives_refs_for_unmarked_index(tmp_path):
    """An index written before refs recording (no ``refs`` map, no
    ``refs_complete`` marker) holds an incremental step whose base a
    keep_last_n GC would drop: the explicit retention check re-derives
    refs from the retained manifests and PINS the base instead of
    orphaning the ``../step_*`` references."""
    root = str(tmp_path / "ckpt")
    mgr = ts.CheckpointManager(root, keep_last_n=2, incremental=True)
    mgr.save(0, _state(offset=1.0))
    mgr.save(1, _state(offset=1.0))  # references step 0's blobs
    man1 = ts.Snapshot(mgr.step_path(1)).metadata.manifest
    assert referenced_steps(man1) == {0}
    # Strip the refs bookkeeping: the pre-incremental index format.
    for slot in (".manager_index", ".manager_index.backup"):
        path = os.path.join(root, slot)
        index = json.loads(open(path).read())
        index.pop("refs", None)
        index.pop("refs_complete", None)
        open(path, "w").write(json.dumps(index))
    # keep_last_n=2: committing step 2 drops step 0 from the visible
    # list — WITHOUT the guard its blobs would be deleted while step
    # 1 still references them.
    mgr.save(2, _state(offset=3.0))
    index = json.loads(open(os.path.join(root, ".manager_index")).read())
    assert index.get("pinned") == [0]  # healed: base pinned, not orphaned
    assert index.get("refs", {}).get("1") == [0]
    assert index.get("refs_complete") is True
    from torchsnapshot_tpu.fsck import verify_snapshot

    report = verify_snapshot(mgr.step_path(1))
    assert report.ok, [p.__dict__ for p in report.problems]
    dest = _state()
    mgr.restore(1, dest)
    np.testing.assert_array_equal(
        dest["m"].tree["w"], _state(offset=1.0)["m"].tree["w"]
    )


# ---------------------------------------------------------------------------
# 2-process replicated-rank dedup
# ---------------------------------------------------------------------------

_CHUNK_WRITES = []


class _ChunkCountingFS(FSStoragePlugin):
    """Accumulates every chunk-blob write this process issues."""

    async def write(self, write_io):
        if is_chunk_location(write_io.path):
            _CHUNK_WRITES.append(write_io.path)
        await super().write(write_io)


def _replicated_dedup_worker(pg, root: str):
    os.environ["TORCHSNAPSHOT_TPU_CAS"] = "1"
    state = {
        "m": ts.PyTreeState(
            {
                # Identical bytes on BOTH ranks, saved per-rank (not
                # declared replicated): the partitioner keeps two
                # entries, the chunk store keeps one blob.
                "same": np.arange(8192, dtype=np.float32),
                "own": np.full(1024, float(pg.rank), dtype=np.float32),
            }
        )
    }
    with patch_storage_plugin(_ChunkCountingFS):
        ts.Snapshot.take(
            os.path.join(root, "step_0000000001"), state, pg=pg
        )
        first = list(_CHUNK_WRITES)
        ts.Snapshot.take(
            os.path.join(root, "step_0000000002"), state, pg=pg
        )        # dedup across steps: nothing new anywhere
        second = [p for p in _CHUNK_WRITES if p not in first]
    return {"rank": pg.rank, "first": first, "second": second}


@pytest.mark.slow
def test_two_proc_replicated_rank_dedup(tmp_path):
    root = str(tmp_path / "ckpt")
    rows = run_multiprocess(_replicated_dedup_worker, nproc=2, args=(root,))
    files = _chunk_files(root)
    snap = ts.Snapshot(os.path.join(root, "step_0000000001"))
    manifest = snap.metadata.manifest
    same_locs = {
        manifest["0/m/same"].location,
        manifest["1/m/same"].location,
    }
    # Replica dedup: both ranks' identical leaves resolve to ONE stored
    # blob (one location, one file).
    assert len(same_locs) == 1
    key = key_of_location(next(iter(same_locs)))
    assert key in files
    # Exactly one stored copy per unique digest overall: 'same' (x1) +
    # per-rank 'own' (x2) = 3 chunk files.
    assert len(files) == 3
    # Step 2 (identical state) wrote NOTHING on either rank.
    for row in rows:
        assert row["second"] == []


# ---------------------------------------------------------------------------
# fsck --cas
# ---------------------------------------------------------------------------


def test_fsck_cas_store_audit(tmp_path):
    from torchsnapshot_tpu.fsck import main as fsck_main, verify_cas_store

    root = str(tmp_path / "ckpt")
    with knobs.enable_cas():
        mgr = ts.CheckpointManager(root, keep_last_n=5)
        for i in range(3):
            mgr.save(i, _state(offset=float(i)))
    report = verify_cas_store(root, deep=True)
    assert report.ok
    assert report.steps == [0, 1, 2]
    assert report.crcs_verified == report.chunks_referenced
    # 3 'w' variants + 1 shared 'frozen': 4 stored, logical = 3 steps
    # x 2 leaves -> dedup ratio > 1.
    assert report.chunks_present == 4
    assert report.dedup_ratio > 1.1
    assert report.bytes_per_retained_step > 0
    assert fsck_main([root, "--cas", "--deep"]) == 0

    cdir = os.path.join(root, "chunks")
    victim = sorted(k for k in _chunk_files(root))[0]
    # Corruption -> deep audit checksum problem.
    with open(os.path.join(cdir, victim), "r+b") as f:
        f.seek(3)
        f.write(b"\x99")
    deep = verify_cas_store(root, deep=True)
    assert any(p.kind == "checksum" for p in deep.problems)
    # Dangling ref -> missing problem (shallow sees it too).
    os.remove(os.path.join(cdir, victim))
    shallow = verify_cas_store(root)
    assert any(
        p.kind == "missing" and victim in p.location
        for p in shallow.problems
    )
    assert fsck_main([root, "--cas"]) == 1
    # A stray (unreferenced) chunk is informational, never a failure.
    stray = digest_key(compute_checksum_entry(b"stray bytes"))
    open(os.path.join(cdir, stray), "wb").write(b"stray bytes")
    report = verify_cas_store(root)
    assert stray in report.unreferenced
    assert not any(stray in p.location for p in report.problems)


# ---------------------------------------------------------------------------
# mirror: chunk-level shipping
# ---------------------------------------------------------------------------


def test_mirror_ships_only_novel_chunks(tmp_path):
    from torchsnapshot_tpu.tiered.mirror import get_mirror, reset_mirror

    fast = str(tmp_path / "fast")
    dur = str(tmp_path / "dur")
    root = f"tiered://{fast}/ckpt|{dur}/ckpt"
    reset_mirror()
    try:
        with knobs.enable_cas():
            mgr = ts.CheckpointManager(root, keep_last_n=4)
            mgr.save(0, _state(offset=6.0))
            mgr.wait_durable(0)
            shipped_first = get_mirror().metrics()["bytes_mirrored"]
            mgr.save(1, _state(offset=6.0))  # identical: chunks all held
            mgr.wait_durable(1)
            shipped_second = (
                get_mirror().metrics()["bytes_mirrored"] - shipped_first
            )
        state_bytes = 4096 * 4 + 1024 * 4
        assert shipped_first > state_bytes  # data + metadata shipped
        # Step 1 ships only control blobs (manifest, tables, maps) —
        # every data chunk is skipped by the durable existence probe.
        assert shipped_second < state_bytes / 4
        # Durable tier holds the chunks once.
        assert sorted(_chunk_files(os.path.join(dur, "ckpt"))) == sorted(
            _chunk_files(os.path.join(fast, "ckpt"))
        )
        # And a fast-tier loss restores from durable alone.
        import shutil

        shutil.rmtree(fast)
        dest = _state()
        mgr2 = ts.CheckpointManager(root, keep_last_n=4)
        assert mgr2.restore_latest(dest) == 1
        np.testing.assert_array_equal(
            dest["m"].tree["w"], _state(offset=6.0)["m"].tree["w"]
        )
    finally:
        reset_mirror()


# ---------------------------------------------------------------------------
# peer tier: chunk pool + inventory-by-digest
# ---------------------------------------------------------------------------


def test_peer_cache_chunk_pool_refcounts():
    from torchsnapshot_tpu.scheduler import PeerCacheBudget
    from torchsnapshot_tpu.tiered.peer import PeerCache

    cache = PeerCache(budget=PeerCacheBudget(1 << 20))
    data = b"c" * 1000
    entry = compute_checksum_entry(data)
    loc = chunk_location(digest_key(entry))
    ok, _ = cache.put("stepA", 1, loc, entry, data)
    assert ok
    bytes_after_one = cache.stats()["bytes"]
    # A second step referencing the same chunk adds NO bytes.
    assert cache.reference_chunks("stepB", 2, [loc, "../chunks/cas-x"]) == [
        loc
    ]
    assert cache.stats()["bytes"] == bytes_after_one
    assert loc in cache.inventory("stepB")
    # Served for any step key: content-addressed.
    assert cache.get("stepB", loc)[1] == data
    assert cache.get("stepC", loc)[1] == data
    # Dropping ONE referencing step keeps the pooled chunk.
    assert cache.evict_step("stepA")
    assert cache.get("stepB", loc)[1] == data
    assert cache.stats()["bytes"] == bytes_after_one
    # Dropping the last reference frees the bytes.
    assert cache.evict_step("stepB")
    assert cache.get("stepB", loc) is None
    assert cache.stats()["bytes"] == 0


def test_peer_transport_refchunks_roundtrip():
    from torchsnapshot_tpu.scheduler import PeerCacheBudget
    from torchsnapshot_tpu.tiered.peer import (
        PeerCache,
        PeerClient,
        _PeerServer,
    )

    cache = PeerCache(budget=PeerCacheBudget(1 << 20))
    server = _PeerServer(("127.0.0.1", 0), cache)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        host, port = server.server_address
        client = PeerClient(host, port, timeout=10.0)
        data = b"z" * 512
        entry = compute_checksum_entry(data)
        loc = chunk_location(digest_key(entry))
        assert client.push("s1", 1, loc, entry, data) == (True, "ok")
        client.commit("s1", 1)
        # Inventory-by-digest: the next step's pusher learns the chunk
        # is already held and ships nothing.
        assert client.reference_chunks("s2", 2, [loc]) == [loc]
        assert client.reference_chunks("s2", 2, ["../chunks/cas-nope"]) == []
        got = client.pull("s2", loc)
        assert got is not None and bytes(got[1]) == data
        assert loc in client.list_step("s2")
        client.close()
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# ledger accounting + the dedup-ineffective doctor rule
# ---------------------------------------------------------------------------


def test_ledger_step_committed_cas_accounting(tmp_path):
    from torchsnapshot_tpu.telemetry import names as tn
    from torchsnapshot_tpu.telemetry.ledger import load_ledger

    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.enable_ledger():
        mgr = ts.CheckpointManager(root, keep_last_n=5)
        mgr.save(0, _state(offset=4.0), record_digests=True)
        mgr.save(1, _state(offset=4.0), record_digests=True)
    records = load_ledger(os.path.join(root, ".ledger.jsonl"))
    committed = [
        r for r in records if r.get("event") == tn.EVENT_STEP_COMMITTED
    ]
    assert len(committed) == 2
    first, second = committed
    assert first["cas"] and second["cas"]
    assert first["bytes_reused"] == 0
    assert first["bytes_new"] == first["bytes_total"] > 0
    # The identical second step is pure reuse — the EXACT accounting
    # the prefix heuristic could never produce for chunk refs.
    assert second["bytes_new"] == 0
    assert second["bytes_reused"] == second["bytes_total"] > 0
    assert second["chunks_new"] == 0 and second["chunks_reused"] > 0
    # Digest evidence: the unchanged state is fully digest-covered.
    assert second["bytes_digest_unchanged"] > 0
    assert (
        second["bytes_digest_unchanged"] == second["bytes_digest_covered"]
    )


def _step_record(step, total, reused, unchanged, covered, cas=True):
    from torchsnapshot_tpu.telemetry import names as tn

    return {
        "event": tn.EVENT_STEP_COMMITTED,
        "step": step,
        "cas": cas,
        "bytes_total": total,
        "bytes_new": total - reused,
        "bytes_reused": reused,
        "bytes_digest_unchanged": unchanged,
        "bytes_digest_covered": covered,
    }


def test_dedup_ineffective_rule_fires_and_stays_quiet():
    from torchsnapshot_tpu.telemetry import names as tn
    from torchsnapshot_tpu.telemetry.doctor import (
        Evidence,
        diagnose_evidence,
    )

    def verdicts(records):
        ev = Evidence(
            path="/r", ledger_records=records, ledger_file="/r/.ledger.jsonl"
        )
        return [
            v
            for v in diagnose_evidence(ev)
            if v.rule == tn.RULE_DEDUP_INEFFECTIVE
        ]

    # Broken dedup: digests say ~90% unchanged, reuse ~0 across the
    # window -> fires, citing the records.
    bad = [
        _step_record(i, 1000, 0, 900, 1000) for i in range(4)
    ]
    out = verdicts(bad)
    assert len(out) == 1
    assert out[0].evidence["reuse_fraction"] == 0.0
    assert out[0].evidence["digest_unchanged_fraction"] == 0.9
    # Healthy dedup (unchanged bytes ARE reused) -> quiet.
    assert verdicts(
        [_step_record(i, 1000, 900, 900, 1000) for i in range(4)]
    ) == []
    # Genuinely-changing state (digests agree nothing holds) -> quiet.
    assert verdicts(
        [_step_record(i, 1000, 0, 50, 1000) for i in range(4)]
    ) == []
    # No digest coverage -> cannot claim the state was static -> quiet.
    assert verdicts(
        [_step_record(i, 1000, 0, 0, 0) for i in range(4)]
    ) == []
    # Too few CAS records -> quiet.
    assert verdicts([_step_record(0, 1000, 0, 900, 1000)]) == []
    # Legacy records never trigger it.
    assert verdicts(
        [_step_record(i, 1000, 0, 900, 1000, cas=False) for i in range(4)]
    ) == []


# ---------------------------------------------------------------------------
# review-hardening regressions: durable-side repair + stray GC + tier audit
# ---------------------------------------------------------------------------


def test_mirror_reships_deduped_chunk_missing_from_durable(tmp_path):
    """A dedup hit writes nothing, but the step's durability claim
    still covers the chunk: if the original writer's mirror never
    landed it (crash before commit, manual durable-tier damage), the
    next referencing step's mirror job must ship it — the deduped
    chunk rides the job and the durable probe decides."""
    from torchsnapshot_tpu.tiered.mirror import reset_mirror

    fast = str(tmp_path / "fast")
    dur = str(tmp_path / "dur")
    root = f"tiered://{fast}/ckpt|{dur}/ckpt"
    reset_mirror()
    try:
        with knobs.enable_cas():
            mgr = ts.CheckpointManager(root, keep_last_n=4)
            mgr.save(0, _state(offset=8.0))
            mgr.wait_durable(0)
            dchunks = os.path.join(dur, "ckpt", "chunks")
            victim = sorted(_chunk_files(os.path.join(dur, "ckpt")))[0]
            os.remove(os.path.join(dchunks, victim))
            mgr.save(1, _state(offset=8.0))  # identical: pure dedup
            mgr.wait_durable(1)
            assert victim in _chunk_files(os.path.join(dur, "ckpt"))
        # The repaired durable tier alone restores the step.
        import shutil

        shutil.rmtree(fast)
        dest = _state()
        mgr2 = ts.CheckpointManager(root, keep_last_n=4)
        assert mgr2.restore_latest(dest) == 1
        np.testing.assert_array_equal(
            dest["m"].tree["w"], _state(offset=8.0)["m"].tree["w"]
        )
    finally:
        reset_mirror()


def test_mirror_reships_torn_durable_chunk(tmp_path):
    """The durable existence probe is size-verified (the key embeds
    nbytes, the probe reads the LAST byte): a truncated durable copy —
    a crash mid-upload; fs writes have no temp+rename — misses the
    probe and is overwritten whole instead of being trusted forever."""
    from torchsnapshot_tpu.tiered.mirror import reset_mirror

    fast = str(tmp_path / "fast")
    dur = str(tmp_path / "dur")
    root = f"tiered://{fast}/ckpt|{dur}/ckpt"
    reset_mirror()
    try:
        with knobs.enable_cas():
            mgr = ts.CheckpointManager(root, keep_last_n=4)
            mgr.save(0, _state(offset=9.0))
            mgr.wait_durable(0)
            dchunks = os.path.join(dur, "ckpt", "chunks")
            victim = sorted(_chunk_files(os.path.join(dur, "ckpt")))[0]
            want = nbytes_of_key(victim)
            with open(os.path.join(dchunks, victim), "r+b") as f:
                f.truncate(want // 2)  # torn upload
            mgr.save(1, _state(offset=9.0))
            mgr.wait_durable(1)
            assert (
                os.path.getsize(os.path.join(dchunks, victim)) == want
            )
    finally:
        reset_mirror()


def test_gc_sweeps_stray_unpinned_chunks(tmp_path):
    """Chunks in NO pin and NO orphan record (a take that crashed
    before its commit pinned them) still become GC candidates via the
    on-disk stray sweep — they age through the grace window like any
    orphan instead of leaking forever."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(0):
        mgr = ts.CheckpointManager(root, keep_last_n=1)
        mgr.save(0, _state(offset=0.0))
        # Simulate a crashed take: chunk bytes on disk, never pinned.
        stray = digest_key(compute_checksum_entry(b"crashed take bytes"))
        stray_path = os.path.join(root, "chunks", stray)
        open(stray_path, "wb").write(b"crashed take bytes")
        mgr.save(1, _state(offset=1.0))  # retention GC pass runs
        assert not os.path.exists(stray_path)
        # Live chunks were untouched.
        dest = _state()
        assert mgr.restore_latest(dest) == 1


def test_gc_stray_sweep_defers_fresh_chunks(tmp_path):
    """The stray sweep must not reclaim a concurrent in-flight take's
    freshly-written (not yet pinned) chunks: inside the grace window a
    stray is deferred as a journaled orphan; the take's commit pin
    revives it."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(3600):
        mgr = ts.CheckpointManager(root, keep_last_n=1)
        mgr.save(0, _state(offset=0.0))
        inflight = digest_key(compute_checksum_entry(b"in-flight bytes"))
        inflight_path = os.path.join(root, "chunks", inflight)
        open(inflight_path, "wb").write(b"in-flight bytes")
        mgr.save(1, _state(offset=1.0))
        assert os.path.exists(inflight_path)  # deferred, not reclaimed
        store = CASStore(root)
        _pins, orphans = store.load()
        assert inflight in orphans
        # The "in-flight take" commits: its pin revives the chunk.
        store.pin(99, {inflight: len(b"in-flight bytes")})
        store.clear_orphans([inflight])
        _pins, orphans = store.load()
        assert inflight not in orphans


def test_fsck_cas_flags_torn_copy_in_one_tier(tmp_path):
    """Per-tier size audit: a truncated chunk copy on ONE tier is a
    finding even when the other tier holds the full bytes — collapsing
    sizes with max() would pass a root whose durable tier alone is
    unrestorable."""
    from torchsnapshot_tpu.fsck import verify_cas_store
    from torchsnapshot_tpu.tiered.mirror import reset_mirror

    fast = str(tmp_path / "fast")
    dur = str(tmp_path / "dur")
    root = f"tiered://{fast}/ckpt|{dur}/ckpt"
    reset_mirror()
    try:
        with knobs.enable_cas():
            mgr = ts.CheckpointManager(root, keep_last_n=4)
            mgr.save(0, _state(offset=11.0))
            mgr.wait_durable(0)
    finally:
        reset_mirror()
    assert verify_cas_store(root).ok
    victim = sorted(_chunk_files(os.path.join(dur, "ckpt")))[0]
    dcopy = os.path.join(dur, "ckpt", "chunks", victim)
    with open(dcopy, "r+b") as f:
        f.truncate(nbytes_of_key(victim) // 2)
    report = verify_cas_store(root)
    assert any(
        p.kind == "truncated"
        and victim in p.location
        and os.path.join(dur, "ckpt", "chunks") in p.detail
        for p in report.problems
    )


def test_reconcile_heals_partially_lost_pin(tmp_path):
    """Partial journal damage: one committed step's pin record lost
    while OTHER pins survive. Reconcile must re-derive the missing pin
    from that step's manifest — otherwise the stray sweep would reclaim
    a committed step's chunks once they aged past the grace window."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(0):
        mgr = ts.CheckpointManager(root, keep_last_n=3)
        mgr.save(0, _state(offset=0.0))
        mgr.save(1, _state(offset=1.0))
        del mgr
        # Drop ONLY step 0's pin (rewrite the journal without it).
        store = CASStore(root)
        pins, orphans = store.load()
        assert sorted(pins) == [0, 1]
        step0_chunks = set(pins.pop(0))
        store.compact(pins, orphans)
        # Next construction heals the missing pin from the manifest...
        mgr2 = ts.CheckpointManager(root, keep_last_n=3)
        pins, _ = CASStore(root).load()
        assert sorted(pins) == [0, 1]
        assert set(pins[0]) == step0_chunks
        # ...so a GC pass (runs on every commit) cannot touch step 0.
        mgr2.save(2, _state(offset=2.0))
        assert step0_chunks <= set(_chunk_files(root))
        dest = _state()
        ts.Snapshot(mgr2.step_path(0)).restore(dest)
        np.testing.assert_array_equal(
            dest["m"].tree["w"], _state(offset=0.0)["m"].tree["w"]
        )


def test_gc_runs_without_retention_deletes(tmp_path):
    """Chunk GC rides EVERY commit, not only ones that dropped steps:
    a keep-everything manager still reclaims crashed takes' strays and
    aged-out orphans."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(0):
        mgr = ts.CheckpointManager(root)  # no retention: never deletes
        mgr.save(0, _state(offset=0.0))
        stray = digest_key(compute_checksum_entry(b"crashed take bytes"))
        stray_path = os.path.join(root, "chunks", stray)
        open(stray_path, "wb").write(b"crashed take bytes")
        mgr.save(1, _state(offset=1.0))  # drops nothing
        assert not os.path.exists(stray_path)
        dest = _state()
        assert mgr.restore_latest(dest) == 1
