"""Peer-RAM checkpoint tier (tiered/peer.py, docs/peer.md).

Unit coverage for the cache/budget/transport, in-process integration of
the take-side push hook and the restore-side peer -> fast -> durable
ladder (including every degradation mode: dead peer, stale step,
checksum mismatch, budget overflow, kill switch), the
``peer-tier-degraded`` doctor rule, ``fsck --tier peer``, and the
2-process preemption-recovery acceptance harness: after a simulated
single-rank preemption the replacement's restore is served >= 95% of
its bytes from the surviving peer's RAM with zero data-blob storage
reads, ledger-verified.
"""

import glob
import os
import tempfile
import threading
import time

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.dist_store import InProcessStore, publish_endpoint
from torchsnapshot_tpu.integrity import compute_checksum_entry
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.scheduler import PeerCacheBudget
from torchsnapshot_tpu.telemetry import names as metric_names
from torchsnapshot_tpu.telemetry.doctor import diagnose_reports
from torchsnapshot_tpu.test_utils import (
    faulty_fs_plugin,
    multiprocess_test,
    patch_storage_plugin,
)
from torchsnapshot_tpu.tiered import peer


# ---------------------------------------------------------------------------
# Unit: budget + cache + transport
# ---------------------------------------------------------------------------


def test_peer_cache_budget_reserve_release_refuse() -> None:
    budget = PeerCacheBudget(100)
    assert budget.try_reserve(60)
    assert not budget.try_reserve(50)
    assert budget.try_reserve(40)
    assert budget.reserved_bytes() == 100
    assert budget.peak_reserved_bytes == 100
    budget.release(60)
    assert budget.reserved_bytes() == 40
    assert budget.try_reserve(50)


def test_peer_cache_lru_eviction_pins_newest_committed() -> None:
    cache = peer.PeerCache(budget=PeerCacheBudget(100), keep_last_n=2)
    entry = compute_checksum_entry(b"x" * 40)
    assert cache.put("s1", 1, "a", entry, b"x" * 40)[0]
    cache.commit("s1", 1)
    assert cache.put("s2", 2, "a", entry, b"x" * 40)[0]
    cache.commit("s2", 2)
    # keep_last_n=2 retains both; a third step's put must evict the
    # LRU (s1) but never the pinned newest committed (s2).
    assert cache.put("s3", 3, "a", entry, b"x" * 40)[0]
    assert cache.get("s1", "a") is None
    assert cache.get("s2", "a") is not None
    assert cache.get("s3", "a") is not None
    # An oversized put that cannot fit even after evicting everything
    # unpinned is REFUSED with the budget reason, cache intact.
    ok, reason = cache.put(
        "s4", 4, "big", compute_checksum_entry(b"y" * 90), b"y" * 90
    )
    assert (ok, reason) == (False, "budget")
    assert cache.get("s2", "a") is not None


def test_peer_cache_empty_commit_does_not_steal_pin_or_evict() -> None:
    """A commit for a step whose pushes all failed/were refused must
    not steal the pin from (or retention-evict) the last step that
    actually holds bytes — that copy is the one a replacement rank can
    still use."""
    cache = peer.PeerCache(budget=PeerCacheBudget(100), keep_last_n=1)
    entry = compute_checksum_entry(b"x" * 40)
    assert cache.put("s1", 1, "a", entry, b"x" * 40)[0]
    cache.commit("s1", 1)
    cache.commit("s2", 2)  # empty step: every push was refused
    assert cache.stats()["pinned"] == "s1"
    assert cache.get("s1", "a") is not None
    # A blob larger than the WHOLE budget is refused up front — no
    # collateral eviction of steps that could never have made it fit.
    ok, reason = cache.put(
        "s3", 3, "huge", compute_checksum_entry(b"y" * 200), b"y" * 200
    )
    assert (ok, reason) == (False, "budget")
    assert cache.get("s1", "a") is not None


def test_peer_cache_keep_last_n_commit_eviction() -> None:
    cache = peer.PeerCache(budget=PeerCacheBudget(10**6), keep_last_n=1)
    entry = compute_checksum_entry(b"z")
    for i, key in enumerate(("s1", "s2", "s3")):
        assert cache.put(key, i, "a", entry, b"z")[0]
        cache.commit(key, i)
    stats = cache.stats()
    assert stats["committed_steps"] == ["s3"]
    assert cache.get("s1", "a") is None and cache.get("s2", "a") is None


def _serve(cache: peer.PeerCache):
    server = peer._PeerServer(("127.0.0.1", 0), cache)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def test_peer_transport_roundtrip_and_dead_endpoint() -> None:
    cache = peer.PeerCache(budget=PeerCacheBudget(10**6))
    server = _serve(cache)
    try:
        client = peer.PeerClient(
            "127.0.0.1", server.server_address[1], timeout=5
        )
        entry = compute_checksum_entry(b"hello")
        # The typed liveness probe: a full request/response round trip
        # through the dispatch loop (the RPC_PEER_PING handler's paired
        # client side).
        assert client.ping() is True
        assert client.push("s", 0, "blob", entry, b"hello") == (True, "ok")
        client.commit("s", 0)
        assert sorted(client.list_step("s")) == ["blob"]
        got = client.pull("s", "blob")
        assert got is not None and bytes(got[1]) == b"hello"
        assert client.pull("s", "absent") is None
        assert client.pull("stale-step", "blob") is None
        assert client.evict("s") and client.list_step("s") == {}
        client.close()
    finally:
        server.shutdown()
        server.server_close()
    # A dead endpoint fails FAST (bounded by the transfer timeout),
    # never a hang.
    t0 = time.monotonic()
    dead = peer.PeerClient("127.0.0.1", 1, timeout=0.5)
    with pytest.raises(peer.PeerTransferError):
        dead.request("ping")
    # ping() maps transport failure to False instead of raising.
    assert dead.ping() is False
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# In-process integration: push hook + restore ladder + degradation
# ---------------------------------------------------------------------------


class _FakeWorld:
    """This process as rank 0 of a 2-rank world: the real replicator
    singleton configured against an in-process store, plus a standalone
    'rank 1' cache server — the surviving-peer stand-in every
    degradation scenario manipulates."""

    def __init__(self, budget_bytes: int = 1 << 30):
        self.store = InProcessStore()
        self.rep = peer.get_replicator()
        assert self.rep.configure(self.store, rank=0, world_size=2)
        self.rank1_cache = peer.PeerCache(
            budget=PeerCacheBudget(budget_bytes)
        )
        self.rank1_server = _serve(self.rank1_cache)
        publish_endpoint(
            self.store,
            peer.PEER_SERVICE,
            1,
            "127.0.0.1",
            self.rank1_server.server_address[1],
        )

    def close(self):
        peer.reset_peer_tier()
        try:
            self.rank1_server.shutdown()
            self.rank1_server.server_close()
        except OSError:
            pass


@pytest.fixture
def fake_world():
    with knobs.enable_peer_tier():
        world = _FakeWorld()
        try:
            yield world
        finally:
            world.close()


def _take(path: str, n: int = 50_000):
    state = {"m": ts.PyTreeState({"w": np.arange(n, dtype=np.float32)})}
    ts.Snapshot.take(path, state)
    return state


def _restore_and_verify(path: str, n: int = 50_000):
    dest = {"m": ts.PyTreeState({"w": np.zeros(n, dtype=np.float32)})}
    ts.Snapshot(path).restore(dest)
    np.testing.assert_array_equal(
        dest["m"].tree["w"], np.arange(n, dtype=np.float32)
    )
    return telemetry.last_report("restore", path=path)


def test_take_pushes_and_restore_serves_from_peer(fake_world, tmp_path):
    path = str(tmp_path / "snap")
    _take(path)
    assert fake_world.rep.drain(timeout=60)
    assert fake_world.rank1_cache.stats()["blobs"] > 0
    assert not fake_world.rep.degraded
    # Placement journal written next to the snapshot.
    assert os.path.exists(
        os.path.join(path, peer.placement_doc_path(0))
    )
    # Delete every data blob from storage: ONLY peer RAM can serve
    # them now — the replacement-rank scenario in one process.
    for blob in glob.glob(os.path.join(path, "m", "*")):
        os.remove(blob)
    report = _restore_and_verify(path)
    assert report.tier_split is not None
    total = sum(report.tier_split.values())
    assert report.tier_split["peer"] / total >= 0.95
    assert report.peer["failures"] == 0
    assert report.peer["fallthrough_bytes"] == 0
    # Healthy peer-served restore: the degradation rule stays quiet.
    assert not [
        v
        for v in diagnose_reports([report.to_dict()])
        if v.rule == metric_names.RULE_PEER_TIER_DEGRADED
    ]


def test_ranged_pull_slices_server_side_and_verifies() -> None:
    """A ranged read of a paged blob ships only the window over the
    socket (verified via the covered page digests); a window covering
    no full page falls back to one whole-blob verified transfer; a
    corrupted cache page is refused either way."""
    from torchsnapshot_tpu.integrity import PAGE_SIZE, compute_checksum_entry

    data = (bytes(range(256)) * ((2 * PAGE_SIZE) // 256 + 1))[
        : 2 * PAGE_SIZE + 1024
    ]
    entry = compute_checksum_entry(data)
    assert len(entry) >= 5  # paged
    cache = peer.PeerCache(budget=PeerCacheBudget(len(data) * 2))
    server = _serve(cache)
    try:
        endpoint = ("127.0.0.1", server.server_address[1])
        client = peer.PeerClient(*endpoint, timeout=10)
        assert client.push("s", 0, "blob", entry, data)[0]
        client.close()
        ctx = peer.PeerRestoreContext(
            {"blob": (1, endpoint, entry)}, "s", timeout=10
        )
        # Page-aligned window: server-side slice, page-digest verified.
        out = ctx.pull("blob", (PAGE_SIZE, 2 * PAGE_SIZE))
        assert out == data[PAGE_SIZE : 2 * PAGE_SIZE]
        # Sub-page window: whole-blob fallback, still exactly the window.
        out2 = ctx.pull("blob", (10, 100))
        assert out2 == data[10:100]
        assert ctx.peer_failures == 0
        # Corrupt the cached bytes: both shapes refuse and miss.
        with cache._lock:
            slot = cache._steps["s"]
            slot.blobs["blob"] = (entry, b"\x00" * len(data))
        assert ctx.pull("blob", (PAGE_SIZE, 2 * PAGE_SIZE)) is None
        assert ctx.pull("blob", None) is None
        assert ctx.peer_failures >= 2
        ctx.close()
    finally:
        server.shutdown()
        server.server_close()


def test_tiered_root_local_fast_short_circuits_peer(fake_world, tmp_path):
    """On a tiered root, a blob still resident on the LOCAL fast tier
    is read from local disk — no interconnect traffic, no degradation
    flagged — and only once the fast copy is gone (the replacement-host
    case) does the same blob ride the peer tier."""
    fast = str(tmp_path / "fast")
    durable = str(tmp_path / "durable")
    path = f"tiered://{fast}|{durable}"
    _take(path)
    assert fake_world.rep.drain(timeout=60)
    assert fake_world.rank1_cache.stats()["blobs"] > 0
    report = _restore_and_verify(path)
    assert report.tier_split["fast"] > 0
    assert report.tier_split["peer"] == 0
    assert report.peer["failures"] == 0
    assert report.peer["fallthrough_bytes"] == 0  # a local hit is not
    # a degradation — the doctor rule stays quiet
    assert not [
        v
        for v in diagnose_reports([report.to_dict()])
        if v.rule == metric_names.RULE_PEER_TIER_DEGRADED
    ]
    # The replacement-host case: the fast-tier data is gone.
    removed = 0
    for blob in glob.glob(
        os.path.join(fast, "**", "m", "*"), recursive=True
    ):
        if os.path.isfile(blob):
            os.remove(blob)
            removed += 1
    assert removed > 0
    report2 = _restore_and_verify(path)
    assert report2.tier_split["peer"] > 0
    assert report2.tier_split["durable"] == 0  # zero durable-tier reads
    # for the peer-resident shards (metadata rode the intact fast tier)


def test_checksum_mismatch_falls_through_to_storage(fake_world, tmp_path):
    path = str(tmp_path / "snap")
    _take(path)
    assert fake_world.rep.drain(timeout=60)
    # Corrupt every cached byte payload on the peer (keep the recorded
    # entries): pulls verify against the inventory digests and MUST
    # refuse the bytes, falling through to intact storage.
    with fake_world.rank1_cache._lock:
        for slot in fake_world.rank1_cache._steps.values():
            slot.blobs = {
                p: (e, b"\x00" * len(d))
                for p, (e, d) in slot.blobs.items()
            }
    report = _restore_and_verify(path)
    assert report.peer["failures"] > 0
    assert report.tier_split["peer"] == 0
    assert report.peer["fallthrough_bytes"] > 0
    verdicts = [
        v
        for v in diagnose_reports([report.to_dict()])
        if v.rule == metric_names.RULE_PEER_TIER_DEGRADED
    ]
    assert verdicts, "degraded restore must raise peer-tier-degraded"
    assert verdicts[0].evidence["peer_failures"] > 0
    assert verdicts[0].evidence["durable_bytes"] > 0


def test_stale_step_misses_and_restores_from_storage(fake_world, tmp_path):
    path = str(tmp_path / "snap")
    _take(path)
    assert fake_world.rep.drain(timeout=60)
    # The peer only holds some OLDER step: evict this one entirely.
    fake_world.rank1_cache.evict_step(peer.peer_step_key(path))
    report = _restore_and_verify(path)
    # No peer holds the step -> no ladder at all (tier_split absent),
    # restore identical to the pre-peer path.
    assert report.tier_split is None


def test_budget_overflow_refuses_push_and_degrades(tmp_path):
    with knobs.enable_peer_tier():
        world = _FakeWorld(budget_bytes=64)  # nothing fits
        try:
            path = str(tmp_path / "snap")
            _take(path)
            assert world.rep.drain(timeout=60)
            assert world.rank1_cache.stats()["blobs"] == 0
            # The refusal is recorded in the placement journal and the
            # push counters; restore is storage-served and correct.
            report = _restore_and_verify(path)
            assert report.tier_split is None
            import json

            doc = json.loads(
                open(
                    os.path.join(path, peer.placement_doc_path(0))
                ).read()
            )
            assert doc["blobs_refused"] > 0
            # fsck --tier peer surfaces the degraded push.
            from torchsnapshot_tpu.fsck import verify_snapshot

            fsck_report = verify_snapshot(path, tier="peer")
            assert not fsck_report.ok
            assert any(
                p.kind in ("unmirrored", "missing")
                for p in fsck_report.problems
            )
        finally:
            world.close()


def test_dead_peer_mid_push_degrades_without_wedging(tmp_path):
    with knobs.override_peer_transfer_timeout_seconds(1.0):
        with knobs.enable_peer_tier():
            world = _FakeWorld()
            try:
                # Kill the peer BEFORE the push: the job must settle
                # degraded within a few transfer timeouts, never wedge.
                world.rank1_server.shutdown()
                world.rank1_server.server_close()
                path = str(tmp_path / "snap")
                t0 = time.monotonic()
                _take(path)
                assert world.rep.drain(timeout=30)
                assert time.monotonic() - t0 < 30.0
                assert world.rep.degraded
                report = _restore_and_verify(path)
                assert report.tier_split is None  # dead peer skipped
            finally:
                world.close()


def test_dead_peer_at_restore_falls_through(fake_world, tmp_path):
    path = str(tmp_path / "snap")
    _take(path)
    assert fake_world.rep.drain(timeout=60)
    fake_world.rank1_server.shutdown()
    fake_world.rank1_server.server_close()
    with knobs.override_peer_transfer_timeout_seconds(1.0):
        t0 = time.monotonic()
        report = _restore_and_verify(path)
        assert time.monotonic() - t0 < 30.0
    # Context build skipped the dead endpoint: storage-only restore.
    assert report.tier_split is None


def test_kill_switch_means_no_server_no_pushes(tmp_path):
    store = InProcessStore()
    with knobs.disable_peer_tier():
        assert not peer.maybe_configure(
            PGWrapper(None)
        )  # single-process is inert anyway
        assert peer.maybe_drain() is True
        path = str(tmp_path / "snap")
        _take(path)
        report = _restore_and_verify(path)
        assert report.tier_split is None
        assert not os.path.exists(
            os.path.join(path, peer.placement_doc_path(0))
        )
    assert store.try_get("__endpoint/peer-tier/0") is None


def test_fsck_tier_peer_reports_unplaced_blobs(fake_world, tmp_path):
    from torchsnapshot_tpu.fsck import verify_snapshot

    path = str(tmp_path / "snap")
    _take(path)
    assert fake_world.rep.drain(timeout=60)
    report = verify_snapshot(path, tier="peer")
    assert report.ok, [p.detail for p in report.problems]
    assert report.blobs_checked > 0
    # Remove the placement journal: every required blob is unplaced.
    os.remove(os.path.join(path, peer.placement_doc_path(0)))
    report = verify_snapshot(path, tier="peer")
    assert not report.ok
    assert any(p.kind == "missing" for p in report.problems)


def test_doctor_rule_quiet_on_takes_and_missing_fields() -> None:
    quiet = [
        {"kind": "take", "phases": {"staging": 1.0}},
        {"kind": "restore", "phases": {"loading": 1.0}},
        {
            "kind": "restore",
            "peer": {"failures": 0, "fallthrough_bytes": 0},
            "tier_split": {"peer": 100, "fast": 0, "durable": 0},
        },
    ]
    assert not [
        v
        for v in diagnose_reports(quiet)
        if v.rule == metric_names.RULE_PEER_TIER_DEGRADED
    ]


def test_preemption_close_flushes_peer_tier(fake_world, tmp_path) -> None:
    """PreemptionSaver.close() runs the built-in peer drain before any
    registered drain hook — the grace window ships the last delta."""
    from torchsnapshot_tpu.preemption import PreemptionSaver

    path = str(tmp_path / "snap")
    _take(path)
    order = []
    saver = PreemptionSaver(signals=())
    saver.register_drain(lambda: order.append("custom"))
    saver.close()
    assert order == ["custom"]
    # The push settled by close() time: the peer holds the step.
    assert fake_world.rank1_cache.stats()["blobs"] > 0


# ---------------------------------------------------------------------------
# 2-process acceptance harness: preemption recovery at host-RAM speed
# ---------------------------------------------------------------------------


def _data_blob(path: str) -> bool:
    return "/m/" in path or "batched" in path


@multiprocess_test(nproc=2)
def test_preemption_recovery_served_from_peer_ram(pg) -> None:
    """ISSUE 10 acceptance: after a simulated single-rank preemption,
    the replacement's restore is served >= 95% of its bytes from the
    surviving peer's RAM — zero data-blob storage reads — and the run
    ledger records the tier split; with the peer wiped the same harness
    completes correctly from storage."""
    import contextlib
    import shutil

    os.environ["TORCHSNAPSHOT_TPU_PEER_TIER"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_PEER_TRANSFER_TIMEOUT_SECONDS"] = "5"
    os.environ["TORCHSNAPSHOT_TPU_LEDGER"] = "1"

    root = os.path.join(tempfile.gettempdir(), "peer-accept")
    if pg.rank == 0:
        shutil.rmtree(root, ignore_errors=True)
    wrapper = PGWrapper(pg)
    wrapper.barrier()

    n = 200_000
    state = {
        "m": ts.PyTreeState(
            {"w": np.arange(n, dtype=np.float32) + pg.rank}
        )
    }
    mgr = ts.CheckpointManager(root, pg=pg)
    mgr.save(0, state)
    assert peer.maybe_drain(timeout=60)
    wrapper.barrier()

    if pg.rank == 1:
        # Simulated preemption of rank 1: the host died (peer cache and
        # process tier state gone); the replacement re-announces itself
        # under the same rank id.
        peer.reset_peer_tier()
        assert peer.maybe_configure(wrapper)
    wrapper.barrier()

    # The replacement restores behind a counting plugin: data-blob
    # reads from STORAGE must be zero — every data byte rides the
    # surviving peer's RAM.
    storage_data_reads = []

    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    class _Counting(FSStoragePlugin):
        async def read(self, read_io):
            if _data_blob(read_io.path):
                storage_data_reads.append(read_io.path)
            await super().read(read_io)

    ctx = (
        patch_storage_plugin(_Counting)
        if pg.rank == 1
        else contextlib.nullcontext()
    )
    dest = {"m": ts.PyTreeState({"w": np.zeros(n, dtype=np.float32)})}
    with ctx:
        restored = mgr.restore_latest(dest)
    assert restored == 0
    np.testing.assert_array_equal(
        dest["m"].tree["w"], np.arange(n, dtype=np.float32) + pg.rank
    )
    report = telemetry.last_report("restore", path=mgr.step_path(0))
    if pg.rank == 1:
        assert not storage_data_reads, storage_data_reads
        assert report.tier_split is not None
        total = sum(report.tier_split.values())
        assert report.tier_split["peer"] / total >= 0.95, report.tier_split
        assert report.peer["failures"] == 0
    wrapper.barrier()
    if pg.rank == 0:
        # Ledger-verified tier split: the restore-served event carries
        # the WORLD's per-tier byte map (the replacement's peer bytes
        # included) and names the dominant tier.
        from torchsnapshot_tpu.telemetry.ledger import (
            ledger_path_for,
            load_ledger,
        )

        records = load_ledger(ledger_path_for(root))
        served = [
            r for r in records if r.get("event") == "restore-served"
        ]
        assert served, records
        tier_split = served[-1].get("tier_split")
        assert tier_split and tier_split.get("peer", 0) >= int(
            0.95 * n * 4
        ), served[-1]
        assert "tier" in served[-1]
    wrapper.barrier()

    # Degraded rerun: wipe BOTH peer caches (double preemption) — the
    # same restore completes correctly from storage alone.
    peer.reset_peer_tier()
    assert peer.maybe_configure(wrapper)
    wrapper.barrier()
    dest2 = {"m": ts.PyTreeState({"w": np.zeros(n, dtype=np.float32)})}
    assert mgr.restore_latest(dest2) == 0
    np.testing.assert_array_equal(
        dest2["m"].tree["w"], np.arange(n, dtype=np.float32) + pg.rank
    )
    report2 = telemetry.last_report("restore", path=mgr.step_path(0))
    assert report2.tier_split is None  # nothing peer-resident: no ladder
    peer.reset_peer_tier()


def test_restore_setup_endpoint_resolve_is_one_round_trip() -> None:
    """Satellite pin: the peer registry resolve the restore setup rides
    (``PeerReplicator.resolve_endpoints`` -> ``lookup_endpoints``)
    costs ONE batched store round trip for the whole world — not world
    sequential lookups — and skips unpublished/garbage entries."""
    from torchsnapshot_tpu.dist_store import InProcessStore, publish_endpoint
    from torchsnapshot_tpu.scalemodel import CountingStore
    from torchsnapshot_tpu.tiered.peer import PEER_SERVICE, PeerReplicator

    inner = InProcessStore()
    world = 32
    for rank in range(world):
        if rank == 9:
            continue  # never published (dead before configure)
        publish_endpoint(inner, PEER_SERVICE, rank, "h", 7000 + rank)
    inner.set(f"__endpoint/{PEER_SERVICE}/5", b"garbage-no-port")
    counting = CountingStore(inner)
    rep = PeerReplicator()
    rep._store = counting
    endpoints = rep.resolve_endpoints(range(world))
    assert counting.counts == {"multi_get": 1}
    assert set(endpoints) == set(range(world)) - {9, 5}
    assert endpoints[0] == ("h", 7000)
