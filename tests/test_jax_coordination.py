"""JaxCoordinationStore + jax_process_group over a real (single-process)
jax.distributed runtime.

Reference analog: tests/test_dist_store.py's TCPStore coverage — here the
store rides the JAX coordination service instead, the path multi-host TPU
pods use (SURVEY.md §2.11 TPU-equivalent). jax.distributed.initialize is
process-global and irreversible, so the exercise runs in a spawned worker
(the harness pins workers to the CPU backend).
"""

import pytest

from torchsnapshot_tpu.test_utils import run_multiprocess


def _jaxlib_has_kv_try_get() -> bool:
    """The store's absent-key probe needs ``key_value_try_get_bytes``
    on the distributed runtime client; older jaxlibs (this container's
    included) ship the KV API without it, and JaxCoordinationStore
    refuses to construct there (directing users at TCPStore). Skip
    rather than carry a known-red environment failure."""
    try:
        import jaxlib.xla_extension as xe

        return hasattr(
            xe.DistributedRuntimeClient, "key_value_try_get_bytes"
        )
    except Exception:  # noqa: BLE001 - no probe = assume modern jaxlib
        return True


pytestmark = pytest.mark.skipif(
    not _jaxlib_has_kv_try_get(),
    reason="jaxlib's DistributedRuntimeClient lacks "
    "key_value_try_get_bytes; JaxCoordinationStore cannot serve here "
    "(TCPStore coordination is the supported path)",
)


def _jax_coordination_worker(pg, port: int):
    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=1, process_id=0
    )
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.dist_store import (
        JaxCoordinationStore,
        LinearBarrier,
        jax_process_group,
    )

    store = JaxCoordinationStore()
    # KV primitives.
    store.set("k1", b"value-1")
    assert store.try_get("k1") == b"value-1"
    assert store.try_get("missing") is None
    store.delete("k1")
    assert store.try_get("k1") is None

    counters_ok = True
    try:
        assert store.add("ctr", 2) == 2
        assert store.add("ctr", 3) == 5
    except NotImplementedError:
        counters_ok = False  # older jaxlib: documented degradation

    # Object collectives (world 1 semantics still run real KV traffic).
    if counters_ok:
        assert store.exchange("ex", 0, 1, {"x": 1}) == [{"x": 1}]
        assert store.broadcast("bc", 0, 1, "hello") == "hello"
        barrier = LinearBarrier("b", store, rank=0, world_size=1)
        barrier.arrive()
        barrier.depart()

    # The convenience pg threads through the Snapshot API (world size 1
    # short-circuits collectives, so KV coverage comes from the block
    # above; this asserts construction + end-to-end compatibility).
    jpg = jax_process_group()
    assert jpg.rank == 0 and jpg.world_size == 1
    import tempfile

    path = tempfile.mkdtemp(prefix="ts_jaxcoord_")
    arr = np.arange(16.0)
    ts.Snapshot.take(path, {"s": ts.PyTreeState({"w": arr})}, pg=jpg)
    dst = {"s": ts.PyTreeState({"w": np.zeros(16)})}
    ts.Snapshot(path, pg=jpg).restore(dst)
    np.testing.assert_array_equal(dst["s"].tree["w"], arr)
    return counters_ok


def test_jax_coordination_store() -> None:
    # Allocate the coordinator port and the harness TCPStore port from two
    # simultaneously-bound sockets: sequential get_free_port() calls can
    # return the same just-released port.
    import socket

    with socket.socket() as s1, socket.socket() as s2:
        s1.bind(("127.0.0.1", 0))
        s2.bind(("127.0.0.1", 0))
        coord_port = s1.getsockname()[1]
        store_port = s2.getsockname()[1]

    [counters_ok] = run_multiprocess(
        _jax_coordination_worker, nproc=1, args=(coord_port,), port=store_port
    )
    assert isinstance(counters_ok, bool)


def _jax_dist2_worker(pg, coord_port: int, root: str):
    """A genuine 2-process jax.distributed job: the coordination service
    carries ALL snapshot metadata traffic (key gathers, replication
    verification, partitioning, manifest gather, commit barrier)."""
    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=2,
        process_id=pg.rank,
    )
    import numpy as np

    import torchsnapshot_tpu as ts
    from torchsnapshot_tpu.dist_store import jax_process_group

    jpg = jax_process_group()
    assert jpg.world_size == 2 and jpg.rank == pg.rank

    state = {
        "shared": ts.PyTreeState({"w": np.full((64, 4), 2.5, np.float32)}),
        "mine": ts.StateDict(rank_val=40 + pg.rank),
    }
    snap = ts.Snapshot.take(
        root, state, pg=jpg, replicated=["shared/**"]
    )
    md = snap.metadata
    assert md.world_size == 2
    assert md.manifest["0/shared/w"].replicated
    assert "1/shared/w" not in md.manifest

    dst = {
        "shared": ts.PyTreeState({"w": np.zeros((64, 4), np.float32)}),
        "mine": ts.StateDict(rank_val=-1),
    }
    ts.Snapshot(root, pg=jpg).restore(dst)
    assert float(dst["shared"].tree["w"][3, 3]) == 2.5
    assert dst["mine"]["rank_val"] == 40 + pg.rank

    # Preemption agreement over the SAME coordination service (the pod
    # path): an eviction notice on rank 1 only; both ranks must save the
    # same step through the manager.
    from torchsnapshot_tpu.test_utils import drive_preemption_loop

    mgr = ts.CheckpointManager(root + "_mgr", pg=jpg)
    saver = ts.PreemptionSaver(jpg, signals=(), poll_interval=0.1)
    saved_at = drive_preemption_loop(
        jpg,
        saver,
        lambda step: mgr.save(step, {"s": ts.StateDict(step=step)}),
        evict_rank=1,
    )
    assert saved_at is not None
    return saved_at


def test_two_process_jax_distributed_snapshot(tmp_path) -> None:
    import socket

    with socket.socket() as s1, socket.socket() as s2:
        s1.bind(("127.0.0.1", 0))
        s2.bind(("127.0.0.1", 0))
        coord_port = s1.getsockname()[1]
        store_port = s2.getsockname()[1]

    results = run_multiprocess(
        _jax_dist2_worker,
        nproc=2,
        args=(coord_port, str(tmp_path / "snap")),
        port=store_port,
    )
    # Both ranks agreed on one preemption-save step over the
    # coordination service.
    assert results[0] == results[1] and results[0] is not None, results


def test_constructor_probe_rejects_misclassifying_client() -> None:
    """The absent-key self-check (round 5): a jaxlib whose coordination
    client words the absent-key status in a way try_get cannot classify
    as NOT_FOUND must be rejected loudly AT CONSTRUCTION — otherwise
    every absent-key poll raises and, past the transient-read grace, all
    barriers and preemption polls fail on real pods with the cause
    (message wording) nowhere near the symptom."""
    from unittest import mock

    import pytest

    class WeirdClient:
        def key_value_try_get_bytes(self, key):
            raise ValueError("no such entry exists")  # not a NOT_FOUND token

    class _State:
        client = WeirdClient()

    with mock.patch("jax._src.distributed.global_state", _State()):
        from torchsnapshot_tpu.dist_store import JaxCoordinationStore

        with pytest.raises(RuntimeError, match="absent-key probe"):
            JaxCoordinationStore()


def test_constructor_probe_rejects_phantom_values() -> None:
    """A store returning a value for a never-set key has broken get
    semantics (e.g. a client echoing defaults); refuse it."""
    from unittest import mock

    import pytest

    class EchoClient:
        def key_value_try_get_bytes(self, key):
            return b"phantom"

    class _State:
        client = EchoClient()

    with mock.patch("jax._src.distributed.global_state", _State()):
        from torchsnapshot_tpu.dist_store import JaxCoordinationStore

        with pytest.raises(RuntimeError, match="never set"):
            JaxCoordinationStore()
