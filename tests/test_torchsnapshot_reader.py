"""Reading reference-format (TorchSnapshot 0.0.3) snapshots.

Two layers:

- Hand-built fixtures covering the full documented schema (reference
  manifest.py:27-290): every entry type, both serializers, byte_range
  slabs, sharded/chunked assembly, and the cross-rank availability rules
  — written by this test from the format spec, so the coverage holds
  even where the reference library itself cannot run.
- A live interop test that saves with the *actual* reference library
  (its source tree ships in this environment) and reads the result back
  with our reader — the end-to-end migration path, skipped gracefully
  when the reference import is unavailable.
"""

import base64
import os
import struct
from collections import OrderedDict

import numpy as np
import pytest

from interop_utils import import_reference
import yaml

from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
    ReferenceSnapshotReader,
    read_reference_snapshot,
)

ml_dtypes = pytest.importorskip("ml_dtypes")



def _write(path, data: bytes) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_bytes(data)


def _prim(kind: str, serialized: str, replicated=False) -> dict:
    return {
        "type": kind,
        "serialized_value": serialized,
        "replicated": replicated,
        "readable": None,
    }


def _tensor_entry(
    location: str, dtype: str, shape, serializer="buffer_protocol",
    replicated=False, byte_range=None,
) -> dict:
    return {
        "type": "Tensor",
        "location": location,
        "serializer": serializer,
        "dtype": dtype,
        "shape": list(shape),
        "replicated": replicated,
        "byte_range": byte_range,
    }


def _box(offsets, sizes, tensor: dict) -> dict:
    return {"offsets": list(offsets), "sizes": list(sizes), "tensor": tensor}


@pytest.fixture
def hand_built(tmp_path):
    """A world_size-2 snapshot written from the format spec alone."""
    rng = np.random.default_rng(0)
    f32 = rng.standard_normal((3, 4), dtype=np.float32)
    bf16 = rng.standard_normal((8,), dtype=np.float32).astype(ml_dtypes.bfloat16)
    slab_a = rng.standard_normal((4,), dtype=np.float32)
    slab_b = np.arange(6, dtype=np.int32)
    chunk_full = rng.standard_normal((6, 2), dtype=np.float32)
    shard_full = rng.standard_normal((4, 4), dtype=np.float32)
    repl = np.arange(5, dtype=np.int64)

    _write(tmp_path / "0/app/weights", f32.tobytes())
    _write(tmp_path / "0/app/halfs", bf16.tobytes())
    slab = slab_a.tobytes() + slab_b.tobytes()
    _write(tmp_path / "batched/slab0", slab)
    _write(tmp_path / "0/app/chunked_0_0", chunk_full[:3].tobytes())
    _write(tmp_path / "0/app/chunked_3_0", chunk_full[3:].tobytes())
    _write(tmp_path / "sharded/app/sharded_0", shard_full[:2].tobytes())
    _write(tmp_path / "sharded/app/sharded_1", shard_full[2:].tobytes())
    _write(tmp_path / "replicated/app/ids", repl.tobytes())

    manifest = {
        "0/app": {"type": "dict", "keys": [
            "weights", "halfs", "lst", "od", "n", "pi", "flag", "blob",
            "name", "chunked", 7,
        ]},
        "0/app/weights": _tensor_entry("0/app/weights", "torch.float32", (3, 4)),
        "0/app/halfs": _tensor_entry("0/app/halfs", "torch.bfloat16", (8,)),
        "0/app/lst": {"type": "list"},
        "0/app/lst/0": _tensor_entry(
            "batched/slab0", "torch.float32", (4,), byte_range=[0, 16]
        ),
        "0/app/lst/1": _tensor_entry(
            "batched/slab0", "torch.int32", (6,), byte_range=[16, 40]
        ),
        "0/app/od": {"type": "OrderedDict", "keys": ["b", "a"]},
        "0/app/od/b": _prim("int", "2"),
        "0/app/od/a": _prim("int", "1"),
        "0/app/n": _prim("int", "-42"),
        "0/app/pi": _prim(
            "float",
            base64.b64encode(struct.pack("d", 3.14159)).decode(),
        ),
        "0/app/flag": _prim("bool", "False"),
        "0/app/blob": _prim("bytes", base64.b64encode(b"\x00\xffhi").decode()),
        "0/app/name": _prim("str", "tpu"),
        "0/app/7": _prim("str", "int-key"),
        "0/app/chunked": {
            "type": "ChunkedTensor",
            "dtype": "torch.float32",
            "shape": [6, 2],
            "replicated": False,
            "chunks": [
                _box((0, 0), (3, 2), _tensor_entry(
                    "0/app/chunked_0_0", "torch.float32", (3, 2))),
                _box((3, 0), (3, 2), _tensor_entry(
                    "0/app/chunked_3_0", "torch.float32", (3, 2))),
            ],
        },
        # rank 0 holds shard 0, rank 1 shard 1 — reader must merge.
        "0/sh": {"type": "dict", "keys": ["emb"]},
        "0/sh/emb": {"type": "ShardedTensor", "shards": [
            _box((0, 0), (2, 4), _tensor_entry(
                "sharded/app/sharded_0", "torch.float32", (2, 4))),
        ]},
        "1/sh": {"type": "dict", "keys": ["emb"]},
        "1/sh/emb": {"type": "ShardedTensor", "shards": [
            _box((2, 0), (2, 4), _tensor_entry(
                "sharded/app/sharded_1", "torch.float32", (2, 4))),
        ]},
        # replicated entry recorded on rank 0 only (post-partitioning
        # form): must be available to rank 1 too, container chain included.
        "0/rep": {"type": "dict", "keys": ["ids"]},
        "0/rep/ids": _tensor_entry(
            "replicated/app/ids", "torch.int64", (5,), replicated=True
        ),
    }
    doc = {"version": "0.0.3", "world_size": 2, "manifest": manifest}
    (tmp_path / ".snapshot_metadata").write_text(
        yaml.safe_dump(doc, sort_keys=False)
    )
    return tmp_path, {
        "f32": f32, "bf16": bf16, "slab_a": slab_a, "slab_b": slab_b,
        "chunk_full": chunk_full, "shard_full": shard_full, "repl": repl,
    }


def test_read_state_rank0(hand_built):
    path, ref = hand_built
    state = read_reference_snapshot(str(path), rank=0)
    app = state["app"]
    np.testing.assert_array_equal(app["weights"], ref["f32"])
    assert app["halfs"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        app["halfs"].view(np.uint16), ref["bf16"].view(np.uint16)
    )
    np.testing.assert_array_equal(app["lst"][0], ref["slab_a"])
    np.testing.assert_array_equal(app["lst"][1], ref["slab_b"])
    assert isinstance(app["od"], OrderedDict)
    assert list(app["od"].items()) == [("b", 2), ("a", 1)]
    assert app["n"] == -42
    assert app["pi"] == struct.unpack("d", struct.pack("d", 3.14159))[0]
    assert app["flag"] is False
    assert app["blob"] == b"\x00\xffhi"
    assert app["name"] == "tpu"
    assert app[7] == "int-key"
    np.testing.assert_array_equal(app["chunked"], ref["chunk_full"])
    np.testing.assert_array_equal(state["sh"]["emb"], ref["shard_full"])
    np.testing.assert_array_equal(state["rep"]["ids"], ref["repl"])
    # Original dict key order preserved from the recorded keys.
    assert list(app.keys())[:3] == ["weights", "halfs", "lst"]


def test_rank1_sees_replicated_and_merged_sharded(hand_built):
    path, ref = hand_built
    state = read_reference_snapshot(str(path), rank=1)
    # own sharded entry merged with rank 0's shards -> full tensor
    np.testing.assert_array_equal(state["sh"]["emb"], ref["shard_full"])
    # replicated entry adopted from rank 0, container chain intact
    np.testing.assert_array_equal(state["rep"]["ids"], ref["repl"])
    # rank-0-private entries are NOT visible
    assert "app" not in state


def test_read_object_paths(hand_built):
    path, ref = hand_built
    reader = ReferenceSnapshotReader(str(path))
    assert reader.world_size == 2
    np.testing.assert_array_equal(
        reader.read_object("0/app/weights"), ref["f32"]
    )
    np.testing.assert_array_equal(
        reader.read_object("app/lst/1", rank=0), ref["slab_b"]
    )
    assert reader.read_object("0/app/od/a") == 1
    with pytest.raises(KeyError):
        reader.read_object("0/app/nope")


def test_buffer_protocol_snapshot_reads_without_torch(hand_built):
    """The module's promise: buffer_protocol entries decode with numpy
    alone. Pin it by reading the full fixture (which has no torch_save
    entries) in a subprocess where importing torch is poisoned."""
    import subprocess
    import sys

    path, _ = hand_built
    code = f"""
import sys
sys.modules["torch"] = None  # any torch import now raises ImportError
from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
    read_reference_snapshot,
)
state = read_reference_snapshot({str(path)!r})
assert state["app"]["weights"].shape == (3, 4)
assert state["app"]["n"] == -42
print("NO-TORCH OK")
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={
            **__import__("os").environ,
            "JAX_PLATFORMS": "cpu",
        },
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "NO-TORCH OK" in proc.stdout


def test_torch_save_entries(tmp_path):
    torch = pytest.importorskip("torch")
    t = torch.arange(12, dtype=torch.float64).reshape(3, 4)
    # Plain lists (incl. a list of tuples) inside the pickled object are
    # user data — inflation must not mistake them for its own
    # (index, value) accumulator lists.
    obj = {
        "vals": torch.ones(2, dtype=torch.bfloat16),
        "n": 5,
        "leaf_list": [1, 2, 3],
        "pairs": [(0, "a"), (1, "b")],
        # numpy payloads are rejected by torch>=2.6's weights_only
        # default — the reader must load the user's own checkpoint fully.
        "np_payload": np.arange(3),
    }
    import io as _io

    buf = _io.BytesIO()
    torch.save(t, buf)
    _write(tmp_path / "0/s/t", buf.getvalue())
    buf = _io.BytesIO()
    torch.save(obj, buf)
    _write(tmp_path / "0/s/o", buf.getvalue())
    manifest = {
        "0/s": {"type": "dict", "keys": ["t", "o"]},
        "0/s/t": _tensor_entry(
            "0/s/t", "torch.float64", (3, 4), serializer="torch_save"
        ),
        "0/s/o": {
            "type": "object",
            "location": "0/s/o",
            "serializer": "torch_save",
            "obj_type": "dict",
            "replicated": False,
        },
    }
    (tmp_path / ".snapshot_metadata").write_text(yaml.safe_dump(
        {"version": "0.0.3", "world_size": 1, "manifest": manifest},
        sort_keys=False,
    ))
    state = read_reference_snapshot(str(tmp_path))
    np.testing.assert_array_equal(state["s"]["t"], t.numpy())
    assert isinstance(state["s"]["o"]["vals"], np.ndarray)
    assert state["s"]["o"]["vals"].dtype == ml_dtypes.bfloat16
    assert state["s"]["o"]["n"] == 5
    assert state["s"]["o"]["leaf_list"] == [1, 2, 3]
    assert state["s"]["o"]["pairs"] == [(0, "a"), (1, "b")]
    np.testing.assert_array_equal(state["s"]["o"]["np_payload"], np.arange(3))


def test_qtensor_serializer_rejected_with_explanation(tmp_path):
    _write(tmp_path / "0/a/q", b"\x00" * 8)
    manifest = {
        "0/a": {"type": "dict", "keys": ["q"]},
        "0/a/q": _tensor_entry(
            "0/a/q", "torch.float32", (2,), serializer="per_tensor_qtensor"
        ),
    }
    (tmp_path / ".snapshot_metadata").write_text(yaml.safe_dump(
        {"version": "0.0.3", "world_size": 1, "manifest": manifest},
        sort_keys=False,
    ))
    with pytest.raises(NotImplementedError, match="torch_save"):
        read_reference_snapshot(str(tmp_path))


# ---------------------------------------------------------------------------
# Live interop: save with the actual reference library, read with ours.
# ---------------------------------------------------------------------------


def test_reference_library_interop(tmp_path):
    torch = pytest.importorskip("torch")
    torchsnapshot = import_reference()

    torch.manual_seed(3)
    app_state = {
        "model": torchsnapshot.StateDict(
            w=torch.randn(16, 8),
            halfs=torch.randn(32).to(torch.bfloat16),
            ints=torch.arange(10, dtype=torch.int32),
            nested={"bias": torch.zeros(8), "meta": {"epoch": 4}},
            lst=[1.5, "two", torch.ones(3, dtype=torch.float64)],
            flag=True,
            raw=b"\x01\x02",
        ),
        "progress": torchsnapshot.StateDict(step=17),
    }
    snap_dir = str(tmp_path / "ref_snap")
    torchsnapshot.Snapshot.take(snap_dir, app_state)

    state = read_reference_snapshot(snap_dir)
    model = state["model"]
    np.testing.assert_array_equal(model["w"], app_state["model"]["w"].numpy())
    assert model["halfs"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        model["halfs"].view(np.uint16),
        app_state["model"]["halfs"].view(torch.uint16).numpy(),
    )
    np.testing.assert_array_equal(
        model["ints"], app_state["model"]["ints"].numpy()
    )
    np.testing.assert_array_equal(
        model["nested"]["bias"], np.zeros(8, np.float32)
    )
    assert model["nested"]["meta"]["epoch"] == 4
    assert model["lst"][0] == 1.5
    assert model["lst"][1] == "two"
    np.testing.assert_array_equal(model["lst"][2], np.ones(3, np.float64))
    assert model["flag"] is True
    assert model["raw"] == b"\x01\x02"
    assert state["progress"]["step"] == 17


def test_reference_library_interop_hostile_keys(tmp_path):
    """Keys containing the path separator, percent signs, and int keys —
    the percent-encoding corners (reference flatten.py:204-211) — written
    by the actual reference library, decoded by our reader."""
    torch = pytest.importorskip("torch")
    torchsnapshot = import_reference()

    hostile = {
        "a/b": torch.ones(2),
        "100%": "percent",
        "%2F": "encoded-looking",
        7: torch.zeros(3),
        "plain": {"x/y%z": 1},
    }
    app_state = {"s": torchsnapshot.StateDict(**{"outer": hostile})}
    snap = str(tmp_path / "hostile")
    torchsnapshot.Snapshot.take(snap, app_state)

    state = read_reference_snapshot(snap)
    outer = state["s"]["outer"]
    np.testing.assert_array_equal(outer["a/b"], np.ones(2, np.float32))
    assert outer["100%"] == "percent"
    assert outer["%2F"] == "encoded-looking"
    np.testing.assert_array_equal(outer[7], np.zeros(3, np.float32))
    assert outer["plain"] == {"x/y%z": 1}


def test_reference_library_interop_real_sharded_tensor(tmp_path):
    """A ShardedTensor written by the ACTUAL reference library (the FSDP
    LOCAL_STATE_DICT / torchrec layout, SURVEY §2.12) — saved in a
    subprocess with its own gloo world so torch.distributed state never
    leaks into the test process — assembled and shard-placed by our
    reader."""
    import subprocess
    import sys as _sys

    pytest.importorskip("torch")
    import_reference()  # skip early if the reference is unavailable

    snap = str(tmp_path / "sharded_ref")
    code = f"""
import os, sys
sys.path.insert(0, "/root/reference")
import numpy as np, torch
import torch.distributed as dist
os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
os.environ.setdefault("MASTER_PORT", "29583")
dist.init_process_group("gloo", rank=0, world_size=1)
from torch.distributed._shard import sharded_tensor as st
from torch.distributed._shard.sharding_spec import ChunkShardingSpec
import torchsnapshot
spec = ChunkShardingSpec(dim=0, placements=["rank:0/cpu"])
t = st.zeros(spec, (8, 4))
full = torch.arange(32, dtype=torch.float32).reshape(8, 4)
t.local_shards()[0].tensor.copy_(full)
torchsnapshot.Snapshot.take({snap!r}, {{"s": torchsnapshot.StateDict(emb=t)}})
dist.destroy_process_group()
print("SAVED")
"""
    proc = subprocess.run(
        [_sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if proc.returncode != 0 or "SAVED" not in proc.stdout:
        pytest.skip(
            f"reference ShardedTensor save unavailable on this torch: "
            f"{proc.stderr[-300:]}"
        )

    full = np.arange(32, dtype=np.float32).reshape(8, 4)
    state = read_reference_snapshot(snap)
    np.testing.assert_array_equal(state["s"]["emb"], full)

    # And straight onto a mesh (resharding the saved 1-way layout).
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = min(4, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n]), ("x",))
    arr = ReferenceSnapshotReader(snap).read_sharded(
        "0/s/emb", NamedSharding(mesh, P("x", None)), global_shape=(8, 4)
    )
    np.testing.assert_array_equal(np.asarray(arr), full)


def test_reference_library_interop_chunked_and_batched(tmp_path):
    torch = pytest.importorskip("torch")
    torchsnapshot = import_reference()

    big = torch.randn(1 << 14)  # 64 KiB fp32 — chunks at a 16 KiB knob
    small = [torch.randn(16) for _ in range(4)]
    app_state = {
        "s": torchsnapshot.StateDict(
            big=big, **{f"small{i}": t for i, t in enumerate(small)}
        )
    }
    snap_dir = str(tmp_path / "ref_chunked")
    env = {
        "TORCHSNAPSHOT_MAX_CHUNK_SIZE_BYTES_OVERRIDE": str(1 << 14),
        "TORCHSNAPSHOT_ENABLE_BATCHING": "1",
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        torchsnapshot.Snapshot.take(snap_dir, app_state)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    reader = ReferenceSnapshotReader(snap_dir)
    kinds = {e["type"] for e in reader.metadata["manifest"].values()}
    assert "ChunkedTensor" in kinds, "knob did not force chunking"
    state = reader.read_state()
    np.testing.assert_array_equal(state["s"]["big"], big.numpy())
    for i, t in enumerate(small):
        np.testing.assert_array_equal(state["s"][f"small{i}"], t.numpy())


def test_assemble_raises_on_shard_coverage_holes(tmp_path):
    """A sharded entry with an interior hole must raise, not silently
    zero-fill (read_sharded's covered-mask contract, applied to the
    dense _assemble path convert.py reads through)."""
    full = np.arange(16, dtype=np.float32).reshape(4, 4)
    _write(tmp_path / "s0", full[:1].tobytes())
    _write(tmp_path / "s3", full[3:].tobytes())  # rows 1-2 missing
    manifest = {
        "0/m": {"type": "dict", "keys": ["emb"]},
        "0/m/emb": {"type": "ShardedTensor", "shards": [
            _box((0, 0), (1, 4), _tensor_entry("s0", "torch.float32", (1, 4))),
            _box((3, 0), (1, 4), _tensor_entry("s3", "torch.float32", (1, 4))),
        ]},
    }
    doc = {"version": "0.0.3", "world_size": 1, "manifest": manifest}
    (tmp_path / ".snapshot_metadata").write_text(
        yaml.safe_dump(doc, sort_keys=False)
    )
    reader = ReferenceSnapshotReader(str(tmp_path))
    with reader:
        with pytest.raises(ValueError, match="holes"):
            reader.read_object("0/m/emb")


def test_read_blobs_surfaces_unfilled_buffer_explicitly(tmp_path):
    """A plugin completing read() without populating buf must raise a
    named RuntimeError (an assert would vanish under python -O)."""
    arr = np.ones(4, dtype=np.float32)
    _write(tmp_path / "0/m/w", arr.tobytes())
    manifest = {
        "0/m": {"type": "dict", "keys": ["w"]},
        "0/m/w": _tensor_entry("0/m/w", "torch.float32", (4,)),
    }
    doc = {"version": "0.0.3", "world_size": 1, "manifest": manifest}
    (tmp_path / ".snapshot_metadata").write_text(
        yaml.safe_dump(doc, sort_keys=False)
    )

    class _NoFill:
        async def read(self, read_io):
            pass  # never sets read_io.buf

        async def close(self):
            pass

    reader = ReferenceSnapshotReader(str(tmp_path))
    try:
        import asyncio

        reader._loop = asyncio.new_event_loop()
        reader._storage = _NoFill()
        with pytest.raises(RuntimeError, match="_NoFill.*without populating"):
            reader._read_blobs([("0/m/w", None)])
    finally:
        reader.close()
