"""Streaming restore placement: leaves device_put while later reads are
still in flight (rolling batches), with the flush knob controlling
granularity. No reference counterpart (its restore consumes directly into
torch tensors); this is the TPU H2D-overlap path."""

import asyncio
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import snapshot as snapshot_mod
from torchsnapshot_tpu.knobs import override_restore_placement_flush_bytes
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import assert_tree_eq


EVENTS = []


class RecordingFSStoragePlugin(FSStoragePlugin):
    async def _record(self, path):
        if path.startswith("0/"):
            EVENTS.append(("read", path))
        await asyncio.sleep(0.02)  # keep later reads in flight past flushes

    async def read(self, read_io):
        await super().read(read_io)
        await self._record(read_io.path)

    async def read_with_checksum(self, read_io):
        # Whole-blob reads take the fused read+CRC path; record those too.
        pages = await super().read_with_checksum(read_io)
        if pages is not None:
            await self._record(read_io.path)
        return pages


def _patch_plugin(cls):
    return mock.patch(
        "torchsnapshot_tpu.snapshot.url_to_storage_plugin",
        side_effect=lambda url: cls(root=url.split("://")[-1]),
    )


def _recording_run(monkeypatch):
    orig = snapshot_mod._PlacementBatch.run

    def run(self):
        if self._values:
            EVENTS.append(("flush", len(self._values)))
        return orig(self)

    monkeypatch.setattr(snapshot_mod._PlacementBatch, "run", run)


def _tree(seed: float):
    return {
        f"w{i}": jnp.full((64, 8), seed + i, jnp.float32) for i in range(6)
    }


def _committed_zeros_like(tree):
    """Device-committed destinations: uncommitted leaves (plain jnp ops)
    convert via jnp.asarray and never enter a placement batch."""
    dev = jax.devices()[0]
    return {
        k: jax.device_put(np.zeros(v.shape, v.dtype), dev)
        for k, v in tree.items()
    }


def test_streaming_placement_overlaps_reads(tmp_path, monkeypatch):
    """With a tiny flush threshold, placements run between read
    completions — not one batch after all reads."""
    EVENTS.clear()
    _recording_run(monkeypatch)
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PER_RANK_IO_CONCURRENCY", "1")
    src = _tree(2.0)
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, {"m": ts.PyTreeState(src)})

    dest = {"m": ts.PyTreeState(_committed_zeros_like(src))}
    with override_restore_placement_flush_bytes(1), _patch_plugin(
        RecordingFSStoragePlugin
    ):
        ts.Snapshot(p).restore(dest)
    assert_tree_eq(dest["m"].tree, src)

    flushes = [i for i, (kind, _) in enumerate(EVENTS) if kind == "flush"]
    reads = [i for i, (kind, _) in enumerate(EVENTS) if kind == "read"]
    assert len(flushes) >= 2, EVENTS
    # At least one placement flushed before the last read completed.
    assert flushes[0] < reads[-1], EVENTS


def test_flush_disabled_places_in_one_batch(tmp_path, monkeypatch):
    """flush_bytes=0 restores the pre-streaming behavior: exactly one
    batched device_put after all reads."""
    EVENTS.clear()
    _recording_run(monkeypatch)
    src = _tree(4.0)
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, {"m": ts.PyTreeState(src)})

    dest = {"m": ts.PyTreeState(_committed_zeros_like(src))}
    with override_restore_placement_flush_bytes(0):
        ts.Snapshot(p).restore(dest)
    assert_tree_eq(dest["m"].tree, src)
    assert [e for e in EVENTS if e[0] == "flush"] == [("flush", len(src))]


def test_streaming_async_restore_roundtrip(tmp_path):
    """Async restore with per-leaf streaming matches the source."""
    src = _tree(7.0)
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, {"m": ts.PyTreeState(src)})
    dest = {"m": ts.PyTreeState(_committed_zeros_like(src))}
    with override_restore_placement_flush_bytes(1):
        pending = ts.Snapshot(p).async_restore(dest)
        pending.wait()
    assert_tree_eq(dest["m"].tree, src)


def test_streaming_with_batched_reads(tmp_path):
    """Spanning slab reads complete their member requests: streaming and
    read batching compose (merged-req completion fans out to leaves)."""
    from torchsnapshot_tpu.knobs import (
        enable_batching,
        override_slab_size_threshold_bytes,
    )

    src = _tree(9.0)
    p = str(tmp_path / "snap")
    with enable_batching(), override_slab_size_threshold_bytes(1 << 20):
        ts.Snapshot.take(p, {"m": ts.PyTreeState(src)})
        dest = {"m": ts.PyTreeState(_committed_zeros_like(src))}
        with override_restore_placement_flush_bytes(1):
            ts.Snapshot(p).restore(dest)
    assert_tree_eq(dest["m"].tree, src)


def test_streaming_sharded_restore(tmp_path):
    """Sharded-array finalizers stream too: a resharded restore under a
    tiny flush threshold stays byte-exact."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs a multi-device mesh")
    full = np.arange(32 * 8, dtype=np.float32).reshape(32, 8)
    mesh4 = Mesh(np.array(devs[:4]), ("x",))
    arr = jax.device_put(full, NamedSharding(mesh4, P("x")))
    p = str(tmp_path / "snap")
    ts.Snapshot.take(p, {"m": ts.PyTreeState({"w": arr})})

    mesh2 = Mesh(np.array(devs[:2]), ("x",))
    target = jax.device_put(np.zeros_like(full), NamedSharding(mesh2, P("x")))
    dest = {"m": ts.PyTreeState({"w": target})}
    with override_restore_placement_flush_bytes(1):
        ts.Snapshot(p).restore(dest)
    np.testing.assert_array_equal(np.asarray(dest["m"].tree["w"]), full)
