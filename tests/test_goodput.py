"""Goodput engine: run-level wall-time attribution, lost-work
accounting, storage-cost curves, the CLI, Prometheus gauges, and the
ledger-driven doctor rules.

Acceptance pins (ISSUE 9): ``telemetry goodput <root>`` over a
multi-step manager run with one injected interruption + restore emits
an attribution whose buckets sum to measured wall time within 5%,
reports nonzero lost work for the interrupted segment, and the
``recovery-cost-high`` doctor rule fires in an injection test citing
ledger evidence."""

import json
import time

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.telemetry import doctor, goodput, ledger, names
from torchsnapshot_tpu.telemetry.stats import main as stats_main


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset_metrics()
    yield
    telemetry.reset_metrics()


def _state(n=2, size=1024, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n)
    }


def _interrupted_run(root: str):
    """A real manager run with one injected interruption + restore.

    Segment 1: saves at steps 0 and 2, preemption notice at step 3
    whose coordinated save never lands (the grace window is 'missed'),
    so step 3's work — the time since step 2's commit — is lost.
    Segment 2: a fresh manager restores and saves one more step.
    Returns (measured_wall_s, lost_window_s): the test's own clocks
    around exactly what the ledger should measure."""
    from torchsnapshot_tpu.preemption import PreemptionSaver

    t0 = time.time()
    mgr = ts.CheckpointManager(root, keep_last_n=4)
    saver = PreemptionSaver(signals=(), ledger_root=root)
    try:
        for step in range(4):
            if step % 2 == 0:
                mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
                lost_t0 = time.time()
            time.sleep(0.15)  # "training"
            if step == 3:
                saver.request_save()
                assert saver.should_save(step)
                # The save misses the grace window: nothing commits.
    finally:
        saver.uninstall()
    lost_window = time.time() - lost_t0
    seg1_wall = time.time() - t0

    t1 = time.time()
    mgr2 = ts.CheckpointManager(root, keep_last_n=4)
    dest = {"s": ts.PyTreeState(_state(seed=2))}
    assert mgr2.restore_latest(dest) == 2
    time.sleep(0.15)
    mgr2.save(3, {"s": ts.PyTreeState(_state(seed=3))})
    seg2_wall = time.time() - t1
    return seg1_wall + seg2_wall, lost_window


def test_attribution_sums_to_measured_wall_within_tolerance(tmp_path):
    """The headline acceptance: buckets (train + visible stall +
    restore + lost work) sum to the ledger-measured wall, which matches
    the test's own wall clock within 5%; the interrupted segment
    reports nonzero lost work (in seconds AND steps)."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        measured_wall, lost_window = _interrupted_run(root)
        analysis = goodput.analyze_root(root)
    run = goodput.latest_run(analysis)
    assert run is not None and len(run["segments"]) == 2

    buckets = (
        run["train_s"]
        + run["visible_stall_s"]
        + run["restore_s"]
        + run["lost_work_s"]
    )
    # Buckets sum to the ledger wall by construction...
    assert buckets == pytest.approx(run["wall_s"], rel=1e-6, abs=1e-3)
    # ...and the ledger wall tracks the real wall within the 5%
    # acceptance tolerance (event timestamps trail the test's clocks by
    # microseconds, not fractions).
    assert run["wall_s"] == pytest.approx(measured_wall, rel=0.05)

    seg1 = run["segments"][0]
    assert seg1["interrupted"]
    assert seg1["lost_work_s"] > 0
    # The lost window is everything after step 2's commit, give or take
    # the instants between the test's clock reads and the event stamps.
    assert seg1["lost_work_s"] == pytest.approx(lost_window, rel=0.25)
    assert seg1["lost_steps"] == 1  # preempted at 3, last committed 2
    assert seg1["preemption_step"] == 3
    assert run["interruptions"][0]["recovery_cost_s"] > 0
    assert run["restore_s"] > 0


def test_goodput_cli_table_and_json(tmp_path, capsys):
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        _interrupted_run(root)
        rc = stats_main(["goodput", root])
        out = capsys.readouterr().out
        assert rc == 0
        assert "lost work" in out and "visible stall" in out
        assert "preempted at step 3" in out
        assert "storage:" in out

        rc = stats_main(["goodput", root, "--json"])
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out)
        assert doc["runs"][-1]["segments"][0]["interrupted"]
        assert doc["storage"]["retained_steps"] > 0


def test_goodput_cli_without_ledger(tmp_path, capsys):
    rc = stats_main(["goodput", str(tmp_path)])
    assert rc == 1
    assert "no run ledger" in capsys.readouterr().out


def test_manager_commits_refresh_goodput_gauges(tmp_path):
    """Every committed step refreshes the goodput_* gauges from the
    ledger — scrapes track the run, not just the last op."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        mgr = ts.CheckpointManager(root)
        for step in range(2):
            mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
    gauges = telemetry.metrics().collect()["gauges"]
    assert names.GOODPUT_OVERHEAD_FRACTION in gauges
    assert gauges[names.GOODPUT_STORAGE_BYTES_PER_STEP] > 0
    assert 0.0 <= gauges[names.GOODPUT_OVERHEAD_FRACTION] <= 1.0


def test_storage_curve_tracks_retention(tmp_path):
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        mgr = ts.CheckpointManager(root, keep_last_n=2)
        for step in range(4):
            mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
        storage = goodput.analyze_root(root)["storage"]
    assert storage["retained_steps"] == 2
    assert [row["step"] for row in storage["per_step"]] == [2, 3]
    assert storage["bytes_per_retained_step"] > 0
    assert storage["reclaimed_steps"] == 2
    assert storage["reclaimed_bytes"] > 0


# ---------------------------------------------------------------------------
# Ledger-driven doctor rules
# ---------------------------------------------------------------------------


def _synthetic_interrupted_ledger(root: str, lost_s: float, restore_s: float):
    """A ledger written through the real API with injected timestamps:
    a 10-minute segment committing through t+300, dying at t+300+lost_s,
    then a resumed segment whose recovery restore took restore_s."""
    t0 = 1_700_000_000.0
    rid = ledger.open_run(root)
    assert rid is not None
    path = ledger.ledger_path_for(root)
    # Rewrite the auto-stamped run-start with a controlled timeline.
    from torchsnapshot_tpu.telemetry.sink import atomic_write_text

    atomic_write_text(path, "")
    ledger.post_event(
        root, names.EVENT_RUN_START, create=True,
        run_id=rid, segment=1, world_size=1, unix_ts=t0,
    )
    for i, ts_off in enumerate((60.0, 180.0, 300.0)):
        ledger.post_event(
            root, names.EVENT_VISIBLE_STALL, step=i, kind="take",
            visible_s=2.0, wall_s=2.0, nbytes=1 << 20, unix_ts=t0 + ts_off,
        )
        ledger.post_event(
            root, names.EVENT_STEP_COMMITTED, step=i, bytes_new=1 << 20,
            bytes_reused=0, bytes_total=1 << 20, blobs=2,
            unix_ts=t0 + ts_off + 0.5,
        )
    ledger.post_event(
        root, names.EVENT_PREEMPTION, step=5, target_step=6,
        unix_ts=t0 + 300.0 + lost_s,
    )
    t1 = t0 + 300.0 + lost_s + 30.0  # restart gap
    ledger.post_event(
        root, names.EVENT_RUN_START, run_id=rid, segment=2,
        world_size=1, unix_ts=t1,
    )
    ledger.post_event(
        root, names.EVENT_RESTORE_SERVED, step=2, kind="restore",
        restore_s=restore_s, nbytes=1 << 20, unix_ts=t1 + restore_s,
    )
    ledger.post_event(
        root, names.EVENT_STEP_COMMITTED, step=3, bytes_new=1 << 20,
        bytes_reused=0, bytes_total=1 << 20, blobs=2,
        unix_ts=t1 + restore_s + 60.0,
    )


def test_recovery_cost_high_fires_with_ledger_evidence(tmp_path):
    """The acceptance injection test: an interruption whose replayed
    work + restore crosses the recovery budget raises
    ``recovery-cost-high`` citing the ledger records (lost work, lost
    steps, the preemption step, the restore that recovered it)."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        _synthetic_interrupted_ledger(root, lost_s=90.0, restore_s=45.0)
        verdicts = doctor.diagnose_ledger(root)
    by_rule = {v.rule: v for v in verdicts}
    assert names.RULE_RECOVERY_COST_HIGH in by_rule
    v = by_rule[names.RULE_RECOVERY_COST_HIGH]
    assert v.evidence["recovery_cost_s"] == pytest.approx(135.0, abs=2.0)
    assert v.evidence["lost_work_s"] == pytest.approx(89.5, abs=2.0)
    assert v.evidence["lost_steps"] == 3  # preempted at 5, committed 2
    assert v.evidence["preemption_step"] == 5
    assert v.evidence["last_committed_step"] == 2
    assert v.evidence["restore_s"] == pytest.approx(45.0, abs=1.0)
    assert v.source == ledger.LEDGER_BASENAME
    assert v.evidence["threshold_s"] == doctor.RECOVERY_COST_S


def test_recovery_cost_excludes_deliberate_restores(tmp_path):
    """Only the RECOVERY restores (before the resumed segment's first
    commit) price an interruption — a later eval rollback restore stays
    in the restore bucket but never inflates the recovery cost."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        _synthetic_interrupted_ledger(root, lost_s=10.0, restore_s=5.0)
        # A big deliberate restore AFTER segment 2's commit.
        ledger.post_event(
            root, names.EVENT_RESTORE_SERVED, step=1, kind="restore",
            restore_s=300.0, nbytes=1, unix_ts=1_700_000_900.0,
        )
        run = goodput.latest_run(goodput.analyze_root(root))
        verdicts = doctor.diagnose_ledger(root)
    itr = run["interruptions"][0]
    assert itr["restore_s"] == pytest.approx(5.0)
    assert itr["recovery_cost_s"] == pytest.approx(15.0, abs=1.0)
    # The deliberate restore still counts as restore-bucket wall time...
    assert run["restore_s"] == pytest.approx(305.0)
    # ...but recovery-cost-high stays quiet (15s < 60s budget).
    assert names.RULE_RECOVERY_COST_HIGH not in {v.rule for v in verdicts}


def test_by_tier_durable_tracks_retention(tmp_path):
    """GC'd steps' mirror bytes leave the durable tier total exactly as
    pruning removes them from the primary one — the per-tier comparison
    stays apples-to-apples after retention."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        rid = ledger.open_run(root)
        assert rid is not None
        for step in range(3):
            ledger.post_event(
                root, names.EVENT_STEP_COMMITTED, step=step,
                bytes_new=100, bytes_reused=0, bytes_total=100, blobs=1,
            )
            ledger.post_event(
                root, names.EVENT_MIRROR_SETTLED, step=step,
                lag_s=1.0, nbytes=100, blobs=1, error=None,
            )
        # Retention drops step 0: its storage record prunes, its
        # mirror-settled record survives (time attribution).
        ledger.post_event(
            root, names.EVENT_GC_RECLAIMED, step=0,
            bytes_reclaimed=100, blobs=1,
        )
        ledger.prune_steps(root, {0})
        storage = goodput.analyze_root(root)["storage"]
    assert storage["retained_steps"] == 2
    assert storage["by_tier"] == {"primary": 200, "durable": 200}


def test_recovery_cost_quiet_below_threshold(tmp_path):
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        _synthetic_interrupted_ledger(root, lost_s=10.0, restore_s=5.0)
        verdicts = doctor.diagnose_ledger(root)
    assert names.RULE_RECOVERY_COST_HIGH not in {v.rule for v in verdicts}


def test_goodput_degraded_fires_on_overhead_heavy_run(tmp_path):
    """A run whose stalls + recovery eat >15% of wall raises
    ``goodput-degraded`` with the attribution as evidence."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        # 90s lost + 45s restore + 6s stalls over ~8 min of wall ≈ 26%.
        _synthetic_interrupted_ledger(root, lost_s=90.0, restore_s=45.0)
        verdicts = doctor.diagnose_ledger(root)
    by_rule = {v.rule: v for v in verdicts}
    assert names.RULE_GOODPUT_DEGRADED in by_rule
    ev = by_rule[names.RULE_GOODPUT_DEGRADED].evidence
    assert ev["overhead_fraction"] >= doctor.GOODPUT_DEGRADED_FRAC
    assert ev["lost_work_s"] > 0 and ev["visible_stall_s"] > 0


def test_goodput_quiet_on_healthy_run(tmp_path):
    """A clean run (no interruption, tiny stalls against minutes of
    wall) raises neither ledger rule — and the snapshot-level doctor
    sees the same ledger through gather_evidence."""
    root = str(tmp_path / "ckpts")
    t0 = 1_700_000_000.0
    with knobs.enable_ledger():
        rid = ledger.open_run(root)
        from torchsnapshot_tpu.telemetry.sink import atomic_write_text

        atomic_write_text(ledger.ledger_path_for(root), "")
        ledger.post_event(
            root, names.EVENT_RUN_START, create=True, run_id=rid,
            segment=1, world_size=1, unix_ts=t0,
        )
        for i in range(3):
            ledger.post_event(
                root, names.EVENT_VISIBLE_STALL, step=i, kind="take",
                visible_s=1.0, wall_s=1.0, nbytes=1,
                unix_ts=t0 + 100.0 * (i + 1),
            )
            ledger.post_event(
                root, names.EVENT_STEP_COMMITTED, step=i, bytes_new=1,
                bytes_reused=0, bytes_total=1, blobs=1,
                unix_ts=t0 + 100.0 * (i + 1) + 0.5,
            )
        verdicts = doctor.diagnose_ledger(root)
        assert verdicts == []
        # The evidence bundle for a step dir resolves the root ledger.
        ev = doctor.gather_evidence(f"{root}/step_0000000001")
        assert len(ev.ledger_records) == 7
        assert ev.ledger_file.endswith(ledger.LEDGER_BASENAME)


def test_doctor_trend_appends_run_level_verdicts(tmp_path, capsys):
    """``doctor --trend`` on a root with an expensive interruption
    speaks run-level cost alongside the per-step rows."""
    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger(), knobs.override_history_max_records(16):
        mgr = ts.CheckpointManager(root)
        for step in range(3):
            mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
        _synthetic_interrupted_ledger_append(root)
        rc = doctor.main(["--trend", root])
    out = capsys.readouterr().out
    assert rc == 2
    assert names.RULE_RECOVERY_COST_HIGH in out


def _synthetic_interrupted_ledger_append(root: str):
    """Append an expensive historical interruption to an existing
    ledger (timestamps in the past so live segments stay untouched)."""
    t0 = 1_600_000_000.0
    rid = "history00run"
    ledger.post_event(
        root, names.EVENT_RUN_START, run_id=rid, segment=1,
        world_size=1, unix_ts=t0,
    )
    ledger.post_event(
        root, names.EVENT_STEP_COMMITTED, step=0, bytes_new=1,
        bytes_reused=0, bytes_total=1, blobs=1, unix_ts=t0 + 10.0,
    )
    ledger.post_event(
        root, names.EVENT_PREEMPTION, step=4, target_step=5,
        unix_ts=t0 + 200.0,
    )
    ledger.post_event(
        root, names.EVENT_RUN_START, run_id=rid, segment=2,
        world_size=1, unix_ts=t0 + 230.0,
    )
    ledger.post_event(
        root, names.EVENT_RESTORE_SERVED, step=0, kind="restore",
        restore_s=40.0, nbytes=1, unix_ts=t0 + 270.0,
    )


def test_fsck_stats_summarizes_ledger(tmp_path, capsys):
    """``fsck --stats`` lists the ledger as a first-class artifact:
    event counts, run span, and the interrupted segment."""
    from torchsnapshot_tpu.fsck import main as fsck_main

    root = str(tmp_path / "ckpts")
    with knobs.enable_ledger():
        _interrupted_run(root)
        rc = fsck_main([f"{root}/step_0000000002", "--stats"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "run ledger" in out
    assert "run-start=2" in out
    assert "preempted at step 3" in out
    assert "interrupted" in out


def test_restore_rows_land_in_history_with_kind_isolation(tmp_path):
    """Satellite: manager restores append history rows, and trend
    detection baselines per kind — a 40x-slower restore population must
    neither flag against the take baseline nor hide a real take
    regression."""
    from torchsnapshot_tpu.telemetry import history

    root = str(tmp_path / "ckpts")
    with knobs.override_history_max_records(32):
        mgr = ts.CheckpointManager(root)
        for step in range(3):
            mgr.save(step, {"s": ts.PyTreeState(_state(seed=step))})
        dest = {"s": ts.PyTreeState(_state(seed=0))}
        mgr.restore(2, dest)
        pending = mgr.async_restore(2, dest)
        pending.wait()
        records = history.load_history(history.history_path_for(root))
    kinds = [r["kind"] for r in records]
    assert kinds == ["take", "take", "take", "restore", "async_restore"]
    # Kind isolation: synthetic mixed history where restores are 40x
    # slower than takes but internally flat — no cross-kind flagging.
    mixed = []
    for i in range(6):
        mixed.append(
            {"step": i, "kind": "take", "take_s": 1.0, "mb_s": 100.0,
             "budget_wait_s": 0.0, "phases": {"writing": 1.0}}
        )
        mixed.append(
            {"step": i, "kind": "restore", "take_s": 40.0, "mb_s": 10.0,
             "budget_wait_s": 0.0, "phases": {"loading": 40.0}}
        )
    assert history.detect_trend_regressions(mixed) == []
    # A genuine take regression still flags, and carries its kind.
    mixed.append(
        {"step": 6, "kind": "take", "take_s": 5.0, "mb_s": 100.0,
         "budget_wait_s": 0.0, "phases": {"writing": 5.0}}
    )
    rows = history.detect_trend_regressions(mixed)
    assert rows and all(r["kind"] == "take" for r in rows)
    assert {r["step"] for r in rows} == {6}
