"""Shared helper for the reference-library interop tests.

One definition of "import the actual TorchSnapshot library" so the
reader and writer interop suites cannot drift: same location policy
(``TS_REFERENCE_ROOT`` env override, default ``/root/reference``), same
skip behavior when the library or its dependencies are absent.
"""

import os
import sys

import pytest

_REFERENCE_ROOT = os.environ.get("TS_REFERENCE_ROOT", "/root/reference")


def import_reference():
    """Import and return the reference ``torchsnapshot`` package, or
    skip the calling test when it is unavailable."""
    if not os.path.isdir(_REFERENCE_ROOT):
        pytest.skip("reference tree not present")
    sys.path.insert(0, _REFERENCE_ROOT)
    try:
        import torchsnapshot  # noqa: F401

        return torchsnapshot
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"reference library not importable: {e!r}")
    finally:
        sys.path.remove(_REFERENCE_ROOT)
