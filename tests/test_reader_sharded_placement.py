"""Sharded placement of reference ShardedTensor entries into jax.Arrays.

The TPU-native migration path for big sharded checkpoints: per-device
shard assembly via box overlap (no full-array host materialization),
including resharding-on-read to layouts different from the saved one.
"""

import numpy as np
import pytest
import yaml

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
    ReferenceSnapshotReader,
)


def _sharded_snapshot(tmp_path, full: np.ndarray, row_splits):
    """Write a hand-built world_size=len(row_splits) snapshot whose one
    entry 'sh/emb' is row-sharded at the given boundaries."""
    manifest = {}
    start = 0
    for rnk, rows in enumerate(row_splits):
        piece = full[start : start + rows]
        blob = tmp_path / "sharded" / f"emb_{rnk}"
        blob.parent.mkdir(parents=True, exist_ok=True)
        blob.write_bytes(piece.tobytes())
        manifest[f"{rnk}/sh"] = {"type": "dict", "keys": ["emb"]}
        manifest[f"{rnk}/sh/emb"] = {
            "type": "ShardedTensor",
            "shards": [
                {
                    "offsets": [start, 0],
                    "sizes": [rows, full.shape[1]],
                    "tensor": {
                        "type": "Tensor",
                        "location": f"sharded/emb_{rnk}",
                        "serializer": "buffer_protocol",
                        "dtype": "torch.float32",
                        "shape": [rows, full.shape[1]],
                        "replicated": False,
                        "byte_range": None,
                    },
                }
            ],
        }
        start += rows
    doc = {
        "version": "0.0.3",
        "world_size": len(row_splits),
        "manifest": manifest,
    }
    (tmp_path / ".snapshot_metadata").write_text(
        yaml.safe_dump(doc, sort_keys=False)
    )


@pytest.fixture
def snapshot_8x4(tmp_path):
    full = (
        np.arange(32, dtype=np.float32).reshape(8, 4)
        + np.random.default_rng(1).standard_normal((8, 4)).astype(np.float32)
    )
    _sharded_snapshot(tmp_path, full, row_splits=[4, 4])
    return tmp_path, full

require_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh"
)


@require_8_devices
def test_resharding_on_read_8_way(snapshot_8x4):
    path, full = snapshot_8x4
    mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
    sharding = NamedSharding(mesh, P("x", None))
    arr = ReferenceSnapshotReader(str(path)).read_sharded("0/sh/emb", sharding)
    assert arr.shape == (8, 4)
    assert arr.sharding == sharding
    np.testing.assert_array_equal(np.asarray(arr), full)
    # Placement-correct, not just value-equal: each device shard holds
    # exactly its row.
    for s in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(s.data), full[s.index])


@require_8_devices
def test_resharding_to_2d_mesh_and_replicated(snapshot_8x4):
    path, full = snapshot_8x4
    reader = ReferenceSnapshotReader(str(path))
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("a", "b"))
    arr = reader.read_sharded("0/sh/emb", NamedSharding(mesh, P("a", "b")))
    np.testing.assert_array_equal(np.asarray(arr), full)
    # Fully replicated destination: every device holds the whole array,
    # assembled from both rank shards.
    rep = reader.read_sharded("0/sh/emb", NamedSharding(mesh, P(None, None)))
    np.testing.assert_array_equal(np.asarray(rep), full)
    for s in rep.addressable_shards:
        np.testing.assert_array_equal(np.asarray(s.data), full)


@require_8_devices
def test_uneven_saved_splits_reshard(tmp_path):
    full = np.random.default_rng(2).standard_normal((8, 4)).astype(np.float32)
    _sharded_snapshot(tmp_path, full, row_splits=[3, 5])
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    arr = ReferenceSnapshotReader(str(tmp_path)).read_sharded(
        "0/sh/emb", NamedSharding(mesh, P("x", None))
    )
    np.testing.assert_array_equal(np.asarray(arr), full)


@require_8_devices
def test_holes_are_detected(tmp_path):
    full = np.ones((8, 4), np.float32)
    _sharded_snapshot(tmp_path, full, row_splits=[4, 4])
    # Remove rank 1's entry (and its manifest rows) to create a hole.
    import yaml as _y

    meta = tmp_path / ".snapshot_metadata"
    doc = _y.safe_load(meta.read_text())
    del doc["manifest"]["1/sh/emb"]
    del doc["manifest"]["1/sh"]
    meta.write_text(_y.safe_dump(doc, sort_keys=False))
    mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
    # Without global_shape the envelope silently shrinks to (4, 4) (the
    # entry records no global shape); passing it makes the hole loud.
    with pytest.raises(ValueError, match="holes"):
        ReferenceSnapshotReader(str(tmp_path)).read_sharded(
            "0/sh/emb",
            NamedSharding(mesh, P("x", None)),
            global_shape=(8, 4),
        )


@require_8_devices
def test_row_contiguous_overlaps_use_ranged_reads(snapshot_8x4, monkeypatch):
    """Dim-0 resharding (the FSDP case): each device's rows must arrive
    via a ranged read of just those rows — never a whole-shard blob
    read."""
    path, full = snapshot_8x4
    reader = ReferenceSnapshotReader(str(path))
    reads = []
    orig = ReferenceSnapshotReader._read_blobs

    def spy(self, requests):
        reads.extend(
            (loc, br) for loc, br in requests if loc != ".snapshot_metadata"
        )
        return orig(self, requests)

    monkeypatch.setattr(ReferenceSnapshotReader, "_read_blobs", spy)
    mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
    arr = reader.read_sharded("0/sh/emb", NamedSharding(mesh, P("x", None)))
    np.testing.assert_array_equal(np.asarray(arr), full)
    assert reads, "no blob reads recorded"
    row_bytes = 4 * 4  # 4 cols x float32
    for location, byte_range in reads:
        assert byte_range is not None, f"whole-blob read of {location}"
        start, end = byte_range
        assert end - start == row_bytes, (location, byte_range)

    # Column sharding: overlaps are not row slabs -> falls back to whole
    # source pieces, still correct.
    reads.clear()
    col_mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    col = reader.read_sharded(
        "0/sh/emb", NamedSharding(col_mesh, P(None, "x"))
    )
    np.testing.assert_array_equal(np.asarray(col), full)
    assert any(br is None for _, br in reads), "expected full-piece reads"


@require_8_devices
def test_duplicate_saved_shards_cannot_mask_holes(tmp_path):
    """Two ranks recording the SAME shard box (DP-replicated saves) must
    not double-count coverage: with a real hole in rows 4-8, a summed
    count (2 x 16 == 32 == numel) would pass silently — the boolean
    coverage mask must still raise."""
    full = np.ones((8, 4), np.float32)
    blob = tmp_path / "sharded" / "emb_dup"
    blob.parent.mkdir(parents=True)
    blob.write_bytes(full[:4].tobytes())
    manifest = {}
    for rnk in (0, 1):
        manifest[f"{rnk}/sh"] = {"type": "dict", "keys": ["emb"]}
        manifest[f"{rnk}/sh/emb"] = {
            "type": "ShardedTensor",
            "shards": [
                {
                    "offsets": [0, 0],
                    "sizes": [4, 4],
                    "tensor": {
                        "type": "Tensor",
                        "location": "sharded/emb_dup",
                        "serializer": "buffer_protocol",
                        "dtype": "torch.float32",
                        "shape": [4, 4],
                        "replicated": False,
                        "byte_range": None,
                    },
                }
            ],
        }
    (tmp_path / ".snapshot_metadata").write_text(
        yaml.safe_dump(
            {"version": "0.0.3", "world_size": 2, "manifest": manifest},
            sort_keys=False,
        )
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
    with pytest.raises(ValueError, match="holes"):
        ReferenceSnapshotReader(str(tmp_path)).read_sharded(
            "0/sh/emb",
            NamedSharding(mesh, P(None, None)),
            global_shape=(8, 4),
        )


def test_plain_tensor_entry_shardable(tmp_path):
    full = np.random.default_rng(3).standard_normal((4, 4)).astype(np.float32)
    blob = tmp_path / "0" / "s" / "w"
    blob.parent.mkdir(parents=True)
    blob.write_bytes(full.tobytes())
    doc = {
        "version": "0.0.3",
        "world_size": 1,
        "manifest": {
            "0/s": {"type": "dict", "keys": ["w"]},
            "0/s/w": {
                "type": "Tensor",
                "location": "0/s/w",
                "serializer": "buffer_protocol",
                "dtype": "torch.float32",
                "shape": [4, 4],
                "replicated": False,
                "byte_range": None,
            },
        },
    }
    (tmp_path / ".snapshot_metadata").write_text(
        yaml.safe_dump(doc, sort_keys=False)
    )
    n = min(4, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n]), ("x",))
    arr = ReferenceSnapshotReader(str(tmp_path)).read_sharded(
        "0/s/w", NamedSharding(mesh, P("x", None))
    )
    np.testing.assert_array_equal(np.asarray(arr), full)
