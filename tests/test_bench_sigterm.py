"""The bench record must survive a driver kill.

Round 4's signal of record died as ``rc: 124, parsed: null``: the driver
SIGTERMed ``bench.py`` before its single end-of-run emission point. The
round-5 redesign promises that ANY termination still yields a parsed
final JSON line (``complete: false``, ``terminated_by``) plus rolling
``bench-partial:`` lines. This test pins that contract end-to-end: it
launches the real ``bench.py`` (tiny state, CPU backend), waits for the
first partial emission, SIGTERMs the process mid-run — exactly what
``timeout(1)`` does — and asserts the record came out anyway.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BENCH = REPO / "bench.py"


def test_sigterm_mid_run_still_emits_parsed_record(tmp_path):
    bench_md_before = (REPO / "BENCH.md").read_bytes()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # Overrides set => bench must NOT rewrite BENCH.md's block.
        TS_BENCH_GB="0.001",
        TS_BENCH_SKIP_PROTOCOL="1",
        TS_BENCH_PARTIAL_PATH=str(tmp_path / "BENCH_partial.json"),
        TMPDIR=str(tmp_path),
    )
    proc = subprocess.Popen(
        [sys.executable, str(BENCH)],
        cwd=str(tmp_path),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    # Hard watchdog: readline() below blocks, so a wedged bench.py (no
    # stdout at all) would otherwise hang the whole test session.
    watchdog = threading.Timer(120, proc.kill)
    watchdog.start()
    lines = []
    saw_partial = False
    deadline = time.monotonic() + 120
    try:
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line.rstrip("\n"))
            if line.startswith("bench-partial: "):
                saw_partial = True
                proc.send_signal(signal.SIGTERM)
                break
        assert saw_partial, f"no bench-partial line before timeout: {lines}"
        # Drain remaining stdout; the handler writes the bare record line.
        rest, _ = proc.communicate(timeout=60)
        lines += rest.splitlines()
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    assert proc.returncode == 128 + signal.SIGTERM, lines[-3:]

    bare = [
        ln for ln in lines if ln.startswith("{") and not ln.startswith("bench-partial")
    ]
    assert bare, f"no final bare JSON line emitted: {lines[-5:]}"
    record = json.loads(bare[-1])
    assert record["metric"] == "checkpoint_save_throughput"
    assert record["complete"] is False
    assert record["terminated_by"] == "SIGTERM"
    # The partial line that triggered the kill parses too, and the two
    # agree on the leg structure.
    partial = json.loads(
        next(ln for ln in lines if ln.startswith("bench-partial: ")).split(
            "bench-partial: ", 1
        )[1]
    )
    assert partial["metric"] == "checkpoint_save_throughput"
    assert "last_leg" in partial

    # Non-default run (TS_BENCH_* overrides): the committed doc block is
    # untouched even on the termination path.
    assert (REPO / "BENCH.md").read_bytes() == bench_md_before
