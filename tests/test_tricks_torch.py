"""Torch migration bridge round-trips.

Reference parity: the reference's own test_snapshot.py nn.Module/optimizer
round-trips (tests/test_snapshot.py:25-145) — here exercised through the
TorchStateful adapter, including the save-from-torch → restore-into-jax
migration path.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.tricks.torch import TorchStateful


def _model() -> "torch.nn.Module":
    torch.manual_seed(7)
    return torch.nn.Sequential(
        torch.nn.Linear(8, 16),
        torch.nn.ReLU(),
        torch.nn.Linear(16, 4),
    )


def test_module_and_optimizer_roundtrip(tmp_path) -> None:
    model = _model()
    optim = torch.optim.Adam(model.parameters(), lr=1e-3)
    # One step so the optimizer has real state tensors.
    loss = model(torch.randn(4, 8)).sum()
    loss.backward()
    optim.step()

    app_state = {"model": TorchStateful(model), "optim": TorchStateful(optim)}
    ts.Snapshot.take(str(tmp_path), app_state)

    fresh_model = _model()
    with torch.no_grad():
        for p in fresh_model.parameters():
            p.zero_()
    fresh_optim = torch.optim.Adam(fresh_model.parameters(), lr=1e-3)
    loss = fresh_model(torch.randn(4, 8)).sum()
    loss.backward()
    fresh_optim.step()

    ts.Snapshot(str(tmp_path)).restore(
        {"model": TorchStateful(fresh_model), "optim": TorchStateful(fresh_optim)}
    )

    for (k1, v1), (k2, v2) in zip(
        model.state_dict().items(), fresh_model.state_dict().items()
    ):
        assert k1 == k2
        assert torch.equal(v1, v2), k1
    s1, s2 = optim.state_dict(), fresh_optim.state_dict()
    assert s1["param_groups"] == s2["param_groups"]
    for pid in s1["state"]:
        for field, val in s1["state"][pid].items():
            got = s2["state"][pid][field]
            if isinstance(val, torch.Tensor):
                assert torch.equal(val, got), (pid, field)
            else:
                assert val == got, (pid, field)


def test_bf16_tensor_roundtrip(tmp_path) -> None:
    t = torch.arange(64, dtype=torch.float32).reshape(8, 8).to(torch.bfloat16)
    state = {"t": t.clone()}
    ts.Snapshot.take(str(tmp_path), {"s": TorchStateful(state)})

    dst = {"t": torch.zeros(8, 8, dtype=torch.bfloat16)}
    stateful = TorchStateful(dst)
    ts.Snapshot(str(tmp_path)).restore({"s": stateful})
    assert torch.equal(stateful.obj["t"], t)


def test_noncontiguous_and_scalar(tmp_path) -> None:
    state = {
        "strided": torch.arange(24, dtype=torch.float32).reshape(4, 6).t(),
        "scalar": torch.tensor(3.5),
        "step": 12,
    }
    ts.Snapshot.take(str(tmp_path), {"s": TorchStateful(dict(state))})
    dst = {
        "strided": torch.zeros(6, 4),
        "scalar": torch.tensor(0.0),
        "step": 0,
    }
    stateful = TorchStateful(dst)
    ts.Snapshot(str(tmp_path)).restore({"s": stateful})
    assert torch.equal(stateful.obj["strided"], state["strided"])
    assert float(stateful.obj["scalar"]) == 3.5
    assert stateful.obj["step"] == 12


def test_save_from_torch_restore_into_jax(tmp_path) -> None:
    """The migration path: a torch trainer writes the snapshot, a jax
    process restores the same logical paths as plain arrays."""
    import jax.numpy as jnp

    w = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    ts.Snapshot.take(str(tmp_path), {"params": TorchStateful({"w": w})})

    fresh = {"params": ts.PyTreeState({"w": jnp.zeros((3, 4))})}
    ts.Snapshot(str(tmp_path)).restore(fresh)
    np.testing.assert_array_equal(
        np.asarray(fresh["params"].tree["w"]), w.numpy()
    )


def test_plain_dict_restore_mutates_original_in_place(tmp_path) -> None:
    """A caller holding the original plain dict must observe restored
    non-tensor leaves (step counters, lr floats) after restore, not just
    the in-place-copied tensors."""
    src = {
        "w": torch.arange(6, dtype=torch.float32),
        "step": 41,
        "lr": 0.25,
        "sched": [1, 2, {"gamma": 0.9}],
    }
    ts.Snapshot.take(str(tmp_path), {"s": TorchStateful(src)})

    dst = {
        "w": torch.zeros(6, dtype=torch.float32),
        "step": 0,
        "lr": 0.0,
        "sched": [0, 0, {"gamma": 0.0}],
    }
    held = dst  # what a training loop would keep a reference to
    held_sched = dst["sched"]
    ts.Snapshot(str(tmp_path)).restore({"s": TorchStateful(dst)})
    assert held["step"] == 41
    assert held["lr"] == 0.25
    assert held_sched[0] == 1 and held_sched[2]["gamma"] == pytest.approx(0.9)
    np.testing.assert_array_equal(held["w"].numpy(), np.arange(6, dtype=np.float32))
