"""The crash matrix (chaos/harness.py): a take killed at every declared
crash point leaves a store where fsck finds nothing critical, the
newest committed step restores bit-identical, CAS refcounts reconcile,
and the mirror resumes to durability.

Two lanes: a fast 8-point smoke on the fullest configuration
(tiered+CAS) rides tier-1; the slow-marked sweep runs EVERY declared
point × {legacy, CAS} × {plain, tiered} and additionally pins that
every declared point actually fires somewhere — an unthreaded (or
renamed) crash point fails the sweep rather than silently shrinking
the matrix. Any red cell's failure message carries the seed +
fault-plan JSON line that replays it deterministically."""

import json

import pytest

from torchsnapshot_tpu.chaos import declared_crashpoints
from torchsnapshot_tpu.chaos.harness import (
    CONFIGS,
    FULL_CONFIG,
    CrashCaseResult,
    assert_matrix_green,
    run_crash_case,
    run_crash_matrix,
)
from torchsnapshot_tpu.telemetry import names

# The tier-1 smoke: the eight windows where a kill historically hurts
# most — data durable but control plane absent, the torn index pair,
# the commit bracket, and the CAS pin/map/chunk states.
SMOKE_POINTS = (
    names.CRASH_TAKE_WRITES_DONE,
    names.CRASH_CHECKSUM_TABLE_WRITTEN,
    names.CRASH_CAS_CHUNK_WRITTEN,
    names.CRASH_CAS_MAP_WRITTEN,
    names.CRASH_PRE_COMMIT_MARKER,
    names.CRASH_COMMIT_MARKER,
    names.CRASH_INDEX_BACKUP_WRITTEN,
    names.CRASH_REFCOUNT_PINNED,
)


def test_smoke_points_are_declared():
    declared = set(declared_crashpoints())
    assert set(SMOKE_POINTS) <= declared
    assert len(SMOKE_POINTS) == 8


@pytest.mark.parametrize("point", SMOKE_POINTS)
def test_crash_matrix_smoke(tmp_path, point):
    """8-point smoke on tiered+CAS: every point fires and every
    invariant holds."""
    result = run_crash_case(str(tmp_path), point, FULL_CONFIG, seed=0)
    assert_matrix_green([result])
    assert result.fired, f"{point} did not fire under {FULL_CONFIG.name}"


def test_red_cell_prints_replayable_fault_plan(tmp_path):
    """A failing cell's message must carry the one JSON line that
    replays its fault schedule (the red-run workflow docs/chaos.md
    documents)."""
    bad = CrashCaseResult(
        point=names.CRASH_COMMIT_MARKER,
        config="tiered-cas",
        seed=17,
        fired=True,
        applicable=True,
        failures=["synthetic violation"],
    )
    with pytest.raises(AssertionError) as exc:
        assert_matrix_green([bad])
    message = str(exc.value)
    assert "replay:" in message
    line = next(
        l.split("replay:", 1)[1].strip()
        for l in message.splitlines()
        if "replay:" in l
    )
    plan = json.loads(line)
    assert plan["seed"] == 17
    assert plan["faults"][0]["match"] == names.CRASH_COMMIT_MARKER


@pytest.mark.slow
def test_crash_matrix_full(tmp_path):
    """Every declared crash point × {legacy, CAS} × {plain, tiered}:
    green across the board, and every point fires in the fullest
    configuration (so the declared registry can never drift from the
    threaded reality)."""
    results = run_crash_matrix(str(tmp_path))
    assert_matrix_green(results)
    assert len(results) == len(declared_crashpoints()) * len(CONFIGS)
    fired_in_full = {
        r.point
        for r in results
        if r.config == FULL_CONFIG.name and r.fired
    }
    missing = set(declared_crashpoints()) - fired_in_full
    assert not missing, (
        f"declared crash points never fired under {FULL_CONFIG.name}: "
        f"{sorted(missing)} — the point is declared but not threaded"
    )
