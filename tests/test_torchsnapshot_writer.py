"""Writing reference-format snapshots from JAX state.

The proof obligation is interop: what we write must restore through the
*actual* reference library (`torchsnapshot.Snapshot.restore`), so the
headline test round-trips JAX arrays → reference-format snapshot →
torch state dict via the reference's own code. The reader tests double
as a second witness (our reader consumes our writer's output).
"""

from collections import OrderedDict

import numpy as np
import pytest

from interop_utils import import_reference

import jax
import jax.numpy as jnp

from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
    read_reference_snapshot,
)
from torchsnapshot_tpu.tricks.torchsnapshot_writer import (
    write_reference_snapshot,
)

ml_dtypes = pytest.importorskip("ml_dtypes")



def _state():
    k = jax.random.PRNGKey(0)
    return {
        "model": {
            "w": jax.random.normal(k, (8, 4), dtype=jnp.float32),
            "emb": jax.random.normal(k, (16,), dtype=jnp.bfloat16),
            "ids": jnp.arange(6, dtype=jnp.int32),
            "od": OrderedDict(b=2, a=1),
            "lst": [1.25, "x", np.ones(3, dtype=np.float64)],
        },
        "progress": {"step": 7, "done": False, "tag": b"\x01\x02"},
    }


def test_roundtrip_through_own_reader(tmp_path):
    state = _state()
    snap = str(tmp_path / "snap")
    write_reference_snapshot(snap, state)
    back = read_reference_snapshot(snap)
    np.testing.assert_array_equal(
        back["model"]["w"], np.asarray(state["model"]["w"])
    )
    assert back["model"]["emb"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        back["model"]["emb"].view(np.uint16),
        np.asarray(state["model"]["emb"]).view(np.uint16),
    )
    np.testing.assert_array_equal(back["model"]["ids"], np.arange(6))
    assert isinstance(back["model"]["od"], OrderedDict)
    assert list(back["model"]["od"].items()) == [("b", 2), ("a", 1)]
    assert back["model"]["lst"][0] == 1.25
    assert back["model"]["lst"][1] == "x"
    np.testing.assert_array_equal(back["model"]["lst"][2], np.ones(3))
    assert back["progress"] == {"step": 7, "done": False, "tag": b"\x01\x02"}


def test_bridge_over_non_fs_url():
    """The bridge rides the storage-plugin URL grammar: write and read a
    reference-format snapshot through the in-memory plugin (the same
    plumbing s3:// / gs:// use), not just bare filesystem paths."""
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    url = "memory://ref_bridge_roundtrip"
    state = {"m": {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}}
    try:
        write_reference_snapshot(url, state)
        back = read_reference_snapshot(url)
        np.testing.assert_array_equal(back["m"]["w"], state["m"]["w"])
    finally:
        MemoryStoragePlugin.drop_store("ref_bridge_roundtrip")


def test_unrepresentable_dtype_rejected(tmp_path):
    with pytest.raises(ValueError, match="cast to a supported dtype"):
        write_reference_snapshot(
            str(tmp_path / "bad"),
            {"s": {"x": np.zeros(2, dtype=np.uint32)}},
        )


def test_reference_library_restores_our_snapshot(tmp_path):
    torch = pytest.importorskip("torch")
    torchsnapshot = import_reference()

    state = _state()
    snap = str(tmp_path / "export")
    write_reference_snapshot(snap, state)

    # A torch user restores with the reference's own code path. The
    # destination state dict mirrors the structure with torch tensors.
    dest = {
        "model": torchsnapshot.StateDict(
            w=torch.zeros(8, 4),
            emb=torch.zeros(16, dtype=torch.bfloat16),
            ids=torch.zeros(6, dtype=torch.int32),
            od=OrderedDict(b=0, a=0),
            lst=[0.0, "", torch.zeros(3, dtype=torch.float64)],
        ),
        "progress": torchsnapshot.StateDict(step=0, done=True, tag=b""),
    }
    torchsnapshot.Snapshot(snap).restore(dest)

    np.testing.assert_array_equal(
        dest["model"]["w"].numpy(), np.asarray(state["model"]["w"])
    )
    assert dest["model"]["emb"].dtype == torch.bfloat16
    np.testing.assert_array_equal(
        dest["model"]["emb"].view(torch.uint16).numpy(),
        np.asarray(state["model"]["emb"]).view(np.uint16),
    )
    np.testing.assert_array_equal(dest["model"]["ids"].numpy(), np.arange(6))
    assert dict(dest["model"]["od"]) == {"b": 2, "a": 1}
    assert dest["model"]["lst"][0] == 1.25
    assert dest["model"]["lst"][1] == "x"
    np.testing.assert_array_equal(
        dest["model"]["lst"][2].numpy(), np.ones(3)
    )
    assert dest["progress"]["step"] == 7
    assert dest["progress"]["done"] is False
    assert dest["progress"]["tag"] == b"\x01\x02"


def test_reference_library_reads_complex_and_objects(tmp_path):
    torch = pytest.importorskip("torch")
    torchsnapshot = import_reference()

    # A dict with tuple keys is non-flattenable (reference
    # flatten.py:142-154) and goes down the object/torch_save path as a
    # plain container — restorable under torch>=2.6's weights_only
    # default (custom classes would need the user's own allowlisting;
    # that is torch.load policy, not format).
    opaque = {(1, 2): "x", (3, 4): "y"}
    cplx = (np.arange(4) + 1j * np.arange(4)).astype(np.complex64)
    snap = str(tmp_path / "cplx")
    write_reference_snapshot(snap, {"s": {"z": cplx, "o": opaque}})

    dest = {
        "s": torchsnapshot.StateDict(
            z=torch.zeros(4, dtype=torch.complex64), o={}
        )
    }
    torchsnapshot.Snapshot(snap).restore(dest)
    np.testing.assert_array_equal(dest["s"]["z"].numpy(), cplx)
    assert dest["s"]["o"] == opaque


def test_big_endian_arrays_normalized_before_serialization(tmp_path):
    """A '>f4' array (dtype.name is still 'float32') must round-trip
    value-exact: the reference format is raw LITTLE-endian bytes, so the
    writer normalizes byte order before tobytes()."""
    big = np.arange(12, dtype=np.float32).astype(">f4").reshape(3, 4)
    big_i = np.array([1, -2, 3], dtype=">i8")
    snap = str(tmp_path / "snap")
    write_reference_snapshot(snap, {"m": {"w": big, "i": big_i}})
    back = read_reference_snapshot(snap)
    np.testing.assert_array_equal(back["m"]["w"], big.astype("<f4"))
    np.testing.assert_array_equal(back["m"]["i"], big_i.astype("<i8"))
    assert float(back["m"]["w"][1, 2]) == 6.0  # not byte-swapped garbage


def test_big_endian_complex_normalized_on_torch_save_path(tmp_path):
    torch = pytest.importorskip("torch")
    del torch
    big_c = (np.arange(4) + 1j * np.arange(4)).astype(">c8")
    snap = str(tmp_path / "snapc")
    write_reference_snapshot(snap, {"m": {"c": big_c}})
    back = read_reference_snapshot(snap)
    np.testing.assert_array_equal(back["m"]["c"], big_c.astype("<c8"))
