"""Replication auto-inference from fully-replicated GSPMD shardings.

Reference parity: tests/test_ddp_infer_replication.py — the reference
auto-marks DDP module state as replicated (snapshot.py:828-844). The
TPU-native signal is the sharding itself: a jax.Array fully replicated
over more than one device is replicated by construction. Single-device
arrays must never be inferred (per-rank state stays per-rank).
"""

import chex
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.manifest import ArrayEntry, ShardedArrayEntry
from torchsnapshot_tpu.snapshot import _infer_replicated_paths


def _mesh() -> Mesh:
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    return Mesh(np.array(devs), ("x",))


def test_infer_replicated_paths_unit() -> None:
    mesh = _mesh()
    replicated = jax.device_put(
        jnp.arange(16.0).reshape(4, 4), NamedSharding(mesh, P())
    )
    sharded = jax.device_put(
        jnp.arange(float(8 * len(mesh.devices))).reshape(-1, 8),
        NamedSharding(mesh, P("x", None)),
    )
    single = jnp.ones((4,))  # committed to one device only
    flattened = {
        "model/w": replicated,
        "model/emb": sharded,
        "local/buf": single,
        "step": 7,
        "np": np.ones(3),
    }
    assert _infer_replicated_paths(flattened, world_size=1) == {"model/w"}
    # world > 1 but every device lives in this process: local replication
    # carries no cross-rank guarantee, nothing is inferred.
    assert _infer_replicated_paths(flattened, world_size=2) == set()


def test_take_marks_inferred_entries_replicated(tmp_path) -> None:
    mesh = _mesh()
    replicated = jax.device_put(
        jnp.arange(16.0).reshape(4, 4), NamedSharding(mesh, P())
    )
    sharded = jax.device_put(
        jnp.arange(float(8 * len(mesh.devices))).reshape(-1, 8),
        NamedSharding(mesh, P("x", None)),
    )
    single = jnp.full((4,), 3.0)
    app_state = {
        "state": ts.PyTreeState(
            {"w": replicated, "emb": sharded, "buf": single}
        )
    }
    ts.Snapshot.take(str(tmp_path), app_state)

    manifest = ts.Snapshot(str(tmp_path)).get_manifest()
    w = manifest["0/state/w"]
    assert isinstance(w, ArrayEntry)
    assert w.replicated
    assert w.location.startswith("replicated/")

    buf = manifest["0/state/buf"]
    assert isinstance(buf, ArrayEntry)
    assert not buf.replicated
    assert buf.location.startswith("0/")

    assert isinstance(manifest["0/state/emb"], ShardedArrayEntry)

    # Round-trip: restored values match regardless of replication marking.
    fresh = {
        "state": ts.PyTreeState(
            {
                "w": jax.device_put(jnp.zeros((4, 4)), NamedSharding(mesh, P())),
                "emb": jax.device_put(
                    jnp.zeros_like(sharded), NamedSharding(mesh, P("x", None))
                ),
                "buf": jnp.zeros((4,)),
            }
        )
    }
    ts.Snapshot(str(tmp_path)).restore(fresh)
    chex.assert_trees_all_equal(fresh["state"].tree["w"], replicated)
    chex.assert_trees_all_equal(fresh["state"].tree["emb"], sharded)
    chex.assert_trees_all_equal(fresh["state"].tree["buf"], single)


@pytest.mark.parametrize("nproc", [2])
def test_local_replication_not_inferred_multiprocess(nproc, tmp_path) -> None:
    """World size > 1 with device_sets that never leave the rank's own
    process: replication must NOT be inferred — each rank's value may
    differ (the review scenario: per-host statistics replicated over
    local devices only)."""
    import os
    import tempfile

    from torchsnapshot_tpu.test_utils import run_multiprocess

    path = os.path.join(tempfile.gettempdir(), "infer-local-rep-test")
    results = run_multiprocess(_local_replication_worker, nproc=nproc, args=(path,))
    assert all(results)


def _local_replication_worker(pg, path: str):
    import shutil

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import torchsnapshot_tpu as ts

    if pg.rank == 0:
        shutil.rmtree(path, ignore_errors=True)
    mesh = Mesh(np.array(jax.devices()), ("x",))
    # Replicated over this rank's local devices only; value differs per rank.
    local_rep = jax.device_put(
        jnp.full((4,), float(pg.rank)), NamedSharding(mesh, P())
    )
    snap = ts.Snapshot.take(path, {"s": ts.PyTreeState({"v": local_rep})}, pg=pg)
    md = snap.metadata
    # Per-rank entries for both ranks, nothing marked replicated.
    return (
        not md.manifest["0/s/v"].replicated
        and "1/s/v" in md.manifest
        and not md.manifest["1/s/v"].replicated
    )


def test_explicit_glob_still_wins_for_single_device(tmp_path) -> None:
    # Users can still force replication of single-device state via globs;
    # inference only ever widens the set.
    app_state = {"s": ts.PyTreeState({"a": jnp.ones((3,)), "b": jnp.zeros((2,))})}
    ts.Snapshot.take(str(tmp_path), app_state, replicated=["s/a"])
    manifest = ts.Snapshot(str(tmp_path)).get_manifest()
    assert manifest["0/s/a"].replicated
    assert not manifest["0/s/b"].replicated
