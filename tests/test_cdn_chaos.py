"""CDN under chaos (docs/cdn.md, docs/chaos.md): publisher killed
mid-announce, subscriber killed mid-swap, corrupted peer frames, and
``fsck --cas`` cleanliness with fleet leases outstanding. The
invariants: subscribers converge to the last FULLY published step, a
torn announce is never swapped in, and a fleet-held chunk never reads
as store damage."""

import os
import zlib

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs
from torchsnapshot_tpu.cas import CASStore, digest_key
from torchsnapshot_tpu.cdn import (
    CdnPublisher,
    CdnSubscriber,
    WeightSwapper,
    read_announce,
    read_head,
)
from torchsnapshot_tpu.chaos import (
    ChaosEngine,
    FaultPlan,
    SimulatedCrash,
    arm,
    declared_crashpoints,
    disarm,
    install_wire_chaos,
    uninstall_wire_chaos,
)
from torchsnapshot_tpu.dist_store import InProcessStore
from torchsnapshot_tpu.fsck import verify_cas_store
from torchsnapshot_tpu.telemetry import names


def _chunk(seed: int, nbytes: int = 256):
    data = (seed.to_bytes(8, "little") * (nbytes // 8 + 1))[:nbytes]
    return digest_key(("crc32", zlib.crc32(data), len(data))), data


def _blobs(*seeds):
    out = {}
    for s in seeds:
        key, data = _chunk(s)
        out[key] = data
    return out


def test_cdn_crash_points_join_the_matrix():
    declared = declared_crashpoints()
    assert names.CRASH_CDN_PUBLISH_ANNOUNCED in declared
    assert names.CRASH_CDN_SWAP_STAGED in declared


def test_publisher_killed_mid_announce_leaves_head_unmoved():
    """The announce record lands BEFORE the head bump: a publisher
    killed between the two leaves an unobservable record, never a torn
    announce. A restarted trainer re-publishes over it and the fleet
    converges to the re-published step only."""
    store = InProcessStore()
    blobs = _blobs(1, 2)
    chunks = {k: len(v) for k, v in blobs.items()}

    pub = CdnPublisher(store, "t")
    arm(names.CRASH_CDN_PUBLISH_ANNOUNCED)
    try:
        with pytest.raises(SimulatedCrash):
            pub.publish(100, chunks)
    finally:
        disarm()
    # Head never moved; the half-written announce is invisible.
    assert read_head(store, "t") == 0
    sub = CdnSubscriber(store, "t", 0, 1, durable_fetch=blobs.__getitem__)
    try:
        assert sub.wait_for_update(timeout=0.1) is None

        # Trainer restart: a fresh publisher resumes from the durable
        # head and re-announces (possibly a LATER step — the crashed
        # one is gone for good, which is the contract).
        pub2 = CdnPublisher(store, "t")
        ann = pub2.publish(101, chunks)
        assert ann is not None and ann.seq == 1
        got = sub.track_once(timeout=5.0)
        assert got is not None and got.step == 101
        assert sub.applied_seq == 1
    finally:
        sub.close()


def test_subscriber_killed_mid_swap_serves_previous_step():
    """The crash point sits between stage and swap: a subscriber killed
    there still serves the previous fully-applied step, and a restart
    of its tracking loop applies the update cleanly."""
    store = InProcessStore()
    blobs1 = _blobs(1)
    blobs2 = _blobs(2)
    blobs = dict(blobs1, **blobs2)
    payload1 = b"".join(blobs1[k] for k in sorted(blobs1))
    template = {"w": np.zeros(len(payload1), dtype=np.uint8)}

    pub = CdnPublisher(store, "t")
    sub = CdnSubscriber(store, "t", 0, 1, durable_fetch=blobs.__getitem__)
    swapper = WeightSwapper(template)
    try:
        pub.publish(1, {k: len(v) for k, v in blobs1.items()})
        assert sub.track_once(swapper, timeout=5.0) is not None
        assert sub.applied_seq == 1
        served_before = np.array(swapper.weights["w"], copy=True)

        pub.publish(2, {k: len(v) for k, v in blobs2.items()})
        arm(names.CRASH_CDN_SWAP_STAGED)
        try:
            with pytest.raises(SimulatedCrash):
                sub.track_once(swapper, timeout=5.0)
        finally:
            disarm()
        # Torn announce never swapped in: applied seq and the served
        # bytes are still step 1's.
        assert sub.applied_seq == 1
        assert swapper.swapped_step == 1
        np.testing.assert_array_equal(swapper.weights["w"], served_before)

        # Restarted tracking loop converges to step 2.
        assert sub.track_once(swapper, timeout=5.0) is not None
        assert sub.applied_seq == 2 and swapper.swapped_step == 2
        payload2 = b"".join(blobs2[k] for k in sorted(blobs2))
        np.testing.assert_array_equal(
            swapper.weights["w"],
            np.frombuffer(payload2, dtype=np.uint8),
        )
    finally:
        sub.close()


def test_corrupt_peer_frames_never_poison_the_swap():
    """Wire chaos corrupts peer-transport frames: the digest check
    rejects the damaged bytes and the subscriber retries/falls back —
    the swapped-in weights are always the announced bytes."""
    store = InProcessStore()
    blobs = _blobs(1, 2, 3)
    chunks = {k: len(v) for k, v in blobs.items()}
    os.environ["TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS"] = "1.0"

    # Owner (rank 1) syncs first so the victim's pulls have a live
    # peer to hit; every frame the victim receives is corrupted.
    subs = [
        CdnSubscriber(store, "t", i, 2, durable_fetch=blobs.__getitem__)
        for i in range(2)
    ]
    try:
        CdnPublisher(store, "t").publish(5, chunks)
        assert subs[1].track_once(timeout=5.0) is not None

        engine = ChaosEngine(
            FaultPlan.single(point="wire-recv", mode="corrupt", times=3)
        )
        install_wire_chaos(engine)
        try:
            assert subs[0].track_once(timeout=5.0) is not None
        finally:
            uninstall_wire_chaos()
        assert engine.fired  # the cell actually injected
        assert subs[0].applied_seq == 1
        # Whatever mix of peer retries and durable fallbacks happened,
        # the synced bytes match the announced digests.
        for key, data in subs[0].sync(
            read_announce(store, "t", 1)
        ).items():
            assert data == blobs[key]
    finally:
        for s in subs:
            s.close()
        os.environ.pop("TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS", None)


def test_fsck_cas_clean_with_fleet_lease_outstanding(tmp_path):
    """Retention dropped a step the fleet still serves: the leased
    chunks survive GC as UNREFERENCED entries — informational, never
    problems — so ``fsck --cas`` stays clean."""
    root = str(tmp_path / "ckpt")
    with knobs.enable_cas(), knobs.override_cas_gc_grace_seconds(0):
        mgr = ts.CheckpointManager(root, keep_last_n=1)
        mgr.save(
            0, {"m": ts.PyTreeState({"w": np.arange(512, dtype=np.float32)})}
        )
        store = CASStore(root)
        pins, _, _ = store.load_full()
        step0_chunks = dict(pins[0])
        store.lease("cdn/t/0", step0_chunks)
        mgr.save(
            1,
            {"m": ts.PyTreeState({"w": np.arange(512, dtype=np.float32) + 9.0})},
        )
    report = verify_cas_store(root, deep=True)
    assert report.ok, [str(p) for p in report.problems]
    # The fleet-held chunks are present and accounted as unreferenced.
    for key in step0_chunks:
        if key not in report.unreferenced:
            # Shared with the live step — also fine, also clean.
            assert key in {
                k for k in os.listdir(os.path.join(root, "chunks"))
            }
