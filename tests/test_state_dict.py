"""StateDict / PyTreeState / RngState adapters."""

import chex
import jax
import jax.numpy as jnp
import numpy as np
import optax

from torchsnapshot_tpu import PyTreeState, RngState, StateDict
from torchsnapshot_tpu.state_dict import pytree_to_state_dict, state_dict_to_pytree


def test_state_dict_adapter() -> None:
    sd = StateDict(epoch=3, steps=[1, 2])
    out = sd.state_dict()
    assert out == {"epoch": 3, "steps": [1, 2]}
    sd2 = StateDict(epoch=0, steps=[])
    sd2.load_state_dict(out)
    assert dict(sd2) == {"epoch": 3, "steps": [1, 2]}


def test_pytree_state_dict_conversion_namedtuple() -> None:
    tree = {"a": [jnp.ones(2), (1, 2)], "b": {"c": 3.0}}
    sd = pytree_to_state_dict(tree)
    assert isinstance(sd["a"], list)
    assert isinstance(sd["a"][1], dict)  # tuple became {"0":..,"1":..}
    rebuilt = state_dict_to_pytree(sd, tree)
    assert isinstance(rebuilt["a"][1], tuple)
    assert rebuilt["a"][1] == (1, 2)


def test_pytree_state_with_optax() -> None:
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros(3)}
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    holder = PyTreeState(opt_state)
    sd = holder.state_dict()

    # Simulate restore into a freshly-initialized state.
    fresh = PyTreeState(opt.init(jax.tree_util.tree_map(lambda x: x * 0, params)))
    fresh.load_state_dict(sd)
    restored = fresh.tree
    assert type(restored) is type(opt_state)
    chex.assert_trees_all_equal(restored, opt_state)


def test_pytree_state_single_leaf() -> None:
    holder = PyTreeState(jnp.arange(4))
    sd = holder.state_dict()
    fresh = PyTreeState(jnp.zeros(4, dtype=jnp.int32))
    fresh.load_state_dict(sd)
    np.testing.assert_array_equal(np.asarray(fresh.tree), np.arange(4))


def test_rng_state_typed_and_raw_keys() -> None:
    typed = jax.random.key(7)
    raw = jax.random.PRNGKey(9)
    rng = RngState({"typed": typed, "raw": raw})
    sd = rng.state_dict()

    fresh = RngState({"typed": jax.random.key(0), "raw": jax.random.PRNGKey(0)})
    fresh.load_state_dict(sd)
    assert jnp.array_equal(
        jax.random.key_data(fresh.keys["typed"]), jax.random.key_data(typed)
    )
    assert jnp.array_equal(fresh.keys["raw"], raw)
    # Restored typed key is usable.
    jax.random.normal(fresh.keys["typed"], (2,))


def test_pytree_state_int_keyed_dict() -> None:
    """Regression: int-keyed dicts must restore (review finding)."""
    tree = {5: jnp.arange(3), 7: jnp.ones(2)}
    holder = PyTreeState(tree)
    sd = holder.state_dict()
    fresh = PyTreeState({5: jnp.zeros(3, jnp.int32), 7: jnp.zeros(2)})
    fresh.load_state_dict(sd)
    np.testing.assert_array_equal(np.asarray(fresh.tree[5]), np.arange(3))
    assert set(fresh.tree.keys()) == {5, 7}


def test_pytree_state_mixed_keys() -> None:
    tree = {1: jnp.arange(2), "a": jnp.ones(2)}
    holder = PyTreeState(tree)
    fresh = PyTreeState({1: jnp.zeros(2, jnp.int32), "a": jnp.zeros(2)})
    fresh.load_state_dict(holder.state_dict())
    assert set(fresh.tree.keys()) == {1, "a"}


def test_pytree_state_leaf_sentinel_collision() -> None:
    """Regression: a user dict keyed '__leaf__' must not be misrouted."""
    tree = {"__leaf__": jnp.arange(3)}
    holder = PyTreeState(tree)
    fresh = PyTreeState({"__leaf__": jnp.zeros(3, jnp.int32)})
    fresh.load_state_dict(holder.state_dict())
    np.testing.assert_array_equal(np.asarray(fresh.tree["__leaf__"]), np.arange(3))
