"""Replication glob semantics: pattern matching, cross-rank intersection,
and existence verification.

Reference parity: tests/test_replication_glob.py +
tests/test_ddp_replication_glob.py (snapshot.py:623-656, :789-849). The
thread-over-InProcessStore harness replaces process fan-out for the pure
coordination logic; one end-to-end multiprocess case lives in
tests/test_distributed_snapshot.py.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List

import numpy as np

from torchsnapshot_tpu.dist_store import InProcessStore
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.snapshot import (
    _calculate_replicated_entries,
    _coalesce_replicated,
)
from torchsnapshot_tpu.test_utils import ProcessGroup


def run_ranks(world_size: int, fn: Callable[[PGWrapper, int], Any]) -> List[Any]:
    store = InProcessStore()
    pgs = [
        PGWrapper(ProcessGroup(store=store, rank=r, world_size=world_size))
        for r in range(world_size)
    ]
    with ThreadPoolExecutor(max_workers=world_size) as ex:
        futs = [ex.submit(fn, pg, r) for r, pg in enumerate(pgs)]
        return [f.result(timeout=60) for f in futs]


FLATTENED: Dict[str, Any] = {
    "model/layer0/w": np.ones(2),
    "model/layer0/b": np.ones(2),
    "model/layer1/w": np.ones(2),
    "optim/step": 3,
    "optim/layer0/m": np.ones(2),
}


def test_single_process_glob_matching() -> None:
    pg = PGWrapper(None)
    assert _calculate_replicated_entries(FLATTENED, ["**"], pg) == set(FLATTENED)
    assert _calculate_replicated_entries(FLATTENED, ["model/**"], pg) == {
        "model/layer0/w",
        "model/layer0/b",
        "model/layer1/w",
    }
    # fnmatch "*" crosses "/" (it is not a filesystem glob): document that.
    assert _calculate_replicated_entries(FLATTENED, ["model/*/w"], pg) == {
        "model/layer0/w",
        "model/layer1/w",
    }
    assert _calculate_replicated_entries(FLATTENED, ["optim/step"], pg) == {
        "optim/step"
    }
    assert _calculate_replicated_entries(FLATTENED, [], pg) == set()
    assert _calculate_replicated_entries(FLATTENED, ["nomatch/**"], pg) == set()


def test_multi_pattern_union() -> None:
    pg = PGWrapper(None)
    got = _calculate_replicated_entries(
        FLATTENED, ["optim/step", "model/layer1/**"], pg
    )
    assert got == {"optim/step", "model/layer1/w"}


def test_coalesce_intersects_patterns_across_ranks() -> None:
    def fn(pg: PGWrapper, rank: int) -> List[str]:
        patterns = ["model/**", "optim/**"] if rank == 0 else ["model/**"]
        return _coalesce_replicated(patterns, pg)

    for res in run_ranks(2, fn):
        assert res == ["model/**"]


def test_coalesce_world1_passthrough() -> None:
    assert _coalesce_replicated(["a", "b"], PGWrapper(None)) == ["a", "b"]


def test_path_missing_on_one_rank_not_replicated() -> None:
    """A matched path must exist on every rank to be treated as replicated
    (reference all-rank verification, snapshot.py:623-656)."""

    def fn(pg: PGWrapper, rank: int) -> set:
        flattened = dict(FLATTENED)
        if rank == 1:
            del flattened["model/layer1/w"]  # only rank 0 has it
        return _calculate_replicated_entries(flattened, ["model/**"], pg)

    for res in run_ranks(2, fn):
        assert res == {"model/layer0/w", "model/layer0/b"}


def test_all_ranks_agree_on_result() -> None:
    def fn(pg: PGWrapper, rank: int) -> set:
        return _calculate_replicated_entries(FLATTENED, ["**"], pg)

    results = run_ranks(3, fn)
    assert results[0] == results[1] == results[2] == set(FLATTENED)
