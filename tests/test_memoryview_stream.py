"""MemoryviewStream: zero-copy file-like reads.

Reference parity: tests/test_memoryview_stream.py (reference
memoryview_stream.py:12-81).
"""

import io

import numpy as np
import pytest

from torchsnapshot_tpu.memoryview_stream import MemoryviewStream


def _stream(data: bytes = b"0123456789") -> MemoryviewStream:
    return MemoryviewStream(memoryview(data))


def test_sequential_reads() -> None:
    s = _stream()
    assert bytes(s.read(3)) == b"012"
    assert s.tell() == 3
    assert bytes(s.read(4)) == b"3456"
    assert bytes(s.read(-1)) == b"789"
    assert bytes(s.read(5)) == b""  # EOF
    assert s.tell() == 10


def test_reads_are_zero_copy_views() -> None:
    data = bytearray(b"abcdef")
    s = MemoryviewStream(memoryview(data))
    chunk = s.read(3)
    assert isinstance(chunk, memoryview)
    data[0] = ord("z")  # same backing buffer
    assert bytes(chunk) == b"zbc"


def test_seek_whence() -> None:
    s = _stream()
    assert s.seek(4) == 4
    assert bytes(s.read(2)) == b"45"
    assert s.seek(-3, io.SEEK_CUR) == 3
    assert bytes(s.read(1)) == b"3"
    assert s.seek(-2, io.SEEK_END) == 8
    assert bytes(s.read(-1)) == b"89"
    with pytest.raises(ValueError):
        s.seek(-1)
    with pytest.raises(ValueError):
        s.seek(0, 7)


def test_seek_past_end_reads_empty() -> None:
    s = _stream()
    s.seek(100)
    assert bytes(s.read(5)) == b""
    assert s.tell() == 100  # position preserved, like BytesIO


def test_readinto() -> None:
    s = _stream()
    buf = bytearray(4)
    assert s.readinto(buf) == 4
    assert bytes(buf) == b"0123"
    s.seek(8)
    buf = bytearray(4)
    assert s.readinto(buf) == 2  # short read at EOF
    assert bytes(buf[:2]) == b"89"


def test_multidim_and_typed_views_are_flattened() -> None:
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    s = MemoryviewStream(memoryview(arr))
    assert len(s) == arr.nbytes
    assert bytes(s.read(-1)) == arr.tobytes()


def test_io_flags_and_close() -> None:
    s = _stream()
    assert s.readable() and s.seekable() and not s.writable()
    assert len(s) == 10
    s.close()
    with pytest.raises(ValueError):
        s.read(1)


def test_bufferedreader_compatible() -> None:
    # Clients may wrap bodies in BufferedReader; RawIOBase contract must hold.
    s = MemoryviewStream(memoryview(b"x" * 10000))
    reader = io.BufferedReader(s)
    assert reader.read() == b"x" * 10000
