"""Blob-level CRC integrity: recorded at take, verified on restore.

No reference counterpart (the reference's durability story ends at the
commit marker); this subsystem rides the native CRC32-C kernel. The
commit invariant extends: a committed snapshot always has complete
checksum tables (written before the barrier).
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.integrity import (
    ChecksumError,
    compute_checksum,
    load_checksum_tables,
    table_path,
    verify_checksum,
)
from torchsnapshot_tpu.knobs import disable_checksums


def test_compute_verify_roundtrip() -> None:
    buf = b"hello, checkpoint world" * 100
    alg, crc = compute_checksum(buf)
    assert alg in ("crc32c", "crc32")
    verify_checksum(buf, (alg, crc, len(buf)), "p")  # no raise

    corrupted = bytearray(buf)
    corrupted[7] ^= 0xFF
    with pytest.raises(ChecksumError, match="mismatch"):
        verify_checksum(bytes(corrupted), (alg, crc, len(buf)), "p")

    with pytest.raises(ChecksumError, match="size mismatch"):
        verify_checksum(buf[:-1], (alg, crc, len(buf)), "p")

    # Unknown algorithm from a future version: skipped, not fatal.
    verify_checksum(buf, ("sha999", 0, len(buf)), "p")


def test_take_writes_checksum_table(tmp_path) -> None:
    state = {"s": ts.PyTreeState({"w": jnp.ones((8, 8)), "n": np.arange(10)})}
    ts.Snapshot.take(str(tmp_path), state)
    table_file = tmp_path / table_path(0)
    assert table_file.exists()
    table = json.loads(table_file.read_text())
    assert "0/s/w" in table and "0/s/n" in table
    for alg, crc, nbytes in table.values():
        assert alg in ("crc32c", "crc32")
        assert nbytes > 0


def test_corruption_detected_on_restore(tmp_path) -> None:
    arr = np.arange(64, dtype=np.float64).reshape(8, 8)
    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"w": arr.copy()})})

    # Same-length bit flip: only the digest can catch this.
    blob = tmp_path / "0" / "s" / "w"
    data = bytearray(blob.read_bytes())
    data[5] ^= 0x40
    blob.write_bytes(bytes(data))

    dst = {"s": ts.PyTreeState({"w": np.zeros((8, 8))})}
    with pytest.raises(ChecksumError, match="0/s/w"):
        ts.Snapshot(str(tmp_path)).restore(dst)
    # The in-place destination was not touched by the failed restore.
    np.testing.assert_array_equal(dst["s"].tree["w"], np.zeros((8, 8)))


def test_corruption_detected_for_jax_destination(tmp_path) -> None:
    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"w": jnp.ones((4, 4))})})
    blob = tmp_path / "0" / "s" / "w"
    data = bytearray(blob.read_bytes())
    data[0] ^= 0x01
    blob.write_bytes(bytes(data))
    with pytest.raises(ChecksumError):
        ts.Snapshot(str(tmp_path)).restore(
            {"s": ts.PyTreeState({"w": jnp.zeros((4, 4))})}
        )


def test_read_object_verifies(tmp_path) -> None:
    arr = np.arange(16.0)
    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"w": arr})})
    blob = tmp_path / "0" / "s" / "w"
    data = bytearray(blob.read_bytes())
    data[-1] ^= 0x80
    blob.write_bytes(bytes(data))
    with pytest.raises(ChecksumError):
        ts.Snapshot(str(tmp_path)).read_object("0/s/w")


def test_disable_checksums(tmp_path) -> None:
    with disable_checksums():
        ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"w": np.ones(4)})})
        assert not (tmp_path / table_path(0)).exists()
        # Restore of an unchecksummed snapshot works.
        dst = {"s": ts.PyTreeState({"w": np.zeros(4)})}
        ts.Snapshot(str(tmp_path)).restore(dst)
        np.testing.assert_array_equal(dst["s"].tree["w"], np.ones(4))


def test_missing_tables_restore_without_verification(tmp_path) -> None:
    """Snapshots whose tables were deleted (or predate checksums) restore
    fine — verification is best-effort, the commit marker is the
    correctness gate."""
    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"w": np.ones(4)})})
    os.remove(tmp_path / table_path(0))
    dst = {"s": ts.PyTreeState({"w": np.zeros(4)})}
    ts.Snapshot(str(tmp_path)).restore(dst)
    np.testing.assert_array_equal(dst["s"].tree["w"], np.ones(4))


def test_sharded_blobs_are_checksummed(tmp_path) -> None:
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs), ("x",))
    sharded = jax.device_put(
        jnp.arange(float(8 * len(devs))).reshape(-1, 8),
        NamedSharding(mesh, P("x", None)),
    )
    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"emb": sharded})})
    table = json.loads((tmp_path / table_path(0)).read_text())
    shard_keys = [k for k in table if k.startswith("sharded/s/emb")]
    assert len(shard_keys) == len(devs)

    # Corrupt one shard; resharded restore must fail.
    victim = tmp_path / shard_keys[0]
    data = bytearray(victim.read_bytes())
    data[3] ^= 0x10
    victim.write_bytes(bytes(data))
    with pytest.raises(ChecksumError):
        ts.Snapshot(str(tmp_path)).restore(
            {
                "s": ts.PyTreeState(
                    {
                        "emb": jax.device_put(
                            jnp.zeros_like(sharded), NamedSharding(mesh, P("x", None))
                        )
                    }
                )
            }
        )


def test_load_checksum_tables_merges_ranks(tmp_path) -> None:
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    (tmp_path / "checksums").mkdir()
    (tmp_path / table_path(0)).write_text(json.dumps({"a": ["crc32c", 1, 2]}))
    (tmp_path / table_path(1)).write_text(json.dumps({"b": ["crc32c", 3, 4]}))

    import asyncio

    loop = asyncio.new_event_loop()
    try:
        plugin = FSStoragePlugin(str(tmp_path))
        merged = load_checksum_tables(2, plugin, loop)
        loop.run_until_complete(plugin.close())
    finally:
        loop.close()
    assert merged == {"a": ("crc32c", 1, 2), "b": ("crc32c", 3, 4)}


def test_sharded_ranged_restore_verifies_pages(tmp_path, monkeypatch) -> None:
    """Memory-budgeted sharded restores split each shard into ranged row
    reads; every page a range fully covers is verified, so mid-shard
    corruption is caught even though no read sees the whole shard blob.
    (Dense restores read whole blobs and are covered by the blob digest.)"""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import torchsnapshot_tpu.integrity as integrity
    from torchsnapshot_tpu.knobs import override_per_rank_memory_budget_bytes

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    monkeypatch.setattr(integrity, "PAGE_SIZE", 64 * 1024)

    # 128 rows x 4 KiB per device shard = 512 KiB/shard = 8 pages.
    # (float32: jax keeps x64 disabled by default.)
    rows, cols = 128 * len(devs), 1024
    arr = jax.device_put(
        jnp.arange(float(rows * cols)).reshape(rows, cols).astype(jnp.float32),
        NamedSharding(Mesh(np.array(devs), ("x",)), P("x", None)),
    )
    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"emb": arr})})

    table = json.loads((tmp_path / table_path(0)).read_text())
    shard_keys = sorted(k for k in table if k.startswith("sharded/"))
    assert len(table[shard_keys[0]]) == 5  # paged entry

    # Flip a byte in the middle of the first shard (page 4 of 8).
    victim = tmp_path / shard_keys[0]
    data = bytearray(victim.read_bytes())
    data[4 * 64 * 1024 + 17] ^= 0x04
    victim.write_bytes(bytes(data))

    mesh = Mesh(np.array(devs), ("x",))
    with override_per_rank_memory_budget_bytes(128 * 1024):
        dst = {
            "s": ts.PyTreeState(
                {
                    "emb": jax.device_put(
                        jnp.zeros((rows, cols), jnp.float32),
                        NamedSharding(mesh, P("x", None)),
                    )
                }
            )
        }
        with pytest.raises(ChecksumError, match="page"):
            ts.Snapshot(str(tmp_path)).restore(dst)

    # Clean blob again: the same budgeted restore succeeds.
    data[4 * 64 * 1024 + 17] ^= 0x04
    victim.write_bytes(bytes(data))
    with override_per_rank_memory_budget_bytes(128 * 1024):
        dst = {
            "s": ts.PyTreeState(
                {
                    "emb": jax.device_put(
                        jnp.zeros((rows, cols), jnp.float32),
                        NamedSharding(mesh, P("x", None)),
                    )
                }
            )
        }
        ts.Snapshot(str(tmp_path)).restore(dst)
        np.testing.assert_array_equal(
            np.asarray(dst["s"].tree["emb"]), np.asarray(arr)
        )


def test_read_object_budgeted_verifies_pages(tmp_path, monkeypatch) -> None:
    import torchsnapshot_tpu.integrity as integrity

    monkeypatch.setattr(integrity, "PAGE_SIZE", 64 * 1024)
    arr = np.arange(64 * 1024, dtype=np.float64)  # 512 KiB = 8 pages
    ts.Snapshot.take(str(tmp_path), {"s": ts.PyTreeState({"big": arr.copy()})})
    blob = tmp_path / "0" / "s" / "big"
    data = bytearray(blob.read_bytes())
    data[3 * 64 * 1024 + 9] ^= 0x10
    blob.write_bytes(bytes(data))
    with pytest.raises(ChecksumError, match="page 3"):
        ts.Snapshot(str(tmp_path)).read_object(
            "0/s/big", memory_budget_bytes=128 * 1024
        )


def test_verify_range_checksum_unit() -> None:
    from torchsnapshot_tpu.integrity import (
        compute_checksum_entry,
        verify_range_checksum,
    )
    import torchsnapshot_tpu.integrity as integrity

    page = integrity.PAGE_SIZE
    blob = bytes(bytearray((i * 7) % 256 for i in range(page * 2 + 100)))
    entry = compute_checksum_entry(blob)
    assert len(entry) == 5
    # Paged entries still carry a real whole-blob digest (chained from
    # the page walk) so older readers can verify whole reads.
    from torchsnapshot_tpu.integrity import compute_checksum

    assert entry[1] == compute_checksum(blob)[1]

    # Full-page-aligned range: the page verifies.
    assert verify_range_checksum(blob[:page], entry, (0, page), "p")
    # Unaligned range fully inside one page: nothing fully covered.
    assert not verify_range_checksum(
        blob[10 : page - 10], entry, (10, page - 10), "p"
    )
    # Range covering the partial tail page verifies it.
    assert verify_range_checksum(
        blob[page * 2 :], entry, (page * 2, len(blob)), "p"
    )
    # Corrupted page detected.
    bad = bytearray(blob[:page])
    bad[50] ^= 0xFF
    with pytest.raises(ChecksumError, match="page 0"):
        verify_range_checksum(bytes(bad), entry, (0, page), "p")
    # Truncated ranged read fails loudly, not as an opaque consumer error.
    with pytest.raises(ChecksumError, match="returned"):
        verify_range_checksum(blob[: page - 1], entry, (0, page), "p")

    # Whole-blob verification of a paged entry uses the chained digest.
    from torchsnapshot_tpu.integrity import verify_checksum as _vc

    _vc(blob, entry, "p")  # no raise
    whole_bad = bytearray(blob)
    whole_bad[page + 5] ^= 0x01
    with pytest.raises(ChecksumError, match="mismatch"):
        _vc(bytes(whole_bad), entry, "p")

    # Interim paged format (whole digest None) verifies page-by-page.
    interim = (entry[0], None, entry[2], entry[3], entry[4])
    _vc(blob, interim, "p")  # no raise
    with pytest.raises(ChecksumError, match="page 1"):
        _vc(bytes(whole_bad), interim, "p")


def test_fused_write_checksum_matches_two_step(tmp_path) -> None:
    """FSStoragePlugin.write_with_checksum produces byte-identical table
    entries to compute-then-write, across page boundaries, and the bytes
    on disk are the same."""
    import asyncio

    from torchsnapshot_tpu.integrity import PAGE_SIZE, compute_checksum_entry
    from torchsnapshot_tpu.io_types import WriteIO
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    plugin = FSStoragePlugin(root=str(tmp_path))
    if plugin._native is False:
        import pytest

        pytest.skip("native runtime unavailable")
    rng = __import__("numpy").random.default_rng(0)
    sizes = [
        0,
        1,
        PAGE_SIZE - 1,
        PAGE_SIZE,
        PAGE_SIZE + 1,
        2 * PAGE_SIZE,
        2 * PAGE_SIZE + 12345,
    ]

    async def run() -> None:
        for i, size in enumerate(sizes):
            buf = rng.integers(0, 256, size, dtype="uint8").tobytes()
            entry = await plugin.write_with_checksum(
                WriteIO(path=f"blob{i}", buf=buf)
            )
            assert entry == compute_checksum_entry(buf), size
            assert (tmp_path / f"blob{i}").read_bytes() == buf

    asyncio.run(run())


def test_fused_write_checksum_declines_without_native(tmp_path) -> None:
    """A plugin whose native runtime is unavailable declines the fused
    path (returns None) so the scheduler falls back to two-step."""
    import asyncio

    from torchsnapshot_tpu.io_types import WriteIO
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    plugin = FSStoragePlugin(root=str(tmp_path))
    plugin._native = False
    assert asyncio.run(
        plugin.write_with_checksum(WriteIO(path="x", buf=b"abc"))
    ) is None


def test_fused_read_checksum_roundtrip_and_corruption(tmp_path) -> None:
    """read_with_checksum returns page digests that verify against both
    entry formats, and a corrupted blob fails through the fused path."""
    import asyncio

    from torchsnapshot_tpu.integrity import (
        PAGE_SIZE,
        ChecksumError,
        compute_checksum_entry,
        verify_page_crcs,
    )
    from torchsnapshot_tpu.io_types import ReadIO, WriteIO
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    plugin = FSStoragePlugin(root=str(tmp_path))
    if plugin._native is False:
        import pytest

        pytest.skip("native runtime unavailable")
    rng = __import__("numpy").random.default_rng(1)

    async def run() -> None:
        for i, size in enumerate([10, PAGE_SIZE, 2 * PAGE_SIZE + 7]):
            buf = rng.integers(0, 256, size, dtype="uint8").tobytes()
            await plugin.write(WriteIO(path=f"b{i}", buf=buf))
            entry = compute_checksum_entry(buf)
            read_io = ReadIO(path=f"b{i}")
            pages = await plugin.read_with_checksum(read_io)
            assert pages is not None and bytes(read_io.buf) == buf
            verify_page_crcs(pages, size, entry, f"b{i}")  # no raise
            # Ranged reads decline the fused path.
            assert (
                await plugin.read_with_checksum(
                    ReadIO(path=f"b{i}", byte_range=(0, 1))
                )
                is None
            )

        # An interim entry at a foreign page granularity cannot be checked
        # from these pages: signalled as False (caller re-verifies bytes),
        # never a crash.
        buf0 = (tmp_path / "b0").read_bytes()
        read_io0 = ReadIO(path="b0")
        pages0 = await plugin.read_with_checksum(read_io0)
        foreign = ("crc32c", None, len(buf0), PAGE_SIZE * 2, [0])
        assert verify_page_crcs(pages0, len(buf0), foreign, "b0") is False

        # Corruption detected from the digests computed during the read.
        blob = tmp_path / "b2"
        data = bytearray(blob.read_bytes())
        entry = compute_checksum_entry(bytes(data))
        data[PAGE_SIZE + 3] ^= 0xFF
        blob.write_bytes(bytes(data))
        read_io = ReadIO(path="b2")
        pages = await plugin.read_with_checksum(read_io)
        try:
            verify_page_crcs(pages, len(data), entry, "b2")
            raise AssertionError("corruption not detected")
        except ChecksumError:
            pass

    asyncio.run(run())
