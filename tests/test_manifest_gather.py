"""Metadata-plane scaling: manifest (and partitioner/verification) gathers
go TO the leader; non-leader ranks pay O(own manifest) coordinator
traffic, not O(world x manifest).

Round-3 review finding: ``Store.exchange`` had rank 0 serve the combined
manifest blob to every rank — ~0.7 GB through one TCP socket at 1e5
leaves x 32 ranks — although non-leaders never consume the global
manifest (rank 0 alone writes metadata; restore lazy-loads it from
storage). These tests pin the replacement protocol:

- correctness: distributed take -> every rank restores; non-leader ranks
  (whose in-memory metadata is now None) lazy-load committed metadata.
- traffic: with a large manifest, each non-leader's received coordinator
  bytes stay a small fraction of the leader's (the leader still ingests
  every rank manifest — that part is inherent to a gather).
"""

import numpy as np

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.dist_store import ProcessGroup
from torchsnapshot_tpu.test_utils import (
    ByteCountingStore,
    assert_tree_eq,
    run_multiprocess,
)

N_LEAVES = 300  # per rank: pickled rank manifest is tens of KB


def _traffic_worker(pg, root: str):
    counting = ByteCountingStore(pg.store)
    cpg = ProcessGroup(
        store=counting, rank=pg.rank, world_size=pg.world_size
    )
    state = {
        f"t{i:04d}": np.full((4,), pg.rank * 100_000 + i, np.float32)
        for i in range(N_LEAVES)
    }
    snap = ts.Snapshot.take(root, {"m": ts.PyTreeState(state)}, pg=cpg)
    take_sent, take_received = counting.sent_bytes, counting.received_bytes

    # Non-leader ranks hold no in-memory metadata — the property must
    # lazy-load the committed global manifest from storage.
    md = snap.metadata
    assert md.world_size == pg.world_size
    assert f"{pg.rank}/m/t0000" in md.manifest

    dest = {
        f"t{i:04d}": np.zeros((4,), np.float32) for i in range(N_LEAVES)
    }
    dest_state = ts.PyTreeState(dest)
    ts.Snapshot(root, pg=cpg).restore({"m": dest_state})
    assert_tree_eq(dest_state.tree, state)
    return take_sent, take_received


def test_manifest_gather_traffic_is_leader_bound(tmp_path) -> None:
    results = run_multiprocess(
        _traffic_worker, nproc=4, args=(str(tmp_path / "snap"),)
    )
    sent = [s for s, _ in results]
    received = [r for _, r in results]
    # Every non-leader shipped its own manifest; the leader's own blob
    # never touches the store (it is consumed locally), so its sent
    # column is control traffic only.
    assert all(s > 10_000 for s in sent[1:]), sent
    assert sent[0] < min(sent[1:]) / 3, sent
    # The leader ingests the other ranks' manifests; each non-leader
    # receives only control traffic + the broadcast decisions — far less
    # than one rank manifest, let alone world x manifest.
    assert received[0] > 2 * min(sent[1:]), (sent, received)
    for r in received[1:]:
        assert r < received[0] / 3, received
        assert r < min(sent[1:]), received
