"""FS and memory storage plugin round-trips, ranged reads, deletes."""

import asyncio

import pytest

from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture(params=["fs", "memory"])
def plugin(request, tmp_path):
    if request.param == "fs":
        p = FSStoragePlugin(root=str(tmp_path))
        yield p
    else:
        name = f"test-{id(request)}"
        p = MemoryStoragePlugin(name=name)
        yield p
        MemoryStoragePlugin.drop_store(name)


def test_write_read_roundtrip(plugin) -> None:
    async def go():
        payload = bytes(range(256)) * 4
        await plugin.write(WriteIO(path="a/b/data", buf=payload))
        read_io = ReadIO(path="a/b/data")
        await plugin.read(read_io)
        assert bytes(read_io.buf) == payload

        ranged = ReadIO(path="a/b/data", byte_range=(256, 512))
        await plugin.read(ranged)
        assert bytes(ranged.buf) == bytes(range(256))

        await plugin.delete("a/b/data")
        with pytest.raises(Exception):
            await plugin.read(ReadIO(path="a/b/data"))
        await plugin.close()

    _run(go())


def test_ranged_read_past_eof_is_an_error(plugin) -> None:
    """Both plugins share the contract: a ranged read past the end of a
    blob is corruption (the manifest promised bytes that aren't there),
    never a silent partial result."""

    async def go():
        await plugin.write(WriteIO(path="short", buf=b"0123456789"))
        with pytest.raises(OSError) as exc_info:
            await plugin.read(ReadIO(path="short", byte_range=(4, 64)))
        import errno

        assert exc_info.value.errno == errno.EIO
        await plugin.close()

    _run(go())


def test_write_accepts_memoryview_and_bytearray(plugin) -> None:
    async def go():
        await plugin.write(WriteIO(path="mv", buf=memoryview(b"hello")))
        await plugin.write(WriteIO(path="ba", buf=bytearray(b"world")))
        r1, r2 = ReadIO(path="mv"), ReadIO(path="ba")
        await plugin.read(r1)
        await plugin.read(r2)
        assert bytes(r1.buf) == b"hello" and bytes(r2.buf) == b"world"

    _run(go())


def test_url_dispatch(tmp_path) -> None:
    assert isinstance(url_to_storage_plugin(str(tmp_path)), FSStoragePlugin)
    assert isinstance(url_to_storage_plugin(f"fs://{tmp_path}"), FSStoragePlugin)
    assert isinstance(url_to_storage_plugin("memory://x"), MemoryStoragePlugin)
    with pytest.raises(RuntimeError, match="Unsupported storage scheme"):
        url_to_storage_plugin("warpdrive://x")


def test_fs_overwrite(tmp_path) -> None:
    async def go():
        p = FSStoragePlugin(root=str(tmp_path))
        await p.write(WriteIO(path="f", buf=b"111111"))
        await p.write(WriteIO(path="f", buf=b"22"))
        r = ReadIO(path="f")
        await p.read(r)
        assert bytes(r.buf) == b"22"

    _run(go())
