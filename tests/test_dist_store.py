"""TCP store primitives, object collectives, and LinearBarrier semantics.

Structural model: reference tests/test_dist_store.py:57-194 (TCPStore +
LinearBarrier incl. timeout and error propagation).
"""

import threading
import time

import pytest

from torchsnapshot_tpu.dist_store import (
    BarrierError,
    InProcessStore,
    LinearBarrier,
    StoreTimeoutError,
    TCPStore,
)
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import ProcessGroup, get_free_port, multiprocess_test


def test_tcp_store_primitives() -> None:
    port = get_free_port()
    server = TCPStore("127.0.0.1", port, is_server=True)
    client = TCPStore("127.0.0.1", server.port, is_server=False)
    try:
        server.set("k", b"v")
        assert client.try_get("k") == b"v"
        assert client.try_get("missing") is None
        assert client.add("ctr", 3) == 3
        assert server.add("ctr", 2) == 5
        client.delete("k")
        assert server.try_get("k") is None
        with pytest.raises(StoreTimeoutError):
            client.get("never", timeout=0.2)
    finally:
        client.close()
        server.close()


def test_store_collectives_threads() -> None:
    """Exercise exchange/broadcast/scatter/barrier with threads sharing one
    in-process store."""
    store = InProcessStore()
    world = 3
    results = {}

    def worker(rank: int) -> None:
        pg = PGWrapper(ProcessGroup(store=store, rank=rank, world_size=world))
        results[(rank, "ag")] = pg.all_gather_object(f"obj{rank}")
        results[(rank, "bc")] = pg.broadcast_object(
            "from0" if rank == 0 else None
        )
        results[(rank, "sc")] = pg.scatter_object_list(
            [f"to{i}" for i in range(world)] if rank == 0 else None
        )
        pg.barrier()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for r in range(world):
        assert results[(r, "ag")] == ["obj0", "obj1", "obj2"]
        assert results[(r, "bc")] == "from0"
        assert results[(r, "sc")] == f"to{r}"
    # Collective keys are transient: nothing should linger.
    assert store._kv == {}


def test_gather_object_to_leader_threads() -> None:
    """gather: dst receives rank-ordered blobs, others receive None, the
    dst's own blob never touches the store, and keys are cleaned up."""
    store = InProcessStore()
    world = 3
    results = {}
    set_keys = []
    orig_set = store.set

    def spying_set(key, value):
        set_keys.append(key)
        orig_set(key, value)

    store.set = spying_set

    def worker(rank: int) -> None:
        pg = PGWrapper(ProcessGroup(store=store, rank=rank, world_size=world))
        results[rank] = pg.gather_object({"rank": rank})

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results[0] == [{"rank": 0}, {"rank": 1}, {"rank": 2}]
    assert results[1] is None and results[2] is None
    assert store._kv == {}  # transient keys cleaned
    # Only non-destination ranks published blobs (suffixes /1 and /2).
    gather_sets = [k for k in set_keys if "/ga/" in k]
    assert sorted(k.rsplit("/", 1)[1] for k in gather_sets) == ["1", "2"]


class _FlakyStore(InProcessStore):
    """Raises on the first ``fail_first_n`` reads, then recovers."""

    def __init__(self, fail_first_n: int) -> None:
        super().__init__()
        self.fails_left = fail_first_n
        self.raised = 0

    def try_get(self, key):
        if self.fails_left > 0:
            self.fails_left -= 1
            self.raised += 1
            raise ConnectionError("simulated transport hiccup")
        return super().try_get(key)


class _DeadStore(InProcessStore):
    def try_get(self, key):
        raise ConnectionError("store is gone")


def test_get_rides_out_transient_read_failures() -> None:
    """try_get raising means "could not observe", not "absent"; the
    deadline-bounded helpers retry through brief failures."""
    store = _FlakyStore(fail_first_n=3)
    store.set("k", b"v")
    assert store.get("k", timeout=5.0) == b"v"
    assert store.raised == 3


def test_get_reraises_on_persistently_dead_store() -> None:
    """A store failing continuously must re-raise after the short grace,
    not be polled until the full deadline (a dead TCPStore socket means
    the leader is gone)."""
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        _DeadStore().get("k", timeout=60.0)
    assert time.monotonic() - t0 < 30.0  # grace, not the 60s deadline


def test_barrier_tolerates_transient_read_failures() -> None:
    """A momentary store error inside a barrier wait must not abort the
    commit barrier."""
    store = _FlakyStore(fail_first_n=2)
    world = 2
    errors = []

    def worker(rank: int) -> None:
        try:
            b = LinearBarrier("b", store, rank=rank, world_size=world)
            b.arrive(timeout=30.0)
            b.depart(timeout=30.0)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert store.raised == 2  # the hiccups actually happened


def test_linear_barrier_happy_path() -> None:
    store = InProcessStore()
    world = 3
    order = []

    def worker(rank: int) -> None:
        b = LinearBarrier("test", store, rank, world)
        b.arrive(timeout=10)
        order.append(rank)
        b.depart(timeout=10)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(order) == [0, 1, 2]
    assert store._kv == {}  # cleaned up after depart


def test_linear_barrier_error_propagation() -> None:
    """A peer's report_error poisons every other rank's wait — no rank may
    proceed to commit (reference dist_store.py:177-193)."""
    store = InProcessStore()
    world = 2
    caught = {}

    def rank0() -> None:
        b = LinearBarrier("err", store, 0, world)
        try:
            b.arrive(timeout=10)
        except BarrierError as e:
            caught[0] = e

    def rank1() -> None:
        b = LinearBarrier("err", store, 1, world)
        time.sleep(0.05)
        b.report_error(RuntimeError("injected rank-1 failure"))

    threads = [threading.Thread(target=rank0), threading.Thread(target=rank1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert 0 in caught
    assert "injected rank-1 failure" in repr(caught[0].__cause__)


def test_linear_barrier_timeout() -> None:
    store = InProcessStore()
    b = LinearBarrier("t", store, 0, 2)  # peer never arrives
    with pytest.raises(StoreTimeoutError):
        b.arrive(timeout=0.2)


def test_barrier_depart_requires_arrive() -> None:
    b = LinearBarrier("x", InProcessStore(), 0, 1)
    with pytest.raises(RuntimeError, match="before arrive"):
        b.depart()


@multiprocess_test(nproc=2)
def test_collectives_across_processes(pg) -> None:
    wrapper = PGWrapper(pg)
    gathered = wrapper.all_gather_object({"rank": pg.rank})
    assert gathered == [{"rank": 0}, {"rank": 1}]
    assert wrapper.broadcast_object("x" if pg.rank == 0 else None) == "x"
    wrapper.barrier()


def test_world_32_stress_over_tcp() -> None:
    """Scale check for the coordination layer (VERDICT r1 item 4): 32 ranks
    — each with its own TCP client connection — run LinearBarrier
    arrive/depart, a manifest-sized exchange, and a counter barrier, and
    the whole thing completes in seconds. The leader's waits are single
    counter-key polls and exchange is a rank-0 aggregate + one fetch per
    rank, so wall time stays flat-ish in world size."""
    world = 32
    server = TCPStore("127.0.0.1", 0, is_server=True)
    payload = {"manifest": ["0/model/layer/%d" % i for i in range(200)]}
    results: dict = {}
    errors: list = []

    def worker(rank: int) -> None:
        client = (
            server
            if rank == 0
            else TCPStore("127.0.0.1", server.port, is_server=False)
        )
        try:
            pg = PGWrapper(
                ProcessGroup(store=client, rank=rank, world_size=world)
            )
            gathered = pg.all_gather_object({**payload, "rank": rank})
            assert [g["rank"] for g in gathered] == list(range(world))
            barrier = LinearBarrier(
                "stress32", client, rank=rank, world_size=world
            )
            barrier.arrive(timeout=60)
            barrier.depart(timeout=60)
            pg.barrier()
            results[rank] = True
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))
        finally:
            if rank != 0:
                client.close()

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(r,)) for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    elapsed = time.monotonic() - t0
    server.close()
    assert not errors, errors[:3]
    assert len(results) == world
    assert elapsed < 60, f"world-32 coordination took {elapsed:.1f}s"


def test_jax_pg_fallback_bootstraps_tcp_store() -> None:
    """A coordination client without atomic increment must get a TCPStore
    bootstrapped through set/get (the two primitives every KV has) instead
    of NotImplementedError surfacing mid-collective."""
    from torchsnapshot_tpu.dist_store import _bootstrap_tcp_store

    kv = InProcessStore()  # stands in for the coordination KV (set/get only)
    stores = {}

    def worker(rank: int) -> None:
        stores[rank] = _bootstrap_tcp_store(kv, rank, timeout=30)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert sorted(stores) == [0, 1, 2]
    try:
        stores[0].set("k", b"v")
        assert stores[1].try_get("k") == b"v"
        assert stores[2].add("c", 5) == 5
        assert stores[1].add("c", 1) == 6
    finally:
        for s in stores.values():
            s.close()


def test_world_32_snapshot_take_restore(tmp_path) -> None:
    """Full Snapshot.take + restore at world 32 over one TCP store: the
    manifest gather (rank-0 aggregate exchange), replicated verification,
    partitioning, commit barrier — every coordination round at a pod-ish
    world size, in seconds."""
    import numpy as np

    import torchsnapshot_tpu as ts

    world = 32
    server = TCPStore("127.0.0.1", 0, is_server=True)
    path = str(tmp_path / "snap")
    errors: list = []

    def worker(rank: int) -> None:
        client = (
            server
            if rank == 0
            else TCPStore("127.0.0.1", server.port, is_server=False)
        )
        try:
            pg = ProcessGroup(store=client, rank=rank, world_size=world)
            state = {"w": np.full((64,), float(rank), np.float32), "r": rank}
            ts.Snapshot.take(path, {"s": ts.PyTreeState(state)}, pg=pg)
            dst = {"w": np.zeros((64,), np.float32), "r": -1}
            wrapped = ts.PyTreeState(dst)
            ts.Snapshot(path, pg=pg).restore({"s": wrapped})
            np.testing.assert_array_equal(
                wrapped.tree["w"], np.full((64,), float(rank), np.float32)
            )
            assert wrapped.tree["r"] == rank
        except Exception as e:  # noqa: BLE001
            errors.append((rank, repr(e)))
        finally:
            if rank != 0:
                client.close()

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(r,)) for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    elapsed = time.monotonic() - t0
    server.close()
    assert not errors, errors[:3]
    assert elapsed < 120, f"world-32 take+restore took {elapsed:.1f}s"


def test_jax_process_group_is_cached(monkeypatch) -> None:
    """Repeated jax_process_group() calls must return the same ProcessGroup
    (same store object): op-seq namespaces stay shared, and the TCPStore
    fallback never bootstraps a second server under the same address key."""
    import torchsnapshot_tpu.dist_store as ds

    monkeypatch.setattr(ds, "_JAX_PG", None)
    sentinel_store = InProcessStore()
    monkeypatch.setattr(ds, "JaxCoordinationStore", lambda: sentinel_store)
    monkeypatch.setattr(
        ds.InProcessStore, "supports_add", lambda self: True, raising=False
    )
    pg1 = ds.jax_process_group()
    pg2 = ds.jax_process_group()
    assert pg1 is pg2
    assert pg1.store is sentinel_store
    monkeypatch.setattr(ds, "_JAX_PG", None)


def test_tcp_store_connect_timeout_is_a_clear_error() -> None:
    """A client whose rank-0 store server never comes up must fail with
    a deadline-bounded StoreTimeoutError naming the address — not a raw
    ECONNREFUSED escaping from deep inside a collective (snaplint
    satellite: every dist_store poll loop is deadline-bounded with a
    clear timeout error)."""
    port = get_free_port()  # freed immediately: nothing listens on it
    client = TCPStore(
        "127.0.0.1", port, is_server=False, connect_timeout=0.3
    )
    t0 = time.monotonic()
    with pytest.raises(StoreTimeoutError, match="Timed out connecting"):
        client.try_get("anything")
    assert time.monotonic() - t0 < 10.0


# ---------------------------------------------------------------------------
# Batched store ops (multi_set / multi_get / multi_delete)
# ---------------------------------------------------------------------------


def test_tcp_store_multi_ops_roundtrip() -> None:
    """The batched wire commands: one frame each way per BATCH, same
    semantics as the per-key primitives (absent keys -> None)."""
    server = TCPStore("127.0.0.1", 0, is_server=True)
    client = TCPStore("127.0.0.1", server.port, is_server=False)
    try:
        client.multi_set({"a": b"1", "b": b"2", "c": b"3"})
        assert server.try_get("b") == b"2"
        got = client.multi_get(["a", "b", "missing"])
        assert got == {"a": b"1", "b": b"2", "missing": None}
        client.multi_delete(["a", "c", "never-existed"])
        assert client.multi_get(["a", "b", "c"]) == {
            "a": None,
            "b": b"2",
            "c": None,
        }
    finally:
        client.close()
        server.close()


def test_sharded_store_routing_and_collectives() -> None:
    """Deterministic key->shard routing (every client agrees), per-key
    atomicity for counters, and the base-class collectives running
    unchanged over the sharded store."""
    from torchsnapshot_tpu.dist_store import ShardedStore, shard_for_key

    members = [InProcessStore() for _ in range(3)]
    store = ShardedStore(members)
    keys = [f"k{i}" for i in range(30)]
    store.multi_set({k: k.encode() for k in keys})
    # Every key lives on exactly its hashed member, nowhere else.
    for k in keys:
        shard = shard_for_key(k, 3)
        assert members[shard].try_get(k) == k.encode()
        for other in range(3):
            if other != shard:
                assert members[other].try_get(k) is None
    assert store.multi_get(keys) == {k: k.encode() for k in keys}
    assert store.add("ctr", 2) == 2 and store.add("ctr", 3) == 5
    store.multi_delete(keys[:15])
    assert store.try_get(keys[0]) is None
    assert store.try_get(keys[20]) == keys[20].encode()

    world, results = 3, {}

    def worker(rank: int) -> None:
        pg = PGWrapper(ProcessGroup(store=store, rank=rank, world_size=world))
        results[(rank, "ag")] = pg.all_gather_object(rank)
        pg.barrier()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results[(1, "ag")] == [0, 1, 2]


# ---------------------------------------------------------------------------
# TreeBarrier
# ---------------------------------------------------------------------------


def _run_barrier_world(make, world: int):
    errors = {}

    def worker(rank: int) -> None:
        try:
            b = make(rank)
            b.arrive(timeout=10.0)
            b.depart(timeout=10.0)
        except Exception as e:  # noqa: BLE001 - collected for asserts
            errors[rank] = e

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return errors


def test_tree_barrier_happy_path_and_cleanup() -> None:
    from torchsnapshot_tpu.dist_store import TreeBarrier

    store = InProcessStore()
    errors = _run_barrier_world(
        lambda r: TreeBarrier("tb", store, r, 9, fanout=2), world=9
    )
    assert errors == {}
    # Transient keys cleaned up: each rank deletes its own node keys,
    # the root the error key — a long-lived store must not accumulate.
    assert store._kv == {}


def test_tree_barrier_error_propagation() -> None:
    """report_error poisons every pending wait with BarrierError — the
    same contract LinearBarrier pins (the swap must be transparent to
    snapshot.py/fanout.py call sites)."""
    from torchsnapshot_tpu.dist_store import TreeBarrier

    store = InProcessStore()
    world = 7
    errors = {}
    release = threading.Event()

    def worker(rank: int) -> None:
        b = TreeBarrier("err", store, rank, world, fanout=2)
        try:
            if rank == 3:
                release.wait(5.0)
                b.report_error(ValueError("rank 3 exploded"))
                return
            b.arrive(timeout=10.0)
            b.depart(timeout=10.0)
        except Exception as e:  # noqa: BLE001
            errors[rank] = e

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    release.set()
    for t in threads:
        t.join()
    assert set(errors) == set(range(world)) - {3}
    for e in errors.values():
        assert isinstance(e, BarrierError)
        assert isinstance(e.__cause__, ValueError)


def test_tree_barrier_timeout_and_depart_guard() -> None:
    from torchsnapshot_tpu.dist_store import TreeBarrier

    b = TreeBarrier("t", InProcessStore(), 0, 2, fanout=4)
    with pytest.raises(StoreTimeoutError):
        b.arrive(timeout=0.2)
    b2 = TreeBarrier("t2", InProcessStore(), 0, 2, fanout=4)
    with pytest.raises(RuntimeError, match="depart"):
        b2.depart()


def test_tree_barrier_world_one_is_a_noop() -> None:
    from torchsnapshot_tpu.dist_store import TreeBarrier

    b = TreeBarrier("solo", InProcessStore(), 0, 1, fanout=4)
    b.arrive(timeout=1.0)
    b.depart(timeout=1.0)


def test_make_barrier_honors_kill_switch() -> None:
    from torchsnapshot_tpu import knobs
    from torchsnapshot_tpu.dist_store import (
        LinearBarrier as _Linear,
        TreeBarrier as _Tree,
        make_barrier,
    )

    store = InProcessStore()
    assert isinstance(make_barrier("p", store, 0, 4), _Tree)
    with knobs.disable_tree_barrier():
        assert isinstance(make_barrier("p", store, 0, 4), _Linear)
    with knobs.override_barrier_fanout(5):
        assert make_barrier("p", store, 0, 4).fanout == 5


# ---------------------------------------------------------------------------
# Poll backoff (satellite: request-count reduction while waiting)
# ---------------------------------------------------------------------------


def test_wait_loops_back_off_exponentially() -> None:
    """A follower parked in a barrier wait must poll at backed-off
    intervals, not a fixed 5 ms tick: ~0.6 s of waiting costs a
    bounded handful of requests (fixed-interval polling would issue
    ~120). Pinned through the counting store, world 256 so the scaled
    cap is at its ceiling."""
    from torchsnapshot_tpu.scalemodel import CountingStore

    inner = InProcessStore()
    store = CountingStore(inner)
    barrier = LinearBarrier("bo", store, rank=1, world_size=256)

    def release_late() -> None:
        time.sleep(0.6)
        inner.set("bo/arrive/go", b"1")

    t = threading.Thread(target=release_late)
    t.start()
    barrier.arrive(timeout=10.0)
    t.join()
    # add(count) + N batched polls of [error, go]; exponential backoff
    # capped at 100 ms bounds N to ~12 for a 0.6 s wait.
    assert store.counts["multi_get"] <= 20
    assert store.counts["multi_get"] >= 3


def test_store_get_backs_off_but_stays_deadline_accurate() -> None:
    from torchsnapshot_tpu.scalemodel import CountingStore

    inner = InProcessStore()
    store = CountingStore(inner)

    def set_late() -> None:
        time.sleep(0.4)
        inner.set("late", b"v")

    t = threading.Thread(target=set_late)
    t.start()
    assert store.get("late", timeout=10.0) == b"v"
    t.join()
    assert store.counts["try_get"] <= 15
    with pytest.raises(StoreTimeoutError):
        store.get("never", timeout=0.3)
