"""TCP store primitives, object collectives, and LinearBarrier semantics.

Structural model: reference tests/test_dist_store.py:57-194 (TCPStore +
LinearBarrier incl. timeout and error propagation).
"""

import threading
import time

import pytest

from torchsnapshot_tpu.dist_store import (
    BarrierError,
    InProcessStore,
    LinearBarrier,
    StoreTimeoutError,
    TCPStore,
)
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import ProcessGroup, get_free_port, multiprocess_test


def test_tcp_store_primitives() -> None:
    port = get_free_port()
    server = TCPStore("127.0.0.1", port, is_server=True)
    client = TCPStore("127.0.0.1", server.port, is_server=False)
    try:
        server.set("k", b"v")
        assert client.try_get("k") == b"v"
        assert client.try_get("missing") is None
        assert client.add("ctr", 3) == 3
        assert server.add("ctr", 2) == 5
        client.delete("k")
        assert server.try_get("k") is None
        with pytest.raises(StoreTimeoutError):
            client.get("never", timeout=0.2)
    finally:
        client.close()
        server.close()


def test_store_collectives_threads() -> None:
    """Exercise exchange/broadcast/scatter/barrier with threads sharing one
    in-process store."""
    store = InProcessStore()
    world = 3
    results = {}

    def worker(rank: int) -> None:
        pg = PGWrapper(ProcessGroup(store=store, rank=rank, world_size=world))
        results[(rank, "ag")] = pg.all_gather_object(f"obj{rank}")
        results[(rank, "bc")] = pg.broadcast_object(
            "from0" if rank == 0 else None
        )
        results[(rank, "sc")] = pg.scatter_object_list(
            [f"to{i}" for i in range(world)] if rank == 0 else None
        )
        pg.barrier()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for r in range(world):
        assert results[(r, "ag")] == ["obj0", "obj1", "obj2"]
        assert results[(r, "bc")] == "from0"
        assert results[(r, "sc")] == f"to{r}"
    # Collective keys are transient: nothing should linger.
    assert store._kv == {}


def test_linear_barrier_happy_path() -> None:
    store = InProcessStore()
    world = 3
    order = []

    def worker(rank: int) -> None:
        b = LinearBarrier("test", store, rank, world)
        b.arrive(timeout=10)
        order.append(rank)
        b.depart(timeout=10)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(order) == [0, 1, 2]
    assert store._kv == {}  # cleaned up after depart


def test_linear_barrier_error_propagation() -> None:
    """A peer's report_error poisons every other rank's wait — no rank may
    proceed to commit (reference dist_store.py:177-193)."""
    store = InProcessStore()
    world = 2
    caught = {}

    def rank0() -> None:
        b = LinearBarrier("err", store, 0, world)
        try:
            b.arrive(timeout=10)
        except BarrierError as e:
            caught[0] = e

    def rank1() -> None:
        b = LinearBarrier("err", store, 1, world)
        time.sleep(0.05)
        b.report_error(RuntimeError("injected rank-1 failure"))

    threads = [threading.Thread(target=rank0), threading.Thread(target=rank1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert 0 in caught
    assert "injected rank-1 failure" in repr(caught[0].__cause__)


def test_linear_barrier_timeout() -> None:
    store = InProcessStore()
    b = LinearBarrier("t", store, 0, 2)  # peer never arrives
    with pytest.raises(StoreTimeoutError):
        b.arrive(timeout=0.2)


def test_barrier_depart_requires_arrive() -> None:
    b = LinearBarrier("x", InProcessStore(), 0, 1)
    with pytest.raises(RuntimeError, match="before arrive"):
        b.depart()


@multiprocess_test(nproc=2)
def test_collectives_across_processes(pg) -> None:
    wrapper = PGWrapper(pg)
    gathered = wrapper.all_gather_object({"rank": pg.rank})
    assert gathered == [{"rank": 0}, {"rank": 1}]
    assert wrapper.broadcast_object("x" if pg.rank == 0 else None) == "x"
    wrapper.barrier()
